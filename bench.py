"""Throughput benchmark: XE + CST train steps/sec/chip on MSR-VTT-shaped work.

Run on real TPU hardware (do NOT set JAX_PLATFORMS=cpu).  Prints ONE JSON
line: {"metric", "value", "unit", "vs_baseline", "extra": {...}}.  The
headline metric stays the XE throughput (comparable across rounds); the
CST regime (SURVEY.md §3.2, the paper's core loop) and an analytic MFU
estimate ride along in "extra".

Workload (driver config 2, BASELINE.json: "MSR-VTT, ResNet-152 + C3D
feats, XE-loss pretrain"): batch 64 videos x 20 captions/video, 28 frames,
resnet-2048 + c3d-4096 features, LSTM-512 decoder, T=30, bfloat16 compute.
CST workload (driver config 4): 64 videos x 20 multinomial rollouts,
self-consensus (SCB) baseline, in-loop CIDEr-D over 20 refs/video.

``vs_baseline`` compares against the EARLIEST recorded round
(``BENCH_r01.json``-style driver artifacts, which wrap the JSON under a
"parsed" key), so later rounds report cumulative speedup over round 1.

The record line is RE-EMITTED after every completed sub-bench (last
line = most complete; earlier lines carry "partial": true), so a
mid-run backend loss still leaves the driver a parseable record, and
the first XE measurement runs a small chunk (BENCH_FIRST_CHUNK, default
12) purely to get `value != null` on the wire early — the full-chunk
measurement then replaces it (VERDICT r5 #2).

Env knobs: BENCH_FIRST_CHUNK (steps in the cheap first XE dispatch),
BENCH_CHUNK (steps per dispatch), BENCH_ITERS, BENCH_PALLAS,
BENCH_CST=0 to skip the CST section, BENCH_ATTN=0 to skip the
attention-fusion XE bench (it compiles a second model), BENCH_DECODE=0
to skip greedy/beam decode throughput, BENCH_SERVING=0 to skip the
online-serving continuous-vs-ladder sweep (BENCH_SERVING_REQS /
BENCH_SERVING_CLIENTS / BENCH_SERVING_OPEN_N size it), BENCH_REPLICAS=0
to skip the multi-replica 1-vs-N serving sweep (BENCH_REPLICAS_N /
BENCH_REPLICAS_REQS / BENCH_REPLICAS_OPEN_N size it), BENCH_LOADER=0
to skip the
packed-loader assembly bench, BENCH_CST_PIPE=0 to skip the paired
serial-vs-pipelined CST reward-scheduling rows (subprocess CPU child;
BENCH_CST_PIPE_BATCH / _ROLLOUTS / _WORKERS / _STEPS / _REPS size it),
BENCH_CST_SLOT=0 to skip the paired padded-vs-slot CST rollout rows
(subprocess CPU child; BENCH_CST_SLOT_BATCH / _ROLLOUTS / _L / _RNN /
_EOS_BIAS / _BLOCK / _STEPS / _WARM size it), BENCH_SLOT_MEM=0 to skip
the paired replicated-vs-deduped decode-state memory rows (subprocess
CPU child; BENCH_SLOT_MEM_SLOTS / _CLIENTS / _REQS / _EOS_BIAS size
it),
BENCH_SHARD=0 to skip the paired replicated-vs-model-sharded XE rows
(subprocess virtual-CPU child; BENCH_SHARD_N / _BATCH / _VOCAB /
_STEPS size it), BENCH_SHARD_FUSED=0 to skip the paired fused-vs-scan
model-sharded slot-decode rows (subprocess virtual-CPU child;
BENCH_SHARD_FUSED_N / _VOCAB / _STEPS size it — candidate-all-gather
vs full-vocab-gather collective bytes plus steps/s under M=2),
BENCH_TRACE=0 to skip the paired tracing-on/off
serving rows (subprocess CPU child; BENCH_TRACE_REQS / _CLIENTS /
_REPS size it), BENCH_SLO=0 to skip the chaos-soak/SLO-attainment
rows (subprocess CPU child; BENCH_SLO_SEED / _REQS size it — the
slo_reference_attainment row feeds the SLO regression gate, which
exits 3 on a pinned-threshold breach),
BENCH_COLDSTART=0 to skip the paired warm-vs-AOT replica cold-start
rows (subprocess CPU child spawning one fresh process per boot arm;
BENCH_COLDSTART_SLOTS sizes the slot bank),
BENCH_RNG to override the PRNG impl,
BENCH_ATT_HIDDEN to override model.att_hidden_size (A-width sweeps),
BENCH_CST_OVERLAP=0 to skip the unchunked-CST comparison re-run,
BENCH_MATCHED=0 to skip the chunk-10 matched-baseline re-run,
ATTLSTM_SCORE_MXU=1 to route the fused attention kernel's score
reduction through the MXU (the VERDICT r4 #6 counter-attempt — compare
xe_attention_steps_per_sec_chip with it 0 vs 1).
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# Steps per timed dispatch (see bench_xe): single source of truth so the
# recorded `bench_chunk` extra always matches what actually ran.
DEFAULT_CHUNK = 60


# ------------------------------------------------------ record schema
#
# Every BENCH_* / MULTICHIP_* JSON row is validated against a
# lightweight schema BEFORE it is written to stdout (the driver
# artifact): a malformed row must fail loudly at the emit site, not
# parse half-heartedly downstream.  Rules follow ADVICE r5: measured
# fields must be real numbers, never bools (bool subclasses int, which
# silently satisfies numeric checks and poisons "was anything measured"
# heuristics).

def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_record(rec: dict, kind: str = "bench") -> dict:
    """Validate one emitted JSON record; returns it or raises ValueError.

    ``kind``: "bench" (the headline record printed by :func:`main`),
    "multichip_partial" / "multichip_stalled" (the dryrun's incremental
    lines from ``__graft_entry__._PhaseTracker``).
    """
    def fail(msg):
        raise ValueError(f"malformed {kind} record: {msg} ({rec!r:.300})")

    if not isinstance(rec, dict):
        fail("not a dict")
    if kind == "bench":
        for key in ("metric", "value", "unit", "vs_baseline", "extra"):
            if key not in rec:
                fail(f"missing required key {key!r}")
        for key in ("metric", "unit"):
            if not (isinstance(rec[key], str) and rec[key]):
                fail(f"{key!r} must be a non-empty string")
        for key in ("value", "vs_baseline"):
            if rec[key] is not None and not _is_number(rec[key]):
                fail(f"{key!r} must be a real number or null, got "
                     f"{type(rec[key]).__name__}")
        if not isinstance(rec["extra"], dict):
            fail("'extra' must be a dict")
        for k in rec["extra"]:
            if not isinstance(k, str):
                fail(f"extra key {k!r} is not a string")
        # Measured-looking extras must not be bool-typed: a *_ms /
        # *_per_sec / *_frac / vs_* field is a measurement by contract.
        measured_suffixes = ("_ms", "_per_sec", "_per_sec_chip", "_s",
                             "_frac", "_pct", "_ratio", "_speedup",
                             "_steps_per_row", "_ticks", "_bytes")
        for k, v in rec["extra"].items():
            if isinstance(v, bool) and (
                k.endswith(measured_suffixes) or k.startswith("vs_")
            ):
                fail(f"measured extra {k!r} is bool-typed")
        # Memory accounting is exact pytree arithmetic by contract
        # (ISSUE 7): any *_bytes field must be a real number — a bool,
        # string, or None would mean nothing was measured.
        for k, v in rec["extra"].items():
            if k.endswith("_bytes") and not _is_number(v):
                fail(
                    f"{k!r} must be a numeric byte count, got {v!r}"
                )
        # Tracing-overhead pairing (ISSUE 10): every trace_overhead_*
        # field is a measurement by contract — the paired on/off rows
        # are only comparable if both sides are real numbers (a bool,
        # None, or prose value means one side never ran).
        for k, v in rec["extra"].items():
            if k.startswith("trace_overhead_") and not _is_number(v):
                fail(
                    f"{k!r} must be a real number, got {v!r}"
                )
        # CPU-host caveats are machine-readable, not prose: any
        # *_host_cores field (cst_pipe_, serving_replicas_, cst_slot_,
        # ...) must be a real core count.
        for k, v in rec["extra"].items():
            if k.endswith("_host_cores") and not (
                _is_number(v) and v >= 1
            ):
                fail(
                    f"{k!r} must be a positive core count, got {v!r}"
                )
        # SLO soak rows (ISSUE 11): every slo_* field is a measurement
        # by contract — numeric, never bool/None/prose — and attainment
        # fields are FRACTIONS in [0, 1] (the SLO gate compares them
        # against the pinned threshold; a value outside the unit
        # interval means the soak mis-counted).
        for k, v in rec["extra"].items():
            if not k.startswith("slo_"):
                continue
            if not _is_number(v):
                fail(f"{k!r} must be a real number, got {v!r}")
            if "attainment" in k and not (0.0 <= v <= 1.0):
                fail(
                    f"{k!r} must be an attainment fraction in [0, 1], "
                    f"got {v!r}"
                )
        # Cold-start rows (ISSUE 13): every coldstart_* field is a
        # measurement by contract — numeric, never bool/None/prose.
        # The paired warm-vs-AOT rows are only comparable when both
        # processes really booted and served (a missing side must fail
        # the emit, not ship as prose).
        for k, v in rec["extra"].items():
            if k.startswith("coldstart_") and not _is_number(v):
                fail(f"{k!r} must be a real number, got {v!r}")
        # Analysis-preflight provenance (ISSUE 12): every analysis_*
        # extra is a measurement by contract — finding/rule/file
        # counts and durations are numbers, never bool/None/prose
        # (validate_record is how the driver trusts the row ran a
        # real, cache-accounted invariant pass).
        for k, v in rec["extra"].items():
            if k.startswith("analysis_") and not _is_number(v):
                fail(f"{k!r} must be a real number, got {v!r}")
        # Shard-fused decode rows (ISSUE 14): every shard_fused_*
        # field is a measurement by contract — numeric, never
        # bool/None/prose (the candidate-vs-vocab gather comparison
        # and the fused/scan steps/s pair are only meaningful when
        # both arms really compiled and ran).  The *_mesh_shape and
        # provenance string fields keep their own formats below.
        for k, v in rec["extra"].items():
            if not k.startswith("shard_fused_"):
                continue
            if k.endswith(("_mesh_shape", "_xla_flags",
                           "_jax_platforms")):
                continue
            if k == "shard_fused_virtual_cpu":
                continue
            if not _is_number(v):
                fail(f"{k!r} must be a real number, got {v!r}")
        # Low-precision serving rows (ISSUE 16): every lowprec_* field
        # is a measurement by contract — numeric, never bool/None/
        # prose (the f32/bf16/int8w triple is only comparable when all
        # three arms really decoded at matched load), except the
        # provenance string fields which keep their own formats.  Any
        # *match_rate* field is a caption-match FRACTION in [0, 1]:
        # the relaxed-serving parity gate compares it against the
        # pinned floor before the row is ever emitted, and a value
        # outside the unit interval means the match counting is wrong.
        for k, v in rec["extra"].items():
            if not k.startswith("lowprec_"):
                continue
            if k.endswith(("_mesh_shape", "_xla_flags",
                           "_jax_platforms")):
                continue
            if not _is_number(v):
                fail(f"{k!r} must be a real number, got {v!r}")
            if "match_rate" in k and not (0.0 <= v <= 1.0):
                fail(
                    f"{k!r} must be a caption-match fraction in "
                    f"[0, 1], got {v!r}"
                )
        # Fused×int8w composition rows (ISSUE 20): lowprec_fused_*
        # rides the lowprec_* numeric contract above, plus two
        # closed-form invariants the bench asserts before emit and the
        # validator re-checks at the schema layer: every *_tile_ratio
        # is EXACTLY 0.25 (int8 code bytes over the f32 vocab tile —
        # any other value means the kernels stopped streaming int8
        # codes or the tile arithmetic drifted), and every *_declines
        # count is EXACTLY 0 (serving.dtype=int8w must never gate a
        # requested fused kernel off on a supported grid — the decline
        # lift IS the tentpole claim, so the schema enforces it).
        for k, v in rec["extra"].items():
            if not k.startswith("lowprec_fused_"):
                continue
            if k.endswith("_tile_ratio") and v != 0.25:
                fail(
                    f"{k!r} must be exactly 0.25 (int8 codes over the "
                    f"f32 vocab tile), got {v!r}"
                )
            if k.endswith("_declines") and (
                isinstance(v, bool) or v != 0
            ):
                fail(
                    f"{k!r} must be exactly 0 — int8w composes with "
                    f"the fused kernels by contract, got {v!r}"
                )
        # Speculative-decode rows (ISSUE 18): every spec_* field is a
        # measurement by contract — numeric, never bool/None/prose
        # (the paired spec/baseline rows are only comparable when both
        # arms really served at matched load, and the token-exactness
        # claim rides on spec_token_mismatches being a REAL count that
        # was asserted 0 before emit, not a True that leaked from a
        # comparison).  spec_acceptance_rate is a fraction in [0, 1];
        # the provenance string fields keep their own formats.
        for k, v in rec["extra"].items():
            if not k.startswith("spec_"):
                continue
            if k.endswith(("_mesh_shape", "_xla_flags",
                           "_jax_platforms")):
                continue
            if isinstance(v, bool):
                fail(f"{k!r} must be a real number, got a bool")
            if not _is_number(v):
                fail(f"{k!r} must be a real number, got {v!r}")
            if "acceptance_rate" in k and not (0.0 <= v <= 1.0):
                fail(
                    f"{k!r} must be an acceptance fraction in [0, 1], "
                    f"got {v!r}"
                )
        # Mesh topology is a machine-readable string by contract
        # (ISSUE 9): any *_mesh_shape field must look like "2x4" —
        # axis sizes joined by "x" in declared axis order.  A bool,
        # None, or free-prose value would make MULTICHIP/shard rows
        # unreproducible from the record alone.
        for k, v in rec["extra"].items():
            if k.endswith("_mesh_shape") and not (
                isinstance(v, str)
                and not isinstance(v, bool)
                and re.fullmatch(r"\d+(x\d+)+", v)
            ):
                fail(
                    f"{k!r} must be a \"2x4\"-style mesh string, "
                    f"got {v!r}"
                )
    elif kind == "multichip_partial":
        body = rec.get("dryrun_partial")
        if not isinstance(body, dict) or "phases" not in body:
            fail("'dryrun_partial' must be a dict with 'phases'")
        if not _is_number(rec.get("elapsed_s")):
            fail("'elapsed_s' must be a real number")
        for name, ph in body["phases"].items():
            if not isinstance(ph, dict) or not _is_number(ph.get("s")):
                fail(f"phase {name!r} missing numeric wall time 's'")
    elif kind == "multichip_stalled":
        if not isinstance(rec.get("dryrun_phase_stalled"), str):
            fail("'dryrun_phase_stalled' must name a phase")
        for key in ("phase_budget_s", "elapsed_s"):
            if not _is_number(rec.get(key)):
                fail(f"{key!r} must be a real number")
    else:
        fail(f"unknown record kind {kind!r}")
    return rec


def bench_chunk() -> int:
    return int(os.environ.get("BENCH_CHUNK", str(DEFAULT_CHUNK)))


def _msrvtt_cfg():
    from cst_captioning_tpu.config import get_preset

    cfg = get_preset("msrvtt_resnet_c3d_xe")
    cfg.model.vocab_size = 10496  # MSR-VTT-scale vocab, multiple of 256
    if os.environ.get("BENCH_PALLAS", "1") == "1":
        cfg.model.use_pallas_lstm = True
        cfg.model.use_pallas_attention = True
    # Attention-MLP width sweep knob (VERDICT r2 #5: tanh cost is linear
    # in att_hidden_size; the reference's 512 is convention, not physics).
    ah = os.environ.get("BENCH_ATT_HIDDEN", "")
    if ah:
        cfg.model.att_hidden_size = int(ah)
    return cfg


def _fake_batch(cfg, rng):
    B, S, F, T = (
        cfg.data.batch_size,
        cfg.data.seq_per_img,
        cfg.data.max_frames,
        cfg.data.max_seq_len,
    )
    batch = {
        "feats": {
            "resnet": rng.randn(B, F, 2048).astype(np.float32),
            "c3d": rng.randn(B, F, 4096).astype(np.float32),
        },
        "feat_masks": {
            "resnet": np.ones((B, F), np.float32),
            "c3d": np.ones((B, F), np.float32),
        },
        "captions": rng.randint(
            4, cfg.model.vocab_size, size=(B, S, T + 2)
        ).astype(np.int32),
        "weights": np.ones((B, S), np.float32),
        "category": np.zeros((B,), np.int32),
        "video_idx": np.arange(B, dtype=np.int32),
    }
    batch["captions"][:, :, 0] = 1  # BOS
    return batch


def xe_step_flops(cfg) -> float:
    """Analytic FLOPs per XE train step (fwd*3 for fwd+bwd), counting the
    GEMM families that dominate (SURVEY.md §3 hot loop #1): feature
    projections, the LSTM recurrence, the vocab logit GEMM — and, for
    attention fusion, the per-step Bahdanau attention work (query proj,
    score MLP over the concatenated frame axis, context reduction),
    which the round-2 bench left uncounted (ADVICE r2 #4)."""
    B, S, F, T = (
        cfg.data.batch_size,
        cfg.data.seq_per_img,
        cfg.data.max_frames,
        cfg.data.max_seq_len,
    )
    H = cfg.model.rnn_size
    E = cfg.model.input_encoding_size
    V = cfg.model.vocab_size
    rows = B * S          # caption sequences per step
    steps = T + 1         # scan length over [BOS..EOS] inputs
    proj = 2.0 * B * F * sum(cfg.data.feature_dims.values()) * E
    # LSTM: (input E + context E + hidden H) -> 4H gates, per token.
    lstm = 2.0 * rows * steps * (2 * E + H) * 4 * H
    logit = 2.0 * rows * steps * H * V
    attn = 0.0
    if cfg.model.feature_fusion == "attention":
        A = cfg.model.att_hidden_size
        F_att = F * len(cfg.data.feature_modalities)  # concat frame axis
        # One-time key projection (per VIDEO — like the feature
        # projections, computed before the seq_per_img cache tiling) +
        # per step per caption row: query proj (H -> A), score MLP
        # (add+tanh+dot over A per frame), context reduction over E.
        attn = (
            2.0 * B * F_att * E * A
            + 2.0 * rows * steps * (H * A + F_att * (A + E))
        )
    return 3.0 * (proj + lstm + logit + attn)


def bench_xe(fusion: str = "meanpool", chunk: int = None):
    from cst_captioning_tpu.models import model_from_config
    from cst_captioning_tpu.parallel import (
        batch_sharding,
        make_mesh,
        shard_batch,
    )
    from cst_captioning_tpu.training.steps import (
        create_train_state,
        make_optimizer,
        make_xe_train_step,
    )

    cfg = _msrvtt_cfg()
    cfg.model.feature_fusion = fusion
    batch = _fake_batch(cfg, np.random.RandomState(0))
    model = model_from_config(cfg)
    tx = make_optimizer(cfg.train, steps_per_epoch=100)
    # Data-parallel mesh over ALL chips (single chip degenerates to a 1-way
    # mesh) so the per-chip number divides honest work, not idle chips.
    mesh = make_mesh({"data": -1, "model": 1})
    state = create_train_state(
        jax.random.PRNGKey(0), model, tx, batch, mesh=mesh
    )
    step = make_xe_train_step(model)
    sh = batch_sharding(mesh)
    args = (
        shard_batch(batch["feats"], mesh),
        shard_batch(batch["feat_masks"], mesh),
        jax.device_put(jnp.asarray(batch["captions"]), sh),
        jax.device_put(jnp.asarray(batch["weights"]), sh),
        None,
        jax.device_put(jnp.asarray(batch["video_idx"]), sh),
    )

    # The per-step python dispatch crosses a (possibly tunneled) transport;
    # timing individual dispatches measures the tunnel, not the chip.  Run
    # CHUNK steps per dispatch under one jitted lax.scan and time that.
    # Measured per-dispatch overhead here is ~140ms, so chunk=10 (the
    # round-1 setting) under-reported the chip by ~25%; at 60 the residual
    # is ~7%.  NOTE for cross-round ratios: vs round-1 numbers recorded at
    # chunk=10, ~0.2x of any improvement is this measurement fix — the
    # matched-chunk algorithmic speedup this round is ~1.18x (rbg PRNG,
    # docs/PERF.md).
    chunk = chunk or bench_chunk()
    iters = int(os.environ.get("BENCH_ITERS", "6"))

    def run_chunk(state, rng, *op):
        def body(carry, k):
            st, _ = carry
            st, m = step(st, *op, k, 0.0)
            return (st, m["loss"]), None

        keys = jax.random.split(rng, chunk)
        (state, loss), _ = jax.lax.scan(body, (state, jnp.float32(0)), keys)
        return state, loss

    run_chunk = jax.jit(run_chunk, donate_argnums=(0,))

    # Warmup / compile.  float() forces a device->host transfer of the
    # result — block_until_ready alone can return early through the
    # remote-device transport.
    state, loss = run_chunk(state, jax.random.PRNGKey(7), *args)
    float(loss)

    rng = jax.random.PRNGKey(8)
    times = []
    for _ in range(iters):
        rng, k = jax.random.split(rng)
        t0 = time.perf_counter()
        state, loss = run_chunk(state, k, *args)
        float(loss)
        times.append(time.perf_counter() - t0)
    # Median chunk time: robust to transport hiccups.
    dt = sorted(times)[len(times) // 2]
    n_chips = max(1, len(jax.devices()))
    sps_chip = chunk / dt / n_chips
    tflops = xe_step_flops(cfg) * (chunk / dt) / n_chips / 1e12
    return sps_chip, tflops


class _RefCorpus:
    """Minimal CaptionDataset view for the rewarder: MSR-VTT-scale vocab,
    ``refs_per_video`` references of ``ref_len`` words per video."""

    def __init__(self, num_videos, refs_per_video=20, ref_len=10,
                 vocab_size=10496, seed=3):
        from cst_captioning_tpu.data.vocab import Vocabulary

        self.vocab = Vocabulary([f"w{i}" for i in range(vocab_size - 4)])
        rng = np.random.RandomState(seed)
        # Zipf-ish id draws so n-gram df tables have realistic collisions.
        ids = rng.zipf(1.3, size=(num_videos, refs_per_video, ref_len))
        ids = np.minimum(ids, vocab_size - 5)
        self._refs = [
            [" ".join(f"w{t - 1}" for t in ref) for ref in vid]
            for vid in ids
        ]

    def __len__(self):
        return len(self._refs)

    def references(self, i):
        return self._refs[i]


def bench_cst():
    """CST/SCST steps/sec/chip (driver config 4 shape) + host scorer cost.

    Uses whichever execution strategy ``make_cst_train_step`` picks for
    this backend (one-graph io_callback, or the split rollout/score/update
    pipeline on runtimes without host callbacks)."""
    from cst_captioning_tpu.models import model_from_config
    from cst_captioning_tpu.training.cst import (
        io_callback_supported,
        make_cst_train_step,
    )
    from cst_captioning_tpu.training.rewards import CiderDRewarder
    from cst_captioning_tpu.training.steps import (
        create_train_state,
        make_optimizer,
    )

    cfg = _msrvtt_cfg()
    cfg.train.train_mode = "cst"
    cfg.train.cst_baseline = "scb"
    cfg.train.cst_num_samples = cfg.data.seq_per_img  # 20 rollouts/video
    B = cfg.data.batch_size
    S = cfg.train.cst_num_samples
    corpus = _RefCorpus(num_videos=B * 4, vocab_size=cfg.model.vocab_size)

    batch = _fake_batch(cfg, np.random.RandomState(1))
    model = model_from_config(cfg)
    tx = make_optimizer(cfg.train, steps_per_epoch=100)
    rewarder = CiderDRewarder(corpus, df_mode="corpus")

    feats = {m: jnp.asarray(v) for m, v in batch["feats"].items()}
    masks = {m: jnp.asarray(v) for m, v in batch["feat_masks"].items()}
    vid = jnp.asarray(batch["video_idx"])
    iters = int(os.environ.get("BENCH_ITERS", "6"))

    def time_step(step_cfg):
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, batch, mesh=None
        )
        step = make_cst_train_step(model, step_cfg, corpus)
        state, metrics = step(  # warmup/compile
            state, feats, masks, None, None, None, vid,
            jax.random.PRNGKey(9), 0.0,
        )
        float(metrics["reward"])
        rng = jax.random.PRNGKey(10)
        pipelined = getattr(step, "layout", "") == "pipeline"
        times = []
        for _ in range(iters):
            rng, k = jax.random.split(rng)
            t0 = time.perf_counter()
            state, metrics = step(
                state, feats, masks, None, None, None, vid, k, 0.0
            )
            # Completion gate.  The pipelined step blocks internally on
            # its token fetch (the whole dispatched graph has executed by
            # then) and its loss is a device scalar from that same graph —
            # float()ing it would add a second transport round-trip per
            # step that the production trainer (which accumulates device
            # scalars and converts at epoch end) never pays.
            if not pipelined:
                float(metrics["loss"])
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2], step

    dt, timed_step = time_step(cfg)
    n_chips = max(1, len(jax.devices()))

    # Host scorer cost in isolation, on the same (B*S, T) id workload the
    # step scores each iteration (SURVEY.md hard part #1: must stay well
    # under the step time to hide behind device compute).
    ids = np.random.RandomState(2).randint(
        4, cfg.model.vocab_size, size=(B * S, cfg.data.max_seq_len)
    ).astype(np.int32)
    vid_r = np.repeat(np.arange(B, dtype=np.int32), S)
    rewarder.score_ids(vid_r, ids)  # warm caches
    t0 = time.perf_counter()
    reps = 5
    for _ in range(reps):
        rewarder.score_ids(vid_r, ids)
    scorer_ms = (time.perf_counter() - t0) / reps * 1e3

    from cst_captioning_tpu.training.cst import (
        _CHUNK_MAX_DISPATCH_MS,
        _chunk_count,
        dispatch_latency_ms,
    )

    lat = dispatch_latency_ms()
    if io_callback_supported():
        variant = "one_graph"
    elif getattr(timed_step, "layout", "") == "pipeline":
        variant = "split_pipeline"
    else:
        variant = "split"
    chunking_active = (
        variant == "split"
        and cfg.train.cst_score_chunks > 1
        and lat <= _CHUNK_MAX_DISPATCH_MS
    )
    out = {
        "cst_steps_per_sec_chip": round(1.0 / dt / n_chips, 4),
        "cst_variant": variant,
        # Whether the fused Pallas sampler (ops/pallas_sampler.py) is on
        # the rollout path for this run (TPU-gated in model_from_config).
        "cst_fused_sampler": bool(
            getattr(model, "use_pallas_sampler", False)
        ),
        # The EFFECTIVE chunk count the split step actually uses (the
        # divisor rule of _chunk_count, and 1 whenever per-dispatch
        # latency would cost more than the scoring overlap recovers —
        # tunneled runtimes — or the one-graph variant runs).
        "cst_score_chunks": (
            _chunk_count(cfg.train.cst_score_chunks, B)
            if chunking_active
            else 1
        ),
        "cst_dispatch_latency_ms": round(lat, 2),
        "cst_scorer_ms_per_step": round(scorer_ms, 2),
        "cst_scorer_backend": rewarder.backend,
        "cst_rollouts_per_step": B * S,
    }
    # Phase breakdown (VERDICT r3 #3): where a CST step's wall time goes.
    # The pipelined step self-reports its two host-visible phases; the
    # device-compute estimate subtracts the measured dispatch RTT from the
    # blocking fetch.
    phases = getattr(timed_step, "phase_ms", None)
    if phases:
        out.update({f"cst_phase_{k}": v for k, v in phases.items()})
        if "dispatch_and_device_ms" in phases:
            out["cst_phase_device_est_ms"] = round(
                phases["dispatch_and_device_ms"] - lat, 2
            )
    # Scorer-overlap evidence (VERDICT r2 #2): the split step's chunked
    # dispatch hides host scoring behind device compute; the unchunked
    # (K=1) variant serializes them — the delta IS the recovered stall.
    # Only measurable where chunking actually engages (low-latency
    # dispatch, i.e. a real TPU-VM host rather than a tunnel).
    if (
        out["cst_variant"] == "split"
        and chunking_active
        and os.environ.get("BENCH_CST_OVERLAP", "1") == "1"
    ):
        try:
            cfg1 = cfg.replace(**{"train.cst_score_chunks": 1})
            dt1, _ = time_step(cfg1)
            out["cst_steps_per_sec_chip_nochunk"] = round(
                1.0 / dt1 / n_chips, 4
            )
            out["cst_scorer_overlap_ms_recovered"] = round(
                (dt1 - dt) * 1e3, 2
            )
        except Exception as e:
            out["cst_overlap_error"] = f"{type(e).__name__}: {e}"
    return out


def _bench_cst_pipeline_impl():
    """Paired SERIAL-vs-PIPELINED CST step rows on the CPU smoke shape.

    Both rows run the SAME split CST step (``training/cst.py::
    _make_split_step``) on the same batch/params/rng — the serial row
    with in-place host scoring (``overlap_rewards=False``, no pool), the
    pipelined row with the overlapped schedule: rollout chunks fed to a
    ``RewardPool`` stream as they come off the device, greedy decode
    overlapping worker-side scoring, one blocking wait at the PG-update
    dispatch.  Rewards are bit-identical between the rows
    (``cst_pipe_reward_delta`` pins it at 0.0 in the record).

    Two pairs are measured:

    * **real** — the actual python scorer.  On a multi-core host the
      pool shards real scoring work; on THIS repo's 1-core dev host the
      workers time-slice with device compute, so sustained parity
      (~1.0) is the physical ceiling — ``cst_pipe_host_cores`` records
      the context (the PR-4 replica sweep precedent).
    * **modeled** — the scorer cost inflated with an idle per-row sleep
      sized to the measured device decode time (the
      ``tools/overlap_sim.py`` technique: sleep releases the GIL and
      burns no CPU, exactly like host scoring that runs on OTHER cores
      or beside a TPU).  This is the regime the overlap targets
      (MSR-VTT scorer ~44 ms vs device decode ~38 ms, docs/PERF.md);
      the pipelined row's win here is real measured wall clock, with
      the injected cost recorded alongside.

    Runs in a subprocess on the in-process CPU backend (see
    :func:`bench_cst_pipeline`).  Env: BENCH_CST_PIPE_BATCH,
    BENCH_CST_PIPE_ROLLOUTS, BENCH_CST_PIPE_WORKERS,
    BENCH_CST_PIPE_STEPS, BENCH_CST_PIPE_REPS."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.data import BatchIterator, make_synthetic_dataset
    from cst_captioning_tpu.models import model_from_config
    from cst_captioning_tpu.training import cst as cst_mod
    from cst_captioning_tpu.training.rewards import (
        CiderDRewarder,
        RewardPool,
    )
    from cst_captioning_tpu.training.steps import (
        create_train_state,
        make_optimizer,
    )

    B = int(os.environ.get("BENCH_CST_PIPE_BATCH", "32"))
    S = int(os.environ.get("BENCH_CST_PIPE_ROLLOUTS", "4"))
    workers = int(os.environ.get("BENCH_CST_PIPE_WORKERS", "4"))
    steps = int(os.environ.get("BENCH_CST_PIPE_STEPS", "5"))
    reps = int(os.environ.get("BENCH_CST_PIPE_REPS", "3"))
    rows = B * S

    ds, vocab = make_synthetic_dataset(
        num_videos=B * 2, max_frames=6, max_words=10, seed=11
    )
    cfg = get_preset("synthetic_smoke")
    cfg.data.batch_size = B
    cfg.data.seq_per_img = 2
    cfg.data.max_frames = 6
    cfg.data.max_seq_len = 10
    cfg.train.train_mode = "cst"
    cfg.train.cst_baseline = "greedy"  # exercises the greedy-decode overlap
    cfg.train.cst_num_samples = S
    cfg.train.cst_score_chunks = 2
    # Real decode compute for scoring to hide behind (overlap_sim sizing).
    cfg.model.rnn_size = 256
    cfg.model.vocab_size = len(vocab)
    model = model_from_config(cfg)
    it = BatchIterator(ds, batch_size=B, seq_per_img=2, max_frames=6,
                       shuffle=False)
    batch = next(iter(it.epoch(0)))
    tx = make_optimizer(cfg.train, 10)
    rewarder = CiderDRewarder(ds, backend="python")

    def build(overlap: bool, scorer):
        cfg_x = cfg.replace(**{"train.overlap_rewards": overlap})
        step = cst_mod._make_split_step(model, cfg_x, scorer)
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, batch._asdict()
        )
        state, m = step(  # compile/warm
            state, batch.feats, batch.feat_masks, batch.captions,
            batch.weights, None, batch.video_idx, jax.random.PRNGKey(7),
            0.0,
        )
        return step, [state], float(m["reward"])

    def sweep(step, box, rep: int) -> float:
        rng = jax.random.fold_in(jax.random.PRNGKey(5), rep)
        times = []
        for i in range(steps):
            k = jax.random.fold_in(rng, i)
            t0 = time.perf_counter()
            box[0], m = step(
                box[0], batch.feats, batch.feat_masks, batch.captions,
                batch.weights, None, batch.video_idx, k, 0.0,
            )
            float(m["loss"])
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2]

    class SleepScorer:
        """Serial-side twin of the pool's ``simulate_ms_per_row`` knob:
        the same idle per-row cost, slept inline.  Scores unchanged."""

        def __init__(self, inner, ms_per_row: float):
            self.inner, self.ms_per_row = inner, ms_per_row
            self.backend = inner.backend

        def score_ids(self, video_idx, token_ids):
            time.sleep(self.ms_per_row * token_ids.shape[0] / 1e3)
            return self.inner.score_ids(video_idx, token_ids)

        def gt_consensus(self):
            return self.inner.gt_consensus()

    # ------------------------------------------------- real-scorer pair
    step_s, box_s, reward_s = build(False, rewarder)
    pool_real = RewardPool(rewarder, workers)
    step_p, box_p, reward_p = build(True, pool_real)
    ts, tp = [], []
    for r in range(reps):  # interleaved: load shifts hit both rows
        ts.append(sweep(step_s, box_s, r))
        tp.append(sweep(step_p, box_p, r))
    real_serial = sorted(ts)[len(ts) // 2]
    real_pipe = sorted(tp)[len(tp) // 2]
    pool_real.close()

    # Parity: same params, same rng -> bit-identical rewards.
    reward_delta = abs(reward_s - reward_p)

    # ---------------------------------------------- modeled-cost pair
    # Size the injected scorer to the measured serial device+host step
    # so t_score ~ t_device — the MSR-VTT regime (docs/PERF.md).
    injected_ms = max(1.0, real_serial * 1e3)
    per_row = injected_ms / rows
    step_ms, box_ms, _ = build(False, SleepScorer(rewarder, per_row))
    pool_sim = RewardPool(
        rewarder, workers, simulate_ms_per_row=per_row
    )
    step_mp, box_mp, _ = build(True, pool_sim)
    tms, tmp = [], []
    for r in range(reps):
        tms.append(sweep(step_ms, box_ms, 100 + r))
        tmp.append(sweep(step_mp, box_mp, 100 + r))
    mod_serial = sorted(tms)[len(tms) // 2]
    mod_pipe = sorted(tmp)[len(tmp) // 2]
    pool_sim.close()

    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:
        cores = os.cpu_count() or 1
    phases = {
        f"cst_pipe_phase_{k}": v
        for k, v in dict(step_mp.phase_ms).items()
    }
    out = {
        "cst_pipe_host_cores": cores,
        "cst_pipe_workers": workers,
        "cst_pipe_rollout_rows": rows,
        "cst_pipe_score_chunks": 2,
        "cst_pipe_reward_delta": round(reward_delta, 9),
        # Real-scorer pair (tiny smoke corpus: scoring is cheap, and on
        # a 1-core host pool workers time-slice with device compute —
        # parity is the ceiling there; see docstring).
        "cst_pipe_real_serial_steps_per_sec": round(1.0 / real_serial, 3),
        "cst_pipe_real_overlap_steps_per_sec": round(1.0 / real_pipe, 3),
        "cst_pipe_real_speedup": round(real_serial / real_pipe, 3),
        # Modeled pair: scorer cost injected as GIL-releasing idle time
        # at ~1x device decode (the MSR-VTT scorer:decode ratio) — the
        # sustained serial-vs-pipelined comparison the overlap targets.
        "cst_pipe_injected_scorer_ms": round(injected_ms, 2),
        "cst_pipe_serial_steps_per_sec": round(1.0 / mod_serial, 3),
        "cst_pipe_overlap_steps_per_sec": round(1.0 / mod_pipe, 3),
        "cst_pipe_speedup": round(mod_serial / mod_pipe, 3),
        "cst_pipe_serial_step_ms": round(mod_serial * 1e3, 2),
        "cst_pipe_overlap_step_ms": round(mod_pipe * 1e3, 2),
    }
    out.update(phases)
    return out


def bench_cst_pipeline():
    """Serial-vs-pipelined CST reward scheduling, paired rows (see
    :func:`_bench_cst_pipeline_impl`).  Always re-execs into a
    subprocess pinned to the in-process CPU backend — the main bench
    process may hold the TPU, and the comparison targets the smoke
    shape by design (the overlap_sim precedent); runs in degraded mode
    too (no live backend required in the parent)."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_CST_PIPE_CHILD"] = "1"
    here = os.path.abspath(__file__)
    r = subprocess.run(
        [sys.executable, here],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(here),
    )
    lines = [
        ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")
    ]
    if r.returncode != 0 or not lines:
        tail = (r.stderr or r.stdout).strip().splitlines()
        raise RuntimeError(
            f"cst pipeline child rc={r.returncode}: "
            f"{tail[-1] if tail else 'no output'}"
        )
    return json.loads(lines[-1])


def _bench_cst_slot_impl():
    """Paired PADDED-vs-SLOT CST rollout rows on the CPU smoke shape
    (ISSUE 6 acceptance): both rows run the slot-machinery CST step
    (``training/cst.py::_make_slot_step``) with the row-keyed sampler —
    the padded row with every row resident for the full ``L`` decode
    steps (today's rollout cost), the slot row with rows exiting on EOS
    and harvests streamed to the scorer.  The token matrices are
    BIT-identical (row-keyed PRNG), so fixed-seed losses AND params are
    bit-identical between the rows — ``cst_slot_param_delta`` /
    ``cst_slot_loss_delta`` pin both at 0.0 in the record.

    A third row measures today's DEFAULT rollout (``cst_rollout=
    "scan"``: the fused-scan ``model.sample`` with the full-length PG
    update) for the end-to-end ratio.

    The decode really ends early because the smoke model's ``logit_b``
    is EOS-biased by ``BENCH_CST_SLOT_EOS_BIAS`` (recorded, with the
    resulting ``cst_slot_mean_len``): a randomly initialized smoke
    model would never emit EOS (P ~ 1/V per step) and every layout
    would pay the full L — the bias stands in for what a TRAINED
    captioner does naturally (MSR-VTT E[len] ~9-12 vs L 28-30).

    1-core-host caveat (the PR-4/PR-5 precedent): ``cst_slot_host_cores``
    records the CPU context; on real accelerators the win follows the
    E[len]/L arithmetic in docs/PERF.md r10 rather than the host's
    fixed per-dispatch costs."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.constants import EOS_ID
    from cst_captioning_tpu.data import (
        BatchIterator,
        make_synthetic_dataset,
    )
    from cst_captioning_tpu.models import model_from_config
    from cst_captioning_tpu.training import cst as cst_mod
    from cst_captioning_tpu.training.rewards import CiderDRewarder
    from cst_captioning_tpu.training.steps import (
        create_train_state,
        make_optimizer,
    )

    B = int(os.environ.get("BENCH_CST_SLOT_BATCH", "16"))
    S = int(os.environ.get("BENCH_CST_SLOT_ROLLOUTS", "4"))
    L = int(os.environ.get("BENCH_CST_SLOT_L", "64"))
    rnn = int(os.environ.get("BENCH_CST_SLOT_RNN", "192"))
    bias = float(os.environ.get("BENCH_CST_SLOT_EOS_BIAS", "2.8"))
    block = int(os.environ.get("BENCH_CST_SLOT_BLOCK", "2"))
    steps = int(os.environ.get("BENCH_CST_SLOT_STEPS", "5"))
    warm = int(os.environ.get("BENCH_CST_SLOT_WARM", "2"))
    rows = B * S + B  # rollout rows + greedy-baseline rows

    ds, vocab = make_synthetic_dataset(
        num_videos=B * 2, max_frames=6, max_words=10, seed=11
    )
    cfg = get_preset("synthetic_smoke")
    cfg.data.batch_size = B
    cfg.data.seq_per_img = 2
    cfg.data.max_frames = 6
    cfg.data.max_seq_len = L
    cfg.train.train_mode = "cst"
    cfg.train.cst_baseline = "greedy"
    cfg.train.cst_num_samples = S
    cfg.model.rnn_size = rnn
    cfg.model.vocab_size = len(vocab)
    model = model_from_config(cfg)
    it = BatchIterator(ds, batch_size=B, seq_per_img=2, max_frames=6,
                       shuffle=False)
    batch = next(iter(it.epoch(0)))
    tx = make_optimizer(cfg.train, 10)
    rewarder = CiderDRewarder(ds, backend="python")

    def bias_eos(params):
        p = dict(params)
        pp = dict(p["params"])
        lb = np.asarray(pp["logit_b"]).copy()
        lb[EOS_ID] += bias
        pp["logit_b"] = jnp.asarray(lb)
        p["params"] = pp
        return p

    def build(layout, slots=0):
        cfg_x = cfg.replace(**{
            "train.cst_rollout": layout,
            "train.cst_slot_count": slots,
            "train.cst_slot_block_steps": block,
        })
        if layout == "scan":
            step = cst_mod._make_split_step(model, cfg_x, rewarder)
        else:
            step = cst_mod._make_slot_step(model, cfg_x, rewarder, layout)
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, batch._asdict()
        )
        return step, [state.replace(params=bias_eos(state.params))]

    def sweep(step, box):
        ts, m = [], None
        for i in range(steps + warm):
            k = jax.random.fold_in(jax.random.PRNGKey(5), i)
            t0 = time.perf_counter()
            box[0], m = step(
                box[0], batch.feats, batch.feat_masks, batch.captions,
                batch.weights, None, batch.video_idx, k, 0.0,
            )
            float(m["loss"])
            if i >= warm:
                ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2], m

    cst_mod.dispatch_latency_ms.cache_clear()
    results, states, last_loss, stats = {}, {}, {}, {}
    for name, layout, slots in (
        ("scan", "scan", 0),
        ("padded", "padded", 0),
        ("slot", "slot", rows),
    ):
        step, box = build(layout, slots)
        t, m = sweep(step, box)
        results[name] = t
        states[name] = box[0]
        last_loss[name] = float(m["loss"])
        if name == "slot":
            stats = dict(step.rollout_stats)

    # Parity pin: same row-keyed tokens -> bit-identical params/losses.
    param_delta = float(
        max(
            jax.tree.leaves(
                jax.tree.map(
                    lambda a, b: jnp.max(jnp.abs(
                        a.astype(jnp.float32) - b.astype(jnp.float32)
                    )),
                    states["padded"].params, states["slot"].params,
                )
            )
        )
    )
    loss_delta = abs(last_loss["padded"] - last_loss["slot"])

    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:
        cores = os.cpu_count() or 1
    return {
        "cst_slot_host_cores": cores,
        "cst_slot_rows": rows,
        "cst_slot_L": L,
        "cst_slot_block_steps": block,
        "cst_slot_slots": stats.get("rollout_slots", rows),
        "cst_slot_eos_bias": bias,
        "cst_slot_mean_len": stats.get("rollout_mean_len"),
        # Decode-step accounting (ISSUE 6 satellite): steps each row
        # actually paid, plus the device tick/step totals per CST step.
        "cst_rollout_steps_per_row": stats.get("rollout_steps_per_row"),
        "cst_slot_harvest_ticks": stats.get("rollout_ticks"),
        "cst_slot_decode_steps": stats.get("rollout_decode_steps"),
        "cst_slot_update_trim_len": stats.get("update_trim_len"),
        "cst_slot_padded_steps_per_row": float(L),
        # The paired rows.
        "cst_slot_scan_steps_per_sec": round(1.0 / results["scan"], 3),
        "cst_slot_padded_steps_per_sec": round(
            1.0 / results["padded"], 3
        ),
        "cst_slot_steps_per_sec": round(1.0 / results["slot"], 3),
        "cst_slot_speedup": round(
            results["padded"] / results["slot"], 3
        ),
        "cst_slot_speedup_vs_scan": round(
            results["scan"] / results["slot"], 3
        ),
        "cst_slot_param_delta": param_delta,
        "cst_slot_loss_delta": round(loss_delta, 9),
    }


def bench_cst_slot():
    """Padded-vs-slot CST rollout pair (see :func:`_bench_cst_slot_impl`).
    Always re-execs into a subprocess pinned to the in-process CPU
    backend — the comparison targets the smoke shape by design and must
    run in degraded mode too (the bench_cst_pipeline precedent)."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_CST_SLOT_CHILD"] = "1"
    here = os.path.abspath(__file__)
    r = subprocess.run(
        [sys.executable, here],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(here),
    )
    lines = [
        ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")
    ]
    if r.returncode != 0 or not lines:
        tail = (r.stderr or r.stdout).strip().splitlines()
        raise RuntimeError(
            f"cst slot child rc={r.returncode}: "
            f"{tail[-1] if tail else 'no output'}"
        )
    return json.loads(lines[-1])


def bench_decode():
    """Inference throughput: greedy decode (the per-epoch validation
    pass) and beam-5 decode (the test eval), videos/sec on one chip at
    MSR-VTT shape.  Records whether the fused beam kernel
    (ops/pallas_beam.py) engaged (``beam_fused``), and when it did,
    re-times the lax.scan path as ``beam{K}_videos_per_sec_scan`` so the
    kernel's win is machine-readable against the same weights (the
    BENCH_r03 scan-path record was 2388 videos/s ± 40% spread)."""
    from cst_captioning_tpu.decoding.beam import (
        fused_beam_engaged,
        make_beam_search_fn,
    )
    from cst_captioning_tpu.models import model_from_config
    from cst_captioning_tpu.training.steps import make_greedy_sample_fn

    cfg = _msrvtt_cfg()
    B = cfg.data.batch_size
    batch = _fake_batch(cfg, np.random.RandomState(3))
    model = model_from_config(cfg)
    feats = {m: jnp.asarray(v) for m, v in batch["feats"].items()}
    masks = {m: jnp.asarray(v) for m, v in batch["feat_masks"].items()}
    params = model.init(
        jax.random.PRNGKey(0), feats, masks,
        jnp.ones((B, 2), jnp.int32),
    )
    engaged, _ = fused_beam_engaged(model, feats, cfg.eval.beam_size)
    out = {"beam_fused": bool(engaged)}
    greedy = make_greedy_sample_fn(model, cfg.eval.max_decode_len)
    beam = make_beam_search_fn(
        model, beam_size=cfg.eval.beam_size,
        max_len=cfg.eval.max_decode_len,
    )

    first_m = next(iter(feats))

    def timed(fn, label):
        def reps(params):
            def body(c, _):
                # Carry-dependent input perturbation (numerically zero,
                # but data-dependent) so loop-invariant code motion can't
                # hoist the decode out of the scan and deflate dt.
                bump = jnp.where(c == jnp.int32(-1), 1e-6, 0.0)
                f = dict(feats)
                f[first_m] = f[first_m] + bump
                toks = fn(params, f)
                return c + toks.sum(), None
            acc, _ = jax.lax.scan(body, jnp.int32(0), None, length=5)
            return acc
        r = jax.jit(reps)
        float(r(params))
        ts = []
        for _ in range(4):
            t0 = time.perf_counter()
            float(r(params))
            ts.append(time.perf_counter() - t0)
        ts.sort()
        dt = ts[len(ts) // 2] / 5
        out[label] = round(B / dt, 1)
        # Tunnel/transport noise indicator (VERDICT r2 weak #5: decode
        # numbers drifted between docs with no variance statement).
        out[f"{label}_spread_pct"] = round(
            100.0 * (ts[-1] - ts[0]) / ts[len(ts) // 2], 1
        )

    timed(
        lambda p, f: greedy(p, f, masks, None), "greedy_videos_per_sec"
    )
    timed(
        lambda p, f: beam(p, f, masks, None).tokens,
        f"beam{cfg.eval.beam_size}_videos_per_sec",
    )
    if engaged:
        # Same weights through the scan path: the fused-vs-scan delta in
        # one record (flags don't change the param pytree).
        cfg_scan = cfg.replace(**{"model.use_pallas_beam": False})
        beam_scan = make_beam_search_fn(
            model_from_config(cfg_scan), beam_size=cfg.eval.beam_size,
            max_len=cfg.eval.max_decode_len,
        )
        timed(
            lambda p, f: beam_scan(p, f, masks, None).tokens,
            f"beam{cfg.eval.beam_size}_videos_per_sec_scan",
        )
    return out


def bench_serving():
    """Serving subsystem sweep (serving/): CONTINUOUS in-flight batching
    (slot loop) vs the batch-at-a-time shape LADDER, paired row for row
    on the same engine, same mixed-length synthetic workload, same
    offered load.

    Workload: random weights decode almost every caption to the length
    cap, which would hide what continuous batching is for — so the EOS
    logit bias is calibrated (bisection) until ~75% of a feature pool
    decodes short (slot occupancy <= L/2) and ~25% rides to the cap,
    approximating the MSR-VTT short-caption/long-cap regime.  Each
    measured request is a unique pool item (tier-1 hits would otherwise
    dominate both modes and mask the decode comparison).

    Two load patterns per mode:
    * closed-loop: N clients, back-to-back requests -> max sustained
      captions/s (capacity) + p50/p99;
    * open-loop: a fixed arrival schedule at the geometric mean of the
      two measured capacities — an offered load the ladder cannot
      sustain but the slot loop can — plus a 0.6x-ladder-capacity
      underload control point.

    On TPU the engine runs the MSR-VTT shape (driver config 5: beam-5,
    resnet+c3d); on CPU hosts a small-but-not-trivial shape
    (rnn256/V2048/K3/L24) keeps device step time above dispatch noise
    while the sweep stays seconds; `serving_shape` records which ran.
    Env: BENCH_SERVING_REQS (requests per client per closed-loop point,
    default 8), BENCH_SERVING_CLIENTS (default "2,8,16"),
    BENCH_SERVING_OPEN_N (open-loop requests per point, default 300)."""
    import threading

    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.constants import EOS_ID, PAD_ID
    from cst_captioning_tpu.data.vocab import Vocabulary
    from cst_captioning_tpu.serving.batcher import (
        ContinuousBatcher,
        MicroBatcher,
    )
    from cst_captioning_tpu.serving.engine import InferenceEngine
    from cst_captioning_tpu.serving.metrics import ServingMetrics

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = _msrvtt_cfg()
        cfg.eval.beam_size = 5
        vocab = Vocabulary(
            [f"w{i}" for i in range(cfg.model.vocab_size - 4)]
        )
        cfg.serving.max_batch_size = cfg.data.batch_size
        cfg.serving.batch_shapes = [8, 16, 32, 64]
        cfg.serving.num_slots = cfg.data.batch_size
        shape = "msrvtt"
    else:
        # Small-but-real CPU shape: one decode step at S*K rows costs
        # ~1ms, so the continuous/ladder split measures decode steps,
        # not python dispatch.
        cfg = get_preset("synthetic_smoke")
        cfg.model.rnn_size = 256
        cfg.model.input_encoding_size = 256
        cfg.model.att_hidden_size = 256
        cfg.data.feature_dims = {"resnet": 512}
        cfg.data.max_frames = 16
        cfg.eval.beam_size = 3
        cfg.eval.max_decode_len = 24
        vocab = Vocabulary([f"w{i}" for i in range(2044)])
        cfg.model.vocab_size = len(vocab)
        cfg.serving.max_batch_size = 8
        cfg.serving.batch_shapes = [1, 2, 4, 8]
        cfg.serving.num_slots = 8
        shape = "smoke"
    cfg.serving.max_wait_ms = 5.0
    cfg.serving.queue_depth = 4096  # sweep measures latency, not rejects
    cfg.serving.slot_block_steps = 2
    cfg.serving.warmup = True
    cfg.serving.continuous = True   # warmup covers BOTH dispatch paths
    engine = InferenceEngine(cfg, random_init=True, vocab=vocab)
    decoder = engine.slot_decoder()
    L = cfg.eval.max_decode_len

    # ---------------- mixed-length workload calibration ----------------
    rng = np.random.RandomState(17)
    F = cfg.data.max_frames
    n_pool = 128
    pool = [
        {
            "features": {
                m: rng.randn(F, d).astype(np.float32)
                for m, d in cfg.data.feature_dims.items()
            }
        }
        for _ in range(n_pool)
    ]
    prepared = [engine.prepare(q) for q in pool]
    base_logit_b = np.asarray(engine.params["params"]["logit_b"]).copy()

    def set_eos_bias(delta):
        b = base_logit_b.copy()
        b[EOS_ID] += delta
        p = dict(engine.params)
        pp = dict(p["params"])
        pp["logit_b"] = jnp.asarray(b)
        p["params"] = pp
        engine.params = p

    def slot_occupancy(idx):
        """Decode steps until each request's slot frees — the quantity
        continuous batching actually saves (for beam: until the LAST
        beam finishes, not the winning caption's length)."""
        steps = {}
        pending = list(idx)
        while pending or decoder.occupied:
            adm = []
            while pending and len(adm) < min(
                len(decoder.free), decoder.admit_cap
            ):
                adm.append(pending.pop())
            done = decoder.tick([prepared[i] for i in adm], adm)
            for i, _, _, st in decoder.harvest_many(done):
                steps[i] = st
        return np.asarray([steps[i] for i in idx])

    probe = list(range(32))
    lo, hi = 0.0, 8.0
    for _ in range(9):
        mid = (lo + hi) / 2
        set_eos_bias(mid)
        frac_short = float((slot_occupancy(probe) <= L // 2).mean())
        if frac_short < 0.75:
            lo = mid
        else:
            hi = mid
    eos_bias = hi
    set_eos_bias(eos_bias)
    occ = slot_occupancy(list(range(n_pool)))
    short = [i for i in range(n_pool) if occ[i] <= L // 2]
    long_ = [i for i in range(n_pool) if occ[i] > L // 2]
    if not short or not long_:
        # Degenerate weights: fall back to an unlabeled pool; the rows
        # still pair, the short/long split is just absent.
        short = short or list(range(n_pool))
        long_ = long_ or list(range(n_pool))
    workload = {
        "eos_bias": round(eos_bias, 4),
        "pool": n_pool,
        "short": len(short),
        "long": len(long_),
        "mean_occupancy_steps": round(float(occ.mean()), 2),
        "max_steps": L,
    }

    def picks(n, seed):
        """75/25 short/long mixed draw (unique-leaning)."""
        r = np.random.RandomState(seed)
        n_long = max(1, int(round(n * 0.25)))
        ks = list(r.choice(long_, size=n_long, replace=True))
        ks += list(r.choice(short, size=n - n_long, replace=True))
        r.shuffle(ks)
        return ks

    def make_batcher(mode, metrics):
        cls = ContinuousBatcher if mode == "continuous" else MicroBatcher
        return cls(engine, metrics)

    def summarize(lat_ms, wall, metrics, errors):
        return {
            "captions_per_sec": round(len(lat_ms) / wall, 2)
            if wall > 0 else None,
            "p50_ms": round(np.percentile(lat_ms, 50), 2)
            if lat_ms else None,
            "p99_ms": round(np.percentile(lat_ms, 99), 2)
            if lat_ms else None,
            "served": metrics.requests_served.value,
            "steps_per_caption": round(
                metrics.steps_per_caption.snapshot()["mean_ms"], 2
            ),
            "errors": len(errors),
            "error_sample": errors[:3],
        }

    def run_closed(mode, n_clients, reqs_per_client):
        engine.cache.captions.clear()
        metrics = ServingMetrics()
        batcher = make_batcher(mode, metrics)
        lat_ms, errors = [], []
        lock = threading.Lock()
        assign = {
            c: picks(reqs_per_client, 1000 + c) for c in range(n_clients)
        }

        def client(cid):
            for k in assign[cid]:
                t0 = time.perf_counter()
                try:
                    batcher.submit(pool[k], deadline_ms=120_000.0)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")
                    continue
                with lock:
                    lat_ms.append((time.perf_counter() - t0) * 1e3)

        with batcher:
            threads = [
                threading.Thread(target=client, args=(c,))
                for c in range(n_clients)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
        point = summarize(lat_ms, wall, metrics, errors)
        point["queue_p50_ms"] = round(
            metrics.stages[
                "admission" if mode == "continuous" else "queue"
            ].percentile(50), 2,
        )
        point["device_p50_ms"] = round(
            metrics.stages["device"].percentile(50), 2
        )
        point["mean_batch"] = round(metrics.mean_batch_size(), 2)
        return point

    def run_open(mode, rate_cps, n):
        """Fixed arrival schedule — the literal same offered load for
        both modes."""
        engine.cache.captions.clear()
        metrics = ServingMetrics()
        batcher = make_batcher(mode, metrics)
        lat_ms, errors = [], []
        lock = threading.Lock()
        ks = picks(n, 11)

        def worker(k):
            t0 = time.perf_counter()
            try:
                batcher.submit(pool[k], deadline_ms=120_000.0)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return
            with lock:
                lat_ms.append((time.perf_counter() - t0) * 1e3)

        with batcher:
            threads = []
            t_start = time.perf_counter()
            for i, k in enumerate(ks):
                target = t_start + i / rate_cps
                now = time.perf_counter()
                if target > now:
                    time.sleep(target - now)
                th = threading.Thread(target=worker, args=(k,))
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
            wall = time.perf_counter() - t_start
        point = summarize(lat_ms, wall, metrics, errors)
        point["offered_cps"] = round(rate_cps, 1)
        return point

    reqs_per_client = int(os.environ.get("BENCH_SERVING_REQS", "8"))
    clients = [
        int(c)
        for c in os.environ.get("BENCH_SERVING_CLIENTS", "2,8,16").split(",")
    ]
    open_n = int(os.environ.get("BENCH_SERVING_OPEN_N", "300"))

    out = {"serving_shape": shape, "serving_workload": workload}
    sweep = {"continuous": {}, "ladder": {}}
    for n_clients in clients:
        for mode in ("continuous", "ladder"):
            sweep[mode][f"clients{n_clients}"] = run_closed(
                mode, n_clients, reqs_per_client
            )

    # Open loop: pick the offered load between the two measured
    # capacities (the region continuous mode unlocks) + an underload
    # control at 0.6x ladder capacity.
    top = f"clients{max(clients)}"
    lad_cap = sweep["ladder"][top]["captions_per_sec"] or 1.0
    cont_cap = sweep["continuous"][top]["captions_per_sec"] or 1.0
    mid_rate = float(np.sqrt(lad_cap * cont_cap))
    for name, rate in (
        ("underload", 0.6 * lad_cap),
        ("over_ladder_capacity", mid_rate),
    ):
        for mode in ("continuous", "ladder"):
            sweep[mode][f"open_{name}"] = run_open(mode, rate, open_n)

    # Headline extras: the paired open-loop point (same offered load)
    # and the closed-loop capacity split.
    oc = sweep["continuous"]["open_over_ladder_capacity"]
    ol = sweep["ladder"]["open_over_ladder_capacity"]
    c8 = sweep["continuous"].get("clients8") or sweep["continuous"][top]
    out.update({
        "serving_captions_per_sec": c8["captions_per_sec"],
        "serving_p50_ms": c8["p50_ms"],
        "serving_p99_ms": c8["p99_ms"],
        "serving_capacity_continuous": cont_cap,
        "serving_capacity_ladder": lad_cap,
        "serving_capacity_ratio": round(cont_cap / lad_cap, 3),
        "serving_offered_load_cps": round(mid_rate, 1),
        "serving_offered_p99_continuous_ms": oc["p99_ms"],
        "serving_offered_p99_ladder_ms": ol["p99_ms"],
        "serving_offered_p99_ratio": round(
            (ol["p99_ms"] or 0.0) / oc["p99_ms"], 3
        ) if oc["p99_ms"] else None,
        "serving_steps_per_caption": oc["steps_per_caption"],
        "serving_max_decode_len": L,
        "serving_dropped_live": (
            oc["errors"] + ol["errors"]
        ),
    })
    out["serving_sweep"] = sweep
    return out


def _bench_trace_overhead_impl():
    """Paired tracing-ON vs tracing-OFF serving rows (ISSUE 10).

    Same weights, same workload, same closed-loop load, two engines
    that differ ONLY in ``serving.tracing`` — the on side pays the full
    span load (root + queue/admit/decode/detok per request, plus the
    slot loop's tick_dispatch/tick_wait/harvest), the off side runs the
    disabled no-op tracer.  Acceptance bar: overhead <= 2% on sustained
    captions/s (recorded honestly either way; the 1-core dev host's
    noise floor rides in ``trace_overhead_host_cores``).

    Env: BENCH_TRACE_REQS (requests per client per rep, default 40 —
    short runs are dominated by 1-core scheduling noise),
    BENCH_TRACE_CLIENTS (default 4), BENCH_TRACE_REPS (default 3 —
    best-of pairing, same discipline as the other CPU pairs).
    """
    import threading

    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.data.vocab import Vocabulary
    from cst_captioning_tpu.observability.trace import get_tracer
    from cst_captioning_tpu.serving.batcher import ContinuousBatcher
    from cst_captioning_tpu.serving.engine import InferenceEngine
    from cst_captioning_tpu.serving.metrics import ServingMetrics

    reqs_per_client = int(os.environ.get("BENCH_TRACE_REQS", "40"))
    n_clients = int(os.environ.get("BENCH_TRACE_CLIENTS", "4"))
    reps = int(os.environ.get("BENCH_TRACE_REPS", "3"))

    vocab = Vocabulary([f"w{i}" for i in range(1020)])

    def build(tracing: bool):
        cfg = get_preset("synthetic_smoke")
        cfg.model.rnn_size = 128
        cfg.model.input_encoding_size = 128
        cfg.model.att_hidden_size = 128
        cfg.data.feature_dims = {"resnet": 256}
        cfg.data.max_frames = 8
        cfg.model.vocab_size = len(vocab)
        cfg.eval.beam_size = 3
        cfg.eval.max_decode_len = 16
        cfg.serving.decode_mode = "beam"
        cfg.serving.max_batch_size = 8
        cfg.serving.batch_shapes = [1, 2, 4, 8]
        cfg.serving.num_slots = 8
        cfg.serving.queue_depth = 4096
        cfg.serving.slot_block_steps = 2
        cfg.serving.tracing = tracing
        return InferenceEngine(cfg, random_init=True, vocab=vocab)

    rng = np.random.RandomState(23)
    n_pool = 64
    pool = [
        {
            "features": {
                "resnet": rng.randn(8, 256).astype(np.float32)
            }
        }
        for _ in range(n_pool)
    ]

    tracer = get_tracer()

    def run_closed(engine, traced: bool):
        engine.cache.captions.clear()
        metrics = ServingMetrics()
        batcher = ContinuousBatcher(engine, metrics)
        lat_ms, errors = [], []
        lock = threading.Lock()

        def client(cid):
            r = np.random.RandomState(7000 + cid)
            for _ in range(reqs_per_client):
                k = int(r.randint(0, n_pool))
                trace = (
                    (tracer.new_trace_id(), tracer.new_span_id())
                    if traced else None
                )
                t0 = time.perf_counter()
                try:
                    batcher.submit(
                        pool[k], deadline_ms=120_000.0, trace=trace
                    )
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")
                    continue
                with lock:
                    lat_ms.append((time.perf_counter() - t0) * 1e3)

        with batcher:
            threads = [
                threading.Thread(target=client, args=(c,))
                for c in range(n_clients)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
        if errors:
            raise RuntimeError(f"trace bench dropped requests: {errors[:3]}")
        return (
            len(lat_ms) / wall,
            float(np.percentile(lat_ms, 99)) if lat_ms else 0.0,
        )

    eng_on = build(True)
    eng_off = build(False)
    best = {"on": (0.0, 0.0), "off": (0.0, 0.0)}
    for _ in range(reps):
        for key, eng, traced in (
            ("on", eng_on, True), ("off", eng_off, False),
        ):
            cps, p99 = run_closed(eng, traced)
            if cps > best[key][0]:
                best[key] = (cps, p99)
    on_cps, on_p99 = best["on"]
    off_cps, off_p99 = best["off"]
    spans = sum(1 for _ in tracer.spans())
    return {
        "trace_overhead_captions_per_sec_on": round(on_cps, 2),
        "trace_overhead_captions_per_sec_off": round(off_cps, 2),
        # sustained-throughput ratio on/off: 1.0 = free, 0.98 = the
        # 2% acceptance bar.
        "trace_overhead_ratio": round(on_cps / off_cps, 4),
        "trace_overhead_pct": round(
            (1.0 - on_cps / off_cps) * 100.0, 2
        ),
        "trace_overhead_p99_on_ms": round(on_p99, 2),
        "trace_overhead_p99_off_ms": round(off_p99, 2),
        "trace_overhead_p99_delta_ms": round(on_p99 - off_p99, 2),
        "trace_overhead_spans": spans,
        "trace_overhead_reqs": n_clients * reqs_per_client,
        "trace_overhead_host_cores": float(os.cpu_count() or 1),
    }


def bench_trace_overhead():
    """Tracing on/off serving pair (see
    :func:`_bench_trace_overhead_impl`).  Re-execs into a CPU
    subprocess (the bench_slot_mem precedent): the pairing targets the
    smoke shape by design and must not disturb the TPU-held parent."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_TRACE_CHILD"] = "1"
    here = os.path.abspath(__file__)
    r = subprocess.run(
        [sys.executable, here],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(here),
    )
    lines = [
        ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")
    ]
    if r.returncode != 0 or not lines:
        tail = (r.stderr or r.stdout).strip().splitlines()
        raise RuntimeError(
            f"trace overhead child rc={r.returncode}: "
            f"{tail[-1] if tail else 'no output'}"
        )
    return json.loads(lines[-1])


# --------------------------------------------------------- SLO gate
#
# The chaos-soak rows (ISSUE 11) turn the bench from a speedometer into
# a survival certificate: slo_reference_attainment is the fraction of
# recorded-trace requests a healthy fleet served under deadline at the
# reference load.  A change that drops it below the pinned threshold
# fails the WHOLE bench run loudly (exit 3, named reason) — the SLO
# regression gate.
SLO_GATE_METRIC = "slo_reference_attainment"
# The pinned threshold; BENCH_SLO_GATE_MIN overrides it so the failure
# path is demonstrable from the shell (set it above the measured
# attainment and the run exits 3 with the named reason).
SLO_GATE_MIN = float(os.environ.get("BENCH_SLO_GATE_MIN", "0.9"))


def bench_exit_code(measured: bool, errors: dict) -> int:
    """The bench process's exit-code contract: 3 = the SLO regression
    gate tripped (a named, dedicated failure — it outranks 'something
    was measured'), 0 = at least one metric landed, 1 = nothing at
    all was measured."""
    if "slo_gate" in errors:
        return 3
    return 0 if measured else 1


def slo_gate(extra: dict):
    """Evaluate the SLO regression gate over an emitted extras dict.
    Returns None when the gate passes (or the soak didn't run), else
    the named failure reason the driver surfaces."""
    v = extra.get(SLO_GATE_METRIC)
    if v is None:
        return None
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return (
            f"slo_regression: {SLO_GATE_METRIC} is non-numeric "
            f"({v!r}) — the soak mis-reported"
        )
    if v < SLO_GATE_MIN:
        return (
            f"slo_regression: {SLO_GATE_METRIC}={v:.3f} fell below the "
            f"pinned threshold {SLO_GATE_MIN} at reference load"
        )
    return None


def _bench_slo_impl():
    """Chaos soak + SLO-attainment rows (ISSUE 11): replay recorded
    arrival traces against a real 2-replica ``ReplicaSet`` through the
    virtual-time soak harness (serving/chaos.py::run_soak — the
    single-threaded drive that makes every shed/requeue/expiry decision
    deterministic in the chaos seed).

    Scenarios:

    * **reference** — steady load a healthy fleet sustains; its
      attainment is the SLO gate's input (``slo_reference_attainment``).
    * **chaos** — a diurnal burst trace with mid-traffic chaos (one
      replica kill + periodic tick stalls + queue bursts + cache-miss
      storms + deadline-adjacent arrivals) at overload: per-priority
      attainment shows the degradation ladder holding (interactive >=
      best-effort), with zero lost requests.  The chaos scenario replays
      TWICE with the same seed; ``slo_replay_mismatches`` counts
      decision-log divergences (0 = deterministic, the acceptance bar).

    Env: BENCH_SLO_SEED (default 1123), BENCH_SLO_REQS (requests per
    scenario, default 60)."""
    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.data.vocab import Vocabulary
    from cst_captioning_tpu.serving.chaos import (
        ChaosEngine,
        make_diurnal_trace,
        run_soak,
    )
    from cst_captioning_tpu.serving.engine import InferenceEngine
    from cst_captioning_tpu.serving.metrics import ServingMetrics
    from cst_captioning_tpu.serving.replicas import ReplicaSet

    seed = int(os.environ.get("BENCH_SLO_SEED", "1123"))
    n_reqs = int(os.environ.get("BENCH_SLO_REQS", "60"))

    cfg = get_preset("synthetic_smoke")
    cfg.serving.warmup = False
    cfg.serving.num_slots = 4
    vocab = Vocabulary([f"w{i}" for i in range(252)])
    cfg.model.vocab_size = len(vocab)
    engine = InferenceEngine(cfg, random_init=True, vocab=vocab)
    dev = jax.devices()[0]
    clones = [
        engine.clone_for_device(dev, replica_id=i) for i in range(2)
    ]

    rng = np.random.RandomState(seed)
    F = cfg.data.max_frames
    n_keys = 24
    payloads = [
        {
            "features": {
                m: rng.randn(F, d).astype(np.float32).tolist()
                for m, d in cfg.data.feature_dims.items()
            }
        }
        for _ in range(n_keys)
    ]

    def fresh_rs(queue_depth):
        for c in clones:
            c.cache.captions.clear()
        return ReplicaSet(
            clones, ServingMetrics(), queue_depth=queue_depth,
        )

    # ---- reference scenario: healthy fleet, sustainable steady load
    ref_trace = make_diurnal_trace(
        seed, n_reqs, n_keys, base_per_tick=0.5, burst_factor=1.0,
    )
    ref_slo_ticks = 60
    rs = fresh_rs(queue_depth=256)
    t0 = time.perf_counter()
    ref = run_soak(rs, payloads, ref_trace)
    ref_wall = time.perf_counter() - t0
    ref_att = ref.attainment(ref_slo_ticks)

    # ---- attainment curve (ISSUE 18): the SAME healthy fleet at three
    # offered-load points (base_per_tick 0.5 / 2.0 / 4.0, no chaos) —
    # the knee of attainment-vs-load is what capacity planning reads,
    # and a single reference point can't show it.  The 0.5 point IS the
    # reference scenario above (same trace parameters), so it re-uses
    # that run instead of soaking twice.  The curve's latency bound is
    # tighter than the gate's (queueing delay, not just service time):
    # at the reference bound every point saturates at 1.0 and the knee
    # is invisible.
    curve_slo_ticks = 20
    curve = {}
    for tag, load in (("050", 0.5), ("200", 2.0), ("400", 4.0)):
        if load == 0.5:
            rep = ref
        else:
            trace = make_diurnal_trace(
                seed, n_reqs, n_keys,
                base_per_tick=load, burst_factor=1.0,
            )
            rep = run_soak(fresh_rs(queue_depth=256), payloads, trace)
        curve[f"slo_attainment_curve_load{tag}"] = round(
            rep.attainment(curve_slo_ticks)["overall"], 4
        )
        curve[f"slo_curve_served_load{tag}"] = float(rep.served)
    curve["slo_curve_slo_ticks"] = float(curve_slo_ticks)

    # ---- chaos scenario: diurnal burst + mid-traffic chaos, overload
    chaos_schedule = [
        {"site": "replica_kill", "at": 8, "replica": 0},
        {"site": "tick_stall", "every": 5, "replica": 1, "value": 0.02},
        {"site": "queue_burst", "every": 7, "value": 4},
        {"site": "cache_miss", "p": 0.2},
        {"site": "deadline_skew", "every": 17, "value": 0.0},
    ]
    chaos_trace = make_diurnal_trace(
        seed + 1, n_reqs, n_keys, base_per_tick=1.0, burst_factor=6.0,
    )
    chaos_slo_ticks = 40

    def chaos_run():
        rs = fresh_rs(queue_depth=6)
        ce = ChaosEngine(seed=seed, schedule=chaos_schedule)
        rep = run_soak(rs, payloads, chaos_trace, chaos=ce)
        return rep

    r1 = chaos_run()
    r2 = chaos_run()
    mismatches = sum(
        1 for a, b in zip(r1.decisions, r2.decisions) if a != b
    ) + abs(len(r1.decisions) - len(r2.decisions)) + sum(
        1 for a, b in zip(r1.chaos_log, r2.chaos_log) if a != b
    ) + abs(len(r1.chaos_log) - len(r2.chaos_log))
    att = r1.attainment(chaos_slo_ticks)

    return {
        "chaos_soak_shape": "smoke",
        "slo_host_cores": float(os.cpu_count() or 1),
        "slo_chaos_seed": float(seed),
        "slo_requests": float(n_reqs),
        "slo_reference_attainment": round(ref_att["overall"], 4),
        "slo_reference_ticks": float(ref.ticks),
        "slo_reference_wall_s": round(ref_wall, 2),
        "slo_reference_lost": float(ref.lost),
        "slo_curve_points": 3.0,
        **curve,
        "slo_chaos_attainment_overall": round(att["overall"], 4),
        "slo_chaos_attainment_interactive": round(
            att.get("interactive", 0.0), 4
        ),
        "slo_chaos_attainment_batch": round(att.get("batch", 0.0), 4),
        "slo_chaos_attainment_best_effort": round(
            att.get("best_effort", 0.0), 4
        ),
        "slo_chaos_lost": float(r1.lost),
        "slo_chaos_kills": float(r1.kills),
        "slo_chaos_stall_ticks": float(r1.stall_ticks),
        "slo_chaos_served": float(r1.served),
        "slo_chaos_shed": float(r1.count("shed")),
        "slo_chaos_expired": float(r1.count("expired")),
        "slo_chaos_faults_fired": float(len(r1.chaos_log)),
        "slo_replay_mismatches": float(mismatches),
    }


def bench_slo():
    """Chaos soak + SLO rows (see :func:`_bench_slo_impl`).  Re-execs
    into a CPU subprocess (the bench_trace_overhead precedent): the
    soak targets the smoke shape and must not disturb the TPU-held
    parent."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_SLO_CHILD"] = "1"
    here = os.path.abspath(__file__)
    r = subprocess.run(
        [sys.executable, here],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(here),
    )
    lines = [
        ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")
    ]
    if r.returncode != 0 or not lines:
        tail = (r.stderr or r.stdout).strip().splitlines()
        raise RuntimeError(
            f"slo soak child rc={r.returncode}: "
            f"{tail[-1] if tail else 'no output'}"
        )
    return json.loads(lines[-1])


def _coldstart_serve_once():
    """Grandchild body (BENCH_COLDSTART_MODE=warm|aot): boot a replica
    from the artifact's params — warm-compiling the whole ladder, or
    installing the artifact's pre-compiled executables — then serve ONE
    caption through the slot loop.  Prints internal timings + the
    decoded tokens; the PARENT measures total process wall (spawn ->
    line), which is the honest process-start -> first-caption metric
    (both arms pay the same interpreter/import tax)."""
    import numpy as np

    from cst_captioning_tpu.config import Config
    from cst_captioning_tpu.data.vocab import Vocabulary
    from cst_captioning_tpu.serving.artifact import (
        _resolve_version_dir,
        load_manifest,
    )
    from cst_captioning_tpu.serving.engine import InferenceEngine

    mode = os.environ["BENCH_COLDSTART_MODE"]
    vdir = _resolve_version_dir(os.environ["BENCH_COLDSTART_ARTIFACT"])
    t0 = time.perf_counter()
    if mode == "aot":
        eng = InferenceEngine.from_artifact(vdir)
        dec = eng.slot_decoder()
    else:
        man = load_manifest(vdir)
        cfg = Config.from_dict(man["config"])
        cfg.serving.warmup = True     # the full trace+compile ladder
        vocab = Vocabulary.load(os.path.join(vdir, "vocab.json"))
        eng = InferenceEngine(cfg, checkpoint=vdir, vocab=vocab)
        dec = eng.slot_decoder()
    t_boot = time.perf_counter()
    rng = np.random.RandomState(0)
    d = eng.cfg.data
    payload = {
        "features": {
            m: rng.randn(d.max_frames, d.feature_dims[m]).astype(
                np.float32
            )
            for m in d.feature_modalities
        }
    }
    req = eng.prepare(payload)
    done = dec.tick([req], ["coldstart"])
    while not done:
        done = dec.tick()
    _, tokens, _, _ = dec.harvest_many(done)[0]
    print(json.dumps({
        "boot_s": round(t_boot - t0, 4),
        "first_decode_s": round(time.perf_counter() - t_boot, 4),
        "compile_count": dec.compile_count,
        "tokens": [int(t) for t in tokens],
    }), flush=True)


def _bench_coldstart_impl():
    """Paired cold-start rows (ISSUE 13): process start -> first
    caption served, WARM-compile vs AOT artifact boot, measured on
    fresh subprocesses over the SAME artifact params (the warm arm
    restores the artifact's orbax item as a checkpoint).  Plus the
    artifact build time / on-disk bytes and the compile_count == 0 pin
    carried as a measured field.  Smoke shape on the CPU backend —
    `coldstart_host_cores` records the caveat; the RATIO is the
    portable number (both arms pay identical interpreter/import and
    decode costs, the delta is the compile ladder)."""
    import shutil
    import subprocess
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")

    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.data.vocab import Vocabulary
    from cst_captioning_tpu.serving.artifact import build_artifact
    from cst_captioning_tpu.serving.engine import InferenceEngine

    out_root = tempfile.mkdtemp(prefix="bench_coldstart_")
    try:
        cfg = get_preset("synthetic_smoke")
        cfg.serving.warmup = False
        cfg.serving.num_slots = int(
            os.environ.get("BENCH_COLDSTART_SLOTS", "4")
        )
        cfg.serving.slot_bank_min = 2
        vocab = Vocabulary([f"w{i}" for i in range(252)])
        cfg.model.vocab_size = len(vocab)
        engine = InferenceEngine(cfg, random_init=True, vocab=vocab)
        summary = build_artifact(engine, out_root)

        here = os.path.abspath(__file__)

        def run_mode(mode):
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["BENCH_COLDSTART_MODE"] = mode
            env["BENCH_COLDSTART_ARTIFACT"] = summary["path"]
            env.pop("BENCH_COLDSTART_CHILD", None)
            t0 = time.perf_counter()
            r = subprocess.run(
                [sys.executable, here],
                capture_output=True, text=True, timeout=900, env=env,
                cwd=os.path.dirname(here),
            )
            wall = time.perf_counter() - t0
            lines = [
                ln for ln in r.stdout.strip().splitlines()
                if ln.startswith("{")
            ]
            if r.returncode != 0 or not lines:
                tail = (r.stderr or r.stdout).strip().splitlines()
                raise RuntimeError(
                    f"coldstart {mode} child rc={r.returncode}: "
                    f"{tail[-1] if tail else 'no output'}"
                )
            return wall, json.loads(lines[-1])

        warm_wall, warm = run_mode("warm")
        aot_wall, aot = run_mode("aot")
        return {
            "coldstart_host_cores": float(os.cpu_count() or 1),
            "coldstart_warm_s": round(warm_wall, 3),
            "coldstart_aot_s": round(aot_wall, 3),
            "coldstart_ratio": round(warm_wall / max(aot_wall, 1e-9), 3),
            "coldstart_warm_boot_s": round(warm["boot_s"], 3),
            "coldstart_aot_boot_s": round(aot["boot_s"], 3),
            "coldstart_warm_compile_count": float(warm["compile_count"]),
            "coldstart_aot_compile_count": float(aot["compile_count"]),
            "coldstart_artifact_build_s": round(summary["build_s"], 3),
            "coldstart_artifact_bytes": float(summary["artifact_bytes"]),
            "coldstart_variants": float(
                summary["variants"] + summary["encode_variants"]
            ),
            "coldstart_tokens_match": (
                1.0 if warm["tokens"] == aot["tokens"] else 0.0
            ),
        }
    finally:
        shutil.rmtree(out_root, ignore_errors=True)


def bench_coldstart():
    """Cold-start rows (see :func:`_bench_coldstart_impl`).  Re-execs
    into a CPU subprocess (the bench_slo precedent) — the artifact
    build and both boot arms target the smoke shape and must not
    disturb the TPU-held parent."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_COLDSTART_CHILD"] = "1"
    here = os.path.abspath(__file__)
    r = subprocess.run(
        [sys.executable, here],
        capture_output=True, text=True, timeout=1800, env=env,
        cwd=os.path.dirname(here),
    )
    lines = [
        ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")
    ]
    if r.returncode != 0 or not lines:
        tail = (r.stderr or r.stdout).strip().splitlines()
        raise RuntimeError(
            f"coldstart child rc={r.returncode}: "
            f"{tail[-1] if tail else 'no output'}"
        )
    return json.loads(lines[-1])


def _bench_slot_mem_impl():
    """Paired REPLICATED-vs-DEDUPED decode-state memory rows (ISSUE 7).

    Decode-state bytes per in-flight request are DETERMINISTIC pytree
    arithmetic — measured by summing the actual slot-state leaves of
    both layouts (``SlotDecoder.state_bytes``), cross-checked against
    the closed-form shape formula (``expected_state_bytes``; the delta
    is recorded and must be 0) — so this row is machine-checked, not
    wall-clock, and means the same thing on the CPU dev host and on
    TPU.  Alongside: paired closed-loop captions/s + p99 at the same
    offered load (both layouts, same weights/workload — wall-clock,
    with the usual ``slot_mem_host_cores`` caveat), the elastic-bank
    regrow count + worst regrow stall under a burst/idle drive, and the
    capacity-at-fixed-memory-budget arithmetic (how many deduped slots
    fit in the replicated bank's byte budget).

    Env: BENCH_SLOT_MEM_SLOTS / _CLIENTS / _REQS / _EOS_BIAS size it."""
    import threading

    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.constants import EOS_ID
    from cst_captioning_tpu.data.vocab import Vocabulary
    from cst_captioning_tpu.serving.batcher import ContinuousBatcher
    from cst_captioning_tpu.serving.engine import InferenceEngine
    from cst_captioning_tpu.serving.metrics import ServingMetrics

    S = int(os.environ.get("BENCH_SLOT_MEM_SLOTS", "8"))
    n_clients = int(os.environ.get("BENCH_SLOT_MEM_CLIENTS", "4"))
    reqs_per_client = int(os.environ.get("BENCH_SLOT_MEM_REQS", "6"))
    eos_bias = float(os.environ.get("BENCH_SLOT_MEM_EOS_BIAS", "3.0"))

    cfg = get_preset("synthetic_smoke")
    # Small-but-real CPU shape where the projected cache dominates the
    # carry — the regime the dedup targets (MSR-VTT: cache ~93% of a
    # beam-5 slot's bytes, docs/PERF.md r11).
    cfg.model.rnn_size = 128
    cfg.model.input_encoding_size = 128
    cfg.model.att_hidden_size = 128
    cfg.data.feature_dims = {"resnet": 256}
    cfg.data.max_frames = 24
    cfg.eval.beam_size = 3
    cfg.eval.max_decode_len = 16
    vocab = Vocabulary([f"w{i}" for i in range(1020)])
    cfg.model.vocab_size = len(vocab)
    cfg.serving.max_batch_size = S
    cfg.serving.batch_shapes = []   # default power-of-two ladder
    cfg.serving.num_slots = S
    cfg.serving.queue_depth = 4096
    cfg.serving.warmup = False          # slot-loop warmup only, below
    cfg.serving.slot_block_steps = 1
    K, L = cfg.eval.beam_size, cfg.eval.max_decode_len

    def build(dedup: bool, bank_min: int = 0):
        c = cfg.replace(**{
            "serving.dedup_cache": dedup,
            "serving.slot_bank_min": bank_min,
            "serving.slot_shrink_idle_ticks": 3,
        })
        eng = InferenceEngine(c, random_init=True, vocab=vocab)
        b = np.asarray(eng.params["params"]["logit_b"]).copy()
        b[EOS_ID] += eos_bias           # recorded: random weights never
        p = dict(eng.params)            # EOS without it (cst_slot
        pp = dict(p["params"])          # precedent)
        pp["logit_b"] = jnp.asarray(b)
        p["params"] = pp
        eng.params = p
        dec = eng.slot_decoder()
        dec.warmup()
        return eng, dec

    rng = np.random.RandomState(23)
    F = cfg.data.max_frames
    pool = [
        {
            "features": {
                m: rng.randn(F, d).astype(np.float32)
                for m, d in cfg.data.feature_dims.items()
            }
        }
        for _ in range(n_clients * reqs_per_client)
    ]

    def run_closed(eng):
        eng.cache.captions.clear()
        metrics = ServingMetrics()
        lat_ms, errors = [], []
        lock = threading.Lock()

        def client(cid):
            for j in range(reqs_per_client):
                k = cid * reqs_per_client + j
                t0 = time.perf_counter()
                try:
                    batcher.submit(pool[k], deadline_ms=120_000.0)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")
                    continue
                with lock:
                    lat_ms.append((time.perf_counter() - t0) * 1e3)

        with ContinuousBatcher(eng, metrics) as batcher:
            threads = [
                threading.Thread(target=client, args=(c,))
                for c in range(n_clients)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
        return {
            "captions_per_sec": round(len(lat_ms) / wall, 2)
            if wall > 0 else 0.0,
            "p99_ms": round(np.percentile(lat_ms, 99), 2)
            if lat_ms else 0.0,
            "mean_steps": round(
                metrics.steps_per_caption.snapshot()["mean_ms"], 2
            ),
            "errors": len(errors),
        }

    out = {"slot_mem_slots": S, "slot_mem_K": K, "slot_mem_L": L,
           "slot_mem_eos_bias": eos_bias}

    # ------- exact byte accounting, both layouts, same config --------
    eng_d, dec_d = build(dedup=True)
    eng_r, dec_r = build(dedup=False)
    for tag, dec in (("dedup", dec_d), ("replicated", dec_r)):
        out[f"slot_mem_{tag}_state_bytes"] = dec.state_bytes()
        out[f"slot_mem_{tag}_bytes_per_request"] = dec.per_slot_bytes()
        # Machine check: measured pytree bytes == closed-form formula.
        out[f"slot_mem_{tag}_formula_delta_bytes"] = (
            dec.state_bytes() - dec.expected_state_bytes()
        )
    out["slot_mem_bytes_per_request_ratio"] = round(
        dec_r.per_slot_bytes() / dec_d.per_slot_bytes(), 3
    )
    out["slot_mem_cache_bytes_ratio"] = round(
        dec_r.cache_bytes() / dec_d.cache_bytes(), 3
    )
    # Capacity at a fixed memory budget: deduped slots that fit in the
    # replicated bank's bytes (the elastic top bank a deploy could set).
    out["slot_mem_slots_at_replicated_budget"] = int(
        dec_r.state_bytes() // dec_d.per_slot_bytes()
    )

    # ------------ paired load, same offered pattern ------------------
    pt_d = run_closed(eng_d)
    pt_r = run_closed(eng_r)
    out.update({
        "slot_mem_dedup_captions_per_sec": pt_d["captions_per_sec"],
        "slot_mem_replicated_captions_per_sec": pt_r["captions_per_sec"],
        "slot_mem_dedup_p99_ms": pt_d["p99_ms"],
        "slot_mem_replicated_p99_ms": pt_r["p99_ms"],
        "slot_mem_throughput_ratio": round(
            pt_d["captions_per_sec"] / pt_r["captions_per_sec"], 3
        ) if pt_r["captions_per_sec"] else None,
        "slot_mem_mean_steps": pt_d["mean_steps"],
        "slot_mem_dropped_live": pt_d["errors"] + pt_r["errors"],
    })

    # ------------- elastic regrow under burst/idle drive --------------
    eng_e, dec_e = build(dedup=True, bank_min=max(2, S // 4))
    compiles_after_warmup = dec_e.compile_count
    prepared = [eng_e.prepare(q) for q in pool]
    pending = list(range(len(prepared)))
    while pending or dec_e.occupied:
        dec_e.maybe_resize(len(pending))
        n = min(len(pending), len(dec_e.free), dec_e.admit_cap)
        adm = [pending.pop(0) for _ in range(n)]
        done = dec_e.tick([prepared[i] for i in adm], adm)
        dec_e.harvest_many(done)
    for _ in range(dec_e.shrink_after * (len(dec_e.bank_ladder) + 1)):
        dec_e.maybe_resize(0)       # idle: walk the ladder back down
    out.update({
        "slot_mem_bank_min": dec_e.bank_ladder[0],
        "slot_mem_bank_max": dec_e.bank_ladder[-1],
        "slot_mem_bank_final": dec_e.S,
        "slot_mem_regrow_count": dec_e.resize_count,
        "slot_mem_regrow_worst_ms": round(dec_e.worst_resize_ms, 3),
        # 0 = every transition was a pre-jitted ladder hit (no cold
        # retrace on the request path).
        "slot_mem_regrow_new_compiles": (
            dec_e.compile_count - compiles_after_warmup
        ),
    })
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:
        cores = os.cpu_count() or 1
    out["slot_mem_host_cores"] = cores
    return out


def bench_slot_mem():
    """Replicated-vs-deduped decode-state pair (see
    :func:`_bench_slot_mem_impl`).  Always re-execs into a subprocess
    pinned to the in-process CPU backend — the byte accounting is
    deterministic arithmetic that means the same thing everywhere, and
    the wall-clock pairing targets the smoke shape by design (the
    bench_cst_slot precedent), so it must run in degraded mode too."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_SLOT_MEM_CHILD"] = "1"
    here = os.path.abspath(__file__)
    r = subprocess.run(
        [sys.executable, here],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(here),
    )
    lines = [
        ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")
    ]
    if r.returncode != 0 or not lines:
        tail = (r.stderr or r.stdout).strip().splitlines()
        raise RuntimeError(
            f"slot mem child rc={r.returncode}: "
            f"{tail[-1] if tail else 'no output'}"
        )
    return json.loads(lines[-1])


def _bench_serving_replicas_impl():
    """Multi-replica serving sweep body (see bench_serving_replicas).

    Paired rows — same weights, same workload, same offered load:

    * closed-loop capacity at 1 replica vs N replicas;
    * open-loop p50/p99 at the SAME offered load (0.8x the measured
      1-replica capacity, a rate the single-replica row sustains) for
      the PR-3 single-replica scheduler (ContinuousBatcher),
      ``ReplicaSet`` at 1 replica, and ``ReplicaSet`` at N replicas —
      the 1-vs-N pairing plus the no-regression check on the
      single-replica configuration;
    * double-buffered vs synchronous tick dispatch at 1 replica,
      closed loop: device decode steps/s per replica — the host-sync
      stall the double buffer removes.

    Scheduler-scale shape (the sweep measures the replica scheduler,
    not the model): rnn256/V2048/K3/L16 on 8-frame resnet-256 rows.
    Env: BENCH_REPLICAS_N (replica count, default min(devices, 4) or 4
    in the virtual-CPU child), BENCH_REPLICAS_REQS (closed-loop
    requests per client, default 6), BENCH_REPLICAS_OPEN_N (open-loop
    requests per point, default 120)."""
    import threading

    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.data.vocab import Vocabulary
    from cst_captioning_tpu.serving.batcher import ContinuousBatcher
    from cst_captioning_tpu.serving.engine import InferenceEngine
    from cst_captioning_tpu.serving.metrics import ServingMetrics
    from cst_captioning_tpu.serving.replicas import ReplicaSet

    cfg = get_preset("synthetic_smoke")
    cfg.model.rnn_size = 256
    cfg.model.input_encoding_size = 256
    cfg.model.att_hidden_size = 256
    cfg.data.feature_dims = {"resnet": 256}
    cfg.data.max_frames = 8
    cfg.eval.beam_size = 3
    cfg.eval.max_decode_len = 16
    vocab = Vocabulary([f"w{i}" for i in range(2044)])
    cfg.model.vocab_size = len(vocab)
    cfg.serving.max_batch_size = 4
    cfg.serving.batch_shapes = [1, 2, 4]
    cfg.serving.num_slots = 4
    cfg.serving.slot_block_steps = 2
    cfg.serving.queue_depth = 4096
    cfg.serving.warmup = False
    cfg.serving.continuous = True
    source = InferenceEngine(cfg, random_init=True, vocab=vocab)
    devices = jax.devices()
    N = int(os.environ.get("BENCH_REPLICAS_N", "0")) or min(
        len(devices), 4
    )
    clones = [
        source.clone_for_device(devices[i % len(devices)], replica_id=i)
        for i in range(N)
    ]

    rng = np.random.RandomState(23)
    F, dims = cfg.data.max_frames, cfg.data.feature_dims
    pool = [
        {
            "features": {
                m: rng.randn(F, d).astype(np.float32)
                for m, d in dims.items()
            }
        }
        for _ in range(64)
    ]

    def run_load(make_batcher, n_clients, reqs_per_client,
                 rate_cps=None, n_open=0):
        source.cache.captions.clear()
        metrics = ServingMetrics()
        batcher = make_batcher(metrics)
        lat, errors = [], []
        lock = threading.Lock()

        def one(k):
            t0 = time.perf_counter()
            try:
                batcher.submit(pool[k % len(pool)], deadline_ms=120_000.0)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return
            with lock:
                lat.append((time.perf_counter() - t0) * 1e3)

        with batcher:
            t0 = time.perf_counter()
            if rate_cps:   # open loop: fixed arrival schedule
                threads = []
                for i in range(n_open):
                    target = t0 + i / rate_cps
                    now = time.perf_counter()
                    if target > now:
                        time.sleep(target - now)
                    th = threading.Thread(target=one, args=(i,))
                    th.start()
                    threads.append(th)
            else:          # closed loop: back-to-back clients
                def client(cid):
                    for j in range(reqs_per_client):
                        one(cid * reqs_per_client + j)

                threads = [
                    threading.Thread(target=client, args=(c,))
                    for c in range(n_clients)
                ]
                for th in threads:
                    th.start()
            for th in threads:
                th.join()
            wall = time.perf_counter() - t0
        return {
            "captions_per_sec": round(len(lat) / wall, 2)
            if wall > 0 else None,
            "p50_ms": round(float(np.percentile(lat, 50)), 2)
            if lat else None,
            "p99_ms": round(float(np.percentile(lat, 99)), 2)
            if lat else None,
            "errors": len(errors),
            "error_sample": errors[:3],
            "device_steps": metrics.slot_steps_total.value,
            "wall_s": round(wall, 3),
        }

    def mk_base(m):
        return ContinuousBatcher(source, m)

    def mk_r1(dbuf):
        return lambda m: ReplicaSet(clones[:1], m, double_buffer=dbuf)

    def mk_rn(m):
        return ReplicaSet(clones, m, double_buffer=True)

    clients = max(4, 2 * N)
    reqs = int(os.environ.get("BENCH_REPLICAS_REQS", "6"))
    # Warm EVERY decoder across EVERY admission bucket outside the
    # timed region — a cold tick variant costs ~1.5s of XLA compile and
    # would dominate any p99 it lands in.
    for e in clones + [source]:
        e.slot_decoder().warmup()
    run_load(mk_rn, clients, 2)
    run_load(mk_base, 2, 2)

    rows = {}
    rows["closed_1r"] = run_load(mk_r1(True), clients, reqs)
    rows["closed_nr"] = run_load(mk_rn, clients, reqs)
    cap1 = rows["closed_1r"]["captions_per_sec"] or 1.0
    capn = rows["closed_nr"]["captions_per_sec"] or 1.0

    n_open = int(os.environ.get("BENCH_REPLICAS_OPEN_N", "120"))
    rate = 0.8 * cap1
    rows["open_baseline_continuous"] = run_load(
        mk_base, 0, 0, rate_cps=rate, n_open=n_open
    )
    rows["open_1r"] = run_load(mk_r1(True), 0, 0, rate_cps=rate,
                               n_open=n_open)
    rows["open_nr"] = run_load(mk_rn, 0, 0, rate_cps=rate,
                               n_open=n_open)

    rows["dbuf_on_1r"] = run_load(mk_r1(True), 4, 3 * reqs)
    rows["dbuf_off_1r"] = run_load(mk_r1(False), 4, 3 * reqs)
    sps_on = rows["dbuf_on_1r"]["device_steps"] / max(
        rows["dbuf_on_1r"]["wall_s"], 1e-9
    )
    sps_off = rows["dbuf_off_1r"]["device_steps"] / max(
        rows["dbuf_off_1r"]["wall_s"], 1e-9
    )

    # The 1-vs-N acceptance pairing is the OPEN-LOOP rows (the literal
    # same offered load); closed-loop capacity rows are detail.  On a
    # host with fewer cores than replicas the virtual devices
    # time-slice, so sustained parity (ratio ~1.0) is the ceiling —
    # real multi-chip scaling arithmetic lives in docs/PERF.md.
    sus1 = rows["open_1r"]["captions_per_sec"] or 1.0
    susn = rows["open_nr"]["captions_per_sec"] or 1.0
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:  # non-linux
        cores = os.cpu_count() or 1
    return {
        "serving_replicas_devices": len(devices),
        "serving_replicas_n": N,
        "serving_replicas_backend": jax.default_backend(),
        "serving_replicas_host_cores": cores,
        "serving_replica_sustained_1r": sus1,
        "serving_replica_sustained_nr": susn,
        "serving_replica_sustained_ratio": round(susn / sus1, 3),
        "serving_replica_capacity_1r": cap1,
        "serving_replica_capacity_nr": capn,
        "serving_replica_capacity_ratio": round(capn / cap1, 3),
        "serving_replica_open_rate_cps": round(rate, 1),
        "serving_replica_open_p99_1r_ms": rows["open_1r"]["p99_ms"],
        "serving_replica_open_p99_nr_ms": rows["open_nr"]["p99_ms"],
        "serving_replica_open_p99_baseline_ms":
            rows["open_baseline_continuous"]["p99_ms"],
        "serving_dbuf_steps_per_sec": round(sps_on, 1),
        "serving_sync_steps_per_sec": round(sps_off, 1),
        "serving_dbuf_speedup": round(sps_on / sps_off, 3)
        if sps_off else None,
        "serving_replica_sweep": rows,
    }


def bench_serving_replicas(backend_ok: bool = True):
    """Multi-replica data-parallel serving sweep (serving/replicas.py).

    On a multi-device host the sweep runs inline; on a single-device
    host (or with the backend down) it re-execs itself onto a virtual
    multi-device CPU platform (``BENCH_REPLICAS_N`` ways, default 4 —
    the tests/conftest.py recipe) so the 1-vs-N pairing measures real
    device-parallel scaling rather than N workers time-slicing one
    device.  The child prints one JSON object on its last stdout line;
    ``serving_replicas_virtual_cpu`` marks re-exec'd records."""
    import subprocess

    if backend_ok:
        try:
            if len(jax.devices()) > 1:
                return _bench_serving_replicas_impl()
        except Exception:  # noqa: BLE001 — fall through to the child
            pass
    env = dict(os.environ)
    n = int(env.get("BENCH_REPLICAS_N", "0")) or 4
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_REPLICA_CHILD"] = "1"
    here = os.path.abspath(__file__)
    r = subprocess.run(
        [sys.executable, here],
        capture_output=True, text=True, timeout=1800, env=env,
        cwd=os.path.dirname(here),
    )
    lines = [
        ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")
    ]
    if r.returncode != 0 or not lines:
        tail = (r.stderr or r.stdout).strip().splitlines()
        raise RuntimeError(
            f"replica sweep child rc={r.returncode}: "
            f"{tail[-1] if tail else 'no output'}"
        )
    out = json.loads(lines[-1])
    out["serving_replicas_virtual_cpu"] = True
    return out


def _hlo_collective_bytes(hlo: str) -> dict:
    """Output bytes of every cross-device collective in a compiled HLO,
    split by op kind.  Counts each op's result shape(s) — the tensor
    that actually crosses the interconnect boundary — so a replicated
    layout's grad all-reduces and a TP layout's logit all-gathers are
    comparable on one axis."""
    kinds = ("all-gather", "all-reduce", "reduce-scatter",
             "collective-permute", "all-to-all")
    dt_bytes = {"f32": 4, "bf16": 2, "f16": 2, "f64": 8,
                "s32": 4, "u32": 4, "s8": 1, "u8": 1, "pred": 1}
    shape_pat = re.compile(r"(f32|bf16|f16|f64|s32|u32|s8|u8|pred)\[([\d,]*)\]")
    out = {k: 0 for k in kinds}
    count = 0
    for line in hlo.splitlines():
        sep = next(
            (s for k in kinds for s in (f" {k}(", f" {k}-start(")
             if s in line),
            None,
        )
        if sep is None:
            continue
        kind = sep.strip().split("(")[0].removesuffix("-start")
        # Result shapes precede the op name: "%x = f32[a,b] all-gather("
        # or "(f32[a], f32[b]) all-reduce-start(".  Split on the op
        # CALL (" op(") — the op name also appears in result variable
        # names ("%all-reduce.25 = ..."), which must stay in the head.
        head = line.split(sep)[0]
        total = 0
        for dt, dims in shape_pat.findall(head):
            elems = int(np.prod([int(d) for d in dims.split(",") if d] or [1]))
            total += dt_bytes[dt] * elems
        if total:
            out[kind] += total
            count += 1
    out["total"] = sum(out[k] for k in kinds)
    out["ops"] = count
    return out


def _bench_shard_impl():
    """Replicated-vs-model-sharded XE pair on a virtual multi-device CPU
    mesh (the in-process child of :func:`bench_shard`).

    Same batch, same params, same rng through two meshes over the SAME
    n devices: pure data parallelism (n x 1) vs a real 2D mesh
    (n/2 x 2) with the vocab projection + embedding + Adam moments
    sharded over the model axis per parallel/partition.py and the
    update step a NamedSharding-in/out jit.  Records steps/s both ways,
    the per-step HLO collective bytes (grad all-reduce vs logit
    all-gather trade — docs/PERF.md r12 has the closed-form), the
    per-device vocab-param bytes (the capacity win that motivates TP),
    and the first-step loss delta (the PARITY r12 tolerance tier).
    Virtual-CPU steps/s are not TPU steps/s; the honest
    ``shard_host_cores``/``*_mesh_shape``/``shard_xla_flags`` fields
    keep the rows reproducible and caveated from the record alone."""
    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.models import model_from_config
    from cst_captioning_tpu.parallel import (
        batch_sharding,
        make_mesh,
        mesh_shape_str,
        shard_batch,
    )
    from cst_captioning_tpu.training.steps import (
        create_train_state,
        make_optimizer,
        make_xe_train_step,
    )

    n = len(jax.devices())
    if n < 4 or n % 2:
        raise RuntimeError(
            f"shard pair needs an even >=4 virtual device count, have {n}"
        )
    B = int(os.environ.get("BENCH_SHARD_BATCH", "8"))
    V = int(os.environ.get("BENCH_SHARD_VOCAB", "2048"))
    steps = int(os.environ.get("BENCH_SHARD_STEPS", "8"))
    cfg = get_preset("synthetic_smoke")
    cfg.data.batch_size = B
    cfg.data.seq_per_img = 2
    cfg.data.max_seq_len = 10
    cfg.data.max_frames = 4
    cfg.data.feature_modalities = ["resnet"]
    cfg.data.feature_dims = {"resnet": 64}
    cfg.model.vocab_size = V          # divides every power-of-two axis
    cfg.model.rnn_size = 64
    cfg.model.input_encoding_size = 64
    cfg.model.att_hidden_size = 64
    cfg.model.drop_prob = 0.0
    cfg.model.compute_dtype = "float32"

    rng = np.random.RandomState(0)
    T = cfg.data.max_seq_len
    batch = {
        "feats": {"resnet": rng.randn(B, 4, 64).astype(np.float32)},
        "feat_masks": {"resnet": np.ones((B, 4), np.float32)},
        "captions": rng.randint(4, V, size=(B, 2, T)).astype(np.int32),
        "weights": np.ones((B, 2), np.float32),
        "category": np.zeros((B,), np.int32),
        "video_idx": np.arange(B, dtype=np.int32),
    }
    batch["captions"][:, :, 0] = 1  # BOS

    vocab_leaves = ("word_embed", "logit_w", "logit_b")

    def measure(mesh):
        model = model_from_config(cfg, mesh=mesh)
        tx = make_optimizer(cfg.train, 10)
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, batch, mesh=mesh
        )
        step = make_xe_train_step(model, mesh=mesh, state_template=state)
        sh = batch_sharding(mesh)
        args = (
            shard_batch(batch["feats"], mesh),
            shard_batch(batch["feat_masks"], mesh),
            jax.device_put(jnp.asarray(batch["captions"]), sh),
            jax.device_put(jnp.asarray(batch["weights"]), sh),
            None,
            jax.device_put(jnp.asarray(batch["video_idx"]), sh),
        )
        # Per-device bytes of the vocab-sized params: the TP capacity
        # win, exact from the committed shardings.
        vocab_dev_bytes = 0
        for name, leaf in state.params["params"].items():
            if name in vocab_leaves:
                vocab_dev_bytes += leaf.addressable_shards[0].data.nbytes
        coll = _hlo_collective_bytes(
            step.lower(state, *args, jax.random.PRNGKey(1), 0.0)
            .compile().as_text()
        )
        # Warmup compile, then fixed-seed first step for the parity row.
        state, m = step(state, *args, jax.random.PRNGKey(1), 0.0)
        loss0 = float(m["loss"])
        times = []
        for i in range(steps):
            t0 = time.perf_counter()
            state, m = step(
                state, *args, jax.random.PRNGKey(2 + i), 0.0
            )
            float(m["loss"])
            times.append(time.perf_counter() - t0)
        dt = sorted(times)[len(times) // 2]
        return {
            "steps_per_sec": 1.0 / dt,
            "loss0": loss0,
            "collective_bytes": coll["total"],
            "all_gather_bytes": coll["all-gather"],
            "all_reduce_bytes": coll["all-reduce"],
            "vocab_param_bytes_per_device": vocab_dev_bytes,
            "mesh_shape": mesh_shape_str(mesh),
        }

    rep = measure(make_mesh({"data": n, "model": 1}))
    tp = measure(make_mesh({"data": n // 2, "model": 2}))
    out = {
        "shard_virtual_devices": n,
        "shard_host_cores": float(os.cpu_count() or 1),
        # Reproducibility: the exact virtual-platform setup these rows
        # ran under (ISSUE 9 satellite — MULTICHIP/shard rows must be
        # reproducible from the record alone).
        "shard_xla_flags": os.environ.get("XLA_FLAGS", ""),
        "shard_jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
        "shard_batch": B,
        "shard_vocab": V,
        "shard_replicated_mesh_shape": rep["mesh_shape"],
        "shard_tp_mesh_shape": tp["mesh_shape"],
        "shard_replicated_steps_per_sec": round(rep["steps_per_sec"], 3),
        "shard_tp_steps_per_sec": round(tp["steps_per_sec"], 3),
        "shard_tp_vs_replicated_ratio": round(
            tp["steps_per_sec"] / rep["steps_per_sec"], 4
        ),
        "shard_replicated_collective_bytes": rep["collective_bytes"],
        "shard_tp_collective_bytes": tp["collective_bytes"],
        "shard_replicated_all_gather_bytes": rep["all_gather_bytes"],
        "shard_tp_all_gather_bytes": tp["all_gather_bytes"],
        "shard_replicated_all_reduce_bytes": rep["all_reduce_bytes"],
        "shard_tp_all_reduce_bytes": tp["all_reduce_bytes"],
        "shard_replicated_vocab_param_bytes": rep[
            "vocab_param_bytes_per_device"
        ],
        "shard_tp_vocab_param_bytes": tp["vocab_param_bytes_per_device"],
        # PARITY r12: losses live in the relaxed tolerance tier (the
        # sharded log_softmax reduces in a different order), so the
        # delta is recorded, not asserted-zero.
        "shard_loss_delta": abs(rep["loss0"] - tp["loss0"]),
    }
    return out


def bench_shard(backend_ok: bool = True):
    """Replicated-vs-model-sharded pair (see :func:`_bench_shard_impl`).
    Runs inline on a >=4-device host, otherwise re-execs onto a virtual
    multi-device CPU platform (``BENCH_SHARD_N`` ways, default 4 — the
    tests/conftest.py recipe) so the pair measures real device-parallel
    sharding rather than one device pretending."""
    import subprocess

    if backend_ok:
        try:
            if len(jax.devices()) >= 4 and len(jax.devices()) % 2 == 0:
                return _bench_shard_impl()
        except Exception:  # noqa: BLE001 — fall through to the child
            pass
    env = dict(os.environ)
    n = int(env.get("BENCH_SHARD_N", "0")) or 4
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_SHARD_CHILD"] = "1"
    here = os.path.abspath(__file__)
    r = subprocess.run(
        [sys.executable, here],
        capture_output=True, text=True, timeout=1200, env=env,
        cwd=os.path.dirname(here),
    )
    lines = [
        ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")
    ]
    if r.returncode != 0 or not lines:
        tail = (r.stderr or r.stdout).strip().splitlines()
        raise RuntimeError(
            f"shard pair child rc={r.returncode}: "
            f"{tail[-1] if tail else 'no output'}"
        )
    out = json.loads(lines[-1])
    out["shard_virtual_cpu"] = True
    return out


def _bench_shard_fused_impl():
    """Fused-vs-scan model-sharded slot decode on a virtual 2-device
    CPU mesh (the in-process child of :func:`bench_shard_fused`).

    Same params, same requests, the SAME (data=1, model=2) mesh, two
    compiled tick variants of the serving slot decoder: the PR-9 scan
    path (`serving.shard_fused_decode=false` — logits constrained
    vocab-over-model, inline `lax.top_k`, the SPMD partitioner inserts
    the O(V) full-vocab gather every step) vs the ISSUE-14 fused path
    (per-shard vocab-tile top-K + O(shards*K) candidate all-gather,
    `decoding/core.py::make_tp_beam_topk`).  Records steps/s both
    ways, the per-tick HLO all-gather bytes for both (the candidate
    table must be STRICTLY below the vocab gather — asserted, not just
    recorded), and a token-parity count across fused/scan/unsharded
    (must be 0; the PARITY r15 contract measured end-to-end).
    Virtual-CPU steps/s are not TPU steps/s; the honest
    ``shard_fused_host_cores``/``*_mesh_shape`` fields keep the rows
    caveated from the record alone."""
    import copy

    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.data.build import build_dataset
    from cst_captioning_tpu.serving.engine import InferenceEngine

    n = len(jax.devices())
    if n < 2:
        raise RuntimeError(
            f"shard-fused pair needs >=2 virtual devices, have {n}"
        )
    # Default vocab 2048 (the bench_shard shape): the smoke dataset's
    # ~36-word vocab would understate the O(V)-vs-O(K) gather story;
    # extra rows beyond the real vocabulary are legal (never sampled
    # into detokenization here — harvest compares raw token ids).
    V = int(os.environ.get("BENCH_SHARD_FUSED_VOCAB", "2048"))
    steps = int(os.environ.get("BENCH_SHARD_FUSED_STEPS", "16"))
    cfg = get_preset("synthetic_smoke")
    cfg.serving.warmup = False
    cfg.serving.continuous = True
    cfg.serving.num_slots = 4
    cfg.serving.slot_block_steps = 1
    cfg.eval.beam_size = 3
    cfg.eval.max_decode_len = 12
    ds, vocab = build_dataset(cfg, cfg.eval.eval_split)
    # Even vocab tile over the 2-way model axis (shard_decode_ok).
    cfg.model.vocab_size = max(V, (len(vocab) + 1) // 2 * 2) // 2 * 2
    base = InferenceEngine(cfg, random_init=True, vocab=vocab)
    payloads = [
        {"features": {m: a.tolist() for m, a in ds.features(i).items()}}
        for i in range(4)
    ]

    def build(model_shards, fused):
        c = copy.deepcopy(cfg)
        c.serving.model_shards = model_shards
        c.serving.shard_fused_decode = fused
        c.serving.replicas = 1
        return InferenceEngine(c, params=base.params, vocab=base.vocab)

    def slot_decode(eng):
        """All payloads through the slot loop; list of token rows."""
        dec = eng.slot_decoder()
        prepared = [eng.prepare(p) for p in payloads]
        out = {}
        pending = list(range(len(prepared)))
        while pending or dec.occupied:
            k = min(2, len(pending), len(dec.free))
            adm = [pending.pop(0) for _ in range(k)]
            done = dec.tick([prepared[i] for i in adm], adm)
            for i, tokens, _score, _steps in dec.harvest_many(done):
                out[i] = np.asarray(tokens)
        return [out[i] for i in range(len(prepared))]

    def measure(eng):
        dec = eng.slot_decoder()
        tokens = slot_decode(eng)          # also warms the tick fns
        # Keep a couple of slots occupied so the timed pure-step tick
        # does real decode work.
        prepared = [eng.prepare(p) for p in payloads[:2]]
        dec.tick(prepared, [0, 1])
        fn = dec._tick_fn(0)
        coll = _hlo_collective_bytes(
            fn.lower(eng.params, dec._st, None, None)
            .compile().as_text()
        )
        times = []
        for _ in range(steps):
            t0 = time.perf_counter()
            dec._st, done, _s, _c = fn(
                eng.params, dec._st, None, None
            )
            jax.block_until_ready(done)
            times.append(time.perf_counter() - t0)
        dt = sorted(times)[len(times) // 2]
        for s in list(dec.occupied):
            dec.evict(s)
        return {
            "tokens": tokens,
            "steps_per_sec": dec.block / dt,
            "all_gather_bytes": coll["all-gather"],
            "collective_bytes": coll["total"],
            "mesh_shape": eng.describe()["mesh_shape"],
        }

    ref = slot_decode(build(1, False))         # unsharded truth
    scan = measure(build(2, False))
    fused = measure(build(2, True))

    mismatches = 0
    for arm in (scan["tokens"], fused["tokens"]):
        for a, b in zip(arm, ref):
            if not np.array_equal(a, b):
                mismatches += 1
    if mismatches:
        raise RuntimeError(
            f"shard-fused decode diverged from the unsharded slot "
            f"path on {mismatches} request(s) — the PARITY r15 "
            "contract is broken; do not record perf for wrong tokens"
        )
    if not fused["all_gather_bytes"] < scan["all_gather_bytes"]:
        raise RuntimeError(
            "candidate all-gather bytes "
            f"({fused['all_gather_bytes']}) not strictly below the "
            f"full-vocab gather ({scan['all_gather_bytes']}) — the "
            "fused merge is not engaging"
        )
    return {
        "shard_fused_virtual_devices": n,
        "shard_fused_host_cores": float(os.cpu_count() or 1),
        "shard_fused_xla_flags": os.environ.get("XLA_FLAGS", ""),
        "shard_fused_jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
        "shard_fused_mesh_shape": fused["mesh_shape"],
        "shard_fused_vocab": cfg.model.vocab_size,
        "shard_fused_beam": cfg.eval.beam_size,
        "shard_fused_slots": cfg.serving.num_slots,
        "shard_fused_steps_per_sec": round(fused["steps_per_sec"], 3),
        "shard_fused_scan_steps_per_sec": round(
            scan["steps_per_sec"], 3
        ),
        "shard_fused_vs_scan_ratio": round(
            fused["steps_per_sec"] / scan["steps_per_sec"], 4
        ),
        # The collective-layout headline: per-tick all-gather bytes of
        # the candidate merge vs the forbidden full-vocab gather.
        "shard_fused_candidate_all_gather_bytes": fused[
            "all_gather_bytes"
        ],
        "shard_fused_scan_all_gather_bytes": scan["all_gather_bytes"],
        "shard_fused_gather_ratio": round(
            fused["all_gather_bytes"] / max(scan["all_gather_bytes"], 1),
            6,
        ),
        "shard_fused_collective_bytes": fused["collective_bytes"],
        "shard_fused_scan_collective_bytes": scan["collective_bytes"],
        "shard_fused_token_mismatches": mismatches,
    }


def bench_shard_fused(backend_ok: bool = True):
    """Fused-vs-scan model-sharded slot-decode pair (see
    :func:`_bench_shard_fused_impl`).  Runs inline on a >=2-device
    host, otherwise re-execs onto a virtual 2-device CPU platform —
    the pair must measure real cross-device collectives, not one
    device pretending."""
    import subprocess

    if backend_ok:
        try:
            if len(jax.devices()) >= 2:
                return _bench_shard_fused_impl()
        except Exception:  # noqa: BLE001 — fall through to the child
            pass
    env = dict(os.environ)
    n = int(env.get("BENCH_SHARD_FUSED_N", "0")) or 2
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_SHARD_FUSED_CHILD"] = "1"
    here = os.path.abspath(__file__)
    r = subprocess.run(
        [sys.executable, here],
        capture_output=True, text=True, timeout=1200, env=env,
        cwd=os.path.dirname(here),
    )
    lines = [
        ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")
    ]
    if r.returncode != 0 or not lines:
        tail = (r.stderr or r.stdout).strip().splitlines()
        raise RuntimeError(
            f"shard-fused pair child rc={r.returncode}: "
            f"{tail[-1] if tail else 'no output'}"
        )
    out = json.loads(lines[-1])
    out["shard_fused_virtual_cpu"] = True
    return out


def _bench_lowprec_impl():
    """Paired f32/bf16/int8w serving rows at matched offered load (the
    in-process child of :func:`bench_lowprec`; ISSUE 16).

    One random init, one fixed payload set, three engines per grid —
    ``serving.dtype`` in f32/bf16/int8w on the 1-device placement and
    the (1, 2) tensor-parallel submesh.  The relaxed-serving parity
    contract is ASSERTED before anything is recorded: caption-match
    rate vs the f32 arm >= RELAXED_SERVING_MATCH_FLOOR and per-caption
    beam-score gap <= RELAXED_SERVING_SCORE_RTOL
    (analysis/jit_registry.py; docs/PARITY.md r17) — perf for wrong
    captions must never ship.  Weight residency is recorded both ways:
    the closed-form vocab-tile arithmetic (``quantized_leaf_bytes`` —
    the int8 payload is EXACTLY 0.25x the f32 tile, asserted) and the
    measured per-shard resident bytes (``param_bytes_per_shard``).
    Virtual-CPU captions/s are not TPU captions/s; the honest
    ``lowprec_host_cores``/``*_mesh_shape`` provenance keeps the rows
    caveated from the record alone."""
    import copy

    from cst_captioning_tpu.analysis.jit_registry import (
        RELAXED_SERVING_MATCH_FLOOR,
        RELAXED_SERVING_SCORE_RTOL,
    )
    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.data.build import build_dataset
    from cst_captioning_tpu.decoding.beam import make_beam_search_fn
    from cst_captioning_tpu.ops import quant
    from cst_captioning_tpu.serving.engine import InferenceEngine

    n = len(jax.devices())
    if n < 2:
        raise RuntimeError(
            f"lowprec TP arm needs >=2 virtual devices, have {n}"
        )
    V = int(os.environ.get("BENCH_LOWPREC_VOCAB", "2048"))
    rounds = int(os.environ.get("BENCH_LOWPREC_ROUNDS", "6"))
    B = int(os.environ.get("BENCH_LOWPREC_BATCH", "8"))
    cfg = get_preset("synthetic_smoke")
    cfg.serving.warmup = False
    cfg.serving.max_batch_size = B
    cfg.serving.batch_shapes = [B]
    cfg.eval.beam_size = 3
    cfg.eval.max_decode_len = 12
    ds, vocab = build_dataset(cfg, cfg.eval.eval_split)
    # Even vocab tile over the 2-way model axis; extra rows beyond the
    # real vocabulary are legal (random-init captions either way).
    cfg.model.vocab_size = max(V, (len(vocab) + 1) // 2 * 2) // 2 * 2
    base = InferenceEngine(cfg, random_init=True, vocab=vocab)
    payloads = [
        {"features": {m: a.tolist() for m, a in ds.features(i).items()}}
        for i in range(B)
    ]

    def build(dtype, model_shards=1):
        c = copy.deepcopy(cfg)
        c.serving.dtype = dtype
        c.serving.model_shards = model_shards
        c.serving.replicas = 1
        # base.params are float: the int8w ctor quantizes them ONCE at
        # boot, so every arm serves the same logical weights.
        return InferenceEngine(c, params=base.params, vocab=base.vocab)

    def measure(eng):
        reqs = [eng.prepare(dict(p)) for p in payloads]
        caps = [r.caption for r in eng.decode_prepared(reqs, store=False)]
        times = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            out = eng.decode_prepared(reqs, store=False)
            times.append(time.perf_counter() - t0)
        assert [r.caption for r in out] == caps  # steady-state decode
        times.sort()
        p99 = times[min(len(times) - 1, int(len(times) * 0.99))]
        return {
            "captions": caps,
            "captions_per_sec": len(reqs) * rounds / sum(times),
            "p99_batch_ms": p99 * 1e3,
            "bytes_per_shard": eng.param_bytes_per_shard(),
            "mesh_shape": eng.describe()["mesh_shape"],
        }

    def scores(eng):
        reqs = [eng.prepare(dict(p)) for p in payloads]
        feats = {
            m: jnp.asarray(np.stack([r.feats[m] for r in reqs]))
            for m in reqs[0].feats
        }
        masks = {
            m: jnp.asarray(np.stack([r.masks[m] for r in reqs]))
            for m in reqs[0].masks
        }
        fn = make_beam_search_fn(
            eng.model,
            beam_size=cfg.eval.beam_size,
            max_len=cfg.eval.max_decode_len,
            length_normalize=cfg.eval.length_normalize,
        )
        return np.asarray(
            fn(eng.params, feats, masks).score, np.float64
        )

    arms = {d: measure(build(d)) for d in ("f32", "bf16", "int8w")}
    tp = {d: measure(build(d, 2)) for d in ("f32", "bf16", "int8w")}
    eng_by_dtype = {d: build(d) for d in ("bf16", "int8w")}
    f32_eng = build("f32")
    s_ref = scores(f32_eng)

    # ---- the relaxed-serving gate: parity BEFORE perf is recorded
    parity = {}
    for d in ("bf16", "int8w"):
        ref, got = arms["f32"]["captions"], arms[d]["captions"]
        match = sum(a == b for a, b in zip(ref, got)) / len(ref)
        if match < RELAXED_SERVING_MATCH_FLOOR:
            raise RuntimeError(
                f"{d} caption-match rate {match:.3f} below the pinned "
                f"relaxed-serving floor {RELAXED_SERVING_MATCH_FLOOR} "
                "— do not record perf for out-of-contract captions"
            )
        s_low = scores(eng_by_dtype[d])
        gap = float(np.max(
            np.abs(s_low - s_ref) / np.maximum(np.abs(s_ref), 1e-6)
        ))
        if gap > RELAXED_SERVING_SCORE_RTOL:
            raise RuntimeError(
                f"{d} per-caption score gap {gap:.4f} above the pinned "
                f"relaxed-serving rtol {RELAXED_SERVING_SCORE_RTOL}"
            )
        tp_match = sum(
            a == b for a, b in zip(got, tp[d]["captions"])
        ) / len(got)
        if tp_match < RELAXED_SERVING_MATCH_FLOOR:
            raise RuntimeError(
                f"{d} TP=2 captions diverged from the 1-device arm "
                f"(match {tp_match:.3f})"
            )
        parity[d] = {"match": match, "gap": gap, "tp_match": tp_match}

    # ---- closed-form vocab-tile bytes vs measured residency
    H = cfg.model.rnn_size
    Vp = cfg.model.vocab_size
    f32_tile = H * Vp * 4                       # logit_w, f32
    int8_tile, scale_bytes = quant.quantized_leaf_bytes((H, Vp), 1)
    if int8_tile * 4 != f32_tile:
        raise RuntimeError(
            f"int8w vocab tile {int8_tile} B is not exactly 0.25x the "
            f"f32 tile {f32_tile} B — the closed form drifted"
        )
    p = eng_by_dtype["int8w"].params
    p = p["params"] if "params" in p else p
    measured_tile = int(np.asarray(p["logit_w"]).nbytes)
    if measured_tile != int8_tile:
        raise RuntimeError(
            f"measured int8 logit_w bytes {measured_tile} != closed "
            f"form {int8_tile} — the byte accounting is dishonest"
        )

    out = {
        "lowprec_virtual_devices": n,
        "lowprec_host_cores": float(os.cpu_count() or 1),
        "lowprec_xla_flags": os.environ.get("XLA_FLAGS", ""),
        "lowprec_jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
        "lowprec_mesh_shape": tp["f32"]["mesh_shape"],
        "lowprec_vocab": Vp,
        "lowprec_beam": cfg.eval.beam_size,
        "lowprec_batch": B,
        "lowprec_match_floor": RELAXED_SERVING_MATCH_FLOOR,
        "lowprec_score_rtol": RELAXED_SERVING_SCORE_RTOL,
        # Closed-form vocab tile (logit_w): int8 is EXACTLY 0.25x f32;
        # the per-channel scales are the honest small print.
        "lowprec_vocab_tile_f32_bytes": f32_tile,
        "lowprec_vocab_tile_int8w_bytes": int8_tile,
        "lowprec_vocab_tile_scale_bytes": scale_bytes,
        "lowprec_vocab_tile_ratio": round(int8_tile / f32_tile, 6),
        "lowprec_vocab_tile_measured_bytes": measured_tile,
    }
    for d in ("f32", "bf16", "int8w"):
        out[f"lowprec_{d}_captions_per_sec"] = round(
            arms[d]["captions_per_sec"], 3
        )
        out[f"lowprec_{d}_p99_batch_ms"] = round(
            arms[d]["p99_batch_ms"], 2
        )
        out[f"lowprec_{d}_param_bytes_per_shard"] = arms[d][
            "bytes_per_shard"
        ]
        out[f"lowprec_{d}_tp2_captions_per_sec"] = round(
            tp[d]["captions_per_sec"], 3
        )
        out[f"lowprec_{d}_tp2_param_bytes_per_shard"] = tp[d][
            "bytes_per_shard"
        ]
    for d, pv in parity.items():
        out[f"lowprec_{d}_match_rate"] = round(pv["match"], 4)
        out[f"lowprec_{d}_score_gap_max"] = round(pv["gap"], 6)
        out[f"lowprec_{d}_tp2_match_rate"] = round(pv["tp_match"], 4)
        out[f"lowprec_{d}_vs_f32_ratio"] = round(
            arms[d]["captions_per_sec"] / arms["f32"]["captions_per_sec"],
            4,
        )
    return out


def bench_lowprec(backend_ok: bool = True):
    """Paired f32/bf16/int8w serving rows (see
    :func:`_bench_lowprec_impl`).  Runs inline on a >=2-device host,
    otherwise re-execs onto a virtual 2-device CPU platform so the
    TP=2 arm shards a real mesh."""
    import subprocess

    if backend_ok:
        try:
            if len(jax.devices()) >= 2:
                return _bench_lowprec_impl()
        except Exception:  # noqa: BLE001 — fall through to the child
            pass
    env = dict(os.environ)
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_LOWPREC_CHILD"] = "1"
    here = os.path.abspath(__file__)
    r = subprocess.run(
        [sys.executable, here],
        capture_output=True, text=True, timeout=1200, env=env,
        cwd=os.path.dirname(here),
    )
    lines = [
        ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")
    ]
    if r.returncode != 0 or not lines:
        tail = (r.stderr or r.stdout).strip().splitlines()
        raise RuntimeError(
            f"lowprec pair child rc={r.returncode}: "
            f"{tail[-1] if tail else 'no output'}"
        )
    out = json.loads(lines[-1])
    out["lowprec_virtual_cpu"] = 1
    return out


def _bench_lowprec_fused_impl():
    """Paired fused-f32 / fused-int8w / unfused-int8w beam-serving rows
    (the in-process child of :func:`bench_lowprec_fused`; ISSUE 20).

    One random init, one fixed payload set, three engines per grid on
    the 1-device placement and the (1, 2) tensor-parallel submesh:

    * ``f32_fused``     — ``use_pallas_*`` on, serving.dtype=f32
    * ``int8w_fused``   — ``use_pallas_*`` on, serving.dtype=int8w:
      the kernels stream int8 code tiles + per-channel scale rows and
      dequantize IN-KERNEL (``ops/quant.py::quant_matmul`` semantics —
      scale after the f32-pinned accumulation)
    * ``int8w_unfused`` — ``use_pallas_*`` off: the scan/XLA reference
      the relaxed-serving bounds are pinned against

    THREE gates run before anything records.  (1) Zero int8w-caused
    declines: ``warn_fused_decline`` lines are counted during
    build+decode of each fused arm, and the int8w arm must log EXACTLY
    as many as the f32 arm built identically — quantization itself
    must never gate a kernel off (the decline lift IS the tentpole).
    Environmental declines (the CPU-backend interpret gate fires for
    f32 and int8w alike; the TP=2 shard_map port is pure XLA and
    engages on any backend) cancel in the comparison, so the recorded
    ``*_extra_declines`` fields are 0 by contract on every host.
    (2) Relaxed-serving parity: fused-int8w caption match
    vs BOTH the fused-f32 arm and the unfused-int8w reference >=
    RELAXED_SERVING_MATCH_FLOOR, and per-caption beam-score gap vs the
    unfused-int8w reference <= RELAXED_SERVING_SCORE_RTOL — perf for
    out-of-contract captions must never ship.  (3) The streamed vocab
    tile is EXACTLY 0.25x the f32 tile by closed form
    (``quantized_leaf_bytes``), on the 1-device grid AND per shard on
    TP=2, cross-checked against the measured engine bytes.

    Off-TPU the single-device kernels run in Pallas interpret mode —
    the captions/s rows caveat themselves through the recorded
    ``*_jax_platforms``/``*_host_cores`` provenance; the TP=2 arm is
    the pure-XLA ``ops/shard_decode.py`` port either way."""
    import copy
    import logging

    from cst_captioning_tpu.analysis.jit_registry import (
        RELAXED_SERVING_MATCH_FLOOR,
        RELAXED_SERVING_SCORE_RTOL,
    )
    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.data.build import build_dataset
    from cst_captioning_tpu.decoding.beam import make_beam_search_fn
    from cst_captioning_tpu.ops import quant
    from cst_captioning_tpu.serving.engine import InferenceEngine

    n = len(jax.devices())
    if n < 2:
        raise RuntimeError(
            f"lowprec_fused TP arm needs >=2 virtual devices, have {n}"
        )
    V = int(os.environ.get("BENCH_LOWPREC_FUSED_VOCAB", "1024"))
    rounds = int(os.environ.get("BENCH_LOWPREC_FUSED_ROUNDS", "4"))
    B = int(os.environ.get("BENCH_LOWPREC_FUSED_BATCH", "8"))
    cfg = get_preset("synthetic_smoke")
    cfg.serving.warmup = False
    cfg.serving.max_batch_size = B
    cfg.serving.batch_shapes = [B]
    cfg.eval.beam_size = 3
    cfg.eval.max_decode_len = 12
    ds, vocab = build_dataset(cfg, cfg.eval.eval_split)
    cfg.model.vocab_size = max(V, (len(vocab) + 1) // 2 * 2) // 2 * 2
    base = InferenceEngine(cfg, random_init=True, vocab=vocab)
    payloads = [
        {"features": {m: a.tolist() for m, a in ds.features(i).items()}}
        for i in range(B)
    ]

    class _Declines(logging.Handler):
        """Counts ``warn_fused_decline`` lines (models/captioner.py):
        they all carry the literal "gated off"."""

        def __init__(self):
            super().__init__()
            self.count = 0

        def emit(self, record):
            if "gated off" in record.getMessage():
                self.count += 1

    declines = {}

    def build_measure(arm, dtype, fused, model_shards=1):
        c = copy.deepcopy(cfg)
        c.serving.dtype = dtype
        c.serving.model_shards = model_shards
        c.serving.replicas = 1
        c.model.use_pallas_lstm = fused
        c.model.use_pallas_attention = fused
        c.model.use_pallas_sampler = fused
        c.model.use_pallas_beam = fused
        h = _Declines()
        lg = logging.getLogger("cst_captioning_tpu.models")
        lg.addHandler(h)
        try:
            # base.params are float: the int8w ctor quantizes ONCE at
            # boot, so every arm serves the same logical weights.
            eng = InferenceEngine(c, params=base.params, vocab=base.vocab)
            reqs = [eng.prepare(dict(p)) for p in payloads]
            caps = [
                r.caption for r in eng.decode_prepared(reqs, store=False)
            ]
            times = []
            for _ in range(rounds):
                t0 = time.perf_counter()
                out = eng.decode_prepared(reqs, store=False)
                times.append(time.perf_counter() - t0)
            assert [r.caption for r in out] == caps  # steady-state
        finally:
            lg.removeHandler(h)
        if fused:
            declines[arm] = h.count
        times.sort()
        p99 = times[min(len(times) - 1, int(len(times) * 0.99))]
        return {
            "eng": eng,
            "captions": caps,
            "captions_per_sec": len(reqs) * rounds / sum(times),
            "p99_batch_ms": p99 * 1e3,
            "bytes_per_shard": eng.param_bytes_per_shard(),
            "mesh_shape": eng.describe()["mesh_shape"],
        }

    def scores(eng):
        reqs = [eng.prepare(dict(p)) for p in payloads]
        feats = {
            m: jnp.asarray(np.stack([r.feats[m] for r in reqs]))
            for m in reqs[0].feats
        }
        masks = {
            m: jnp.asarray(np.stack([r.masks[m] for r in reqs]))
            for m in reqs[0].masks
        }
        fn = make_beam_search_fn(
            eng.model,
            beam_size=cfg.eval.beam_size,
            max_len=cfg.eval.max_decode_len,
            length_normalize=cfg.eval.length_normalize,
        )
        return np.asarray(
            fn(eng.params, feats, masks).score, np.float64
        )

    ARMS = (
        ("f32_fused", "f32", True),
        ("int8w_fused", "int8w", True),
        ("int8w_unfused", "int8w", False),
    )
    one = {a: build_measure(a, d, f) for a, d, f in ARMS}
    tp = {a: build_measure(f"{a}_tp2", d, f, 2) for a, d, f in ARMS}

    # ---- gate 1: int8w adds ZERO declines over the identically-built
    # f32 arm, on both grids (environmental declines cancel)
    extra_1dev = declines["int8w_fused"] - declines["f32_fused"]
    extra_tp2 = (
        declines["int8w_fused_tp2"] - declines["f32_fused_tp2"]
    )
    if extra_1dev or extra_tp2:
        raise RuntimeError(
            f"serving.dtype=int8w caused {extra_1dev} extra fused-"
            f"kernel decline(s) on 1-device and {extra_tp2} on TP=2 "
            "vs the f32 arm — quantization must never gate a kernel "
            "off; not recording perf around a silent scan fallback"
        )

    # ---- gate 2: relaxed-serving parity BEFORE perf is recorded
    ref = one["int8w_unfused"]["captions"]
    got = one["int8w_fused"]["captions"]
    kernel_match = sum(a == b for a, b in zip(ref, got)) / len(ref)
    if kernel_match < RELAXED_SERVING_MATCH_FLOOR:
        raise RuntimeError(
            f"fused-int8w caption match {kernel_match:.3f} vs the "
            f"unfused int8w reference is below the pinned floor "
            f"{RELAXED_SERVING_MATCH_FLOOR} — not recording"
        )
    f32_match = sum(
        a == b
        for a, b in zip(one["f32_fused"]["captions"], got)
    ) / len(got)
    if f32_match < RELAXED_SERVING_MATCH_FLOOR:
        raise RuntimeError(
            f"fused-int8w caption match {f32_match:.3f} vs the fused "
            f"f32 arm is below the pinned floor "
            f"{RELAXED_SERVING_MATCH_FLOOR} — not recording"
        )
    s_ref = scores(one["int8w_unfused"]["eng"])
    s_fused = scores(one["int8w_fused"]["eng"])
    gap = float(np.max(
        np.abs(s_fused - s_ref) / np.maximum(np.abs(s_ref), 1e-6)
    ))
    if gap > RELAXED_SERVING_SCORE_RTOL:
        raise RuntimeError(
            f"fused-int8w per-caption score gap {gap:.4f} vs the "
            f"unfused reference is above the pinned rtol "
            f"{RELAXED_SERVING_SCORE_RTOL}"
        )
    tp_match = sum(
        a == b for a, b in zip(got, tp["int8w_fused"]["captions"])
    ) / len(got)
    if tp_match < RELAXED_SERVING_MATCH_FLOOR:
        raise RuntimeError(
            f"fused-int8w TP=2 captions diverged from the 1-device "
            f"arm (match {tp_match:.3f})"
        )

    # ---- gate 3: the streamed vocab tile is EXACTLY 0.25x f32, by
    # closed form, on both grids, before anything records
    H = cfg.model.rnn_size
    Vp = cfg.model.vocab_size
    f32_tile = H * Vp * 4                        # logit_w, f32
    int8_tile, scale_bytes = quant.quantized_leaf_bytes((H, Vp), 1)
    if int8_tile * 4 != f32_tile:
        raise RuntimeError(
            f"int8w vocab tile {int8_tile} B is not exactly 0.25x the "
            f"f32 tile {f32_tile} B — the closed form drifted"
        )
    f32_ps = H * (Vp // 2) * 4                   # per TP=2 shard
    int8_ps, scale_ps = quant.quantized_leaf_bytes((H, Vp // 2), 1)
    if int8_ps * 4 != f32_ps:
        raise RuntimeError(
            f"per-shard int8w vocab tile {int8_ps} B is not exactly "
            f"0.25x the f32 shard tile {f32_ps} B under TP=2"
        )
    p = one["int8w_fused"]["eng"].params
    p = p["params"] if "params" in p else p
    measured_tile = int(np.asarray(p["logit_w"]).nbytes)
    if measured_tile != int8_tile:
        raise RuntimeError(
            f"measured int8 logit_w bytes {measured_tile} != closed "
            f"form {int8_tile} — the byte accounting is dishonest"
        )

    out = {
        "lowprec_fused_virtual_devices": n,
        "lowprec_fused_host_cores": float(os.cpu_count() or 1),
        "lowprec_fused_xla_flags": os.environ.get("XLA_FLAGS", ""),
        "lowprec_fused_jax_platforms": os.environ.get(
            "JAX_PLATFORMS", ""
        ),
        "lowprec_fused_mesh_shape": tp["int8w_fused"]["mesh_shape"],
        "lowprec_fused_vocab": Vp,
        "lowprec_fused_beam": cfg.eval.beam_size,
        "lowprec_fused_batch": B,
        "lowprec_fused_match_floor": RELAXED_SERVING_MATCH_FLOOR,
        "lowprec_fused_score_rtol": RELAXED_SERVING_SCORE_RTOL,
        # Closed-form streamed vocab tile: int8 codes are EXACTLY
        # 0.25x the f32 tile (asserted above); the per-channel scale
        # rows are the honest small print, on both grids.
        "lowprec_fused_vocab_tile_f32_bytes": f32_tile,
        "lowprec_fused_vocab_tile_int8w_bytes": int8_tile,
        "lowprec_fused_vocab_tile_scale_bytes": scale_bytes,
        "lowprec_fused_vocab_tile_ratio": round(int8_tile / f32_tile, 6),
        "lowprec_fused_vocab_tile_measured_bytes": measured_tile,
        "lowprec_fused_tp2_vocab_tile_f32_bytes": f32_ps,
        "lowprec_fused_tp2_vocab_tile_int8w_bytes": int8_ps,
        "lowprec_fused_tp2_vocab_tile_scale_bytes": scale_ps,
        "lowprec_fused_tp2_vocab_tile_ratio": round(int8_ps / f32_ps, 6),
        "lowprec_fused_int8w_match_rate": round(kernel_match, 4),
        "lowprec_fused_int8w_f32_match_rate": round(f32_match, 4),
        "lowprec_fused_int8w_tp2_match_rate": round(tp_match, 4),
        "lowprec_fused_int8w_score_gap_max": round(gap, 6),
        "lowprec_fused_int8w_vs_f32_ratio": round(
            one["int8w_fused"]["captions_per_sec"]
            / one["f32_fused"]["captions_per_sec"], 4
        ),
        "lowprec_fused_vs_unfused_ratio": round(
            one["int8w_fused"]["captions_per_sec"]
            / one["int8w_unfused"]["captions_per_sec"], 4
        ),
    }
    for arm, _d, _f in ARMS:
        out[f"lowprec_fused_{arm}_captions_per_sec"] = round(
            one[arm]["captions_per_sec"], 3
        )
        out[f"lowprec_fused_{arm}_p99_batch_ms"] = round(
            one[arm]["p99_batch_ms"], 2
        )
        out[f"lowprec_fused_{arm}_param_bytes_per_shard"] = one[arm][
            "bytes_per_shard"
        ]
        out[f"lowprec_fused_{arm}_tp2_captions_per_sec"] = round(
            tp[arm]["captions_per_sec"], 3
        )
        out[f"lowprec_fused_{arm}_tp2_p99_batch_ms"] = round(
            tp[arm]["p99_batch_ms"], 2
        )
        out[f"lowprec_fused_{arm}_tp2_param_bytes_per_shard"] = tp[
            arm
        ]["bytes_per_shard"]
    # Schema-pinned (validate_record): *_extra_declines is EXACTLY 0 —
    # the raw per-arm counts are environmental (CPU interpret gate)
    # and recorded under a suffix the pin doesn't bite.
    out["lowprec_fused_int8w_extra_declines"] = extra_1dev
    out["lowprec_fused_int8w_tp2_extra_declines"] = extra_tp2
    for arm, count in declines.items():
        out[f"lowprec_fused_{arm}_env_gate_lines"] = count
    return out


def bench_lowprec_fused(backend_ok: bool = True):
    """Fused×int8w composition rows (see
    :func:`_bench_lowprec_fused_impl`).  Runs inline on a >=2-device
    host, otherwise re-execs onto a virtual 2-device CPU platform so
    the TP=2 arm shards a real mesh."""
    import subprocess

    if backend_ok:
        try:
            if len(jax.devices()) >= 2:
                return _bench_lowprec_fused_impl()
        except Exception:  # noqa: BLE001 — fall through to the child
            pass
    env = dict(os.environ)
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_LOWPREC_FUSED_CHILD"] = "1"
    here = os.path.abspath(__file__)
    r = subprocess.run(
        [sys.executable, here],
        capture_output=True, text=True, timeout=1200, env=env,
        cwd=os.path.dirname(here),
    )
    lines = [
        ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")
    ]
    if r.returncode != 0 or not lines:
        tail = (r.stderr or r.stdout).strip().splitlines()
        raise RuntimeError(
            f"lowprec_fused child rc={r.returncode}: "
            f"{tail[-1] if tail else 'no output'}"
        )
    out = json.loads(lines[-1])
    out["lowprec_fused_virtual_cpu"] = 1
    return out


def _bench_spec_impl():
    """Speculative-decode serving rows (the in-process child of
    :func:`bench_spec`; ISSUE 18).

    One random init, one fixed request stream, two slot decoders on the
    SAME weights — plain greedy vs ``serving.speculative`` — driven
    through the identical admit/tick/harvest loop at matched offered
    load.  Two gates run BEFORE anything records:

    * **token-exactness** — every harvested token array from the
      speculative arm must equal the plain arm's byte-for-byte
      (``spec_token_mismatches`` is asserted 0; the rejection rule
      makes this an invariant, so a nonzero count is a bug, not noise).
    * **speedup floor** — mean emitted tokens per live slot-round must
      beat 1.0 (the non-speculative floor); a draft that never gets a
      token accepted must not record as a win.

    The draft is distilled IN the child against the request pool's own
    teacher streams (the ``cli/distill_draft.py`` update step, a few
    hundred Adam steps on a tiny pool — memorization is the point:
    acceptance on this pool stands in for a distilled draft's
    acceptance on its serving distribution).  Virtual-CPU captions/s
    are not TPU captions/s; ``spec_host_cores``/``spec_mesh_shape``
    provenance keeps the rows caveated from the record alone."""
    import copy
    import shutil
    import tempfile

    import optax

    from cst_captioning_tpu.cli.distill_draft import _make_update
    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.constants import BOS_ID, EOS_ID, PAD_ID
    from cst_captioning_tpu.data.vocab import Vocabulary
    from cst_captioning_tpu.decoding.speculative import (
        make_draft_params,
        save_draft_params,
    )
    from cst_captioning_tpu.serving.engine import InferenceEngine

    k = int(os.environ.get("BENCH_SPEC_K", "4"))
    steps = int(os.environ.get("BENCH_SPEC_STEPS", "200"))
    lr = float(os.environ.get("BENCH_SPEC_LR", "0.003"))
    n_reqs = int(os.environ.get("BENCH_SPEC_REQS", "48"))
    n_pool = int(os.environ.get("BENCH_SPEC_POOL", "4"))

    cfg = get_preset("synthetic_smoke")
    cfg.serving.warmup = False
    cfg.serving.decode_mode = "greedy"     # spec is greedy-only
    cfg.serving.num_slots = 4
    cfg.serving.slot_block_steps = 1       # 1 token/slot-tick floor
    cfg.serving.dedup_cache = False        # pool keys repeat on purpose
    vocab = Vocabulary([f"w{i}" for i in range(252)])
    cfg.model.vocab_size = len(vocab)
    base = InferenceEngine(cfg, random_init=True, vocab=vocab)

    rng = np.random.RandomState(20260807)
    F = cfg.data.max_frames
    pool = [
        {
            "features": {
                m: rng.randn(F, d).astype(np.float32)
                for m, d in cfg.data.feature_dims.items()
            }
        }
        for _ in range(n_pool)
    ]

    # ---- teacher streams for the pool (the full model's greedy
    # tokens), then distill the draft to memorize them
    T = int(cfg.eval.max_decode_len)
    reqs = [base.prepare(dict(p)) for p in pool]
    feats = {
        m: jnp.asarray(np.stack([r.feats[m] for r in reqs]))
        for m in reqs[0].feats
    }
    masks = {
        m: jnp.asarray(np.stack([r.masks[m] for r in reqs]))
        for m in reqs[0].masks
    }
    state, cache = base.model.apply(
        base.params, feats, masks, None, method="init_decode"
    )
    tok = jnp.full((n_pool,), BOS_ID, jnp.int32)
    finished = jnp.zeros((n_pool,), bool)
    cols = [tok]
    for _ in range(T):
        state, logits = base.model.apply(
            base.params, state, cache, tok, method="decode_logits"
        )
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        col = jnp.where(finished, PAD_ID, nxt)
        cols.append(col)
        finished = finished | (col == EOS_ID)
        tok = jnp.where(finished, EOS_ID, col)
    seqs = jnp.stack(cols, axis=1)

    p = base.params["params"] if "params" in base.params else base.params
    hd = int(os.environ.get("BENCH_SPEC_HIDDEN", "0")) or min(
        p["word_embed"].shape[1], p["logit_w"].shape[0]
    )
    dp = {k2: jnp.asarray(v) for k2, v in
          make_draft_params(base.params, hd).items()}
    opt = optax.adam(lr)
    opt_state = opt.init(dp)
    update = _make_update(opt, bool(base.model.decode_suppress_unk))
    agree = None
    for _ in range(steps):
        dp, opt_state, _loss, agree = update(dp, opt_state, seqs)
    teacher_match = float(jax.device_get(agree))

    tmp = tempfile.mkdtemp(prefix="bench_spec_draft_")
    try:
        draft_path = os.path.join(tmp, "draft.npz")
        save_draft_params(draft_path, dp)
        c = copy.deepcopy(cfg)
        c.serving.speculative = {
            "draft_k": k, "draft_hidden": hd,
            "draft_params": draft_path,
        }
        spec_eng = InferenceEngine(c, params=base.params, vocab=vocab)
        # ISSUE 20 composition arm: the SAME draft over int8w-quantized
        # verify weights (the verifier's batched vocab GEMM rides the
        # model's quantized logit path).  Built inside the tempdir so
        # the draft file is still on disk at boot.
        c8 = copy.deepcopy(c)
        c8.serving.dtype = "int8w"
        spec8_eng = InferenceEngine(c8, params=base.params, vocab=vocab)
        p8 = copy.deepcopy(cfg)
        p8.serving.dtype = "int8w"
        plain8_eng = InferenceEngine(p8, params=base.params, vocab=vocab)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # ---- matched-load drive: same request stream, same loop
    def drive(eng):
        dec = eng.slot_decoder()
        dec.warmup()
        pending = [
            (i, eng.prepare(dict(pool[i % n_pool])))
            for i in range(n_reqs)
        ]
        got = {}
        tick_s = []
        t0 = time.perf_counter()
        while pending or dec.occupied:
            n = min(len(pending), len(dec.free), dec.admit_cap)
            batch = [pending.pop(0) for _ in range(n)]
            tt = time.perf_counter()
            done = dec.tick(
                [r for _, r in batch], [i for i, _ in batch]
            )
            tick_s.append(time.perf_counter() - tt)
            for i, tokens, _score, _steps in dec.harvest_many(done):
                got[i] = np.asarray(tokens)
        wall = time.perf_counter() - t0
        tick_s.sort()
        p99 = tick_s[min(len(tick_s) - 1, int(len(tick_s) * 0.99))]
        return got, wall, len(tick_s), p99, dec

    got_base, wall_base, ticks_base, p99_base, _ = drive(base)
    got_spec, wall_spec, ticks_spec, p99_spec, dec_spec = drive(spec_eng)

    # ---- gate 1: token-exactness, asserted BEFORE recording
    mismatches = sum(
        1 for i in range(n_reqs)
        if not np.array_equal(got_spec[i], got_base[i])
    )
    if mismatches:
        raise RuntimeError(
            f"speculative decode diverged on {mismatches}/{n_reqs} "
            "requests — token-exactness is the contract "
            "(docs/PARITY.md r18); not recording perf for wrong tokens"
        )
    stats = dec_spec.spec_stats()
    # ---- gate 2: the speedup floor — >1 token per live slot-round
    if stats["tokens_per_round"] <= 1.0:
        raise RuntimeError(
            f"speculation emitted {stats['tokens_per_round']:.3f} "
            "tokens per live slot-round — no better than the "
            "non-speculative floor; not recording as a win"
        )

    # ---- ISSUE 20 composition row: speculation × int8w.  Token-
    # exactness is asserted against the PLAIN int8w decoder (same
    # quantized weights, same rejection rule) — the relaxed-serving
    # bound lives between int8w and f32, never between spec and plain,
    # so a single diverged token here is a verifier bug, not noise.
    got_p8, wall_p8, _tk, _p9, _ = drive(plain8_eng)
    got_s8, wall_s8, _tk2, _p92, dec8 = drive(spec8_eng)
    mm8 = sum(
        1 for i in range(n_reqs)
        if not np.array_equal(got_s8[i], got_p8[i])
    )
    if mm8:
        raise RuntimeError(
            f"speculative decode over int8w weights diverged on "
            f"{mm8}/{n_reqs} requests vs the plain int8w decoder — "
            "the verify GEMM must ride the same quantized logit path"
        )
    st8 = dec8.spec_stats()
    if st8["tokens_per_round"] <= 1.0:
        raise RuntimeError(
            f"int8w speculation emitted {st8['tokens_per_round']:.3f} "
            "tokens per live slot-round — no better than the "
            "non-speculative floor; not recording as a win"
        )

    return {
        "spec_host_cores": float(os.cpu_count() or 1),
        "spec_xla_flags": os.environ.get("XLA_FLAGS", ""),
        "spec_jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
        "spec_mesh_shape": spec_eng.describe()["mesh_shape"],
        "spec_draft_k": float(k),
        "spec_draft_hidden": float(hd),
        "spec_distill_steps": float(steps),
        "spec_distill_teacher_match": round(teacher_match, 4),
        "spec_requests": float(n_reqs),
        "spec_pool_keys": float(n_pool),
        "spec_token_mismatches": float(mismatches),
        "spec_acceptance_rate": round(stats["acceptance_rate"], 4),
        "spec_tokens_per_tick": round(stats["tokens_per_round"], 4),
        "spec_emitted_tokens": stats["emitted_tokens"],
        "spec_live_slot_rounds": stats["live_slot_rounds"],
        "spec_captions_per_sec": round(n_reqs / wall_spec, 3),
        "spec_baseline_captions_per_sec": round(n_reqs / wall_base, 3),
        "spec_vs_baseline_ratio": round(wall_base / wall_spec, 4),
        "spec_ticks": float(ticks_spec),
        "spec_baseline_ticks": float(ticks_base),
        "spec_p99_tick_ms": round(p99_spec * 1e3, 3),
        "spec_baseline_p99_tick_ms": round(p99_base * 1e3, 3),
        "spec_int8w_token_mismatches": float(mm8),
        "spec_int8w_acceptance_rate": round(st8["acceptance_rate"], 4),
        "spec_int8w_tokens_per_tick": round(st8["tokens_per_round"], 4),
        "spec_int8w_captions_per_sec": round(n_reqs / wall_s8, 3),
        "spec_int8w_baseline_captions_per_sec": round(
            n_reqs / wall_p8, 3
        ),
        "spec_int8w_vs_baseline_ratio": round(wall_p8 / wall_s8, 4),
    }


def bench_spec():
    """Speculative-decode rows (see :func:`_bench_spec_impl`).
    Re-execs into a CPU subprocess (the bench_slo precedent): the
    distill loop + paired drive target the smoke shape and must not
    disturb a TPU-held parent."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_SPEC_CHILD"] = "1"
    here = os.path.abspath(__file__)
    r = subprocess.run(
        [sys.executable, here],
        capture_output=True, text=True, timeout=1200, env=env,
        cwd=os.path.dirname(here),
    )
    lines = [
        ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")
    ]
    if r.returncode != 0 or not lines:
        tail = (r.stderr or r.stdout).strip().splitlines()
        raise RuntimeError(
            f"spec decode child rc={r.returncode}: "
            f"{tail[-1] if tail else 'no output'}"
        )
    return json.loads(lines[-1])


def bench_loader():
    """Host batch assembly from the packed feature store at MSR-VTT shape
    (B=64 videos, 28 frames, resnet-2048 + c3d-4096, float16 on disk).
    The bar (VERDICT r1 #6): assembly must be well under the TPU step time
    so the prefetch thread hides it completely."""
    import shutil
    import tempfile

    from cst_captioning_tpu.data.packed import PackedSource, pack_modality

    V, F, B = 128, 28, 64
    dims = {"resnet": 2048, "c3d": 4096}
    tmp = tempfile.mkdtemp(prefix="bench_packed_")
    try:
        rng = np.random.RandomState(0)
        srcs = {}
        for m, D in dims.items():
            pack_modality(
                tmp, m, [f"v{i}" for i in range(V)],
                (rng.randn(F, D).astype(np.float16) for _ in range(V)),
                F, D, dtype="float16",
            )
            srcs[m] = PackedSource(tmp, m)
        idxs = rng.permutation(V)[:B]
        for src in srcs.values():  # warm the page cache
            src.get_batch(idxs, F)
        times = []
        for _ in range(7):
            t0 = time.perf_counter()
            for src in srcs.values():
                src.get_batch(idxs, F)
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2] * 1e3
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def load_round_baseline(metric: str, unit: str):
    """Earliest recorded round for this metric.  Driver artifacts are
    zero-padded (BENCH_r01.json) and wrap the line under "parsed"."""
    recs = []
    for p in glob.glob("BENCH_r*.json"):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if not m:
            continue
        try:
            with open(p) as f:
                rec = json.load(f)
        except Exception:
            continue
        parsed = rec.get("parsed", rec)
        if (
            isinstance(parsed, dict)
            and parsed.get("metric") == metric
            and parsed.get("unit") == unit
            # Degraded-mode records carry value: null (backend down but
            # sub-metrics measured) — they are not baselines.
            and isinstance(parsed.get("value"), (int, float))
        ):
            recs.append((int(m.group(1)), float(parsed["value"])))
    if not recs:
        return None
    return min(recs)[1]


def _probe_backend_subprocess(timeout_s: float):
    """Check backend health in a subprocess with a hard timeout.

    The tunneled runtime fails BOTH ways: a raised ``UNAVAILABLE`` (what
    zeroed BENCH_r04) and a silent HANG inside backend init (observed in
    the judge's session and reproduced here) — and an in-process
    ``jax.devices()`` that hangs cannot be cancelled, so the probe must
    live in a killable subprocess.  Returns ``(ok, info_str)``.
    """
    import subprocess

    code = "import jax; d = jax.devices(); print(len(d), d[0].platform)"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"backend init hung > {timeout_s:.0f}s (probe killed)"
    if r.returncode == 0 and r.stdout.strip():
        return True, r.stdout.strip().splitlines()[-1]
    tail = (r.stderr or r.stdout).strip().splitlines()
    return False, (tail[-1] if tail else f"probe rc={r.returncode}")


def _clear_backends():
    try:
        from jax.extend import backend as _jeb

        _jeb.clear_backends()
    except Exception:
        pass


def _wait_for_backend(max_wait_s: float, reset_first: bool = False):
    """Retry backend init until it comes up or the budget runs out.

    Round 4's record was zeroed by a single transient
    ``UNAVAILABLE: TPU backend setup/compile error`` raised at the first
    ``jax.devices()`` — before any metric was emitted (VERDICT r4 #1-2).
    Each attempt first proves the backend healthy in a killable
    subprocess (see ``_probe_backend_subprocess``), then initialises
    in-process.  ``reset_first``: the caller already holds a (possibly
    stale, possibly device-locking) backend client from an earlier
    successful init — drop it BEFORE probing, so (a) the probe
    subprocess can attach to a locally-locked TPU and (b) the in-process
    re-init below builds a fresh client instead of returning the cached
    dead one.  Returns ``(ok, last_error, waited_s)``.

    A probe verdict of "init hung > Ns" is DETERMINISTIC — round 5
    re-probed the same hung backend three times and burned 388 s
    (BENCH_r05 ``backend_init_wait_s``) to learn nothing new — so a
    hang fails fast after the FIRST verdict; the retry loop is only for
    transient init ERRORS (raised UNAVAILABLE and friends).
    """
    t0 = time.monotonic()
    delay = 5.0
    last = None
    probe_budget = min(90.0, max(15.0, max_wait_s / 3.0))
    reinit = reset_first
    while True:
        if reinit:
            _clear_backends()
        ok, info = _probe_backend_subprocess(probe_budget)
        if ok:
            if reinit:
                # An in-process client may have been rebuilt lazily by
                # anything touching jax between the clear and now; clear
                # again right before the fresh init.
                _clear_backends()
            try:
                jax.devices()
                return True, last, time.monotonic() - t0
            except Exception as e:  # noqa: BLE001 — init is retryable
                last = f"{type(e).__name__}: {e}"
                reinit = True
        else:
            last = info
            if "hung" in info:
                print(
                    f"bench: backend init hung — deterministic verdict, "
                    f"skipping retries ({info})",
                    file=sys.stderr, flush=True,
                )
                return False, last, time.monotonic() - t0
        waited = time.monotonic() - t0
        if waited >= max_wait_s:
            return False, last, waited
        print(
            f"bench: backend unavailable ({str(last)[:140]}); retrying in "
            f"{delay:.0f}s ({waited:.0f}s/{max_wait_s:.0f}s)",
            file=sys.stderr, flush=True,
        )
        time.sleep(min(delay, max(0.0, max_wait_s - waited)))
        delay = min(delay * 1.7, 60.0)


def main() -> int:
    # TPU-first PRNG: hardware rbg instead of threefry (config default;
    # the bench creates its own keys, so set the impl here too).
    jax.config.update(
        "jax_default_prng_impl", os.environ.get("BENCH_RNG", "rbg")
    )
    metric = "xe_train_throughput_msrvtt_resnet_c3d"
    unit = "steps/sec/chip"
    extra = {"bench_chunk": bench_chunk()}
    errors = {}
    state = {"sps_chip": None}

    # ISSUE 18 satellite: BENCH_ONLY=<prefix> (or ``--only <prefix>`` on
    # the command line) narrows the run to sub-bench families whose
    # name starts with the prefix, case-insensitive — BENCH_ONLY=spec
    # runs just the speculative rows without flipping a dozen BENCH_*
    # switches off by hand.  The per-family BENCH_<NAME>=0 kill
    # switches still win, and the active filter is recorded in the row
    # (``bench_only``) so a narrowed artifact can never masquerade as
    # a full run.
    only = os.environ.get("BENCH_ONLY", "")
    if "--only" in sys.argv[1:]:
        i = sys.argv.index("--only")
        if i + 1 < len(sys.argv):
            only = sys.argv[i + 1]
    if only:
        extra["bench_only"] = only

    def family_on(name: str) -> bool:
        if os.environ.get(f"BENCH_{name}", "1") != "1":
            return False
        return not only or name.lower().startswith(only.lower())

    # PR 8: invariant-engine preflight.  The pure-AST pass costs ~2s, so
    # a bench run never measures a tree that violates the machine-checked
    # contracts (docs/ANALYSIS.md) without the record SAYING so — the
    # measurements still run (numbers from a dirty tree beat no numbers),
    # but ``errors.analysis`` marks them.  BENCH_SKIP_ANALYSIS=1 bypasses.
    if not int(os.environ.get("BENCH_SKIP_ANALYSIS", "0") or 0):
        try:
            from pathlib import Path as _Path

            from cst_captioning_tpu.analysis import (
                run_analysis,
                validate_report,
            )

            # ISSUE 12: the preflight rides the incremental cache —
            # an unchanged tree re-validates in milliseconds, and the
            # record says how much was reused (cache_hit_files) and
            # how many rule families gated the run (rules_active).
            _cache_dir = _Path(
                os.environ.get("BENCH_ANALYSIS_CACHE", "")
                or _Path(__file__).resolve().parent / ".analysis_cache"
            )
            _rep = run_analysis(cache_dir=_cache_dir)
            validate_report(_rep.to_dict())
            extra["analysis_findings"] = len(_rep.findings)
            extra["analysis_duration_s"] = round(_rep.duration_s, 3)
            extra["analysis_rules_active"] = len(_rep.rules_run)
            extra["analysis_cache_hit_files"] = _rep.cache_hit_files
            # ISSUE 15: how many rule families actually gated this run,
            # and what the dtype/shape abstract interpreter cost on top
            # (0.0 on a warm cache hit — the flow never ran).
            extra["analysis_families_active"] = len(_rep.rules_run)
            from cst_captioning_tpu.analysis import typeflow as _tfmod

            extra["analysis_typeflow_duration_s"] = round(
                0.0 if _rep.cache_hit_files else _tfmod.last_duration(),
                3,
            )
            if not _rep.clean:
                errors["analysis"] = "; ".join(
                    f.render() for f in _rep.findings[:5]
                )
        except Exception as e:  # noqa: BLE001 — preflight never sinks bench
            errors["analysis"] = f"{type(e).__name__}: {e}"

    def emit(partial: bool = True):
        """Print the record as it stands — ONE line per completed
        sub-bench (VERDICT r5 #2): a ~3-minute backend window
        mid-outage, or a mid-bench crash/timeout, still leaves the
        driver a parseable line with every metric measured so far (the
        last line printed is the most complete).  The final call drops
        the ``partial`` marker."""
        sps = state["sps_chip"]
        prev = load_round_baseline(metric, unit)
        vs = (sps / prev) if (prev and sps is not None) else (
            1.0 if sps is not None else None
        )
        rec = {
            "metric": metric,
            "value": round(sps, 4) if sps is not None else None,
            "unit": unit,
            "vs_baseline": round(vs, 4) if vs is not None else None,
            "extra": dict(extra),
        }
        if errors:
            rec["errors"] = dict(errors)
        if partial:
            rec["partial"] = True
        # Fail loudly on a malformed row BEFORE it reaches the driver
        # artifact (required keys, no bool-typed measured fields).
        validate_record(rec, kind="bench")
        print(json.dumps(rec), flush=True)
        return rec

    ok, err, waited = _wait_for_backend(
        float(os.environ.get("BENCH_BACKEND_WAIT_S", "300"))
    )
    if waited > 1:
        extra["backend_init_wait_s"] = round(waited, 1)
    if not ok:
        errors["backend"] = err
        # Machine-readable reason the device sub-benches were skipped
        # (null headline): "hung" verdicts fail fast (one probe), only
        # transient errors exhaust the retry budget.
        extra["backend_skip_reason"] = str(err)

    # The headline bench gets the same don't-sink-the-record treatment as
    # the sub-benches (VERDICT r4 weak #1): retry once across a backend
    # reset, and on final failure still emit the JSON line with an error
    # field so the driver records whatever WAS measured.  The FIRST
    # attempt runs a small chunk — a cheap time-to-first-metric so a
    # brief backend window yields ``value != null`` (VERDICT r5 #2) —
    # then the full-chunk measurement replaces it.
    first_chunk = int(os.environ.get("BENCH_FIRST_CHUNK", "12"))
    sps_chip = tflops = None
    # The headline rides the BENCH_ONLY filter too (family name "xe"):
    # a narrowed run skips straight to the selected sub-bench, leaving
    # value=null — the recorded bench_only says why.
    if ok and family_on("XE"):
        try:
            sps_first, tflops = bench_xe(chunk=first_chunk)
            sps_chip = sps_first
            state["sps_chip"] = sps_chip
            extra["bench_chunk"] = first_chunk
            extra["xe_steps_per_sec_chip_first_chunk"] = round(
                sps_first, 4
            )
            emit()
        except Exception as e:  # noqa: BLE001
            errors["xe"] = f"{type(e).__name__}: {e}"
            # reset_first: the client that just failed is cached (and on
            # a local TPU holds the device lock) — it must be dropped or
            # the retry reuses it verbatim.
            re_ok, _, re_waited = _wait_for_backend(
                120.0, reset_first=True
            )
            extra["backend_retry_wait_s"] = round(re_waited, 1)
            ok = re_ok
        if ok:
            try:
                sps_chip, tflops = bench_xe()
                errors.pop("xe", None)
                extra["bench_chunk"] = bench_chunk()
                state["sps_chip"] = sps_chip
            except Exception as e:  # noqa: BLE001
                # Keep the small-chunk headline if the full run died.
                if sps_chip is None:
                    errors["xe"] = f"{type(e).__name__}: {e}"
                else:
                    errors["xe_full_chunk"] = f"{type(e).__name__}: {e}"
    if sps_chip is not None:
        extra["xe_tflops_per_sec_chip"] = round(tflops, 2)
        # v5e bf16 peak ~197 TFLOP/s; report MFU only when plausible.
        dev = jax.devices()[0]
        if "cpu" not in dev.platform:
            extra["xe_mfu_vs_v5e_peak"] = round(tflops / 197.0, 4)
        emit()
    if ok and family_on("ATTN"):
        # The flagship (entry()) attention-fusion model — slower than
        # meanpool by construction (per-step Bahdanau attention inside the
        # decode scan); the Pallas fused step (ops/pallas_attention.py)
        # closes part of that gap.  Tracked as an extra so regressions on
        # the flagship are visible without moving the headline metric.
        try:
            attn_sps, attn_tflops = bench_xe(fusion="attention")
            extra["xe_attention_steps_per_sec_chip"] = round(attn_sps, 4)
            extra["xe_attention_tflops_per_sec_chip"] = round(
                attn_tflops, 2
            )
        except Exception as e:
            extra["attn_error"] = f"{type(e).__name__}: {e}"
        emit()
    if ok and family_on("CST"):
        try:
            extra.update(bench_cst())
        except Exception as e:  # CST bench must never sink the headline
            extra["cst_error"] = f"{type(e).__name__}: {e}"
        emit()
    if family_on("OVERLAP_SIM"):
        # Chunked-scoring overlap evidence (VERDICT r3 weak #2): the
        # latency gate disables chunking on tunneled runtimes, so the
        # pipeline the default config ships is demonstrated in a
        # subprocess on the in-process CPU backend (dispatch ~0.02 ms)
        # with the scorer cost injected at the measured scorer:rollout
        # ratio.  Subprocess: this process holds the TPU.
        try:
            import subprocess

            r = subprocess.run(
                [sys.executable, "-m",
                 "cst_captioning_tpu.tools.overlap_sim"],
                capture_output=True, text=True, timeout=600,
            )
            line = r.stdout.strip().splitlines()[-1]
            extra.update(json.loads(line))
        except Exception as e:
            extra["overlap_sim_error"] = f"{type(e).__name__}: {e}"
        emit()
    if family_on("CST_PIPE"):
        # Paired serial-vs-pipelined CST reward-scheduling rows
        # (subprocess on the in-process CPU backend; no live backend
        # needed in this process, so it runs in degraded mode too).
        try:
            extra.update(bench_cst_pipeline())
        except Exception as e:  # noqa: BLE001
            extra["cst_pipe_error"] = f"{type(e).__name__}: {e}"
        emit()
    if family_on("CST_SLOT"):
        # Paired padded-vs-slot CST rollout rows (subprocess on the
        # in-process CPU backend; degraded-mode safe like cst_pipe).
        try:
            extra.update(bench_cst_slot())
        except Exception as e:  # noqa: BLE001
            extra["cst_slot_error"] = f"{type(e).__name__}: {e}"
        emit()
    if ok and family_on("DECODE"):
        try:
            extra.update(bench_decode())
        except Exception as e:
            extra["decode_error"] = f"{type(e).__name__}: {e}"
        emit()
    if family_on("SLOT_MEM"):
        # Paired replicated-vs-deduped decode-state memory rows
        # (subprocess on the in-process CPU backend; the byte rows are
        # deterministic pytree arithmetic — degraded-mode safe).
        try:
            extra.update(bench_slot_mem())
        except Exception as e:  # noqa: BLE001
            extra["slot_mem_error"] = f"{type(e).__name__}: {e}"
        emit()
    if family_on("SERVING"):
        # Serving subsystem sweep (serving/): needs a live jax backend
        # but drops to the CPU-sized shape off-TPU, so it runs in
        # degraded mode too as long as ANY backend initializes.
        try:
            extra.update(bench_serving())
        except Exception as e:
            extra["serving_error"] = f"{type(e).__name__}: {e}"
        emit()
    if family_on("REPLICAS"):
        # Multi-replica scheduler sweep: inline on multi-device hosts,
        # re-exec'd onto a virtual multi-device CPU platform otherwise
        # — so it records 1-vs-N scaling even with the backend down.
        try:
            extra.update(bench_serving_replicas(backend_ok=ok))
        except Exception as e:  # noqa: BLE001
            extra["replicas_error"] = f"{type(e).__name__}: {e}"
        emit()
    if family_on("TRACE"):
        # Paired tracing-on/off serving rows (ISSUE 10): the span
        # layer's cost on sustained captions/s + p99, measured in a
        # CPU subprocess (degraded-mode safe) — the <=2% acceptance bar
        # rides in trace_overhead_ratio.
        try:
            extra.update(bench_trace_overhead())
        except Exception as e:  # noqa: BLE001
            extra["trace_bench_error"] = f"{type(e).__name__}: {e}"
        emit()
    if family_on("SLO"):
        # Chaos soak + SLO-attainment rows (ISSUE 11): recorded-trace
        # replay against a 2-replica set with mid-traffic chaos, in a
        # CPU subprocess (degraded-mode safe).  The reference-load
        # attainment feeds the SLO regression gate below.
        try:
            extra.update(bench_slo())
        except Exception as e:  # noqa: BLE001
            extra["slo_error"] = f"{type(e).__name__}: {e}"
        gate_reason = slo_gate(extra)
        if gate_reason is not None:
            errors["slo_gate"] = gate_reason
            print(f"SLO GATE FAILED: {gate_reason}", file=sys.stderr)
        emit()
    if family_on("COLDSTART"):
        # Paired warm-vs-AOT cold-start rows (ISSUE 13): process start
        # -> first caption served, measured on fresh subprocesses over
        # one shared artifact (CPU child; degraded-mode safe).  The
        # coldstart_ratio row is the elastic-fleet acceptance number.
        try:
            extra.update(bench_coldstart())
        except Exception as e:  # noqa: BLE001
            extra["coldstart_error"] = f"{type(e).__name__}: {e}"
        emit()
    if family_on("SHARD"):
        # Paired replicated-vs-model-sharded XE rows on a >=4-device
        # mesh (ISSUE 9): inline on multi-device hosts, re-exec'd onto
        # a virtual CPU platform otherwise — vocab-matmul collective
        # bytes + steps/s + per-device vocab-param bytes, with honest
        # *_mesh_shape / *_host_cores / xla-flags provenance fields.
        try:
            extra.update(bench_shard(backend_ok=ok))
        except Exception as e:  # noqa: BLE001
            extra["shard_error"] = f"{type(e).__name__}: {e}"
        emit()
    if family_on("SHARD_FUSED"):
        # Paired fused-vs-scan model-sharded slot-decode rows (ISSUE
        # 14): candidate-all-gather vs full-vocab-gather collective
        # bytes + steps/s under M=2 on a virtual 2-device CPU mesh,
        # token parity asserted before anything is recorded.
        try:
            extra.update(bench_shard_fused(backend_ok=ok))
        except Exception as e:  # noqa: BLE001
            extra["shard_fused_error"] = f"{type(e).__name__}: {e}"
        emit()
    if family_on("LOWPREC"):
        # Paired f32/bf16/int8w serving rows (ISSUE 16): captions/s +
        # p99 + per-shard weight bytes at matched offered load on the
        # 1-device and TP=2 grids, with the relaxed-serving parity
        # bounds (caption-match floor, score-gap rtol) asserted BEFORE
        # anything records.
        try:
            extra.update(bench_lowprec(backend_ok=ok))
        except Exception as e:  # noqa: BLE001
            extra["lowprec_error"] = f"{type(e).__name__}: {e}"
        emit()
    if family_on("LOWPREC_FUSED"):
        # Fused×int8w composition rows (ISSUE 20): fused-f32 vs
        # fused-int8w vs unfused-int8w captions/s + p99 on the
        # 1-device and TP=2 grids — zero fused declines, the
        # relaxed-serving parity bounds, and the exact 0.25x streamed
        # vocab tile all asserted BEFORE anything records.
        try:
            extra.update(bench_lowprec_fused(backend_ok=ok))
        except Exception as e:  # noqa: BLE001
            extra["lowprec_fused_error"] = f"{type(e).__name__}: {e}"
        emit()
    if family_on("SPEC"):
        # Speculative-decode rows (ISSUE 18): draft-LSTM propose +
        # full-model batched verify on the slot runtime, distilled
        # in-child, token-exactness AND the >1 token/slot-round floor
        # asserted before anything records (CPU subprocess;
        # degraded-mode safe).
        try:
            extra.update(bench_spec())
        except Exception as e:  # noqa: BLE001
            extra["spec_error"] = f"{type(e).__name__}: {e}"
        emit()
    if family_on("LOADER"):
        # Host-only bench: runs even when the device backend is down.
        try:
            ms = bench_loader()
            extra["loader_packed_assembly_ms"] = round(ms, 2)
            if sps_chip is not None:
                extra["loader_vs_step_time"] = round(
                    ms / (1e3 / sps_chip / max(1, len(jax.devices()))), 4
                )
        except Exception as e:
            extra["loader_error"] = f"{type(e).__name__}: {e}"
        emit()

    prev = load_round_baseline(metric, unit)
    # The round-1 baseline was recorded at BENCH_CHUNK=10, where ~140ms
    # of per-dispatch tunnel overhead deflates the number; vs_baseline
    # therefore conflates the chunk-10->60 measurement fix with real
    # speedup (VERDICT r2 weak #6).  Re-measure at chunk 10 so the
    # apples-to-apples ratio is machine-readable.
    if (
        ok
        and sps_chip is not None
        and family_on("MATCHED")
        and prev
    ):
        try:
            sps10, _ = bench_xe(chunk=10)
            extra["xe_steps_per_sec_chip_chunk10"] = round(sps10, 4)
            extra["vs_baseline_matched_chunk"] = round(sps10 / prev, 4)
        except Exception as e:
            extra["matched_chunk_error"] = f"{type(e).__name__}: {e}"
    emit(partial=False)
    # Exit 0 whenever ANY metric was recorded — a partial record must
    # reach the driver artifact instead of being discarded (VERDICT r4
    # #2).  Non-zero only when nothing at all was measured; the
    # diagnostic fields (config echo, backend wait times) don't count,
    # and neither do bools (engagement flags like ``beam_fused`` —
    # ``bool`` subclasses ``int``; ADVICE r5).
    diagnostic = {"bench_chunk", "backend_init_wait_s",
                  "backend_retry_wait_s"}
    measured = sps_chip is not None or any(
        isinstance(v, (int, float))
        and not isinstance(v, bool)
        and k not in diagnostic
        for k, v in extra.items()
    )
    # The SLO regression gate (ISSUE 11) fails the run LOUDLY even when
    # everything else measured fine: a fleet that stopped meeting its
    # latency contract at reference load must not land quietly in the
    # artifact trail.  Exit 3 is the gate's dedicated, named code.
    return bench_exit_code(measured, errors)


if __name__ == "__main__":
    if os.environ.get("BENCH_CST_PIPE_CHILD") == "1":
        # Re-exec'd serial-vs-pipelined CST child (bench_cst_pipeline):
        # parent set JAX_PLATFORMS=cpu; repeat the config update so a
        # sitecustomize platform pin can't win.
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_bench_cst_pipeline_impl()), flush=True)
        sys.exit(0)
    if os.environ.get("BENCH_CST_SLOT_CHILD") == "1":
        # Re-exec'd padded-vs-slot CST rollout child (bench_cst_slot).
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_bench_cst_slot_impl()), flush=True)
        sys.exit(0)
    if os.environ.get("BENCH_SLOT_MEM_CHILD") == "1":
        # Re-exec'd replicated-vs-deduped decode-state child
        # (bench_slot_mem).
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_bench_slot_mem_impl()), flush=True)
        sys.exit(0)
    if os.environ.get("BENCH_SLO_CHILD") == "1":
        # Re-exec'd chaos-soak/SLO child (bench_slo).
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_bench_slo_impl()), flush=True)
        sys.exit(0)
    if os.environ.get("BENCH_COLDSTART_MODE"):
        # Cold-start GRANDCHILD: one fresh process booting warm or from
        # the artifact, serving one caption (bench_coldstart).
        jax.config.update("jax_platforms", "cpu")
        _coldstart_serve_once()
        sys.exit(0)
    if os.environ.get("BENCH_COLDSTART_CHILD") == "1":
        # Re-exec'd cold-start child (bench_coldstart): builds the
        # artifact and times both boot arms as subprocesses.
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_bench_coldstart_impl()), flush=True)
        sys.exit(0)
    if os.environ.get("BENCH_TRACE_CHILD") == "1":
        # Re-exec'd tracing-on/off serving child (bench_trace_overhead).
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_bench_trace_overhead_impl()), flush=True)
        sys.exit(0)
    if os.environ.get("BENCH_SHARD_CHILD") == "1":
        # Re-exec'd replicated-vs-model-sharded child (bench_shard):
        # parent forced a virtual multi-device CPU platform; repeat the
        # config update so a sitecustomize platform pin can't win.
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_bench_shard_impl()), flush=True)
        sys.exit(0)
    if os.environ.get("BENCH_SHARD_FUSED_CHILD") == "1":
        # Re-exec'd fused-vs-scan model-sharded slot-decode child
        # (bench_shard_fused), same virtual-platform discipline.
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_bench_shard_fused_impl()), flush=True)
        sys.exit(0)
    if os.environ.get("BENCH_SPEC_CHILD") == "1":
        # Re-exec'd speculative-decode child (bench_spec).
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_bench_spec_impl()), flush=True)
        sys.exit(0)
    if os.environ.get("BENCH_LOWPREC_CHILD") == "1":
        # Re-exec'd f32/bf16/int8w low-precision serving child
        # (bench_lowprec), same virtual-platform discipline.
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_bench_lowprec_impl()), flush=True)
        sys.exit(0)
    if os.environ.get("BENCH_LOWPREC_FUSED_CHILD") == "1":
        # Re-exec'd fused×int8w composition child (bench_lowprec_fused),
        # same virtual-platform discipline.
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_bench_lowprec_fused_impl()), flush=True)
        sys.exit(0)
    if os.environ.get("BENCH_REPLICA_CHILD") == "1":
        # Re-exec'd replica-sweep child (bench_serving_replicas): the
        # parent set JAX_PLATFORMS=cpu + a forced device count; repeat
        # the config update so a sitecustomize platform pin can't win
        # (the tests/conftest.py recipe).
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_bench_serving_replicas_impl()), flush=True)
        sys.exit(0)
    sys.exit(main())
