"""Throughput benchmark: XE train steps/sec/chip on MSR-VTT-shaped work.

Run on real TPU hardware (do NOT set JAX_PLATFORMS=cpu).  Prints ONE JSON
line: {"metric", "value", "unit", "vs_baseline"}.

Workload (driver config 2, BASELINE.json: "MSR-VTT, ResNet-152 + C3D
feats, XE-loss pretrain"): batch 64 videos x 20 captions/video, 28 frames,
resnet-2048 + c3d-4096 features, LSTM-512 decoder, T=30, bfloat16 compute.
The reference trains this single-GPU with a per-timestep Python loop;
BASELINE.json fixes no reference number ("published": {}), so
``vs_baseline`` is reported against the recorded value in BENCH_r1.json
once it exists (1.0 on the first round).
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def build_workload():
    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.models import model_from_config
    from cst_captioning_tpu.training.steps import (
        create_train_state,
        make_optimizer,
        make_xe_train_step,
    )

    from cst_captioning_tpu.parallel import (
        batch_sharding,
        make_mesh,
        shard_batch,
    )

    cfg = get_preset("msrvtt_resnet_c3d_xe")
    cfg.model.vocab_size = 10496  # MSR-VTT-scale vocab, multiple of 256
    if os.environ.get("BENCH_PALLAS", "1") == "1":
        cfg.model.use_pallas_lstm = True
    B, S, F, T = (
        cfg.data.batch_size,
        cfg.data.seq_per_img,
        cfg.data.max_frames,
        cfg.data.max_seq_len,
    )
    rng = np.random.RandomState(0)
    batch = {
        "feats": {
            "resnet": rng.randn(B, F, 2048).astype(np.float32),
            "c3d": rng.randn(B, F, 4096).astype(np.float32),
        },
        "feat_masks": {
            "resnet": np.ones((B, F), np.float32),
            "c3d": np.ones((B, F), np.float32),
        },
        "captions": rng.randint(
            4, cfg.model.vocab_size, size=(B, S, T + 2)
        ).astype(np.int32),
        "weights": np.ones((B, S), np.float32),
        "category": np.zeros((B,), np.int32),
        "video_idx": np.arange(B, dtype=np.int32),
    }
    batch["captions"][:, :, 0] = 1  # BOS
    model = model_from_config(cfg)
    tx = make_optimizer(cfg.train, steps_per_epoch=100)
    # Data-parallel mesh over ALL chips (single chip degenerates to a 1-way
    # mesh) so the per-chip number divides honest work, not idle chips.
    mesh = make_mesh({"data": -1, "model": 1})
    state = create_train_state(
        jax.random.PRNGKey(0), model, tx, batch, mesh=mesh
    )
    step = make_xe_train_step(model)
    sh = batch_sharding(mesh)
    args = (
        shard_batch(batch["feats"], mesh),
        shard_batch(batch["feat_masks"], mesh),
        jax.device_put(jnp.asarray(batch["captions"]), sh),
        jax.device_put(jnp.asarray(batch["weights"]), sh),
        None,
        jax.device_put(jnp.asarray(batch["video_idx"]), sh),
    )
    return state, step, args


def main() -> int:
    n_chips = max(1, len(jax.devices()))
    state, step, args = build_workload()

    # The per-step python dispatch crosses a (possibly tunneled) transport;
    # timing individual dispatches measures the tunnel, not the chip.  Run
    # CHUNK steps per dispatch under one jitted lax.scan and time that.
    chunk = int(os.environ.get("BENCH_CHUNK", "10"))
    iters = int(os.environ.get("BENCH_ITERS", "6"))

    import jax.numpy as jnp

    def run_chunk(state, rng, *op):
        def body(carry, k):
            st, _ = carry
            st, m = step(st, *op, k, 0.0)
            return (st, m["loss"]), None

        keys = jax.random.split(rng, chunk)
        (state, loss), _ = jax.lax.scan(body, (state, jnp.float32(0)), keys)
        return state, loss

    run_chunk = jax.jit(run_chunk, donate_argnums=(0,))

    # Warmup / compile.  float() forces a device->host transfer of the
    # result — block_until_ready alone can return early through the
    # remote-device transport.
    state, loss = run_chunk(state, jax.random.PRNGKey(7), *args)
    float(loss)

    rng = jax.random.PRNGKey(8)
    times = []
    for i in range(iters):
        rng, k = jax.random.split(rng)
        t0 = time.perf_counter()
        state, loss = run_chunk(state, k, *args)
        float(loss)
        times.append(time.perf_counter() - t0)
    # Median chunk time: robust to transport hiccups.
    dt = sorted(times)[len(times) // 2]
    steps_per_sec_chip = chunk / dt / n_chips

    prev = None
    for r in range(1, 10):
        p = f"BENCH_r{r}.json"
        if os.path.exists(p):
            try:
                with open(p) as f:
                    rec = json.load(f)
                if rec.get("unit") == "steps/sec/chip":
                    prev = float(rec["value"])
            except Exception:
                pass
    vs = steps_per_sec_chip / prev if prev else 1.0
    print(
        json.dumps(
            {
                "metric": "xe_train_throughput_msrvtt_resnet_c3d",
                "value": round(steps_per_sec_chip, 4),
                "unit": "steps/sec/chip",
                "vs_baseline": round(vs, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
