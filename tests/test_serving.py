"""Online caption-serving subsystem (cst_captioning_tpu/serving/).

Covers the ISSUE-2 acceptance bar plus the ISSUE-3 continuous
in-flight batching bar:
* micro-batcher coalescing / deadline / backpressure semantics (stub
  engine — no jax in the scheduler tests), and the same semantics for
  the continuous slot scheduler (stub slot decoder);
* two-tier cache eviction + hit accounting, including the tier-2 byte
  budget (eviction by bytes, counters on /metrics);
* served-vs-offline TOKEN PARITY: the engine's captions are exactly
  what ``evaluation.py`` produces for the same params/features — across
  ladder buckets, the tier-2 encoder-state fast path, AND the
  continuous slot loop (admission/eviction fuzz: random arrival order,
  greedy and beam, staggered admissions — admission order must not
  change any row's math);
* the offline beam early-exit wrapper's all-EOS parity;
* graceful shutdown: drain-to-completion, 503 on new work;
* an end-to-end in-process HTTP server test and a >= 8-concurrent-client
  smoke test with zero dropped non-expired requests and a /metrics
  queue/device latency split + cache hit rate.

NOTE on ordering: tests that drive ``engine.slot_decoder()`` directly
or via a private ContinuousBatcher must run BEFORE the module-scoped
``live_server`` fixture exists — the decoder is single-owner and the
live server's scheduler thread stays up until module teardown.  Tier-1
runs without test randomization (ROADMAP.md), so file order holds.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from cst_captioning_tpu.config import get_preset
from cst_captioning_tpu.serving.batcher import (
    BackpressureError,
    ContinuousBatcher,
    DeadlineExceededError,
    MicroBatcher,
    ShuttingDownError,
)
from cst_captioning_tpu.serving.cache import (
    LRUCache,
    TwoTierCache,
    content_key,
)
from cst_captioning_tpu.serving.engine import DecodedResult, PreparedRequest
from cst_captioning_tpu.serving.metrics import (
    Gauge,
    LatencyHistogram,
    ServingMetrics,
)


# ----------------------------------------------------------------- caches

class TestLRUCache:
    def test_eviction_is_lru(self):
        c = LRUCache(2)
        c.put("a", 1)
        c.put("b", 2)
        assert c.get("a") == 1       # refresh a
        c.put("c", 3)                # evicts b (least recent)
        assert c.get("b") is None
        assert c.get("a") == 1 and c.get("c") == 3
        assert len(c) == 2

    def test_hit_miss_counters(self):
        c = LRUCache(4)
        assert c.get("x") is None
        c.put("x", 1)
        assert c.get("x") == 1
        st = c.stats()
        assert st["hits"] == 1 and st["misses"] == 1
        assert st["hit_rate"] == 0.5

    def test_zero_capacity_never_stores(self):
        c = LRUCache(0)
        c.put("a", 1)
        assert c.get("a") is None and len(c) == 0

    def test_two_tier_stats(self):
        t = TwoTierCache(2, 2)
        t.captions.put("k", {"caption": "x"})
        t.captions.get("k")
        st = t.stats()
        assert st["captions"]["hits"] == 1
        assert st["features"]["misses"] == 0

    def test_content_key_sensitivity(self):
        f = {"resnet": np.ones((3, 4), np.float32)}
        k1 = content_key(f, "tag")
        assert k1 == content_key(
            {"resnet": np.ones((3, 4), np.float32)}, "tag"
        )
        f2 = {"resnet": np.ones((3, 4), np.float32)}
        f2["resnet"][0, 0] = 2.0
        assert content_key(f2, "tag") != k1       # content changes key
        assert content_key(f, "other-tag") != k1  # params tag changes key


class TestByteBudgetLRU:
    """Tier-2 is bounded by BYTES (projected encoder rows are the
    payload, entry counts lie about the working set)."""

    def _row(self, kb):
        return {"enc": np.zeros((kb, 256), np.float32)}  # kb KiB

    def test_byte_budget_evicts_lru_first(self):
        # Each row is 1KiB of numpy + 64B container overhead.
        c = LRUCache(capacity=100, max_bytes=3 * 1024 + 256)
        c.put("a", self._row(1))
        c.put("b", self._row(1))
        c.put("c", self._row(1))
        assert len(c) == 3
        assert c.get("a") is not None            # refresh a
        c.put("d", self._row(1))                 # busts budget -> evict b
        assert c.get("b") is None
        assert c.get("a") is not None and c.get("d") is not None
        st = c.stats()
        assert st["evictions"] == 1
        assert st["bytes"] <= st["max_bytes"]

    def test_oversized_entry_never_exceeds_budget(self):
        c = LRUCache(capacity=100, max_bytes=2 * 1024)
        c.put("big", self._row(8))               # alone exceeds budget
        assert c.get("big") is None
        assert c.stats()["bytes"] == 0
        assert c.stats()["evictions"] >= 1

    def test_replace_updates_byte_accounting(self):
        c = LRUCache(capacity=100, max_bytes=10 * 1024)
        c.put("k", self._row(4))
        b4 = c.stats()["bytes"]
        c.put("k", self._row(1))
        assert c.stats()["bytes"] < b4
        assert len(c) == 1

    def test_two_tier_wires_feature_byte_budget(self):
        t = TwoTierCache(4, 4, feature_max_bytes=1024)
        assert t.features.max_bytes == 1024
        assert t.captions.max_bytes == 0         # tier-1: strings
        t.features.put("f1", self._row(2))       # 2KiB > 1KiB budget
        st = t.stats()["features"]
        assert st["size"] == 0 and st["evictions"] == 1


# ---------------------------------------------------------------- metrics

class TestMetrics:
    def test_histogram_percentiles(self):
        h = LatencyHistogram()
        for ms in [1.0] * 90 + [400.0] * 10:
            h.observe(ms)
        assert h.percentile(50) <= 2.0
        assert h.percentile(99) > 100.0
        snap = h.snapshot()
        assert snap["count"] == 100 and snap["max_ms"] == 400.0

    def test_prometheus_render(self):
        m = ServingMetrics()
        m.requests_total.inc(3)
        m.observe_stage("queue", 1.5)
        m.observe_stage("device", 10.0)
        text = m.to_prometheus({"captions": {"hits": 2, "misses": 1}})
        assert "caption_requests_total 3" in text
        assert 'caption_latency_queue_ms_bucket{le="2.0"}' in text
        assert "caption_cache_captions_hits 2" in text

    def test_slot_metrics_render(self):
        m = ServingMetrics()
        m.slots_total.set(8)
        m.slots_occupied.set(3)
        m.slots_admitted_total.inc(5)
        m.steps_per_caption.observe(4)
        m.observe_stage("admission", 2.0)
        text = m.to_prometheus(
            {"features": {"evictions": 7, "bytes": 123}}
        )
        assert "caption_slots_total 8.0" in text
        assert "caption_slots_occupied 3.0" in text
        assert "caption_slots_admitted_total 5" in text
        assert "caption_steps_per_caption_count 1" in text
        assert "caption_latency_admission_ms_count 1" in text
        assert "caption_cache_features_evictions 7" in text
        assert "caption_cache_features_bytes 123" in text
        d = m.to_dict()
        assert d["slots"]["occupied"] == 3.0
        assert d["slots"]["steps_per_caption"]["count"] == 1

    def test_gauge(self):
        g = Gauge()
        assert g.value == 0.0
        g.set(2.5)
        assert g.value == 2.5


# ----------------------------------------------------- batcher (stub engine)

class _StubEngine:
    """Engine-shaped test double: records batch sizes, optionally holds
    decode until released (to pin queue states deterministically)."""

    def __init__(self, max_batch=4):
        self.cfg = get_preset("synthetic_smoke")
        self.max_batch = max_batch
        self.ladder = [1, 2, max_batch] if max_batch > 2 else [max_batch]
        self.cache = TwoTierCache(8, 8)
        self.batches = []
        self.entered = threading.Event()   # set when decode begins
        self.release = threading.Event()   # decode blocks until set
        self.release.set()                 # default: don't block

    def prepare(self, payload):
        return PreparedRequest(
            feats=None, masks=None, category=0, feature_id=None,
            cache_key=payload.get("key", ""), enc_row=None,
        )

    def lookup_caption(self, key):
        return self.cache.captions.get(key) if key else None

    def bucket(self, n):
        for b in self.ladder:
            if b >= n:
                return b
        raise ValueError(n)

    def decode_prepared(self, reqs, store=True):
        self.entered.set()
        self.release.wait(timeout=30.0)
        self.batches.append(len(reqs))
        t = {"pad_ms": 0.1, "device_ms": 1.0, "detok_ms": 0.1}
        return [
            DecodedResult(caption="stub", tokens=[2], timings_ms=t)
            for _ in reqs
        ]


class TestMicroBatcher:
    def test_coalesces_concurrent_requests_into_one_batch(self):
        eng = _StubEngine(max_batch=4)
        with MicroBatcher(eng, max_wait_ms=150.0) as b:
            threads = [
                threading.Thread(target=b.submit, args=({"key": ""},))
                for _ in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10.0)
        assert eng.batches == [4], eng.batches
        assert b.metrics.batches_total.value == 1
        assert b.metrics.requests_served.value == 4

    def test_full_batch_dispatches_before_wait_window(self):
        eng = _StubEngine(max_batch=2)
        with MicroBatcher(eng, max_wait_ms=10_000.0) as b:
            t0 = time.monotonic()
            threads = [
                threading.Thread(target=b.submit, args=({"key": ""},))
                for _ in range(2)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10.0)
            assert time.monotonic() - t0 < 5.0  # did not sit out 10s
        assert eng.batches == [2]

    def test_deadline_exceeded_while_queued(self):
        eng = _StubEngine(max_batch=1)
        eng.release.clear()  # hold the first decode
        errors = []
        with MicroBatcher(eng, max_wait_ms=0.0) as b:
            t1 = threading.Thread(target=b.submit, args=({"key": ""},))
            t1.start()
            assert eng.entered.wait(timeout=10.0)  # r1 is in decode

            def submit_r2():
                try:
                    b.submit({"key": ""}, deadline_ms=30.0)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            t2 = threading.Thread(target=submit_r2)
            t2.start()
            time.sleep(0.15)          # r2's 30ms deadline passes queued
            eng.release.set()
            t1.join(timeout=10.0)
            t2.join(timeout=10.0)
        assert len(errors) == 1 and isinstance(
            errors[0], DeadlineExceededError
        )
        assert b.metrics.requests_expired.value == 1
        assert eng.batches == [1]     # r2 never reached the engine

    def test_backpressure_rejects_when_queue_full(self):
        eng = _StubEngine(max_batch=1)
        eng.release.clear()
        results = []
        with MicroBatcher(eng, max_wait_ms=0.0, queue_depth=1) as b:
            t1 = threading.Thread(target=b.submit, args=({"key": ""},))
            t1.start()
            assert eng.entered.wait(timeout=10.0)  # r1 out of the queue

            def submit_r2():
                results.append(b.submit({"key": ""}))

            t2 = threading.Thread(target=submit_r2)
            t2.start()
            # Wait until r2 occupies the queue's single slot.
            for _ in range(100):
                if b.depth >= 1:
                    break
                time.sleep(0.01)
            assert b.depth == 1
            with pytest.raises(BackpressureError) as ei:
                b.submit({"key": ""})
            assert ei.value.retry_after_s > 0
            eng.release.set()
            t1.join(timeout=10.0)
            t2.join(timeout=10.0)
        # The ACCEPTED request was served despite the rejection of r3.
        assert results and results[0]["caption"] == "stub"
        assert b.metrics.requests_rejected.value == 1
        assert b.metrics.requests_expired.value == 0

    def test_tier1_hit_short_circuits_queue(self):
        eng = _StubEngine()
        eng.cache.captions.put("k1", {"caption": "hot", "tokens": [5, 2]})
        with MicroBatcher(eng) as b:
            out = b.submit({"key": "k1"})
        assert out["cached"] is True and out["caption"] == "hot"
        assert eng.batches == []      # never dispatched

    def test_graceful_drain_serves_queued_then_rejects(self):
        """Satellite: shutdown stops admissions (-> 503 upstream) but
        drains accepted work to completion."""
        eng = _StubEngine(max_batch=1)
        eng.release.clear()            # hold the in-flight decode
        results = []
        b = MicroBatcher(eng, max_wait_ms=0.0).start()
        t1 = threading.Thread(
            target=lambda: results.append(b.submit({"key": ""}))
        )
        t1.start()
        assert eng.entered.wait(timeout=10.0)   # r1 is in decode
        t2 = threading.Thread(
            target=lambda: results.append(b.submit({"key": ""}))
        )
        t2.start()
        for _ in range(100):                    # r2 occupies the queue
            if b.depth >= 1:
                break
            time.sleep(0.01)
        b.begin_drain()
        with pytest.raises(ShuttingDownError):  # admissions closed
            b.submit({"key": ""})
        eng.release.set()                       # let decodes finish
        stopper = threading.Thread(target=b.stop)
        stopper.start()
        t1.join(timeout=10.0)
        t2.join(timeout=10.0)
        stopper.join(timeout=10.0)
        # BOTH accepted requests were served despite the shutdown.
        assert len(results) == 2
        assert all(r["caption"] == "stub" for r in results)
        assert b.metrics.requests_served.value == 2


# ------------------------------------- continuous scheduler (stub slots)

class _StubSlotDecoder:
    """SlotDecoder-shaped double: each prepared request carries a step
    budget; tick() decrements, done at zero.  Lets the scheduler tests
    pin admission/deadline/drain semantics without jax."""

    def __init__(self, S=2, block=1):
        self.S, self.K, self.L, self.block = S, 1, 10, block
        self.admit_cap = S
        self.free = list(range(S))
        self.occupied = {}
        self.steps_paid = {}
        self._remaining = {}
        self.resize_count = 0

    @property
    def n_occupied(self):
        return len(self.occupied)

    def maybe_resize(self, pending=0):
        return self.S

    def live_state_bytes(self):
        return 64 * self.n_occupied

    def tick(self, prepared=(), datas=()):
        for req, data in zip(prepared, datas):
            slot = self.free.pop()
            assert slot not in self.occupied, "double-assigned"
            self.occupied[slot] = data
            self.steps_paid[slot] = 0
            self._remaining[slot] = req.category  # step budget rides here
        if not self.occupied:
            return []
        time.sleep(0.001)                        # a "device step"
        for s in self.occupied:
            self.steps_paid[s] += self.block
            self._remaining[s] -= self.block
        return [s for s in self.occupied if self._remaining[s] <= 0]

    def harvest_many(self, slots):
        out = []
        for s in slots:
            data = self.occupied.pop(s)
            steps = self.steps_paid.pop(s)
            self._remaining.pop(s)
            self.free.append(s)
            out.append((data, np.asarray([5, 2], np.int32), 0.0, steps))
        return out

    def evict(self, slot):
        data = self.occupied.pop(slot)
        self.steps_paid.pop(slot, None)
        self._remaining.pop(slot, None)
        self.free.append(slot)
        return data


class _StubSlotEngine(_StubEngine):
    def __init__(self, S=2, steps_by_key=None):
        super().__init__(max_batch=S)
        self._decoder = _StubSlotDecoder(S=S)
        self.steps_by_key = steps_by_key or {}

    def prepare(self, payload):
        # Step budget smuggled through the `category` field.
        return PreparedRequest(
            feats=None, masks=None,
            category=int(payload.get("steps", 3)),
            feature_id=None, cache_key=payload.get("key", ""),
            enc_row=None,
        )

    def slot_decoder(self):
        return self._decoder

    def result_from_tokens(self, req, tokens, timings_ms, store=True):
        if store and req.cache_key:
            self.cache.captions.put(
                req.cache_key,
                {"caption": "slot-stub", "tokens": [int(t) for t in tokens]},
            )
        return DecodedResult(
            caption="slot-stub",
            tokens=[int(t) for t in tokens],
            timings_ms=timings_ms,
        )


class TestContinuousScheduler:
    def test_short_caption_overtakes_long(self):
        """The headline behavior: a short request admitted into a free
        slot finishes while a longer one is still decoding — no
        batch-boundary head-of-line blocking."""
        eng = _StubSlotEngine(S=2)
        order = []
        lock = threading.Lock()
        with ContinuousBatcher(eng) as b:
            def go(name, steps):
                b.submit({"steps": steps})
                with lock:
                    order.append(name)

            t_long = threading.Thread(target=go, args=("long", 40))
            t_long.start()
            time.sleep(0.02)                    # long is mid-decode
            t_short = threading.Thread(target=go, args=("short", 1))
            t_short.start()
            t_short.join(timeout=10.0)
            t_long.join(timeout=10.0)
        assert order == ["short", "long"]
        m = b.metrics
        assert m.requests_served.value == 2
        assert m.slots_admitted_total.value == 2
        # steps-per-caption histogram saw one short and one long decode.
        snap = m.steps_per_caption.snapshot()
        assert snap["count"] == 2 and snap["max_ms"] >= 40

    def test_deadline_expires_while_awaiting_slot(self):
        eng = _StubSlotEngine(S=1)
        errors = []
        with ContinuousBatcher(eng) as b:
            t1 = threading.Thread(
                target=lambda: b.submit({"steps": 200})
            )
            t1.start()
            time.sleep(0.05)                    # slot occupied

            def submit_r2():
                try:
                    b.submit({"steps": 1}, deadline_ms=30.0)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            t2 = threading.Thread(target=submit_r2)
            t2.start()
            t2.join(timeout=10.0)
            t1.join(timeout=10.0)
        assert len(errors) == 1
        assert isinstance(errors[0], DeadlineExceededError)
        assert b.metrics.requests_expired.value == 1

    def test_drain_completes_inflight_and_rejects_new(self):
        eng = _StubSlotEngine(S=1)
        results = []
        b = ContinuousBatcher(eng).start()
        t1 = threading.Thread(
            target=lambda: results.append(b.submit({"steps": 50}))
        )
        t1.start()
        time.sleep(0.02)                        # in a slot now
        b.begin_drain()
        with pytest.raises(ShuttingDownError):
            b.submit({"steps": 1})
        b.stop()                                # drains to completion
        t1.join(timeout=10.0)
        assert len(results) == 1
        assert results[0]["caption"] == "slot-stub"
        assert b.metrics.requests_failed.value == 0
        assert not eng._decoder.occupied
        assert sorted(eng._decoder.free) == [0]

    def test_hard_stop_abandons_inflight(self):
        eng = _StubSlotEngine(S=1)
        errors = []

        def submit():
            try:
                b.submit({"steps": 10_000})
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        b = ContinuousBatcher(eng).start()
        t1 = threading.Thread(target=submit)
        t1.start()
        time.sleep(0.02)
        b.stop(drain=False)
        t1.join(timeout=10.0)
        assert len(errors) == 1 and isinstance(errors[0], RuntimeError)
        assert not eng._decoder.occupied        # slot freed on abandon


# ------------------------------------------------- engine parity (real jax)

@pytest.fixture(scope="module")
def served_world():
    """Shared tiny engine + dataset + OFFLINE predictions (compiles the
    decode graphs once for the whole module)."""
    from cst_captioning_tpu.data.build import build_dataset
    from cst_captioning_tpu.evaluation import beam_decode_dataset
    from cst_captioning_tpu.serving.engine import InferenceEngine

    cfg = get_preset("synthetic_smoke")
    cfg.serving.warmup = False          # compile lazily, tests are tiny
    cfg.serving.default_deadline_ms = 120_000.0  # compiles != expiries
    cfg.serving.max_wait_ms = 10.0
    ds, vocab = build_dataset(cfg, cfg.eval.eval_split)
    cfg.model.vocab_size = len(vocab)
    engine = InferenceEngine(cfg, random_init=True, vocab=vocab)
    offline = beam_decode_dataset(engine.model, engine.params, ds, cfg)
    payloads = [
        {
            "features": {m: a.tolist() for m, a in ds.features(i).items()},
            "feature_id": f"fid{i}",
        }
        for i in range(len(ds))
    ]
    return engine, ds, offline, payloads


class TestEngineParity:
    def test_served_tokens_match_offline_eval_across_buckets(
        self, served_world
    ):
        """THE serving correctness bar: token-exact vs evaluation.py for
        the same params/features, at every ladder bucket (1->2, 3->4,
        8->8) including padded batches."""
        engine, ds, offline, payloads = served_world
        chunks = [(0, 1), (1, 3), (4, 8), (12, 4)]
        for start, size in chunks:
            reqs = [
                engine.prepare(payloads[i])
                for i in range(start, start + size)
            ]
            results = engine.decode_prepared(reqs)
            for i, res in zip(range(start, start + size), results):
                assert res.caption == offline[ds.video_id(i)], (
                    f"video {i} bucket {engine.bucket(size)}"
                )

    def test_feature_cache_state_path_is_token_exact(self, served_world):
        """Tier-2: a feature_id-only re-request decodes from the cached
        projected encoder state (beam_search_from_state) and must
        produce the identical caption."""
        engine, ds, offline, payloads = served_world
        # First pass stored enc rows (test above ran full coverage);
        # re-request by id only.
        reqs = [
            engine.prepare({"feature_id": f"fid{i}"}) for i in range(8)
        ]
        assert all(r.enc_row is not None for r in reqs)
        results = engine.decode_prepared(reqs)
        for i, res in enumerate(results):
            assert res.caption == offline[ds.video_id(i)]
        assert engine.cache.features.stats()["hits"] > 0

    def test_caption_cache_roundtrip(self, served_world):
        engine, ds, offline, payloads = served_world
        req = engine.prepare(payloads[0])
        hit = engine.lookup_caption(req.cache_key)
        assert hit is not None and hit["caption"] == offline[ds.video_id(0)]

    def test_unknown_feature_id_raises(self, served_world):
        engine, *_ = served_world
        with pytest.raises(KeyError):
            engine.prepare({"feature_id": "never-seen"})

    def test_bad_features_rejected(self, served_world):
        engine, *_ = served_world
        with pytest.raises(ValueError):
            engine.prepare({"features": {"resnet": [[1.0, 2.0]]}})  # dim
        with pytest.raises(ValueError):
            engine.prepare({})


# --------------------------- continuous slot loop (real jax, ISSUE 3)

class TestContinuousParity:
    """Slot-decoded captions are TOKEN-EXACT vs the offline
    ``evaluation.py`` path — under fuzzed admission order, staggered
    in-flight admissions, and for both decode modes.  (Runs before the
    ``live_server`` fixture per the module-docstring ordering note.)"""

    def test_slot_fuzz_beam_parity_random_arrival(self, served_world):
        """Admission/eviction fuzz: 16 requests (incl. feature_id
        repeats) arrive in random order with jitter into a 4-slot
        continuous batcher; every caption must match the offline beam
        decode, nothing may drop, and the slot matrix must end clean
        (no double assignment — the decoder hard-raises on it)."""
        engine, ds, offline, payloads = served_world
        # Earlier parity tests populated tier 1 for these payloads; a
        # hit would bypass the slot loop entirely.
        engine.cache.captions.clear()
        rng = np.random.RandomState(31)
        idx = list(rng.permutation(16))
        results: dict = {}
        errors = []
        lock = threading.Lock()

        def client(i):
            time.sleep(float(rng.rand()) * 0.05)  # jittered arrival
            try:
                out = b.submit(
                    dict(payloads[i]), deadline_ms=120_000.0
                )
                with lock:
                    results[i] = out
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append((i, repr(e)))

        with ContinuousBatcher(engine) as b:
            threads = [
                threading.Thread(target=client, args=(i,)) for i in idx
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120.0)
        assert not errors, errors
        assert len(results) == 16
        for i in range(16):
            assert results[i]["caption"] == offline[ds.video_id(i)], (
                f"video {i}: slot loop diverged from offline beam"
            )
        decoder = engine.slot_decoder()
        assert not decoder.occupied
        assert sorted(decoder.free) == list(range(decoder.S))
        assert b.metrics.requests_expired.value == 0
        assert b.metrics.requests_failed.value == 0
        assert b.metrics.steps_per_caption.snapshot()["count"] > 0

    # The direct staggered-admission row-exactness drive moved to the
    # SHARED parity harness (tests/test_decode_core.py,
    # "slot_decoder_beam"/"slot_decoder_greedy" backends — same staggered
    # admit pattern, pinned token-exact vs the scan references).


@pytest.fixture(scope="module")
def greedy_world(served_world):
    """A greedy-mode engine over the SAME params + its offline greedy
    predictions (the validation decode path)."""
    from cst_captioning_tpu.evaluation import decode_dataset
    from cst_captioning_tpu.serving.engine import InferenceEngine
    from cst_captioning_tpu.training.steps import make_greedy_sample_fn

    engine, ds, _, payloads = served_world
    cfg = get_preset("synthetic_smoke")
    cfg.serving.warmup = False
    cfg.serving.decode_mode = "greedy"
    cfg.model.vocab_size = len(engine.vocab)
    geng = InferenceEngine(
        cfg, params=engine.params, vocab=engine.vocab
    )
    gfn = make_greedy_sample_fn(geng.model, cfg.eval.max_decode_len)
    offline = decode_dataset(
        ds, cfg, lambda f, m, c: gfn(geng.params, f, m, c),
        geng.model.use_category,
    )
    return geng, ds, offline, payloads


class TestContinuousGreedyParity:
    def test_slot_fuzz_greedy_parity(self, greedy_world):
        """The greedy half of the mixed-mode fuzz bar: slot-decoded
        greedy captions are token-exact vs the offline greedy sampler
        under randomized concurrent arrival."""
        geng, ds, offline, payloads = greedy_world
        rng = np.random.RandomState(7)
        idx = list(rng.permutation(10))
        results: dict = {}
        errors = []
        lock = threading.Lock()

        def client(i):
            time.sleep(float(rng.rand()) * 0.03)
            try:
                out = b.submit(dict(payloads[i]), deadline_ms=120_000.0)
                with lock:
                    results[i] = out
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append((i, repr(e)))

        with ContinuousBatcher(geng) as b:
            threads = [
                threading.Thread(target=client, args=(i,)) for i in idx
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120.0)
        assert not errors, errors
        for i in range(10):
            assert results[i]["caption"] == offline[ds.video_id(i)], (
                f"video {i}: greedy slot loop diverged"
            )
        decoder = geng.slot_decoder()
        assert not decoder.occupied
        assert sorted(decoder.free) == list(range(decoder.S))


# ----------------------- decode-state memory (dedup + elastic, ISSUE 7)

@pytest.fixture(scope="module")
def mem_world():
    """Two engines over the SAME params — deduped (default) and legacy
    replicated decode-state layouts — on a cache-dominant shape (more
    frames than the smoke preset, the MSR-VTT regime where the
    projected cache is most of a slot's bytes)."""
    from cst_captioning_tpu.data.build import build_dataset
    from cst_captioning_tpu.evaluation import beam_decode_dataset
    from cst_captioning_tpu.serving.engine import InferenceEngine

    cfg = get_preset("synthetic_smoke")
    cfg.serving.warmup = False
    cfg.data.max_frames = 20
    cfg.serving.num_slots = 4
    cfg.serving.slot_block_steps = 1
    ds, vocab = build_dataset(cfg, cfg.eval.eval_split)
    cfg.model.vocab_size = len(vocab)
    dd = InferenceEngine(cfg, random_init=True, vocab=vocab)
    rr = InferenceEngine(
        cfg.replace(**{"serving.dedup_cache": False}),
        params=dd.params, vocab=vocab,
    )
    offline = beam_decode_dataset(dd.model, dd.params, ds, cfg)
    payloads = [
        {
            "features": {m: a.tolist() for m, a in ds.features(i).items()},
            "feature_id": f"mem{i}",
        }
        for i in range(8)
    ]
    return dd, rr, ds, offline, payloads


def _drive_staggered(engine, reqs, datas):
    """Decode ``reqs`` through the engine's slot decoder with staggered
    admissions; returns {data: (tokens, steps)}."""
    dec = engine.slot_decoder()
    got = {}
    pending = list(zip(reqs, datas))
    stagger = 0
    while pending or dec.occupied:
        dec.maybe_resize(len(pending))
        n = min(1 + stagger % 2, len(pending), len(dec.free),
                dec.admit_cap)
        batch = [pending.pop(0) for _ in range(n)]
        stagger += 1
        done = dec.tick([r for r, _ in batch], [d for _, d in batch])
        for d, tokens, score, steps in dec.harvest_many(done):
            got[d] = (tokens, steps)
    return got


class TestDecodeStateMemory:
    def test_state_bytes_formula_machine_checked(self, mem_world):
        """THE memory bar: measured pytree bytes equal the closed-form
        shape formula EXACTLY for both layouts; the dedup collapses the
        cache component exactly K x, leaves the carry untouched, and
        cuts bytes per in-flight request >= 0.8*K x on a cache-dominant
        shape.  A layout regression (an accidental re-replication, a
        new per-row leaf) fails tier-1 here."""
        dd, rr, *_ = mem_world
        dec_d, dec_r = dd.slot_decoder(), rr.slot_decoder()
        K = dec_d.K
        assert K > 1  # beam mode, or the dedup is vacuous
        for dec in (dec_d, dec_r):
            assert dec.state_bytes() == dec.expected_state_bytes()
        assert dec_r.cache_bytes() == K * dec_d.cache_bytes()
        assert dec_r.carry_bytes() == dec_d.carry_bytes()
        ratio = dec_r.per_slot_bytes() / dec_d.per_slot_bytes()
        assert ratio >= 0.8 * K, (
            f"per-request bytes dropped only {ratio:.2f}x "
            f"(bar: {0.8 * K:.1f}x for K={K})"
        )

    def test_layouts_serve_identical_captions_matching_offline(
        self, mem_world
    ):
        """Both layouts, same staggered admission schedule: tokens are
        identical to each other AND to the offline eval decode — the
        shared-copy read cannot change any caption."""
        from cst_captioning_tpu.data.vocab import decode_sequence

        dd, rr, ds, offline, payloads = mem_world
        for eng in (dd, rr):
            reqs = [eng.prepare(dict(p)) for p in payloads]
            got = _drive_staggered(eng, reqs, list(range(len(reqs))))
            assert sorted(got) == list(range(len(payloads)))
            for i, (tokens, steps) in got.items():
                caption = decode_sequence(eng.vocab, tokens[None])[0]
                assert caption == offline[ds.video_id(i)], (
                    f"video {i} diverged under "
                    f"{'dedup' if eng is dd else 'replicated'} layout"
                )
                assert 0 < steps <= eng.slot_decoder().L

    def test_freed_slots_zero_rows_and_live_bytes_are_honest(
        self, mem_world
    ):
        """Zero-on-free: while slots are occupied the live-byte gauge
        is per-slot bytes x occupancy; at free time the slots' cache
        AND carry rows are blanked to the empty pattern.  (Freed CACHE
        rows stay zero forever — they are read-only; freed h/c rows
        are step scratch the next tick recomputes for the whole
        matrix, so they are asserted right after the freeing harvest,
        before any further tick.)"""
        import jax

        from cst_captioning_tpu.constants import PAD_ID

        dd, _, ds, offline, payloads = mem_world
        dec = dd.slot_decoder()
        reqs = [dd.prepare(dict(p)) for p in payloads[:3]]
        done = dec.tick(reqs, [0, 1, 2])
        assert dec.n_occupied == 3
        assert dec.live_state_bytes() == 3 * dec.per_slot_bytes()
        # Step (without harvesting) until all three finish, then free
        # them in ONE harvest so no later tick re-steps the zeroed rows.
        while len(done) < 3:
            done = dec.tick()
        dec.harvest_many(done)
        assert dec.n_occupied == 0
        assert dec.live_state_bytes() == 0
        for leaf in jax.tree.leaves(dec._st.cache):
            assert (np.asarray(leaf) == 0).all(), "stale cache rows"
        nK = 3 * dec.K                    # rows of the 3 freed slots
        assert (np.asarray(dec._st.core.state.h)[:, :nK] == 0).all()
        assert (np.asarray(dec._st.core.state.c)[:, :nK] == 0).all()
        assert (np.asarray(dec._st.core.seqs) == PAD_ID).all()
        assert bool(np.asarray(dec._st.core.finished).all())

    def test_cache_hit_admission_skips_encoder(self, mem_world):
        """Tier-2 zero-recompute admission: rows that carry cached
        encoder state never touch ``init_decode`` — pure hits encode
        nothing, mixed batches encode ONLY the misses — and the mixed
        batch still serves offline-exact captions."""
        from cst_captioning_tpu.data.vocab import decode_sequence

        dd, _, ds, offline, payloads = mem_world
        # The parity test above stored tier-2 rows for these ids.
        hits = [dd.prepare({"feature_id": f"mem{i}"}) for i in (0, 1)]
        assert all(r.enc_row is not None for r in hits)
        e0 = dd.admit_rows_encoded
        dd.encode_prepared_rows(hits)
        assert dd.admit_rows_encoded == e0  # zero encoder recompute
        assert dd.admit_rows_cached >= 2
        # Mixed batch: a hit plus a never-seen request.
        fresh = dd.prepare({
            "features": payloads[2]["features"], "feature_id": None,
        })
        fresh = fresh._replace(enc_row=None)
        e0 = dd.admit_rows_encoded
        got = _drive_staggered(dd, [hits[0], fresh], ["hit", "miss"])
        assert dd.admit_rows_encoded - e0 >= 1  # the miss paid
        caption = decode_sequence(dd.vocab, got["hit"][0][None])[0]
        assert caption == offline[ds.video_id(0)]
        caption = decode_sequence(dd.vocab, got["miss"][0][None])[0]
        assert caption == offline[ds.video_id(2)]


class TestElasticSlotBanks:
    @pytest.fixture(scope="class")
    def elastic_world(self, mem_world):
        """An elastic-bank engine (ladder 2 -> 4 -> 8) over mem_world's
        params, fully warmed so every tick variant and transition is
        compiled."""
        from cst_captioning_tpu.serving.engine import InferenceEngine

        dd, _, ds, offline, payloads = mem_world
        cfg = dd.cfg.replace(**{
            "serving.num_slots": 8,
            "serving.max_batch_size": 8,
            "serving.batch_shapes": [],
            "serving.slot_bank_min": 2,
            "serving.slot_shrink_idle_ticks": 3,
        })
        eng = InferenceEngine(cfg, params=dd.params, vocab=dd.vocab)
        dec = eng.slot_decoder()
        dec.warmup()
        return eng, dec, ds, offline, payloads

    def test_warmup_ends_small_and_ladder_is_complete(
        self, elastic_world
    ):
        eng, dec, *_ = elastic_world
        assert dec.bank_ladder == [2, 4, 8]
        assert dec.S == 2                      # capacity follows traffic
        assert sorted(dec.free) == list(range(dec.S))
        d = dec.describe()
        assert d["bank_ladder"] == [2, 4, 8]
        assert d["dedup_cache"] is True

    def test_regrow_at_tick_boundary_is_prejitted_ladder_hit(
        self, elastic_world
    ):
        """THE no-cold-retrace pin: after warmup, growing under queue
        pressure and shrinking when idle — with real traffic decoded at
        every bank — builds ZERO new compiled variants, and the bank
        follows pressure both ways."""
        eng, dec, ds, offline, payloads = elastic_world
        compiles = dec.compile_count
        reqs = [eng.prepare(dict(p)) for p in payloads]
        got = _drive_staggered(eng, reqs, list(range(len(reqs))))
        assert len(got) == len(payloads)
        # Pressure beyond the current bank grows it (several rungs).
        dec.maybe_resize(pending=7)
        assert dec.S == 8
        assert sorted(dec.free) == list(range(8))
        # Idle ticks walk it back down one rung per streak.
        for _ in range(dec.shrink_after * 4):
            dec.maybe_resize(0)
        assert dec.S == 2
        assert dec.resize_count >= 3
        assert dec.compile_count == compiles, (
            "bank transition retraced — the ladder must be fully "
            "compiled at warmup"
        )

    def test_fuzzed_admit_evict_regrow_no_double_assign(
        self, elastic_world
    ):
        """Randomized admission / eviction / resize sequences across
        bank transitions: the free list and occupancy stay an exact
        partition of the current bank, nothing double-assigns (the
        decoder hard-raises), and the world drains clean."""
        eng, dec, ds, offline, payloads = elastic_world
        rng = np.random.RandomState(5)
        reqs = [eng.prepare(dict(p)) for p in payloads]
        grew = shrank = 0
        serial = 0
        for it in range(60):
            # Burst, then sustained load, then quiet: decodes ride ~L
            # ticks, so occupancy climbs through the burst phase (grow)
            # and drains in the quiet tail (shrink) within one run.
            busy = it < 25
            pending = 8 if it == 0 else (
                int(rng.randint(0, 3)) if busy else 0
            )
            s0 = dec.S
            dec.maybe_resize(pending)
            grew += dec.S > s0
            shrank += dec.S < s0
            n = min(
                int(rng.randint(0, 3)) if busy else 0,
                len(dec.free), dec.admit_cap,
            )
            adm = [reqs[int(rng.randint(0, len(reqs)))] for _ in range(n)]
            done = dec.tick(adm, [f"r{serial + j}" for j in range(n)])
            serial += n
            if done and rng.rand() < 0.3:
                dec.evict(done[0])
                done = done[1:]
            dec.harvest_many(done)
            occ = set(dec.occupied)
            free = set(dec.free)
            assert not (occ & free)
            assert occ | free == set(range(dec.S)), (
                it, sorted(occ), sorted(free), dec.S
            )
        dec.drain()
        for _ in range(dec.shrink_after * 4):
            dec.maybe_resize(0)
        assert grew >= 1 and shrank >= 1
        assert not dec.occupied
        assert sorted(dec.free) == list(range(dec.S))

    def test_chaos_queue_burst_during_regrow_never_drops(
        self, elastic_world
    ):
        """ISSUE 11 satellite (extends the fuzz above): chaos-injected
        admission bursts hammer the pressure signal while real traffic
        decodes across bank transitions — grows fire mid-traffic, the
        free/occupied partition stays exact, nothing drops or
        double-assigns (the decoder hard-raises), and every caption is
        still token-exact vs the offline beam decode."""
        from cst_captioning_tpu.data.vocab import decode_sequence
        from cst_captioning_tpu.serving.chaos import ChaosEngine

        eng, dec, ds, offline, payloads = elastic_world
        ce = ChaosEngine(seed=7, schedule=[
            {"site": "queue_burst", "every": 2, "value": 6},
        ])
        reqs = [eng.prepare(dict(p)) for p in payloads]
        pending = list(zip(reqs, range(len(reqs))))
        got = {}
        grew = 0
        while pending or dec.occupied:
            b = ce.fire("queue_burst")
            burst = int(b) if b else 0
            s0 = dec.S
            dec.maybe_resize(len(pending) + burst)
            grew += dec.S > s0
            occ, free = set(dec.occupied), set(dec.free)
            assert not (occ & free)
            assert occ | free == set(range(dec.S))
            n = min(1, len(pending), len(dec.free), dec.admit_cap)
            batch = [pending.pop(0) for _ in range(n)]
            done = dec.tick(
                [r for r, _ in batch], [d for _, d in batch]
            )
            for d, tokens, _score, _steps in dec.harvest_many(done):
                got[d] = tokens
        assert grew >= 1 and ce.fired >= 1
        assert sorted(got) == list(range(len(payloads)))
        for i, tokens in got.items():
            assert (
                decode_sequence(eng.vocab, tokens[None])[0]
                == offline[ds.video_id(i)]
            ), f"video {i} diverged under chaos-burst regrow"
        # Walk the bank back down so later tests see the idle state.
        for _ in range(dec.shrink_after * 4):
            dec.maybe_resize(0)


class TestBeamEarlyExit:
    """The offline scan beam's all-rows-finished early exit
    (decoding/beam.py) is output-identical to the full fixed-length
    scan — including when EVERY caption ends immediately (EOS-biased
    params, the case the exit actually fires on)."""

    def _compare(self, engine, ds, params, n=6):
        from cst_captioning_tpu.decoding.beam import (
            beam_search_from_state,
        )

        cfg = engine.cfg
        reqs = [
            engine.prepare({
                "features": {
                    m: a.tolist() for m, a in ds.features(i).items()
                }
            })
            for i in range(n)
        ]
        feats = {
            m: np.stack([r.feats[m] for r in reqs])
            for m in cfg.data.feature_modalities
        }
        masks = {
            m: np.stack([r.masks[m] for r in reqs])
            for m in cfg.data.feature_modalities
        }
        state, cache = engine.model.apply(
            params, feats, masks, None, method="init_decode"
        )
        kw = dict(
            beam_size=cfg.eval.beam_size,
            max_len=cfg.eval.max_decode_len,
            length_normalize=cfg.eval.length_normalize,
        )
        fast = beam_search_from_state(
            engine.model, params, state, cache, early_exit=True, **kw
        )
        full = beam_search_from_state(
            engine.model, params, state, cache, early_exit=False, **kw
        )
        np.testing.assert_array_equal(
            np.asarray(fast.tokens), np.asarray(full.tokens)
        )
        np.testing.assert_array_equal(
            np.asarray(fast.all_tokens), np.asarray(full.all_tokens)
        )
        np.testing.assert_allclose(
            np.asarray(fast.all_scores), np.asarray(full.all_scores),
            rtol=0, atol=0,
        )
        return fast

    def test_early_exit_parity_natural_lengths(self, served_world):
        engine, ds, *_ = served_world
        self._compare(engine, ds, engine.params)

    def test_early_exit_parity_all_eos_immediately(self, served_world):
        """EOS-biased params: every beam of every row finishes within a
        couple of steps, the while_loop exits early, and the outputs
        still match the full scan bit-for-bit."""
        import jax.numpy as jnp

        from cst_captioning_tpu.constants import EOS_ID, PAD_ID

        engine, ds, *_ = served_world
        p = dict(engine.params)
        pp = dict(p["params"])
        b = np.asarray(pp["logit_b"]).copy()
        b[EOS_ID] += 50.0               # EOS dominates from step one
        pp["logit_b"] = jnp.asarray(b)
        p["params"] = pp
        res = self._compare(engine, ds, p)
        toks = np.asarray(res.tokens)
        # The decode really did collapse to immediate EOS...
        assert (toks[:, 0] == EOS_ID).all()
        assert (toks[:, 1:] == PAD_ID).all()


# ----------------------------------------------------- HTTP server e2e

def _post(url, obj, timeout=120.0):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(url, timeout=30.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


@pytest.fixture(scope="module")
def live_server(served_world):
    from cst_captioning_tpu.serving.server import CaptionServer

    engine, ds, offline, payloads = served_world
    with CaptionServer(engine, host="127.0.0.1", port=0) as srv:
        yield srv, engine, ds, offline, payloads


class TestHTTPServer:
    def test_healthz(self, live_server):
        srv, *_ = live_server
        status, body = _get(srv.url + "/healthz")
        assert status == 200
        info = json.loads(body)
        assert info["status"] == "ok" and info["decode_mode"] == "beam"

    def test_served_caption_matches_offline(self, live_server):
        srv, engine, ds, offline, payloads = live_server
        status, out = _post(srv.url + "/v1/caption", payloads[5])
        assert status == 200
        assert out["caption"] == offline[ds.video_id(5)]
        assert "timings_ms" in out

    def test_repeat_request_hits_cache(self, live_server):
        srv, engine, ds, offline, payloads = live_server
        _post(srv.url + "/v1/caption", payloads[6])
        status, out = _post(srv.url + "/v1/caption", payloads[6])
        assert status == 200 and out["cached"] is True
        assert out["caption"] == offline[ds.video_id(6)]

    def test_bad_body_is_400(self, live_server):
        srv, *_ = live_server
        req = urllib.request.Request(
            srv.url + "/v1/caption", data=b"not json",
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30.0)
        assert ei.value.code == 400

    def test_unknown_feature_id_is_404(self, live_server):
        srv, *_ = live_server
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.url + "/v1/caption", {"feature_id": "ghost"})
        assert ei.value.code == 404

    def test_stats_and_metrics_endpoints(self, live_server):
        srv, *_ = live_server
        status, body = _get(srv.url + "/stats")
        assert status == 200
        stats = json.loads(body)
        assert {"queue", "device", "total"} <= set(stats["latency_ms"])
        assert "captions" in stats["cache"]
        status, text = _get(srv.url + "/metrics")
        assert status == 200
        assert "caption_latency_queue_ms_bucket" in text
        assert "caption_latency_device_ms_bucket" in text
        assert "caption_cache_captions_hits" in text


class TestConcurrentClients:
    def test_eight_clients_zero_drops(self, live_server):
        """Acceptance criterion: >= 8 concurrent clients through the
        micro-batcher with zero dropped non-expired requests, and
        /metrics reporting the queue/device split + cache hit rate."""
        srv, engine, ds, offline, payloads = live_server
        n_clients, per_client = 8, 4
        failures, served = [], []
        lock = threading.Lock()

        def client(cid):
            rng = np.random.RandomState(cid)
            for _ in range(per_client):
                i = int(rng.randint(0, 10))
                body = dict(payloads[i])
                body["deadline_ms"] = 120_000.0
                try:
                    status, out = _post(srv.url + "/v1/caption", body)
                    assert status == 200
                    assert out["caption"] == offline[ds.video_id(i)]
                    with lock:
                        served.append(i)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        failures.append(f"client{cid}: {e}")

        threads = [
            threading.Thread(target=client, args=(c,))
            for c in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300.0)
        assert not failures, failures
        assert len(served) == n_clients * per_client
        m = srv.metrics
        assert m.requests_expired.value == 0
        assert m.requests_failed.value == 0
        assert m.requests_rejected.value == 0
        # The latency split and cache hit rate are live on /metrics.
        _, text = _get(srv.url + "/metrics")
        assert "caption_latency_queue_ms_count" in text
        assert "caption_latency_device_ms_count" in text
        assert engine.cache.stats()["captions"]["hits"] > 0
        # Continuous-mode observability: slots + admission latency are
        # live too (live_server runs the slot scheduler by default).
        assert "caption_slots_total 4.0" in text
        assert "caption_slots_admitted_total" in text
        assert "caption_steps_per_caption_count" in text


# ------------------------------------- shutdown + ladder fallback (HTTP)

class TestServerLifecycle:
    def test_draining_server_503s_new_requests(self, served_world):
        """Satellite: graceful shutdown closes the front door (503)
        while the listener stays up, then exits clean."""
        from cst_captioning_tpu.serving.server import CaptionServer

        engine, ds, offline, payloads = served_world
        metrics = ServingMetrics()
        srv = CaptionServer(
            engine, host="127.0.0.1", port=0, metrics=metrics,
            batcher=MicroBatcher(engine, metrics),
        ).start()
        try:
            status, out = _post(srv.url + "/v1/caption", payloads[2])
            assert status == 200
            srv.begin_drain()
            status, body = _get(srv.url + "/healthz")
            assert json.loads(body)["status"] == "draining"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(srv.url + "/v1/caption", payloads[3])
            assert ei.value.code == 503
        finally:
            srv.shutdown()
        # Idempotent second shutdown must not raise.
        srv.shutdown()

    def test_ladder_fallback_server_serves_parity(self, served_world):
        """serving.continuous=false path stays wired end to end."""
        from cst_captioning_tpu.serving.server import CaptionServer

        engine, ds, offline, payloads = served_world
        metrics = ServingMetrics()
        engine.cache.captions.clear()
        srv = CaptionServer(
            engine, host="127.0.0.1", port=0, metrics=metrics,
            batcher=MicroBatcher(engine, metrics),
        )
        with srv:
            status, out = _post(srv.url + "/v1/caption", payloads[9])
            assert status == 200
            assert out["caption"] == offline[ds.video_id(9)]
        assert metrics.batches_total.value >= 1  # went through the ladder


# --------------------------------- PR-8 thread-safety fixes (CST-THR-002)

class TestThreadSafetyFixes:
    """Each true-positive the invariant engine surfaced in serving/
    gets its own pin (ISSUE 8 satellite)."""

    def test_concurrent_stop_is_safe_and_idempotent(self):
        """_BatcherBase.stop reads/clears the scheduler-thread handle
        under _cond (the join stays outside — the scheduler needs the
        cond to exit), so racing stop() callers can't tear the handle."""
        eng = _StubEngine(max_batch=2)
        b = MicroBatcher(eng, max_wait_ms=0.0).start()
        b.submit({"key": "warm"})
        errors = []

        def stopper():
            try:
                b.stop()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=stopper) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        assert not errors
        assert not b._running()
        assert b._thread is None
        # restartable after a clean stop
        b.start()
        assert b.submit({"key": "again"})["caption"] == "stub"
        b.stop()

    def test_server_draining_flag_is_event_backed(self):
        """_Server.draining is an Event-backed property (CST-THR-002:
        handler threads read it, control threads flip it) — begin_drain
        makes every handler observe it."""
        from cst_captioning_tpu.serving.server import _Handler, _Server

        srv = _Server(("127.0.0.1", 0), _Handler)
        try:
            assert srv.draining is False
            flips = []
            t = threading.Thread(
                target=lambda: (srv._draining_evt.wait(5.0), flips.append(
                    srv.draining
                ))
            )
            t.start()
            srv._draining_evt.set()
            t.join(timeout=10.0)
            assert flips == [True]
            # the flag is read-only state: no bare-bool attribute left
            assert isinstance(
                type(srv).__dict__.get("draining"), property
            )
        finally:
            srv.server_close()

    def test_pending_declares_single_owner_contract(self):
        """_Pending's cross-thread handoff contract is declared in
        source where the analysis pass (and the next reader) finds it."""
        from cst_captioning_tpu.serving.batcher import _Pending

        assert _Pending._analysis_single_owner is True
