"""Online caption-serving subsystem (cst_captioning_tpu/serving/).

Covers the ISSUE-2 acceptance bar:
* micro-batcher coalescing / deadline / backpressure semantics (stub
  engine — no jax in the scheduler tests);
* two-tier cache eviction + hit accounting;
* served-vs-offline TOKEN PARITY: the engine's captions are exactly
  what ``evaluation.py`` produces for the same params/features, across
  ladder buckets, the tier-2 encoder-state fast path included;
* an end-to-end in-process HTTP server test and a >= 8-concurrent-client
  smoke test with zero dropped non-expired requests and a /metrics
  queue/device latency split + cache hit rate.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from cst_captioning_tpu.config import get_preset
from cst_captioning_tpu.serving.batcher import (
    BackpressureError,
    DeadlineExceededError,
    MicroBatcher,
)
from cst_captioning_tpu.serving.cache import (
    LRUCache,
    TwoTierCache,
    content_key,
)
from cst_captioning_tpu.serving.engine import DecodedResult, PreparedRequest
from cst_captioning_tpu.serving.metrics import (
    LatencyHistogram,
    ServingMetrics,
)


# ----------------------------------------------------------------- caches

class TestLRUCache:
    def test_eviction_is_lru(self):
        c = LRUCache(2)
        c.put("a", 1)
        c.put("b", 2)
        assert c.get("a") == 1       # refresh a
        c.put("c", 3)                # evicts b (least recent)
        assert c.get("b") is None
        assert c.get("a") == 1 and c.get("c") == 3
        assert len(c) == 2

    def test_hit_miss_counters(self):
        c = LRUCache(4)
        assert c.get("x") is None
        c.put("x", 1)
        assert c.get("x") == 1
        st = c.stats()
        assert st["hits"] == 1 and st["misses"] == 1
        assert st["hit_rate"] == 0.5

    def test_zero_capacity_never_stores(self):
        c = LRUCache(0)
        c.put("a", 1)
        assert c.get("a") is None and len(c) == 0

    def test_two_tier_stats(self):
        t = TwoTierCache(2, 2)
        t.captions.put("k", {"caption": "x"})
        t.captions.get("k")
        st = t.stats()
        assert st["captions"]["hits"] == 1
        assert st["features"]["misses"] == 0

    def test_content_key_sensitivity(self):
        f = {"resnet": np.ones((3, 4), np.float32)}
        k1 = content_key(f, "tag")
        assert k1 == content_key(
            {"resnet": np.ones((3, 4), np.float32)}, "tag"
        )
        f2 = {"resnet": np.ones((3, 4), np.float32)}
        f2["resnet"][0, 0] = 2.0
        assert content_key(f2, "tag") != k1       # content changes key
        assert content_key(f, "other-tag") != k1  # params tag changes key


# ---------------------------------------------------------------- metrics

class TestMetrics:
    def test_histogram_percentiles(self):
        h = LatencyHistogram()
        for ms in [1.0] * 90 + [400.0] * 10:
            h.observe(ms)
        assert h.percentile(50) <= 2.0
        assert h.percentile(99) > 100.0
        snap = h.snapshot()
        assert snap["count"] == 100 and snap["max_ms"] == 400.0

    def test_prometheus_render(self):
        m = ServingMetrics()
        m.requests_total.inc(3)
        m.observe_stage("queue", 1.5)
        m.observe_stage("device", 10.0)
        text = m.to_prometheus({"captions": {"hits": 2, "misses": 1}})
        assert "caption_requests_total 3" in text
        assert 'caption_latency_queue_ms_bucket{le="2.0"}' in text
        assert "caption_cache_captions_hits 2" in text


# ----------------------------------------------------- batcher (stub engine)

class _StubEngine:
    """Engine-shaped test double: records batch sizes, optionally holds
    decode until released (to pin queue states deterministically)."""

    def __init__(self, max_batch=4):
        self.cfg = get_preset("synthetic_smoke")
        self.max_batch = max_batch
        self.ladder = [1, 2, max_batch] if max_batch > 2 else [max_batch]
        self.cache = TwoTierCache(8, 8)
        self.batches = []
        self.entered = threading.Event()   # set when decode begins
        self.release = threading.Event()   # decode blocks until set
        self.release.set()                 # default: don't block

    def prepare(self, payload):
        return PreparedRequest(
            feats=None, masks=None, category=0, feature_id=None,
            cache_key=payload.get("key", ""), enc_row=None,
        )

    def lookup_caption(self, key):
        return self.cache.captions.get(key) if key else None

    def bucket(self, n):
        for b in self.ladder:
            if b >= n:
                return b
        raise ValueError(n)

    def decode_prepared(self, reqs, store=True):
        self.entered.set()
        self.release.wait(timeout=30.0)
        self.batches.append(len(reqs))
        t = {"pad_ms": 0.1, "device_ms": 1.0, "detok_ms": 0.1}
        return [
            DecodedResult(caption="stub", tokens=[2], timings_ms=t)
            for _ in reqs
        ]


class TestMicroBatcher:
    def test_coalesces_concurrent_requests_into_one_batch(self):
        eng = _StubEngine(max_batch=4)
        with MicroBatcher(eng, max_wait_ms=150.0) as b:
            threads = [
                threading.Thread(target=b.submit, args=({"key": ""},))
                for _ in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10.0)
        assert eng.batches == [4], eng.batches
        assert b.metrics.batches_total.value == 1
        assert b.metrics.requests_served.value == 4

    def test_full_batch_dispatches_before_wait_window(self):
        eng = _StubEngine(max_batch=2)
        with MicroBatcher(eng, max_wait_ms=10_000.0) as b:
            t0 = time.monotonic()
            threads = [
                threading.Thread(target=b.submit, args=({"key": ""},))
                for _ in range(2)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10.0)
            assert time.monotonic() - t0 < 5.0  # did not sit out 10s
        assert eng.batches == [2]

    def test_deadline_exceeded_while_queued(self):
        eng = _StubEngine(max_batch=1)
        eng.release.clear()  # hold the first decode
        errors = []
        with MicroBatcher(eng, max_wait_ms=0.0) as b:
            t1 = threading.Thread(target=b.submit, args=({"key": ""},))
            t1.start()
            assert eng.entered.wait(timeout=10.0)  # r1 is in decode

            def submit_r2():
                try:
                    b.submit({"key": ""}, deadline_ms=30.0)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            t2 = threading.Thread(target=submit_r2)
            t2.start()
            time.sleep(0.15)          # r2's 30ms deadline passes queued
            eng.release.set()
            t1.join(timeout=10.0)
            t2.join(timeout=10.0)
        assert len(errors) == 1 and isinstance(
            errors[0], DeadlineExceededError
        )
        assert b.metrics.requests_expired.value == 1
        assert eng.batches == [1]     # r2 never reached the engine

    def test_backpressure_rejects_when_queue_full(self):
        eng = _StubEngine(max_batch=1)
        eng.release.clear()
        results = []
        with MicroBatcher(eng, max_wait_ms=0.0, queue_depth=1) as b:
            t1 = threading.Thread(target=b.submit, args=({"key": ""},))
            t1.start()
            assert eng.entered.wait(timeout=10.0)  # r1 out of the queue

            def submit_r2():
                results.append(b.submit({"key": ""}))

            t2 = threading.Thread(target=submit_r2)
            t2.start()
            # Wait until r2 occupies the queue's single slot.
            for _ in range(100):
                if b.depth >= 1:
                    break
                time.sleep(0.01)
            assert b.depth == 1
            with pytest.raises(BackpressureError) as ei:
                b.submit({"key": ""})
            assert ei.value.retry_after_s > 0
            eng.release.set()
            t1.join(timeout=10.0)
            t2.join(timeout=10.0)
        # The ACCEPTED request was served despite the rejection of r3.
        assert results and results[0]["caption"] == "stub"
        assert b.metrics.requests_rejected.value == 1
        assert b.metrics.requests_expired.value == 0

    def test_tier1_hit_short_circuits_queue(self):
        eng = _StubEngine()
        eng.cache.captions.put("k1", {"caption": "hot", "tokens": [5, 2]})
        with MicroBatcher(eng) as b:
            out = b.submit({"key": "k1"})
        assert out["cached"] is True and out["caption"] == "hot"
        assert eng.batches == []      # never dispatched


# ------------------------------------------------- engine parity (real jax)

@pytest.fixture(scope="module")
def served_world():
    """Shared tiny engine + dataset + OFFLINE predictions (compiles the
    decode graphs once for the whole module)."""
    from cst_captioning_tpu.data.build import build_dataset
    from cst_captioning_tpu.evaluation import beam_decode_dataset
    from cst_captioning_tpu.serving.engine import InferenceEngine

    cfg = get_preset("synthetic_smoke")
    cfg.serving.warmup = False          # compile lazily, tests are tiny
    cfg.serving.default_deadline_ms = 120_000.0  # compiles != expiries
    cfg.serving.max_wait_ms = 10.0
    ds, vocab = build_dataset(cfg, cfg.eval.eval_split)
    cfg.model.vocab_size = len(vocab)
    engine = InferenceEngine(cfg, random_init=True, vocab=vocab)
    offline = beam_decode_dataset(engine.model, engine.params, ds, cfg)
    payloads = [
        {
            "features": {m: a.tolist() for m, a in ds.features(i).items()},
            "feature_id": f"fid{i}",
        }
        for i in range(len(ds))
    ]
    return engine, ds, offline, payloads


class TestEngineParity:
    def test_served_tokens_match_offline_eval_across_buckets(
        self, served_world
    ):
        """THE serving correctness bar: token-exact vs evaluation.py for
        the same params/features, at every ladder bucket (1->2, 3->4,
        8->8) including padded batches."""
        engine, ds, offline, payloads = served_world
        chunks = [(0, 1), (1, 3), (4, 8), (12, 4)]
        for start, size in chunks:
            reqs = [
                engine.prepare(payloads[i])
                for i in range(start, start + size)
            ]
            results = engine.decode_prepared(reqs)
            for i, res in zip(range(start, start + size), results):
                assert res.caption == offline[ds.video_id(i)], (
                    f"video {i} bucket {engine.bucket(size)}"
                )

    def test_feature_cache_state_path_is_token_exact(self, served_world):
        """Tier-2: a feature_id-only re-request decodes from the cached
        projected encoder state (beam_search_from_state) and must
        produce the identical caption."""
        engine, ds, offline, payloads = served_world
        # First pass stored enc rows (test above ran full coverage);
        # re-request by id only.
        reqs = [
            engine.prepare({"feature_id": f"fid{i}"}) for i in range(8)
        ]
        assert all(r.enc_row is not None for r in reqs)
        results = engine.decode_prepared(reqs)
        for i, res in enumerate(results):
            assert res.caption == offline[ds.video_id(i)]
        assert engine.cache.features.stats()["hits"] > 0

    def test_caption_cache_roundtrip(self, served_world):
        engine, ds, offline, payloads = served_world
        req = engine.prepare(payloads[0])
        hit = engine.lookup_caption(req.cache_key)
        assert hit is not None and hit["caption"] == offline[ds.video_id(0)]

    def test_unknown_feature_id_raises(self, served_world):
        engine, *_ = served_world
        with pytest.raises(KeyError):
            engine.prepare({"feature_id": "never-seen"})

    def test_bad_features_rejected(self, served_world):
        engine, *_ = served_world
        with pytest.raises(ValueError):
            engine.prepare({"features": {"resnet": [[1.0, 2.0]]}})  # dim
        with pytest.raises(ValueError):
            engine.prepare({})


# ----------------------------------------------------- HTTP server e2e

def _post(url, obj, timeout=120.0):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(url, timeout=30.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


@pytest.fixture(scope="module")
def live_server(served_world):
    from cst_captioning_tpu.serving.server import CaptionServer

    engine, ds, offline, payloads = served_world
    with CaptionServer(engine, host="127.0.0.1", port=0) as srv:
        yield srv, engine, ds, offline, payloads


class TestHTTPServer:
    def test_healthz(self, live_server):
        srv, *_ = live_server
        status, body = _get(srv.url + "/healthz")
        assert status == 200
        info = json.loads(body)
        assert info["status"] == "ok" and info["decode_mode"] == "beam"

    def test_served_caption_matches_offline(self, live_server):
        srv, engine, ds, offline, payloads = live_server
        status, out = _post(srv.url + "/v1/caption", payloads[5])
        assert status == 200
        assert out["caption"] == offline[ds.video_id(5)]
        assert "timings_ms" in out

    def test_repeat_request_hits_cache(self, live_server):
        srv, engine, ds, offline, payloads = live_server
        _post(srv.url + "/v1/caption", payloads[6])
        status, out = _post(srv.url + "/v1/caption", payloads[6])
        assert status == 200 and out["cached"] is True
        assert out["caption"] == offline[ds.video_id(6)]

    def test_bad_body_is_400(self, live_server):
        srv, *_ = live_server
        req = urllib.request.Request(
            srv.url + "/v1/caption", data=b"not json",
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30.0)
        assert ei.value.code == 400

    def test_unknown_feature_id_is_404(self, live_server):
        srv, *_ = live_server
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.url + "/v1/caption", {"feature_id": "ghost"})
        assert ei.value.code == 404

    def test_stats_and_metrics_endpoints(self, live_server):
        srv, *_ = live_server
        status, body = _get(srv.url + "/stats")
        assert status == 200
        stats = json.loads(body)
        assert {"queue", "device", "total"} <= set(stats["latency_ms"])
        assert "captions" in stats["cache"]
        status, text = _get(srv.url + "/metrics")
        assert status == 200
        assert "caption_latency_queue_ms_bucket" in text
        assert "caption_latency_device_ms_bucket" in text
        assert "caption_cache_captions_hits" in text


class TestConcurrentClients:
    def test_eight_clients_zero_drops(self, live_server):
        """Acceptance criterion: >= 8 concurrent clients through the
        micro-batcher with zero dropped non-expired requests, and
        /metrics reporting the queue/device split + cache hit rate."""
        srv, engine, ds, offline, payloads = live_server
        n_clients, per_client = 8, 4
        failures, served = [], []
        lock = threading.Lock()

        def client(cid):
            rng = np.random.RandomState(cid)
            for _ in range(per_client):
                i = int(rng.randint(0, 10))
                body = dict(payloads[i])
                body["deadline_ms"] = 120_000.0
                try:
                    status, out = _post(srv.url + "/v1/caption", body)
                    assert status == 200
                    assert out["caption"] == offline[ds.video_id(i)]
                    with lock:
                        served.append(i)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        failures.append(f"client{cid}: {e}")

        threads = [
            threading.Thread(target=client, args=(c,))
            for c in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300.0)
        assert not failures, failures
        assert len(served) == n_clients * per_client
        m = srv.metrics
        assert m.requests_expired.value == 0
        assert m.requests_failed.value == 0
        assert m.requests_rejected.value == 0
        # The latency split and cache hit rate are live on /metrics.
        _, text = _get(srv.url + "/metrics")
        assert "caption_latency_queue_ms_count" in text
        assert "caption_latency_device_ms_count" in text
        assert engine.cache.stats()["captions"]["hits"] > 0
