"""Parallel layer tests on the 8-device virtual CPU mesh (SURVEY.md §4
"Distributed"): mesh construction, DP grad equivalence vs single device,
TP param sharding, trainer integration, CST-under-mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from cst_captioning_tpu.config import get_preset
from cst_captioning_tpu.data import BatchIterator, make_synthetic_dataset
from cst_captioning_tpu.parallel import (
    batch_sharding,
    make_mesh,
    mesh_from_config,
    param_spec,
    shard_batch,
    shard_params,
)
from cst_captioning_tpu.training import Trainer
from cst_captioning_tpu.training.steps import (
    create_train_state,
    make_optimizer,
    make_xe_train_step,
)
from cst_captioning_tpu.models import model_from_config


def _params_allclose(a, b, rtol=2e-5, atol=1e-5):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol
        ),
        a,
        b,
    )


class TestMesh:
    def test_wildcard_absorbs_devices(self):
        mesh = make_mesh({"data": -1, "model": 1})
        assert mesh.shape == {"data": 8, "model": 1}

    def test_explicit_shape(self):
        mesh = make_mesh({"data": 2, "model": 4})
        assert mesh.shape == {"data": 2, "model": 4}

    def test_errors(self):
        with pytest.raises(ValueError):
            make_mesh({"data": -1, "model": -1})
        with pytest.raises(ValueError):
            make_mesh({"data": 16})
        with pytest.raises(ValueError):
            make_mesh({"data": -1, "model": 3})

    def test_from_config(self):
        cfg = get_preset("synthetic_smoke")
        mesh = mesh_from_config(cfg)
        assert mesh.shape == {"data": 8, "model": 1}

    def test_param_spec_rules(self):
        assert param_spec("params/word_embed") == P("model", None)
        assert param_spec("params/logit_w") == P(None, "model")
        assert param_spec("params/lstm0_w") == P()


def _setup(cfg, vocab_multiple=1):
    ds, _ = make_synthetic_dataset(
        num_videos=16, max_frames=cfg.data.max_frames, seed=3
    )
    # Pad the vocab dimension up to a multiple (TP sharding needs the
    # vocab-sized tensors divisible by the model axis).
    v = len(ds.vocab)
    cfg.model.vocab_size = ((v + vocab_multiple - 1) // vocab_multiple
                            * vocab_multiple)
    it = BatchIterator(
        ds, batch_size=8, seq_per_img=2, max_frames=cfg.data.max_frames,
        shuffle=False,
    )
    batch = next(iter(it.epoch(0)))
    model = model_from_config(cfg)
    tx = make_optimizer(cfg.train, 10)
    return ds, model, tx, batch


class TestDPEquivalence:
    def test_sharded_step_matches_single_device(self):
        cfg = get_preset("synthetic_smoke")
        ds, model, tx, batch = _setup(cfg)
        rng = jax.random.PRNGKey(0)
        step_rng = jax.random.PRNGKey(1)

        # Single device (mesh over devices[:1]).
        s1 = create_train_state(rng, model, tx, batch._asdict())
        step = make_xe_train_step(model)
        ones = jnp.ones_like(jnp.asarray(batch.weights))
        s1b, m1 = step(
            s1, batch.feats, batch.feat_masks, batch.captions, ones, None,
            batch.video_idx, step_rng, 0.0,
        )

        # 8-way DP mesh: replicated params, sharded batch.
        mesh = make_mesh({"data": -1, "model": 1})
        s8 = create_train_state(rng, model, tx, batch._asdict(), mesh=mesh)
        sh = batch_sharding(mesh)
        feats = shard_batch(batch.feats, mesh)
        fmasks = shard_batch(batch.feat_masks, mesh)
        caps = jax.device_put(batch.captions, sh)
        w = jax.device_put(np.ones_like(batch.weights), sh)
        vidx = jax.device_put(batch.video_idx, sh)
        s8b, m8 = step(
            s8, feats, fmasks, caps, w, None, vidx, step_rng, 0.0,
        )

        np.testing.assert_allclose(
            float(m1["loss"]), float(m8["loss"]), rtol=1e-5
        )
        _params_allclose(s1b.params, s8b.params)

    def test_tp_sharding_matches_replicated(self):
        cfg = get_preset("synthetic_smoke")
        ds, model, tx, batch = _setup(cfg, vocab_multiple=4)
        rng = jax.random.PRNGKey(0)
        step_rng = jax.random.PRNGKey(1)
        s1 = create_train_state(rng, model, tx, batch._asdict())
        step = make_xe_train_step(model)
        ones = jnp.ones_like(jnp.asarray(batch.weights))
        s1b, m1 = step(
            s1, batch.feats, batch.feat_masks, batch.captions, ones, None,
            batch.video_idx, step_rng, 0.0,
        )

        mesh = make_mesh({"data": 2, "model": 4})
        stp = create_train_state(rng, model, tx, batch._asdict(), mesh=mesh)
        # vocab-sized params actually sharded over the model axis
        emb_shard = stp.params["params"]["word_embed"].sharding
        assert emb_shard.spec == P("model", None)
        sh = batch_sharding(mesh)
        stpb, mtp = step(
            stp,
            shard_batch(batch.feats, mesh),
            shard_batch(batch.feat_masks, mesh),
            jax.device_put(batch.captions, sh),
            jax.device_put(np.ones_like(batch.weights), sh),
            None,
            jax.device_put(batch.video_idx, sh),
            step_rng,
            0.0,
        )
        np.testing.assert_allclose(
            float(m1["loss"]), float(mtp["loss"]), rtol=1e-5
        )
        _params_allclose(s1b.params, stpb.params)


class TestCompiledCollectives:
    """Compiler-level scaling audit: the collectives XLA inserts for the
    DP step are the ones the sharding design intends — gradient
    all-reduces — and NOT a pathological all-gather of the full
    (rows, T, V) logits or of the batch (which would mean SPMD gave up
    and replicated the computation)."""

    def test_dp_step_collectives(self):
        cfg = get_preset("synthetic_smoke")
        ds, model, tx, batch = _setup(cfg)
        mesh = make_mesh({"data": -1, "model": 1})
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, batch._asdict(), mesh=mesh
        )
        step = make_xe_train_step(model)
        sh = batch_sharding(mesh)
        args = (
            state,
            shard_batch(batch.feats, mesh),
            shard_batch(batch.feat_masks, mesh),
            jax.device_put(batch.captions, sh),
            jax.device_put(np.ones_like(batch.weights), sh),
            None,
            jax.device_put(batch.video_idx, sh),
            jax.random.PRNGKey(1),
        )
        compiled = step.lower(*args, 0.0).compile()
        hlo = compiled.as_text()
        assert "all-reduce" in hlo  # grad psum over the data axis
        # The DP loss reduces locally — the compiled step needs NO
        # all-gather at all (one appearing would mean SPMD replicated
        # something, e.g. the full (B*S, T, V) logits).
        assert "all-gather" not in hlo, "DP step grew an all-gather"
        # Every gradient all-reduce stays parameter-shaped (no tensor
        # larger than the biggest param crosses the interconnect).
        import re

        biggest_param = max(
            int(np.prod(p.shape))
            for p in jax.tree.leaves(state.params)
        )
        audited = 0
        for line in hlo.splitlines():
            if " all-reduce(" not in line and " all-reduce-start(" not in line:
                continue
            m = re.search(r"f32\[([\d,]*)\]", line)
            if m and m.group(1):
                audited += 1
                elems = int(
                    np.prod([int(x) for x in m.group(1).split(",")])
                )
                assert elems <= biggest_param, (
                    f"all-reduce larger than any param: {line}"
                )
        assert audited > 0  # the audit actually saw the grad reduces


class TestTrainerOnMesh:
    def test_fit_epoch_on_mesh(self, tmp_path):
        ds, _ = make_synthetic_dataset(num_videos=16, max_frames=6, seed=9)
        cfg = get_preset("synthetic_smoke")
        cfg.data.batch_size = 8
        cfg.data.seq_per_img = 2
        cfg.train.checkpoint_dir = str(tmp_path)
        cfg.train.max_epochs = 2
        cfg.train.max_patience = 0
        cfg.eval.metrics = ["CIDEr"]
        cfg.eval.max_decode_len = 11
        t = Trainer(cfg, train_ds=ds, val_ds=ds, workdir=str(tmp_path / "w"))
        assert t.mesh is not None and t.mesh.shape == {"data": 8, "model": 1}
        hist = t.fit()
        assert np.isfinite(hist["1"]["train_loss"])
        assert "val" in hist["1"]

    def test_cst_step_on_mesh(self, tmp_path):
        ds, _ = make_synthetic_dataset(num_videos=16, max_frames=6, seed=9)
        cfg = get_preset("synthetic_smoke")
        cfg.data.batch_size = 8
        cfg.data.seq_per_img = 2
        cfg.data.max_seq_len = 11
        cfg.train.checkpoint_dir = str(tmp_path)
        cfg.train.train_mode = "cst"
        cfg.train.cst_baseline = "greedy"
        cfg.train.cst_num_samples = 2
        cfg.train.max_epochs = 1
        cfg.train.max_patience = 0
        cfg.eval.metrics = ["CIDEr"]
        cfg.eval.max_decode_len = 11
        t = Trainer(cfg, train_ds=ds, val_ds=None,
                    workdir=str(tmp_path / "w2"))
        hist = t.fit()
        assert np.isfinite(hist["0"]["train_loss"])
        assert np.isfinite(hist["0"]["reward"])
