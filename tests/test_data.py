"""Data layer tests: vocab round-trip, batch shapes/determinism, host
sharding, h5 round-trip through the prep tool, consensus weights."""

import json
import os

import numpy as np
import pytest

from cst_captioning_tpu.data import (
    BatchIterator,
    H5Dataset,
    Vocabulary,
    decode_sequence,
    make_synthetic_dataset,
)
from cst_captioning_tpu.data.loader import subsample_frames
from cst_captioning_tpu.models.captioner import BOS_ID, EOS_ID, PAD_ID, UNK_ID
from cst_captioning_tpu.tools.prepare_data import (
    consensus_weights,
    prepare,
)


class TestVocabulary:
    def test_build_encode_decode_roundtrip(self):
        vocab = Vocabulary.build([["a", "cat", "runs"], ["a", "dog", "runs"]])
        ids = vocab.encode(["a", "cat", "runs"], max_len=5)
        assert ids[0] == BOS_ID
        assert list(ids).count(EOS_ID) == 1
        assert vocab.decode(ids) == "a cat runs"

    def test_unk_for_oov(self):
        vocab = Vocabulary.build([["cat"]])
        ids = vocab.encode(["dog"], max_len=3)
        assert ids[1] == UNK_ID

    def test_decode_out_of_range_maps_to_unk(self):
        """vocab_size is often padded above len(vocab) for TP-friendly
        shapes; sampled ids beyond the table must decode as <unk>, not
        crash validation decode."""
        vocab = Vocabulary(["cat", "runs"])
        ids = [BOS_ID, vocab.word_to_idx["cat"], len(vocab) + 7, EOS_ID]
        assert vocab.decode(ids) == "cat <unk>"

    def test_min_freq_threshold(self):
        vocab = Vocabulary.build([["a", "a", "rare"]], min_freq=2)
        assert "a" in vocab and "rare" not in vocab

    def test_truncation(self):
        vocab = Vocabulary.build([["w"]])
        ids = vocab.encode(["w"] * 10, max_len=4)
        assert ids.shape == (6,)
        assert ids[5] == EOS_ID

    def test_save_load(self, tmp_path):
        vocab = Vocabulary.build([["x", "y", "z"]])
        p = str(tmp_path / "vocab.json")
        vocab.save(p)
        v2 = Vocabulary.load(p)
        assert v2.idx_to_word == vocab.idx_to_word

    def test_deterministic_order(self):
        v1 = Vocabulary.build([["b", "a", "a"]])
        v2 = Vocabulary.build([["a", "b", "a"]])
        assert v1.idx_to_word == v2.idx_to_word


class TestSynthetic:
    def test_vocab_is_seed_independent(self):
        """Regression: train/val/test synthetic splits (different seeds)
        must share one id<->word table, or decoding val predictions with
        the train vocab mistranslates every caption."""
        _, v0 = make_synthetic_dataset(num_videos=8, seed=0)
        _, v1 = make_synthetic_dataset(num_videos=8, seed=1)
        assert v0.idx_to_word == v1.idx_to_word

    def test_topic_features_are_seed_independent(self):
        ds0, _ = make_synthetic_dataset(num_videos=30, seed=0, noise=0.0)
        ds1, _ = make_synthetic_dataset(num_videos=30, seed=1, noise=0.0)
        # find two videos with the same topic caption across seeds
        for i in range(len(ds0)):
            for j in range(len(ds1)):
                if ds0.references(i)[0] == ds1.references(j)[0]:
                    np.testing.assert_allclose(
                        ds0.features(i)["resnet"][0],
                        ds1.features(j)["resnet"][0],
                    )
                    return
        pytest.skip("no shared topic between seeds")

    def test_learnable_structure(self):
        ds, vocab = make_synthetic_dataset(num_videos=10, seed=3)
        assert len(ds) == 10
        # refs of one video share the topic bigram
        refs = ds.references(0)
        head = " ".join(refs[0].split()[:2])
        assert all(r.startswith(head) for r in refs)
        caps = ds.captions(0)
        assert caps.dtype == np.int32
        assert (caps[:, 0] == BOS_ID).all()
        assert decode_sequence(vocab, caps)[0] == refs[0]


class TestBatchIterator:
    def _it(self, **kw):
        ds, _ = make_synthetic_dataset(num_videos=21, max_frames=6, seed=0)
        defaults = dict(
            dataset=ds, batch_size=8, seq_per_img=3, max_frames=6,
            shuffle=True, seed=1,
        )
        defaults.update(kw)
        return ds, BatchIterator(**defaults)

    def test_fixed_shapes_incl_final_batch(self):
        ds, it = self._it(drop_last=False)
        batches = list(it.epoch(0))
        assert len(batches) == 3  # ceil(21/8)
        for b in batches:
            assert b.feats["resnet"].shape == (8, 6, 64)
            assert b.feat_masks["resnet"].shape == (8, 6)
            assert b.captions.shape == (8, 3, 12)
            assert b.weights.shape == (8, 3)
            assert b.category.shape == (8,)
            assert len(b.video_ids) == 8

    def test_drop_last(self):
        _, it = self._it(drop_last=True)
        assert it.num_batches() == 2
        assert len(list(it.epoch(0))) == 2

    def test_epoch_determinism_and_reshuffle(self):
        _, it = self._it()
        a1 = [b.video_idx.tolist() for b in it.epoch(0)]
        a2 = [b.video_idx.tolist() for b in it.epoch(0)]
        b1 = [b.video_idx.tolist() for b in it.epoch(1)]
        assert a1 == a2
        assert a1 != b1

    def test_covers_all_videos(self):
        _, it = self._it(drop_last=False)
        seen = set()
        for b in it.epoch(0):
            seen.update(b.video_idx.tolist())
        assert seen == set(range(21))

    def test_host_sharding_partitions(self):
        ds, _ = make_synthetic_dataset(num_videos=21, max_frames=6, seed=0)
        seen = []
        for shard in range(2):
            it = BatchIterator(
                ds, batch_size=4, seq_per_img=2, max_frames=6,
                shuffle=False, shard_id=shard, num_shards=2,
            )
            s = set()
            for b in it.epoch(0):
                s.update(b.video_idx.tolist())
            seen.append(s)
        assert seen[0] | seen[1] == set(range(21))
        assert seen[0] & seen[1] == set()

    def test_frame_mask_matches_padding(self):
        ds, it = self._it(shuffle=False)
        b = next(iter(it.epoch(0)))
        fm = b.feat_masks["resnet"]
        feats = b.feats["resnet"]
        # padded frames are exactly zero
        assert np.allclose(feats[fm == 0], 0.0)
        # each video has at least one valid frame
        assert (fm.sum(1) >= 1).all()

    def test_subsample_frames(self):
        fr = np.arange(20, dtype=np.float32)[:, None]
        out = subsample_frames(fr, 5)
        assert out.shape == (5, 1)
        assert out[0, 0] == 0 and out[-1, 0] == 19
        same = subsample_frames(fr, 30)
        assert same.shape == (20, 1)


class TestPrefetch:
    def _batches(self, n=3):
        ds, _ = make_synthetic_dataset(num_videos=8, max_frames=6, seed=0)
        it = BatchIterator(ds, batch_size=4, seq_per_img=2, max_frames=6,
                           shuffle=False)
        return list(it.epoch(0))[:1] * n

    @staticmethod
    def _prefetch_threads():
        import threading

        return [
            t for t in threading.enumerate()
            if t.name == "prefetch_to_device" and t.is_alive()
        ]

    def test_worker_exception_propagates(self):
        """An assembly error mid-epoch must poison-pill through to the
        consumer (not silently end the epoch short) and leave no live
        prefetch thread behind."""
        from cst_captioning_tpu.data.loader import prefetch_to_device

        good = self._batches(2)

        def gen():
            yield good[0]
            raise RuntimeError("h5 read exploded")

        got = []
        with pytest.raises(RuntimeError, match="h5 read exploded"):
            for b in prefetch_to_device(gen()):
                got.append(b)
        assert len(got) == 1  # the batch before the crash still arrived
        import time

        deadline = time.monotonic() + 5.0
        while self._prefetch_threads() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not self._prefetch_threads()

    def test_early_close_joins_worker_thread(self):
        """Abandoning the iterator mid-epoch (break/exception in the
        consumer) must join the worker so it cannot linger holding
        device-resident batches in the queue."""
        from cst_captioning_tpu.data.loader import prefetch_to_device

        batch = self._batches(1)[0]

        def endless():
            while True:
                yield batch

        it = prefetch_to_device(endless(), size=2)
        next(it)
        assert self._prefetch_threads()
        it.close()  # GeneratorExit -> finally: stop, drain, join
        assert not self._prefetch_threads()

    def test_clean_epoch_joins_worker_thread(self):
        from cst_captioning_tpu.data.loader import prefetch_to_device

        out = list(prefetch_to_device(iter(self._batches(3))))
        assert len(out) == 3
        assert not self._prefetch_threads()


class TestConsensusWeights:
    def test_consensus_prefers_agreeing_caption(self):
        toks = [
            ["a", "cat", "runs"],
            ["a", "cat", "runs", "fast"],
            ["purple", "quantum", "xylophone"],
        ]
        w = consensus_weights(toks, normalize=False)
        assert w[0] > w[2] and w[1] > w[2]

    def test_normalized_mean_one(self):
        toks = [["a", "b"], ["a", "c"], ["a", "d"]]
        w = consensus_weights(toks)
        np.testing.assert_allclose(w.mean(), 1.0, rtol=1e-6)

    def test_single_caption_gets_one(self):
        np.testing.assert_array_equal(
            consensus_weights([["solo"]]), np.ones(1, np.float32)
        )


class TestPrepareAndH5:
    @pytest.fixture()
    def raw(self, tmp_path):
        data = {
            "videos": [
                {"video_id": f"v{i}", "split": "train" if i < 4 else "test",
                 "category": i % 3}
                for i in range(6)
            ],
            "sentences": [
                {"video_id": f"v{i}", "caption": c}
                for i in range(6)
                for c in (f"a cat number {i} runs", f"the cat {i} is running",
                          "a dog sleeps")
            ],
        }
        p = tmp_path / "videodatainfo.json"
        p.write_text(json.dumps(data))
        return str(p)

    def test_prepare_msrvtt_roundtrip(self, raw, tmp_path):
        out = str(tmp_path / "out")
        paths = prepare(raw, "msrvtt", out, min_freq=1, max_words=8)
        assert os.path.exists(paths["vocab"])
        assert os.path.exists(paths["idf"])
        vocab = Vocabulary.load(paths["vocab"])
        ds = H5Dataset(
            paths["labels_train"], {}, vocab
        )
        assert len(ds) == 4
        caps = ds.captions(0)
        assert caps.shape[1] == 10  # max_words + BOS/EOS
        assert (caps[:, 0] == BOS_ID).all()
        refs = ds.references(0)
        assert len(refs) == 3
        w = ds.caption_weights(0)
        assert w.shape == (3,)
        # the two agreeing cat captions outweigh the dog caption
        assert w[0] > w[2] and w[1] > w[2]
        assert ds.category(2) == 2
        # cocofmt structure
        with open(paths["cocofmt_test"]) as f:
            coco = json.load(f)
        assert {im["id"] for im in coco["images"]} == {"v4", "v5"}
        assert all("caption" in a for a in coco["annotations"])

    def test_consensus_file_overrides_weights(self, raw, tmp_path):
        """``data.consensus_file`` (json or flat npy) replaces the label
        h5's stored WXE weights on the train split."""
        from cst_captioning_tpu.config import get_preset
        from cst_captioning_tpu.data.build import build_dataset

        out = str(tmp_path / "out")
        paths = prepare(raw, "msrvtt", out, min_freq=1, max_words=8)
        # prepare() writes a standalone consensus artifact that matches
        # the label h5's stored weights exactly.
        with open(paths["consensus_train"]) as f:
            cons = json.load(f)
        vocab = Vocabulary.load(paths["vocab"])
        ds = H5Dataset(paths["labels_train"], {}, vocab)
        for i in range(len(ds)):
            np.testing.assert_allclose(
                cons[ds.video_id(i)], ds.caption_weights(i), rtol=1e-6
            )

        cfg = get_preset("msrvtt_resnet_c3d_xe")
        cfg.data.label_file = os.path.join(out, "labels_{split}.h5")
        cfg.data.vocab_file = paths["vocab"]
        cfg.data.feature_files = {}

        # json override: distinct constants per video
        cpath = str(tmp_path / "cons.json")
        with open(cpath, "w") as f:
            json.dump(
                {f"v{i}": [float(i + 1)] * 3 for i in range(4)}, f
            )
        cfg.data.consensus_file = cpath
        ds2, _ = build_dataset(cfg, "train")
        np.testing.assert_allclose(
            ds2.caption_weights(2), [3.0, 3.0, 3.0]
        )

        # npy override: flat array aligned with caption rows
        npy = str(tmp_path / "cons.npy")
        np.save(npy, np.arange(12, dtype=np.float32))
        cfg.data.consensus_file = npy
        ds3, _ = build_dataset(cfg, "train")
        np.testing.assert_allclose(ds3.caption_weights(1), [3, 4, 5])
        # non-train splits keep stored weights
        ds_t, _ = build_dataset(cfg, "test")
        assert ds_t._weight_override is None

    def test_h5_dataset_with_features(self, raw, tmp_path):
        h5py = pytest.importorskip("h5py")
        out = str(tmp_path / "out")
        paths = prepare(raw, "msrvtt", out, min_freq=1, max_words=8)
        featfile = str(tmp_path / "resnet.h5")
        rng = np.random.RandomState(0)
        with h5py.File(featfile, "w") as f:
            for i in range(6):
                f.create_dataset(f"v{i}", data=rng.randn(7, 16).astype("f4"))
        vocab = Vocabulary.load(paths["vocab"])
        ds = H5Dataset(paths["labels_train"], {"resnet": featfile}, vocab)
        assert ds.feature_dims == {"resnet": 16}
        f0 = ds.features(0)
        assert f0["resnet"].shape == (7, 16)
        it = BatchIterator(ds, batch_size=2, seq_per_img=2, max_frames=4,
                           shuffle=False)
        b = next(iter(it.epoch(0)))
        assert b.feats["resnet"].shape == (2, 4, 16)
        ds.close()
