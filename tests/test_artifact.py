"""AOT serving artifacts (ISSUE 13, serving/artifact.py).

Covers the tentpole acceptance bars:

* build/publish is ATOMIC (tmp + rename, no .tmp leftovers) and
  idempotent (content-hash version: rebuilding an unchanged engine
  reuses the published dir);
* the artifact enumerates EXACTLY the variants warmup() compiles —
  after ``aot_lower`` a full ``warmup()`` builds ZERO new variants (the
  no-drift pin), and the manifest key set equals
  ``aot_variant_keys()``;
* ``InferenceEngine.from_artifact`` boots with ``compile_count == 0``
  (zero fresh tick-ladder compiles) and serves TOKEN-EXACT vs a
  warm-compiled engine over the same params/requests — compile_count
  still 0 after traffic (drift would lazily build);
* the refusal contract: any manifest field diverging from the live
  environment raises ``ArtifactMismatchError`` naming every divergent
  field (toolchain fields AND ladder-drift key sets);
* directory hygiene: the loader GCs versions beyond
  ``serving.artifact_keep``; the ACTIVE version is never collected,
  ``.tmp-*`` crash leftovers are swept.

The shared-harness twin (``slot_decoder_beam_aot`` in
tests/test_decode_core.py) pins the install path token-exact against
the scan reference across the whole backend matrix.
"""

import json
import os
import shutil

import numpy as np
import pytest

from cst_captioning_tpu.config import get_preset
from cst_captioning_tpu.data.vocab import Vocabulary, decode_sequence
from cst_captioning_tpu.serving.artifact import (
    MANIFEST_NAME,
    ArtifactError,
    ArtifactMismatchError,
    build_artifact,
    load_manifest,
    prune_artifacts,
)
from cst_captioning_tpu.serving.engine import InferenceEngine


def _tiny_cfg():
    cfg = get_preset("synthetic_smoke")
    cfg.serving.warmup = False
    cfg.serving.num_slots = 4
    cfg.serving.slot_bank_min = 2
    cfg.serving.max_batch_size = 4
    cfg.serving.batch_shapes = [2, 4]
    return cfg


@pytest.fixture(scope="module")
def art_world(tmp_path_factory):
    """One built artifact over one random-init engine (build is the
    expensive step — shared module-wide)."""
    cfg = _tiny_cfg()
    vocab = Vocabulary([f"w{i}" for i in range(60)])
    cfg.model.vocab_size = len(vocab)
    engine = InferenceEngine(cfg, random_init=True, vocab=vocab)
    root = str(tmp_path_factory.mktemp("artifacts"))
    summary = build_artifact(engine, root)
    return engine, vocab, root, summary


def _payloads(cfg, n, seed=0):
    rng = np.random.RandomState(seed)
    d = cfg.data
    return [
        {
            "features": {
                m: rng.randn(d.max_frames, d.feature_dims[m]).astype(
                    np.float32
                )
                for m in d.feature_modalities
            }
        }
        for _ in range(n)
    ]


def _decode_all(engine, decoder, payloads):
    """Staggered slot decode of every payload; tokens in payload order."""
    reqs = [engine.prepare(dict(p)) for p in payloads]
    pending = list(enumerate(reqs))
    got = {}
    while pending or decoder.occupied:
        n = min(1, len(pending), len(decoder.free))
        batch = [pending.pop(0) for _ in range(n)]
        done = decoder.tick([r for _, r in batch], [i for i, _ in batch])
        for i, tokens, _score, _steps in decoder.harvest_many(done):
            got[i] = tokens
    return [got[i] for i in range(len(payloads))]


class TestArtifactBuild:
    def test_publish_is_atomic_and_versioned(self, art_world):
        _, _, root, summary = art_world
        assert summary["rebuilt"] is True
        vdir = summary["path"]
        assert os.path.exists(os.path.join(vdir, MANIFEST_NAME))
        assert summary["artifact_version"].startswith("v")
        # no half-written build sibling survives a successful publish
        assert not [
            d for d in os.listdir(root) if d.startswith(".tmp-")
        ]
        man = load_manifest(vdir)
        assert man["artifact_version"] == summary["artifact_version"]
        for key in ("params_tag", "mesh_shape", "preset", "version"):
            assert key in man["fingerprint"], key
        for key in ("jax_version", "jaxlib_version", "platform",
                    "device_kind"):
            assert key in man["env"], key

    def test_rebuild_of_unchanged_engine_reuses_version(self, art_world):
        engine, _, root, summary = art_world
        again = build_artifact(engine, root)
        assert again["rebuilt"] is False
        assert again["artifact_version"] == summary["artifact_version"]
        assert again["path"] == summary["path"]

    def test_warmup_builds_nothing_beyond_the_aot_ladder(self, art_world):
        """THE no-drift pin: after ``aot_lower`` enumerated/built every
        variant (inside build_artifact), a FULL warmup() compiles zero
        new ones — the artifact covers exactly warmup's ladder."""
        engine, _, _, summary = art_world
        dec = engine.slot_decoder()
        n0 = dec.compile_count
        dec.warmup()
        assert dec.compile_count == n0, (
            "warmup built a variant aot_lower did not enumerate"
        )
        # and the manifest's key set is the live enumeration, verbatim
        man = load_manifest(summary["path"])
        assert set(man["variants"]) == set(dec.aot_variant_keys())
        assert set(man["encode_variants"]) == {
            f"encode:B{b}" for b in dec.aot_encode_buckets()
        }


class TestArtifactBoot:
    def test_zero_compiles_and_token_exact_vs_warm(self, art_world):
        engine, _, _, summary = art_world
        booted = InferenceEngine.from_artifact(summary["path"])
        dec = booted.slot_decoder()
        assert dec.compile_count == 0, (
            "artifact boot traced/compiled a tick variant"
        )
        assert booted.artifact_version == summary["artifact_version"]
        assert (
            booted.fingerprint()["artifact_version"]
            == summary["artifact_version"]
        )
        assert engine.fingerprint()["artifact_version"] == "warm"
        # Same logical model: the artifact boot inherits the build-time
        # params_tag (cache keys hit across provenance).
        assert booted.params_tag == engine.params_tag
        payloads = _payloads(engine.cfg, 5)
        warm_dec = engine.slot_decoder()   # warmed by the drift test
        warm = _decode_all(engine, warm_dec, payloads)
        aot = _decode_all(booted, dec, payloads)
        for i, (a, b) in enumerate(zip(warm, aot)):
            assert np.array_equal(a, b), (
                f"payload {i}: artifact boot changed tokens\n"
                f"warm: {decode_sequence(engine.vocab, a[None])[0]}\n"
                f"aot:  {decode_sequence(booted.vocab, b[None])[0]}"
            )
        # Traffic (including elastic resizes in _decode_all's ticks)
        # stayed hit-only: drift would have lazily built a variant.
        assert dec.compile_count == 0

    def test_refusal_names_every_divergent_field(
        self, art_world, tmp_path
    ):
        _, _, _, summary = art_world
        vdir = os.path.join(str(tmp_path), "copy")
        shutil.copytree(summary["path"], vdir)
        mpath = os.path.join(vdir, MANIFEST_NAME)
        with open(mpath) as f:
            man = json.load(f)
        man["env"]["jax_version"] = "9.9.9"
        man["fingerprint"]["version"] = "0.0.0-other"
        with open(mpath, "w") as f:
            json.dump(man, f)
        with pytest.raises(ArtifactMismatchError) as ei:
            InferenceEngine.from_artifact(vdir)
        fields = {f for f, _, _ in ei.value.mismatches}
        assert fields == {"env.jax_version", "fingerprint.version"}
        assert "9.9.9" in str(ei.value)

    def test_refusal_on_ladder_drift(self, art_world, tmp_path):
        """A variant-set mismatch (the ladder code moved since build)
        is a named refusal, never a silent retrace."""
        _, _, _, summary = art_world
        vdir = os.path.join(str(tmp_path), "drift")
        shutil.copytree(summary["path"], vdir)
        mpath = os.path.join(vdir, MANIFEST_NAME)
        with open(mpath) as f:
            man = json.load(f)
        man["variants"]["tick:S64:A64"] = man["variants"].pop(
            sorted(man["variants"])[0]
        )
        with open(mpath, "w") as f:
            json.dump(man, f)
        with pytest.raises(ArtifactMismatchError) as ei:
            InferenceEngine.from_artifact(vdir)
        assert any(f == "variants" for f, _, _ in ei.value.mismatches)

    def test_malformed_artifact_is_a_named_error(self, tmp_path):
        with pytest.raises(ArtifactError, match="no published artifact"):
            InferenceEngine.from_artifact(str(tmp_path))


class TestArtifactHygiene:
    def _fake_version(self, root, name, age):
        p = os.path.join(root, name)
        os.makedirs(p)
        with open(os.path.join(p, MANIFEST_NAME), "w") as f:
            f.write("{}")
        os.utime(p, (age, age))
        return p

    def test_prune_keeps_newest_and_never_the_active(self, tmp_path):
        root = str(tmp_path)
        old = self._fake_version(root, "vaaa", 1_000)
        mid = self._fake_version(root, "vbbb", 2_000)
        new = self._fake_version(root, "vccc", 3_000)
        tmp = os.path.join(root, ".tmp-vddd-1")
        os.makedirs(tmp)
        # keep=1: the newest survives, the ACTIVE (oldest!) survives
        # regardless, everything else — including crash leftovers — goes.
        removed = prune_artifacts(root, keep=1, active=old)
        assert os.path.isdir(old), "the active version was collected"
        assert os.path.isdir(new)
        assert not os.path.isdir(mid)
        assert not os.path.isdir(tmp)
        assert set(removed) == {mid, tmp}

    def test_load_gc_respects_artifact_keep(self, art_world):
        """Loading an artifact sweeps stale sibling versions beyond
        serving.artifact_keep (default 2) but keeps the loaded one."""
        _, _, root, summary = art_world
        stale = [
            self._fake_version(root, f"vstale{i}", 10 + i)
            for i in range(3)
        ]
        booted = InferenceEngine.from_artifact(summary["path"])
        assert booted.artifact_version == summary["artifact_version"]
        assert os.path.isdir(summary["path"])
        # keep=2 with the active dir newest: at most one stale survives
        survivors = [p for p in stale if os.path.isdir(p)]
        assert len(survivors) <= 1
