# corpus-rules: donation
"""Seeded donation/compile-discipline violations: an update step whose
registry entry demands donation but whose jit call forgot it, a jit
site with no registry entry at all, and an AOT ``.lower().compile()``
site missing from the AOT registry.  (The corpus test injects the
matching registry entry for the first key.)"""

import jax


def make_bad_update_step(model):
    def train_step(state, batch):
        return state

    # registered update step (injected by the test) WITHOUT donation
    return jax.jit(train_step)  # expect: CST-DON-001


def make_unregistered(model):
    def mystery(x):
        return x

    return jax.jit(mystery)  # expect: CST-DON-002


def make_unregistered_aot(jitted, avals):
    # ahead-of-time compile outside the jit dispatch path, with no
    # AOT_SITE_REGISTRY entry naming its variant/refusal story
    return jitted.lower(avals).compile()  # expect: CST-DON-004
