# corpus-rules: donation
"""Seeded donation/compile-discipline violations: an update step whose
registry entry demands donation but whose jit call forgot it, and a
jit site with no registry entry at all.  (The corpus test injects the
matching registry entry for the first key.)"""

import jax


def make_bad_update_step(model):
    def train_step(state, batch):
        return state

    # registered update step (injected by the test) WITHOUT donation
    return jax.jit(train_step)  # expect: CST-DON-001


def make_unregistered(model):
    def mystery(x):
        return x

    return jax.jit(mystery)  # expect: CST-DON-002
