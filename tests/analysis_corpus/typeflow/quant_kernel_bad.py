# corpus-rules: dtypeflow
"""Seeded ISSUE-20 in-kernel dequant violations: the fused decode
kernels stream int8 vocab/gate code tiles and dequantize in-kernel —
per-channel scale applied AFTER an f32-pinned accumulation
(``ops/quant.py::quant_matmul`` semantics).  Two ways that contract
decays: an unregistered code-tile cast reachable from a jit root (001 —
no CAST_REGISTRY entry claiming the relaxed-serving tier for the
quantization rounding) and a registered in-kernel dequant whose GEMM
loses the f32 accumulation pin (003 — multiplying the per-channel scale
into a bf16 accumulator does not un-round it; the corpus test injects
the ``low_precision=True`` entry for ``registered_kernel_dequant``).
The negative case is the kernels' exact vloop idiom: registered cast,
pinned f32 accumulation, per-logit scale applied after, f32 bias."""

import jax
import jax.numpy as jnp


@jax.jit
def unregistered_kernel_dequant(h, q_tile, scale_tile):
    # a streamed int8 code tile cast to the activation dtype with no
    # CAST_REGISTRY entry naming the parity tier that survives the
    # quantization rounding
    w = q_tile.astype(jnp.bfloat16)  # expect: CST-DTY-001
    return jnp.matmul(
        h, w, preferred_element_type=jnp.float32
    ) * scale_tile


@jax.jit
def registered_kernel_dequant(h, q_tile, scale_tile, bias_tile):
    # the cast sites are registered (relaxed-serving entry injected by
    # the corpus test) ...
    hc = h.astype(jnp.bfloat16)
    wc = q_tile.astype(jnp.bfloat16)
    # ... but the post-accumulation scale multiply only preserves
    # quant_matmul semantics over an f32-PINNED accumulator — scaling a
    # bf16 accumulation does not un-round it
    bad = jnp.matmul(hc, wc) * scale_tile  # expect: CST-DTY-003
    # negative: the fused kernels' vloop idiom — codes cast losslessly
    # to the activation dtype, f32 accumulation pinned, per-logit scale
    # applied after the accumulation, f32 bias, no cdt rounding
    good = jnp.matmul(
        hc, wc, preferred_element_type=jnp.float32
    ) * scale_tile + bias_tile
    return bad + good
