# corpus-rules: shapeflow
"""Seeded CST-SHP violations: jit sites with no SHAPE_LADDER_REGISTRY
entry (001), AOT enumeration drift in a class shipping the artifact
contract (002), and trace-time loop unrolls over ``.shape`` (003).
The data-dependent-dimension half of 001 is seeded separately in
``serving/dispatch_bad.py`` (the rule scopes itself to dispatch
directories).  Negative cases: static-bound loops and a drift-free
AOT pair stay quiet."""

import jax
import jax.numpy as jnp


@jax.jit  # expect: CST-SHP-001
def unladdered_root(x):
    return x + 1


@jax.jit  # expect: CST-SHP-001
def shape_unroll(x):
    acc = jnp.zeros_like(x[0])
    # unrolls at trace time, once per shape: a per-shape graph blowup
    for t in range(x.shape[0]):  # expect: CST-SHP-003
        acc = acc + x[t]
    # negative: a small static bound is ordinary unrolling
    for _ in range(4):
        acc = acc * 1
    n = x.shape[0]
    # the read threads through the def-use chains too
    while n > 0:  # expect: CST-SHP-003
        acc = acc - 1
        n = n - 1
    return acc


class DriftingArtifact:
    """aot_variant_keys / aot_lower disagree on every axis the rule
    checks: key families, builder coverage, ladder sources."""

    def __init__(self):
        self.bank_ladder = [8, 16]
        self._fns = {}

    def warm_admit_counts(self, bank):
        return [0, bank]

    def _tick_fn(self, a):
        return self._fns.setdefault(("tick", a), object())

    def _extra_fn(self, s):  # expect: CST-SHP-002
        # a compiled-variant builder aot_lower never lowers
        return self._fns.setdefault(("extra", s), object())

    def warmup(self):
        for bank in self.bank_ladder:
            for a in self.warm_admit_counts(bank):
                self._tick_fn(a)
            self._extra_fn(bank)

    # emits "free:" keys aot_lower never builds, and ignores the
    # bank_ladder/warm_admit_counts sources warmup walks
    def aot_variant_keys(self):  # expect: CST-SHP-002
        return [f"tick:A{a}" for a in (0, 8)] + ["free:S8"]

    def aot_lower(self):
        return [(f"tick:A{a}", self._tick_fn(a)) for a in (0, 8)]


class CleanArtifact:
    """Negative: enumeration and builder agree — no findings."""

    def __init__(self):
        self.bank_ladder = [8]

    def warm_admit_counts(self, bank):
        return [0, bank]

    def _tick_fn(self, a):
        return object()

    def warmup(self):
        for bank in self.bank_ladder:
            for a in self.warm_admit_counts(bank):
                self._tick_fn(a)

    def aot_variant_keys(self):
        return [
            f"tick:S{b}:A{a}"
            for b in self.bank_ladder
            for a in self.warm_admit_counts(b)
        ]

    def aot_lower(self):
        return [
            (f"tick:S{b}:A{a}", self._tick_fn(a))
            for b in self.bank_ladder
            for a in self.warm_admit_counts(b)
        ]
