# corpus-rules: dtypeflow
"""Seeded CST-DTY violations: an unregistered cast inside traced code
(001), an implicit int-array x float-literal weak promotion (002),
unpinned matmuls on a registered low-precision path (003 — the corpus
test injects the ``low_precision=True`` CAST_REGISTRY entry for
``registered_low_precision``), and a donated parameter cast inside the
traced body (004).  Negative cases prove the rules stay quiet on
registered casts, float-side literals, pinned matmuls, and
un-donated casts."""

import jax
import jax.numpy as jnp


@jax.jit
def unregistered_cast(x):
    # a precision change reachable from a jit root, with no
    # CAST_REGISTRY entry saying which PARITY tier survives it
    return x.astype(jnp.bfloat16)  # expect: CST-DTY-001


@jax.jit
def weak_promotion(logits):
    tok = jnp.arange(8)
    # the interpreter PROVES tok is an i32 array; the bare float
    # literal silently floats it to the default float
    bad = tok * 0.5  # expect: CST-DTY-002
    # a second same-symbol violation: the baseline diff is count-aware
    bad2 = 2.5 - tok  # expect: CST-DTY-002
    # negative: float-array x literal keeps its dtype (weak rule)
    ok = jnp.zeros((8,), jnp.float32) * 0.5
    # negative: bool masks scaled by literals are idiomatic
    mask = tok > 3
    okm = mask * 1.0
    return bad, ok, okm


@jax.jit
def registered_low_precision(x, w):
    # the cast itself is registered (entry injected by the test) ...
    xc = x.astype(jnp.bfloat16)
    # ... but matmuls on a low-precision path must pin accumulation
    bad_op = xc @ w  # expect: CST-DTY-003
    bad_call = jnp.matmul(xc, w)  # expect: CST-DTY-003
    good = jnp.matmul(xc, w, preferred_element_type=jnp.float32)
    return bad_op + bad_call + good


def donated_step(state, batch):
    # dtype-cast of the donated buffer: XLA cannot alias mismatched
    # widths, so donation is silently disabled
    return state.astype(jnp.bfloat16) + batch  # expect: CST-DTY-001, CST-DTY-004


donated = jax.jit(donated_step, donate_argnums=(0,))


def undonated_step(state, batch):
    # negative: same cast, nothing donated -> only DTY-001 territory,
    # and this function is jitted with no donation kwargs
    return state.astype(jnp.float32) + batch  # expect: CST-DTY-001


undonated = jax.jit(undonated_step)


def host_helper(x):
    # negative: NOT reachable from any jit root -> no DTY-001
    return x.astype("float64")
