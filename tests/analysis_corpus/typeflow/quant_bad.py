# corpus-rules: dtypeflow
"""Seeded ISSUE-16 low-precision serving violations: an unregistered
quant cast reachable from a jit root (001 — weight-only int8 codes cast
to the activation dtype with no CAST_REGISTRY entry claiming a parity
tier for the rounding) and a decision-path vocab matmul on a registered
``relaxed-serving`` path missing its f32 accumulation pin (003 — the
corpus test injects the ``low_precision=True`` entry for
``registered_quant_path``).  The negative case proves the rules stay
quiet on the exact idiom ops/quant.py ships: registered cast, f32
accumulation pinned, per-channel scale applied after the accumulation."""

import jax
import jax.numpy as jnp


@jax.jit
def unregistered_quant_cast(q, scale):
    # int8 codes dequantized inline with no CAST_REGISTRY entry saying
    # which PARITY tier survives the quantization rounding
    return q.astype(jnp.bfloat16) * scale  # expect: CST-DTY-001


@jax.jit
def registered_quant_path(h, q, scale):
    # the cast sites are registered (relaxed-serving entry injected by
    # the test) ...
    hc = h.astype(jnp.bfloat16)
    # ... but the DECISION matmul — vocab logits feeding beam top-K —
    # must still pin f32 accumulation: applying the scale after a bf16
    # accumulator does not un-round it
    bad = jnp.matmul(hc, q.astype(jnp.bfloat16)) * scale  # expect: CST-DTY-003
    # negative: the ops/quant.py idiom — pinned f32 accumulation, scale
    # applied after, so decisions consume f32
    good = jnp.matmul(
        hc, q.astype(jnp.bfloat16), preferred_element_type=jnp.float32
    ) * scale
    return bad + good
