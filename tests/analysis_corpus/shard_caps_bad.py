# corpus-rules: partitioning
"""Seeded CST-SHD-005 violations against a toy kernel-capability
table: a declared ``use_pallas_*`` ModelConfig flag with NO caps row
plus a STALE caps row naming no declared flag (both anchor at the
``DECODE_KERNEL_CAPS`` assignment), and a ``_decode_kernel_gate``
function that hardcodes its mesh condition instead of consulting
``kernel_supports``.  The negative cases — the covered flag, the
helper that DOES consult the table — must not fire."""

from dataclasses import dataclass

DECODE_KERNEL_CAPS = {  # expect: CST-SHD-005
    "use_pallas_covered": {"model": True, "data": False},
    "use_pallas_ghost": {"model": False, "data": False},
}


def kernel_supports(flag, axis):
    caps = DECODE_KERNEL_CAPS.get(flag)
    return bool(caps and caps.get(axis, False))


@dataclass
class ModelConfig:
    use_pallas_covered: bool = False
    use_pallas_orphan: bool = False   # no caps row -> fires at the table
    other_field: int = 1


def _decode_kernel_gate(flag_name, mesh):  # expect: CST-SHD-005
    # Hardcoded mesh condition — never consults kernel_supports.
    if mesh is not None and mesh.shape.get("model", 1) > 1:
        return False
    return True


def negative_gate_through_table(flag_name, mesh):
    # A gate that routes through the caps table is the contract; this
    # helper (not named _decode_kernel_gate) must not fire either way.
    return kernel_supports(flag_name, "model")
