# corpus-rules: jit_boundary
"""Seeded host-state hazards inside traced code: decorated roots,
jit-by-call roots, transitive callees through the intra-file call
graph, and the traced-``if`` / set-iteration shapes."""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np


def _helper_with_clock(x):
    # traced TRANSITIVELY (called from bad_decorated below)
    t = time.monotonic()  # expect: CST-JIT-001
    return x + t


@jax.jit
def bad_decorated(x):
    print("tracing", x)  # expect: CST-JIT-001
    noise = np.random.rand()  # expect: CST-JIT-001
    y = _helper_with_clock(x) + noise
    if x > 0:  # expect: CST-JIT-002
        y = y * 2
    return y


@functools.partial(jax.jit, static_argnums=(1,))
def static_arg_ok(x, flag):
    # NEGATIVE case: `flag` is static_argnums-declared — branching on
    # it is fine and must NOT fire CST-JIT-002
    if flag:
        return x + 1
    return x


@jax.jit
def bad_sync(x):
    v = x.sum().item()  # expect: CST-JIT-001
    return x / v


@jax.jit
def bad_set_iteration(x):
    total = x
    for axis in {0, 1}:  # expect: CST-JIT-003
        total = total.sum(axis=axis)
    return total


def jitted_by_call(x, y):
    if y is None:  # NEGATIVE: is-None tests are host-static
        y = jnp.zeros_like(x)
    while x.ndim > 2:  # NEGATIVE: shape reads are host-static
        x = x.sum(0)
    if y:  # expect: CST-JIT-002
        x = x + y
    return x


run = jax.jit(jitted_by_call)
