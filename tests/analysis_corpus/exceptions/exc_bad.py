# corpus-rules: exceptions
"""Seeded silent-exception hazards on the threaded surface: a swallow
inside a worker loop, a swallow in a helper reachable through the call
graph, an uncontained thread target, and a lambda target — plus the
contained/logged/routing negative cases."""

import logging
import threading

log = logging.getLogger("corpus")


def silent_worker(q):
    # contained at the top level (outer handler logs), but the INNER
    # broad handler swallows — the queue consumer dies silently.
    try:
        while True:
            try:
                q.get()
            except Exception:  # expect: CST-EXC-001
                pass
    except Exception:
        log.exception("worker died")


def swallowing_helper(item):
    # reachable from contained_worker (a thread target) below
    try:
        return item.decode()
    except Exception:  # expect: CST-EXC-001
        return None


def uncontained_worker(q):  # expect: CST-EXC-002
    # no top-level try: an exception here kills the thread unlogged
    item = q.get()
    return swallowing_helper(item)


def contained_worker(q):
    try:
        while True:
            swallowing_helper(q.get())
    except Exception:
        log.exception("worker died")


def start_all(q):
    threading.Thread(target=silent_worker, args=(q,)).start()
    threading.Thread(target=uncontained_worker, args=(q,)).start()
    threading.Thread(target=contained_worker, args=(q,)).start()
    threading.Thread(target=lambda: q.get()).start()  # expect: CST-EXC-002


# --------------------------------------------------------------------
# NEGATIVE cases.


def unreachable_helper(item):
    # same swallow shape, but nothing threaded ever reaches it — a
    # request-path broad except answers to different contracts
    try:
        return item.decode()
    except Exception:
        return None


def routing_worker(q, settle):
    # the bound exception is ROUTED onward (the _settle_exception /
    # poison-pill pattern): not a swallow
    try:
        while True:
            q.get()
    except BaseException as e:
        settle(e)


def start_routing(q, settle):
    threading.Thread(target=routing_worker, args=(q, settle)).start()
