# corpus-rules: partitioning
"""Seeded CST-SHD violations against a toy rule table: a leaf matched
by two rules AND a leaf matched by none (both anchor CST-SHD-001 at the
KNOWN_PARAM_LEAVES assignment), a stale rule whose regex matches no
leaf (CST-SHD-003 at the rule's own line), an unregistered
``with_sharding_constraint`` call (CST-SHD-002), and an unregistered
``shard_map`` call (CST-SHD-004).  The negative cases —
``word_proj`` matching exactly one rule, the registered-looking helper
name used as a plain attribute, the shard_map-shaped attribute read —
must NOT fire."""

import jax

PARTITION_RULES = (
    (r"word_embed$", ("model", None)),
    (r"embed$", ()),
    (r"word_proj$", (None, "model")),
    (r"ghost_param$", ("model",)),  # expect: CST-SHD-003
)

KNOWN_PARAM_LEAVES = ("word_embed", "logit_w", "word_proj")  # expect: CST-SHD-001


def unregistered_constraint(x, sharding):
    return jax.lax.with_sharding_constraint(x, sharding)  # expect: CST-SHD-002


def negative_not_a_constraint(table):
    # attribute access / unrelated names must not trip the site scan
    return table.constraints


def unregistered_shard_map(body, mesh, specs):
    return jax.shard_map(body, mesh=mesh, in_specs=specs, out_specs=specs)  # expect: CST-SHD-004


def negative_not_a_shard_map(registry):
    # attribute reads of the name must not trip the site scan
    return registry.shard_map
