"""Seeded CST-OBS violations: a wall clock on a span path, an
unregistered span name, a flight event off the catalogue, and a tracer
call reachable from a jit-traced root.  Parsed, never imported."""
# corpus-rules: observability

import time

import jax


def emit_with_wall_clock(tracer):
    t0 = time.time()                                 # expect: CST-OBS-001
    # negative: registered name, monotonic clocks — must NOT fire
    tracer.record("request", t0, time.monotonic())
    tracer.record("totally_unregistered_span", 0.0, 1.0)  # expect: CST-OBS-002


def flight_bad(flight):
    # negative: a registered event name is fine
    flight.event("tick", admits=1)
    flight.event("not_an_event")                     # expect: CST-OBS-002


@jax.jit
def traced_step(x, tracer):
    tracer.record("tick_dispatch", 0.0, 1.0)         # expect: CST-OBS-003
    return x
