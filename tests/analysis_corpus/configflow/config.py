# corpus-rules: configflow
"""Corpus twin of the real config module: a miniature dataclass tree
the configflow checker resolves sections/fields from, seeded with a
dead knob (read nowhere in the corpus), an undocumented knob (absent
from the sibling docs/ANALYSIS.md catalogue), and a preset typo."""

from dataclasses import dataclass, field


@dataclass
class TrainConfig:
    learning_rate: float = 1e-4
    seed: int = 0
    dead_knob: int = 7  # expect: CST-CFG-002


@dataclass
class ServingConfig:
    port: int = 8000
    undocumented_knob: int = 1  # expect: CST-CFG-003


@dataclass
class Config:
    train: TrainConfig = field(default_factory=TrainConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)


def preset_ok():
    c = Config()
    c.train.seed = 5              # declared: fine
    return c


def preset_typo():
    c = Config()
    c.train.learning_rte = 1.0  # expect: CST-CFG-004
    return c
