# corpus-rules: configflow
"""Seeded config-read hazards against the sibling corpus config.py:
typo'd dotted reads, typo'd getattr string reads, typo'd alias reads —
plus the negative read shapes (direct, getattr, alias) that keep the
declared knobs alive."""


def read_knobs(cfg):
    lr = cfg.train.learning_rate          # declared: fine
    s = cfg.train.seed                    # declared: fine
    p = cfg.serving.port                  # declared: fine
    u = cfg.serving.undocumented_knob     # read, just not documented
    typo = cfg.train.learning_rte  # expect: CST-CFG-001
    g = getattr(cfg.serving, "prot", 0)  # expect: CST-CFG-001
    return lr, s, p, u, typo, g


def read_through_alias(cfg):
    sv = cfg.serving
    ok = sv.port                          # alias read: fine
    bad = sv.reqeue_budget  # expect: CST-CFG-001
    also_ok = getattr(sv, "port", 0)
    return ok, bad, also_ok


def read_through_param(serving_cfg):
    # section-typed parameter: the caller below passes cfg.serving
    return serving_cfg.port


def call_with_section(cfg):
    return read_through_param(cfg.serving)
