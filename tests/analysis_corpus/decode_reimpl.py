# corpus-rules: single_site
"""Seeded re-implementations of the decode recurrence — every pattern
the two retired grep fingerprints (tests/test_decode_core.py pre-PR-8)
used to catch, now as AST shapes the CST-DEC rules must flag in any
file outside the allowlists."""

import jax
import jax.numpy as jnp
from jax.lax import top_k as topk_alias

from cst_captioning_tpu.constants import EOS_ID, PAD_ID


def rogue_beam_select(total, K):
    scores, flat = jax.lax.top_k(total, K)  # expect: CST-DEC-001
    return scores, flat


def rogue_beam_select_aliased(total, K):
    # reformat/alias-resistant: the old grep needed the literal
    # ``top_k(`` token; the AST rule resolves the aliased callee too
    return topk_alias(total, K)  # expect: CST-DEC-001


def rogue_finish_update(tok, finished):
    return finished | (tok == EOS_ID) | (tok == PAD_ID)  # expect: CST-DEC-002


def rogue_finish_update_boolop(tok):
    return (tok == EOS_ID) or (tok == PAD_ID)  # expect: CST-DEC-002


def rogue_pad_eos_feed(tok):
    return jnp.where(tok == PAD_ID, EOS_ID, tok)  # expect: CST-DEC-003


def rogue_cache_replication(cache_row, K):
    # the PR-7 K-by memory regression: fanning cached decode state out
    # per beam row at admission
    return jnp.repeat(cache_row, K, axis=0)  # expect: CST-DEC-004
