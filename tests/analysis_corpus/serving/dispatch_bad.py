# corpus-rules: shapeflow
"""Seeded CST-SHP-001 data-dependent-dimension violation: a device
array created with a ``len(...)``-derived leading dim in serving
dispatch code — one XLA compile per distinct queue depth the moment it
meets a jit boundary.  The negative case routes the count through a
ladder bucket function first (``bucket`` is a registered quantizer
name), which launders the taint."""

import jax.numpy as jnp


def storm_dispatch(requests, width):
    n = len(requests)
    # the raw count becomes a device shape: a recompile storm
    bad = jnp.zeros((n, width))  # expect: CST-SHP-001
    return bad


def laddered_dispatch(engine, requests, width):
    # negative: the count is quantized onto the compiled ladder
    b = engine.bucket(len(requests))
    ok = jnp.zeros((b, width))
    # negative: host-side numpy assembly never compiles
    import numpy as np

    host = np.zeros((len(requests), width))
    return ok, host
