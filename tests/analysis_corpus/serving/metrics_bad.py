# corpus-rules: metrics_registry
"""Seeded unregistered-metric emission: a serving module exporting a
Prometheus series name that METRIC_FAMILIES doesn't know."""


def to_prometheus(value):
    lines = ["# TYPE caption_bogus_series_total counter"]
    lines.append(f"caption_bogus_series_total {value}")  # expect: CST-MET-001
    return "\n".join(lines)
