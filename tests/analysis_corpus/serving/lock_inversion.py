# corpus-rules: thread_safety
# corpus-expect-anywhere: CST-THR-001
"""Seeded lock-order inversion + unguarded shared-state mutation: a
worker thread takes lock_a then lock_b while the public submit surface
takes lock_b then lock_a (a latent deadlock the static pass must see),
and submit bumps a shared counter with no lock at all."""

import threading


class InvertedPair:
    def __init__(self):
        self.lock_a = threading.Lock()
        self.lock_b = threading.Lock()
        self.counter = 0
        self.workers = []
        for _ in range(2):
            t = threading.Thread(target=self._run)
            self.workers.append(t)

    def _run(self):
        with self.lock_a:
            with self.lock_b:
                self.counter += 1

    def submit(self, item):
        self.counter += 1  # expect: CST-THR-002
        with self.lock_b:
            with self.lock_a:
                return self.counter + item
