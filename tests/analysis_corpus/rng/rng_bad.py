# corpus-rules: rng
"""Seeded PRNG-key-discipline hazards: key reuse (straight-line and
loop flavors), untracked entropy (wall-clock seeds, free-name keys),
and rollout token draws outside the row-keyed allowlist — plus the
negative cases (split chains, fold_in loops, branch arms, module-level
roots) that must NOT fire."""

import time

import jax


def bad_double_draw(key, shape):
    a = jax.random.uniform(key, shape)
    b = jax.random.normal(key, shape)  # expect: CST-RNG-001
    return a + b


def bad_loop_reuse(key, xs):
    out = []
    for x in xs:
        out.append(jax.random.normal(key) + x)  # expect: CST-RNG-001
    return out


def bad_wallclock_seed(shape):
    key = jax.random.PRNGKey(int(time.time()))  # expect: CST-RNG-002
    return jax.random.uniform(key, shape)


def bad_untracked_key(shape):
    # `mystery_key` is bound nowhere: not a parameter, enclosing
    # scope, module global, or import.
    return jax.random.normal(mystery_key, shape)  # expect: CST-RNG-002


def bad_rollout_draw(key, logits):
    # token sampling outside decoding/core.py's row-keyed machinery
    return jax.random.categorical(key, logits)  # expect: CST-RNG-003


def bad_vmapped_rollout(keys, logits):
    return jax.vmap(jax.random.categorical)(keys, logits)  # expect: CST-RNG-003


# --------------------------------------------------------------------
# NEGATIVE cases: the idiomatic shapes every real call site uses.


def ok_split_chain(key, shape):
    k1, k2 = jax.random.split(key)
    return jax.random.uniform(k1, shape) + jax.random.normal(k2, shape)


def ok_fold_in_loop(key, xs):
    out = []
    for i, x in enumerate(xs):
        k = jax.random.fold_in(key, i)
        out.append(jax.random.normal(k) + x)
    return out


def ok_branch_arms(key, shape, flag):
    # mutually exclusive arms: one consumption per execution
    if flag:
        return jax.random.uniform(key, shape)
    else:
        return jax.random.normal(key, shape)


GLOBAL_ROOT = jax.random.PRNGKey(0)


def ok_module_level_root(shape):
    # a deterministic module-level root is tracked entropy
    return jax.random.uniform(GLOBAL_ROOT, shape)


def ok_closure_key(key):
    def inner(shape):
        # closure read of the enclosing function's parameter
        return jax.random.bernoulli(key, 0.5, shape)

    return inner


def ok_rederived_key(key, shape):
    a = jax.random.uniform(key, shape)
    key = jax.random.split(key)[0]
    b = jax.random.normal(key, shape)   # fresh binding: no reuse
    return a + b
