"""Seeded CST-RES violations: an unregistered fault site, an unguarded
injection point, and a chaos decision reachable from a jit-traced root.
Parsed, never imported."""
# corpus-rules: resilience

import jax


def unregistered_site(chaos):
    if chaos is not None:
        chaos.fire("spurious_site")                  # expect: CST-RES-001
    # negative: a registered site behind the same guard — must NOT fire
    if chaos is not None:
        chaos.fire("cache_miss")


def unguarded_fire(chaos):
    chaos.fire("tick_stall")                         # expect: CST-RES-002


def guarded_short_circuit(chaos):
    # negative: the `and` chain's left operand IS the guard
    if chaos is not None and chaos.fire("queue_burst"):
        return True
    return False


def guarded_truthiness(self):
    # negative: bare truthiness on a chaos-named attribute
    if self.chaos:
        self.chaos.fire("deadline_skew")


@jax.jit
def traced_fire(x, chaos):
    if chaos is not None:
        chaos.fire("replica_kill")                   # expect: CST-RES-003
    return x
