"""ISSUE 9: real 2D (data x model) sharding — partition rules over
params AND optimizer state, NamedSharding-in/out update steps,
cross-topology checkpoint reshard, model-sharded serving, and the
make_mesh 2D validation contract.  Runs on the 8-device virtual CPU
platform (tests/conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from cst_captioning_tpu.config import get_preset
from cst_captioning_tpu.data import BatchIterator, make_synthetic_dataset
from cst_captioning_tpu.models import model_from_config
from cst_captioning_tpu.parallel import (
    batch_sharding,
    make_mesh,
    mesh_shape_str,
    shard_batch,
)
from cst_captioning_tpu.parallel import partition
from cst_captioning_tpu.training import checkpoint as ckpt
from cst_captioning_tpu.training.steps import (
    create_train_state,
    make_optimizer,
    make_xe_train_step,
)


def _cfg(vocab_multiple=4, fusion="meanpool"):
    cfg = get_preset("synthetic_smoke")
    cfg.model.feature_fusion = fusion
    return cfg


def _world(cfg, vocab_multiple=4, batch_size=8):
    ds, _ = make_synthetic_dataset(
        num_videos=16, max_frames=cfg.data.max_frames, seed=7
    )
    v = len(ds.vocab)
    cfg.model.vocab_size = (
        (v + vocab_multiple - 1) // vocab_multiple * vocab_multiple
    )
    it = BatchIterator(
        ds, batch_size=batch_size, seq_per_img=2,
        max_frames=cfg.data.max_frames, shuffle=False,
    )
    batch = next(iter(it.epoch(0)))
    model = model_from_config(cfg)
    tx = make_optimizer(cfg.train, 10)
    return ds, model, tx, batch


# ------------------------------------------------------------- rule table

class TestPartitionRules:
    def test_known_leaves_cover_real_init_trees(self):
        """KNOWN_PARAM_LEAVES is the static mirror the CST-SHD analysis
        cross-checks — every leaf of every real init tree must appear in
        it, and every entry must exist in SOME real tree (no rot in
        either direction)."""
        seen = set()
        for fusion, cat, layers, serving_dtype in (
            ("meanpool", False, 1, None),
            ("attention", True, 2, None),
            # weight_quant tree (ISSUE 16): the int8w serving model adds
            # the *_scale leaves — they must be KNOWN and rule-covered.
            ("attention", True, 2, "int8w"),
        ):
            cfg = get_preset("synthetic_smoke")
            cfg.model.feature_fusion = fusion
            cfg.model.use_category = cat
            cfg.model.num_layers = layers
            cfg.model.vocab_size = 32
            cfg.data.feature_modalities = ["resnet", "c3d"]
            cfg.data.feature_dims = {"resnet": 16, "c3d": 16}
            m = model_from_config(cfg, serving_dtype=serving_dtype)
            feats = {
                k: jnp.zeros((1, 4, 16)) for k in ("resnet", "c3d")
            }
            masks = {k: jnp.ones((1, 4)) for k in feats}
            c = jnp.zeros((1,), jnp.int32) if cat else None
            params = m.init(
                jax.random.PRNGKey(0), feats, masks,
                jnp.zeros((1, 2), jnp.int32), category=c,
            )
            for path, _ in jax.tree_util.tree_flatten_with_path(params)[0]:
                leaf = partition.path_str(path).rsplit("/", 1)[-1]
                assert leaf in partition.KNOWN_PARAM_LEAVES, (
                    f"param leaf {leaf!r} missing from "
                    "KNOWN_PARAM_LEAVES — the CST-SHD static table "
                    "drifted from the model"
                )
                seen.add(leaf)
        # Speculative-decode draft tree (ISSUE 18): a REAL tree the
        # serving engine ships, so its leaves ride the same
        # no-rot-in-either-direction contract as the model trees.
        from cst_captioning_tpu.decoding.speculative import make_draft_params

        for leaf in make_draft_params(params, draft_hidden=4):
            assert leaf in partition.KNOWN_PARAM_LEAVES, (
                f"draft leaf {leaf!r} missing from KNOWN_PARAM_LEAVES"
            )
            seen.add(leaf)
        missing = set(partition.KNOWN_PARAM_LEAVES) - seen
        assert not missing, (
            f"KNOWN_PARAM_LEAVES entries {sorted(missing)} exist in no "
            "real init tree — stale static table"
        )

    def test_every_leaf_matches_exactly_one_rule(self):
        for leaf in partition.KNOWN_PARAM_LEAVES:
            partition.spec_for_leaf(leaf, strict=True)  # raises on 0/2+

    def test_strict_raises_on_unknown_and_ambiguous(self):
        with pytest.raises(ValueError, match="no partition rule"):
            partition.spec_for_leaf("mystery_tensor_w")
        dbl = ((r"embed$", ()), (r"word_embed$", ("model", None)))
        with pytest.raises(ValueError, match="matches 2"):
            partition.spec_for_leaf(
                "word_embed", rules=partition.compiled_rules(dbl)
            )

    def test_match_partition_rules_covers_opt_state(self):
        """The snippet-[3] port: ONE rule table specs params AND optax
        state — Adam moments mirror the param specs, scalar counters
        come back unpartitioned."""
        cfg = _cfg()
        _, model, tx, batch = _world(cfg)
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, batch._asdict()
        )
        specs = partition.match_partition_rules(
            partition.PARTITION_RULES, state
        )
        assert specs.params["params"]["word_embed"] == P("model", None)
        assert specs.params["params"]["logit_w"] == P(None, "model")
        moment_specs = [
            (partition.path_str(path), spec)
            for path, spec in jax.tree_util.tree_flatten_with_path(
                specs.opt_state, is_leaf=lambda x: isinstance(x, P)
            )[0]
        ]
        emb = [s for p, s in moment_specs if p.endswith("word_embed")]
        assert emb and all(s == P("model", None) for s in emb)
        counts = [s for p, s in moment_specs if "count" in p]
        assert counts and all(s == P() for s in counts)

    def test_state_shardings_divisibility_fallback(self):
        """A vocab that doesn't divide the model axis falls back to
        replication for THAT tensor only (correctness first)."""
        mesh = make_mesh({"data": 2, "model": 4})
        tree = {
            "word_embed": jnp.zeros((10, 8)),   # 10 % 4 != 0 -> P()
            "logit_w": jnp.zeros((8, 16)),      # 16 % 4 == 0 -> sharded
        }
        sh = partition.tree_shardings(tree, mesh)
        assert sh["word_embed"].spec == P()
        assert sh["logit_w"].spec == P(None, "model")


# ---------------------------------------------- sharded update-step jits

class TestShardedUpdateStep:
    def test_named_sharding_step_matches_default_jit(self):
        """The NamedSharding-in/out XE jit on a 2x4 mesh: same losses
        and (tolerance-tier, PARITY r12) same params as the default
        single-device jit, params/moments actually sharded in the
        OUTPUT state, donation preserved in the lowered computation."""
        cfg = _cfg()
        _, model, tx, batch = _world(cfg)
        rng = jax.random.PRNGKey(0)
        step_rng = jax.random.PRNGKey(1)
        ones = jnp.ones_like(jnp.asarray(batch.weights))

        s1 = create_train_state(rng, model, tx, batch._asdict())
        step1 = make_xe_train_step(model)
        s1b, m1 = step1(
            s1, batch.feats, batch.feat_masks, batch.captions, ones,
            None, batch.video_idx, step_rng, 0.0,
        )

        mesh = make_mesh({"data": 2, "model": 4})
        s2 = create_train_state(
            rng, model, tx, batch._asdict(), mesh=mesh
        )
        step2 = make_xe_train_step(model, mesh=mesh, state_template=s2)
        sh = batch_sharding(mesh)
        args2 = (
            shard_batch(batch.feats, mesh),
            shard_batch(batch.feat_masks, mesh),
            jax.device_put(batch.captions, sh),
            jax.device_put(np.ones_like(batch.weights), sh),
            None,
            jax.device_put(batch.video_idx, sh),
        )
        lowered = step2.lower(s2, *args2, step_rng, 0.0)
        assert "tf.aliasing_output" in lowered.as_text()  # donation kept
        s2b, m2 = step2(s2, *args2, step_rng, 0.0)

        np.testing.assert_allclose(
            float(m1["loss"]), float(m2["loss"]), rtol=1e-5
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-5
            ),
            s1b.params, s2b.params,
        )
        # The OUTPUT state keeps the rule-table shardings (out_shardings
        # contract): vocab tensors + Adam moments over model.
        assert s2b.params["params"]["word_embed"].sharding.spec == P(
            "model", None
        )
        mus = [
            leaf
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                s2b.opt_state
            )[0]
            if partition.path_str(path).endswith("word_embed")
        ]
        assert mus and all(
            leaf.sharding.spec == P("model", None) for leaf in mus
        )


# ------------------------------------------- cross-topology reshard

class TestCrossTopologyReshard:
    @pytest.fixture(scope="class")
    def saved(self, tmp_path_factory):
        """One XE state trained a step on a 1x1 mesh, checkpointed."""
        cfg = _cfg()
        _, model, tx, batch = _world(cfg)
        mesh1 = make_mesh(
            {"data": 1, "model": 1}, devices=jax.devices()[:1]
        )
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, batch._asdict(), mesh=mesh1
        )
        step = make_xe_train_step(model, mesh=mesh1, state_template=state)
        sh = batch_sharding(mesh1)
        args = (
            shard_batch(batch.feats, mesh1),
            shard_batch(batch.feat_masks, mesh1),
            jax.device_put(batch.captions, sh),
            jax.device_put(
                np.ones_like(np.asarray(batch.weights)), sh
            ),
            None,
            jax.device_put(batch.video_idx, sh),
        )
        state, _ = step(state, *args, jax.random.PRNGKey(1), 0.0)
        path = str(tmp_path_factory.mktemp("reshard") / "ck")
        ckpt.save_checkpoint(path, state, {"epoch": 0})
        ref = jax.tree.map(np.asarray, state.params)
        return cfg, model, tx, batch, path, ref

    def _load_and_step(self, saved, shape):
        cfg, model, tx, batch, path, ref = saved
        n = shape[0] * shape[1]
        mesh = make_mesh(
            {"data": shape[0], "model": shape[1]},
            devices=jax.devices()[:n],
        )
        template = create_train_state(
            jax.random.PRNGKey(0), model, tx, batch._asdict(), mesh=mesh
        )
        restored = ckpt.restore_checkpoint(path, template)
        # Bit-identical gathered params: a reshard is a layout change,
        # never an arithmetic one.
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), b
            ),
            restored.params, ref,
        )
        # ...and every leaf landed with the template's sharding.
        emb = restored.params["params"]["word_embed"]
        want = template.params["params"]["word_embed"].sharding
        assert emb.sharding == want
        # Green next training step on the NEW topology.
        step = make_xe_train_step(
            model, mesh=mesh, state_template=restored
        )
        sh = batch_sharding(mesh)
        args = (
            shard_batch(batch.feats, mesh),
            shard_batch(batch.feat_masks, mesh),
            jax.device_put(batch.captions, sh),
            jax.device_put(
                np.ones_like(np.asarray(batch.weights)), sh
            ),
            None,
            jax.device_put(batch.video_idx, sh),
        )
        restored, metrics = step(
            restored, *args, jax.random.PRNGKey(2), 0.0
        )
        assert np.isfinite(float(metrics["loss"]))

    @pytest.mark.parametrize("shape", [(2, 1), (1, 2), (2, 2)])
    def test_1x1_checkpoint_loads_on_2d_meshes(self, saved, shape):
        self._load_and_step(saved, shape)

    @pytest.mark.slow
    @pytest.mark.parametrize("shape", [(2, 4), (4, 2)])
    def test_8_device_sweep(self, saved, shape):
        self._load_and_step(saved, shape)

    def test_sidecar_records_mesh_and_specs(self, saved):
        cfg, model, tx, batch, path, ref = saved
        meta = ckpt.saved_sharding(path)
        assert meta.get("mesh_shape") == "1x1"
        assert meta.get("mesh_axes") == ["data", "model"]
        specs = meta.get("specs", {})
        assert any(k.endswith("word_embed") for k in specs)


# --------------------------------------------------- make_mesh validation

class TestMeshValidation:
    def test_non_divisible_wildcard_names_axes(self):
        with pytest.raises(ValueError, match="cannot absorb"):
            make_mesh({"data": -1, "model": 3})

    def test_oversized_mesh_names_shape(self):
        with pytest.raises(ValueError, match="needs 16 devices"):
            make_mesh({"data": 4, "model": 4})

    def test_zero_axis_rejected(self):
        with pytest.raises(ValueError, match="must be positive"):
            make_mesh({"data": 0, "model": 2})

    def test_empty_shape_rejected(self):
        with pytest.raises(ValueError, match="empty"):
            make_mesh({})

    def test_two_wildcards_rejected(self):
        with pytest.raises(ValueError, match="at most one -1"):
            make_mesh({"data": -1, "model": -1})

    def test_deterministic_device_order(self):
        devs = list(jax.devices())
        mesh_a = make_mesh({"data": 2, "model": 2}, devices=devs[:4])
        mesh_b = make_mesh(
            {"data": 2, "model": 2}, devices=list(reversed(devs[:4]))
        )
        assert [
            d.id for d in mesh_a.devices.flat
        ] == [d.id for d in mesh_b.devices.flat]

    def test_mesh_shape_str(self):
        assert mesh_shape_str(make_mesh({"data": 2, "model": 4})) == "2x4"
        assert mesh_shape_str(None) == "1x1"

    def test_submesh_groups_deterministic(self):
        from cst_captioning_tpu.parallel.mesh import submesh_groups

        devs = list(jax.devices())
        a = submesh_groups(devs, 2)
        b = submesh_groups(list(reversed(devs)), 2)
        assert len(a) == len(devs) // 2
        assert [[d.id for d in g] for g in a] == [
            [d.id for d in g] for g in b
        ]
        assert [d.id for d in a[0]] == [0, 1]
        with pytest.raises(ValueError, match="group size"):
            submesh_groups(devs, 0)

    def test_rows_sharding_spec_rule(self):
        from cst_captioning_tpu.parallel.partition import rows_sharding

        dp = make_mesh({"data": 2, "model": 2})
        assert rows_sharding(dp, (8, 3, 12)).spec == P("data", None, None)
        assert rows_sharding(dp, (2, 8, 16), row_axis=1).spec == P(
            None, "data", None
        )
        # non-divisible rows and data=1 meshes both replicate
        assert rows_sharding(dp, (7, 3)).spec == P()
        tp = make_mesh(
            {"data": 1, "model": 2}, devices=jax.devices()[:2]
        )
        assert rows_sharding(tp, (8, 3)).spec == P()


# ------------------------------------------------- model-sharded serving

class TestModelShardedServing:
    @pytest.fixture(scope="class")
    def tp_world(self):
        from cst_captioning_tpu.data.build import build_dataset
        from cst_captioning_tpu.serving.engine import InferenceEngine

        cfg = get_preset("synthetic_smoke")
        cfg.serving.warmup = False
        cfg.serving.batch_shapes = [2]
        cfg.serving.max_batch_size = 2
        cfg.eval.beam_size = 2
        cfg.eval.max_decode_len = 8
        ds, vocab = build_dataset(cfg, cfg.eval.eval_split)
        cfg.model.vocab_size = (len(vocab) + 1) // 2 * 2  # model-axis even
        base = InferenceEngine(cfg, random_init=True, vocab=vocab)

        import copy

        cfg_tp = copy.deepcopy(cfg)
        cfg_tp.serving.model_shards = 2
        tp = InferenceEngine(cfg_tp, params=base.params, vocab=vocab)
        payloads = [
            {
                "features": {
                    m: a.tolist() for m, a in ds.features(i).items()
                },
                "feature_id": f"tp{i}",
            }
            for i in range(2)
        ]
        return base, tp, payloads

    def test_tp_engine_tokens_match_replicated(self, tp_world):
        """serving.model_shards=2: one logical replica over a (1, 2)
        mesh serves the SAME captions as the replicated engine — the
        column-sharded vocab matmul preserves per-column reduction
        order (PARITY r12 serving contract)."""
        base, tp, payloads = tp_world
        assert tp.tp_mesh is not None
        assert tp.describe()["mesh_shape"] == "1x2"
        # vocab params actually sharded: half the bytes per device
        w_base = base.params["params"]["logit_w"]
        w_tp = tp.params["params"]["logit_w"]
        assert (
            w_tp.addressable_shards[0].data.nbytes * 2 == w_base.nbytes
        )
        r_base = base.decode_prepared(
            [base.prepare(p) for p in payloads], store=False
        )
        r_tp = tp.decode_prepared(
            [tp.prepare(p) for p in payloads], store=False
        )
        for a, b in zip(r_base, r_tp):
            assert a.caption == b.caption
            np.testing.assert_array_equal(
                np.asarray(a.tokens), np.asarray(b.tokens)
            )

    def test_model_shards_gating(self):
        """(R, M) grid validation (ISSUE 14): a grid that doesn't fit
        the local device count refuses at engine boot with a message
        naming both axes; an M alone exceeding the device count keeps
        its own message.  replicas x shards that FIT no longer refuse
        (the lifted PR-9 restriction — TestReplicaShardGrid serves
        through one)."""
        from cst_captioning_tpu.data.build import build_dataset
        from cst_captioning_tpu.serving.engine import InferenceEngine

        cfg = get_preset("synthetic_smoke")
        cfg.serving.warmup = False
        _, vocab = build_dataset(cfg, cfg.eval.eval_split)
        bad = get_preset("synthetic_smoke")
        bad.serving.warmup = False
        bad.serving.model_shards = 2
        bad.serving.replicas = 5          # 5 x 2 = 10 > 8 virtual devs
        with pytest.raises(
            ValueError, match=r"serving grid replicas=5 x model_shards=2"
        ):
            InferenceEngine(bad, random_init=True, vocab=vocab)
        worse = get_preset("synthetic_smoke")
        worse.serving.warmup = False
        worse.serving.model_shards = 99
        with pytest.raises(ValueError, match="needs that many devices"):
            InferenceEngine(worse, random_init=True, vocab=vocab)

    def test_tp_engine_refuses_clone(self, tp_world):
        _, tp, _ = tp_world
        with pytest.raises(ValueError, match="cannot be cloned"):
            tp.clone_for_device(jax.devices()[0])

    def test_submesh_clone_validates_group_size(self, tp_world):
        _, tp, _ = tp_world
        with pytest.raises(ValueError, match="exactly model_shards"):
            tp.clone_for_submesh(jax.devices()[:3])


# ------------------------------------------- replica x shard serving grid

class TestReplicaShardGrid:
    """ISSUE 14 acceptance: an (R>=2, M>=2) grid — data-parallel
    replicas OF model-sharded engines on deterministic per-replica
    submeshes — serves token-exact vs the offline eval path on the
    virtual multi-device CPU mesh."""

    @pytest.fixture(scope="class")
    def grid_world(self):
        import threading
        import time as _time

        from cst_captioning_tpu.data.build import build_dataset
        from cst_captioning_tpu.evaluation import beam_decode_dataset
        from cst_captioning_tpu.serving.engine import InferenceEngine

        cfg = get_preset("synthetic_smoke")
        cfg.serving.warmup = False
        cfg.serving.num_slots = 4
        cfg.serving.default_deadline_ms = 120_000.0
        ds, vocab = build_dataset(cfg, cfg.eval.eval_split)
        cfg.model.vocab_size = (len(vocab) + 1) // 2 * 2
        base = InferenceEngine(cfg, random_init=True, vocab=vocab)
        offline = beam_decode_dataset(base.model, base.params, ds, cfg)

        import copy

        cfg_grid = copy.deepcopy(cfg)
        cfg_grid.serving.model_shards = 2
        cfg_grid.serving.replicas = 2
        grid = InferenceEngine(cfg_grid, params=base.params, vocab=vocab)
        payloads = [
            {"features": {m: a.tolist() for m, a in ds.features(i).items()}}
            for i in range(8)
        ]
        return grid, ds, offline, payloads

    def test_grid_serves_token_exact_vs_offline(self, grid_world):
        import threading
        import time as _time

        from cst_captioning_tpu.serving.replicas import ReplicaSet

        grid, ds, offline, payloads = grid_world
        rs = ReplicaSet.from_engine(grid, n_replicas=2)
        # Deterministic submesh assignment: replica i on the id-sorted
        # contiguous device group [i*M, (i+1)*M).
        assert len(rs.replicas) == 2
        for i, rep in enumerate(rs.replicas):
            tp = rep.engine.tp_mesh
            assert tp is not None and tp.shape["model"] == 2
            ids = sorted(d.id for d in tp.devices.flat)
            assert ids == [2 * i, 2 * i + 1], (i, ids)
        grid.cache.captions.clear()
        results, errors = {}, []
        lock = threading.Lock()

        def client(i):
            try:
                out = rs.submit(dict(payloads[i]), deadline_ms=120_000.0)
                with lock:
                    results[i] = out
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append((i, repr(e)))

        with rs:
            threads = [
                threading.Thread(target=client, args=(i,))
                for i in range(len(payloads))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120.0)
        assert not errors, errors
        assert len(results) == len(payloads)
        for i in range(len(payloads)):
            assert results[i]["caption"] == offline[ds.video_id(i)], (
                f"video {i} (replica {results[i].get('replica')}): "
                "grid decode diverged from offline beam"
            )
        used = {results[i].get("replica") for i in results}
        assert len(used) == 2, f"only replicas {used} served"
