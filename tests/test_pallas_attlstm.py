"""Fused attention+LSTM recurrence kernel: forward/backward parity vs the
XLA scan reference (interpret mode on CPU) and model-level equivalence of
the fused attention captioner forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.ops.pallas_attlstm import (
    attlstm_recurrence,
    attlstm_scan,
    attlstm_shapes_ok,
)


def make_inputs(B=16, T=7, H=64, A=32, E=48, F=11, seed=0,
                dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    sc = 0.3
    gx = jnp.asarray(rng.randn(B, T, 4 * H) * sc, jnp.float32)
    wh = jnp.asarray(rng.randn(H, 4 * H) * sc / np.sqrt(H), dtype)
    w_ctx = jnp.asarray(rng.randn(E, 4 * H) * sc / np.sqrt(E), dtype)
    att_wh = jnp.asarray(rng.randn(H, A) * sc, dtype)
    att_v = jnp.asarray(rng.randn(A, 1) * 0.1, dtype)
    att_proj = jnp.asarray(rng.randn(B, F, A) * sc, dtype)
    att_mask = jnp.asarray((rng.rand(B, F) > 0.2), jnp.float32)
    # Every row keeps at least one live frame (all-masked rows are not a
    # real decode state).
    att_mask = att_mask.at[:, 0].set(1.0)
    att_vals = jnp.asarray(rng.randn(B, F, E) * sc, dtype)
    return gx, wh, w_ctx, att_wh, att_v, att_proj, att_mask, att_vals


class TestKernelParity:
    def test_forward_matches_scan(self):
        args = make_inputs()
        ref = attlstm_scan(*args)
        got = attlstm_recurrence(*args)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_forward_score_mxu_variant_matches_scan(self, monkeypatch):
        """SCORE_MXU=True (the VERDICT r4 #6 counter-attempt: score
        reduction as an MXU matvec) must be numerically interchangeable
        with the default VPU reduce.  The env var is read once at module
        import (ADVICE r5 #3), so the test patches the module attribute
        — eager calls re-trace and pick it up."""
        import cst_captioning_tpu.ops.pallas_attlstm as mod

        monkeypatch.setattr(mod, "SCORE_MXU", True)
        args = make_inputs(seed=4)
        ref = attlstm_scan(*args)
        got = attlstm_recurrence(*args)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_forward_batch_tiles(self):
        # B=24 -> bt=24 (one tile); B=48 -> bt=24, a 2-tile grid that
        # exercises the per-tile h/c scratch re-zeroing at program_id==0.
        for B in (24, 48):
            args = make_inputs(B=B, seed=B)
            np.testing.assert_allclose(
                np.asarray(attlstm_recurrence(*args)),
                np.asarray(attlstm_scan(*args)),
                rtol=1e-5, atol=1e-5,
            )

    def test_backward_multi_tile(self):
        # B=48 -> bwd bt=16: a 3-tile grid exercising the cross-tile dv
        # accumulation ((b==0)&(tr==0) init) and per-tile dproj/dvals
        # accumulator re-zeroing.
        args = make_inputs(B=48, seed=9)

        def loss(fn, *a):
            return jnp.sum(fn(*a).astype(jnp.float32) ** 2)

        argnums = tuple(range(len(args)))
        gref = jax.grad(lambda *a: loss(attlstm_scan, *a), argnums)(*args)
        gker = jax.grad(
            lambda *a: loss(attlstm_recurrence, *a), argnums
        )(*args)
        for name, a, b in zip(
            ["gx", "wh", "w_ctx", "att_wh", "att_v", "att_proj",
             "att_mask", "att_vals"], gref, gker,
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
                err_msg=f"cotangent mismatch for {name}",
            )

    def test_backward_matches_scan_autodiff(self):
        args = make_inputs(seed=3)

        def loss(fn, *a):
            return jnp.sum(fn(*a).astype(jnp.float32) ** 2)

        argnums = tuple(range(len(args)))
        gref = jax.grad(lambda *a: loss(attlstm_scan, *a), argnums)(*args)
        gker = jax.grad(
            lambda *a: loss(attlstm_recurrence, *a), argnums
        )(*args)
        names = ["gx", "wh", "w_ctx", "att_wh", "att_v", "att_proj",
                 "att_mask", "att_vals"]
        for name, a, b in zip(names, gref, gker):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
                err_msg=f"cotangent mismatch for {name}",
            )

    def test_masked_frames_cannot_leak(self):
        args = list(make_inputs(seed=4))
        ref = attlstm_recurrence(*args)
        mask, vals = args[6], args[7]
        args[7] = jnp.where(mask[..., None] > 0, vals, 1e3)
        np.testing.assert_allclose(
            np.asarray(attlstm_recurrence(*args)), np.asarray(ref),
            rtol=1e-5, atol=1e-5,
        )

    def test_shapes_gate(self):
        # interpret mode: only batch divisibility applies
        assert attlstm_shapes_ok(16, 64, 32, 48, 11)
        assert not attlstm_shapes_ok(7, 64, 32, 48, 11)
        assert not attlstm_shapes_ok(12, 64, 32, 48, 11)

    def test_shapes_gate_tpu_rules(self, monkeypatch):
        """On a TPU backend the gate must also enforce 128-lane minor
        dims AND reject frame counts whose smallest backward tile busts
        the VMEM budget (falling back to the scan path instead of
        failing to allocate at compile time)."""
        import cst_captioning_tpu.ops.pallas_attlstm as mod

        monkeypatch.setattr(mod, "_interpret", lambda: False)
        # Flagship shape: fits.
        assert mod.attlstm_shapes_ok(1280, 512, 512, 512, 56, 2)
        # Non-128-multiple lanes: rejected.
        assert not mod.attlstm_shapes_ok(1280, 512, 192, 512, 56, 2)
        # Very large concatenated frame axis: the bt=8 backward tile
        # exceeds the VMEM budget -> scan fallback.
        assert not mod.attlstm_shapes_ok(1280, 512, 512, 512, 512, 2)


class TestModelIntegration:
    def _build(self, use_fused):
        from cst_captioning_tpu.models.captioner import CaptionModel

        model = CaptionModel(
            vocab_size=120,
            rnn_size=64,
            embed_size=48,
            fusion="attention",
            att_hidden_size=32,
            modalities=("resnet", "c3d"),
            feature_dims=(96, 64),
            use_category=True,
            num_categories=5,
            category_embed_size=8,
            compute_dtype="float32",
            use_pallas_attention=use_fused,
        )
        rng = np.random.RandomState(11)
        B, Fm, T = 16, 6, 9
        feats = {
            "resnet": jnp.asarray(rng.randn(B, Fm, 96), jnp.float32),
            "c3d": jnp.asarray(rng.randn(B, Fm, 64), jnp.float32),
        }
        masks = {
            "resnet": jnp.ones((B, Fm), jnp.float32),
            "c3d": jnp.ones((B, Fm), jnp.float32),
        }
        cat = jnp.asarray(rng.randint(0, 5, B), jnp.int32)
        ids = jnp.asarray(rng.randint(1, 120, (B, T)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), feats, masks, ids,
                            category=cat)
        return model, params, feats, masks, cat, ids

    def test_fused_forward_matches_scan_path(self):
        model_f, params, feats, masks, cat, ids = self._build(True)
        model_s, *_ = self._build(False)
        out_f = model_f.apply(params, feats, masks, ids, category=cat)
        out_s = model_s.apply(params, feats, masks, ids, category=cat)
        np.testing.assert_allclose(
            np.asarray(out_f), np.asarray(out_s), rtol=2e-4, atol=2e-4
        )

    def test_fused_grads_match_scan_path(self):
        model_f, params, feats, masks, cat, ids = self._build(True)
        model_s, *_ = self._build(False)

        def loss(model, p):
            out = model.apply(p, feats, masks, ids, category=cat)
            return jnp.mean(out.astype(jnp.float32) ** 2)

        gf = jax.grad(lambda p: loss(model_f, p))(params)
        gs = jax.grad(lambda p: loss(model_s, p))(params)
        flat_f = jax.tree_util.tree_leaves_with_path(gf)
        flat_s = {tuple(str(k) for k in path): v
                  for path, v in jax.tree_util.tree_leaves_with_path(gs)}
        for path, v in flat_f:
            key = tuple(str(k) for k in path)
            np.testing.assert_allclose(
                np.asarray(v), np.asarray(flat_s[key]),
                rtol=5e-4, atol=5e-4, err_msg=f"grad mismatch at {key}",
            )

    def test_scheduled_sampling_keeps_scan_path(self):
        # ss_prob > 0 must not take the fused path (it has no per-step
        # sampling); just check it still runs and differs from ss=0.
        model_f, params, feats, masks, cat, ids = self._build(True)
        out = model_f.apply(
            params, feats, masks, ids, category=cat, ss_prob=0.5,
            rng=jax.random.PRNGKey(3),
        )
        assert out.shape == (16, 9, 120)
