"""Porter stemmer golden cases (from Porter's published vocabulary examples)."""

import pytest

from cst_captioning_tpu.metrics.porter import porter_stem

CASES = [
    ("caresses", "caress"), ("ponies", "poni"), ("caress", "caress"),
    ("cats", "cat"), ("feed", "feed"), ("agreed", "agre"),
    ("plastered", "plaster"), ("bled", "bled"), ("motoring", "motor"),
    ("sing", "sing"), ("conflated", "conflat"), ("troubled", "troubl"),
    ("sized", "size"), ("hopping", "hop"), ("tanned", "tan"),
    ("falling", "fall"), ("hissing", "hiss"), ("fizzed", "fizz"),
    ("failing", "fail"), ("filing", "file"), ("happy", "happi"),
    ("sky", "sky"), ("relational", "relat"), ("conditional", "condit"),
    ("rational", "ration"), ("valenci", "valenc"), ("digitizer", "digit"),
    ("conformabli", "conform"), ("radicalli", "radic"),
    ("differentli", "differ"), ("vileli", "vile"), ("analogousli", "analog"),
    ("vietnamization", "vietnam"), ("predication", "predic"),
    ("operator", "oper"), ("feudalism", "feudal"),
    ("decisiveness", "decis"), ("hopefulness", "hope"),
    ("callousness", "callous"), ("formaliti", "formal"),
    ("sensitiviti", "sensit"), ("sensibiliti", "sensibl"),
    ("triplicate", "triplic"), ("formative", "form"), ("formalize", "formal"),
    ("electriciti", "electr"), ("electrical", "electr"), ("hopeful", "hope"),
    ("goodness", "good"), ("revival", "reviv"), ("allowance", "allow"),
    ("inference", "infer"), ("airliner", "airlin"), ("gyroscopic", "gyroscop"),
    ("adjustable", "adjust"), ("defensible", "defens"), ("irritant", "irrit"),
    ("replacement", "replac"), ("adjustment", "adjust"), ("dependent", "depend"),
    ("adoption", "adopt"), ("homologou", "homolog"), ("communism", "commun"),
    ("activate", "activ"), ("angulariti", "angular"), ("homologous", "homolog"),
    ("effective", "effect"), ("bowdlerize", "bowdler"),
    ("probate", "probat"), ("rate", "rate"), ("cease", "ceas"),
    ("controll", "control"), ("roll", "roll"),
    # caption-domain words
    # original-spec Porter applies (*v*) Y -> I, so play -> plai
    ("running", "run"), ("playing", "plai"), ("plays", "plai"),
    ("cooking", "cook"), ("jumps", "jump"), ("dancing", "danc"),
]


@pytest.mark.parametrize("word,stem", CASES)
def test_porter(word, stem):
    assert porter_stem(word) == stem
