"""Multi-host smoke test: two real CPU processes under jax.distributed.

Covers SURVEY.md §5 "Distributed comm backend" beyond the in-process
8-device simulation: cross-process batch assembly
(``put_host_batch`` / ``make_array_from_process_local_data``), a psum
over the global mesh, checkpoint save/restore with orbax's multi-process
coordination, and the rank-0 guard on the json sidecar.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json
import os
import sys

import numpy as np

port, pid, tmp = sys.argv[1], int(sys.argv[2]), sys.argv[3]
import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4, jax.device_count()  # 2 local x 2 procs

import jax.numpy as jnp
import optax
from flax.training.train_state import TrainState
from jax.sharding import NamedSharding, PartitionSpec as P

from cst_captioning_tpu.parallel import make_mesh
from cst_captioning_tpu.parallel.sharding import put_host_batch
from cst_captioning_tpu.training import checkpoint as ckpt

mesh = make_mesh({"data": 4, "model": 1})
sh = NamedSharding(mesh, P("data"))

# --- cross-process global batch assembly + collective ---------------------
# Global batch = [0..7]; each process contributes its contiguous half.
local = np.arange(4, dtype=np.float32) + 4.0 * pid
g = put_host_batch(local, sh)
assert g.shape == (8,)
total = jax.jit(lambda x: jnp.sum(x))(g)
assert float(total) == float(np.arange(8).sum()), float(total)

# --- checkpoint save/restore with multi-process orbax ---------------------
params = {"w": jax.device_put(jnp.ones((4, 2)), NamedSharding(mesh, P()))}
state = TrainState.create(
    apply_fn=lambda *a: None, params=params, tx=optax.sgd(0.1)
)
path = os.path.join(tmp, "ckpt")
ckpt.save_checkpoint(path, state, extra={"epoch": 3, "rank": pid})
from jax.experimental import multihost_utils

multihost_utils.sync_global_devices("infos-written")  # rank 0 wrote sidecar
# rank-0 guard: exactly one process wrote the sidecar, with ITS payload
infos = ckpt.load_infos(path)
assert infos["epoch"] == 3 and infos["rank"] == 0, infos

state2 = state.replace(params={"w": params["w"] * 0.0})
state2 = ckpt.restore_checkpoint(path, state2)
np.testing.assert_allclose(np.asarray(state2.params["w"]), 1.0)

print(f"worker {pid} ok")
"""


def test_two_process_distributed(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, str(port), str(pid), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    timed_out = False
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                timed_out = True
                out = ""
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if timed_out:
        # Deterministic environment gate (PR-6 seed-run flake): on a
        # contended/1-core host the two jax processes can starve each
        # other through the coordination handshake and never reach the
        # collective within the budget.  That is a property of the
        # host, not of the bootstrap code — skip with the reason
        # instead of going intermittently red.
        pytest.skip(
            "2-process jax.distributed workers exceeded the 300s "
            "budget — host too contended for a multiprocess smoke"
        )
    if any(
        "Multiprocess computations aren't implemented on the CPU backend"
        in out
        for out in outs
    ):
        # Environment gate, not a code failure: some jaxlib builds ship
        # a CPU backend without cross-process collectives, so the
        # 2-process bootstrap cannot be exercised here at all.  The
        # bootstrap logic itself (idempotent init, port handshake) still
        # ran up to the first collective.
        pytest.skip(
            "jaxlib CPU backend lacks multiprocess collectives in this "
            "environment"
        )
    if any(
        "DEADLINE_EXCEEDED" in out or "Coordination service" in out
        for out in outs
    ) and any(p.returncode != 0 for p in procs):
        # The coordination-service handshake itself timed out (slow /
        # overloaded host): the same environment condition as above,
        # surfaced by the runtime instead of our timeout.
        pytest.skip(
            "jax coordination-service handshake timed out in this "
            "environment"
        )
    killed = [
        (pid, p.returncode)
        for pid, p in enumerate(procs)
        if p.returncode is not None and p.returncode < 0
    ]
    if killed:
        # A worker was killed by an external signal (rc = -signum:
        # OOM-killer SIGKILL, CI process-group SIGTERM) — the test
        # sends no signals, so this is the environment reclaiming
        # resources, not a code failure.
        pytest.skip(
            f"distributed workers killed by external signal {killed} "
            "(resource-constrained environment)"
        )
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"worker {pid} ok" in out


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
