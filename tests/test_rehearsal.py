"""End-to-end rehearsal tool: the full fabricate -> prep -> pack ->
XE/WXE/CST pipeline -> beam eval chain at tiny scale, plus the corpus
generator's structural guarantees (generic trap, scene mix, sweep-mode
manifest)."""

import json

import numpy as np
import pytest

from cst_captioning_tpu.tools.rehearsal import _GENERIC, fabricate, main


def test_rehearsal_end_to_end(tmp_path, capsys):
    rc = main([
        "--out-dir", str(tmp_path / "r"),
        "--videos", "16",
        "--epochs", "1",
        "--batch-size", "8",  # conftest's 8-device mesh shards the batch
        "--max-frames", "4",
        "--max-words", "8",
        "--beam-size", "2",
        "--cst-samples", "3",
        "--feature-dims", "resnet=16,c3d=8",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["videos"] == 16
    for stage in ("xe", "wxe", "cst"):
        assert stage in summary["stages"]
        bc = summary["stages"][stage]["best_val_cider"]
        assert bc is not None and np.isfinite(bc)
    assert np.isfinite(summary["stages"]["cst"]["final_reward"])
    scores = summary["test_scores"]
    assert {"Bleu_4", "METEOR", "ROUGE_L", "CIDEr"} <= set(scores)
    assert scores["METEOR_backend"] in ("java", "lite", "lite+syn")
    # artifacts on disk: packed store, prep outputs, staged checkpoints
    assert (tmp_path / "r" / "packed" / "resnet.npy").exists()
    assert (tmp_path / "r" / "prep" / "consensus_train.json").exists()
    assert (tmp_path / "r" / "checkpoints" / "rehearsal_cst").exists()
    # sweep-mode manifest written last (certifies prep+pack completed)
    assert (tmp_path / "r" / "prep" / "manifest.json").exists()


class TestFabricate:
    def test_generic_block_and_consensus_structure(self, tmp_path):
        """The corpus-v2 invariants: generic refs are corpus-wide
        identical (idf ~ 0 by construction) and every video carries
        specific refs naming its topic."""
        raw = fabricate(str(tmp_path / "c"), 12, {"resnet": 24}, seed=3,
                        generic_refs=8)
        ann = json.load(open(raw["annotations"]))
        per_vid = {}
        for s in ann["sentences"]:
            per_vid.setdefault(s["video_id"], []).append(s["caption"])
        generic = " ".join(_GENERIC)
        for vid, caps in per_vid.items():
            assert caps.count(generic) == 8
            assert len(caps) == 20
            specific = [c for c in caps if c != generic]
            # modal caption is the generic one
            assert max(specific.count(c) for c in specific) < 8

    def test_scene_mix_perturbs_only_place_slice(self, tmp_path):
        """The scene-mix no-op-stream invariant: turning mixing ON must
        leave noun/verb feature slices AND the annotations bit-identical
        to the unmixed corpus (all mix randomness on a separate rng),
        while actually re-scening some place slices."""
        import h5py

        a = fabricate(str(tmp_path / "a2"), 6, {"resnet": 24}, seed=1)
        c = fabricate(str(tmp_path / "c2"), 6, {"resnet": 24}, seed=1,
                      scene_mix=0.5)
        assert (
            json.load(open(a["annotations"]))
            == json.load(open(c["annotations"]))
        )
        d = 24
        dn = dv = d // 3
        changed = 0
        with h5py.File(a["resnet"]) as fa, h5py.File(c["resnet"]) as fc:
            for k in fa:
                va, vc = fa[k][()], fc[k][()]
                # noun+verb slices untouched
                np.testing.assert_array_equal(
                    va[:, : dn + dv], vc[:, : dn + dv]
                )
                changed += int(
                    not np.array_equal(va[:, dn + dv:], vc[:, dn + dv:])
                )
        assert changed > 0  # some videos actually got a second scene


class TestSweepManifest:
    def _args(self, out):
        return [
            "--out-dir", out, "--videos", "16", "--epochs", "1",
            "--batch-size", "8", "--max-frames", "4", "--max-words", "6",
            "--beam-size", "2", "--cst-samples", "2",
            "--feature-dims", "resnet=8,c3d=8", "--stages", "xe",
        ]

    def test_reuse_rejects_mismatched_corpus(self, tmp_path, capsys):
        out = str(tmp_path / "m")
        assert main(self._args(out)) == 0
        capsys.readouterr()
        with pytest.raises(ValueError, match="fresh --out-dir"):
            main(self._args(out) + ["--reuse-data", "--generic-refs", "2"])

    def test_reuse_without_manifest_rejected(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="manifest"):
            main(self._args(str(tmp_path / "nope")) + ["--reuse-data"])
