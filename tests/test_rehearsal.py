"""End-to-end rehearsal tool: the full fabricate -> prep -> pack ->
XE/WXE/CST pipeline -> beam eval chain at tiny scale."""

import json

import numpy as np

from cst_captioning_tpu.tools.rehearsal import main


def test_rehearsal_end_to_end(tmp_path, capsys):
    rc = main([
        "--out-dir", str(tmp_path / "r"),
        "--videos", "16",
        "--epochs", "1",
        "--batch-size", "8",  # conftest's 8-device mesh shards the batch
        "--max-frames", "4",
        "--max-words", "8",
        "--beam-size", "2",
        "--cst-samples", "3",
        "--feature-dims", "resnet=16,c3d=8",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["videos"] == 16
    for stage in ("xe", "wxe", "cst"):
        assert stage in summary["stages"]
        bc = summary["stages"][stage]["best_val_cider"]
        assert bc is not None and np.isfinite(bc)
    assert np.isfinite(summary["stages"]["cst"]["final_reward"])
    scores = summary["test_scores"]
    assert {"Bleu_4", "METEOR", "ROUGE_L", "CIDEr"} <= set(scores)
    assert scores["METEOR_backend"] in ("java", "lite", "lite+syn")
    # artifacts on disk: packed store, prep outputs, staged checkpoints
    assert (tmp_path / "r" / "packed" / "resnet.npy").exists()
    assert (tmp_path / "r" / "prep" / "consensus_train.json").exists()
    assert (tmp_path / "r" / "checkpoints" / "rehearsal_cst").exists()
