"""Training layer tests: schedule math, checkpoint round-trip, and the
SURVEY.md §4 integration bar — overfit the synthetic corpus with XE and see
val CIDEr improve; WXE runs with consensus weights."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.config import get_preset
from cst_captioning_tpu.data import make_synthetic_dataset
from cst_captioning_tpu.training import Trainer
from cst_captioning_tpu.training.checkpoint import (
    load_infos,
    restore_checkpoint,
    restore_params,
    save_checkpoint,
)
from cst_captioning_tpu.training.steps import make_lr_schedule
from cst_captioning_tpu.training.trainer import scheduled_sampling_prob


def smoke_cfg(tmp_path, **train_overrides):
    cfg = get_preset("synthetic_smoke")
    cfg.data.batch_size = 8
    cfg.data.seq_per_img = 2
    cfg.data.max_frames = 6
    cfg.data.max_seq_len = 12
    cfg.train.checkpoint_dir = str(tmp_path / "ckpt")
    cfg.train.learning_rate = 5e-3
    cfg.train.lr_decay_every = 0
    cfg.train.max_epochs = 12
    cfg.train.max_patience = 0  # no early stop in smoke runs
    cfg.eval.metrics = ["CIDEr"]
    cfg.eval.max_decode_len = 12
    for k, v in train_overrides.items():
        setattr(cfg.train, k, v)
    return cfg


@pytest.fixture(scope="module")
def corpus():
    return make_synthetic_dataset(num_videos=16, max_frames=6, max_words=10,
                                  seed=7)


class TestSchedules:
    def test_lr_schedule_decay(self):
        cfg = get_preset("synthetic_smoke").train
        cfg.learning_rate = 1.0
        cfg.lr_decay = 0.5
        cfg.lr_decay_every = 2
        sched = make_lr_schedule(cfg, steps_per_epoch=10)
        assert float(sched(0)) == 1.0
        assert float(sched(19)) == 1.0
        assert float(sched(20)) == 0.5
        assert float(sched(40)) == 0.25

    def test_lr_schedule_off(self):
        cfg = get_preset("synthetic_smoke").train
        cfg.lr_decay_every = 0
        sched = make_lr_schedule(cfg, steps_per_epoch=10)
        assert float(sched(1000)) == cfg.learning_rate

    def test_scheduled_sampling_prob(self):
        cfg = get_preset("synthetic_smoke").model
        cfg.scheduled_sampling_start = 2
        cfg.scheduled_sampling_increase_every = 3
        cfg.scheduled_sampling_increase_prob = 0.1
        cfg.scheduled_sampling_max_prob = 0.25
        assert scheduled_sampling_prob(cfg, 0) == 0.0
        assert scheduled_sampling_prob(cfg, 1) == 0.0
        # Reference opts.py semantics: frac = (epoch - start) // every, so
        # ss stays 0 for the first `every` epochs after the start epoch.
        assert scheduled_sampling_prob(cfg, 2) == 0.0
        assert scheduled_sampling_prob(cfg, 4) == 0.0
        assert scheduled_sampling_prob(cfg, 5) == pytest.approx(0.1)
        assert scheduled_sampling_prob(cfg, 8) == pytest.approx(0.2)
        assert scheduled_sampling_prob(cfg, 14) == pytest.approx(0.25)
        cfg.scheduled_sampling_start = -1
        assert scheduled_sampling_prob(cfg, 100) == 0.0


class TestTrainerXE:
    def test_overfits_synthetic_and_improves_cider(self, corpus, tmp_path):
        ds, _ = corpus
        cfg = smoke_cfg(tmp_path)
        cfg.data.batch_size = 16
        cfg.data.seq_per_img = 3
        cfg.train.learning_rate = 3e-3
        cfg.train.max_epochs = 150
        cfg.train.eval_every = 30
        trainer = Trainer(cfg, train_ds=ds, val_ds=ds)
        first_loss = trainer.train_epoch(0)["train_loss"]
        early_val = trainer.evaluate()
        hist = trainer.fit()
        last = hist[max(hist, key=int)]
        assert last["train_loss"] < 0.6, (
            f"no overfit: {first_loss} -> {last['train_loss']}"
        )
        # Overfit corpus must yield a real CIDEr, not a degenerate decode.
        assert trainer.best_score > 0.5
        assert trainer.best_score >= early_val["CIDEr"] - 1e-6
        # keep-best checkpoint exists with metadata
        infos = load_infos(os.path.join(trainer.workdir, "best"))
        assert "val" in infos and infos["epoch"] == trainer.best_epoch
        # history json written
        assert os.path.exists(os.path.join(trainer.workdir, "history.json"))

    def test_category_embedding_end_to_end(self, tmp_path):
        """MSR-VTT category conditioning: train + greedy-val + beam eval
        all thread the (B,) category ids through the model."""
        from cst_captioning_tpu.data import make_synthetic_dataset
        from cst_captioning_tpu.evaluation import evaluate_dataset

        ds, _ = make_synthetic_dataset(
            num_videos=16, max_frames=6, num_categories=5, seed=7
        )
        cfg = smoke_cfg(tmp_path)
        cfg.model.use_category = True
        cfg.data.num_categories = 5
        cfg.train.max_epochs = 2
        trainer = Trainer(cfg, train_ds=ds, val_ds=ds)
        hist = trainer.fit()
        assert np.isfinite(hist["1"]["train_loss"])
        assert "cat_embed" in trainer.state.params["params"]
        scores, preds = evaluate_dataset(
            trainer.model, trainer.state.params, ds, cfg
        )
        assert len(preds) == len(ds) and np.isfinite(scores["CIDEr"])

    def test_wxe_uses_weights_and_runs(self, corpus, tmp_path):
        ds, _ = corpus
        cfg = smoke_cfg(tmp_path, train_mode="wxe")
        cfg.train.max_epochs = 2
        trainer = Trainer(cfg, train_ds=ds, val_ds=None)
        hist = trainer.fit()
        assert np.isfinite(hist["1"]["train_loss"])

    def test_early_stopping(self, corpus, tmp_path):
        ds, _ = corpus
        cfg = smoke_cfg(tmp_path, max_patience=1)
        # LR 0: no learning -> val score can never improve after epoch 0.
        cfg.train.learning_rate = 0.0
        cfg.train.max_epochs = 10
        trainer = Trainer(cfg, train_ds=ds, val_ds=ds)
        hist = trainer.fit()
        assert len(hist) <= 3


class TestBufferDonation:
    def test_xe_and_cst_steps_donate_state(self, corpus, tmp_path):
        """donate_argnums on the XE and CST (PG-update) steps: the
        lowered computations must alias the donated TrainState buffers
        into their outputs (``tf.aliasing_output`` in StableHLO) so
        param/optimizer buffers are REUSED across steps instead of
        copied — on accelerator backends this halves state memory
        traffic; it can never change results (the aliased input is
        dead after its last read, docs/PARITY.md)."""
        from cst_captioning_tpu.data import BatchIterator
        from cst_captioning_tpu.models import model_from_config
        from cst_captioning_tpu.training import cst as cst_mod
        from cst_captioning_tpu.training.rewards import CiderDRewarder
        from cst_captioning_tpu.training.steps import (
            create_train_state,
            make_optimizer,
            make_xe_train_step,
        )

        ds, _ = corpus
        cfg = smoke_cfg(tmp_path)
        cfg.data.max_seq_len = 11
        cfg.train.train_mode = "cst"
        cfg.train.cst_baseline = "scb"
        cfg.train.cst_num_samples = 2
        cfg.model.vocab_size = len(ds.vocab)
        model = model_from_config(cfg)
        it = BatchIterator(ds, batch_size=8, seq_per_img=2, max_frames=6,
                           shuffle=False)
        b = next(iter(it.epoch(0)))
        tx = make_optimizer(cfg.train, 10)
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, b._asdict()
        )
        rng = jax.random.PRNGKey(1)

        xe = make_xe_train_step(model)
        lowered = xe.lower(
            state, b.feats, b.feat_masks, b.captions, b.weights, None,
            b.video_idx, rng, 0.0,
        )
        assert "tf.aliasing_output" in lowered.as_text()

        cst = cst_mod._make_one_graph_step(
            model, cfg, CiderDRewarder(ds, backend="python")
        )
        lowered = cst.lower(
            state, b.feats, b.feat_masks, b.captions, b.weights, None,
            b.video_idx, rng, 0.0,
        )
        assert "tf.aliasing_output" in lowered.as_text()


class TestCheckpoint:
    def test_roundtrip_and_warm_start(self, corpus, tmp_path):
        ds, _ = corpus
        cfg = smoke_cfg(tmp_path)
        cfg.train.max_epochs = 1
        trainer = Trainer(cfg, train_ds=ds, val_ds=None)
        trainer.fit()
        path = str(tmp_path / "ck")
        save_checkpoint(path, trainer.state, {"epoch": 0})

        # Full resume into a fresh trainer: params, opt_state, step match.
        t2 = Trainer(cfg, train_ds=ds, val_ds=None, workdir=str(tmp_path / "w2"))
        assert int(t2.state.step) == 0
        restored = restore_checkpoint(path, t2.state)
        assert int(restored.step) == int(trainer.state.step) > 0
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
            restored.params,
            trainer.state.params,
        )

        # Warm start: params only.
        p = restore_params(path, t2.state.params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
            p,
            trainer.state.params,
        )

    def test_start_from_config_plumbs_through(self, corpus, tmp_path):
        ds, _ = corpus
        cfg = smoke_cfg(tmp_path)
        cfg.train.max_epochs = 1
        trainer = Trainer(cfg, train_ds=ds, val_ds=None)
        trainer.fit()
        path = str(tmp_path / "stage1")
        save_checkpoint(path, trainer.state)

        cfg2 = smoke_cfg(tmp_path, start_from=path, train_mode="wxe")
        t2 = Trainer(cfg2, train_ds=ds, val_ds=None,
                     workdir=str(tmp_path / "w3"))
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
            t2.state.params,
            trainer.state.params,
        )
