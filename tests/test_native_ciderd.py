"""Native C++ CIDEr-D scorer: build, exact parity vs the Python scorer
(corpus and idf-table modes), packing-bound guard, and a throughput
sanity check."""

import time

import numpy as np
import pytest

from cst_captioning_tpu.data import make_synthetic_dataset

native = pytest.importorskip("cst_captioning_tpu.native")
from cst_captioning_tpu.native import (  # noqa: E402
    MAX_TOKEN_ID,
    NativeCiderD,
    NativeUnavailable,
    build_ciderd,
)
from cst_captioning_tpu.training.rewards import CiderDRewarder  # noqa: E402


@pytest.fixture(scope="module")
def corpus():
    return make_synthetic_dataset(num_videos=14, max_frames=4, seed=6)


@pytest.fixture(scope="module")
def built():
    try:
        return build_ciderd()
    except NativeUnavailable as e:
        pytest.skip(f"no native toolchain: {e}")


def random_candidates(ds, vocab, n_per_video=4, L=12, seed=0):
    rng = np.random.RandomState(seed)
    B = len(ds) * n_per_video
    vidx = np.repeat(np.arange(len(ds), dtype=np.int32), n_per_video)
    toks = rng.randint(3, len(vocab), size=(B, L)).astype(np.int32)
    # sprinkle in real captions and early terminators
    for i in range(0, B, 3):
        cap = ds.captions(int(vidx[i]))[0]
        toks[i, : cap.shape[0] - 1] = cap[1:]
    toks[1::4, 5] = 2  # EOS mid-sequence
    toks[2::4, 3] = 0  # PAD mid-sequence
    return vidx, toks


class TestParity:
    def test_corpus_mode_matches_python(self, corpus, built):
        ds, vocab = corpus
        py = CiderDRewarder(ds, backend="python")
        nat = CiderDRewarder(ds, backend="native")
        assert nat.backend == "native"
        vidx, toks = random_candidates(ds, vocab)
        np.testing.assert_allclose(
            nat.score_ids(vidx, toks),
            py.score_ids(vidx, toks),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_idf_table_mode_matches_python(self, corpus, built, tmp_path):
        from cst_captioning_tpu.metrics.cider import save_df

        ds, vocab = corpus
        gts = {
            ds.video_id(i): ds.references(i) for i in range(len(ds))
        }
        path = str(tmp_path / "idf.pkl")
        save_df(gts, path)
        py = CiderDRewarder(ds, df_mode=path, backend="python")
        nat = CiderDRewarder(ds, df_mode=path, backend="native")
        assert nat.backend == "native"
        vidx, toks = random_candidates(ds, vocab, seed=1)
        np.testing.assert_allclose(
            nat.score_ids(vidx, toks),
            py.score_ids(vidx, toks),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_exact_match_scores_high(self, corpus, built):
        ds, vocab = corpus
        nat = CiderDRewarder(ds, backend="native")
        cap = ds.captions(0)[0]
        toks = cap[1:][None, :].astype(np.int32)  # strip BOS
        s = nat.score_ids(np.zeros(1, np.int32), toks)
        assert s[0] > 1.0


class TestWeightedConsensus:
    """The paper's weighted consensus reward (driver config 4): each
    reference's CIDEr-D contribution is weighted by its consensus score."""

    @staticmethod
    def weighted_ds(corpus, seed=11):
        ds, vocab = corpus
        rng = np.random.RandomState(seed)
        ds.set_caption_weights(
            {
                ds.video_id(i): rng.uniform(
                    0.2, 2.0, size=len(ds.references(i))
                ).astype(np.float32)
                for i in range(len(ds))
            }
        )
        return ds, vocab

    def test_native_matches_python_with_weights(self, corpus, built):
        ds, vocab = self.weighted_ds(corpus)
        py = CiderDRewarder(ds, backend="python", weighted_refs=True)
        nat = CiderDRewarder(ds, backend="native", weighted_refs=True)
        assert nat.backend == "native"
        vidx, toks = random_candidates(ds, vocab, seed=2)
        np.testing.assert_allclose(
            nat.score_ids(vidx, toks),
            py.score_ids(vidx, toks),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_weighted_differs_from_uniform(self, corpus, built):
        ds, vocab = self.weighted_ds(corpus)
        uni = CiderDRewarder(ds, backend="python")
        wtd = CiderDRewarder(ds, backend="python", weighted_refs=True)
        # Candidate = each video's first reference: its similarity varies
        # across the sibling refs, so re-weighting must shift the score.
        L = ds.captions(0).shape[1]
        cands = np.zeros((len(ds), L), np.int32)
        for i in range(len(ds)):
            cap = ds.captions(i)[0]
            cands[i, : cap.shape[0] - 1] = cap[1:]
        vidx = np.arange(len(ds), dtype=np.int32)
        assert not np.allclose(
            uni.score_ids(vidx, cands), wtd.score_ids(vidx, cands)
        )

    def test_uniform_weights_equal_unweighted(self, corpus, built):
        ds, vocab = corpus
        ds.set_caption_weights(
            {
                ds.video_id(i): np.full(
                    len(ds.references(i)), 3.7, np.float32
                )
                for i in range(len(ds))
            }
        )
        for backend in ("python", "native"):
            base = CiderDRewarder(ds, backend=backend)
            wtd = CiderDRewarder(ds, backend=backend, weighted_refs=True)
            vidx, toks = random_candidates(ds, vocab, seed=3)
            np.testing.assert_allclose(
                wtd.score_ids(vidx, toks),
                base.score_ids(vidx, toks),
                rtol=1e-5,
                atol=1e-6,
            )
        ds._weight_override = None  # un-poison the module-scoped corpus


class TestGtConsensus:
    """Native leave-one-out GT consensus (ADVICE r4 #3): the rewarder
    routes gt_consensus() through C++ when the native backend is active,
    so the two implementations must agree exactly."""

    def test_native_matches_python(self, corpus, built):
        ds, _ = corpus
        py = CiderDRewarder(ds, backend="python")
        nat = CiderDRewarder(ds, backend="native")
        assert nat.backend == "native"
        np.testing.assert_allclose(
            nat.gt_consensus(), py.gt_consensus(), rtol=1e-5, atol=1e-6
        )

    def test_native_matches_python_weighted(self, corpus, built):
        ds, _ = TestWeightedConsensus.weighted_ds(corpus, seed=12)
        try:
            py = CiderDRewarder(ds, backend="python", weighted_refs=True)
            nat = CiderDRewarder(ds, backend="native", weighted_refs=True)
            assert nat.backend == "native"
            np.testing.assert_allclose(
                nat.gt_consensus(), py.gt_consensus(), rtol=1e-5, atol=1e-6
            )
        finally:
            ds._weight_override = None  # un-poison the module-scoped corpus

    def test_under_two_refs_scores_zero(self, built):
        nat = NativeCiderD([[[5, 6, 7]], [], [[5, 6], [5, 6, 7]]])
        out = nat.gt_consensus()
        assert out.shape == (3,)
        assert out[0] == 0.0 and out[1] == 0.0  # <2 refs: no consensus
        assert out[2] > 0.0


class TestGuards:
    def test_packing_bound_rejected(self, built):
        with pytest.raises(NativeUnavailable):
            NativeCiderD([[[MAX_TOKEN_ID + 1]]])

    def test_out_of_range_video_idx_raises(self, corpus, built):
        ds, _ = corpus
        nat = CiderDRewarder(ds, backend="native")
        toks = np.zeros((1, 5), np.int32)
        with pytest.raises(IndexError, match="out of range"):
            nat.score_ids(np.asarray([len(ds)], np.int32), toks)

    def test_zero_reference_video_scores_zero(self, built):
        """A programmatic video with no references must reward 0.0, not
        NaN/inf (division by nref guard, both backends)."""
        from cst_captioning_tpu.metrics.cider import (
            ciderd_score_vec,
            precook,
        )

        nat = NativeCiderD([[[5, 6, 7]], []])
        toks = np.asarray([[5, 6, 7, 0, 0]], np.int32)
        s = nat.score_ids(np.asarray([1], np.int32), toks)
        assert s[0] == 0.0
        assert ciderd_score_vec(precook([5, 6]), [], {}, 1.0) == 0.0

    def test_auto_backend_never_raises(self, corpus):
        ds, _ = corpus
        rw = CiderDRewarder(ds, backend="auto")
        assert rw.backend in ("native", "python")


class TestThroughput:
    def test_native_not_slower(self, corpus, built):
        """Sanity: on a CST-step-sized batch the native scorer should beat
        the Python loop comfortably (asserted at >=2x to stay robust)."""
        ds, vocab = corpus
        py = CiderDRewarder(ds, backend="python")
        nat = CiderDRewarder(ds, backend="native")
        vidx, toks = random_candidates(ds, vocab, n_per_video=40, L=20)

        nat.score_ids(vidx, toks)  # warm
        t0 = time.perf_counter()
        for _ in range(3):
            nat.score_ids(vidx, toks)
        t_nat = time.perf_counter() - t0
        t0 = time.perf_counter()
        py.score_ids(vidx, toks)
        t_py = (time.perf_counter() - t0) * 3
        assert t_nat * 2 < t_py, f"native {t_nat:.4f}s vs python {t_py:.4f}s"
