"""Native C++ CIDEr-D scorer: build, exact parity vs the Python scorer
(corpus and idf-table modes), packing-bound guard, and a throughput
sanity check."""

import time

import numpy as np
import pytest

from cst_captioning_tpu.data import make_synthetic_dataset

native = pytest.importorskip("cst_captioning_tpu.native")
from cst_captioning_tpu.native import (  # noqa: E402
    MAX_TOKEN_ID,
    NativeCiderD,
    NativeUnavailable,
    build_ciderd,
)
from cst_captioning_tpu.training.rewards import CiderDRewarder  # noqa: E402


@pytest.fixture(scope="module")
def corpus():
    return make_synthetic_dataset(num_videos=14, max_frames=4, seed=6)


@pytest.fixture(scope="module")
def built():
    try:
        return build_ciderd()
    except NativeUnavailable as e:
        pytest.skip(f"no native toolchain: {e}")


def random_candidates(ds, vocab, n_per_video=4, L=12, seed=0):
    rng = np.random.RandomState(seed)
    B = len(ds) * n_per_video
    vidx = np.repeat(np.arange(len(ds), dtype=np.int32), n_per_video)
    toks = rng.randint(3, len(vocab), size=(B, L)).astype(np.int32)
    # sprinkle in real captions and early terminators
    for i in range(0, B, 3):
        cap = ds.captions(int(vidx[i]))[0]
        toks[i, : cap.shape[0] - 1] = cap[1:]
    toks[1::4, 5] = 2  # EOS mid-sequence
    toks[2::4, 3] = 0  # PAD mid-sequence
    return vidx, toks


class TestParity:
    def test_corpus_mode_matches_python(self, corpus, built):
        ds, vocab = corpus
        py = CiderDRewarder(ds, backend="python")
        nat = CiderDRewarder(ds, backend="native")
        assert nat.backend == "native"
        vidx, toks = random_candidates(ds, vocab)
        np.testing.assert_allclose(
            nat.score_ids(vidx, toks),
            py.score_ids(vidx, toks),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_idf_table_mode_matches_python(self, corpus, built, tmp_path):
        from cst_captioning_tpu.metrics.cider import save_df

        ds, vocab = corpus
        gts = {
            ds.video_id(i): ds.references(i) for i in range(len(ds))
        }
        path = str(tmp_path / "idf.pkl")
        save_df(gts, path)
        py = CiderDRewarder(ds, df_mode=path, backend="python")
        nat = CiderDRewarder(ds, df_mode=path, backend="native")
        assert nat.backend == "native"
        vidx, toks = random_candidates(ds, vocab, seed=1)
        np.testing.assert_allclose(
            nat.score_ids(vidx, toks),
            py.score_ids(vidx, toks),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_exact_match_scores_high(self, corpus, built):
        ds, vocab = corpus
        nat = CiderDRewarder(ds, backend="native")
        cap = ds.captions(0)[0]
        toks = cap[1:][None, :].astype(np.int32)  # strip BOS
        s = nat.score_ids(np.zeros(1, np.int32), toks)
        assert s[0] > 1.0


class TestGuards:
    def test_packing_bound_rejected(self, built):
        with pytest.raises(NativeUnavailable):
            NativeCiderD([[[MAX_TOKEN_ID + 1]]])

    def test_out_of_range_video_idx_raises(self, corpus, built):
        ds, _ = corpus
        nat = CiderDRewarder(ds, backend="native")
        toks = np.zeros((1, 5), np.int32)
        with pytest.raises(IndexError, match="out of range"):
            nat.score_ids(np.asarray([len(ds)], np.int32), toks)

    def test_auto_backend_never_raises(self, corpus):
        ds, _ = corpus
        rw = CiderDRewarder(ds, backend="auto")
        assert rw.backend in ("native", "python")


class TestThroughput:
    def test_native_not_slower(self, corpus, built):
        """Sanity: on a CST-step-sized batch the native scorer should beat
        the Python loop comfortably (asserted at >=2x to stay robust)."""
        ds, vocab = corpus
        py = CiderDRewarder(ds, backend="python")
        nat = CiderDRewarder(ds, backend="native")
        vidx, toks = random_candidates(ds, vocab, n_per_video=40, L=20)

        nat.score_ids(vidx, toks)  # warm
        t0 = time.perf_counter()
        for _ in range(3):
            nat.score_ids(vidx, toks)
        t_nat = time.perf_counter() - t0
        t0 = time.perf_counter()
        py.score_ids(vidx, toks)
        t_py = (time.perf_counter() - t0) * 3
        assert t_nat * 2 < t_py, f"native {t_nat:.4f}s vs python {t_py:.4f}s"
