"""Beam search tests: toy-vocab optimality vs exhaustive search, beam=1 ==
greedy, ordering/monotonicity properties, eval driver artifacts, CLI."""

import itertools
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.constants import BOS_ID, EOS_ID, PAD_ID
from cst_captioning_tpu.data import make_synthetic_dataset
from cst_captioning_tpu.decoding import beam_search, make_beam_search_fn
from cst_captioning_tpu.evaluation import evaluate_dataset
from cst_captioning_tpu.models import CaptionModel

V, B, F, D, H = 9, 3, 4, 8, 12


def tiny_model(np_rng, **kw):
    kwargs = dict(
        vocab_size=V, rnn_size=H, num_layers=1, embed_size=H,
        modalities=("resnet",), feature_dims=(D,), drop_prob=0.0,
        compute_dtype="float32",
    )
    kwargs.update(kw)
    model = CaptionModel(**kwargs)
    feats = {"resnet": jnp.asarray(np_rng.randn(B, F, D), jnp.float32)}
    masks = {"resnet": jnp.ones((B, F))}
    ids = jnp.asarray(np_rng.randint(4, V, (B, 5)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), feats, masks, ids)
    return model, params, feats, masks


@pytest.fixture(scope="module")
def np_rng():
    return np.random.RandomState(11)


def exhaustive_best(model, params, feats, masks, max_len, length_normalize):
    """Brute-force optimum over all sequences of length <= max_len on the
    tiny vocab (words 4..V-1 + EOS), scoring with the model's own
    decode_one chain."""
    state0, cache = model.apply(params, feats, masks, method="init_decode")

    def seq_logprob(seq, b):
        state = jax.tree.map(lambda x: x[:, b : b + 1] if x.ndim == 3 else x,
                             state0)
        cache_b = jax.tree.map(lambda x: x[b : b + 1], cache)
        tok = jnp.full((1,), BOS_ID, jnp.int32)
        total = 0.0
        for s in seq:
            state, logp = model.apply(
                params, state, cache_b, tok, method="decode_one"
            )
            total += float(logp[0, s])
            tok = jnp.full((1,), s, jnp.int32)
        return total

    best = []
    words = list(range(3, V))  # UNK + real words (beam may emit UNK)
    for b in range(B):
        cands = []
        for n in range(0, max_len):  # n words + EOS (n=0: empty caption)
            for combo in itertools.product(words, repeat=n):
                seq = list(combo) + [EOS_ID]
                lp = seq_logprob(seq, b)
                norm = lp / len(seq) if length_normalize else lp
                cands.append((norm, seq))
        # sequences with no EOS (full length, no terminator)
        for combo in itertools.product(words, repeat=max_len):
            lp = seq_logprob(list(combo), b)
            norm = lp / max_len if length_normalize else lp
            cands.append((norm, list(combo)))
        cands.sort(key=lambda x: -x[0])
        best.append(cands[0])
    return best


class TestBeamSearch:
    def test_shapes_and_jit(self, np_rng):
        model, params, feats, masks = tiny_model(np_rng)
        fn = make_beam_search_fn(model, beam_size=4, max_len=6)
        r = fn(params, feats, masks)
        assert r.tokens.shape == (B, 6)
        assert r.score.shape == (B,)
        assert r.all_tokens.shape == (B, 4, 6)
        assert r.all_scores.shape == (B, 4)

    def test_scores_sorted_best_first(self, np_rng):
        model, params, feats, masks = tiny_model(np_rng)
        r = beam_search(model, params, feats, masks, beam_size=4, max_len=6)
        s = np.asarray(r.all_scores)
        assert (np.diff(s, axis=1) <= 1e-6).all()
        np.testing.assert_array_equal(
            np.asarray(r.tokens), np.asarray(r.all_tokens[:, 0])
        )

    # beam1 == greedy moved to the shared parity harness
    # (tests/test_decode_core.py::TestSharedParity::test_beam1_equals_greedy).

    @pytest.mark.parametrize("length_normalize", [False, True])
    def test_wide_beam_finds_exhaustive_optimum(self, np_rng, length_normalize):
        """With a beam as wide as the whole candidate space per step, beam
        search must recover the true optimum on a tiny vocab, 3 steps."""
        model, params, feats, masks = tiny_model(np_rng)
        max_len = 3
        r = beam_search(
            model, params, feats, masks, beam_size=32, max_len=max_len,
            length_normalize=length_normalize,
        )
        best = exhaustive_best(model, params, feats, masks, max_len,
                               length_normalize)
        for b in range(B):
            got = [int(t) for t in np.asarray(r.tokens[b]) if t != PAD_ID]
            want = [s for s in best[b][1] if s != PAD_ID]
            # compare sequences (strip trailing EOS representation diffs)
            got_w = [t for t in got if t != EOS_ID]
            want_w = [t for t in want if t != EOS_ID]
            assert got_w == want_w, f"video {b}: {got} != {want}"
            np.testing.assert_allclose(
                float(r.score[b]), best[b][0], rtol=1e-4
            )

    def test_after_end_only_pad(self, np_rng):
        model, params, feats, masks = tiny_model(np_rng)
        r = beam_search(model, params, feats, masks, beam_size=3, max_len=8)
        toks = np.asarray(r.all_tokens).reshape(-1, 8)
        for row in toks:
            ends = np.nonzero((row == EOS_ID) | (row == PAD_ID))[0]
            if len(ends):
                assert (row[ends[0] + 1 :] == PAD_ID).all()

    def test_wider_beam_no_worse_unnormalized(self, np_rng):
        model, params, feats, masks = tiny_model(np_rng)
        r2 = beam_search(model, params, feats, masks, beam_size=2, max_len=5,
                         length_normalize=False)
        r8 = beam_search(model, params, feats, masks, beam_size=8, max_len=5,
                         length_normalize=False)
        assert (np.asarray(r8.score) >= np.asarray(r2.score) - 1e-5).all()


class TestEvaluation:
    def test_evaluate_dataset_writes_artifacts(self, tmp_path):
        from cst_captioning_tpu.config import get_preset

        ds, vocab = make_synthetic_dataset(num_videos=8, max_frames=6, seed=4)
        cfg = get_preset("synthetic_smoke")
        cfg.model.vocab_size = len(vocab)
        cfg.eval.metrics = ["Bleu_4", "CIDEr"]
        cfg.eval.beam_size = 3
        cfg.eval.max_decode_len = 8
        from cst_captioning_tpu.models import model_from_config

        model = model_from_config(cfg)
        feats = {"resnet": jnp.zeros((1, 6, 64))}
        masks = {"resnet": jnp.ones((1, 6))}
        params = model.init(
            jax.random.PRNGKey(0), feats, masks,
            jnp.zeros((1, 2), jnp.int32),
        )
        out = str(tmp_path / "eval")
        scores, preds = evaluate_dataset(model, params, ds, cfg, out_dir=out)
        assert set(scores) == {"Bleu_4", "CIDEr"}
        assert len(preds) == 8
        with open(os.path.join(out, "predictions.json")) as f:
            pj = json.load(f)
        assert len(pj) == 8 and {"image_id", "caption"} <= set(pj[0])
        assert os.path.exists(os.path.join(out, "scores.json"))


class TestCLI:
    def test_train_then_test_cli_roundtrip(self, tmp_path):
        from cst_captioning_tpu.cli.test import main as test_main
        from cst_captioning_tpu.cli.train import main as train_main

        ckpt_dir = str(tmp_path / "ck")
        rc = train_main([
            "--preset", "synthetic_smoke",
            "--train.checkpoint_dir", ckpt_dir,
            "--train.max_epochs", "1",
            "--train.max_patience", "0",
            "--eval.metrics", '["CIDEr"]',
            "--eval.max_decode_len", "11",
        ])
        assert rc == 0
        best = os.path.join(ckpt_dir, "synthetic_smoke", "best")
        assert os.path.exists(best)
        out = str(tmp_path / "eval_out")
        rc = test_main([
            "--checkpoint", best,
            "--preset", "synthetic_smoke",
            "--eval.metrics", '["CIDEr"]',
            "--eval.beam_size", "3",
            "--eval.max_decode_len", "11",
            "--eval.out_dir", out,
        ])
        assert rc == 0
        assert os.path.exists(os.path.join(out, "scores.json"))
