"""BENCH_*/MULTICHIP_* record schema validation (bench.validate_record):
malformed rows — missing keys, bool-typed measured fields (ADVICE r5:
bool subclasses int), non-numeric phase times — must fail loudly at the
emit site, before they reach a driver artifact."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import validate_record  # noqa: E402


def good_bench():
    return {
        "metric": "xe_train_throughput_msrvtt_resnet_c3d",
        "value": 1.23,
        "unit": "steps/sec/chip",
        "vs_baseline": 1.1,
        "extra": {
            "bench_chunk": 60,
            "beam_fused": False,          # flags may be bool
            "cst_pipe_speedup": 1.34,
            "serving_shape": "smoke",
            "serving_sweep": {"continuous": {}},
        },
    }


class TestBenchKind:
    def test_good_record_passes(self):
        rec = good_bench()
        assert validate_record(rec) is rec

    def test_null_value_allowed(self):
        rec = good_bench()
        rec["value"] = None
        rec["vs_baseline"] = None
        validate_record(rec)

    @pytest.mark.parametrize(
        "missing", ["metric", "value", "unit", "vs_baseline", "extra"]
    )
    def test_missing_required_key_fails(self, missing):
        rec = good_bench()
        del rec[missing]
        with pytest.raises(ValueError, match=missing):
            validate_record(rec)

    def test_bool_value_fails(self):
        """bool subclasses int — a True headline would count as a
        measurement everywhere downstream (ADVICE r5)."""
        rec = good_bench()
        rec["value"] = True
        with pytest.raises(ValueError, match="value"):
            validate_record(rec)

    def test_bool_measured_extra_fails(self):
        rec = good_bench()
        rec["extra"]["cst_pipe_serial_step_ms"] = True
        with pytest.raises(ValueError, match="bool-typed"):
            validate_record(rec)

    def test_bool_vs_extra_fails(self):
        rec = good_bench()
        rec["extra"]["vs_baseline_matched_chunk"] = False
        with pytest.raises(ValueError, match="bool-typed"):
            validate_record(rec)

    def test_slot_rollout_step_accounting_fields_pass(self):
        """The paired padded-vs-slot CST rows carry decode-step and
        harvest-tick accounting (ISSUE 6): numeric values validate."""
        rec = good_bench()
        rec["extra"].update(
            cst_rollout_steps_per_row=3.3,
            cst_slot_harvest_ticks=6,
            cst_slot_decode_steps=12,
            cst_slot_host_cores=1,
        )
        validate_record(rec)

    def test_bool_steps_per_row_fails(self):
        rec = good_bench()
        rec["extra"]["cst_rollout_steps_per_row"] = True
        with pytest.raises(ValueError, match="bool-typed"):
            validate_record(rec)

    def test_bool_harvest_ticks_fails(self):
        rec = good_bench()
        rec["extra"]["cst_slot_harvest_ticks"] = False
        with pytest.raises(ValueError, match="bool-typed"):
            validate_record(rec)

    @pytest.mark.parametrize(
        "key", ["cst_slot_host_cores", "cst_pipe_host_cores",
                "serving_replicas_host_cores"]
    )
    @pytest.mark.parametrize("bad", [True, None, "1", 0, -2])
    def test_host_cores_must_be_positive_count(self, key, bad):
        """CPU-host caveats are machine-readable (ISSUE 6 satellite):
        any *_host_cores field must be a real positive core count, the
        way PR 5 pinned cst_pipe_host_cores in prose."""
        rec = good_bench()
        rec["extra"][key] = bad
        with pytest.raises(ValueError, match="core count"):
            validate_record(rec)

    def test_host_cores_numeric_passes(self):
        rec = good_bench()
        rec["extra"]["cst_slot_host_cores"] = 8
        validate_record(rec)

    def test_slot_mem_byte_fields_pass(self):
        """The paired replicated-vs-deduped decode-state rows (ISSUE 7)
        carry exact pytree byte accounting: numeric values validate."""
        rec = good_bench()
        rec["extra"].update(
            slot_mem_dedup_state_bytes=129528,
            slot_mem_replicated_state_bytes=335864,
            slot_mem_dedup_bytes_per_request=16191,
            slot_mem_formula_delta_bytes=0,
            slot_mem_bytes_per_request_ratio=2.59,
            slot_mem_regrow_count=4,
            slot_mem_regrow_worst_ms=0.2,
            slot_mem_host_cores=1,
        )
        validate_record(rec)

    @pytest.mark.parametrize("bad", [True, False, None, "129528"])
    def test_non_numeric_bytes_field_fails(self, bad):
        """*_bytes fields are exact measurements by contract: a bool
        (subclasses int!), None, or string means nothing was measured
        and must fail at the emit site."""
        rec = good_bench()
        rec["extra"]["slot_mem_dedup_state_bytes"] = bad
        with pytest.raises(ValueError, match="byte count|bool-typed"):
            validate_record(rec)

    def test_bool_bytes_ratio_fails(self):
        rec = good_bench()
        rec["extra"]["slot_mem_bytes_per_request_ratio"] = True
        with pytest.raises(ValueError, match="bool-typed"):
            validate_record(rec)

    def test_trace_overhead_fields_pass(self):
        """ISSUE 10: paired tracing-on/off rows are numeric by
        contract."""
        rec = good_bench()
        rec["extra"].update({
            "trace_overhead_captions_per_sec_on": 553.3,
            "trace_overhead_captions_per_sec_off": 583.7,
            "trace_overhead_ratio": 0.948,
            "trace_overhead_pct": 5.2,
            "trace_overhead_p99_delta_ms": 6.6,
            "trace_overhead_spans": 1003,
            "trace_overhead_host_cores": 1.0,
        })
        validate_record(rec)

    @pytest.mark.parametrize("bad", [True, None, "fast", [1.0]])
    def test_non_numeric_trace_overhead_fails(self, bad):
        rec = good_bench()
        rec["extra"]["trace_overhead_ratio"] = bad
        with pytest.raises(ValueError, match="trace_overhead_ratio"):
            validate_record(rec)

    def test_slo_soak_fields_pass(self):
        """ISSUE 11: chaos-soak SLO rows are numeric by contract, with
        attainment fields constrained to the unit interval."""
        rec = good_bench()
        rec["extra"].update({
            "slo_reference_attainment": 1.0,
            "slo_chaos_attainment_interactive": 0.67,
            "slo_chaos_attainment_best_effort": 0.5,
            "slo_host_cores": 1.0,
            "slo_chaos_seed": 1123.0,
            "slo_chaos_lost": 0.0,
            "slo_replay_mismatches": 0.0,
        })
        validate_record(rec)

    @pytest.mark.parametrize("bad", [True, None, "1.0", [1.0]])
    def test_non_numeric_slo_field_fails(self, bad):
        rec = good_bench()
        rec["extra"]["slo_chaos_seed"] = bad
        with pytest.raises(ValueError, match="slo_chaos_seed"):
            validate_record(rec)

    @pytest.mark.parametrize("bad", [-0.1, 1.5, 2])
    def test_attainment_outside_unit_interval_fails(self, bad):
        rec = good_bench()
        rec["extra"]["slo_reference_attainment"] = bad
        with pytest.raises(ValueError, match="attainment fraction"):
            validate_record(rec)

    @pytest.mark.parametrize("bad", [True, None])
    def test_bool_attainment_fails(self, bad):
        rec = good_bench()
        rec["extra"]["slo_chaos_attainment_overall"] = bad
        with pytest.raises(ValueError, match="slo_chaos_attainment"):
            validate_record(rec)

    def test_coldstart_fields_pass(self):
        """ISSUE 13: paired warm-vs-AOT cold-start rows are numeric by
        contract (the ratio and the compile_count == 0 pin included)."""
        rec = good_bench()
        rec["extra"].update({
            "coldstart_warm_s": 7.5,
            "coldstart_aot_s": 1.7,
            "coldstart_ratio": 4.3,
            "coldstart_warm_boot_s": 6.4,
            "coldstart_aot_boot_s": 1.0,
            "coldstart_warm_compile_count": 11.0,
            "coldstart_aot_compile_count": 0.0,
            "coldstart_artifact_build_s": 4.9,
            "coldstart_artifact_bytes": 1784953.0,
            "coldstart_variants": 14.0,
            "coldstart_tokens_match": 1.0,
            "coldstart_host_cores": 1.0,
        })
        validate_record(rec)

    @pytest.mark.parametrize("bad", [True, None, "fast", [1.0]])
    def test_non_numeric_coldstart_field_fails(self, bad):
        rec = good_bench()
        rec["extra"]["coldstart_ratio"] = bad
        with pytest.raises(ValueError, match="coldstart_ratio"):
            validate_record(rec)

    @pytest.mark.parametrize("bad", [True, None, "0"])
    def test_non_numeric_coldstart_bytes_fails(self, bad):
        rec = good_bench()
        rec["extra"]["coldstart_artifact_bytes"] = bad
        with pytest.raises(
            ValueError, match="coldstart_artifact_bytes"
        ):
            validate_record(rec)

    def test_shard_fused_row_passes(self):
        """A well-formed fused-vs-scan model-sharded decode row (ISSUE
        14): numeric measurements, "1x2" mesh string, provenance
        strings exempted by name."""
        rec = good_bench()
        rec["extra"].update({
            "shard_fused_mesh_shape": "1x2",
            "shard_fused_steps_per_sec": 2900.0,
            "shard_fused_scan_steps_per_sec": 2300.0,
            "shard_fused_vs_scan_ratio": 1.24,
            "shard_fused_candidate_all_gather_bytes": 192,
            "shard_fused_scan_all_gather_bytes": 98304,
            "shard_fused_token_mismatches": 0,
            "shard_fused_host_cores": 1.0,
            "shard_fused_xla_flags": "--xla_force…=2",
            "shard_fused_jax_platforms": "cpu",
            "shard_fused_virtual_cpu": True,
        })
        validate_record(rec)

    @pytest.mark.parametrize("bad", [True, None, "fast", [1.0]])
    def test_non_numeric_shard_fused_field_fails(self, bad):
        rec = good_bench()
        rec["extra"]["shard_fused_vs_scan_ratio"] = bad
        with pytest.raises(ValueError, match="shard_fused_vs_scan"):
            validate_record(rec)

    @pytest.mark.parametrize("bad", [True, None, "small"])
    def test_non_numeric_candidate_gather_bytes_fails(self, bad):
        rec = good_bench()
        rec["extra"]["shard_fused_candidate_all_gather_bytes"] = bad
        with pytest.raises(
            ValueError, match="shard_fused_candidate_all_gather_bytes"
        ):
            validate_record(rec)

    def test_shard_fused_mesh_shape_still_topology_checked(self):
        rec = good_bench()
        rec["extra"]["shard_fused_mesh_shape"] = "one-by-two"
        with pytest.raises(ValueError, match="mesh"):
            validate_record(rec)

    def test_lowprec_row_passes(self):
        """A well-formed f32/bf16/int8w serving row (ISSUE 16):
        numeric measurements, unit-interval match rates, provenance
        strings exempted by name."""
        rec = good_bench()
        rec["extra"].update({
            "lowprec_mesh_shape": "1x2",
            "lowprec_xla_flags": "--xla_force…=2",
            "lowprec_jax_platforms": "cpu",
            "lowprec_host_cores": 1.0,
            "lowprec_match_floor": 0.75,
            "lowprec_score_rtol": 0.02,
            "lowprec_f32_captions_per_sec": 2630.2,
            "lowprec_int8w_captions_per_sec": 2521.5,
            "lowprec_int8w_p99_batch_ms": 4.57,
            "lowprec_int8w_match_rate": 1.0,
            "lowprec_bf16_match_rate": 0.875,
            "lowprec_int8w_score_gap_max": 0.000183,
            "lowprec_vocab_tile_f32_bytes": 65536,
            "lowprec_vocab_tile_int8w_bytes": 16384,
            "lowprec_vocab_tile_ratio": 0.25,
            "lowprec_int8w_param_bytes_per_shard": 60544,
            "lowprec_virtual_cpu": 1,
        })
        validate_record(rec)

    @pytest.mark.parametrize("bad", [True, None, "fast", [1.0]])
    def test_non_numeric_lowprec_field_fails(self, bad):
        rec = good_bench()
        rec["extra"]["lowprec_int8w_captions_per_sec"] = bad
        with pytest.raises(
            ValueError, match="lowprec_int8w_captions_per_sec"
        ):
            validate_record(rec)

    @pytest.mark.parametrize("bad", [-0.1, 1.5, 100.0])
    def test_lowprec_match_rate_outside_unit_interval_fails(self, bad):
        """Match rates are caption-match FRACTIONS: the parity gate
        compares them to the pinned floor, so a percentage or a
        miscount must fail the emit."""
        rec = good_bench()
        rec["extra"]["lowprec_bf16_match_rate"] = bad
        with pytest.raises(ValueError, match="match_rate"):
            validate_record(rec)

    @pytest.mark.parametrize("bad", [True, None, "all"])
    def test_bool_lowprec_match_rate_fails(self, bad):
        rec = good_bench()
        rec["extra"]["lowprec_int8w_tp2_match_rate"] = bad
        with pytest.raises(ValueError, match="lowprec_int8w_tp2"):
            validate_record(rec)

    def test_lowprec_fused_row_passes(self):
        """A well-formed fused×int8w composition row (ISSUE 20): rides
        the lowprec_* numeric contract, tile ratios exactly 0.25,
        extra-decline counts exactly 0."""
        rec = good_bench()
        rec["extra"].update({
            "lowprec_fused_mesh_shape": "1x2",
            "lowprec_fused_jax_platforms": "cpu",
            "lowprec_fused_host_cores": 1.0,
            "lowprec_fused_match_floor": 0.75,
            "lowprec_fused_int8w_fused_captions_per_sec": 2284.3,
            "lowprec_fused_int8w_unfused_captions_per_sec": 1737.8,
            "lowprec_fused_int8w_fused_p99_batch_ms": 3.77,
            "lowprec_fused_int8w_match_rate": 1.0,
            "lowprec_fused_int8w_tp2_match_rate": 1.0,
            "lowprec_fused_int8w_score_gap_max": 0.0,
            "lowprec_fused_vocab_tile_f32_bytes": 131072,
            "lowprec_fused_vocab_tile_int8w_bytes": 32768,
            "lowprec_fused_vocab_tile_ratio": 0.25,
            "lowprec_fused_tp2_vocab_tile_ratio": 0.25,
            "lowprec_fused_int8w_extra_declines": 0,
            "lowprec_fused_int8w_tp2_extra_declines": 0,
            "lowprec_fused_int8w_fused_env_gate_lines": 2,
            "lowprec_fused_virtual_cpu": 1,
        })
        validate_record(rec)

    @pytest.mark.parametrize("bad", [0.5, 1.0, 0.249999])
    def test_lowprec_fused_tile_ratio_not_quarter_fails(self, bad):
        """The streamed vocab tile is EXACTLY 0.25x f32 by closed form
        (int8 codes) — any other ratio means the kernels stopped
        streaming int8 or the tile arithmetic drifted."""
        rec = good_bench()
        rec["extra"]["lowprec_fused_tp2_vocab_tile_ratio"] = bad
        with pytest.raises(ValueError, match="0.25"):
            validate_record(rec)

    @pytest.mark.parametrize("bad", [1, 2.0, -1, True])
    def test_lowprec_fused_extra_declines_nonzero_fails(self, bad):
        """serving.dtype=int8w must never gate a requested fused
        kernel off — the decline lift is the tentpole claim, so the
        schema pins the count at exactly 0."""
        rec = good_bench()
        rec["extra"]["lowprec_fused_int8w_extra_declines"] = bad
        with pytest.raises(ValueError, match="extra_declines"):
            validate_record(rec)

    @pytest.mark.parametrize("bad", [True, None, "fused"])
    def test_non_numeric_lowprec_fused_field_fails(self, bad):
        """lowprec_fused_* rides the lowprec_ numeric contract — a
        bool/None/prose measurement fails at the emit site."""
        rec = good_bench()
        rec["extra"]["lowprec_fused_int8w_fused_captions_per_sec"] = bad
        with pytest.raises(
            ValueError, match="lowprec_fused_int8w_fused_captions"
        ):
            validate_record(rec)

    def test_spec_row_passes(self):
        """A well-formed speculative-decode row (ISSUE 18): every
        spec_* field numeric by contract, acceptance fractions in the
        unit interval, provenance strings exempted by suffix."""
        rec = good_bench()
        rec["extra"].update({
            "spec_mesh_shape": "1x1",
            "spec_xla_flags": "",
            "spec_jax_platforms": "cpu",
            "spec_host_cores": 1.0,
            "spec_draft_k": 4,
            "spec_draft_hidden": 16,
            "spec_token_mismatches": 0,
            "spec_acceptance_rate": 0.62,
            "spec_tokens_per_tick": 2.1,
            "spec_tokens_per_round": 2.86,
            "spec_captions_per_sec": 1810.4,
            "spec_baseline_captions_per_sec": 1502.7,
            "spec_p99_tick_ms": 3.9,
            "spec_distill_steps": 60,
            # ISSUE 20 composition arm: speculation × int8w weights
            "spec_int8w_token_mismatches": 0,
            "spec_int8w_acceptance_rate": 0.58,
            "spec_int8w_tokens_per_tick": 1.9,
            "spec_int8w_captions_per_sec": 1650.2,
            "spec_int8w_vs_baseline_ratio": 1.12,
        })
        validate_record(rec)

    @pytest.mark.parametrize("bad", [-0.01, 1.01, True])
    def test_spec_int8w_acceptance_contract_holds(self, bad):
        """The int8w composition arm's acceptance fraction rides the
        same unit-interval contract as the float arm's."""
        rec = good_bench()
        rec["extra"]["spec_int8w_acceptance_rate"] = bad
        with pytest.raises(ValueError, match="spec_int8w_acceptance"):
            validate_record(rec)

    @pytest.mark.parametrize("bad", [True, None, "exact", [0]])
    def test_non_numeric_spec_field_fails(self, bad):
        """spec_token_mismatches is THE token-exactness gate count —
        a bool True (== 1 under int arithmetic) or prose must fail the
        emit, not masquerade as a measurement."""
        rec = good_bench()
        rec["extra"]["spec_token_mismatches"] = bad
        with pytest.raises(ValueError, match="spec_token_mismatches"):
            validate_record(rec)

    @pytest.mark.parametrize("bad", [-0.01, 1.01, 62.0])
    def test_spec_acceptance_outside_unit_interval_fails(self, bad):
        rec = good_bench()
        rec["extra"]["spec_acceptance_rate"] = bad
        with pytest.raises(ValueError, match="acceptance"):
            validate_record(rec)

    @pytest.mark.parametrize("bad", [True, False])
    def test_bool_spec_acceptance_fails(self, bad):
        rec = good_bench()
        rec["extra"]["spec_acceptance_rate"] = bad
        with pytest.raises(ValueError, match="spec_acceptance_rate"):
            validate_record(rec)

    def test_spec_mesh_shape_still_topology_checked(self):
        rec = good_bench()
        rec["extra"]["spec_mesh_shape"] = "one"
        with pytest.raises(ValueError, match="mesh"):
            validate_record(rec)

    def test_lowprec_mesh_shape_still_topology_checked(self):
        rec = good_bench()
        rec["extra"]["lowprec_mesh_shape"] = "one-by-two"
        with pytest.raises(ValueError, match="mesh"):
            validate_record(rec)

    def test_mesh_shape_string_passes(self):
        """*_mesh_shape fields carry the topology a row ran on (ISSUE
        9): a "2x4"-style string in declared axis order."""
        rec = good_bench()
        rec["extra"].update({
            "shard_replicated_mesh_shape": "4x1",
            "shard_tp_mesh_shape": "2x2",
            "dryrun_mesh_shape": "2x2x2",
        })
        validate_record(rec)

    @pytest.mark.parametrize(
        "bad", [True, False, None, 8, "8", "2 x 4", "data2model4", ""]
    )
    def test_mesh_shape_rejects_non_topology_values(self, bad):
        rec = good_bench()
        rec["extra"]["shard_tp_mesh_shape"] = bad
        with pytest.raises(ValueError, match="mesh"):
            validate_record(rec)

    def test_non_dict_extra_fails(self):
        rec = good_bench()
        rec["extra"] = [1, 2]
        with pytest.raises(ValueError, match="extra"):
            validate_record(rec)

    def test_string_value_fails(self):
        rec = good_bench()
        rec["value"] = "1.23"
        with pytest.raises(ValueError, match="value"):
            validate_record(rec)


class TestSLOGate:
    """The SLO regression gate (ISSUE 11): bench exits non-zero with a
    NAMED reason when reference-load attainment drops below the pinned
    threshold — the check that turns the bench suite from a speedometer
    into a survival certificate."""

    def test_gate_passes_at_and_above_threshold(self):
        from bench import SLO_GATE_MIN, slo_gate

        assert slo_gate({"slo_reference_attainment": 1.0}) is None
        assert slo_gate(
            {"slo_reference_attainment": SLO_GATE_MIN}
        ) is None

    def test_gate_fails_below_threshold_with_named_reason(self):
        from bench import SLO_GATE_MIN, slo_gate

        reason = slo_gate(
            {"slo_reference_attainment": SLO_GATE_MIN - 0.05}
        )
        assert reason is not None
        assert "slo_regression" in reason
        assert str(SLO_GATE_MIN) in reason

    def test_gate_skips_when_soak_did_not_run(self):
        from bench import slo_gate

        assert slo_gate({}) is None

    def test_gate_rejects_non_numeric_attainment(self):
        from bench import slo_gate

        reason = slo_gate({"slo_reference_attainment": True})
        assert reason is not None and "non-numeric" in reason

    def test_gate_trip_exits_three_even_when_measured(self):
        """The exit-code contract: a tripped gate outranks 'something
        was measured' — the run fails loudly with the dedicated code."""
        from bench import bench_exit_code

        assert bench_exit_code(True, {}) == 0
        assert bench_exit_code(False, {}) == 1
        assert bench_exit_code(
            True, {"slo_gate": "slo_regression: ..."}
        ) == 3
        assert bench_exit_code(
            False, {"slo_gate": "slo_regression: ..."}
        ) == 3


class TestMultichipKinds:
    def test_partial_good(self):
        rec = {
            "dryrun_partial": {
                "n_devices": 8,
                "phases": {"build-main-mesh": {"s": 12.3, "mesh": {}}},
            },
            "elapsed_s": 13.0,
        }
        validate_record(rec, kind="multichip_partial")

    def test_partial_missing_phase_time_fails(self):
        rec = {
            "dryrun_partial": {"phases": {"compile": {"loss": 1.0}}},
            "elapsed_s": 3.0,
        }
        with pytest.raises(ValueError, match="compile"):
            validate_record(rec, kind="multichip_partial")

    def test_partial_bool_elapsed_fails(self):
        rec = {
            "dryrun_partial": {"phases": {}},
            "elapsed_s": True,
        }
        with pytest.raises(ValueError, match="elapsed_s"):
            validate_record(rec, kind="multichip_partial")

    def test_stalled_good(self):
        validate_record(
            {
                "dryrun_phase_stalled": "compile+5steps",
                "phase_budget_s": 165.0,
                "elapsed_s": 170.2,
                "completed": {},
            },
            kind="multichip_stalled",
        )

    def test_stalled_unnamed_fails(self):
        with pytest.raises(ValueError, match="name a phase"):
            validate_record(
                {"dryrun_phase_stalled": 3, "phase_budget_s": 1.0,
                 "elapsed_s": 1.0},
                kind="multichip_stalled",
            )

    def test_unknown_kind_fails(self):
        with pytest.raises(ValueError, match="unknown record kind"):
            validate_record(good_bench(), kind="nonsense")


class TestAnalysisReportSchema:
    """The invariant engine's --json report rides the same contract
    discipline as the bench rows: schema-validated at the emit site
    (analysis.validate_report), exercised here alongside
    validate_record so the two emitters can't drift apart (ISSUE 8)."""

    def good_report(self):
        return {
            "version": 1,
            "clean": False,
            "duration_s": 1.42,
            "files_scanned": 65,
            "rules_run": ["single_site", "donation"],
            "findings": [{
                "rule": "CST-DEC-001", "file": "x.py", "line": 3,
                "symbol": "f", "message": "top_k outside core",
            }],
            "suppressed": [{
                "rule": "CST-JIT-002", "file": "y.py", "line": 9,
                "symbol": "g", "message": "traced if",
                "justification": "argument is a static python flag",
            }],
            "unused_suppressions": [],
        }

    def test_good_report_passes(self):
        from cst_captioning_tpu.analysis import validate_report

        rec = self.good_report()
        assert validate_report(rec) is rec

    def test_clean_must_match_findings(self):
        from cst_captioning_tpu.analysis import validate_report

        rec = self.good_report()
        rec["clean"] = True        # but findings is non-empty
        with pytest.raises(ValueError, match="contradicts"):
            validate_report(rec)

    def test_suppressed_requires_justification(self):
        from cst_captioning_tpu.analysis import validate_report

        rec = self.good_report()
        rec["suppressed"][0]["justification"] = "  "
        with pytest.raises(ValueError, match="justification"):
            validate_report(rec)

    def test_bool_duration_fails(self):
        from cst_captioning_tpu.analysis import validate_report

        rec = self.good_report()
        rec["duration_s"] = True
        with pytest.raises(ValueError, match="duration_s"):
            validate_report(rec)

    def test_bench_preflight_extras_are_schema_clean(self):
        """The preflight's extra fields obey the bench record rules
        (numeric *_s, int counts — never bools)."""
        rec = good_bench()
        rec["extra"]["analysis_findings"] = 0
        rec["extra"]["analysis_duration_s"] = 1.42
        validate_record(rec)
        rec["extra"]["analysis_duration_s"] = True
        with pytest.raises(ValueError, match="analysis_duration_s"):
            validate_record(rec)

    @pytest.mark.parametrize("key", [
        "analysis_rules_active", "analysis_cache_hit_files",
        "analysis_findings",
        # ISSUE 15: the typeflow preflight provenance rides the same
        # numeric contract — family count and interpreter wall time.
        "analysis_families_active", "analysis_typeflow_duration_s",
    ])
    @pytest.mark.parametrize("bad", [True, False, None, "11", [3]])
    def test_analysis_extras_must_be_numeric(self, key, bad):
        """ISSUE 12: every analysis_* extra is a measurement — a
        bool/None/string value means the preflight didn't actually
        run/count what the row claims."""
        rec = good_bench()
        rec["extra"]["analysis_rules_active"] = 11
        rec["extra"]["analysis_cache_hit_files"] = 70
        rec["extra"]["analysis_findings"] = 0
        rec["extra"]["analysis_families_active"] = 13
        rec["extra"]["analysis_typeflow_duration_s"] = 0.41
        validate_record(rec)                 # numeric: fine
        rec["extra"][key] = bad
        with pytest.raises(ValueError, match=key):
            validate_record(rec)

    def test_report_cache_hit_files_bounds(self):
        """cache_hit_files in the --json report: optional, but when
        present a non-negative int bounded by files_scanned."""
        from cst_captioning_tpu.analysis import validate_report

        rec = self.good_report()
        validate_report(rec)                 # absent: fine (old schema)
        rec["cache_hit_files"] = 65
        validate_report(rec)
        rec["cache_hit_files"] = 66
        with pytest.raises(ValueError, match="exceeds"):
            validate_report(rec)
        rec["cache_hit_files"] = True
        with pytest.raises(ValueError, match="cache_hit_files"):
            validate_report(rec)
