"""Fused Bahdanau attention kernel: forward/backward parity vs the dense
XLA math (interpret mode on CPU), fallback behavior, and model-level
equivalence of the use_pallas attention captioner."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.ops.pallas_attention import (
    _pick_bt,
    dense_context_attention,
    fused_context_attention,
)


def make_inputs(B=32, F=56, A=128, E=64, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    return (
        jnp.asarray(rng.randn(B, A), dtype),
        jnp.asarray(rng.randn(B, F, A), dtype),
        jnp.asarray((rng.rand(B, F) > 0.2), jnp.float32),
        jnp.asarray(rng.randn(B, F, E), dtype),
        jnp.asarray(rng.randn(A, 1) * 0.1, dtype),
    )


class TestKernelParity:
    def test_forward_matches_dense(self):
        q, p, mask, vals, v = make_inputs()
        ref = dense_context_attention(q, p, mask, vals, v)
        got = fused_context_attention(q, p, mask, vals, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-6
        )

    def test_backward_matches_dense(self):
        q, p, mask, vals, v = make_inputs(seed=1)

        def loss(fn, q, p, vals, v):
            return jnp.sum(fn(q, p, mask, vals, v) ** 2)

        gd = jax.grad(
            lambda *a: loss(dense_context_attention, *a), argnums=(0, 1, 2, 3)
        )(q, p, vals, v)
        gf = jax.grad(
            lambda *a: loss(fused_context_attention, *a), argnums=(0, 1, 2, 3)
        )(q, p, vals, v)
        for a, b in zip(gd, gf):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            )

    def test_masked_frames_cannot_leak(self):
        q, p, mask, vals, v = make_inputs(seed=2)
        got = fused_context_attention(q, p, mask, vals, v)
        vals_pert = jnp.where(mask[..., None] > 0, vals, 1e3)
        got2 = fused_context_attention(q, p, mask, vals_pert, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(got2), rtol=1e-5, atol=1e-5
        )

    def test_jits(self):
        q, p, mask, vals, v = make_inputs(seed=3)
        out = jax.jit(fused_context_attention)(q, p, mask, vals, v)
        ref = dense_context_attention(q, p, mask, vals, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6
        )


class TestFallback:
    def test_untileable_batch_uses_dense(self):
        assert _pick_bt(7) is None and _pick_bt(12) is None
        q, p, mask, vals, v = make_inputs(B=7, seed=4)
        got = fused_context_attention(q, p, mask, vals, v)
        ref = dense_context_attention(q, p, mask, vals, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref))

    def test_tile_divides(self):
        for B in (8, 16, 32, 64, 1280):
            bt = _pick_bt(B)
            assert bt is not None and B % bt == 0 and bt % 8 == 0


class TestModelEquivalence:
    def test_attention_model_pallas_matches_dense(self):
        from cst_captioning_tpu.config import get_preset
        from cst_captioning_tpu.models import model_from_config

        cfg = get_preset("synthetic_smoke")
        cfg.model.feature_fusion = "attention"
        cfg.data.max_frames = 8
        cfg.model.vocab_size = 32
        rng = np.random.RandomState(5)
        B, F, D = 16, 8, 64
        feats = {"resnet": jnp.asarray(rng.randn(B, F, D), jnp.float32)}
        masks = {"resnet": jnp.ones((B, F)).at[:, -2:].set(0.0)}
        ids = jnp.asarray(
            rng.randint(4, 32, (B, 10)), jnp.int32
        ).at[:, 0].set(1)

        dense = model_from_config(cfg)
        cfg.model.use_pallas_attention = True
        fused = model_from_config(cfg)
        params = dense.init(jax.random.PRNGKey(0), feats, masks, ids)
        out_d = dense.apply(params, feats, masks, ids)
        out_f = fused.apply(params, feats, masks, ids)
        np.testing.assert_allclose(
            np.asarray(out_f), np.asarray(out_d), rtol=1e-4, atol=1e-4
        )
        # gradients flow through the custom VJP identically
        def loss(mdl, p):
            return jnp.sum(mdl.apply(p, feats, masks, ids) ** 2)

        gd = jax.grad(lambda p: loss(dense, p))(params)
        gf = jax.grad(lambda p: loss(fused, p))(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3
            ),
            gd,
            gf,
        )
