"""Regression tests for the ISSUE-15 accumulation-dtype fixes
(CST-DTY-003 true positives): the compute-dtype matmuls in
``ops/rnn.py::lstm_step``, ``ops/pallas_attention.py::
dense_context_attention`` and the captioner's cdt GEMMs now pin
``preferred_element_type=jnp.float32``.

Two kinds of pins:

* **jaxpr pins** — the lowered graph literally carries the f32
  accumulation attribute on the dot (reformulating the matmul back to
  a bare ``@`` fails here even though f32 test numerics would not
  notice);
* **bf16 accumulation pins** — with bf16 operands engineered so bf16
  accumulation visibly loses mass (many small addends against one
  large one), the pinned GEMM stays within f32-grade error of the
  true sum while an unpinned bf16 accumulation would not.

The f32 path is bit-identical by construction (``a @ b`` and
``jnp.matmul(a, b, preferred_element_type=f32)`` are the same op at
f32), which the existing golden/parity suites already pin — these
tests cover the bf16 behavior those suites cannot see.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.ops.pallas_attention import dense_context_attention
from cst_captioning_tpu.ops.rnn import LSTMWeights, lstm_step


def _dot_preferred_f32(jaxpr) -> bool:
    """True when every dot_general in the jaxpr accumulates f32."""
    dots = []

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "dot_general":
                dots.append(eqn.params.get("preferred_element_type"))
            for v in eqn.params.values():
                if hasattr(v, "jaxpr"):
                    walk(v.jaxpr)

    walk(jaxpr.jaxpr)
    assert dots, "no dot_general found — the matmul moved"
    return all(p == jnp.float32 for p in dots)


class TestJaxprPins:
    def test_lstm_step_gate_gemm_accumulates_f32(self):
        w = LSTMWeights(
            w=jnp.zeros((24, 32), jnp.bfloat16),
            b=jnp.zeros((32,), jnp.float32),
        )
        x = jnp.zeros((4, 16), jnp.float32)
        h = jnp.zeros((4, 8), jnp.float32)
        c = jnp.zeros((4, 8), jnp.float32)
        jx = jax.make_jaxpr(
            lambda *a: lstm_step(*a, compute_dtype=jnp.bfloat16)
        )(w, x, h, c)
        assert _dot_preferred_f32(jx)

    def test_dense_attention_gemms_accumulate_f32(self):
        B, F, A, E = 4, 6, 8, 8
        args = (
            jnp.zeros((B, A), jnp.bfloat16),
            jnp.zeros((B, F, A), jnp.bfloat16),
            jnp.ones((B, F), jnp.float32),
            jnp.zeros((B, F, E), jnp.bfloat16),
            jnp.zeros((A, 1), jnp.bfloat16),
        )
        jx = jax.make_jaxpr(dense_context_attention)(*args)
        assert _dot_preferred_f32(jx)

    def test_captioner_logit_and_proj_gemms_accumulate_f32(self):
        """Source-level pin for the captioner's cdt GEMMs (building a
        full model here is heavyweight; the analysis pass enforces the
        same contract at the AST via CST-DTY-003 on the registered
        low-precision paths — this asserts the registry keeps those
        paths registered)."""
        from cst_captioning_tpu.analysis.jit_registry import CAST_REGISTRY

        for key in (
            "models/captioner.py::CaptionModel._logits",
            "models/captioner.py::CaptionModel._encode",
            "models/captioner.py::CaptionModel._context",
        ):
            assert CAST_REGISTRY[key].low_precision, key


class TestBf16Accumulation:
    def test_lstm_gate_sum_survives_bf16_operands(self):
        """1024 addends of 2^-9 against bf16 operands: an f32
        accumulator sums them exactly (2.0); a bf16 accumulator stalls
        once the running sum is large enough that +2^-9 rounds away.
        The pinned GEMM must recover the mass."""
        hidden = 8
        in_dim = 1024 - hidden
        rng = np.random.default_rng(0)
        w = np.zeros((in_dim + hidden, 4 * hidden), np.float32)
        w[:, :] = 1.0
        weights = LSTMWeights(
            w=jnp.asarray(w, jnp.bfloat16),
            b=jnp.zeros((4 * hidden,), jnp.float32),
        )
        x = jnp.full((1, in_dim), 2.0 ** -9, jnp.float32)
        h = jnp.full((1, hidden), 2.0 ** -9, jnp.float32)
        del rng
        c = jnp.zeros((1, hidden), jnp.float32)
        h_new, c_new = lstm_step(
            weights, x, h, c, compute_dtype=jnp.bfloat16
        )
        # every gate pre-activation is sum(1024 * 2^-9) = 2.0 exactly
        # (both the addend and every partial sum are f32-representable)
        i = jax.nn.sigmoid(2.0)
        g = np.tanh(2.0)
        expect_c = float(i * g)
        got = float(c_new[0, 0])
        assert got == pytest.approx(expect_c, rel=1e-3), (
            "gate GEMM lost mass — bf16 accumulation snuck back in"
        )
        assert c_new.dtype == jnp.float32     # cell state stays f32
        assert h_new.dtype == jnp.bfloat16    # activations stay cdt

    def test_dense_attention_context_dtype_contract(self):
        """bf16 values in → bf16 context out (the f32 accumulation is
        internal; the dtype contract at the boundary is unchanged)."""
        B, F, A, E = 2, 3, 8, 8
        key = jax.random.PRNGKey(0)
        ks = jax.random.split(key, 5)
        q = jax.random.normal(ks[0], (B, A), jnp.bfloat16)
        proj = jax.random.normal(ks[1], (B, F, A), jnp.bfloat16)
        mask = jnp.ones((B, F), jnp.float32)
        vals = jax.random.normal(ks[2], (B, F, E), jnp.bfloat16)
        v = jax.random.normal(ks[3], (A, 1), jnp.bfloat16)
        ctx = dense_context_attention(q, proj, mask, vals, v)
        assert ctx.shape == (B, E)
        assert ctx.dtype == jnp.bfloat16
        # f32 reference: bf16 rounding only, no accumulation cliff
        ref = dense_context_attention(
            q.astype(jnp.float32), proj.astype(jnp.float32), mask,
            vals.astype(jnp.float32), v.astype(jnp.float32),
        )
        np.testing.assert_allclose(
            np.asarray(ctx, np.float32), np.asarray(ref),
            rtol=0.05, atol=0.05,
        )
