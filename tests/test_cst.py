"""CST/SCST tests: rewarder parity vs string-based CiderD, baseline
variants, and the SURVEY.md §4 integration bar — CST fine-tuning improves
the mean CIDEr-D reward on the toy corpus."""

import jax
import numpy as np
import pytest

from cst_captioning_tpu.config import get_preset
from cst_captioning_tpu.data import make_synthetic_dataset
from cst_captioning_tpu.metrics.cider import CiderD
from cst_captioning_tpu.training import Trainer
from cst_captioning_tpu.training.rewards import CiderDRewarder, ids_until_end


@pytest.fixture(scope="module")
def corpus():
    return make_synthetic_dataset(num_videos=12, max_frames=6, max_words=10,
                                  seed=5)


class TestRewarder:
    def test_exact_match_beats_garbage(self, corpus):
        ds, vocab = corpus
        rw = CiderDRewarder(ds)
        # candidate = first reference of video 0, vocab-encoded (no BOS/EOS)
        ref_ids = [
            vocab.word_to_idx[w] for w in ds.references(0)[0].split()
        ]
        L = 8
        good = np.zeros((1, L), np.int32)
        good[0, : len(ref_ids)] = ref_ids
        garbage = np.full((1, L), len(vocab) - 1, np.int32)
        vidx = np.zeros((1,), np.int32)
        s_good = rw.score_ids(vidx, good)[0]
        s_garbage = rw.score_ids(vidx, garbage)[0]
        assert s_good > 1.0
        assert s_good > 10 * max(s_garbage, 1e-9)

    def test_matches_string_ciderd(self, corpus):
        """Id-level scoring == string-level CiderD with corpus df over the
        same reference sets."""
        ds, vocab = corpus
        rw = CiderDRewarder(ds)
        gts = {str(i): [" ".join(map(str, ids_until_end(row)))
                        for row in ds.captions(i)]
               for i in range(len(ds))}
        # candidates: first ref of each video, as id-strings
        res = {str(i): [gts[str(i)][0]] for i in range(len(ds))}
        mean_str, per_str = CiderD(df_mode="corpus").compute_score(gts, res)

        L = ds.captions(0).shape[1]
        cands = np.zeros((len(ds), L), np.int32)
        for i in range(len(ds)):
            ids = ids_until_end(ds.captions(i)[0])
            cands[i, : len(ids)] = ids
        got = rw.score_ids(np.arange(len(ds), dtype=np.int32), cands)
        # String CiderD keys sort alphabetically ('0','1','10','11','2'...)
        order = sorted(range(len(ds)), key=str)
        np.testing.assert_allclose(got[order], per_str, rtol=1e-6)

    def test_ids_until_end(self):
        assert ids_until_end([1, 5, 6, 2, 7]) == [5, 6]
        assert ids_until_end([5, 0, 6]) == [5]
        assert ids_until_end([0, 5]) == []

    def test_unk_reward_channel(self, corpus):
        """Pin the UNK reward channel (VERDICT r3 weak #3): references
        are vocab-encoded with OOV -> UNK, so a rollout that EMITS UNK in
        an OOV slot matches the UNK-encoded reference n-gram and harvests
        reward a non-UNK token would not get.  This mirrors the
        reference's own behavior (its reward path scores vocab-decoded
        strings, collapsing every OOV to the same UNK token);
        model.decode_suppress_unk closes the channel when unwanted."""
        from cst_captioning_tpu.constants import UNK_ID
        from cst_captioning_tpu.data.vocab import Vocabulary

        class OOVDataset:
            """One video; second ref word is OOV for the vocab."""

            def __init__(self):
                self.vocab = Vocabulary(["cat", "runs", "fast"])

            def __len__(self):
                return 1

            def references(self, i):
                return ["cat zzcryptic runs fast"]  # zzcryptic -> UNK

        rw = CiderDRewarder(OOVDataset())
        w2i = rw.vocab.word_to_idx
        base = [w2i["cat"], UNK_ID, w2i["runs"], w2i["fast"]]
        with_unk = np.asarray([base], np.int32)
        without = np.asarray(
            [[w2i["cat"], w2i["fast"], w2i["runs"], w2i["fast"]]], np.int32
        )
        vidx = np.zeros((1,), np.int32)
        s_unk = float(rw.score_ids(vidx, with_unk)[0])
        s_plain = float(rw.score_ids(vidx, without)[0])
        # The UNK candidate exactly matches the UNK-encoded ref -> max
        # score; replacing the UNK slot with a real word loses the
        # n-grams through that slot.
        assert s_unk > s_plain * 1.5
        assert s_unk > 5.0

    def test_suppress_unk_masks_policy(self):
        from cst_captioning_tpu.constants import BOS_ID, PAD_ID, UNK_ID
        from cst_captioning_tpu.models.captioner import CaptionModel

        logits = jax.numpy.zeros((2, 8))
        opened = CaptionModel.mask_decode_logits(logits)
        closed = CaptionModel.mask_decode_logits(logits, True)
        assert float(opened[0, UNK_ID]) == 0.0
        assert float(closed[0, UNK_ID]) < -1e29
        for t in (PAD_ID, BOS_ID):
            assert float(opened[0, t]) < -1e29
            assert float(closed[0, t]) < -1e29

    def test_gt_consensus_respects_ref_weights(self):
        """With weighted_refs (cst_weighted_reward), the gt_consensus
        baseline must use the same per-reference consensus weights as
        score_ids rewards — otherwise the baseline sits on a different
        scale than the reward it is subtracted from."""
        ds, _ = make_synthetic_dataset(num_videos=4, max_frames=4,
                                       max_words=8, seed=9)
        base_uniform = CiderDRewarder(ds, backend="python").gt_consensus()
        n0 = len(ds.references(0))
        w0 = np.linspace(0.2, 2.0, n0).astype(np.float32)
        ds.set_caption_weights({ds.video_id(0): w0})
        rw = CiderDRewarder(ds, backend="python", weighted_refs=True)
        base_weighted = rw.gt_consensus()
        # Video 0's nonuniform weights must move its baseline; videos
        # with uniform (ones) weights keep the uniform-mean value.
        assert abs(base_weighted[0] - base_uniform[0]) > 1e-6
        np.testing.assert_allclose(
            base_weighted[1:], base_uniform[1:], rtol=1e-6
        )

    def test_gt_consensus_units_match_rewards(self, corpus):
        """gt_consensus() must be in score_ids units: a rollout equal to
        a reference scores in the same range as the GT consensus."""
        ds, vocab = corpus
        rw = CiderDRewarder(ds)
        base = rw.gt_consensus()
        assert base.shape == (len(ds),)
        assert (base > 0).all()
        # A candidate equal to ref 0 of video 0 scores >= that video's
        # mean GT consensus (it matches itself at 10 plus siblings).
        ids = ids_until_end(ds.captions(0)[0])
        cand = np.zeros((1, ds.captions(0).shape[1]), np.int32)
        cand[0, : len(ids)] = ids
        s = float(rw.score_ids(np.zeros((1,), np.int32), cand)[0])
        assert s >= base[0] * 0.9


def cst_cfg(tmp_path, baseline, **over):
    cfg = get_preset("synthetic_smoke")
    cfg.data.batch_size = 8
    cfg.data.seq_per_img = 2
    cfg.data.max_frames = 6
    cfg.data.max_seq_len = 11  # captions(0).shape[1]-1 (decode len)
    cfg.train.checkpoint_dir = str(tmp_path / "ckpt")
    cfg.train.train_mode = "cst"
    cfg.train.cst_baseline = baseline
    cfg.train.cst_num_samples = 3
    cfg.train.learning_rate = 5e-4
    cfg.train.max_epochs = 6
    cfg.train.max_patience = 0
    cfg.eval.metrics = ["CIDEr"]
    cfg.eval.max_decode_len = 11
    for k, v in over.items():
        setattr(cfg.train, k, v)
    return cfg


def xe_pretrain(ds, tmp_path, epochs=60):
    cfg = get_preset("synthetic_smoke")
    cfg.data.batch_size = 8
    cfg.data.seq_per_img = 3
    cfg.data.max_frames = 6
    cfg.train.checkpoint_dir = str(tmp_path / "xe")
    cfg.train.learning_rate = 3e-3
    cfg.train.max_epochs = epochs
    cfg.train.max_patience = 0
    cfg.eval.metrics = ["CIDEr"]
    cfg.eval.max_decode_len = 11
    t = Trainer(cfg, train_ds=ds, val_ds=None,
                workdir=str(tmp_path / "xe_w"))
    t.fit()
    return t


def split_setup(corpus, tmp_path, baseline, **cfg_over):
    """Shared harness for the split/one-graph step-equivalence tests:
    config, model, one fixed batch, optimizer, rewarder and a runner
    that builds a fresh state and applies one step."""
    from cst_captioning_tpu.data import BatchIterator
    from cst_captioning_tpu.models import model_from_config
    from cst_captioning_tpu.training.rewards import CiderDRewarder
    from cst_captioning_tpu.training.steps import (
        create_train_state,
        make_optimizer,
    )

    ds, _ = corpus
    cfg = cst_cfg(tmp_path, baseline, **cfg_over)
    cfg.model.vocab_size = len(ds.vocab)
    model = model_from_config(cfg)
    it = BatchIterator(ds, batch_size=8, seq_per_img=2, max_frames=6,
                       shuffle=False)
    batch = next(iter(it.epoch(0)))
    tx = make_optimizer(cfg.train, 10)
    rewarder = CiderDRewarder(ds)
    rng = jax.random.PRNGKey(3)

    def run(step_fn):
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, batch._asdict()
        )
        return step_fn(
            state, batch.feats, batch.feat_masks, batch.captions,
            batch.weights, None, batch.video_idx, rng, 0.0,
        )

    def run_steps(step_fn, n):
        """n steps (per-step fold-in rng) + pending-update flush ->
        (final state, list of per-call metrics incl. the flush's)."""
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, batch._asdict()
        )
        ms = []
        for i in range(n):
            state, m = step_fn(
                state, batch.feats, batch.feat_masks, batch.captions,
                batch.weights, None, batch.video_idx,
                jax.random.fold_in(rng, i), 0.0,
            )
            ms.append(m)
        flush = getattr(step_fn, "flush", None)
        if flush is not None:
            state, fm = flush(state)
            if fm:
                ms.append(fm)
        return state, ms

    run.steps = run_steps
    return cfg, model, rewarder, run


def assert_same_update(result_a, result_b):
    """Two (state, metrics) step results must agree on the scalar
    metrics and every updated parameter."""
    s1, m1 = result_a
    s2, m2 = result_b
    for k in ("loss", "reward", "baseline"):
        np.testing.assert_allclose(
            float(m1[k]), float(m2[k]), rtol=1e-5, atol=1e-7
        )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        s1.params,
        s2.params,
    )


class TestSplitStep:
    """The split (no-io_callback) CST path must match the one-graph path
    exactly: same rng -> same rollout -> same rewards -> same update."""

    @pytest.mark.parametrize("baseline", ["greedy", "scb", "gt_consensus"])
    def test_split_matches_one_graph(self, corpus, tmp_path, baseline):
        from cst_captioning_tpu.training.cst import (
            _make_one_graph_step,
            _make_split_step,
        )

        # chunks=1: the split rollout must replay the one-graph rollout's
        # exact rng stream (chunked dispatch folds rng per chunk).
        cfg, model, rewarder, run = split_setup(
            corpus, tmp_path, baseline, cst_score_chunks=1
        )
        assert_same_update(
            run(_make_one_graph_step(model, cfg, rewarder)),
            run(_make_split_step(model, cfg, rewarder)),
        )

    @pytest.mark.parametrize("baseline", ["greedy", "scb"])
    @pytest.mark.parametrize("chunks", [2, 4])
    def test_chunked_scoring_pipeline_is_exact(
        self, corpus, tmp_path, baseline, chunks
    ):
        """The overlapped K-chunk scoring pipeline (VERDICT r2 #2) must
        not change the step's math: at near-zero sampling temperature the
        rollout is deterministic regardless of rng, so K=1 and K>1 must
        produce identical updates."""
        from cst_captioning_tpu.training.cst import _make_split_step

        cfg, model, rewarder, run = split_setup(
            corpus, tmp_path, baseline, sample_temperature=1e-4
        )

        def at_chunks(k):
            cfg.train.cst_score_chunks = k
            return run(_make_split_step(model, cfg, rewarder))

        assert_same_update(at_chunks(1), at_chunks(chunks))

    @pytest.mark.parametrize("baseline", ["greedy", "scb"])
    def test_latency_gated_fused_layout_is_exact(
        self, corpus, tmp_path, baseline, monkeypatch
    ):
        """High-dispatch-latency runtimes take the fused single-dispatch
        layout (rollout + greedy in one graph) — it must produce the
        exact same update as the low-latency two-dispatch K=1 layout
        under the same rng."""
        from cst_captioning_tpu.training import cst as cst_mod

        cfg, model, rewarder, run = split_setup(
            corpus, tmp_path, baseline, cst_score_chunks=1
        )
        # Pin BOTH layouts explicitly — relying on the ambient cached
        # latency measurement could make the first run fused too (e.g.
        # on a loaded host) and the test would compare the fused layout
        # against itself.
        monkeypatch.setattr(cst_mod, "dispatch_latency_ms", lambda: 0.0)
        fast = run(cst_mod._make_split_step(model, cfg, rewarder))
        monkeypatch.setattr(cst_mod, "dispatch_latency_ms", lambda: 1e3)
        gated = run(cst_mod._make_split_step(model, cfg, rewarder))
        assert_same_update(fast, gated)

    @pytest.mark.parametrize("baseline", ["greedy", "scb"])
    def test_pipelined_layout_matches_split(
        self, corpus, tmp_path, baseline, monkeypatch
    ):
        """The software-pipelined layout (one dispatch per step holding
        [previous update + next rollout]) must reproduce the plain split
        step's parameter trajectory and per-step metrics exactly — only
        the dispatch boundaries move, with the trailing update applied by
        flush()."""
        from cst_captioning_tpu.training import cst as cst_mod

        cfg, model, rewarder, run = split_setup(
            corpus, tmp_path, baseline, cst_score_chunks=1
        )
        monkeypatch.setattr(cst_mod, "dispatch_latency_ms", lambda: 0.0)
        s_plain, m_plain = run.steps(
            cst_mod._make_split_step(model, cfg, rewarder), 3
        )
        s_pipe, m_pipe = run.steps(
            cst_mod._make_pipelined_step(model, cfg, rewarder), 3
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            s_plain.params,
            s_pipe.params,
        )
        # Same losses in the same order, shifted one call later (the
        # last one arrives via flush); same per-call reward stream.
        plain_losses = [float(m["loss"]) for m in m_plain]
        pipe_losses = [float(m["loss"]) for m in m_pipe if "loss" in m]
        np.testing.assert_allclose(
            pipe_losses, plain_losses, rtol=1e-5, atol=1e-7
        )
        assert "loss" not in m_pipe[0]
        np.testing.assert_allclose(
            [float(m["reward"]) for m in m_pipe if "reward" in m],
            [float(m["reward"]) for m in m_plain],
            rtol=1e-5, atol=1e-7,
        )

    def test_trainer_flushes_pipelined_updates(
        self, corpus, tmp_path, monkeypatch
    ):
        """End-to-end: a Trainer driving the pipelined layout must leave
        no pending update behind at epoch boundaries (state after fit()
        reflects every dispatched batch)."""
        from cst_captioning_tpu.training import cst as cst_mod

        ds, _ = corpus
        monkeypatch.setattr(cst_mod, "io_callback_supported", lambda: False)
        cfg = cst_cfg(tmp_path, "scb", cst_split_layout="pipeline")
        cfg.train.max_epochs = 2
        t = Trainer(cfg, train_ds=ds, val_ds=None,
                    workdir=str(tmp_path / "pipe_w"))
        assert getattr(t._train_step, "layout", "") == "pipeline"
        hist = t.fit()
        # Both epochs trained and recorded a (lagged) loss.
        assert set(hist) == {"0", "1"}
        for e in hist.values():
            assert np.isfinite(e["train_loss"])
        # flush left nothing pending.
        state2, fm = t._train_step.flush(t.state)
        assert fm is None

    @pytest.mark.parametrize("baseline", ["greedy", "scb"])
    def test_overlap_on_off_fixed_seed_parity(
        self, corpus, tmp_path, baseline, monkeypatch
    ):
        """The overlapped reward schedule (stream-fed pool scoring,
        single wait at the update dispatch) is scheduling only: a
        fixed-seed short CST run must produce IDENTICAL losses and
        params with overlap on (pooled, 2 workers) vs off (serial
        in-place scoring)."""
        from cst_captioning_tpu.training import cst as cst_mod
        from cst_captioning_tpu.training.rewards import RewardPool

        cfg, model, _, run = split_setup(
            corpus, tmp_path, baseline, cst_score_chunks=2
        )
        # Pin the PYTHON scorer on BOTH sides: the pool's parity
        # contract is vs python serial scoring (the native C++ backend
        # has its own float path and is never pooled —
        # make_reward_scorer gates it out).
        ds, _ = corpus
        rewarder = CiderDRewarder(ds, backend="python")
        monkeypatch.setattr(cst_mod, "dispatch_latency_ms", lambda: 0.0)
        cfg.train.overlap_rewards = False
        s_off, m_off = run.steps(
            cst_mod._make_split_step(model, cfg, rewarder), 3
        )
        cfg.train.overlap_rewards = True
        with RewardPool(rewarder, 2) as pool:
            s_on, m_on = run.steps(
                cst_mod._make_split_step(model, cfg, pool), 3
            )
        for a, b in zip(m_off, m_on):
            for k in ("loss", "reward", "baseline", "advantage"):
                assert float(a[k]) == float(b[k]), k
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            s_off.params,
            s_on.params,
        )

    def test_split_step_records_phase_breakdown(
        self, corpus, tmp_path, monkeypatch
    ):
        """Per-phase wall-time breakdown on train_step.phase_ms after a
        step — the observability surface the trainer/bench consume."""
        from cst_captioning_tpu.training import cst as cst_mod

        cfg, model, rewarder, run = split_setup(
            corpus, tmp_path, "greedy", cst_score_chunks=1
        )
        monkeypatch.setattr(cst_mod, "dispatch_latency_ms", lambda: 0.0)
        step = cst_mod._make_split_step(model, cfg, rewarder)
        assert step.layout == "split"
        run(step)
        for key in ("dispatch_ms", "sample_fetch_ms", "score_ms",
                    "greedy_fetch_ms", "update_ms", "total_ms"):
            assert key in step.phase_ms, step.phase_ms
            assert step.phase_ms[key] >= 0.0
        assert step.phase_ms["total_ms"] >= max(
            v for k, v in step.phase_ms.items() if k != "total_ms"
        )

    def test_trainer_logs_phase_breakdown(
        self, corpus, tmp_path, monkeypatch
    ):
        """End-to-end: a Trainer driving the split layout folds the
        per-phase means into the epoch history entry (phase_*_ms keys),
        so scoring regressions are visible in training logs."""
        from cst_captioning_tpu.training import cst as cst_mod

        ds, _ = corpus
        monkeypatch.setattr(cst_mod, "io_callback_supported", lambda: False)
        monkeypatch.setattr(cst_mod, "dispatch_latency_ms", lambda: 0.0)
        cfg = cst_cfg(tmp_path, "scb", cst_split_layout="chunked")
        cfg.train.max_epochs = 1
        t = Trainer(cfg, train_ds=ds, val_ds=None,
                    workdir=str(tmp_path / "phase_w"))
        hist = t.fit()
        e = hist["0"]
        for key in ("phase_sample_fetch_ms", "phase_score_ms",
                    "phase_update_ms", "phase_total_ms"):
            assert key in e and np.isfinite(e[key]), e

    def test_chunk_count_divisor_fallback(self):
        from cst_captioning_tpu.training.cst import _chunk_count

        assert _chunk_count(4, 8) == 4
        assert _chunk_count(4, 6) == 3   # largest divisor <= 4
        assert _chunk_count(3, 7) == 1   # prime batch
        assert _chunk_count(1, 64) == 1
        assert _chunk_count(16, 4) == 4  # capped at B

    def test_probe_runs(self):
        from cst_captioning_tpu.training.cst import io_callback_supported

        assert io_callback_supported() is True  # CPU supports it


class TestSlotRolloutStep:
    """The slot-based CST rollout (training/cst.py::SlotRollout via the
    unified decode core): fixed-seed padded-vs-slot runs must be
    BIT-identical — row-keyed PRNG means slot geometry and admission
    order carry no information (docs/PARITY.md slot-rollout contract)."""

    @pytest.mark.parametrize("baseline", ["greedy", "scb"])
    def test_padded_vs_slot_bit_identical(self, corpus, tmp_path,
                                          baseline):
        from cst_captioning_tpu.training.cst import _make_slot_step

        cfg_p, model_p, rewarder_p, run_p = split_setup(
            corpus, tmp_path, baseline, cst_rollout="padded"
        )
        s_pad, m_pad = run_p.steps(
            _make_slot_step(model_p, cfg_p, rewarder_p, "padded"), 2
        )
        cfg_s, model_s, rewarder_s, run_s = split_setup(
            corpus, tmp_path, baseline, cst_rollout="slot",
            cst_slot_count=5, cst_slot_block_steps=2,
        )
        s_slot, m_slot = run_s.steps(
            _make_slot_step(model_s, cfg_s, rewarder_s, "slot"), 2
        )
        for a, b in zip(m_pad, m_slot):
            for k in ("loss", "reward", "baseline", "advantage"):
                assert float(a[k]) == float(b[k]), k
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            s_pad.params,
            s_slot.params,
        )
        # The slot layout really paid fewer decode steps per row.
        assert float(m_slot[-1]["rollout_steps_per_row"]) <= float(
            m_pad[-1]["rollout_steps_per_row"]
        )

    def test_make_cst_train_step_dispatches_slot(self, corpus, tmp_path):
        from cst_captioning_tpu.training import cst as cst_mod

        cfg, model, rewarder, run = split_setup(
            corpus, tmp_path, "greedy", cst_rollout="slot"
        )
        ds, _ = corpus
        step = cst_mod.make_cst_train_step(model, cfg, ds)
        assert step.layout == "slot:slot"
        _, m = run(step)
        assert "rollout_steps_per_row" in m
        assert step.rollout_stats["rollout_rows"] == 8 * 3 + 8

    def test_unknown_rollout_layout_fails(self, corpus, tmp_path):
        from cst_captioning_tpu.training import cst as cst_mod

        cfg, model, _, _ = split_setup(
            corpus, tmp_path, "greedy", cst_rollout="banana"
        )
        ds, _ = corpus
        with pytest.raises(ValueError, match="cst_rollout"):
            cst_mod.make_cst_train_step(model, cfg, ds)


class TestShardedRewardCallback:
    """One-graph step with a data-sharded reward io_callback (the
    anti-involuntary-remat construction) must match the unannotated
    callback bit-for-bit."""

    @pytest.mark.parametrize("baseline", ["greedy", "scb"])
    def test_sharded_callback_matches_unsharded(
        self, corpus, tmp_path, baseline
    ):
        from cst_captioning_tpu.data import BatchIterator
        from cst_captioning_tpu.models import model_from_config
        from cst_captioning_tpu.parallel import (
            batch_sharding,
            make_mesh,
            shard_batch,
        )
        from cst_captioning_tpu.training.cst import _make_one_graph_step
        from cst_captioning_tpu.training.rewards import CiderDRewarder
        from cst_captioning_tpu.training.steps import (
            create_train_state,
            make_optimizer,
        )

        ds, _ = corpus
        cfg = cst_cfg(tmp_path, baseline)
        cfg.model.vocab_size = len(ds.vocab)
        mesh = make_mesh({"data": 4, "model": 2})
        model = model_from_config(cfg)
        it = BatchIterator(ds, batch_size=8, seq_per_img=2, max_frames=6,
                           shuffle=False)
        batch = next(iter(it.epoch(0)))
        tx = make_optimizer(cfg.train, 10)
        rewarder = CiderDRewarder(ds)
        rng = jax.random.PRNGKey(3)
        sh = batch_sharding(mesh)

        def run(step_mesh):
            state = create_train_state(
                jax.random.PRNGKey(0), model, tx, batch._asdict()
            )
            step = _make_one_graph_step(model, cfg, rewarder,
                                        mesh=step_mesh)
            return step(
                state,
                shard_batch(batch.feats, mesh),
                shard_batch(batch.feat_masks, mesh),
                jax.device_put(batch.captions, sh),
                jax.device_put(batch.weights, sh),
                None,
                jax.device_put(batch.video_idx, sh),
                rng, 0.0,
            )

        s_plain, m_plain = run(None)
        s_shard, m_shard = run(mesh)
        for k in ("loss", "reward", "baseline"):
            np.testing.assert_allclose(
                float(m_plain[k]), float(m_shard[k]), rtol=1e-5, atol=1e-7
            )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            s_plain.params,
            s_shard.params,
        )


class TestCSTTraining:
    @pytest.mark.parametrize("baseline", ["greedy", "scb", "none"])
    def test_step_runs_and_reports_reward(self, corpus, tmp_path, baseline):
        ds, _ = corpus
        cfg = cst_cfg(tmp_path, baseline)
        cfg.train.max_epochs = 1
        t = Trainer(cfg, train_ds=ds, val_ds=None,
                    workdir=str(tmp_path / f"w_{baseline}"))
        hist = t.fit()
        e = hist["0"]
        assert np.isfinite(e["train_loss"])
        assert np.isfinite(e["reward"]) and e["reward"] >= 0.0
        assert "baseline" in e and "advantage" in e

    def test_weighted_reward_end_to_end(self, corpus, tmp_path):
        """Driver config 4 (CST_MS, 20-ref weighted CIDEr): the step runs
        with cst_weighted_reward and reports a reward distinct from the
        uniform-mean regime under identical seeds."""
        ds, _ = corpus
        rng = np.random.RandomState(17)
        ds.set_caption_weights(
            {
                ds.video_id(i): rng.uniform(
                    0.2, 2.0, size=len(ds.references(i))
                ).astype(np.float32)
                for i in range(len(ds))
            }
        )
        try:
            rewards = {}
            for weighted in (False, True):
                cfg = cst_cfg(tmp_path, "scb",
                              cst_weighted_reward=weighted)
                cfg.train.max_epochs = 1
                t = Trainer(
                    cfg, train_ds=ds, val_ds=None,
                    workdir=str(tmp_path / f"wr_{weighted}"),
                )
                hist = t.fit()
                assert np.isfinite(hist["0"]["reward"])
                rewards[weighted] = hist["0"]["reward"]
            assert rewards[True] != rewards[False]
        finally:
            ds._weight_override = None  # module-scoped fixture

    def test_cst_use_gt_dispatches_to_wxe(self, corpus, tmp_path):
        """CST_GT_None: train_mode=cst + cst_use_gt trains on the GT
        captions via the weighted-XE step — same metrics as the wxe mode."""
        ds, _ = corpus

        def run(tag, **over):
            cfg = cst_cfg(tmp_path, "none", **over)
            cfg.train.max_epochs = 1
            t = Trainer(cfg, train_ds=ds, val_ds=None,
                        workdir=str(tmp_path / f"gt_{tag}"))
            return t.fit()["0"]

        e_gt = run("cst", cst_use_gt=True)
        assert np.isfinite(e_gt["train_loss"])
        assert "reward" not in e_gt  # XE-style metrics, no rollouts
        e_wxe = run("wxe", train_mode="wxe")
        np.testing.assert_allclose(
            e_gt["train_loss"], e_wxe["train_loss"], rtol=1e-6
        )

    def test_cst_improves_reward_after_warm_start(self, corpus, tmp_path):
        """The paper's staging: XE pretrain -> CST fine-tune; mean rollout
        reward must go up over CST epochs (SURVEY.md §4 'CST smoke')."""
        ds, _ = corpus
        from cst_captioning_tpu.training.checkpoint import save_checkpoint

        pre = xe_pretrain(ds, tmp_path)
        stage1 = str(tmp_path / "stage1")
        save_checkpoint(stage1, pre.state)

        cfg = cst_cfg(tmp_path, "greedy", start_from=stage1)
        # 16 epochs with a leading-vs-trailing MEAN comparison: the
        # per-epoch rollout reward on this 12-video toy oscillates with
        # the PRNG stream (which differs across jax/backend versions —
        # the 8-epoch single-endpoint form of this test was stream-lucky
        # and went red on a jax upgrade while real-scale CST kept
        # climbing, docs/REHEARSAL.md r6), and the r5/r6 rehearsal
        # lesson applies at smoke scale too: give slow starters budget.
        cfg.train.max_epochs = 16
        t = Trainer(cfg, train_ds=ds, val_ds=None,
                    workdir=str(tmp_path / "cst_w"))
        hist = t.fit()
        rewards = [hist[str(e)]["reward"] for e in range(16)]
        head, tail = np.mean(rewards[:3]), np.mean(rewards[-3:])
        assert tail > head, (
            f"reward did not improve: {head:.4f} -> {tail:.4f} ({rewards})"
        )
