"""Low-precision serving fast path (ISSUE 16, ops/quant.py + the
``serving.dtype`` knob).

Pins the tentpole contracts:

* **Quantization math** — symmetric per-channel int8 weight-only
  codes round-trip within half a scale step per element, all-zero
  channels quantize exactly (scale 1.0 guard), and ``quant_matmul``
  accumulates f32 with the scale applied AFTER the accumulation (the
  CST-DTY-003 idiom the corpus seed mirrors).
* **Scale sharding** — every ``*_scale`` leaf's partition spec follows
  the channel axis of the weight it dequantizes (shard-aligned
  post-accumulation multiply, no gather), straight from the live
  rule table, keyed by ``quant_axis``.
* **f32 byte-identity** — ``serving.dtype="f32"`` is byte-identical
  to an engine that never heard of the knob: same params bytes, same
  ``params_tag`` (cache keys keep hitting), no scale leaves.
* **Relaxed-serving parity** — bf16/int8w engines hold the pinned
  machine-checked bounds vs the f32 engine on the fixed eval set:
  caption-match rate >= RELAXED_SERVING_MATCH_FLOOR and per-caption
  beam-score gap <= RELAXED_SERVING_SCORE_RTOL
  (analysis/jit_registry.py, docs/PARITY.md r17).
* **Quantized AOT artifacts** — an int8w engine publishes its scales
  (hashed into the artifact version), boots from the artifact with
  ``compile_count == 0`` token-exact vs warm, and the loader refuses
  a ``serving_dtype`` or scale-hash divergence by name.
"""

import json
import os
import shutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.analysis.jit_registry import (
    RELAXED_SERVING_MATCH_FLOOR,
    RELAXED_SERVING_SCORE_RTOL,
)
from cst_captioning_tpu.config import get_preset
from cst_captioning_tpu.data.vocab import Vocabulary
from cst_captioning_tpu.decoding.beam import make_beam_search_fn
from cst_captioning_tpu.ops import quant
from cst_captioning_tpu.parallel import partition
from cst_captioning_tpu.serving.artifact import (
    MANIFEST_NAME,
    ArtifactMismatchError,
    build_artifact,
)
from cst_captioning_tpu.serving.engine import InferenceEngine


# ------------------------------------------------------------- primitives

class TestQuantPrimitives:
    def test_round_trip_within_half_a_step(self):
        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(16, 24).astype(np.float32) * 3.0)
        for axis in (0, 1):
            q, scale = quant.quantize_per_channel(w, axis)
            assert q.dtype == jnp.int8
            assert scale.dtype == jnp.float32
            assert scale.shape == (w.shape[axis],)
            assert int(jnp.max(jnp.abs(q))) <= 127
            dq = quant.dequantize(q, scale, axis)
            shape = [1, 1]
            shape[axis] = w.shape[axis]
            step = scale.reshape(shape)
            # symmetric rounding: |w - dq| <= scale/2 per element
            assert bool(jnp.all(jnp.abs(w - dq) <= step / 2 + 1e-6))

    def test_zero_channel_gets_unit_scale_and_exact_zero(self):
        w = jnp.zeros((4, 6), jnp.float32).at[1].set(2.0)
        q, scale = quant.quantize_per_channel(w, 0)
        assert float(scale[0]) == 1.0          # guard, not 0/0
        assert bool(jnp.all(q[0] == 0))
        dq = quant.dequantize(q, scale, 0)
        assert bool(jnp.all(dq[0] == 0.0))
        # the nonzero channel saturates its own range exactly at max
        assert int(jnp.max(jnp.abs(q[1]))) == 127

    def test_quant_matmul_is_f32_scale_after_accumulation(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(4, 16).astype(np.float32)).astype(
            jnp.bfloat16
        )
        w = jnp.asarray(rng.randn(16, 32).astype(np.float32))
        q, scale = quant.quantize_per_channel(w, 1)
        y = quant.quant_matmul(x, q, scale)
        assert y.dtype == jnp.float32
        # scale-after-accumulation: y == (x @ q) * scale with the codes
        # accumulated in f32 — int8 magnitudes are exact in bf16, so
        # the quantized matmul adds NO error beyond the code rounding
        ref = (
            jnp.matmul(
                x.astype(jnp.float32), q.astype(jnp.float32)
            ) * scale
        )
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_dequant_rows_matches_full_dequantize(self):
        rng = np.random.RandomState(2)
        emb = jnp.asarray(rng.randn(10, 8).astype(np.float32))
        q, scale = quant.quantize_per_channel(emb, 0)
        ids = jnp.asarray([3, 0, 7], jnp.int32)
        rows = quant.dequant_rows(q, scale, ids, jnp.bfloat16)
        assert rows.dtype == jnp.bfloat16
        full = quant.dequantize(q, scale, 0).astype(jnp.bfloat16)
        assert bool(jnp.all(rows == full[ids]))

    def test_quantize_params_and_template_agree(self):
        rng = np.random.RandomState(3)
        tree = {"params": {
            "word_embed": jnp.asarray(rng.randn(8, 4), jnp.float32),
            "logit_w": jnp.asarray(rng.randn(4, 8), jnp.float32),
            "lstm0_w": jnp.asarray(rng.randn(8, 16), jnp.float32),
            "att_wf": jnp.asarray(rng.randn(4, 6), jnp.float32),
            "att_b": jnp.asarray(rng.randn(6), jnp.float32),
        }}
        assert not quant.is_quantized(tree)
        qt = quant.quantize_params(tree)
        p = qt["params"]
        assert quant.is_quantized(qt)
        assert p["word_embed"].dtype == jnp.int8
        assert p["word_embed_scale"].shape == (8,)
        assert p["logit_w_scale"].shape == (8,)       # axis 1 channels
        assert p["lstm0_w_scale"].shape == (16,)
        assert p["att_wf_scale"].shape == (6,)
        assert p["att_b"].dtype == jnp.float32        # biases untouched
        # the zero-filled template names the SAME tree structure (what
        # restore_params needs to load a quantized artifact checkpoint)
        t = quant.quantize_template(
            jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                         tree)
        )
        assert (jax.tree_util.tree_structure(t)
                == jax.tree_util.tree_structure(qt))

    def test_quantized_leaf_bytes_closed_form(self):
        wbytes, sbytes = quant.quantized_leaf_bytes((64, 256), 1)
        assert wbytes == 64 * 256          # 1 byte/element
        assert sbytes == 256 * 4           # f32 scale per channel
        # the headline ratio: int8 weight payload is exactly 0.25x f32
        assert wbytes * 4 == 64 * 256 * 4

    def test_unknown_calibration_is_a_named_error(self):
        with pytest.raises(ValueError, match="calibration"):
            quant.quantize_per_channel(
                jnp.ones((4, 4), jnp.float32), 0, "minmax"
            )

    def test_percentile_calibration_clips_the_outlier_tail(self):
        """serving.quant_calibration="percentile": the per-channel scale
        comes from the 99.9th percentile of |w|, so a planted outlier
        tail saturates at +-127 while everything inside the percentile
        keeps the <= scale/2 round-trip bound with a FINER step than
        absmax would have chosen."""
        rng = np.random.RandomState(4)
        w = rng.randn(2000, 4).astype(np.float32)
        w[:2, :] = 50.0                   # 2 outliers per column channel
        w = jnp.asarray(w)
        qa, sa = quant.quantize_per_channel(w, 1, "absmax")
        qp, sp = quant.quantize_per_channel(w, 1, "percentile")
        assert qp.dtype == jnp.int8 and sp.dtype == jnp.float32
        # percentile scale is strictly finer: the outliers set absmax's
        # step (50/127) but sit past the 99.9th percentile here
        assert bool(jnp.all(sp < sa))
        assert bool(jnp.all(sp * 127.0 < 50.0))
        assert bool(jnp.all(jnp.abs(qp[:2]) == 127))   # tail saturates
        dq = quant.dequantize(qp, sp, 1)
        inside = jnp.abs(w) <= sp[None, :] * 127.0
        err = jnp.where(inside, jnp.abs(w - dq), 0.0)
        assert bool(jnp.all(err <= sp[None, :] / 2 + 1e-6))
        # absmax is still the documented default — positionally stable
        q_dflt, s_dflt = quant.quantize_per_channel(w, 1)
        assert bool(jnp.all(s_dflt == sa))
        assert bool(jnp.all(q_dflt == qa))

    def test_quantize_params_plumbs_calibration(self):
        rng = np.random.RandomState(5)
        emb = rng.randn(8, 2000).astype(np.float32)
        emb[:, :2] = 30.0                 # per-row outlier pair
        tree = {"params": {
            "word_embed": jnp.asarray(emb),
            "logit_b": jnp.zeros((8,), jnp.float32),
        }}
        qa = quant.quantize_params(tree)["params"]
        qp = quant.quantize_params(tree, "percentile")["params"]
        assert bool(jnp.all(qp["word_embed_scale"]
                            < qa["word_embed_scale"]))
        # scale_hashes (the artifact integrity record) see the choice
        assert (quant.scale_hashes({"params": qa})
                != quant.scale_hashes({"params": qp}))
        with pytest.raises(ValueError, match="calibration"):
            quant.quantize_params(tree, "median")


# ---------------------------------------------------------- scale specs

class TestScaleShardingSpecs:
    @pytest.mark.parametrize("name", [
        "word_embed", "logit_w", "lstm0_w", "lstm1_w", "att_wf", "att_wh",
    ])
    def test_scale_spec_follows_weight_channel_axis(self, name):
        """The ``*_scale`` spec is the weight spec PROJECTED onto its
        quantization axis — sharded iff the channel dim is sharded, so
        the post-accumulation multiply never gathers."""
        axis = quant.quant_axis(name)
        assert axis is not None, f"{name} is not a quantized leaf"
        w_spec = tuple(partition.spec_for_leaf(name))
        channel = w_spec[axis] if axis < len(w_spec) else None
        s_spec = tuple(partition.spec_for_leaf(name + quant.SCALE_SUFFIX))
        assert s_spec == ((channel,) if channel is not None else ()), (
            f"{name}: weight spec {w_spec} axis {axis} vs scale "
            f"spec {s_spec}"
        )

    def test_biases_and_vectors_are_not_quantized(self):
        for name in ("logit_b", "lstm0_b", "att_b", "att_v",
                     "proj_resnet_w", "cat_embed"):
            assert quant.quant_axis(name) is None, name


# ------------------------------------------------------------- engines

def _tiny_cfg(dtype="f32"):
    cfg = get_preset("synthetic_smoke")
    cfg.serving.warmup = False
    cfg.serving.num_slots = 4
    cfg.serving.slot_bank_min = 2
    cfg.serving.max_batch_size = 4
    cfg.serving.batch_shapes = [2, 4]
    cfg.serving.dtype = dtype
    return cfg


def _payloads(cfg, n, seed=0):
    rng = np.random.RandomState(seed)
    d = cfg.data
    return [
        {
            "features": {
                m: rng.randn(d.max_frames, d.feature_dims[m]).astype(
                    np.float32
                )
                for m in d.feature_modalities
            }
        }
        for _ in range(n)
    ]


def _captions(engine, payloads):
    reqs = [engine.prepare(dict(p)) for p in payloads]
    out = []
    step = engine.cfg.serving.max_batch_size
    for i in range(0, len(reqs), step):
        out += [
            r.caption
            for r in engine.decode_prepared(reqs[i:i + step], store=False)
        ]
    return out


def _beam_scores(engine, payloads):
    """Length-normalized beam scores of the served caption per payload
    (the per-caption score the relaxed-serving gap bound is pinned on)."""
    cfg = engine.cfg
    reqs = [engine.prepare(dict(p)) for p in payloads]
    feats = {
        m: jnp.asarray(np.stack([r.feats[m] for r in reqs]))
        for m in reqs[0].feats
    }
    masks = {
        m: jnp.asarray(np.stack([r.masks[m] for r in reqs]))
        for m in reqs[0].masks
    }
    fn = make_beam_search_fn(
        engine.model,
        beam_size=cfg.eval.beam_size,
        max_len=cfg.eval.max_decode_len,
        length_normalize=cfg.eval.length_normalize,
    )
    return np.asarray(fn(engine.params, feats, masks).score, np.float64)


@pytest.fixture(scope="module")
def dtype_world():
    """One vocab, one random init, three serving dtypes (plus a
    knob-free baseline for the byte-identity pin)."""
    vocab = Vocabulary([f"w{i}" for i in range(60)])

    def mk(dtype):
        cfg = _tiny_cfg(dtype)
        cfg.model.vocab_size = len(vocab)
        return InferenceEngine(cfg, random_init=True, vocab=vocab)

    baseline_cfg = get_preset("synthetic_smoke")
    baseline_cfg.serving.warmup = False
    baseline_cfg.serving.num_slots = 4
    baseline_cfg.serving.slot_bank_min = 2
    baseline_cfg.serving.max_batch_size = 4
    baseline_cfg.serving.batch_shapes = [2, 4]
    baseline_cfg.model.vocab_size = len(vocab)
    baseline = InferenceEngine(baseline_cfg, random_init=True, vocab=vocab)
    return {
        "baseline": baseline,
        "f32": mk("f32"),
        "bf16": mk("bf16"),
        "int8w": mk("int8w"),
    }


class TestServingDtypeEngines:
    def test_unknown_dtype_is_a_named_error(self):
        cfg = _tiny_cfg("fp8")
        with pytest.raises(ValueError, match="serving.dtype"):
            InferenceEngine(cfg, random_init=True)

    def test_f32_knob_is_byte_identical(self, dtype_world):
        """serving.dtype="f32" changes NOTHING: same bytes, same
        params_tag (tier-1/2 cache keys keep hitting), no scale
        leaves, identical captions."""
        base, f32 = dtype_world["baseline"], dtype_world["f32"]
        assert f32.serving_dtype == "f32"
        assert f32.params_tag == base.params_tag
        assert "|dt" not in f32.params_tag
        bl = jax.tree_util.tree_leaves_with_path(base.params)
        fl = jax.tree_util.tree_leaves_with_path(f32.params)
        assert len(bl) == len(fl)
        for (bp, bv), (fp, fv) in zip(bl, fl):
            assert partition.path_str(bp) == partition.path_str(fp)
            assert not partition.path_str(fp).endswith(quant.SCALE_SUFFIX)
            assert bv.dtype == fv.dtype
            assert np.array_equal(np.asarray(bv), np.asarray(fv))
        p = _payloads(f32.cfg, 4)
        assert _captions(f32, p) == _captions(base, p)

    def test_int8w_quantizes_the_published_leaves(self, dtype_world):
        e = dtype_world["int8w"]
        p = e.params["params"] if "params" in e.params else e.params
        assert e.serving_dtype == "int8w"
        assert e.params_tag.endswith("|dtint8w")
        assert quant.is_quantized(e.params)
        assert p["logit_w"].dtype == jnp.int8
        assert p["word_embed"].dtype == jnp.int8
        assert p["logit_w_scale"].dtype == jnp.float32
        assert p["logit_b"].dtype == jnp.float32
        # honest byte accounting: quantized residency really shrinks
        f32_bytes = dtype_world["f32"].param_bytes_per_shard()
        int8_bytes = e.param_bytes_per_shard()
        assert int8_bytes < 0.6 * f32_bytes
        assert e.fingerprint()["serving_dtype"] == "int8w"
        assert e.describe()["serving_dtype"] == "int8w"
        assert e.describe()["param_bytes_per_shard"] == int8_bytes
        assert e.slot_decoder().describe()["serving_dtype"] == "int8w"

    @pytest.mark.parametrize("dtype", ["bf16", "int8w"])
    def test_relaxed_serving_parity_bounds(self, dtype_world, dtype):
        """THE relaxed-serving contract (docs/PARITY.md r17), machine
        checked on the fixed eval set: caption-match rate vs f32 >=
        the pinned floor, per-caption beam-score gap <= the pinned
        rtol.  The same bounds gate the lowprec_* bench rows BEFORE
        they record."""
        f32, low = dtype_world["f32"], dtype_world[dtype]
        payloads = _payloads(f32.cfg, 8)
        ref = _captions(f32, payloads)
        got = _captions(low, payloads)
        match = sum(a == b for a, b in zip(ref, got)) / len(ref)
        assert match >= RELAXED_SERVING_MATCH_FLOOR, (
            f"{dtype}: caption-match rate {match:.3f} below the pinned "
            f"floor {RELAXED_SERVING_MATCH_FLOOR}"
        )
        s_ref = _beam_scores(f32, payloads)
        s_low = _beam_scores(low, payloads)
        gap = np.abs(s_low - s_ref) / np.maximum(np.abs(s_ref), 1e-6)
        assert float(gap.max()) <= RELAXED_SERVING_SCORE_RTOL, (
            f"{dtype}: max per-caption score gap {gap.max():.4f} above "
            f"the pinned rtol {RELAXED_SERVING_SCORE_RTOL}"
        )

    def test_unknown_calibration_knob_refused_at_boot(self):
        cfg = _tiny_cfg("int8w")
        cfg.serving.quant_calibration = "median"
        with pytest.raises(ValueError, match="calibration"):
            InferenceEngine(cfg, random_init=True)

    def test_percentile_calibration_holds_relaxed_bounds(self, dtype_world):
        """serving.quant_calibration="percentile" on the SAME float
        weights: the scales actually move (different hashes than
        absmax), and the engine still holds the relaxed-serving parity
        contract vs f32 — the calibration knob trades step size, never
        the machine-checked bound."""
        f32 = dtype_world["f32"]
        cfg = _tiny_cfg("int8w")
        cfg.model.vocab_size = len(f32.vocab)
        cfg.serving.quant_calibration = "percentile"
        eng = InferenceEngine(cfg, params=f32.params, vocab=f32.vocab)
        assert quant.is_quantized(eng.params)
        assert (quant.scale_hashes(eng.params)
                != quant.scale_hashes(dtype_world["int8w"].params))
        payloads = _payloads(f32.cfg, 8)
        ref = _captions(f32, payloads)
        got = _captions(eng, payloads)
        match = sum(a == b for a, b in zip(ref, got)) / len(ref)
        assert match >= RELAXED_SERVING_MATCH_FLOOR, (
            f"percentile: caption-match rate {match:.3f} below the "
            f"pinned floor {RELAXED_SERVING_MATCH_FLOOR}"
        )
        s_ref = _beam_scores(f32, payloads)
        s_low = _beam_scores(eng, payloads)
        gap = np.abs(s_low - s_ref) / np.maximum(np.abs(s_ref), 1e-6)
        assert float(gap.max()) <= RELAXED_SERVING_SCORE_RTOL


# ------------------------------------------------- autoscale under int8w

class TestInt8wAutoscale:
    def test_add_replica_boots_from_the_quantized_tree(self, dtype_world):
        """Scale-up under serving.dtype=int8w (ISSUE 18): the replica
        admitted by ``ReplicaSet.add_replica`` boots from the ALREADY
        quantized tree — the ``is_quantized`` boot guard skips
        requantization, so there is no double rounding: ``params_tag``,
        every scale hash, and the int8 codes themselves are identical
        to replica 0's."""
        from cst_captioning_tpu.serving.metrics import ServingMetrics
        from cst_captioning_tpu.serving.replicas import ReplicaSet

        e0 = dtype_world["int8w"]
        dev = jax.devices()[0]
        r0 = e0.clone_for_device(dev, replica_id=0)
        rs = ReplicaSet([r0], ServingMetrics())
        rid = rs.add_replica(e0.clone_for_device(dev))
        assert rid == 1
        r1 = rs.replicas[rid].engine
        assert r1.replica_id == rid        # admission stamps the id
        assert quant.is_quantized(r1.params)
        # the tier-1/2 cache-key contract: one logical model fleet-wide
        assert r1.params_tag == r0.params_tag
        h0 = quant.scale_hashes(r0.params)
        assert h0 and quant.scale_hashes(r1.params) == h0
        p0, p1 = (p["params"] if "params" in p else p
                  for p in (r0.params, r1.params))
        for name in p0:
            if quant.quant_axis(name) is None:
                continue
            assert p1[name].dtype == jnp.int8, name
            assert np.array_equal(
                np.asarray(p0[name]), np.asarray(p1[name])
            ), f"{name}: int8 codes moved across add_replica"


# ----------------------------------------------------- quantized artifact

@pytest.fixture(scope="module")
def int8w_artifact(dtype_world, tmp_path_factory):
    engine = dtype_world["int8w"]
    root = str(tmp_path_factory.mktemp("int8w_artifacts"))
    summary = build_artifact(engine, root)
    return engine, summary


def _decode_all(engine, decoder, payloads):
    reqs = [engine.prepare(dict(p)) for p in payloads]
    pending = list(enumerate(reqs))
    got = {}
    while pending or decoder.occupied:
        n = min(1, len(pending), len(decoder.free))
        batch = [pending.pop(0) for _ in range(n)]
        done = decoder.tick([r for _, r in batch], [i for i, _ in batch])
        for i, tokens, _score, _steps in decoder.harvest_many(done):
            got[i] = tokens
    return [got[i] for i in range(len(payloads))]


class TestInt8wArtifact:
    def test_manifest_carries_lowprec_provenance(self, int8w_artifact):
        _, summary = int8w_artifact
        with open(os.path.join(summary["path"], MANIFEST_NAME)) as f:
            man = json.load(f)
        assert man["serving_dtype"] == "int8w"
        assert man["scale_hashes"], "int8w build published no scale hashes"
        for name in ("logit_w_scale", "word_embed_scale"):
            assert any(k.endswith(name) for k in man["scale_hashes"]), name

    def test_boot_zero_compiles_token_exact(self, int8w_artifact):
        """Quantize ONCE at build: the artifact restores int8 codes +
        scales directly (no boot-time requantization), compiles
        nothing, and serves the exact warm-engine tokens."""
        engine, summary = int8w_artifact
        booted = InferenceEngine.from_artifact(summary["path"])
        assert booted.serving_dtype == "int8w"
        assert quant.is_quantized(booted.params)
        dec = booted.slot_decoder()
        assert dec.compile_count == 0
        payloads = _payloads(engine.cfg, 5, seed=7)
        warm = _decode_all(engine, engine.slot_decoder(), payloads)
        aot = _decode_all(booted, dec, payloads)
        for a, b in zip(warm, aot):
            assert np.array_equal(a, b)
        assert dec.compile_count == 0

    def _tampered(self, summary, tmp_path, mutate):
        vdir = os.path.join(str(tmp_path), "tampered")
        shutil.copytree(summary["path"], vdir)
        mpath = os.path.join(vdir, MANIFEST_NAME)
        with open(mpath) as f:
            man = json.load(f)
        mutate(man)
        with open(mpath, "w") as f:
            json.dump(man, f)
        return vdir

    def test_serving_dtype_divergence_refused(
        self, int8w_artifact, tmp_path
    ):
        _, summary = int8w_artifact

        def flip(man):
            man["serving_dtype"] = "f32"

        vdir = self._tampered(summary, tmp_path, flip)
        with pytest.raises(ArtifactMismatchError) as ei:
            InferenceEngine.from_artifact(vdir)
        assert any(f == "serving_dtype" for f, _, _ in ei.value.mismatches)

    def test_scale_hash_drift_refused(self, int8w_artifact, tmp_path):
        _, summary = int8w_artifact

        def drift(man):
            key = sorted(man["scale_hashes"])[0]
            man["scale_hashes"][key] = "0" * 16

        vdir = self._tampered(summary, tmp_path, drift)
        with pytest.raises(ArtifactMismatchError) as ei:
            InferenceEngine.from_artifact(vdir)
        assert any(f == "scale_hashes" for f, _, _ in ei.value.mismatches)
