"""Aux subsystems (SURVEY.md §5) + pipeline runner: remat equivalence,
nan guard, profiler traces, staged XE->WXE->CST pipeline."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.config import get_preset
from cst_captioning_tpu.data import make_synthetic_dataset
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.training import Trainer


class TestRemat:
    def test_forward_and_grads_match(self):
        rng = np.random.RandomState(0)
        V, B, T, F, D, H = 19, 4, 6, 5, 8, 12
        feats = {"resnet": jnp.asarray(rng.randn(B, F, D), jnp.float32)}
        masks = {"resnet": jnp.ones((B, F))}
        ids = jnp.asarray(rng.randint(4, V, (B, T)), jnp.int32).at[:, 0].set(1)

        def build(remat):
            return CaptionModel(
                vocab_size=V, rnn_size=H, num_layers=1, embed_size=H,
                modalities=("resnet",), feature_dims=(D,), drop_prob=0.0,
                compute_dtype="float32", remat=remat,
            )

        m0, m1 = build(False), build(True)
        params = m0.init(jax.random.PRNGKey(0), feats, masks, ids)
        np.testing.assert_allclose(
            np.asarray(m0.apply(params, feats, masks, ids)),
            np.asarray(m1.apply(params, feats, masks, ids)),
            rtol=1e-6,
        )
        g0 = jax.grad(lambda p: jnp.sum(m0.apply(p, feats, masks, ids) ** 2))(
            params
        )
        g1 = jax.grad(lambda p: jnp.sum(m1.apply(p, feats, masks, ids) ** 2))(
            params
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            g0,
            g1,
        )

    def test_config_plumbing(self):
        from cst_captioning_tpu.models import model_from_config

        cfg = get_preset("synthetic_smoke")
        cfg.model.vocab_size = 10
        cfg.train.remat = True
        assert model_from_config(cfg).remat is True


def smoke_trainer(tmp_path, **over):
    ds, _ = make_synthetic_dataset(num_videos=16, max_frames=6, seed=1)
    cfg = get_preset("synthetic_smoke")
    cfg.data.batch_size = 8
    cfg.data.seq_per_img = 2
    cfg.train.checkpoint_dir = str(tmp_path / "ck")
    cfg.train.max_epochs = 1
    cfg.train.max_patience = 0
    cfg.eval.metrics = ["CIDEr"]
    cfg.eval.max_decode_len = 11
    for k, v in over.items():
        setattr(cfg.train, k, v)
    return ds, cfg


class TestNanCheck:
    def test_raises_on_nonfinite_loss(self, tmp_path):
        ds, cfg = smoke_trainer(tmp_path, nan_check=True)
        t = Trainer(cfg, train_ds=ds, val_ds=None,
                    workdir=str(tmp_path / "w"))
        real_step = t._train_step

        def poisoned(*args, **kw):
            state, metrics = real_step(*args, **kw)
            metrics["loss"] = jnp.float32(float("nan"))
            return state, metrics

        t._train_step = poisoned
        with pytest.raises(FloatingPointError, match="non-finite loss"):
            t.fit()

    def test_clean_run_passes(self, tmp_path):
        ds, cfg = smoke_trainer(tmp_path, nan_check=True)
        t = Trainer(cfg, train_ds=ds, val_ds=None,
                    workdir=str(tmp_path / "w2"))
        hist = t.fit()
        assert np.isfinite(hist["0"]["train_loss"])


class TestProfiler:
    def test_trace_written(self, tmp_path):
        prof = str(tmp_path / "prof")
        ds, cfg = smoke_trainer(tmp_path, profile_dir=prof)
        t = Trainer(cfg, train_ds=ds, val_ds=None,
                    workdir=str(tmp_path / "w3"))
        t.fit()
        traces = glob.glob(os.path.join(prof, "**", "*"), recursive=True)
        assert any(os.path.isfile(p) for p in traces), "no trace files"


class TestTensorBoard:
    def test_event_files_written(self, tmp_path):
        pytest.importorskip("tensorflow")
        tb = str(tmp_path / "tb")
        ds, cfg = smoke_trainer(tmp_path, tensorboard_dir=tb)
        t = Trainer(cfg, train_ds=ds, val_ds=ds,
                    workdir=str(tmp_path / "w4"))
        t.fit()
        # Events are namespaced per run name under the logdir.
        events = glob.glob(
            os.path.join(tb, "**", "events.out.tfevents.*"), recursive=True
        )
        assert events, "no TensorBoard event files written"
        # train scalars + val metrics both land in the stream
        import tensorflow as tf

        tags = set()
        for path in events:
            for ev in tf.compat.v1.train.summary_iterator(path):
                for v in ev.summary.value:
                    tags.add(v.tag)
        assert any(tag.startswith("train/") for tag in tags), tags
        assert any(tag.startswith("val/") for tag in tags), tags


class TestPipeline:
    def test_staged_pipeline_runs_and_evaluates(self, tmp_path):
        from cst_captioning_tpu.cli.pipeline import run_pipeline

        cfg = get_preset("synthetic_smoke")
        cfg.data.batch_size = 8
        cfg.data.seq_per_img = 2
        cfg.data.max_seq_len = 11
        cfg.train.checkpoint_dir = str(tmp_path / "ck")
        cfg.train.max_epochs = 1
        cfg.train.max_patience = 0
        cfg.train.cst_num_samples = 2
        cfg.eval.metrics = ["CIDEr"]
        cfg.eval.beam_size = 2
        cfg.eval.max_decode_len = 11
        results = run_pipeline(cfg, ["xe", "wxe", "cst_greedy"],
                               eval_split="test")
        assert set(results) == {"xe", "wxe", "cst_greedy", "eval"}
        # every stage trained and checkpointed
        for stage in ("xe", "wxe", "cst_greedy"):
            wd = os.path.join(
                cfg.train.checkpoint_dir, f"{cfg.name}_{stage}"
            )
            assert os.path.exists(os.path.join(wd, "best")) or os.path.exists(
                os.path.join(wd, "last")
            )
        assert "CIDEr" in results["eval"]["scores"]
        assert os.path.exists(
            os.path.join(results["eval"]["out_dir"], "scores.json")
        )
