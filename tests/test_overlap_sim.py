"""Chunked-scoring overlap simulation: smoke + shape of the evidence.

The timing ASSERTIONS here are deliberately loose — CI hosts are noisy
and the chunked math's exactness is already pinned by
tests/test_cst.py::test_chunked_scoring_pipeline_is_exact; what this
guards is that the simulation harness runs, reports every field the
bench records, and injects the scorer cost it claims to."""

from cst_captioning_tpu.tools.overlap_sim import credibility, simulate


def test_simulate_reports_all_fields():
    out = simulate(
        sleep_ms=8.0, chunks=2, steps=2, batch=8, rollouts=2, reps=2
    )
    # Auto-escalation may raise reps beyond the requested 2 on a noisy
    # host, never lower them.
    assert out["cst_overlap_sim_reps"] >= 2
    assert "cst_overlap_sim_recovered_ms_sd" in out
    for key in (
        "cst_overlap_sim_dispatch_latency_ms",
        "cst_overlap_sim_rollout_compute_ms",
        "cst_overlap_sim_injected_scorer_ms",
        "cst_overlap_sim_k1_step_ms",
        "cst_overlap_sim_k2_step_ms",
        "cst_overlap_sim_recovered_ms",
        "cst_overlap_sim_recoverable_ms",
        "cst_overlap_sim_recovered_frac",
        "cst_overlap_sim_noisy",
    ):
        assert key in out, key
    assert out["cst_overlap_sim_injected_scorer_ms"] == 8.0
    # The injected scorer must actually cost time: both layouts' steps
    # take at least the serialized floor of one chunk's scoring.
    assert out["cst_overlap_sim_k1_step_ms"] >= 8.0
    assert out["cst_overlap_sim_dispatch_latency_ms"] < 5.0, (
        "sim must run on the in-process CPU backend"
    )
    # The headline fraction is always in [0, 1] (raw preserved aside).
    assert 0.0 <= out["cst_overlap_sim_recovered_frac"] <= 1.0
    assert isinstance(out["cst_overlap_sim_noisy"], bool)


class TestCredibility:
    """VERDICT r5 #5: the BENCH_r05 record carried recovered_frac
    1.144 ± 0.301 — >100% recovery — with nothing flagging it."""

    def test_clean_measurement(self):
        recovered, frac, raw, noisy = credibility(
            [50.0, 52.0, 51.0], 65.0
        )
        assert abs(recovered - 51.0) < 1e-9
        assert 0.0 < frac < 1.0 and frac == raw
        assert not noisy

    def test_frac_above_one_is_clamped_and_flagged(self):
        # The exact BENCH_r05 regime: mean recovery above recoverable.
        recovered, frac, raw, noisy = credibility(
            [74.0, 75.0, 74.5], 65.0
        )
        assert raw > 1.0
        assert frac == 1.0
        assert noisy

    def test_negative_recovery_is_clamped_and_flagged(self):
        _, frac, raw, noisy = credibility([-5.0, -6.0], 65.0)
        assert raw < 0.0 and frac == 0.0 and noisy

    def test_wide_spread_is_flagged(self):
        # sd/mean far above 0.3 at a plausible mean.
        _, frac, raw, noisy = credibility([10.0, 60.0, 110.0], 100.0)
        assert noisy and 0.0 <= frac <= 1.0

    def test_tight_spread_not_flagged(self):
        *_, noisy = credibility([58.0, 60.0, 62.0], 65.0)
        assert not noisy

    def test_simulate_escalates_reps_when_noisy(self, monkeypatch):
        """Force perpetual noisiness: simulate must escalate up to the
        cap instead of recording 2 noisy reps."""
        monkeypatch.setenv("CST_OVERLAP_SIM_MAX_REPS", "4")
        import cst_captioning_tpu.tools.overlap_sim as osim

        monkeypatch.setattr(
            osim, "credibility",
            lambda pp, rec: (0.0, 0.0, 0.0, True),
        )
        out = simulate(
            sleep_ms=5.0, chunks=2, steps=1, batch=8, rollouts=2, reps=2
        )
        assert out["cst_overlap_sim_reps"] == 4
        assert out["cst_overlap_sim_noisy"] is True
