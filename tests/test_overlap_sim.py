"""Chunked-scoring overlap simulation: smoke + shape of the evidence.

The timing ASSERTIONS here are deliberately loose — CI hosts are noisy
and the chunked math's exactness is already pinned by
tests/test_cst.py::test_chunked_scoring_pipeline_is_exact; what this
guards is that the simulation harness runs, reports every field the
bench records, and injects the scorer cost it claims to."""

from cst_captioning_tpu.tools.overlap_sim import simulate


def test_simulate_reports_all_fields():
    out = simulate(
        sleep_ms=8.0, chunks=2, steps=2, batch=8, rollouts=2, reps=2
    )
    assert out["cst_overlap_sim_reps"] == 2
    assert "cst_overlap_sim_recovered_ms_sd" in out
    for key in (
        "cst_overlap_sim_dispatch_latency_ms",
        "cst_overlap_sim_rollout_compute_ms",
        "cst_overlap_sim_injected_scorer_ms",
        "cst_overlap_sim_k1_step_ms",
        "cst_overlap_sim_k2_step_ms",
        "cst_overlap_sim_recovered_ms",
        "cst_overlap_sim_recoverable_ms",
        "cst_overlap_sim_recovered_frac",
    ):
        assert key in out, key
    assert out["cst_overlap_sim_injected_scorer_ms"] == 8.0
    # The injected scorer must actually cost time: both layouts' steps
    # take at least the serialized floor of one chunk's scoring.
    assert out["cst_overlap_sim_k1_step_ms"] >= 8.0
    assert out["cst_overlap_sim_dispatch_latency_ms"] < 5.0, (
        "sim must run on the in-process CPU backend"
    )
