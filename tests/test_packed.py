"""Packed contiguous feature store: round-trip parity with per-video
reads, loader fast-path equivalence, CLI converter, and an assembly
throughput sanity check (SURVEY.md hot loop #3)."""

import time

import numpy as np
import pytest

from cst_captioning_tpu.data import BatchIterator, make_synthetic_dataset
from cst_captioning_tpu.data.packed import (
    PackedSource,
    is_packed_dir,
    pack_dataset,
)


@pytest.fixture(scope="module")
def corpus():
    return make_synthetic_dataset(
        num_videos=20, feature_dims={"resnet": 32, "c3d": 16}, max_frames=6,
        seed=9,
    )


class TestPackRoundTrip:
    @pytest.mark.parametrize("dtype", ["float32", "float16"])
    def test_get_matches_dataset(self, corpus, tmp_path, dtype):
        ds, _ = corpus
        d = str(tmp_path / f"packed_{dtype}")
        pack_dataset(ds, d, max_frames=6, dtype=dtype)
        assert is_packed_dir(d)
        src = PackedSource(d, "resnet")
        tol = 1e-6 if dtype == "float32" else 2e-3
        for i in (0, 7, 19):
            ref = ds.features(i)["resnet"]
            got = src.get(i)
            assert got.shape == ref.shape and got.dtype == np.float32
            np.testing.assert_allclose(got, ref, rtol=tol, atol=tol)

    def test_get_batch_gather_and_mask(self, corpus, tmp_path):
        ds, _ = corpus
        d = str(tmp_path / "packed")
        pack_dataset(ds, d, max_frames=6)
        src = PackedSource(d, "c3d")
        idxs = np.asarray([3, 3, 11, 0])
        feats, mask = src.get_batch(idxs, 6)
        assert feats.shape == (4, 6, 16) and mask.shape == (4, 6)
        for b, i in enumerate(idxs):
            ref = ds.features(int(i))["c3d"]
            n = ref.shape[0]
            np.testing.assert_allclose(feats[b, :n], ref, rtol=1e-6)
            assert mask[b].sum() == n and (feats[b, n:] == 0).all()

    def test_max_frames_guard(self, corpus, tmp_path):
        ds, _ = corpus
        d = str(tmp_path / "packed")
        pack_dataset(ds, d, max_frames=6)
        for bad in (5, 7):  # any mismatch: no silent temporal crop
            with pytest.raises(ValueError, match="packed frames"):
                PackedSource(d, "resnet").get_batch(np.asarray([0]), bad)


class TestRemotePackedSource:
    """fsspec-URL packed stores (VERDICT r2 #8): the memory:// filesystem
    stands in for gs:// — same code path (url_to_fs + ranged reads)."""

    @pytest.fixture()
    def remote_dir(self, corpus, tmp_path):
        fsspec = pytest.importorskip("fsspec")
        ds, _ = corpus
        local = str(tmp_path / "packed_remote_src")
        pack_dataset(ds, local, max_frames=6, dtype="float16")
        fs = fsspec.filesystem("memory")
        import os

        for name in os.listdir(local):
            with open(os.path.join(local, name), "rb") as f:
                fs.pipe(f"/packtest/{name}", f.read())
        yield "memory://packtest"
        fs.rm("/packtest", recursive=True)

    def test_is_packed_dir_remote(self, remote_dir):
        assert is_packed_dir(remote_dir)
        assert not is_packed_dir("memory://no_such_dir_anywhere")

    def test_remote_matches_local(self, corpus, tmp_path, remote_dir):
        ds, _ = corpus
        local = str(tmp_path / "packed_remote_src")
        src_l = PackedSource(local, "resnet")
        src_r = PackedSource(remote_dir, "resnet")
        assert src_r.video_ids == src_l.video_ids
        for i in (0, 7, 19):
            np.testing.assert_array_equal(src_r.get(i), src_l.get(i))
        idxs = np.asarray([5, 0, 19, 5])
        fr, mr = src_r.get_batch(idxs, 6)
        fl, ml = src_l.get_batch(idxs, 6)
        assert fr.dtype == fl.dtype == np.float16  # stored dtype kept
        np.testing.assert_array_equal(np.asarray(fr), np.asarray(fl))
        np.testing.assert_array_equal(mr, ml)

    def test_remote_max_frames_guard(self, remote_dir):
        with pytest.raises(ValueError, match="packed frames"):
            PackedSource(remote_dir, "resnet").get_batch(
                np.asarray([0]), 5
            )


class TestLoaderFastPath:
    def test_batches_identical_to_per_video(self, corpus, tmp_path):
        """The packed gather must produce bit-identical batches to the
        per-video read path under the same seed."""
        from cst_captioning_tpu.data.datasets import H5Dataset
        from cst_captioning_tpu.tools.prepare_data import prepare
        import json

        ds, _ = corpus
        # Build an h5-backed split whose features come from the packed dir.
        raw = {
            "splits": {"train": [ds.video_id(i) for i in range(len(ds))]},
            "captions": {
                ds.video_id(i): ds.references(i) for i in range(len(ds))
            },
        }
        ann = tmp_path / "ann.json"
        ann.write_text(json.dumps(raw))
        out = str(tmp_path / "prep")
        paths = prepare(str(ann), "simple", out, max_words=10)
        d = str(tmp_path / "packed")
        pack_dataset(ds, d, max_frames=6)

        from cst_captioning_tpu.data.vocab import Vocabulary

        vocab = Vocabulary.load(paths["vocab"])
        packed_ds = H5Dataset(
            paths["labels_train"], {"resnet": d, "c3d": d}, vocab
        )
        assert packed_ds.feature_dims == {"resnet": 32, "c3d": 16}
        assert packed_ds.features_batch(np.asarray([0, 1]), 6) is not None

        def batches(dataset):
            it = BatchIterator(
                dataset, batch_size=4, seq_per_img=2, max_frames=6,
                shuffle=True, seed=3,
            )
            return list(it.epoch(0))

        # Per-video path: same dataset object with the fast path disabled.
        got = batches(packed_ds)
        plain = batches(ds)  # InMemory original (no features_batch)
        # Same videos in the same shuffled order (same seed over same size)
        for bg, bp in zip(got, plain):
            order = [
                [ds.video_id(i) for i in range(len(ds))].index(v)
                for v in bg.video_ids
            ]
            np.testing.assert_array_equal(
                np.asarray(order, np.int32), bp.video_idx
            )
            for m in ("resnet", "c3d"):
                np.testing.assert_allclose(
                    bg.feats[m], bp.feats[m], rtol=1e-6, atol=1e-6
                )
                np.testing.assert_array_equal(
                    bg.feat_masks[m], bp.feat_masks[m]
                )

    def test_pack_features_cli(self, corpus, tmp_path):
        import h5py
        import json

        from cst_captioning_tpu.tools.pack_features import main as pack_main
        from cst_captioning_tpu.tools.prepare_data import prepare

        ds, _ = corpus
        raw = {
            "splits": {"train": [ds.video_id(i) for i in range(len(ds))]},
            "captions": {
                ds.video_id(i): ds.references(i) for i in range(len(ds))
            },
        }
        ann = tmp_path / "ann.json"
        ann.write_text(json.dumps(raw))
        paths = prepare(str(ann), "simple", str(tmp_path / "prep"),
                        max_words=10)
        feat_h5 = str(tmp_path / "resnet.h5")
        with h5py.File(feat_h5, "w") as f:
            for i in range(len(ds)):
                f.create_dataset(
                    ds.video_id(i), data=ds.features(i)["resnet"]
                )
        out = str(tmp_path / "packed_cli")
        pack_main([
            "--label-file", paths["labels_train"],
            "--features", f"resnet={feat_h5}",
            "--out-dir", out, "--max-frames", "6",
        ])
        src = PackedSource(out, "resnet")
        np.testing.assert_allclose(
            src.get(2), ds.features(2)["resnet"], rtol=1e-6
        )


class TestThroughput:
    def test_packed_assembly_faster_than_per_video_h5(self, tmp_path):
        """MSR-VTT-shaped (scaled-down) assembly race: the packed gather
        must beat per-video h5 reads comfortably."""
        import h5py

        rng = np.random.RandomState(0)
        V, F, D = 64, 28, 512
        feats = rng.randn(V, F, D).astype(np.float32)
        h5p = str(tmp_path / "f.h5")
        with h5py.File(h5p, "w") as f:
            for i in range(V):
                f.create_dataset(f"v{i}", data=feats[i])
        d = str(tmp_path / "packed")
        from cst_captioning_tpu.data.packed import pack_modality

        pack_modality(
            d, "resnet", [f"v{i}" for i in range(V)],
            (feats[i] for i in range(V)), F, D,
        )
        src = PackedSource(d, "resnet")
        idxs = rng.permutation(V)[:32]

        src.get_batch(idxs, F)  # warm page cache
        t0 = time.perf_counter()
        for _ in range(5):
            src.get_batch(idxs, F)
        t_packed = time.perf_counter() - t0

        with h5py.File(h5p, "r") as f:
            t0 = time.perf_counter()
            for _ in range(5):
                out = np.zeros((len(idxs), F, D), np.float32)
                for b, i in enumerate(idxs):
                    out[b] = f[f"v{i}"][()]
            t_h5 = time.perf_counter() - t0
        assert t_packed < t_h5, (t_packed, t_h5)
