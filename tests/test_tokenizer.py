"""Golden tests for the PTB tokenization pipeline (SURVEY.md §4 "tokenizer
parity"): outputs must match what coco-caption's Java PTBTokenizer + punct
strip would produce for caption-style text."""

from cst_captioning_tpu.metrics.tokenizer import (
    ptb_tokenize,
    ptb_word_tokenize,
    tokenize_corpus,
)


def test_basic_lowercase_and_punct_strip():
    assert ptb_tokenize("A man is Playing a Guitar.") == \
        ["a", "man", "is", "playing", "a", "guitar"]


def test_commas_and_final_period():
    assert ptb_tokenize("a dog, a cat, and a bird.") == \
        ["a", "dog", "a", "cat", "and", "a", "bird"]


def test_contractions_split():
    # CoreNLP splits "doesn't" -> "does" + "n't"; punctuation strip keeps both.
    assert ptb_tokenize("The dog doesn't run") == ["the", "dog", "does", "n't", "run"]
    assert ptb_tokenize("he's running") == ["he", "'s", "running"]
    assert ptb_tokenize("they're here") == ["they", "'re", "here"]


def test_question_exclamation():
    assert ptb_tokenize("is it real?!") == ["is", "it", "real"]


def test_brackets_normalized_then_stripped():
    # ( ) -> -LRB- -RRB- which are in the punctuation strip list.
    assert ptb_word_tokenize("a (small) dog")[1] == "-LRB-"
    assert ptb_tokenize("a (small) dog") == ["a", "small", "dog"]


def test_ellipsis_and_dashes_stripped():
    assert ptb_tokenize("wait... what -- no") == ["wait", "what", "no"]


def test_quotes_stripped():
    assert ptb_tokenize('he said "hello world"') == ["he", "said", "hello", "world"]


def test_numbers_kept():
    assert ptb_tokenize("2 men play 3 games") == ["2", "men", "play", "3", "games"]


def test_interior_period_not_split():
    # PTB only splits sentence-final periods; "u.s." style stays intact.
    assert ptb_tokenize("the u.s. team wins") == ["the", "u.s.", "team", "wins"]


def test_tokenize_corpus_shape():
    out = tokenize_corpus({"v1": ["A Dog runs.", "a CAT sits!"], "v2": ["Hi."]})
    assert out == {"v1": ["a dog runs", "a cat sits"], "v2": ["hi"]}
