"""CaptionModel unit tests: shapes, determinism, end-token semantics,
fusion modes, multi-modality, scheduled sampling, bfloat16 path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.models import (
    CaptionModel,
    PAD_ID,
    BOS_ID,
    EOS_ID,
)

V, B, T, F, D, H = 23, 4, 7, 5, 12, 16


def make_model(**kw):
    kwargs = dict(
        vocab_size=V,
        rnn_size=H,
        num_layers=1,
        embed_size=H,
        fusion="meanpool",
        att_hidden_size=H,
        drop_prob=0.0,
        modalities=("resnet",),
        feature_dims=(D,),
        compute_dtype="float32",
    )
    kwargs.update(kw)
    return CaptionModel(**kwargs)


def make_batch(rng, modalities=("resnet",), dims=(D,)):
    feats = {
        m: jnp.asarray(rng.randn(B, F, d).astype(np.float32))
        for m, d in zip(modalities, dims)
    }
    masks = {m: jnp.ones((B, F)) for m in modalities}
    ids = jnp.asarray(rng.randint(4, V, size=(B, T)), jnp.int32)
    ids = ids.at[:, 0].set(BOS_ID)
    return feats, masks, ids


@pytest.fixture(scope="module")
def np_rng():
    return np.random.RandomState(42)


class TestForward:
    def test_shapes_and_dtype(self, np_rng):
        model = make_model()
        feats, masks, ids = make_batch(np_rng)
        params = model.init(jax.random.PRNGKey(0), feats, masks, ids)
        logits = model.apply(params, feats, masks, ids)
        assert logits.shape == (B, T, V)
        assert logits.dtype == jnp.float32

    def test_bfloat16_path_runs(self, np_rng):
        model = make_model(compute_dtype="bfloat16")
        feats, masks, ids = make_batch(np_rng)
        params = model.init(jax.random.PRNGKey(0), feats, masks, ids)
        logits = model.apply(params, feats, masks, ids)
        assert logits.dtype == jnp.float32
        assert np.isfinite(np.asarray(logits)).all()

    def test_attention_fusion(self, np_rng):
        model = make_model(fusion="attention")
        feats, masks, ids = make_batch(np_rng)
        params = model.init(jax.random.PRNGKey(0), feats, masks, ids)
        logits = model.apply(params, feats, masks, ids)
        assert logits.shape == (B, T, V)

    def test_attention_respects_frame_mask(self, np_rng):
        """Masked frames must not influence attention output."""
        model = make_model(fusion="attention")
        feats, masks, ids = make_batch(np_rng)
        params = model.init(jax.random.PRNGKey(0), feats, masks, ids)
        masks2 = {"resnet": jnp.ones((B, F)).at[:, -2:].set(0.0)}
        base = model.apply(params, feats, masks2, ids)
        # Garbage in the masked frames: output must not change.
        feats2 = {"resnet": feats["resnet"].at[:, -2:].set(1e4)}
        pert = model.apply(params, feats2, masks2, ids)
        np.testing.assert_allclose(np.asarray(base), np.asarray(pert), atol=2e-4)

    def test_multimodal_and_category(self, np_rng):
        model = make_model(
            modalities=("resnet", "c3d"),
            feature_dims=(D, 2 * D),
            use_category=True,
            num_categories=5,
            category_embed_size=8,
        )
        feats, masks, ids = make_batch(np_rng, ("resnet", "c3d"), (D, 2 * D))
        cat = jnp.asarray(np_rng.randint(0, 5, size=(B,)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), feats, masks, ids, category=cat)
        logits = model.apply(params, feats, masks, ids, category=cat)
        assert logits.shape == (B, T, V)
        # Category must actually matter.
        logits2 = model.apply(params, feats, masks, ids, category=(cat + 1) % 5)
        assert not np.allclose(np.asarray(logits), np.asarray(logits2))

    def test_two_layer(self, np_rng):
        model = make_model(num_layers=2)
        feats, masks, ids = make_batch(np_rng)
        params = model.init(jax.random.PRNGKey(0), feats, masks, ids)
        assert model.apply(params, feats, masks, ids).shape == (B, T, V)

    def test_grads_flow_everywhere(self, np_rng):
        model = make_model(fusion="attention")
        feats, masks, ids = make_batch(np_rng)
        params = model.init(jax.random.PRNGKey(0), feats, masks, ids)

        def loss(p):
            return jnp.sum(model.apply(p, feats, masks, ids) ** 2)

        grads = jax.grad(loss)(params)
        flat = jax.tree_util.tree_leaves_with_path(grads)
        for path, g in flat:
            assert np.abs(np.asarray(g)).sum() > 0, f"zero grad at {path}"

    @pytest.mark.parametrize("fusion", ["meanpool", "attention"])
    def test_repeat_matches_pretiled_features(self, np_rng, fusion):
        """repeat=S (cache tiled AFTER the projections) must equal
        tiling the raw features BEFORE the model — the S x projection
        saving may not change a single logit."""
        S = 3
        model = make_model(fusion=fusion)
        feats, masks, ids = make_batch(np_rng)
        ids_r = jnp.repeat(ids, S, axis=0)
        params = model.init(jax.random.PRNGKey(0), feats, masks, ids)
        out_repeat = model.apply(params, feats, masks, ids_r, repeat=S)
        feats_t = {m: jnp.repeat(v, S, axis=0) for m, v in feats.items()}
        masks_t = {m: jnp.repeat(v, S, axis=0) for m, v in masks.items()}
        out_tiled = model.apply(params, feats_t, masks_t, ids_r)
        np.testing.assert_allclose(
            np.asarray(out_repeat), np.asarray(out_tiled),
            rtol=1e-6, atol=1e-6,
        )

    def test_repeat_grads_match_pretiled(self, np_rng):
        S = 2
        model = make_model()
        feats, masks, ids = make_batch(np_rng)
        ids_r = jnp.repeat(ids, S, axis=0)
        params = model.init(jax.random.PRNGKey(0), feats, masks, ids)
        feats_t = {m: jnp.repeat(v, S, axis=0) for m, v in feats.items()}
        masks_t = {m: jnp.repeat(v, S, axis=0) for m, v in masks.items()}

        def loss_repeat(p):
            return jnp.sum(
                model.apply(p, feats, masks, ids_r, repeat=S) ** 2
            )

        def loss_tiled(p):
            return jnp.sum(model.apply(p, feats_t, masks_t, ids_r) ** 2)

        g1 = jax.grad(loss_repeat)(params)
        g2 = jax.grad(loss_tiled)(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
            ),
            g1, g2,
        )

    def test_scheduled_sampling_changes_output(self, np_rng):
        model = make_model()
        feats, masks, ids = make_batch(np_rng)
        params = model.init(jax.random.PRNGKey(0), feats, masks, ids)
        base = model.apply(params, feats, masks, ids, ss_prob=0.0)
        ss = model.apply(
            params, feats, masks, ids, ss_prob=1.0, rng=jax.random.PRNGKey(7)
        )
        assert not np.allclose(np.asarray(base), np.asarray(ss))
        # First-step logits identical: BOS input is never replaced.
        np.testing.assert_allclose(
            np.asarray(base[:, 0]), np.asarray(ss[:, 0]), rtol=1e-5
        )

    def test_dropout_train_vs_eval(self, np_rng):
        model = make_model(drop_prob=0.5)
        feats, masks, ids = make_batch(np_rng)
        params = model.init(jax.random.PRNGKey(0), feats, masks, ids)
        e1 = model.apply(params, feats, masks, ids)
        e2 = model.apply(params, feats, masks, ids)
        np.testing.assert_allclose(np.asarray(e1), np.asarray(e2))
        t1 = model.apply(
            params, feats, masks, ids, deterministic=False,
            rngs={"dropout": jax.random.PRNGKey(1)},
        )
        assert not np.allclose(np.asarray(e1), np.asarray(t1))


class TestSample:
    def _setup(self, np_rng, **kw):
        model = make_model(**kw)
        feats, masks, ids = make_batch(np_rng)
        params = model.init(jax.random.PRNGKey(0), feats, masks, ids)
        return model, params, feats, masks

    def test_greedy_shapes_and_determinism(self, np_rng):
        model, params, feats, masks = self._setup(np_rng)
        out1 = model.apply(params, feats, masks, max_len=T, method="sample")
        out2 = model.apply(
            params, feats, masks, max_len=T,
            rng=jax.random.PRNGKey(99), method="sample",
        )
        assert out1.tokens.shape == (B, T)
        assert out1.logprobs.shape == (B, T)
        assert out1.mask.shape == (B, T)
        # Greedy is rng-independent.
        np.testing.assert_array_equal(np.asarray(out1.tokens), np.asarray(out2.tokens))

    def test_end_token_semantics(self, np_rng):
        model, params, feats, masks = self._setup(np_rng)
        out = model.apply(params, feats, masks, max_len=T, method="sample")
        toks = np.asarray(out.tokens)
        mask = np.asarray(out.mask)
        lps = np.asarray(out.logprobs)
        for b in range(B):
            ends = np.nonzero((toks[b] == EOS_ID) | (toks[b] == PAD_ID))[0]
            if len(ends) == 0:
                assert mask[b].all()
                continue
            e = ends[0]
            # mask covers [0, e]; everything after is PAD with 0 logprob.
            assert mask[b, : e + 1].all()
            assert not mask[b, e + 1 :].any()
            assert (toks[b, e + 1 :] == PAD_ID).all()
            np.testing.assert_allclose(lps[b, e + 1 :], 0.0)

    def test_multinomial_differs_by_rng_and_valid_logprobs(self, np_rng):
        model, params, feats, masks = self._setup(np_rng)
        o1 = model.apply(
            params, feats, masks, max_len=T, greedy=False,
            rng=jax.random.PRNGKey(1), method="sample",
        )
        o2 = model.apply(
            params, feats, masks, max_len=T, greedy=False,
            rng=jax.random.PRNGKey(2), method="sample",
        )
        assert not np.array_equal(np.asarray(o1.tokens), np.asarray(o2.tokens))
        lp = np.asarray(o1.logprobs)
        assert (lp <= 0).all() and np.isfinite(lp).all()

    def test_greedy_first_token_logprob_dominates(self, np_rng):
        """At the first step both decoders condition on the same (BOS)
        state, so greedy's token logprob must be >= any sampled token's.
        (After step 0 the trajectories diverge and no ordering is
        guaranteed, so only step 0 is asserted.)"""
        model, params, feats, masks = self._setup(np_rng)
        g = model.apply(params, feats, masks, max_len=T, method="sample")
        m = model.apply(
            params, feats, masks, max_len=T, greedy=False,
            rng=jax.random.PRNGKey(5), method="sample",
        )
        assert (
            np.asarray(g.logprobs[:, 0]) >= np.asarray(m.logprobs[:, 0]) - 1e-6
        ).all()

    def test_sample_jits(self, np_rng):
        model, params, feats, masks = self._setup(np_rng)

        @jax.jit
        def run(p, f, fm, key):
            return model.apply(
                p, f, fm, rng=key, max_len=T, greedy=False, method="sample"
            )

        out = run(params, feats, masks, jax.random.PRNGKey(0))
        assert out.tokens.shape == (B, T)

    def test_sample_repeat_matches_pretiled(self, np_rng):
        """Greedy decode with repeat=S == greedy decode on pre-tiled
        features (deterministic, so exact token equality)."""
        S = 3
        model = make_model()
        feats, masks, ids = make_batch(np_rng)
        params = model.init(jax.random.PRNGKey(0), feats, masks, ids)
        out_r = model.apply(
            params, feats, masks, greedy=True, max_len=T,
            method="sample", repeat=S,
        )
        feats_t = {m: jnp.repeat(v, S, axis=0) for m, v in feats.items()}
        masks_t = {m: jnp.repeat(v, S, axis=0) for m, v in masks.items()}
        out_t = model.apply(
            params, feats_t, masks_t, greedy=True, max_len=T,
            method="sample",
        )
        np.testing.assert_array_equal(
            np.asarray(out_r.tokens), np.asarray(out_t.tokens)
        )
        np.testing.assert_allclose(
            np.asarray(out_r.logprobs), np.asarray(out_t.logprobs),
            rtol=1e-5, atol=1e-6,
        )

    def test_decode_one_matches_sample_first_step(self, np_rng):
        model, params, feats, masks = self._setup(np_rng)
        state, cache = model.apply(params, feats, masks, method="init_decode")
        bos = jnp.full((B,), BOS_ID, jnp.int32)
        _, logp = model.apply(params, state, cache, bos, method="decode_one")
        first = jnp.argmax(logp, axis=-1)
        out = model.apply(params, feats, masks, max_len=T, method="sample")
        np.testing.assert_array_equal(
            np.asarray(first), np.asarray(out.tokens[:, 0])
        )
