"""The unified decode runtime (decoding/core.py).

ONE parity harness for EVERY registered decode backend: the scan beam,
the fused Pallas beam, the fused Pallas sampler, the serving slot
decoder (beam + greedy) and the CST slot rollout all decode the SAME
fixed inputs and are pinned token-exact against their declared
reference — replacing the per-backend parity copies that used to live
in test_beam.py / test_pallas_beam.py / test_pallas_sampler.py /
test_serving.py.

Plus the single-definition-site guard: the per-step decode recurrence
exists exactly once (``decoding/core.py::decode_step``); every XLA
consumer must import it, and the CST-DEC analysis rules
(cst_captioning_tpu/analysis/single_site.py, PR 8 — AST shapes, so
reformatting/aliasing can't dodge them the way they could dodge the
retired grep fingerprints) fail the build if a new module re-implements
the step math (the fused kernel bodies are the explicit allowlist — a
Pallas kernel cannot call back into XLA ops).  The seeded-violation
corpus (tests/analysis_corpus/decode_reimpl.py) pins that each rule
still fires on every pattern the greps used to catch.
"""

from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import cst_captioning_tpu
from cst_captioning_tpu.constants import EOS_ID, PAD_ID
from cst_captioning_tpu.decoding import core
from cst_captioning_tpu.models import CaptionModel

ALL_BACKENDS = core.load_backends()

# Shapes chosen so the fused kernels ENGAGE (B % 8 == 0 for the sampler
# gate; V large enough for the beam kernel's vocab floor) — a gated-off
# kernel would "pass" parity by silently running the scan path.
V, B, F, D, H = 40, 8, 3, 12, 16
K, L = 4, 8


@pytest.fixture(scope="module")
def ctx():
    rng = np.random.RandomState(2)
    base = dict(
        vocab_size=V, rnn_size=H, num_layers=1, embed_size=H,
        att_hidden_size=H, fusion="attention", modalities=("resnet",),
        feature_dims=(D,), compute_dtype="float32", drop_prob=0.0,
    )

    def make_model(**overrides):
        kw = dict(base)
        kw.update(overrides)
        return CaptionModel(**kw)

    feats = {"resnet": jnp.asarray(rng.randn(B, F, D), jnp.float32)}
    masks = {"resnet": jnp.ones((B, F), jnp.float32)}
    ids = jnp.asarray(rng.randint(4, V, (B, 6)), jnp.int32)
    params = make_model().init(jax.random.PRNGKey(0), feats, masks, ids)
    return core.ParityCtx(
        make_model=make_model, params=params, feats=feats, masks=masks,
        category=None, beam_size=K, max_len=L, temperature=0.9,
        rng=jax.random.PRNGKey(11),
        video_idx=jnp.arange(B, dtype=jnp.int32), repeat=2,
    )


class TestSharedParity:
    """Every backend with a declared reference, through identical
    inputs: tokens EXACT, scores/log-probs allclose."""

    @pytest.mark.parametrize(
        "name", [n for n in ALL_BACKENDS if core.get_backend(n).ref]
    )
    def test_backend_matches_reference(self, ctx, name):
        backend = core.get_backend(name)
        got = backend.run(ctx)
        ref = core.get_backend(backend.ref).run(ctx)
        np.testing.assert_array_equal(
            got["tokens"], ref["tokens"],
            err_msg=f"{name} tokens diverged from {backend.ref}",
        )
        if got.get("scores") is not None and ref.get("scores") is not None:
            np.testing.assert_allclose(
                got["scores"], ref["scores"], rtol=1e-4, atol=1e-5,
            )
        if got.get("lps") is not None and ref.get("lps") is not None:
            np.testing.assert_allclose(
                got["lps"], ref["lps"], rtol=1e-4, atol=1e-5,
            )
        if got.get("mask") is not None and ref.get("mask") is not None:
            np.testing.assert_array_equal(got["mask"], ref["mask"])

    def test_all_five_consumers_registered(self):
        """The acceptance bar names five decode consumers; all must sit
        behind the one registry — plus the PR-7 slot-layout variants
        (deduped default, legacy replicated cache, elastic banks), so
        the memory layout is pinned through the same harness."""
        assert {
            "scan_beam", "fused_beam", "fused_sampler",
            "slot_decoder_beam", "slot_decoder_greedy",
            "slot_decoder_beam_replicated", "slot_decoder_beam_elastic",
            "padded_rollout", "slot_rollout",
            # ISSUE 14: the tensor-parallel fast paths — the shard_map
            # kernel ports and the slot loop's cross-shard fused merge
            # (plus the PR-9 gather path kept pinned alongside).
            "fused_beam_tp2", "fused_sampler_tp2",
            "slot_decoder_beam_tp2", "slot_decoder_beam_tp2_fused",
            "slot_decoder_greedy_tp2_fused",
            # ISSUE 18: speculative decode — the offline propose/verify
            # round and the slot-runtime spec tick, both pinned
            # token-exact against scan_greedy through this harness.
            "greedy_spec_offline", "slot_decoder_greedy_spec",
            "slot_decoder_greedy_spec_aot",
        } <= set(ALL_BACKENDS)

    def test_beam1_equals_greedy(self, ctx):
        """Cross-mode coherence: a width-1 beam IS the greedy decode
        (formerly pinned per-backend in test_beam / test_pallas_beam)."""
        from cst_captioning_tpu.decoding import beam_search

        r = beam_search(
            ctx.make_model(), ctx.params, ctx.feats, ctx.masks,
            beam_size=1, max_len=L, length_normalize=False,
        )
        g = core.get_backend("scan_greedy").run(ctx)
        np.testing.assert_array_equal(np.asarray(r.tokens), g["tokens"])


class TestSampleEarlyExit:
    """The offline greedy/multinomial scan paths' all-rows-finished
    ``lax.while_loop`` early exit (the PR-3 beam treatment) is
    output-identical to the fixed-length scan — including when every
    row EOSes immediately, the case the exit actually fires on."""

    def _compare(self, ctx, params, greedy):
        m = ctx.make_model()
        kw = dict(max_len=L, greedy=greedy, method="sample")
        if not greedy:
            kw.update(rng=jax.random.PRNGKey(5), temperature=0.8)
        fast = m.apply(ctx.params if params is None else params,
                       ctx.feats, ctx.masks, early_exit=True, **kw)
        full = m.apply(ctx.params if params is None else params,
                       ctx.feats, ctx.masks, early_exit=False, **kw)
        np.testing.assert_array_equal(
            np.asarray(fast.tokens), np.asarray(full.tokens)
        )
        np.testing.assert_array_equal(
            np.asarray(fast.logprobs), np.asarray(full.logprobs)
        )
        np.testing.assert_array_equal(
            np.asarray(fast.mask), np.asarray(full.mask)
        )
        return fast

    @pytest.mark.parametrize("greedy", [True, False])
    def test_natural_lengths(self, ctx, greedy):
        self._compare(ctx, None, greedy)

    @pytest.mark.parametrize("greedy", [True, False])
    def test_all_eos_immediately(self, ctx, greedy):
        p = dict(ctx.params)
        pp = dict(p["params"])
        b = np.asarray(pp["logit_b"]).copy()
        b[EOS_ID] += 50.0
        pp["logit_b"] = jnp.asarray(b)
        p["params"] = pp
        out = self._compare(ctx, p, greedy)
        toks = np.asarray(out.tokens)
        assert (toks[:, 0] == EOS_ID).all()
        assert (toks[:, 1:] == PAD_ID).all()
        assert np.asarray(out.mask)[:, 1:].sum() == 0


class TestSlotRolloutInvariance:
    """Row-keyed PRNG: the sampled rollout tokens depend on (rng,
    row_id, step) only — slot count, block size, and admission order
    cannot change any token (docs/PARITY.md slot-rollout contract)."""

    @pytest.mark.parametrize("n_slots,block", [(3, 1), (5, 2)])
    def test_tokens_invariant_to_slot_geometry(self, ctx, n_slots, block):
        from cst_captioning_tpu.training.cst import SlotRollout

        ref = core.get_backend("padded_rollout").run(ctx)
        ro = SlotRollout(
            ctx.make_model(), max_len=ctx.max_len,
            temperature=ctx.temperature, n_slots=n_slots, block=block,
        )
        tokens, stats = ro.run(
            ctx.params, ctx.feats, ctx.masks, ctx.category, ctx.rng,
            repeat=ctx.repeat, need_greedy=True,
        )
        np.testing.assert_array_equal(tokens, ref["tokens"])
        assert stats["rollout_slots"] == n_slots

    def test_harvest_stream_covers_all_rows_once(self, ctx):
        from cst_captioning_tpu.training.cst import SlotRollout

        seen = []
        ro = SlotRollout(
            ctx.make_model(), max_len=ctx.max_len,
            temperature=ctx.temperature, n_slots=4,
        )
        tokens, stats = ro.run(
            ctx.params, ctx.feats, ctx.masks, ctx.category, ctx.rng,
            repeat=ctx.repeat, need_greedy=True,
            on_harvest=lambda ids, toks: seen.extend(ids),
        )
        n = B * ctx.repeat + B
        assert sorted(seen) == list(range(n))
        assert stats["rollout_rows"] == n
        assert 0 < stats["rollout_steps_per_row"] <= ctx.max_len


class TestSpeculativeSlotFuzz:
    """ISSUE 18: the speculative stream's token-exactness must survive
    ANY arrival order — fuzzed admission counts leave slots at arbitrary
    staggered depths, so each spec round mixes rows with different
    remaining lengths and EOS proximity (exactly where a sloppy
    accept/truncate rule would drift from the scan reference)."""

    @pytest.mark.parametrize("seed", [7, 19, 123])
    def test_fuzzed_arrival_orders_stay_exact(self, ctx, seed):
        from cst_captioning_tpu.serving.slots import (
            SlotDecoder,
            _ParityEngine,
        )

        ref = core.get_backend("scan_greedy").run(ctx)
        rng = np.random.RandomState(seed)
        eng = _ParityEngine(
            ctx, mode="greedy", num_slots=3, block=1,
            speculative={"draft_k": 3, "draft_hidden": 8},
        )
        dec = SlotDecoder(eng)
        got = {}
        pending = list(range(B))
        while pending or dec.occupied:
            cap = min(len(pending), len(dec.free), dec.admit_cap)
            n = int(rng.randint(0, cap + 1)) if cap else 0
            if n == 0 and not dec.occupied:
                n = min(1, cap)               # never stall an empty bank
            adm = [pending.pop(0) for _ in range(n)]
            done = dec.tick(adm, adm)
            for i, tokens, _score, steps in dec.harvest_many(done):
                got[i] = tokens
                assert 0 < steps <= dec.L
        toks = np.stack([got[i] for i in range(B)])
        np.testing.assert_array_equal(
            toks, ref["tokens"],
            err_msg=f"spec slot tokens diverged under arrival seed {seed}",
        )


# ---------------------------------------------- single-definition guard
#
# PR 8 retired the two tokenizer-stripped grep fingerprints that lived
# here (top_k / finish-update / PAD→EOS feed, and PR 7's jnp.repeat
# cache-replication guard) in favor of the AST rules CST-DEC-001..004 —
# same allowlists, reformat/alias-proof matching.  The rules run over
# the whole package in tests/test_analysis.py; this guard keeps the
# decode-specific invariant visible next to the decode tests.


class TestSingleDefinitionSite:
    def test_consumers_import_the_shared_step(self):
        from cst_captioning_tpu.decoding import beam
        from cst_captioning_tpu.models import captioner
        from cst_captioning_tpu.serving import slots
        from cst_captioning_tpu.training import cst

        for mod in (beam, captioner, slots, cst):
            assert mod.decode_step is core.decode_step, mod.__name__

    def test_no_second_definition_of_the_recurrence(self):
        """The AST replacement of the retired greps: zero CST-DEC
        findings over the package with the kernel-body allowlists in
        place (removal of an allowlist entry is pinned to fail at the
        exact file:line in tests/test_analysis.py)."""
        from cst_captioning_tpu.analysis import CHECKERS
        from cst_captioning_tpu.analysis.astutil import (
            PackageIndex,
            scan_package,
        )
        from cst_captioning_tpu.analysis.engine import (
            CheckContext,
            _load_checkers,
        )

        _load_checkers()  # registry fills lazily; don't rely on test order

        root = Path(cst_captioning_tpu.__file__).parent
        mods = [
            m for m in scan_package(root)
            if not m.rel.startswith("analysis/")
        ]
        ctx = CheckContext(
            index=PackageIndex(mods), package_root=root, docs_root=None
        )
        offenders = CHECKERS["single_site"](mods, ctx)
        assert not offenders, (
            "decode-step recurrence re-implemented outside "
            f"decoding/core.py: {[f.render() for f in offenders]} — "
            "import cst_captioning_tpu.decoding.core.decode_step "
            "instead (kernel bodies: extend the allowlist in "
            "analysis/single_site.py consciously)"
        )
