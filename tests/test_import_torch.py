"""Torch-weight import: full-model forward parity against a torch twin of
the meanpool captioner (embedding + projection + LSTMCell + vocab head)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from cst_captioning_tpu.models import CaptionModel  # noqa: E402
from cst_captioning_tpu.tools.import_torch import (  # noqa: E402
    import_torch_state_dict,
    validate_against_model,
)

V, B, T, F, D, H = 21, 3, 6, 4, 10, 12


class TorchTwin(torch.nn.Module):
    """Torch replica of CaptionModel's meanpool forward (reference-style
    modules producing the documented state_dict layout)."""

    def __init__(self):
        super().__init__()
        self.embed = torch.nn.Embedding(V, H)
        self.feat_proj = torch.nn.ModuleDict(
            {"resnet": torch.nn.Linear(D, H)}
        )
        self.lstm = torch.nn.ModuleList([torch.nn.LSTMCell(2 * H, H)])
        self.logit = torch.nn.Linear(H, V)

    def forward(self, feats, ids):
        ctx = feats.mean(dim=1)
        ctx = self.feat_proj["resnet"](ctx)  # NOTE: proj after meanpool
        emb = self.embed(ids)
        h = torch.zeros(ids.shape[0], H)
        c = torch.zeros(ids.shape[0], H)
        outs = []
        for t in range(ids.shape[1]):
            x = torch.cat([emb[:, t], ctx], dim=-1)
            h, c = self.lstm[0](x, (h, c))
            outs.append(self.logit(h))
        return torch.stack(outs, dim=1)

    def framework_state_dict(self):
        sd = {}
        sd["embed.weight"] = self.embed.weight
        sd["feat_proj.resnet.weight"] = self.feat_proj["resnet"].weight
        sd["feat_proj.resnet.bias"] = self.feat_proj["resnet"].bias
        sd["lstm.0.weight_ih"] = self.lstm[0].weight_ih
        sd["lstm.0.weight_hh"] = self.lstm[0].weight_hh
        sd["lstm.0.bias_ih"] = self.lstm[0].bias_ih
        sd["lstm.0.bias_hh"] = self.lstm[0].bias_hh
        sd["logit.weight"] = self.logit.weight
        sd["logit.bias"] = self.logit.bias
        return sd


class TestImport:
    def test_full_forward_parity(self):
        """Import a torch twin's weights; logits must match the jax model.

        The twin mean-pools BEFORE projecting; our model projects each
        frame then mean-pools — identical math for a linear projection
        with full frame masks, so outputs must agree to float tolerance.
        """
        torch.manual_seed(0)
        twin = TorchTwin()
        rng = np.random.RandomState(1)
        feats_np = rng.randn(B, F, D).astype(np.float32)
        ids_np = rng.randint(4, V, size=(B, T)).astype(np.int64)
        ids_np[:, 0] = 1

        with torch.no_grad():
            ref = twin(
                torch.from_numpy(feats_np), torch.from_numpy(ids_np)
            ).numpy()

        params = import_torch_state_dict(
            twin.framework_state_dict(), ["resnet"], num_layers=1
        )
        model = CaptionModel(
            vocab_size=V, rnn_size=H, num_layers=1, embed_size=H,
            modalities=("resnet",), feature_dims=(D,), drop_prob=0.0,
            compute_dtype="float32",
        )
        feats = {"resnet": jnp.asarray(feats_np)}
        masks = {"resnet": jnp.ones((B, F))}
        ids = jnp.asarray(ids_np, jnp.int32)
        validate_against_model(params, model, (feats, masks, ids))
        params_j = jax.tree.map(jnp.asarray, params)
        got = model.apply(params_j, feats, masks, ids)
        np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4, atol=1e-4)

    def test_validate_catches_shape_mismatch(self):
        torch.manual_seed(0)
        twin = TorchTwin()
        params = import_torch_state_dict(
            twin.framework_state_dict(), ["resnet"], num_layers=1
        )
        model = CaptionModel(
            vocab_size=V, rnn_size=H + 1, num_layers=1, embed_size=H,
            modalities=("resnet",), feature_dims=(D,), drop_prob=0.0,
            compute_dtype="float32",
        )
        feats = {"resnet": jnp.zeros((1, F, D))}
        masks = {"resnet": jnp.ones((1, F))}
        ids = jnp.zeros((1, 2), jnp.int32)
        with pytest.raises(ValueError):
            validate_against_model(params, model, (feats, masks, ids))

    def test_missing_key_reported(self):
        with pytest.raises(KeyError, match="embed.weight"):
            import_torch_state_dict({}, ["resnet"], num_layers=1)
