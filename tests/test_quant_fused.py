"""Int8-native fused decode kernels (ISSUE 20).

Pins the tentpole contracts:

* **Kernel ↔ twin bit-exactness** — every fused kernel family
  (recurrence LSTM, attention-LSTM, sampler, beam) consuming int8 code
  tiles + per-channel scales is EXACTLY equal, on CPU interpret, to its
  chunk-faithful XLA twin: same tile picker, codes cast losslessly into
  the activation dtype, f32-pinned accumulation, scale applied AFTER
  the accumulation (``ops/quant.py::quant_matmul`` semantics), carried
  (h, c) f32 with one rounding at the h_seq write.
* **No quant-caused declines** — ``serving.dtype=int8w`` with
  ``use_pallas_*`` requested logs EXACTLY the decline lines the
  identically-built f32 config logs (environmental gates only), and
  none of them mention quantization.
* **Relaxed-serving parity, fused vs unfused** — the fused int8w
  engine holds the pinned bounds (caption-match floor, per-caption
  beam-score rtol; analysis/jit_registry.py) against the unfused int8w
  reference the bounds were calibrated on.
* **Quantized fused AOT artifacts** — an int8w engine with the fused
  kernels requested builds/boots an artifact with ``compile_count ==
  0``, no boot-time requantization (identical scale hashes), and
  token-exact decodes vs the warm engine.
* **Speculation × int8w** — the draft/verify loop over int8w-quantized
  verify weights stays token-exact vs the plain int8w slot decoder
  (the verifier's batched vocab GEMM rides the same quantized logit
  path; rejection-rule exactness is dtype-internal).
"""

import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.analysis.jit_registry import (
    RELAXED_SERVING_MATCH_FLOOR,
    RELAXED_SERVING_SCORE_RTOL,
)
from cst_captioning_tpu.config import get_preset
from cst_captioning_tpu.data.vocab import Vocabulary
from cst_captioning_tpu.decoding.beam import make_beam_search_fn
from cst_captioning_tpu.ops import quant
from cst_captioning_tpu.ops.pallas_attlstm import (
    attlstm_recurrence_quant,
    attlstm_scan_quant,
)
from cst_captioning_tpu.ops.pallas_beam import (
    attlstm_beam,
    attlstm_beam_scan,
    lstm_beam,
    lstm_beam_scan,
)
from cst_captioning_tpu.ops.pallas_lstm import (
    lstm_recurrence_quant,
    lstm_recurrence_scan_quant,
)
from cst_captioning_tpu.ops.pallas_sampler import (
    attlstm_sample,
    attlstm_sample_scan,
    lstm_sample,
    lstm_sample_scan,
)
from cst_captioning_tpu.serving.artifact import build_artifact
from cst_captioning_tpu.serving.engine import InferenceEngine


# ------------------------------------------------------- quantized args

def make_float_args(B=8, H=16, A=16, E=16, F=5, V=50, seed=0):
    """Float decode-kernel argument tree (the test_pallas_* idiom)."""
    rng = np.random.RandomState(seed)
    arr = lambda *s, sc=0.3: jnp.asarray(rng.randn(*s) * sc, jnp.float32)
    return dict(
        gx_static=jnp.asarray(rng.randn(B, 4 * H) * 0.1, jnp.float32),
        w_x=arr(E, 4 * H),
        wh=arr(H, 4 * H),
        w_ctx=arr(E, 4 * H),
        att_wh=arr(H, A),
        att_v=arr(A, 1),
        att_proj=arr(B, F, A),
        att_mask=jnp.asarray((rng.rand(B, F) > 0.2).astype(np.float32)),
        att_vals=arr(B, F, E),
        emb=arr(V, E),
        w_out=arr(H, V, sc=0.3),
        b_out=jnp.asarray(rng.randn(V) * 0.1, jnp.float32),
    )


def quantize_args(args, cdt, static_ctx=False):
    """Quantize the float tree the way ``quantize_params`` does: emb
    per-row (axis 0), w_out per-column (axis 1), ONE shared (4H,) scale
    across the stacked gate-matrix row slices (w_x/w_ctx/wh are slices
    of the layer's single quantized lstm matrix), att_wh per-column.
    Returns ``(qargs, quant_tuple)`` ready for the kernel entry points.
    """
    q = dict(args)
    q["emb"], emb_s = quant.quantize_per_channel(args["emb"], 0)
    q["w_out"], wout_s = quant.quantize_per_channel(args["w_out"], 1)
    parts = ["w_x", "wh"] if static_ctx else ["w_x", "w_ctx", "wh"]
    cat = jnp.concatenate([args[p] for p in parts], axis=0)
    cat_q, lstm_s = quant.quantize_per_channel(cat, 1)
    r = 0
    for p in parts:
        n = args[p].shape[0]
        q[p] = cat_q[r:r + n]
        r += n
    if static_ctx:
        quant_tuple = (emb_s, wout_s, lstm_s)
    else:
        q["att_wh"], att_s = quant.quantize_per_channel(args["att_wh"], 1)
        quant_tuple = (emb_s, wout_s, lstm_s, att_s)
        for p in ("att_v", "att_proj", "att_vals"):
            q[p] = args[p].astype(cdt)
    return q, quant_tuple


def drop_att(args):
    return {
        k: v for k, v in args.items()
        if not k.startswith("att") and k != "w_ctx"
    }


# --------------------------------------------- kernel ↔ twin bit-exact

CDTS = ["float32", "bfloat16"]


class TestRecurrenceQuantTwinParity:
    @pytest.mark.parametrize("cdt", CDTS)
    def test_lstm_kernel_matches_twin_exactly(self, cdt):
        rng = np.random.RandomState(5)
        B, T, H = 8, 12, 16
        gx = jnp.asarray(rng.randn(B, T, 4 * H) * 0.3, jnp.float32)
        wh = jnp.asarray(rng.randn(H, 4 * H) * 0.3, jnp.float32)
        wh_q, ws = quant.quantize_per_channel(wh, 1)
        k = lstm_recurrence_quant(gx, wh_q, ws, cdt, use_pallas=True)
        r = lstm_recurrence_scan_quant(gx, wh_q, ws, cdt)
        assert k.dtype == jnp.dtype(cdt)
        np.testing.assert_array_equal(np.asarray(k), np.asarray(r))

    @pytest.mark.parametrize("cdt", CDTS)
    def test_attlstm_kernel_matches_twin_exactly(self, cdt):
        rng = np.random.RandomState(9)
        B, T, H, E, F, A = 8, 10, 16, 16, 5, 16
        gx = jnp.asarray(rng.randn(B, T, 4 * H) * 0.3, jnp.float32)
        args = make_float_args(B=B, H=H, A=A, E=E, F=F, seed=9)
        qa, (_, _, ls, asc) = quantize_args(args, jnp.dtype(cdt))
        common = (
            gx, qa["wh"], qa["w_ctx"], ls, qa["att_wh"], asc,
            qa["att_v"], qa["att_proj"], qa["att_mask"], qa["att_vals"],
            cdt,
        )
        k = attlstm_recurrence_quant(*common)
        r = attlstm_scan_quant(*common)
        assert k.dtype == jnp.dtype(cdt)
        np.testing.assert_array_equal(np.asarray(k), np.asarray(r))


class TestSamplerQuantTwinParity:
    @pytest.mark.parametrize("cdt", CDTS)
    @pytest.mark.parametrize("greedy", [True, False])
    def test_attention_exact(self, cdt, greedy):
        args = make_float_args()
        qa, qt = quantize_args(args, jnp.dtype(cdt))
        kw = dict(
            max_len=10, greedy=greedy, quant=qt, compute_dtype=cdt
        )
        k = attlstm_sample(*qa.values(), 7, **kw)
        r = attlstm_sample_scan(*qa.values(), 7, **kw)
        np.testing.assert_array_equal(np.asarray(k[0]), np.asarray(r[0]))
        np.testing.assert_array_equal(np.asarray(k[2]), np.asarray(r[2]))
        np.testing.assert_allclose(
            np.asarray(k[1]), np.asarray(r[1]), rtol=1e-5, atol=1e-5
        )

    def test_multi_tile_vocab_streams_exactly(self):
        """V=1100 forces multiple streamed int8 V-tiles plus a padded
        tail (unit scales, zero codes) — tokens must match the twin and
        never land in the padding."""
        args = make_float_args(V=1100)
        qa, qt = quantize_args(args, jnp.bfloat16)
        for greedy in (True, False):
            kw = dict(
                max_len=8, greedy=greedy, quant=qt,
                compute_dtype="bfloat16",
            )
            k = attlstm_sample(*qa.values(), 3, **kw)
            r = attlstm_sample_scan(*qa.values(), 3, **kw)
            np.testing.assert_array_equal(
                np.asarray(k[0]), np.asarray(r[0])
            )
            assert np.asarray(k[0]).max() < 1100

    @pytest.mark.parametrize("cdt", CDTS)
    def test_static_ctx_exact(self, cdt):
        args = make_float_args(seed=11)
        qa, qt = quantize_args(args, jnp.dtype(cdt), static_ctx=True)
        sa = drop_att(qa)
        kw = dict(max_len=8, greedy=False, quant=qt, compute_dtype=cdt)
        k = lstm_sample(*sa.values(), 13, **kw)
        r = lstm_sample_scan(*sa.values(), 13, **kw)
        np.testing.assert_array_equal(np.asarray(k[0]), np.asarray(r[0]))
        np.testing.assert_array_equal(np.asarray(k[2]), np.asarray(r[2]))

    def test_quant_geometry_matches_float_counter_stream(self):
        """Tile pickers use the ACTIVATION itemsize in quant mode, so
        the hash-Gumbel counter stream (seeded per batch tile over the
        padded vocab) is IDENTICAL to the float kernel's — same seed,
        same multinomial draws when the logits agree."""
        args = make_float_args(seed=21)
        f = attlstm_sample(*args.values(), 5, max_len=8, greedy=False)
        qa, qt = quantize_args(args, jnp.float32)
        q = attlstm_sample(
            *qa.values(), 5, max_len=8, greedy=False,
            quant=qt, compute_dtype="float32",
        )
        # Not bit-equal (the weights were rounded to int8 steps), but
        # the streams align: most steps pick the same token.
        agree = np.mean(np.asarray(f[0]) == np.asarray(q[0]))
        assert agree > 0.5, f"counter streams diverged (agree={agree})"


class TestBeamQuantTwinParity:
    @pytest.mark.parametrize("cdt", CDTS)
    @pytest.mark.parametrize("beam_size", [1, 3])
    def test_attention_exact(self, cdt, beam_size):
        args = make_float_args(B=4)
        qa, qt = quantize_args(args, jnp.dtype(cdt))
        sa = {k: v for k, v in qa.items() if k != "gx_static"}
        kw = dict(
            beam_size=beam_size, max_len=8, quant=qt, compute_dtype=cdt
        )
        k = attlstm_beam(qa["gx_static"], *sa.values(), **kw)
        r = attlstm_beam_scan(qa["gx_static"], *sa.values(), **kw)
        np.testing.assert_array_equal(np.asarray(k[0]), np.asarray(r[0]))
        np.testing.assert_array_equal(np.asarray(k[1]), np.asarray(r[1]))

    @pytest.mark.parametrize("cdt", CDTS)
    def test_static_ctx_exact(self, cdt):
        args = make_float_args(B=4, V=60, seed=31)
        qa, qt = quantize_args(args, jnp.dtype(cdt), static_ctx=True)
        sa = drop_att(qa)
        kw = dict(beam_size=3, max_len=8, quant=qt, compute_dtype=cdt)
        k = lstm_beam(*sa.values(), **kw)
        r = lstm_beam_scan(*sa.values(), **kw)
        np.testing.assert_array_equal(np.asarray(k[0]), np.asarray(r[0]))
        np.testing.assert_array_equal(np.asarray(k[1]), np.asarray(r[1]))

    def test_multi_tile_vocab_exact(self):
        args = make_float_args(B=4, V=1100)
        qa, qt = quantize_args(args, jnp.bfloat16)
        sa = {k: v for k, v in qa.items() if k != "gx_static"}
        kw = dict(
            beam_size=3, max_len=6, quant=qt, compute_dtype="bfloat16"
        )
        k = attlstm_beam(qa["gx_static"], *sa.values(), **kw)
        r = attlstm_beam_scan(qa["gx_static"], *sa.values(), **kw)
        np.testing.assert_array_equal(np.asarray(k[0]), np.asarray(r[0]))
        np.testing.assert_array_equal(np.asarray(k[1]), np.asarray(r[1]))
        assert np.asarray(k[0]).max() < 1100


# --------------------------------------------------- engines + declines

def _fused_cfg(dtype, fused):
    cfg = get_preset("synthetic_smoke")
    cfg.serving.warmup = False
    cfg.serving.num_slots = 4
    cfg.serving.max_batch_size = 4
    cfg.serving.batch_shapes = [4]
    cfg.serving.dtype = dtype
    cfg.model.use_pallas_lstm = fused
    cfg.model.use_pallas_attention = fused
    cfg.model.use_pallas_sampler = fused
    cfg.model.use_pallas_beam = fused
    return cfg


def _payloads(cfg, n, seed=0):
    rng = np.random.RandomState(seed)
    d = cfg.data
    return [
        {
            "features": {
                m: rng.randn(d.max_frames, d.feature_dims[m]).astype(
                    np.float32
                )
                for m in d.feature_modalities
            }
        }
        for _ in range(n)
    ]


@pytest.fixture(scope="module")
def fused_world():
    """One vocab, one random float init; int8w engines with the fused
    kernels requested vs declined, quantized from the SAME weights."""
    vocab = Vocabulary([f"w{i}" for i in range(60)])
    base_cfg = _fused_cfg("f32", fused=False)
    base_cfg.model.vocab_size = len(vocab)

    base = InferenceEngine(base_cfg, random_init=True, vocab=vocab)

    def mk(dtype, fused):
        cfg = _fused_cfg(dtype, fused)
        cfg.model.vocab_size = len(vocab)
        return InferenceEngine(cfg, params=base.params, vocab=vocab)

    return {
        "base": base,
        "fused_int8w": mk("int8w", True),
        "unfused_int8w": mk("int8w", False),
    }


def _captions(engine, payloads):
    reqs = [engine.prepare(dict(p)) for p in payloads]
    out = []
    step = engine.cfg.serving.max_batch_size
    for i in range(0, len(reqs), step):
        out += [
            r.caption
            for r in engine.decode_prepared(reqs[i:i + step], store=False)
        ]
    return out


def _beam_scores(engine, payloads):
    cfg = engine.cfg
    reqs = [engine.prepare(dict(p)) for p in payloads]
    feats = {
        m: jnp.asarray(np.stack([r.feats[m] for r in reqs]))
        for m in reqs[0].feats
    }
    masks = {
        m: jnp.asarray(np.stack([r.masks[m] for r in reqs]))
        for m in reqs[0].masks
    }
    fn = make_beam_search_fn(
        engine.model,
        beam_size=cfg.eval.beam_size,
        max_len=cfg.eval.max_decode_len,
        length_normalize=cfg.eval.length_normalize,
    )
    return np.asarray(fn(engine.params, feats, masks).score, np.float64)


class TestNoQuantDecline:
    def test_int8w_declines_exactly_match_f32(self, caplog):
        """THE decline-lift pin: building the fused model under
        serving_dtype="int8w" logs EXACTLY the ``warn_fused_decline``
        lines the identical f32 build logs (environmental gates — the
        CPU backend — fire dtype-blind), and no line blames
        quantization.  Before ISSUE 20 the int8w build declined every
        kernel up front with a "weight_quant" reason."""
        from cst_captioning_tpu.models.captioner import model_from_config

        cfg = _fused_cfg("int8w", fused=True)
        cfg.model.vocab_size = 64

        def declines(serving_dtype):
            caplog.clear()
            with caplog.at_level(
                logging.WARNING, logger="cst_captioning_tpu.models"
            ):
                model_from_config(cfg, serving_dtype=serving_dtype)
            return sorted(
                r.getMessage() for r in caplog.records
                if "gated off" in r.getMessage()
            )

        f32_lines = declines("f32")
        int8_lines = declines("int8w")
        assert int8_lines == f32_lines, (
            "serving.dtype=int8w changed the fused-decline set:\n"
            f"f32:   {f32_lines}\nint8w: {int8_lines}"
        )
        for line in int8_lines:
            for word in ("quant", "int8"):
                assert word not in line.lower(), (
                    f"decline blames quantization: {line}"
                )

    def test_fused_int8w_model_keeps_kernel_flags(self, fused_world):
        """The built model keeps weight_quant AND the fused-forward
        kernel flags together — quantization no longer clears them."""
        m = fused_world["fused_int8w"].model
        assert m.weight_quant
        assert m.use_pallas or m.use_pallas_attention


class TestFusedUnfusedParity:
    def test_relaxed_serving_bounds_hold(self, fused_world):
        """Fused int8w vs the unfused int8w reference: caption-match
        rate >= the pinned floor and per-caption beam-score gap <= the
        pinned rtol — the same bounds that gate the lowprec_fused_*
        bench rows before they record."""
        fused = fused_world["fused_int8w"]
        unfused = fused_world["unfused_int8w"]
        payloads = _payloads(fused.cfg, 8)
        ref = _captions(unfused, payloads)
        got = _captions(fused, payloads)
        match = sum(a == b for a, b in zip(ref, got)) / len(ref)
        assert match >= RELAXED_SERVING_MATCH_FLOOR, (
            f"fused-int8w caption-match {match:.3f} below the pinned "
            f"floor {RELAXED_SERVING_MATCH_FLOOR}"
        )
        s_ref = _beam_scores(unfused, payloads)
        s_got = _beam_scores(fused, payloads)
        gap = np.abs(s_got - s_ref) / np.maximum(np.abs(s_ref), 1e-6)
        assert float(gap.max()) <= RELAXED_SERVING_SCORE_RTOL, (
            f"fused-int8w score gap {gap.max():.4f} above the pinned "
            f"rtol {RELAXED_SERVING_SCORE_RTOL}"
        )


def _decode_all(engine, decoder, payloads):
    reqs = [engine.prepare(dict(p)) for p in payloads]
    pending = list(enumerate(reqs))
    got = {}
    while pending or decoder.occupied:
        n = min(1, len(pending), len(decoder.free))
        batch = [pending.pop(0) for _ in range(n)]
        done = decoder.tick([r for _, r in batch], [i for i, _ in batch])
        for i, tokens, _score, _steps in decoder.harvest_many(done):
            got[i] = tokens
    return [got[i] for i in range(len(payloads))]


class TestInt8wFusedArtifact:
    def test_aot_boot_zero_compiles_no_requant(
        self, fused_world, tmp_path
    ):
        """int8w + use_pallas_* through the AOT artifact: boots with
        ``compile_count == 0``, restores the int8 codes + scales as
        built (identical scale hashes — no boot-time requantization),
        and serves token-exact vs the warm fused engine."""
        engine = fused_world["fused_int8w"]
        summary = build_artifact(engine, str(tmp_path))
        booted = InferenceEngine.from_artifact(summary["path"])
        assert booted.serving_dtype == "int8w"
        assert quant.is_quantized(booted.params)
        assert (quant.scale_hashes(booted.params)
                == quant.scale_hashes(engine.params))
        assert booted.params_tag == engine.params_tag
        dec = booted.slot_decoder()
        assert dec.compile_count == 0
        payloads = _payloads(engine.cfg, 4, seed=7)
        warm = _decode_all(engine, engine.slot_decoder(), payloads)
        aot = _decode_all(booted, dec, payloads)
        for a, b in zip(warm, aot):
            assert np.array_equal(a, b)
        assert dec.compile_count == 0


class TestSpecInt8wComposition:
    def test_spec_over_int8w_weights_token_exact(self, tmp_path):
        """ISSUE 20 composition: speculative decode whose VERIFY model
        serves int8w weights emits byte-identical token streams to the
        plain int8w slot decoder — the batched verify GEMM rides the
        same quantized logit path, and the rejection rule keeps
        exactness dtype-internal (an undistilled draft only costs
        acceptance, never correctness)."""
        import copy

        from cst_captioning_tpu.decoding.speculative import (
            make_draft_params,
            save_draft_params,
        )

        vocab = Vocabulary([f"w{i}" for i in range(60)])
        cfg = _fused_cfg("int8w", fused=False)
        cfg.serving.decode_mode = "greedy"
        cfg.serving.slot_block_steps = 1
        cfg.model.vocab_size = len(vocab)
        base_cfg = _fused_cfg("f32", fused=False)
        base_cfg.model.vocab_size = len(vocab)
        base = InferenceEngine(base_cfg, random_init=True, vocab=vocab)
        plain = InferenceEngine(cfg, params=base.params, vocab=vocab)
        dp = make_draft_params(base.params, 16)
        path = os.path.join(str(tmp_path), "draft.npz")
        save_draft_params(path, dp)
        c = copy.deepcopy(cfg)
        c.serving.speculative = {
            "draft_k": 3, "draft_hidden": 16, "draft_params": path,
        }
        spec = InferenceEngine(c, params=base.params, vocab=vocab)
        payloads = _payloads(cfg, 6, seed=3)
        ref = _decode_all(plain, plain.slot_decoder(), payloads)
        got = _decode_all(spec, spec.slot_decoder(), payloads)
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
