"""Tier-1 wiring of the invariant engine (ISSUE 8):

* the whole package runs CLEAN (zero unsuppressed findings) inside the
  < 30 s wall-clock budget (``ANALYSIS_BUDGET_S`` discipline);
* every seeded corpus violation (tests/analysis_corpus/) fires exactly
  its annotated rule ID at exactly its annotated line;
* removing a decode-guard allowlist entry makes the pass fail with the
  correct ``file:line`` (the acceptance bar for retiring the grep
  fingerprints);
* the suppression file requires justifications, matches precisely, and
  surfaces stale entries;
* ``python -m cst_captioning_tpu.analysis --json`` emits a
  schema-valid report and the right exit codes.
"""

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from cst_captioning_tpu.analysis import CHECKERS, run_analysis, validate_report
from cst_captioning_tpu.analysis.astutil import PackageIndex, scan_package
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    Suppression,
    _load_checkers,
    load_suppressions,
)

REPO = Path(__file__).resolve().parent.parent
PACKAGE_ROOT = REPO / "cst_captioning_tpu"
CORPUS = Path(__file__).resolve().parent / "analysis_corpus"

ANALYSIS_BUDGET_S = 30.0

_FAMILY_OF_PREFIX = {
    "CST-JIT": "jit_boundary",
    "CST-THR": "thread_safety",
    "CST-DEC": "single_site",
    "CST-DON": "donation",
    "CST-MET": "metrics_registry",
    "CST-SHD": "partitioning",
    "CST-OBS": "observability",
    "CST-RES": "resilience",
    "CST-RNG": "rng",
    "CST-CFG": "configflow",
    "CST-EXC": "exceptions",
    "CST-DTY": "dtypeflow",
    "CST-SHP": "shapeflow",
}


def _family(rule: str) -> str:
    return _FAMILY_OF_PREFIX[rule.rsplit("-", 1)[0]]


# --------------------------------------------------- the package is clean

class TestPackageClean:
    def test_zero_unsuppressed_findings_within_budget(self):
        # Cache-enabled (ISSUE 15): tier-1 gates on 0 findings without
        # the bench preflight, and repeat suite runs on an unchanged
        # tree pay milliseconds (the store is the same .analysis_cache
        # bench uses; the key hashes every source, so a hit can never
        # hide a finding).
        report = run_analysis(
            PACKAGE_ROOT, cache_dir=REPO / ".analysis_cache"
        )
        assert report.clean, "\n" + report.render()
        assert report.duration_s < ANALYSIS_BUDGET_S, (
            f"analysis took {report.duration_s:.1f}s — over the "
            f"{ANALYSIS_BUDGET_S:.0f}s preflight budget; a pass this "
            "slow can't gate commits"
        )
        assert report.files_scanned > 50
        assert set(report.rules_run) == set(CHECKERS)
        # suppressions must not rot: every entry still matches a finding
        assert not report.unused_suppressions, report.unused_suppressions

    def test_thread_pass_sees_the_serving_lock_graph(self):
        """Guard against the pass going vacuously green: the static
        lock pass must actually SEE the serving layer's locks, roots,
        and nested acquisitions."""
        from cst_captioning_tpu.analysis import thread_safety as ts

        mods = [
            m for m in scan_package(PACKAGE_ROOT)
            if not m.rel.startswith("analysis/")
        ]
        ctx = CheckContext(
            index=PackageIndex(mods), package_root=PACKAGE_ROOT,
            docs_root=None,
        )
        world = ts._World(mods, ctx)
        assert "_BatcherBase._cond" in world.locks
        assert "ServingMetrics._replicas_lock" in world.locks
        assert "LRUCache._lock" in world.locks
        roots = ts._collect_roots(world)
        kinds = {qn: kind for (_, qn), (kind, _) in roots.items()}
        assert kinds.get("_BatcherBase.submit") == "multi"
        assert kinds.get("ReplicaSet._worker") == "multi"
        assert kinds.get("_Handler.do_POST") == "multi"
        _, edges = ts._reachability(world, roots)
        # the scheduler cond is held around metrics-lock acquisitions
        assert any(
            a == "_BatcherBase._cond" for (a, b) in edges
        ), sorted(edges)
        assert not ts._find_cycles(edges)

    def test_jit_pass_sees_the_traced_surface(self):
        """The jit auditor must trace the real roots AND their
        transitive callees — decode_step is reached from several jit
        boundaries without being decorated itself."""
        from cst_captioning_tpu.analysis import jit_boundary as jb

        mods = [
            m for m in scan_package(PACKAGE_ROOT)
            if not m.rel.startswith("analysis/")
        ]
        ctx = CheckContext(
            index=PackageIndex(mods), package_root=PACKAGE_ROOT,
            docs_root=None,
        )
        traced = jb._TracedSet()
        jb._collect_roots(mods, traced)
        jb._expand(mods, ctx, traced)
        assert ("training/steps.py", "make_xe_train_step.train_step") in traced.roots
        assert ("decoding/core.py", "decode_step") in traced.static
        assert ("decoding/core.py", "decode_step") not in traced.roots

    def test_resilience_pass_sees_the_real_injection_sites(self):
        """Vacuous-green guard for CST-RES: the checker must discover
        the REAL chaos.fire sites in serving/ — every registered
        FAULT_SITES name with at least one live call site, all of them
        guarded (the package scan stays at zero findings)."""
        from cst_captioning_tpu.analysis import resilience as rz
        from cst_captioning_tpu.serving.chaos import FAULT_SITES

        mods = [
            m for m in scan_package(PACKAGE_ROOT)
            if not m.rel.startswith("analysis/")
        ]
        sites = rz.fire_sites(mods)
        assert len(sites) >= 6
        names = {name for _, _, name in sites if name}
        assert names == {s for s, _, _ in FAULT_SITES}
        files = {mi.rel for mi, _, _ in sites}
        assert {"serving/batcher.py", "serving/replicas.py"} <= files
        for mi, node, name in sites:
            assert rz._is_guarded(mi, node), (
                f"{mi.rel}:{node.lineno} chaos site {name} unguarded"
            )

    def test_rng_pass_sees_the_real_draw_surface(self):
        """Vacuous-green guard for CST-RNG: the dataflow-backed
        checker must discover the REAL jax.random draw sites and prove
        the PARITY r10 row-keying contract (fold-depth 2) at
        decoding/core.py::row_sample_fn via the provenance walk."""
        from cst_captioning_tpu.analysis import rng

        mods = [
            m for m in scan_package(PACKAGE_ROOT)
            if not m.rel.startswith("analysis/")
        ]
        sites = rng.draw_sites(mods)
        assert len(sites) >= 8
        at = {(mi.rel, name) for mi, _, _, name, _ in sites}
        assert ("decoding/core.py", "categorical") in at
        assert ("models/captioner.py", "categorical") in at
        assert ("models/captioner.py", "bernoulli") in at
        assert ("ops/rnn.py", "uniform") in at
        core = next(m for m in mods if m.rel == "decoding/core.py")
        depths = [
            rng.row_key_fold_depth(core, fn)
            for fn in core.functions.values()
        ]
        assert 2 in depths, (
            "the row-keyed draw in row_sample_fn must prove "
            "fold_in(fold_in(rng, row_id), t) via the def-use chains"
        )

    def test_configflow_pass_sees_the_real_read_surface(self):
        """Vacuous-green guard for CST-CFG: the interprocedural read
        discovery must find the real knob-read shapes — direct chains,
        sv-alias reads, getattr string reads, section-typed parameters
        (make_optimizer(cfg.train) -> cfg_train.beta1), and
        constant-string getattr gates (use_pallas_beam)."""
        from cst_captioning_tpu.analysis import configflow as cf

        mods = [
            m for m in scan_package(PACKAGE_ROOT)
            if not m.rel.startswith("analysis/")
        ]
        ctx = CheckContext(
            index=PackageIndex(mods), package_root=PACKAGE_ROOT,
            docs_root=None,
        )
        config_mi = cf.find_config_module(mods)
        fields = cf.declared_fields(config_mi)
        assert set(fields) == {
            "data", "model", "train", "eval", "serving"
        }
        assert sum(len(v) for v in fields.values()) > 100
        accesses = cf.collect_accesses(mods, ctx, set(fields))
        knobs = {(s, f) for s, f, _, _, k in accesses if k != "store"}
        # direct dotted read
        assert ("serving", "hedge_ms") in knobs
        # sv = cfg.serving alias read
        assert ("serving", "num_slots") in knobs
        # getattr string read
        assert ("train", "cst_split_layout") in knobs
        assert ("serving", "flight_events") in knobs
        # section-typed parameter (interprocedural)
        assert ("train", "beta1") in knobs
        assert ("model", "scheduled_sampling_start") in knobs
        # constant-string propagation into a getattr gate
        assert ("model", "use_pallas_beam") in knobs
        # the PR-12 true positive stays wired
        assert ("serving", "trace_buffer_spans") in knobs

    def test_exceptions_pass_sees_the_real_thread_surface(self):
        """Vacuous-green guard for CST-EXC: the root collector must
        resolve the real serving worker threads, and the reachable
        broad handlers must all be non-silent (the package scan's
        zero findings mean every one logs/routes, not that nothing
        was looked at)."""
        from cst_captioning_tpu.analysis import exceptions as ex

        mods = [
            m for m in scan_package(PACKAGE_ROOT)
            if not m.rel.startswith("analysis/")
        ]
        ctx = CheckContext(
            index=PackageIndex(mods), package_root=PACKAGE_ROOT,
            docs_root=None,
        )
        targets = {
            fn.qualname
            for _, _, fn in ex.thread_targets(mods) if fn is not None
        }
        assert {
            "_BatcherBase._run",
            "ReplicaSet._worker",
            "prefetch_to_device.worker",
            "_Server.start_profile._window",
            "CaptionServer._signal_shutdown",
        } <= targets
        roots = ex.collect_roots(mods)
        assert any(r == "reward pool" for r in roots.values())
        assert any(
            qn == "_Handler.do_POST" for (_, qn) in roots
        )
        reach = ex.reachable_from_roots(mods, ctx)
        assert len(reach) > len(roots)
        handlers = ex.broad_handlers(mods)
        assert len(handlers) >= 10
        reachable_handlers = [
            h for h in handlers
            if (h[0].rel, h[1].qualname) in reach
        ]
        assert len(reachable_handlers) >= 5
        assert all(not silent for *_, silent in reachable_handlers)

    def test_partition_pass_sees_rules_and_constraint_sites(self):
        """Vacuous-green guard for CST-SHD: the checker must actually
        find the real rule table and every known constraint site."""
        from cst_captioning_tpu.analysis import partitioning as sp

        mods = [
            m for m in scan_package(PACKAGE_ROOT)
            if not m.rel.startswith("analysis/")
        ]
        mi = next(m for m in mods if m.rel == "parallel/partition.py")
        rules = sp._rule_table(sp._module_assign(mi, sp.RULES_NAME))
        leaves = sp._leaf_list(sp._module_assign(mi, sp.LEAVES_NAME))
        assert rules and len(rules) >= 5
        assert leaves and "word_embed" in leaves
        seen = {}
        for m in mods:
            sp._check_constraint_sites(m, seen)
        for key in (
            "parallel/partition.py::constrain",
            "training/steps.py::make_xe_train_step.train_step.loss_fn",
            "training/cst.py::_pg_update.loss_fn",
            "serving/slots.py::SlotDecoder._build_step"
            ".step_once.step_logits",
        ):
            assert key in seen, f"constraint site {key} not discovered"

    def test_partition_pass_sees_shard_map_sites(self):
        """Vacuous-green guard for CST-SHD-004: the checker must
        discover every real shard_map entry — the compat wrapper, ring
        attention, the CST reward callback, the ISSUE-14 slot-step
        merges and the fused-kernel ports."""
        from cst_captioning_tpu.analysis import partitioning as sp

        mods = [
            m for m in scan_package(PACKAGE_ROOT)
            if not m.rel.startswith("analysis/")
        ]
        seen = {}
        for m in mods:
            sp._check_shard_map_sites(m, seen)
        for key in (
            "parallel/mesh.py::shard_map",
            "parallel/ring.py::ring_attention",
            "parallel/ring.py::sharded_context_attention",
            "training/cst.py::_make_one_graph_step.score",
            "decoding/core.py::make_tp_beam_topk.topk",
            "decoding/core.py::make_tp_row_pick.pick",
            "ops/shard_decode.py::_sharded_beam_impl",
            "ops/shard_decode.py::_sharded_sample_impl",
        ):
            assert key in seen, f"shard_map site {key} not discovered"

    def test_stale_shard_map_registry_entry_fires(self, monkeypatch):
        """A SHARD_MAP_REGISTRY entry whose site moved must surface as
        CST-SHD-004 (the rot guard the satellite pins)."""
        from cst_captioning_tpu.analysis import partitioning as sp
        from cst_captioning_tpu.analysis import jit_registry

        ghost = "parallel/ring.py::retired_ring_helper"
        monkeypatch.setitem(
            jit_registry.SHARD_MAP_REGISTRY, ghost, "moved away"
        )
        mods = [
            m for m in scan_package(PACKAGE_ROOT)
            if not m.rel.startswith("analysis/")
        ]
        ctx = CheckContext(
            index=PackageIndex(mods), package_root=PACKAGE_ROOT,
            docs_root=None,
        )
        findings = CHECKERS["partitioning"](mods, ctx)
        assert any(
            f.rule == "CST-SHD-004" and ghost in f.message
            and "stale" in f.message
            for f in findings
        ), [f.render() for f in findings]

    def test_kernel_caps_table_checked_against_model_config(self):
        """Vacuous-green guard for CST-SHD-005: the real package's caps
        table covers exactly the declared use_pallas_* flags and the
        real gate consults kernel_supports — so the rule's silence on
        the package scan is a verified pass, not a scoping miss."""
        from cst_captioning_tpu.analysis import partitioning as sp

        mods = list(scan_package(PACKAGE_ROOT))
        core_mi = next(m for m in mods if m.rel == "decoding/core.py")
        caps = sp._caps_table(
            sp._module_assign(core_mi, sp.CAPS_NAME), core_mi
        )
        assert caps and set(caps) == {
            "use_pallas_lstm", "use_pallas_attention",
            "use_pallas_sampler", "use_pallas_beam",
        }
        cfg_mi = next(m for m in mods if m.rel == "config.py")
        assert set(sp._model_config_flags(cfg_mi)) == set(caps)
        cap_mi = next(m for m in mods if m.rel == "models/captioner.py")
        gates = sp._gate_functions(cap_mi)
        assert gates, "models/captioner.py lost _decode_kernel_gate"
        assert not sp._check_kernel_caps(mods)
        # ...and a gate that stops consulting the table fires.
        import ast as _ast

        class _NoCall(_ast.NodeTransformer):
            def visit_Call(self, node):
                self.generic_visit(node)
                name = sp.call_name(node)
                if name and name.endswith("kernel_supports"):
                    return _ast.copy_location(
                        _ast.Constant(value=True), node
                    )
                return node

        stripped = _NoCall().visit(_ast.parse(cap_mi.source))
        _ast.fix_missing_locations(stripped)
        import dataclasses as _dc

        hacked = _dc.replace(cap_mi, tree=stripped)
        out = sp._check_kernel_caps(
            [hacked if m is cap_mi else m for m in mods]
        )
        assert any(
            f.rule == "CST-SHD-005" and "kernel_supports" in f.message
            for f in out
        )


# ------------------------------------------------------------- the corpus

def _parse_corpus():
    """[(module, header families, anywhere rules,
    {line -> set(rule)})]"""
    out = []
    for mi in scan_package(CORPUS):
        header_families, anywhere = set(), set()
        expects = {}
        for lineno, line in enumerate(mi.source.splitlines(), 1):
            m = re.search(r"#\s*corpus-rules:\s*(.+)$", line)
            if m:
                header_families |= {
                    f.strip() for f in m.group(1).split(",")
                }
            m = re.search(r"#\s*corpus-expect-anywhere:\s*(.+)$", line)
            if m:
                anywhere |= {r.strip() for r in m.group(1).split(",")}
            m = re.search(r"#\s*expect:\s*(CST[-A-Z0-9, ]+)$", line)
            if m:
                expects[lineno] = {
                    r.strip() for r in m.group(1).split(",")
                }
        assert header_families, f"{mi.rel}: missing # corpus-rules header"
        out.append((mi, header_families, anywhere, expects))
    return out


class TestCorpus:
    @pytest.fixture(scope="class")
    def corpus_findings(self):
        """All findings over the corpus dir, with a registry entry
        injected for the seeded DON-001 key (corpus keys cannot live in
        the real registry — they would be stale for the package scan)."""
        _load_checkers()
        from cst_captioning_tpu.analysis.jit_registry import (
            JIT_SITE_REGISTRY,
            JitSite,
        )

        mods = scan_package(CORPUS)
        mods = [m for m in mods if m.rel.endswith(".py")]
        ctx = CheckContext(
            index=PackageIndex(mods), package_root=CORPUS, docs_root=None
        )
        from cst_captioning_tpu.analysis.jit_registry import (
            CAST_REGISTRY,
            CastSite,
        )

        key = "donation_bad.py::make_bad_update_step::train_step"
        JIT_SITE_REGISTRY[key] = JitSite(
            "corpus-injected update step", update_step=True
        )
        # the CST-DTY-003 seeds live on registered low-precision paths
        # (legal tiers — an illegal tier would now fire the ISSUE-16
        # tier-vocabulary check against the registry itself)
        cast_key = "typeflow/dty_bad.py::registered_low_precision"
        CAST_REGISTRY[cast_key] = CastSite(
            "relaxed-rtol", "corpus-injected low-precision path",
            low_precision=True,
        )
        quant_key = "typeflow/quant_bad.py::registered_quant_path"
        CAST_REGISTRY[quant_key] = CastSite(
            "relaxed-serving",
            "corpus-injected quantized decision path",
            low_precision=True,
        )
        kern_key = (
            "typeflow/quant_kernel_bad.py::registered_kernel_dequant"
        )
        CAST_REGISTRY[kern_key] = CastSite(
            "relaxed-serving",
            "corpus-injected in-kernel dequant path",
            low_precision=True,
        )
        # configflow's doc-coverage rule (CST-CFG-003) runs against the
        # corpus's own docs twin; every other family runs doc-less.
        cfg_ctx = CheckContext(
            index=ctx.index, package_root=CORPUS,
            docs_root=CORPUS / "configflow" / "docs",
        )
        try:
            findings = []
            for name in sorted(CHECKERS):
                findings.extend(CHECKERS[name](
                    mods, cfg_ctx if name == "configflow" else ctx
                ))
        finally:
            del JIT_SITE_REGISTRY[key]
            del CAST_REGISTRY[cast_key]
            del CAST_REGISTRY[quant_key]
            del CAST_REGISTRY[kern_key]
        return findings

    def test_every_seeded_violation_fires_exactly_its_rule(
        self, corpus_findings
    ):
        for mi, families, anywhere, expects in _parse_corpus():
            got = [
                f for f in corpus_findings
                if f.file == mi.rel and _family(f.rule) in families
            ]
            got_by_line = {}
            for f in got:
                got_by_line.setdefault(f.line, set()).add(f.rule)
            anywhere_hit = {
                f.rule for f in got if f.rule in anywhere
            }
            assert anywhere_hit == anywhere, (
                f"{mi.rel}: anywhere-rules {sorted(anywhere)} vs fired "
                f"{sorted(anywhere_hit)}"
            )
            # line-annotated expectations must match EXACTLY (a seeded
            # violation that stops firing, or a rule that over-fires on
            # the negative-case lines, both fail)
            got_lines = {
                ln: rules for ln, rules in got_by_line.items()
                if not (rules <= anywhere)
            }
            assert got_lines == expects, (
                f"{mi.rel}: expected {expects}, got {got_lines}"
            )

    def test_corpus_covers_every_rule_family(self, corpus_findings):
        fired = {_family(f.rule) for f in corpus_findings}
        assert fired == set(CHECKERS), (
            f"corpus exercises {sorted(fired)}, engine has "
            f"{sorted(CHECKERS)}"
        )


# -------------------------------------- allowlist removal = exact file:line

class TestAllowlistRemoval:
    """The acceptance bar for retiring the grep guards: pulling either
    decode-guard allowlist entry makes the pass fail at the exact
    file:line of the now-unallowed pattern."""

    def _run_single_site(self):
        mods = [
            m for m in scan_package(PACKAGE_ROOT)
            if not m.rel.startswith("analysis/")
        ]
        ctx = CheckContext(
            index=PackageIndex(mods), package_root=PACKAGE_ROOT,
            docs_root=None,
        )
        return CHECKERS["single_site"](mods, ctx)

    def test_removing_core_from_top_k_allowlist(self, monkeypatch):
        from cst_captioning_tpu.analysis import single_site as ss

        monkeypatch.setattr(
            ss, "TOP_K_ALLOWED",
            ss.TOP_K_ALLOWED - {"decoding/core.py"},
        )
        findings = self._run_single_site()
        hits = [
            f for f in findings
            if f.rule == "CST-DEC-001" and f.file == "decoding/core.py"
        ]
        # Two real top_k sites since ISSUE 14: the inline decode_step
        # selection and the cross-shard merge's per-shard local top-K
        # (make_tp_beam_topk.body).
        assert len(hits) == 2
        src = (PACKAGE_ROOT / "decoding/core.py").read_text().splitlines()
        for h in hits:
            assert "top_k" in src[h.line - 1] + src[h.line]

    def test_removing_slots_from_repeat_allowlist(self, monkeypatch):
        from cst_captioning_tpu.analysis import single_site as ss

        monkeypatch.setattr(
            ss, "REPEAT_ALLOWED",
            ss.REPEAT_ALLOWED - {"serving/slots.py"},
        )
        findings = self._run_single_site()
        hits = [
            f for f in findings
            if f.rule == "CST-DEC-004" and f.file == "serving/slots.py"
        ]
        assert len(hits) == 1
        src = (PACKAGE_ROOT / "serving/slots.py").read_text().splitlines()
        window = "\n".join(src[hits[0].line - 2: hits[0].line + 1])
        assert "repeat" in window

    def test_package_has_zero_single_site_findings_with_allowlists(self):
        assert not self._run_single_site()


# ----------------------------------------------------------- suppressions

class TestSuppressions:
    def test_entry_without_justification_is_a_finding(self, tmp_path):
        p = tmp_path / "suppressions.json"
        p.write_text(json.dumps({"entries": [{
            "rule": "CST-DEC-001", "file": "x.py", "symbol": "f",
            "justification": "   ",
        }]}))
        entries, problems = load_suppressions(p)
        assert not entries
        assert problems and problems[0].rule == "CST-SUP-001"
        assert "empty justification" in problems[0].message

    def test_matching_suppression_moves_finding_aside(self, tmp_path):
        f = Finding("CST-DEC-001", "a.py", 3, "f", "msg")
        s = Suppression(
            "CST-DEC-001", "a.py", "f", "kernel twin by necessity"
        )
        from cst_captioning_tpu.analysis.engine import _matches

        assert _matches(s, f)
        assert not _matches(s, Finding("CST-DEC-001", "b.py", 3, "f", "m"))
        assert not _matches(s, Finding("CST-DEC-002", "a.py", 3, "f", "m"))

    def test_malformed_file_is_a_finding_not_a_crash(self, tmp_path):
        p = tmp_path / "suppressions.json"
        p.write_text("{not json")
        entries, problems = load_suppressions(p)
        assert not entries and problems[0].rule == "CST-SUP-001"

    def test_stale_suppression_is_surfaced(self, tmp_path):
        p = tmp_path / "suppressions.json"
        p.write_text(json.dumps({"entries": [{
            "rule": "CST-DEC-001", "file": "never/was.py",
            "symbol": "ghost", "justification": "left over",
        }]}))
        report = run_analysis(PACKAGE_ROOT, suppressions_path=p)
        assert [s.symbol for s in report.unused_suppressions] == ["ghost"]


# -------------------------------------------------- registry + MET fault

class TestRegistryFaults:
    def _ctx_mods(self):
        mods = [
            m for m in scan_package(PACKAGE_ROOT)
            if not m.rel.startswith("analysis/")
        ]
        return mods, CheckContext(
            index=PackageIndex(mods), package_root=PACKAGE_ROOT,
            docs_root=REPO / "docs",
        )

    def test_unregistering_a_jit_site_fires_don002(self, monkeypatch):
        from cst_captioning_tpu.analysis import jit_registry as jr

        key = "training/steps.py::make_xe_train_step::train_step"
        reg = dict(jr.JIT_SITE_REGISTRY)
        entry = reg.pop(key)
        assert entry.update_step
        monkeypatch.setattr(jr, "JIT_SITE_REGISTRY", reg)
        mods, ctx = self._ctx_mods()
        findings = CHECKERS["donation"](mods, ctx)
        assert any(
            f.rule == "CST-DON-002" and key in f.message
            for f in findings
        )

    def test_unregistering_an_aot_site_fires_don004(self, monkeypatch):
        """The PR-13 AOT coverage: dropping the artifact builder's
        AOT_SITE_REGISTRY entry makes its `.lower().compile()` loop an
        unregistered AOT site."""
        from cst_captioning_tpu.analysis import jit_registry as jr

        key = "serving/artifact.py::build_artifact"
        reg = dict(jr.AOT_SITE_REGISTRY)
        assert key in reg
        reg.pop(key)
        monkeypatch.setattr(jr, "AOT_SITE_REGISTRY", reg)
        mods, ctx = self._ctx_mods()
        findings = CHECKERS["donation"](mods, ctx)
        assert any(
            f.rule == "CST-DON-004" and key in f.message
            for f in findings
        )

    def test_stale_aot_entry_fires_don005(self, monkeypatch):
        """The AOT registry cannot rot: an entry matching no live
        lower/compile or executable-load site is a finding."""
        from cst_captioning_tpu.analysis import jit_registry as jr

        reg = dict(jr.AOT_SITE_REGISTRY)
        reg["serving/artifact.py::retired_builder"] = "moved away"
        monkeypatch.setattr(jr, "AOT_SITE_REGISTRY", reg)
        mods, ctx = self._ctx_mods()
        findings = CHECKERS["donation"](mods, ctx)
        assert any(
            f.rule == "CST-DON-005"
            and "retired_builder" in f.message
            for f in findings
        )

    def test_undonated_update_step_fires_don001(self, monkeypatch):
        """Flip the XE train step's registry entry onto a site that
        does NOT donate (the validation sampler) — DON-001 must fire."""
        from cst_captioning_tpu.analysis import jit_registry as jr

        reg = dict(jr.JIT_SITE_REGISTRY)
        key = "training/steps.py::make_greedy_sample_fn::sample"
        reg[key] = jr.JitSite("pretend update step", update_step=True)
        monkeypatch.setattr(jr, "JIT_SITE_REGISTRY", reg)
        mods, ctx = self._ctx_mods()
        findings = CHECKERS["donation"](mods, ctx)
        assert any(
            f.rule == "CST-DON-001" and f.file == "training/steps.py"
            for f in findings
        )

    def test_every_live_cast_tier_is_legal(self):
        """The ISSUE-16 tier vocabulary: every CAST_REGISTRY entry names
        a PARITY_TIERS member, and the relaxed-serving bounds are sane
        pinned constants (a fraction floor, a small positive rtol)."""
        from cst_captioning_tpu.analysis import jit_registry as jr

        for key, entry in jr.CAST_REGISTRY.items():
            assert entry.tier in jr.PARITY_TIERS, (key, entry.tier)
        assert "relaxed-serving" in jr.PARITY_TIERS
        assert 0.0 < jr.RELAXED_SERVING_MATCH_FLOOR <= 1.0
        assert 0.0 < jr.RELAXED_SERVING_SCORE_RTOL < 1.0

    def test_illegal_cast_tier_fires_dty001(self, monkeypatch):
        """An entry claiming a tier outside PARITY_TIERS — a typo'd or
        invented guarantee — must surface against the registry itself."""
        from cst_captioning_tpu.analysis import jit_registry as jr

        key = "ops/quant.py::quant_matmul"
        assert key in jr.CAST_REGISTRY
        monkeypatch.setitem(
            jr.CAST_REGISTRY, key,
            jr.CastSite(
                "close-enough", "typo'd tier", low_precision=True
            ),
        )
        mods, ctx = self._ctx_mods()
        findings = CHECKERS["dtypeflow"](mods, ctx)
        assert any(
            f.rule == "CST-DTY-001"
            and f.file == "analysis/jit_registry.py"
            and key in f.message
            and "illegal parity tier" in f.message
            for f in findings
        ), [f.render() for f in findings]

    def test_duplicate_metric_family_fires_met003(self, monkeypatch):
        import cst_captioning_tpu.serving.metrics as sm

        monkeypatch.setattr(
            sm, "METRIC_FAMILIES",
            sm.METRIC_FAMILIES + [sm.METRIC_FAMILIES[0]],
        )
        mods, ctx = self._ctx_mods()
        findings = CHECKERS["metrics_registry"](mods, ctx)
        assert any(f.rule == "CST-MET-003" for f in findings)

    def test_undocumented_metric_family_fires_met002(self, monkeypatch):
        import cst_captioning_tpu.serving.metrics as sm

        monkeypatch.setattr(
            sm, "METRIC_FAMILIES",
            sm.METRIC_FAMILIES + [("caption_new_series_total", "counter")],
        )
        mods, ctx = self._ctx_mods()
        findings = CHECKERS["metrics_registry"](mods, ctx)
        assert any(
            f.rule == "CST-MET-002"
            and f.symbol == "caption_new_series_total"
            for f in findings
        )


# ------------------------------------------------------------------- CLI

class TestCLI:
    def _run(self, *args, env=None):
        import os

        e = dict(os.environ)
        e["JAX_PLATFORMS"] = "cpu"
        if env:
            e.update(env)
        return subprocess.run(
            [sys.executable, "-m", "cst_captioning_tpu.analysis", *args],
            capture_output=True, text=True, cwd=str(REPO), env=e,
            timeout=120,
        )

    def test_json_mode_is_schema_valid_and_exit_zero(self):
        proc = self._run("--json")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        rec = validate_report(json.loads(proc.stdout))
        assert rec["clean"] is True
        assert rec["findings"] == []

    def test_findings_mean_nonzero_exit(self):
        proc = self._run("--root", str(CORPUS), "--rules", "single_site")
        assert proc.returncode == 1
        assert "CST-DEC-001" in proc.stdout

    def test_budget_overrun_exits_two(self):
        proc = self._run(
            "--rules", "single_site",
            env={"ANALYSIS_BUDGET_S": "0.000001"},
        )
        assert proc.returncode == 2
        assert "ANALYSIS BUDGET EXCEEDED" in proc.stderr

    def test_sarif_mode_is_schema_valid(self):
        from cst_captioning_tpu.analysis import validate_sarif

        proc = self._run(
            "--sarif", "--root", str(CORPUS), "--rules", "rng"
        )
        assert proc.returncode == 1          # corpus has findings
        doc = validate_sarif(json.loads(proc.stdout))
        assert doc["runs"][0]["results"]

    def test_cached_run_keeps_budget_contract(self, tmp_path):
        """ISSUE 12: the ANALYSIS_BUDGET_S exit-2 contract holds with
        the cache enabled — a warm hit is well under any sane budget,
        and a zero budget still exits 2."""
        cache = tmp_path / "cache"
        p1 = self._run(
            "--rules", "single_site", "--cache-dir", str(cache)
        )
        assert p1.returncode == 0, p1.stdout + p1.stderr
        p2 = self._run(
            "--json", "--rules", "single_site",
            "--cache-dir", str(cache),
        )
        assert p2.returncode == 0
        rec = validate_report(json.loads(p2.stdout))
        assert rec["cache_hit_files"] == rec["files_scanned"] > 0
        p3 = self._run(
            "--rules", "single_site", "--cache-dir", str(cache),
            env={"ANALYSIS_BUDGET_S": "0.000001"},
        )
        assert p3.returncode == 2

    def test_changed_only_mode(self, tmp_path):
        """--changed-only: full findings with no baseline, then only
        findings from files whose hash moved."""
        import shutil

        root = tmp_path / "corpus"
        shutil.copytree(CORPUS, root)
        cache = tmp_path / "cache"
        p1 = self._run(
            "--changed-only", "--rules", "rng",
            "--root", str(root), "--cache-dir", str(cache),
        )
        assert p1.returncode == 1            # no baseline: everything
        assert "CST-RNG-001" in p1.stdout
        p2 = self._run(
            "--changed-only", "--rules", "rng",
            "--root", str(root), "--cache-dir", str(cache),
        )
        assert p2.returncode == 0, p2.stdout  # nothing changed
        assert "0 finding(s)" in p2.stdout
        # touch a file that holds findings -> they come back
        bad = root / "rng" / "rng_bad.py"
        bad.write_text(bad.read_text() + "\n# touched\n")
        p3 = self._run(
            "--changed-only", "--rules", "rng",
            "--root", str(root), "--cache-dir", str(cache),
        )
        assert p3.returncode == 1
        assert "rng/rng_bad.py" in p3.stdout
        assert "1 changed file(s)" in p3.stdout


# ------------------------------------------------------------ JSON schema

class TestReportSchema:
    def test_live_report_validates(self):
        rec = run_analysis(PACKAGE_ROOT).to_dict()
        assert validate_report(rec) is rec

    @pytest.mark.parametrize("mutate, msg", [
        (lambda r: r.pop("findings"), "missing required key"),
        (lambda r: r.update(clean="yes"), "'clean' must be a bool"),
        (lambda r: r.update(duration_s=True), "must be a number"),
        (lambda r: r.update(files_scanned=-1), "non-negative"),
        (lambda r: r.update(clean=False), "contradicts"),
        (
            lambda r: r["findings"].append(
                {"rule": "", "file": "f", "line": 1,
                 "symbol": "s", "message": "m"}
            ),
            "non-empty string",
        ),
        (
            lambda r: r.update(cache_hit_files=True),
            "cache_hit_files",
        ),
        (
            lambda r: r.update(cache_hit_files=10**9),
            "exceeds 'files_scanned'",
        ),
    ])
    def test_malformed_reports_fail(self, mutate, msg):
        rec = run_analysis(PACKAGE_ROOT).to_dict()
        mutate(rec)
        with pytest.raises(ValueError, match=msg):
            validate_report(rec)


# ------------------------------------------------------ incremental cache

class TestIncrementalCache:
    RULES = ["rng", "exceptions", "single_site"]

    def test_warm_run_is_faster_and_byte_identical(self, tmp_path):
        """The ISSUE-12 cache contract: a warm full-package re-run is
        measurably faster than cold AND its stable payload is
        byte-identical."""
        cache = tmp_path / "cache"
        cold = run_analysis(
            PACKAGE_ROOT, rules=self.RULES, cache_dir=cache
        )
        assert cold.cache_hit_files == 0
        warm = run_analysis(
            PACKAGE_ROOT, rules=self.RULES, cache_dir=cache
        )
        assert warm.cache_hit_files == warm.files_scanned > 0
        assert json.dumps(
            cold.to_stable_dict(), sort_keys=True
        ) == json.dumps(warm.to_stable_dict(), sort_keys=True)
        # the warm path skips parsing + checking entirely; "measurably
        # faster" with a wide margin so the pin never flakes
        assert warm.duration_s < cold.duration_s / 2

    def test_source_change_invalidates(self, tmp_path):
        """Cold -> hit -> edit one file -> miss (recomputed)."""
        root = tmp_path / "pkg"
        root.mkdir()
        (root / "a.py").write_text("def f(x):\n    return x\n")
        cache = tmp_path / "cache"
        r1 = run_analysis(root, rules=self.RULES, cache_dir=cache)
        r2 = run_analysis(root, rules=self.RULES, cache_dir=cache)
        assert r2.cache_hit_files == 1
        (root / "a.py").write_text(
            "import jax\n\n\ndef f(key, logits):\n"
            "    return jax.random.categorical(key, logits)\n"
        )
        r3 = run_analysis(root, rules=self.RULES, cache_dir=cache)
        assert r3.cache_hit_files == 0
        assert [f.rule for f in r3.findings] == ["CST-RNG-003"]
        assert r1.clean

    def test_rule_selection_is_part_of_the_key(self, tmp_path):
        cache = tmp_path / "cache"
        run_analysis(PACKAGE_ROOT, rules=["rng"], cache_dir=cache)
        other = run_analysis(
            PACKAGE_ROOT, rules=["exceptions"], cache_dir=cache
        )
        assert other.cache_hit_files == 0
        assert other.rules_run == ["exceptions"]

    def test_changed_files_tracking(self, tmp_path):
        from cst_captioning_tpu.analysis import cache as ac

        root = tmp_path / "pkg"
        root.mkdir()
        (root / "a.py").write_text("x = 1\n")
        (root / "b.py").write_text("y = 2\n")
        cache = tmp_path / "cache"
        files = ac.file_digests(root)
        assert ac.changed_files(cache, files) is None  # no baseline
        run_analysis(root, rules=["rng"], cache_dir=cache)
        assert ac.changed_files(cache, ac.file_digests(root)) == []
        (root / "b.py").write_text("y = 3\n")
        assert ac.changed_files(
            cache, ac.file_digests(root)
        ) == ["b.py"]


# ------------------------------------------------------------ SARIF export

class TestSarif:
    def _corpus_report(self):
        return run_analysis(
            CORPUS, rules=["single_site", "rng"],
            suppressions_path=Path("/nonexistent-suppressions.json"),
        )

    def test_corpus_sarif_is_schema_valid_with_results(self):
        from cst_captioning_tpu.analysis import to_sarif, validate_sarif

        rep = self._corpus_report()
        assert rep.findings
        doc = validate_sarif(to_sarif(rep.to_dict()))
        results = doc["runs"][0]["results"]
        assert len(results) == len(rep.findings)
        rules = {
            r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]
        }
        assert {res["ruleId"] for res in results} <= rules
        assert all(res["level"] == "error" for res in results)
        one = next(
            r for r in results if r["ruleId"] == "CST-RNG-001"
        )
        loc = one["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "rng/rng_bad.py"
        assert loc["region"]["startLine"] >= 1

    def test_suppressed_findings_export_as_notes(self, tmp_path):
        from cst_captioning_tpu.analysis import to_sarif, validate_sarif

        rep = self._corpus_report()
        target = rep.findings[0]
        sup = tmp_path / "sup.json"
        sup.write_text(json.dumps({"entries": [{
            "rule": target.rule, "file": target.file,
            "symbol": target.symbol,
            "justification": "corpus example, annotated on purpose",
        }]}))
        rep2 = run_analysis(
            CORPUS, rules=["single_site", "rng"],
            suppressions_path=sup,
        )
        assert rep2.suppressed
        doc = validate_sarif(to_sarif(rep2.to_dict()))
        notes = [
            r for r in doc["runs"][0]["results"]
            if r["level"] == "note"
        ]
        assert notes and all(
            n["suppressions"][0]["justification"] for n in notes
        )

    @pytest.mark.parametrize("mutate, msg", [
        (lambda d: d.update(version="2.0.0"), "version"),
        (lambda d: d.pop("runs"), "one-element list"),
        (
            lambda d: d["runs"][0]["results"].append(
                {"ruleId": "CST-NOPE-999", "ruleIndex": 0,
                 "level": "error", "message": {"text": "x"},
                 "locations": []}
            ),
            "not in",
        ),
        (
            lambda d: d["runs"][0]["results"][0].update(level="fatal"),
            "level",
        ),
        (
            lambda d: d["runs"][0]["results"][0]["locations"][0][
                "physicalLocation"
            ]["region"].update(startLine=0),
            "startLine",
        ),
    ])
    def test_malformed_sarif_fails(self, mutate, msg):
        from cst_captioning_tpu.analysis import to_sarif, validate_sarif

        doc = to_sarif(self._corpus_report().to_dict())
        mutate(doc)
        with pytest.raises(ValueError, match=msg):
            validate_sarif(doc)


# ------------------------------------------------- suppression expiry

class TestSuppressionExpiry:
    def _entry(self, **kv):
        e = {
            "rule": "CST-DEC-001", "file": "never/was.py",
            "symbol": "ghost", "justification": "dated debt",
        }
        e.update(kv)
        return {"entries": [e]}

    def test_expired_entry_fires_sup002(self, tmp_path):
        p = tmp_path / "sup.json"
        p.write_text(json.dumps(self._entry(expires="2020-01-01")))
        rep = run_analysis(PACKAGE_ROOT, suppressions_path=p)
        rules = [f.rule for f in rep.findings]
        assert rules == ["CST-SUP-002"]
        assert "2020-01-01" in rep.findings[0].message
        assert "dated debt" in rep.findings[0].message

    def test_future_dated_entry_stays_quiet(self, tmp_path):
        p = tmp_path / "sup.json"
        p.write_text(json.dumps(self._entry(expires="2099-01-01")))
        rep = run_analysis(PACKAGE_ROOT, suppressions_path=p)
        assert not any(
            f.rule == "CST-SUP-002" for f in rep.findings
        )
        # matching nothing, it still surfaces as stale
        assert [s.symbol for s in rep.unused_suppressions] == ["ghost"]

    def test_invalid_date_is_sup001(self, tmp_path):
        from cst_captioning_tpu.analysis.engine import load_suppressions

        p = tmp_path / "sup.json"
        p.write_text(json.dumps(self._entry(expires="next-tuesday")))
        entries, problems = load_suppressions(p)
        assert not entries
        assert problems[0].rule == "CST-SUP-001"
        assert "YYYY-MM-DD" in problems[0].message

    def test_expired_entry_still_matches_its_target(self, tmp_path):
        """The expiry contract: the target finding surfaces exactly
        once — as the CST-SUP-002 — not twice."""
        rep0 = run_analysis(
            CORPUS, rules=["rng"],
            suppressions_path=Path("/nonexistent.json"),
        )
        target = next(
            f for f in rep0.findings if f.rule == "CST-RNG-001"
        )
        p = tmp_path / "sup.json"
        p.write_text(json.dumps({"entries": [{
            "rule": target.rule, "file": target.file,
            "symbol": target.symbol,
            "justification": "corpus debt",
            "expires": "2020-01-01",
        }]}))
        rep = run_analysis(CORPUS, rules=["rng"], suppressions_path=p)
        assert any(f.rule == "CST-SUP-002" for f in rep.findings)
        assert not any(
            f.rule == target.rule and f.file == target.file
            and f.symbol == target.symbol
            for f in rep.findings
        )
        assert rep.suppressed
        assert not rep.unused_suppressions


# ------------------------------------- ISSUE 15: dtype/shape flow engine

def _package_world():
    mods = [
        m for m in scan_package(PACKAGE_ROOT)
        if not m.rel.startswith("analysis/")
    ]
    ctx = CheckContext(
        index=PackageIndex(mods), package_root=PACKAGE_ROOT,
        docs_root=None,
    )
    return mods, ctx


@pytest.fixture(scope="module")
def typeflow_world():
    _load_checkers()
    mods, ctx = _package_world()
    from cst_captioning_tpu.analysis import typeflow as tfmod

    return mods, ctx, tfmod.build(mods, ctx)


class TestTypeflowGuards:
    """Vacuous-green guards: the abstract interpreter must actually SEE
    the real cast surface, the real jit-site ladder surface, and the
    real AOT contract class — and prove real dtype facts — before its
    0-findings package run means anything."""

    def test_cast_surface_discovery(self, typeflow_world):
        from cst_captioning_tpu.analysis.jit_registry import CAST_REGISTRY
        from cst_captioning_tpu.analysis.typeflow import cast_sites

        mods, ctx, tf = typeflow_world
        sites = cast_sites(mods, tf)
        keys = {k for k, *_ in sites}
        # the real package's traced cast surface (39 sites / 140+ casts
        # at ISSUE 15) — shrinking discovery must fail loudly
        assert len(keys) >= 35, sorted(keys)
        assert len(sites) >= 120
        for expected in (
            "decoding/core.py::decode_step",
            "models/captioner.py::CaptionModel._logits",
            "ops/rnn.py::lstm_step",
            "ops/pallas_sampler.py::_gumbel_from_counter",
            # r18: admission casts moved from .tick into the shared
            # admit_all helper (plain + spec ticks both call it)
            "serving/slots.py::SlotDecoder._tick_fn.admit_all",
        ):
            assert expected in keys
        # and every discovered site is registered (the 0-findings run
        # is coverage, not blindness)
        assert keys <= set(CAST_REGISTRY)

    def test_every_jit_site_has_a_shape_ladder(self, typeflow_world):
        from cst_captioning_tpu.analysis.donation import collect_jit_sites
        from cst_captioning_tpu.analysis.jit_registry import (
            JIT_SITE_REGISTRY,
            SHAPE_LADDER_REGISTRY,
        )

        mods, ctx, tf = typeflow_world
        sites = collect_jit_sites(mods)
        assert len(sites) >= 26          # the registered jit surface
        keys = {k for k, *_ in sites}
        assert keys == set(JIT_SITE_REGISTRY)
        assert keys == set(SHAPE_LADDER_REGISTRY)
        enumerated = {
            k for k, e in SHAPE_LADDER_REGISTRY.items()
            if e.kind == "enumerated"
        }
        # the serving ladder + slot bank grid + PG trim buckets
        assert len(enumerated) >= 5
        defined = {
            f"{m.rel}::{qn}" for m in mods for qn in m.functions
        }
        for k in enumerated:
            assert SHAPE_LADDER_REGISTRY[k].bucket_fns, k
            for fq in SHAPE_LADDER_REGISTRY[k].bucket_fns:
                assert fq in defined, f"{k} names dead bucket fn {fq}"

    def test_aot_drift_checker_sees_slotdecoder(self, typeflow_world):
        from cst_captioning_tpu.analysis.shapeflow import (
            aot_contract_classes,
        )

        mods, ctx, tf = typeflow_world
        found = {
            (mi.rel, cls) for mi, cls, _ in aot_contract_classes(mods)
        }
        assert ("serving/slots.py", "SlotDecoder") in found
        _, _, methods = next(
            t for t in aot_contract_classes(mods)
            if t[1] == "SlotDecoder"
        )
        # the three compiled-variant families the drift rule audits
        assert {"_tick_fn", "_free_fn", "_resize_fn"} <= set(methods)

    def test_interpreter_proves_f32_logits_exit(self, typeflow_world):
        """The PARITY contract 'decode scores exit f32' is now a
        dataflow FACT: the abstract value of _logits' return is f32
        (matmul preferred_element_type + f32 bias promotion)."""
        import ast as _ast

        from cst_captioning_tpu.analysis.astutil import walk_body

        mods, ctx, tf = typeflow_world
        mi = next(m for m in mods if m.rel == "models/captioner.py")
        fn = mi.functions["CaptionModel._logits"]
        types = tf.types_of(fn)
        ret = next(
            n for n in walk_body(fn) if isinstance(n, _ast.Return)
        )
        v = types.value_of(ret.value)
        assert v.dtype == "f32", v

    def test_interpreter_proves_int_arrays(self, tmp_path):
        """End-to-end dtype propagation on a synthetic root: arange →
        i32, astype → bf16, weak literal does NOT widen."""
        import ast as _ast

        from cst_captioning_tpu.analysis import typeflow as tfmod
        from cst_captioning_tpu.analysis.astutil import walk_body

        (tmp_path / "m.py").write_text(
            "import jax\nimport jax.numpy as jnp\n\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    tok = jnp.arange(8)\n"
            "    half = jnp.zeros((4,), jnp.bfloat16) * 0.5\n"
            "    down = tok.astype(jnp.int8)\n"
            "    return tok, half, down\n"
        )
        mods = scan_package(tmp_path)
        ctx = CheckContext(
            index=PackageIndex(mods), package_root=tmp_path,
            docs_root=None,
        )
        tf = tfmod.TypeFlow(mods, ctx)
        fn = mods[0].functions["f"]
        types = tf.types_of(fn)
        vals = {}
        for n in walk_body(fn):
            if isinstance(n, _ast.Assign) and isinstance(
                n.targets[0], _ast.Name
            ):
                vals[n.targets[0].id] = types.value_of(n.value)
        assert vals["tok"].dtype == "i32" and vals["tok"].array
        assert vals["half"].dtype == "bf16"     # weak 0.5 can't widen
        assert vals["down"].dtype == "i8"

    def test_promotion_lattice_weak_rules(self):
        from cst_captioning_tpu.analysis.typeflow import promote

        assert promote("bf16", "wf") == "bf16"   # scalar never widens
        assert promote("i32", "wf") == "f32"     # ...but floats ints
        assert promote("i32", "wi") == "i32"
        assert promote("bf16", "f16") == "f32"   # jax's odd couple
        assert promote("bf16", "f32") == "f32"
        assert promote("any", "f32") == "any"    # top absorbs

    def test_low_precision_surface_stays_declared(self):
        """The compute-dtype paths must keep their low_precision flag —
        flipping one off silently exempts its matmuls from the
        CST-DTY-003 accumulation pin."""
        from cst_captioning_tpu.analysis.jit_registry import CAST_REGISTRY

        for key in (
            "models/captioner.py::CaptionModel._logits",
            "models/captioner.py::CaptionModel._encode",
            "ops/rnn.py::lstm_step",
            "ops/pallas_attention.py::dense_context_attention",
            "ops/shard_decode.py::_local_logits",
            "ops/pallas_beam.py::_make_beam_kernel.kernel",
        ):
            assert CAST_REGISTRY[key].low_precision, key


class TestTypeflowRegistryFaults:
    """The acceptance bar: removing any single CAST_REGISTRY /
    SHAPE_LADDER_REGISTRY entry fails the pass at the exact
    file:line."""

    def _run(self, family, mods, ctx):
        return CHECKERS[family](mods, ctx)

    def test_unregistering_a_cast_site_fires_dty001(
        self, monkeypatch, typeflow_world
    ):
        from cst_captioning_tpu.analysis import jit_registry as jr

        mods, ctx, tf = typeflow_world
        key = "decoding/core.py::decode_step"
        monkeypatch.delitem(jr.CAST_REGISTRY, key)
        hits = [
            f for f in self._run("dtypeflow", mods, ctx)
            if f.rule == "CST-DTY-001" and f.file == "decoding/core.py"
        ]
        assert len(hits) == 1
        src = (PACKAGE_ROOT / "decoding/core.py").read_text().splitlines()
        assert "astype" in src[hits[0].line - 1]

    def test_stale_cast_entry_fires_dty001(
        self, monkeypatch, typeflow_world
    ):
        from cst_captioning_tpu.analysis import jit_registry as jr

        mods, ctx, tf = typeflow_world
        monkeypatch.setitem(
            jr.CAST_REGISTRY,
            "decoding/core.py::no_such_function",
            jr.CastSite("token-exact", "stale"),
        )
        hits = [
            f for f in self._run("dtypeflow", mods, ctx)
            if f.rule == "CST-DTY-001"
            and "stale" in f.message
            and f.symbol == "decoding/core.py::no_such_function"
        ]
        assert len(hits) == 1
        assert hits[0].file == "analysis/jit_registry.py"

    def test_unregistering_a_shape_ladder_fires_shp001(
        self, monkeypatch, typeflow_world
    ):
        from cst_captioning_tpu.analysis import jit_registry as jr

        mods, ctx, tf = typeflow_world
        key = "serving/slots.py::SlotDecoder._tick_fn.tick"
        monkeypatch.delitem(jr.SHAPE_LADDER_REGISTRY, key)
        hits = [
            f for f in self._run("shapeflow", mods, ctx)
            if f.rule == "CST-SHP-001" and f.file == "serving/slots.py"
        ]
        assert len(hits) == 1
        src = (PACKAGE_ROOT / "serving/slots.py").read_text().splitlines()
        window = src[hits[0].line - 1] + src[hits[0].line]
        assert "jit" in window

    def test_stale_ladder_entry_fires_shp001(
        self, monkeypatch, typeflow_world
    ):
        from cst_captioning_tpu.analysis import jit_registry as jr

        mods, ctx, tf = typeflow_world
        monkeypatch.setitem(
            jr.SHAPE_LADDER_REGISTRY,
            "serving/slots.py::no_such_site",
            jr.ShapeLadder("fixed", "stale"),
        )
        hits = [
            f for f in self._run("shapeflow", mods, ctx)
            if f.rule == "CST-SHP-001" and "stale" in f.message
        ]
        assert [f.symbol for f in hits] == [
            "serving/slots.py::no_such_site"
        ]

    def test_dead_bucket_fn_fires_shp001(
        self, monkeypatch, typeflow_world
    ):
        from cst_captioning_tpu.analysis import jit_registry as jr

        mods, ctx, tf = typeflow_world
        key = "serving/slots.py::SlotDecoder._free_fn.free_rows"
        old = jr.SHAPE_LADDER_REGISTRY[key]
        monkeypatch.setitem(
            jr.SHAPE_LADDER_REGISTRY, key,
            old._replace(
                bucket_fns=("serving/slots.py::_renamed_ladder",)
            ),
        )
        hits = [
            f for f in self._run("shapeflow", mods, ctx)
            if f.rule == "CST-SHP-001" and "no live def" in f.message
        ]
        assert len(hits) == 1 and hits[0].symbol == key


class TestBaselineCLI:
    """--baseline / --fail-on-new semantics (ISSUE 15): a committed
    baseline absorbs known findings, the gate trips only on new ones,
    and a malformed baseline refuses loudly."""

    def _run(self, *args, env=None):
        import os

        e = dict(os.environ)
        e["JAX_PLATFORMS"] = "cpu"
        if env:
            e.update(env)
        return subprocess.run(
            [sys.executable, "-m", "cst_captioning_tpu.analysis", *args],
            capture_output=True, text=True, cwd=str(REPO), env=e,
            timeout=120,
        )

    def _corpus_json(self):
        proc = self._run(
            "--json", "--root", str(CORPUS), "--rules", "dtypeflow"
        )
        assert proc.returncode == 1          # corpus seeds findings
        return json.loads(proc.stdout)

    def test_baseline_absorbs_known_findings(self, tmp_path):
        rec = self._corpus_json()
        base = tmp_path / "baseline.json"
        base.write_text(json.dumps(rec))
        # fail-on-new: everything known -> exit 0
        p = self._run(
            "--root", str(CORPUS), "--rules", "dtypeflow",
            "--baseline", str(base), "--fail-on-new",
        )
        assert p.returncode == 0, p.stdout + p.stderr
        assert "0 new" in p.stdout
        # without --fail-on-new the baseline only annotates: the old
        # findings still gate (exit 1)
        p2 = self._run(
            "--root", str(CORPUS), "--rules", "dtypeflow",
            "--baseline", str(base),
        )
        assert p2.returncode == 1
        assert "0 new" in p2.stdout

    def test_new_finding_trips_the_gate(self, tmp_path):
        rec = self._corpus_json()
        assert len(rec["findings"]) >= 2
        dropped = rec["findings"].pop(0)     # one triple becomes NEW
        base = tmp_path / "baseline.json"
        base.write_text(json.dumps(rec))
        p = self._run(
            "--root", str(CORPUS), "--rules", "dtypeflow",
            "--baseline", str(base), "--fail-on-new",
        )
        assert p.returncode == 1
        assert "NEW:" in p.stdout
        assert "1 new" in p.stdout
        assert dropped["rule"] in p.stdout

    def test_json_mode_carries_new_findings(self, tmp_path):
        rec = self._corpus_json()
        rec["findings"] = rec["findings"][1:]
        base = tmp_path / "baseline.json"
        base.write_text(json.dumps(rec))
        p = self._run(
            "--json", "--root", str(CORPUS), "--rules", "dtypeflow",
            "--baseline", str(base), "--fail-on-new",
        )
        assert p.returncode == 1
        out = json.loads(p.stdout)
        validate_report(out)
        assert len(out["new_findings"]) == 1

    def test_baseline_is_count_aware(self, tmp_path):
        """Two same-triple findings against ONE baseline entry: one is
        absorbed, the second is new (a regression that adds a second
        violation to an already-dirty symbol still trips)."""
        rec = self._corpus_json()
        trip_counts = {}
        for f in rec["findings"]:
            k = (f["rule"], f["file"], f["symbol"])
            trip_counts[k] = trip_counts.get(k, 0) + 1
        dup = next(
            (k for k, n in trip_counts.items() if n >= 2), None
        )
        assert dup is not None, (
            "corpus must seed a symbol with two same-rule findings "
            "(registered_low_precision's two unpinned matmuls)"
        )
        kept = []
        skipped = False
        for f in rec["findings"]:
            if not skipped and (
                f["rule"], f["file"], f["symbol"]
            ) == dup:
                skipped = True
                continue
            kept.append(f)
        rec["findings"] = kept
        base = tmp_path / "baseline.json"
        base.write_text(json.dumps(rec))
        p = self._run(
            "--root", str(CORPUS), "--rules", "dtypeflow",
            "--baseline", str(base), "--fail-on-new",
        )
        assert p.returncode == 1
        assert "1 new" in p.stdout

    def test_malformed_baseline_exits_two(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        p = self._run(
            "--root", str(CORPUS), "--rules", "dtypeflow",
            "--baseline", str(bad), "--fail-on-new",
        )
        assert p.returncode == 2
        assert "unreadable" in p.stderr
        bad.write_text(json.dumps({"findings": [{"rule": 3}]}))
        p2 = self._run(
            "--root", str(CORPUS), "--rules", "dtypeflow",
            "--baseline", str(bad), "--fail-on-new",
        )
        assert p2.returncode == 2
        assert "malformed" in p2.stderr

    def test_fail_on_new_requires_baseline(self):
        p = self._run("--fail-on-new")
        assert p.returncode == 2
        assert "--baseline" in p.stderr


class TestTypeflowSarif:
    def test_sarif_export_includes_the_new_rules(self):
        """ISSUE 15 satellite: the corpus SARIF carries CST-DTY and
        CST-SHP driver rules (the scanning UIs discover them there)."""
        from cst_captioning_tpu.analysis.sarif import (
            to_sarif,
            validate_sarif,
        )

        _load_checkers()
        mods = scan_package(CORPUS)
        ctx = CheckContext(
            index=PackageIndex(mods), package_root=CORPUS,
            docs_root=None,
        )
        findings = []
        for name in ("dtypeflow", "shapeflow"):
            findings.extend(CHECKERS[name](mods, ctx))
        from cst_captioning_tpu.analysis.engine import Report

        rep = Report(
            findings=findings, suppressed=[], unused_suppressions=[],
            rules_run=["dtypeflow", "shapeflow"],
            files_scanned=len(mods), duration_s=0.1,
        )
        doc = validate_sarif(to_sarif(rep.to_dict()))
        ids = {
            r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]
        }
        assert {"CST-DTY-001", "CST-DTY-002", "CST-DTY-004"} <= ids
        assert {"CST-SHP-001", "CST-SHP-002", "CST-SHP-003"} <= ids
