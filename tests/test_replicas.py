"""Multi-replica data-parallel serving (cst_captioning_tpu/serving/replicas.py).

Covers the ISSUE-4 acceptance bar:

* Router policy units: least-loaded by free-slot count with a
  round-robin tiebreak, plus the plain round-robin policy;
* scheduler semantics on stub engines (no jax): admission fairness
  across replicas, no request double-assigned (the decoder
  hard-raises), worker death -> unhealthy + requeue-to-survivor with
  deadlines honored, zero-healthy-replicas rejection;
* cross-replica TOKEN EXACTNESS (real jax, the 8 forced CPU devices
  from conftest): captions served by ANY replica — double-buffered and
  synchronous dispatch, beam and greedy, random concurrent arrival —
  are exactly what the offline ``evaluation.py`` path produces for the
  same params/features;
* ``kill_replica`` mid-traffic: every accepted request still completes
  (on a survivor) with the exact offline caption;
* HTTP surface: per-replica ``/metrics`` labels, ``/healthz`` replica
  counts, and the 503 degradation ONLY at zero healthy replicas.

Ordering note: like test_serving.py, the real-engine fixtures are
module-scoped and tier-1 runs without randomization, so file order
holds.
"""

import threading
import time

import numpy as np
import pytest

from cst_captioning_tpu.config import get_preset
from cst_captioning_tpu.serving.batcher import DeadlineExceededError
from cst_captioning_tpu.serving.cache import TwoTierCache
from cst_captioning_tpu.serving.engine import DecodedResult, PreparedRequest
from cst_captioning_tpu.serving.metrics import ServingMetrics
from cst_captioning_tpu.serving.replicas import (
    NoHealthyReplicasError,
    ReplicaSet,
    Router,
)


# ------------------------------------------------------------------ router

class _FakeRep:
    def __init__(self, cap):
        self._cap = cap

    def free_capacity(self):
        return self._cap


class TestRouter:
    def test_least_loaded_prefers_most_free_slots(self):
        r = Router("least_loaded")
        a, b, c = _FakeRep(1), _FakeRep(3), _FakeRep(2)
        assert r.pick([a, b, c]) is b
        b._cap = 0
        assert r.pick([a, b, c]) is c

    def test_least_loaded_tiebreak_is_round_robin(self):
        r = Router("least_loaded")
        a, b = _FakeRep(2), _FakeRep(2)
        picks = [r.pick([a, b]) for _ in range(4)]
        assert picks == [a, b, a, b]

    def test_round_robin_ignores_load(self):
        r = Router("round_robin")
        a, b = _FakeRep(0), _FakeRep(5)
        picks = [r.pick([a, b]) for _ in range(4)]
        assert picks == [a, b, a, b]

    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError):
            Router("fifo")
        with pytest.raises(ValueError):
            Router("least_loaded").pick([])


# ------------------------------------------- scheduler (stub engines)

class _StubDecoder:
    """Async-API SlotDecoder double: each request carries a tick budget
    (smuggled via ``prepared.category``); a tick decrements every
    occupant, done at zero.  Hard-asserts on slot double-assignment."""

    def __init__(self, S=2, block=1):
        self.S, self.K, self.L, self.block = S, 1, 10_000, block
        self.admit_cap = S
        self.free = list(range(S))
        self.occupied = {}
        self._remaining = {}
        self._admit_seq = {}
        self._seq = 0
        self.fail_next = False    # poison pill: next tick_begin raises
        self.resize_count = 0

    @property
    def n_occupied(self):
        return len(self.occupied)

    def maybe_resize(self, pending=0):
        return self.S

    def live_state_bytes(self):
        return 64 * self.n_occupied

    def tick_begin(self, prepared=(), datas=()):
        if self.fail_next:
            raise RuntimeError("injected decoder failure")
        for req, data in zip(prepared, datas):
            slot = self.free.pop()
            assert slot not in self.occupied, "slot double-assigned"
            self.occupied[slot] = data
            self._remaining[slot] = req.category
            self._admit_seq[slot] = self._seq + 1
        if not self.occupied:
            return None
        self._seq += 1
        for s in self.occupied:
            self._remaining[s] -= self.block
        done = tuple(
            s for s in self.occupied if self._remaining[s] <= 0
        )
        return (self._seq, done)

    def tick_wait(self, handle):
        time.sleep(0.001)         # a "device step block"
        seq, done = handle
        return [
            s for s in done
            if s in self.occupied and self._admit_seq[s] <= seq
        ]

    def harvest_from(self, handle, slots):
        seq, _ = handle
        out = []
        for s in slots:
            data = self.occupied.pop(s)
            steps = (seq - self._admit_seq.pop(s) + 1) * self.block
            self._remaining.pop(s, None)
            self.free.append(s)
            out.append((data, np.asarray([5, 2], np.int32), 0.0, steps))
        return out

    def evict(self, slot):
        data = self.occupied.pop(slot)
        self._remaining.pop(slot, None)
        self._admit_seq.pop(slot, None)
        self.free.append(slot)
        return data


class _StubEngine:
    def __init__(self, S=2):
        self.cfg = get_preset("synthetic_smoke")
        self.cache = TwoTierCache(8, 8)
        self._decoder = _StubDecoder(S=S)
        self.device = None

    def prepare(self, payload):
        return PreparedRequest(
            feats=None, masks=None,
            category=int(payload.get("steps", 3)),  # tick budget
            feature_id=None, cache_key=payload.get("key", ""),
            enc_row=None,
        )

    def lookup_caption(self, key):
        return self.cache.captions.get(key) if key else None

    def slot_decoder(self):
        return self._decoder

    def result_from_tokens(self, req, tokens, timings_ms, store=True):
        return DecodedResult(
            caption="replica-stub",
            tokens=[int(t) for t in tokens],
            timings_ms=timings_ms,
        )


def _submit_bg(rs, payload, results, errors, lock, deadline_ms=None):
    def go():
        try:
            out = rs.submit(payload, deadline_ms=deadline_ms)
            with lock:
                results.append(out)
        except Exception as e:  # noqa: BLE001
            with lock:
                errors.append(e)

    t = threading.Thread(target=go)
    t.start()
    return t


class TestReplicaScheduler:
    def test_admission_fairness_across_replicas(self):
        """Equal replicas split the load: no replica is starved and no
        request is served twice (the stub decoder asserts on
        double-assignment)."""
        rs = ReplicaSet([_StubEngine(S=2), _StubEngine(S=2)])
        results, errors = [], []
        lock = threading.Lock()
        with rs:
            threads = [
                _submit_bg(rs, {"steps": 5}, results, errors, lock)
                for _ in range(12)
            ]
            for t in threads:
                t.join(timeout=20.0)
        assert not errors, errors
        assert len(results) == 12
        a0 = rs.metrics.replica(0).admitted_total.value
        a1 = rs.metrics.replica(1).admitted_total.value
        assert a0 + a1 == 12
        assert a0 >= 3 and a1 >= 3, (a0, a1)
        assert rs.metrics.requests_served.value == 12
        for rep in rs.replicas:
            assert not rep.decoder.occupied
            assert sorted(rep.decoder.free) == list(range(2))

    def test_worker_death_requeues_inflight_to_survivor(self):
        """A dead replica's in-flight request completes on a survivor
        instead of being dropped; the replica is drained from routing
        and its health gauge goes to 0."""
        engines = [_StubEngine(S=1), _StubEngine(S=1)]
        rs = ReplicaSet(engines)
        results, errors = [], []
        lock = threading.Lock()
        with rs:
            threads = [
                _submit_bg(rs, {"steps": 100}, results, errors, lock)
                for _ in range(2)
            ]
            # Both replicas are mid-decode (one job each, S=1); poison
            # replica 0's next tick.
            for _ in range(200):
                if all(e._decoder.occupied for e in engines):
                    break
                time.sleep(0.005)
            engines[0]._decoder.fail_next = True
            for t in threads:
                t.join(timeout=30.0)
        assert not errors, errors
        assert len(results) == 2           # nothing dropped
        assert rs.healthy_replicas == 1
        assert not rs.replicas[0].healthy
        assert rs.metrics.replica(0).healthy.value == 0
        assert rs.metrics.replica(1).healthy.value == 1
        assert not engines[0]._decoder.occupied   # evicted clean
        assert rs.metrics.requests_failed.value == 0

    def test_requeue_honors_deadlines(self):
        """A request stranded on a killed replica past its deadline
        fails with DeadlineExceededError — not silently, not served
        late."""
        engines = [_StubEngine(S=1), _StubEngine(S=1)]
        rs = ReplicaSet(engines)
        results, errors = [], []
        lock = threading.Lock()
        rs.start()
        try:
            # Fill BOTH single-slot replicas with long jobs, then queue
            # a short-deadline request behind one of them.
            blockers = [
                _submit_bg(rs, {"steps": 5000}, results, errors, lock)
                for _ in range(2)
            ]
            for _ in range(200):
                if all(e._decoder.occupied for e in engines):
                    break
                time.sleep(0.005)
            t3 = _submit_bg(
                rs, {"steps": 1}, results, errors, lock,
                deadline_ms=40.0,
            )
            for _ in range(100):               # r3 lands in some queue
                if any(r.q for r in rs.replicas):
                    break
                time.sleep(0.005)
            holder = next(r for r in rs.replicas if r.q)
            time.sleep(0.1)                    # r3's 40ms deadline passes
            rs.kill_replica(holder.rid)
            t3.join(timeout=20.0)
        finally:
            rs.stop(drain=False)
            for t in blockers:
                t.join(timeout=20.0)
        deadline_errs = [
            e for e in errors if isinstance(e, DeadlineExceededError)
        ]
        assert len(deadline_errs) == 1, errors
        assert rs.metrics.requests_expired.value == 1

    def test_zero_healthy_replicas_rejects_submit(self):
        rs = ReplicaSet([_StubEngine(S=1)])
        with rs:
            rs.kill_replica(0)
            assert rs.healthy_replicas == 0
            with pytest.raises(NoHealthyReplicasError):
                rs.submit({"steps": 1})

    def test_sync_dispatch_mode(self):
        """double_buffer=False runs the same worker with one sync per
        tick and identical semantics."""
        rs = ReplicaSet(
            [_StubEngine(S=2), _StubEngine(S=2)], double_buffer=False
        )
        results, errors = [], []
        lock = threading.Lock()
        with rs:
            threads = [
                _submit_bg(rs, {"steps": 3}, results, errors, lock)
                for _ in range(6)
            ]
            for t in threads:
                t.join(timeout=20.0)
        assert not errors and len(results) == 6
        assert rs.metrics.requests_served.value == 6


# ---------------------------- cross-replica parity (real jax, 8 devices)

class TestReplicaMemoryMetrics:
    def test_per_replica_decode_state_gauges_render(self):
        """ISSUE-7 satellite: the decode-state byte and slot-bank-size
        gauges exist per replica, matching the PR-4 label scheme."""
        from cst_captioning_tpu.serving.metrics import ServingMetrics

        m = ServingMetrics()
        m.replica(0).decode_state_bytes.set(4096)
        m.replica(0).slot_bank_size.set(8)
        m.replica(1).decode_state_bytes.set(0)
        m.decode_state_bytes.set(4096)
        m.slot_bank_size.set(8)
        m.slot_bank_resizes.inc(2)
        text = m.to_prometheus()
        assert 'caption_replica_decode_state_bytes{replica="0"} 4096' in text
        assert 'caption_replica_slot_bank_size{replica="0"} 8' in text
        assert 'caption_replica_decode_state_bytes{replica="1"} 0' in text
        assert "caption_decode_state_bytes 4096" in text
        assert "caption_slot_bank_size 8" in text
        assert "caption_slot_bank_resizes_total 2" in text
        d = m.to_dict()
        assert d["slots"]["decode_state_bytes"] == 4096.0
        assert d["slots"]["bank_size"] == 8.0
        assert d["replicas"]["0"]["decode_state_bytes"] == 4096.0
        assert d["replicas"]["0"]["slot_bank_size"] == 8.0


@pytest.fixture(scope="module")
def replica_world():
    """Source engine + offline beam predictions + two device-pinned
    replica clones (weights device_put once per clone)."""
    import jax

    from cst_captioning_tpu.data.build import build_dataset
    from cst_captioning_tpu.evaluation import beam_decode_dataset
    from cst_captioning_tpu.serving.engine import InferenceEngine

    cfg = get_preset("synthetic_smoke")
    cfg.serving.warmup = False
    cfg.serving.num_slots = 4
    cfg.serving.default_deadline_ms = 120_000.0
    ds, vocab = build_dataset(cfg, cfg.eval.eval_split)
    cfg.model.vocab_size = len(vocab)
    engine = InferenceEngine(cfg, random_init=True, vocab=vocab)
    offline = beam_decode_dataset(engine.model, engine.params, ds, cfg)
    payloads = [
        {"features": {m: a.tolist() for m, a in ds.features(i).items()}}
        for i in range(len(ds))
    ]
    devices = jax.devices()
    assert len(devices) >= 2, "conftest must force multiple CPU devices"
    clones = [
        engine.clone_for_device(devices[i], replica_id=i)
        for i in range(2)
    ]
    return engine, clones, ds, offline, payloads


def _fuzz_submit(rs, payloads, idx, rng, jitter_s=0.05):
    results, errors = {}, []
    lock = threading.Lock()

    def client(i):
        time.sleep(float(rng.rand()) * jitter_s)
        try:
            out = rs.submit(dict(payloads[i]), deadline_ms=120_000.0)
            with lock:
                results[i] = out
        except Exception as e:  # noqa: BLE001
            with lock:
                errors.append((i, repr(e)))

    with rs:
        threads = [
            threading.Thread(target=client, args=(i,)) for i in idx
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
    return results, errors


class TestCrossReplicaParity:
    def test_beam_parity_random_arrival_double_buffered(
        self, replica_world
    ):
        """THE tentpole bar: 16 requests fuzzed across 2 replicas with
        double-buffered dispatch — every caption token-exact vs the
        offline beam decode, both replicas actually used, both slot
        matrices clean afterwards."""
        engine, clones, ds, offline, payloads = replica_world
        engine.cache.captions.clear()
        rng = np.random.RandomState(31)
        idx = list(rng.permutation(16))
        rs = ReplicaSet(clones, double_buffer=True)
        results, errors = _fuzz_submit(rs, payloads, idx, rng)
        assert not errors, errors
        assert len(results) == 16
        for i in range(16):
            assert results[i]["caption"] == offline[ds.video_id(i)], (
                f"video {i} (replica {results[i].get('replica')}): "
                "cross-replica decode diverged from offline beam"
            )
        a0 = rs.metrics.replica(0).admitted_total.value
        a1 = rs.metrics.replica(1).admitted_total.value
        assert a0 + a1 == 16 and a0 > 0 and a1 > 0, (a0, a1)
        for rep in rs.replicas:
            assert not rep.decoder.occupied
            assert sorted(rep.decoder.free) == list(range(rep.decoder.S))
        assert rs.metrics.requests_failed.value == 0
        assert rs.metrics.requests_expired.value == 0

    def test_beam_parity_synchronous_dispatch(self, replica_world):
        """serving.double_buffer=false path: same parity bar through
        the one-sync-per-tick worker loop."""
        engine, clones, ds, offline, payloads = replica_world
        engine.cache.captions.clear()
        rng = np.random.RandomState(7)
        idx = list(rng.permutation(8))
        rs = ReplicaSet(clones, double_buffer=False)
        results, errors = _fuzz_submit(rs, payloads, idx, rng)
        assert not errors, errors
        for i in range(8):
            assert results[i]["caption"] == offline[ds.video_id(i)]

    def test_kill_replica_mid_traffic_completes_on_survivor(
        self, replica_world
    ):
        """Replica 0 is killed while traffic is in flight: every
        accepted request still resolves with the exact offline caption
        (requeued work redecodes on the survivor), the dead replica is
        drained from routing, and its slot matrix ends clean."""
        engine, clones, ds, offline, payloads = replica_world
        engine.cache.captions.clear()
        rng = np.random.RandomState(5)
        idx = list(rng.permutation(12))
        rs = ReplicaSet(clones, double_buffer=True)
        results, errors = {}, []
        lock = threading.Lock()

        def client(i):
            time.sleep(float(rng.rand()) * 0.03)
            try:
                out = rs.submit(dict(payloads[i]), deadline_ms=120_000.0)
                with lock:
                    results[i] = out
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append((i, repr(e)))

        with rs:
            threads = [
                threading.Thread(target=client, args=(i,)) for i in idx
            ]
            for t in threads:
                t.start()
            time.sleep(0.02)            # traffic in flight
            rs.kill_replica(0)
            for t in threads:
                t.join(timeout=120.0)
        assert not errors, errors
        assert len(results) == 12       # zero drops despite the kill
        for i in range(12):
            assert results[i]["caption"] == offline[ds.video_id(i)], (
                f"video {i}: requeued decode diverged"
            )
        assert rs.healthy_replicas == 1
        assert rs.metrics.replica(0).healthy.value == 0
        assert not rs.replicas[0].decoder.occupied
        assert sorted(rs.replicas[0].decoder.free) == list(
            range(rs.replicas[0].decoder.S)
        )

    def test_hedged_requests_stay_token_exact(self, replica_world):
        """ISSUE 11 tentpole bar: with an aggressive hedge threshold
        (~every request hedges onto the second replica), first result
        wins — and every caption is STILL exactly the offline beam
        decode, served exactly once.  Both replicas hold byte-identical
        weights and the per-step math is row-independent, so the two
        copies compute identical rows; hedging can change which replica
        answers, never the tokens."""
        engine, clones, ds, offline, payloads = replica_world
        engine.cache.captions.clear()
        rng = np.random.RandomState(17)
        idx = list(rng.permutation(10))
        rs = ReplicaSet(clones, double_buffer=True, hedge_ms=1.0)
        results, errors = _fuzz_submit(rs, payloads, idx, rng)
        assert not errors, errors
        assert len(results) == 10
        for i in range(10):
            assert results[i]["caption"] == offline[ds.video_id(i)], (
                f"video {i}: hedged decode diverged from offline beam"
            )
        assert rs.metrics.hedges_total.value >= 1
        # Exactly one result per request despite the duplicate copies.
        assert rs.metrics.requests_served.value == 10
        assert rs.metrics.requests_failed.value == 0
        for rep in rs.replicas:
            assert not rep.decoder.occupied

    def test_cross_replica_cache_hit_admits_with_zero_encode(
        self, replica_world
    ):
        """ISSUE-7: tier-2 encoder rows are shared across replicas
        under one ``params_tag`` — after replica 0 encodes a
        ``feature_id`` request, replica 1 admits the same id with ZERO
        encoder recompute, and the hit-admitted slot decode still
        produces the exact offline caption."""
        from cst_captioning_tpu.data.vocab import decode_sequence

        engine, clones, ds, offline, payloads = replica_world
        c0, c1 = clones
        body = dict(payloads[3])
        body["feature_id"] = "xrep3"
        req = c0.prepare(body)
        e0 = c0.admit_rows_encoded
        c0.encode_prepared_rows([req])      # miss: pays the encode once
        assert c0.admit_rows_encoded == e0 + 1
        req1 = c1.prepare({"feature_id": "xrep3"})
        assert req1.enc_row is not None     # shared tier-2 hit
        hits0, enc0 = c1.admit_rows_cached, c1.admit_rows_encoded
        c1.encode_prepared_rows([req1])
        assert c1.admit_rows_encoded == enc0    # zero recompute
        assert c1.admit_rows_cached == hits0 + 1
        dec = c1.slot_decoder()
        done = dec.tick([req1], ["x"])
        while not done:
            done = dec.tick()
        _, tokens, _, _ = dec.harvest_many(done)[0]
        assert (
            decode_sequence(c1.vocab, tokens[None])[0]
            == offline[ds.video_id(3)]
        )


@pytest.fixture(scope="module")
def greedy_replica_world(replica_world):
    """Greedy-mode engine over the SAME params + two clones + offline
    greedy predictions."""
    import jax

    from cst_captioning_tpu.evaluation import decode_dataset
    from cst_captioning_tpu.serving.engine import InferenceEngine
    from cst_captioning_tpu.training.steps import make_greedy_sample_fn

    engine, _, ds, _, payloads = replica_world
    cfg = get_preset("synthetic_smoke")
    cfg.serving.warmup = False
    cfg.serving.decode_mode = "greedy"
    cfg.serving.num_slots = 2
    cfg.serving.default_deadline_ms = 120_000.0
    cfg.model.vocab_size = len(engine.vocab)
    geng = InferenceEngine(cfg, params=engine.params, vocab=engine.vocab)
    gfn = make_greedy_sample_fn(geng.model, cfg.eval.max_decode_len)
    offline = decode_dataset(
        ds, cfg, lambda f, m, c: gfn(geng.params, f, m, c),
        geng.model.use_category,
    )
    devices = jax.devices()
    clones = [
        geng.clone_for_device(devices[2 + i], replica_id=i)
        for i in range(2)
    ]
    return geng, clones, ds, offline, payloads


class TestCrossReplicaGreedyParity:
    def test_greedy_parity_random_arrival(self, greedy_replica_world):
        geng, clones, ds, offline, payloads = greedy_replica_world
        geng.cache.captions.clear()
        rng = np.random.RandomState(13)
        idx = list(rng.permutation(10))
        rs = ReplicaSet(clones, double_buffer=True)
        results, errors = _fuzz_submit(
            rs, payloads, idx, rng, jitter_s=0.03
        )
        assert not errors, errors
        for i in range(10):
            assert results[i]["caption"] == offline[ds.video_id(i)], (
                f"video {i}: greedy cross-replica decode diverged"
            )
        for rep in rs.replicas:
            assert not rep.decoder.occupied


@pytest.mark.slow
class TestCrossReplicaParitySweep:
    """Heavyweight sweep variant of the fuzz bar — 4 replicas over the
    forced 8-device platform, 32 requests, repeated arrival orders.
    Excluded from the tier-1 budgeted run (conftest TIER1_BUDGET_S):
    the 2-replica fuzz above already pins the contract; this widens
    coverage on demand (`pytest -m slow`)."""

    def test_four_replica_beam_fuzz(self, replica_world):
        import jax

        engine, _, ds, offline, payloads = replica_world
        devices = jax.devices()
        clones = [
            engine.clone_for_device(devices[4 + i], replica_id=i)
            for i in range(min(4, len(devices) - 4))
        ]
        for trial in range(2):
            engine.cache.captions.clear()
            rng = np.random.RandomState(100 + trial)
            idx = list(rng.permutation(16)) * 2   # repeats too
            rs = ReplicaSet(clones, double_buffer=True)
            results, errors = _fuzz_submit(rs, payloads, idx, rng)
            assert not errors, errors
            for i in set(idx):
                assert results[i]["caption"] == offline[ds.video_id(i)]
            admitted = [
                rs.metrics.replica(r.rid).admitted_total.value
                for r in rs.replicas
            ]
            assert all(a > 0 for a in admitted), admitted
            for rep in rs.replicas:
                assert not rep.decoder.occupied


# ------------------------------------------------ HTTP surface (replicas)

class TestReplicaServer:
    def test_healthz_metrics_and_zero_healthy_503(self, replica_world):
        """Per-replica /metrics labels are live; /healthz reports
        replica counts and degrades to 503 ONLY at zero healthy
        replicas (one dead replica = degraded capacity, still 200)."""
        import json
        import urllib.error
        import urllib.request

        from cst_captioning_tpu.serving.server import CaptionServer

        engine, clones, ds, offline, payloads = replica_world
        engine.cache.captions.clear()
        metrics = ServingMetrics()
        rs = ReplicaSet(clones, metrics)
        srv = CaptionServer(
            engine, host="127.0.0.1", port=0, metrics=metrics,
            batcher=rs,
        ).start()
        try:
            def get(path):
                with urllib.request.urlopen(
                    srv.url + path, timeout=30.0
                ) as r:
                    return r.status, r.read().decode()

            # One served request through the replica set over HTTP.
            body = json.dumps(
                dict(payloads[3], deadline_ms=120_000.0)
            ).encode()
            req = urllib.request.Request(
                srv.url + "/v1/caption", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=120.0) as r:
                out = json.loads(r.read())
            assert out["caption"] == offline[ds.video_id(3)]

            status, text = get("/healthz")
            info = json.loads(text)
            assert status == 200
            assert info["replicas"] == {"healthy": 2, "total": 2}
            status, text = get("/metrics")
            assert 'caption_replica_healthy{replica="0"} 1' in text
            assert 'caption_replica_healthy{replica="1"} 1' in text
            assert 'caption_replica_captions_total{replica=' in text
            assert 'caption_replica_queue_depth{replica="0"}' in text
            assert 'caption_replica_slots_occupied{replica="0"}' in text

            # One replica down: still 200 (degraded), label flips.
            rs.kill_replica(0)
            for _ in range(200):
                if metrics.replica(0).healthy.value == 0:
                    break
                time.sleep(0.01)
            status, text = get("/healthz")
            assert status == 200
            assert json.loads(text)["replicas"]["healthy"] == 1
            _, text = get("/metrics")
            assert 'caption_replica_healthy{replica="0"} 0' in text

            # Zero healthy: /healthz 503, submits 503.
            rs.kill_replica(1)
            for _ in range(200):
                if rs.healthy_replicas == 0:
                    break
                time.sleep(0.01)
            with pytest.raises(urllib.error.HTTPError) as ei:
                get("/healthz")
            assert ei.value.code == 503
            assert json.loads(ei.value.read())["status"] == "unhealthy"
            # An UNCACHED request (payloads[3] is a tier-1 hit by now —
            # cache hits rightly keep serving without replicas).
            fresh = json.dumps(
                dict(payloads[4], deadline_ms=120_000.0)
            ).encode()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    urllib.request.Request(
                        srv.url + "/v1/caption", data=fresh,
                        headers={"Content-Type": "application/json"},
                    ),
                    timeout=30.0,
                )
            assert ei.value.code == 503
        finally:
            srv.shutdown()


# --------------------------------- PR-8 thread-safety fixes (CST-THR-002)

class TestReplicaStopRace:
    def test_concurrent_stop_is_safe_and_idempotent(self):
        """ReplicaSet.stop snapshots worker handles under _cond and
        clears _threads under _cond after the joins, so racing stop()
        callers (SIGTERM thread + context exit) can't tear the list or
        double-fail queued futures."""
        rs = ReplicaSet([_StubEngine(S=1), _StubEngine(S=1)])
        rs.start()
        results, errors, lock = [], [], threading.Lock()
        _submit_bg(rs, {"steps": 1}, results, errors, lock).join(10.0)
        stop_errors = []

        def stopper():
            try:
                rs.stop()
            except Exception as e:  # noqa: BLE001
                stop_errors.append(e)

        threads = [threading.Thread(target=stopper) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15.0)
        assert not stop_errors
        assert not rs._running()
        assert rs._threads == []
        assert results and not errors
