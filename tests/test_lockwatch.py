"""Dynamic lock-order harness (analysis/lockwatch.py): instrumented
locks under stub traffic through both serving schedulers must produce
an ACYCLIC acquisition graph — the runtime twin of the static
CST-THR-001 rule (ISSUE 8).

The stubs are the same engine/decoder doubles the scheduler behavior
tests use (test_serving / test_replicas), so the traffic exercises the
real lock-bearing paths: admission under ``_cond``, tick + harvest,
metrics updates from inside and outside the lock, replica
kill/requeue, drain/stop."""

import threading
import time

from cst_captioning_tpu.analysis.lockwatch import InstrumentedLock, LockWatch
from cst_captioning_tpu.serving.batcher import ContinuousBatcher
from cst_captioning_tpu.serving.replicas import ReplicaSet

from test_replicas import _StubEngine as _ReplicaStubEngine
from test_serving import _StubSlotEngine


class TestLockWatchUnit:
    def test_seeded_inversion_is_detected(self):
        """Two locks taken in both orders on two threads IS a cycle,
        even though this run didn't deadlock."""
        watch = LockWatch()
        a = InstrumentedLock(watch)
        b = InstrumentedLock(watch)

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        t1, t2 = threading.Thread(target=ab), threading.Thread(target=ba)
        t1.start(); t1.join()
        t2.start(); t2.join()
        cycles = watch.cycles()
        assert cycles, "inversion not detected"
        assert {a.label, b.label} <= set(cycles[0])
        try:
            watch.assert_acyclic()
        except AssertionError as e:
            assert "lock-order inversion" in str(e)
        else:
            raise AssertionError("assert_acyclic did not raise")

    def test_consistent_order_is_acyclic(self):
        watch = LockWatch()
        a = InstrumentedLock(watch)
        b = InstrumentedLock(watch)
        for _ in range(3):
            with a:
                with b:
                    pass
        assert set(watch.edges) == {(a.label, b.label)}
        watch.assert_acyclic()

    def test_condition_wait_keeps_stack_truthful(self):
        """Condition.wait releases/reacquires through the instrumented
        lock, so a lock acquired AFTER a wait records no edge from the
        waited-on lock's pre-wait hold."""
        watch = LockWatch()
        with watch.patched():
            cond = threading.Condition()
        other = InstrumentedLock(watch)
        done = threading.Event()

        def waiter():
            with cond:
                cond.wait(timeout=5.0)
            with other:
                done.set()

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cond:
            cond.notify_all()
        t.join(timeout=5.0)
        assert done.is_set()
        watch.assert_acyclic()
        # the post-wait acquisition must NOT appear nested under cond
        cond_labels = {
            a for (a, b) in watch.edges if b == other.label
        }
        assert not cond_labels, cond_labels


class TestContinuousBatcherLockOrder:
    def test_stub_traffic_acyclic(self):
        """Admission, tick, harvest, cache store, deadline bookkeeping
        and drain through ContinuousBatcher under instrumented locks:
        the observed acquisition graph has no cycle."""
        watch = LockWatch()
        with watch.patched():
            eng = _StubSlotEngine(S=2)
            b = ContinuousBatcher(eng)
        with b:
            threads = [
                threading.Thread(
                    target=lambda i=i: b.submit(
                        {"steps": 1 + (i % 3), "key": f"k{i}"}
                    )
                )
                for i in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10.0)
        assert b.metrics.requests_served.value == 8
        # the traffic actually exercised instrumented locks
        assert sum(watch.acquisitions.values()) > 0
        assert watch.edges, "no nested acquisitions recorded"
        watch.assert_acyclic()


class TestReplicaSetLockOrder:
    def test_stub_traffic_with_kill_requeue_acyclic(self):
        """The full replica lifecycle — admission + routing under the
        shared cond, double-buffered tick/harvest, kill_replica with
        in-flight requeue onto the survivor, drain — stays acyclic."""
        watch = LockWatch()
        with watch.patched():
            rs = ReplicaSet(
                [_ReplicaStubEngine(S=2), _ReplicaStubEngine(S=2)]
            )
        results, errors = [], []
        lock = threading.Lock()

        def go(steps):
            try:
                out = rs.submit({"steps": steps})
                with lock:
                    results.append(out)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(e)

        rs.start()
        try:
            threads = [
                threading.Thread(target=go, args=(2 + (i % 4),))
                for i in range(6)
            ]
            for t in threads:
                t.start()
            time.sleep(0.02)          # some work lands on replica 0
            rs.kill_replica(0)        # drain + requeue path
            more = [
                threading.Thread(target=go, args=(1,)) for _ in range(3)
            ]
            for t in more:
                t.start()
            for t in threads + more:
                t.join(timeout=15.0)
        finally:
            rs.stop()
        assert not errors, errors
        assert len(results) == 9
        assert sum(watch.acquisitions.values()) > 0
        assert watch.edges, "no nested acquisitions recorded"
        watch.assert_acyclic()
