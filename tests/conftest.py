"""Test configuration: force an 8-device virtual CPU platform BEFORE jax
initializes, so distributed/mesh tests run without TPU hardware (SURVEY.md §4
"Distributed" strategy)."""

import os

# Hard-override: the session sitecustomize registers the axon TPU backend and
# calls jax.config.update("jax_platforms", "axon,cpu"), which wins over the
# env var — so update the config again after importing jax.  Unit tests must
# run on the virtual multi-device CPU platform.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import time  # noqa: E402

import pytest  # noqa: E402

_SESSION_T0 = time.monotonic()


def pytest_configure(config):
    # Tier-1 runs `-m 'not slow'` (ROADMAP.md): anything marked slow is
    # excluded from the runtime-budgeted suite.
    config.addinivalue_line(
        "markers",
        "slow: heavy test excluded from the tier-1 budgeted run "
        "(pytest -m 'not slow')",
    )


def pytest_sessionfinish(session, exitstatus):
    """Tier-1 runtime guard: with TIER1_BUDGET_S set (seconds), a run
    that exceeds the budget FAILS even if every test passed — so a new
    expensive test can't silently eat the suite's timeout headroom; mark
    it ``slow`` instead."""
    budget = float(os.environ.get("TIER1_BUDGET_S", "0") or 0)
    elapsed = time.monotonic() - _SESSION_T0
    if budget and elapsed > budget and session.exitstatus == 0:
        print(
            f"\nTIER1 BUDGET EXCEEDED: suite took {elapsed:.0f}s > "
            f"TIER1_BUDGET_S={budget:.0f}s — mark new heavy tests "
            "@pytest.mark.slow (see tests/conftest.py)"
        )
        session.exitstatus = 1


@pytest.fixture(scope="session")
def rng_seed():
    return 213
