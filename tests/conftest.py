"""Test configuration: force an 8-device virtual CPU platform BEFORE jax
initializes, so distributed/mesh tests run without TPU hardware (SURVEY.md §4
"Distributed" strategy)."""

import os

# Hard-override: the session sitecustomize registers the axon TPU backend and
# calls jax.config.update("jax_platforms", "axon,cpu"), which wins over the
# env var — so update the config again after importing jax.  Unit tests must
# run on the virtual multi-device CPU platform.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng_seed():
    return 213
