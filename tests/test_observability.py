"""ISSUE 10: end-to-end span tracing, the crash flight recorder, and
the Prometheus text-format audit.

* tracer units: ids, nesting, bounded per-thread buffers, strictness,
  disabled no-op, schema-valid Chrome-trace export;
* tracer concurrency: spans from N worker threads interleave without
  loss or cross-talk;
* served-request e2e (beam, continuous batcher, 2 replicas + greedy,
  single-replica): one trace_id links root -> queue -> admit -> decode
  -> detok with consistent parent ids, the X-Trace-Id header echoes it,
  /stats stamps it as the latency exemplar, /healthz//stats carry the
  build fingerprint;
* flight recorder: ring bounds, drain start/requeue/exit events
  (shutdown satellite), watchdog dump, and fuzzed kill-mid-traffic
  always yielding a schema-valid dump with the dead replica's ticks;
* /metrics exposition pinned by a PARSER (HELP/TYPE per family,
  registry-consistent types, correct content type) instead of
  substring checks.
"""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from cst_captioning_tpu.config import get_preset
from cst_captioning_tpu.data.vocab import Vocabulary
from cst_captioning_tpu.observability.flight import (
    FlightRecorder,
    validate_flight_dump,
)
from cst_captioning_tpu.observability.trace import (
    EVENT_CATALOGUE,
    SPAN_CATALOGUE,
    Tracer,
    get_tracer,
    registered,
    validate_chrome_trace,
)
from cst_captioning_tpu.serving.metrics import (
    METRIC_FAMILIES,
    METRIC_HELP,
    ServingMetrics,
)

# ------------------------------------------------------------ tracer units


class TestTracer:
    def test_record_and_export_schema(self):
        t = Tracer()
        sid = t.record("request", 1.0, 1.5, tags={"status": 200})
        assert sid
        obj = t.export_chrome_trace()
        validate_chrome_trace(obj)
        ev = [e for e in obj["traceEvents"] if e["ph"] == "X"]
        assert len(ev) == 1
        assert ev[0]["name"] == "request"
        assert ev[0]["dur"] == pytest.approx(0.5e6)
        assert ev[0]["args"]["status"] == 200

    def test_span_nesting_links_parent_and_trace(self):
        t = Tracer()
        with t.span("request") as root:
            with t.span("queue") as child:
                pass
        spans = {s["name"]: s for s in t.spans()}
        assert spans["queue"]["parent_id"] == root.span_id
        assert spans["queue"]["trace_id"] == root.trace_id
        assert spans["request"]["parent_id"] is None
        assert child.parent_id == root.span_id

    def test_unregistered_name_raises(self):
        t = Tracer()
        with pytest.raises(ValueError, match="not registered"):
            t.record("made_up_span", 0.0, 1.0)
        with pytest.raises(ValueError, match="not registered"):
            t.span("also_made_up")

    def test_wildcard_families_match(self):
        t = Tracer()
        assert t.record("phase/dispatch", 0.0, 1.0)
        assert registered("phase/score_wait")
        assert not registered("phases/nope")

    def test_buffers_are_bounded_per_thread(self):
        t = Tracer(buffer_spans=8)
        for _ in range(50):
            t.record("tick_dispatch", 0.0, 0.1)
        assert len(list(t.spans())) == 8

    def test_disabled_tracer_is_noop(self):
        t = Tracer(enabled=False)
        assert t.record("request", 0.0, 1.0) is None
        with t.span("request") as s:
            assert s.span_id is None
        assert list(t.spans()) == []

    def test_clear(self):
        t = Tracer()
        t.record("harvest", 0.0, 1.0)
        t.clear()
        assert list(t.spans()) == []

    def test_ids_are_unique(self):
        t = Tracer()
        ids = {t.new_trace_id() for _ in range(1000)}
        ids |= {t.new_span_id() for _ in range(1000)}
        assert len(ids) == 2000

    def test_concurrent_emission_no_loss_no_crosstalk(self):
        """Spans emitted from N worker threads + a 'batcher' thread
        interleave without loss; each thread's spans stay on its own
        exported tid (no cross-talk)."""
        t = Tracer(buffer_spans=512)
        N, per = 8, 50

        def worker(i):
            for k in range(per):
                t.record(
                    "tick_dispatch", k, k + 0.5,
                    tags={"replica": i, "k": k},
                )

        threads = [
            threading.Thread(target=worker, args=(i,), name=f"w{i}")
            for i in range(N)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=30.0)
        obj = validate_chrome_trace(t.export_chrome_trace())
        ev = [e for e in obj["traceEvents"] if e["ph"] == "X"]
        assert len(ev) == N * per
        by_replica = {}
        for e in ev:
            by_replica.setdefault(e["args"]["replica"], set()).add(
                e["tid"]
            )
        for i in range(N):
            # every span of worker i landed, on exactly one tid
            assert len(by_replica[i]) == 1
        ks = {
            (e["args"]["replica"], e["args"]["k"]) for e in ev
        }
        assert len(ks) == N * per

    def test_catalogue_entries_are_well_formed_and_unique(self):
        names = [p for p, _, _ in SPAN_CATALOGUE + EVENT_CATALOGUE]
        assert len(names) == len(set(names)), "duplicate family"
        for pattern, component, help_text in SPAN_CATALOGUE + EVENT_CATALOGUE:
            assert pattern and component and help_text


class TestPhaseClockSpans:
    def test_laps_become_spans_under_one_step_root(self):
        from cst_captioning_tpu.training.steps import PhaseClock

        tracer = Tracer()
        clock = PhaseClock(tags={"layout": "split"}, tracer=tracer)
        clock.start()
        time.sleep(0.001)
        clock.lap("dispatch_ms")
        clock.lap("score_ms")
        out = {}
        clock.commit(out)
        assert out["total_ms"] > 0
        spans = {s["name"]: s for s in tracer.spans()}
        assert {"phase/dispatch", "phase/score", "cst/step"} <= set(spans)
        root = spans["cst/step"]
        for name in ("phase/dispatch", "phase/score"):
            assert spans[name]["parent_id"] == root["span_id"]
            assert spans[name]["trace_id"] == root["trace_id"]
            assert spans[name]["tags"]["layout"] == "split"
        validate_chrome_trace(tracer.export_chrome_trace())

    def test_each_step_is_its_own_trace(self):
        from cst_captioning_tpu.training.steps import PhaseClock

        tracer = Tracer()
        clock = PhaseClock(tracer=tracer)
        ids = set()
        for _ in range(3):
            clock.start()
            clock.lap("update_ms")
            clock.commit({})
            ids = {s["trace_id"] for s in tracer.spans()}
        assert len(ids) == 3


# ------------------------------------------------------- flight recorder


class TestFlightRecorder:
    def test_ring_is_bounded_and_snapshot_validates(self):
        fr = FlightRecorder("replica0", max_events=4)
        for i in range(10):
            fr.event("tick", seq=i)
        snap = fr.snapshot()
        validate_flight_dump(snap)
        assert len(snap["events"]) == 4
        assert snap["events"][-1]["tags"]["seq"] == 9

    def test_unregistered_event_raises(self):
        fr = FlightRecorder("x")
        with pytest.raises(ValueError, match="not registered"):
            fr.event("nope")

    def test_dump_writes_schema_valid_json(self, tmp_path):
        tracer = Tracer()
        tracer.record("tick_dispatch", 0.0, 0.1, tags={"replica": 3})
        tracer.record("tick_dispatch", 0.0, 0.1, tags={"replica": 4})
        fr = FlightRecorder(
            "replica3", out_dir=str(tmp_path), tracer=tracer,
            tags={"replica": 3},
        )
        fr.event("tick", seq=1)
        path = fr.dump("worker_death")
        assert path is not None
        body = validate_flight_dump(json.loads(open(path).read()))
        assert body["reason"] == "worker_death"
        assert "wall_time_utc" in body and "pid" in body
        # only replica 3's spans ride along
        assert body["spans"] and all(
            s["tags"]["replica"] == 3 for s in body["spans"]
        )

    def test_dump_without_dir_is_noop(self):
        fr = FlightRecorder("r")
        fr.event("tick")
        assert fr.dump("watchdog") is None


# ---------------------------------------------- scheduler drain satellite

# Stub engine/decoder pair mirroring tests/test_serving.py: the drain
# semantics are scheduler-level, no jax needed.
from test_serving import _StubSlotEngine  # noqa: E402


class TestDrainFlightEvents:
    def test_graceful_stop_records_drain_start_and_exit(self, tmp_path):
        from cst_captioning_tpu.serving.batcher import ContinuousBatcher

        eng = _StubSlotEngine(S=2)
        eng.cfg.serving.flight_dir = str(tmp_path)
        b = ContinuousBatcher(eng, ServingMetrics()).start()
        b.submit({"steps": 2, "key": "k1"})
        b.stop()
        snap = b.flight_snapshot()["scheduler"]
        validate_flight_dump(snap)
        names = [e["event"] for e in snap["events"]]
        assert "tick" in names
        assert "drain_start" in names
        assert "drain_exit" in names
        assert names.index("drain_start") < names.index("drain_exit")
        exit_ev = next(
            e for e in snap["events"] if e["event"] == "drain_exit"
        )
        assert exit_ev["tags"]["served_all"] is True
        # a completed drain leaves its post-mortem on disk too
        dumps = list(tmp_path.glob("flight-scheduler-*-drain.json"))
        assert len(dumps) == 1
        validate_flight_dump(json.loads(dumps[0].read_text()))

    def test_watchdog_deadline_dumps_flight(self, tmp_path):
        from cst_captioning_tpu.serving.batcher import ContinuousBatcher

        eng = _StubSlotEngine(S=1)
        eng.cfg.serving.flight_dir = str(tmp_path)
        b = ContinuousBatcher(
            eng, ServingMetrics(), drain_timeout_s=0.3
        ).start()
        done = threading.Thread(
            target=lambda: pytest.raises(
                Exception, b.submit, {"steps": 10**9, "key": "never"}
            )
        )
        done.start()
        for _ in range(200):  # wait until the request occupies a slot
            if eng.slot_decoder().n_occupied:
                break
            time.sleep(0.005)
        b.stop()  # drain cannot finish -> watchdog
        done.join(timeout=30.0)
        snap = b.flight_snapshot()["scheduler"]
        names = [e["event"] for e in snap["events"]]
        assert "watchdog" in names
        assert "dump" in names  # the dump itself is on the record
        dumps = list(tmp_path.glob("flight-scheduler-*-watchdog.json"))
        assert len(dumps) == 1
        validate_flight_dump(json.loads(dumps[0].read_text()))


# ----------------------------------------------------- served-request e2e


def _tiny_cfg(mode="beam"):
    cfg = get_preset("synthetic_smoke")
    cfg.serving.decode_mode = mode
    cfg.serving.max_batch_size = 2
    cfg.serving.batch_shapes = [1, 2]
    cfg.serving.num_slots = 3
    cfg.eval.beam_size = 2
    cfg.eval.max_decode_len = 8
    cfg.data.max_frames = 4
    cfg.serving.warmup = True
    return cfg


def _payload(seed):
    rng = np.random.RandomState(seed)
    return {
        "features": {
            "resnet": rng.randn(4, 64).astype(np.float32).tolist()
        }
    }


def _post(url, obj, timeout=120.0):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), json.loads(r.read())


def _get(url, timeout=30.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, dict(r.headers), r.read().decode()


@pytest.fixture(scope="module")
def replica_server():
    """Beam decode, continuous batching, TWO replicas behind one door
    (the acceptance shape)."""
    from cst_captioning_tpu.serving.engine import InferenceEngine
    from cst_captioning_tpu.serving.server import CaptionServer

    cfg = _tiny_cfg("beam")
    cfg.serving.replicas = 2
    vocab = Vocabulary([f"w{i}" for i in range(40)])
    engine = InferenceEngine(cfg, random_init=True, vocab=vocab)
    with CaptionServer(engine, host="127.0.0.1", port=0) as srv:
        yield srv


@pytest.fixture(scope="module")
def greedy_server(tmp_path_factory):
    """Greedy decode, single-replica continuous batcher, profiling
    endpoint armed."""
    from cst_captioning_tpu.serving.engine import InferenceEngine
    from cst_captioning_tpu.serving.server import CaptionServer

    cfg = _tiny_cfg("greedy")
    cfg.serving.profile_dir = str(
        tmp_path_factory.mktemp("profiles")
    )
    vocab = Vocabulary([f"w{i}" for i in range(40)])
    engine = InferenceEngine(cfg, random_init=True, vocab=vocab)
    with CaptionServer(engine, host="127.0.0.1", port=0) as srv:
        yield srv


def _trace_spans(srv):
    _, _, body = _get(srv.url + "/debug/trace")
    obj = validate_chrome_trace(json.loads(body))
    return [e for e in obj["traceEvents"] if e["ph"] == "X"]


def _spans_for(events, trace_id):
    return {
        e["name"]: e for e in events
        if e["args"]["trace_id"] == trace_id
    }


class TestServedRequestTimeline:
    def test_beam_replicated_request_has_linked_span_chain(
        self, replica_server
    ):
        srv = replica_server
        tids = []
        for seed in (1, 2, 3):
            status, headers, out = _post(
                srv.url + "/v1/caption", _payload(seed)
            )
            assert status == 200
            assert "X-Trace-Id" in headers
            tids.append(headers["X-Trace-Id"])
        events = _trace_spans(srv)
        for tid in tids:
            spans = _spans_for(events, tid)
            # the acceptance chain: root -> queue -> admit -> decode ->
            # detok, all one trace, all parented on the root span.
            assert {
                "request", "queue", "admit", "decode", "detok"
            } <= set(spans), sorted(spans)
            root = spans["request"]
            assert "parent_id" not in root["args"]
            assert root["args"]["status"] == 200
            for child in ("queue", "admit", "decode", "detok"):
                assert spans[child]["args"]["parent_id"] == \
                    root["args"]["span_id"]
            # timeline sanity on the shared monotonic base
            assert spans["queue"]["ts"] <= spans["admit"]["ts"]
            assert spans["decode"]["ts"] <= spans["detok"]["ts"]
            # the decode span names the replica that served it
            assert spans["decode"]["args"]["replica"] in (0, 1)

    def test_engine_timeline_has_tick_and_harvest_spans(
        self, replica_server
    ):
        events = _trace_spans(replica_server)
        names = {e["name"] for e in events}
        assert {"tick_dispatch", "tick_wait", "harvest"} <= names
        reps = {
            e["args"].get("replica")
            for e in events if e["name"] == "tick_dispatch"
        }
        # warmup ticks of the un-cloned front engine carry no replica
        # tag; served traffic must have come from tagged replicas.
        assert {0, 1} <= reps

    def test_stats_exemplar_and_build_fingerprint(self, replica_server):
        srv = replica_server
        _, headers, out = _post(srv.url + "/v1/caption", _payload(7))
        tid = headers["X-Trace-Id"]
        _, _, body = _get(srv.url + "/stats")
        stats = json.loads(body)
        ex = stats["latency_ms"]["total"].get("exemplar")
        assert ex is not None and ex["trace_id"] == tid
        assert ex["value_ms"] >= 0
        build = stats["build"]
        assert build["params_tag"] == srv.engine.params_tag
        assert build["mesh_shape"] == "1x1"
        assert build["preset"] == "synthetic_smoke"
        # low-precision provenance (ISSUE 16): the serving dtype is
        # part of the build identity on every HTTP surface
        assert build["serving_dtype"] == "f32"
        assert re.fullmatch(r"\d+\.\d+\.\d+", build["version"])
        # /healthz carries the same block
        _, _, hz = _get(srv.url + "/healthz")
        assert json.loads(hz)["build"] == build

    def test_debug_flight_live_view(self, replica_server):
        _, _, body = _get(replica_server.url + "/debug/flight")
        out = json.loads(body)
        assert set(out["recorders"]) == {"replica0", "replica1"}
        for snap in out["recorders"].values():
            validate_flight_dump(snap)
        assert "params_tag" in out["build"]
        ticks = [
            e for e in out["recorders"]["replica0"]["events"]
            + out["recorders"]["replica1"]["events"]
            if e["event"] == "tick"
        ]
        assert ticks  # traffic from the tests above left tick events

    def test_greedy_request_traced_too(self, greedy_server):
        srv = greedy_server
        status, headers, _ = _post(srv.url + "/v1/caption", _payload(11))
        assert status == 200
        spans = _spans_for(_trace_spans(srv), headers["X-Trace-Id"])
        assert {"request", "queue", "admit", "decode", "detok"} <= set(
            spans
        )

    def test_error_response_still_closes_root_span(self, greedy_server):
        srv = greedy_server
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.url + "/v1/caption", {"feature_id": "ghost"})
        assert ei.value.code == 404
        tid = ei.value.headers["X-Trace-Id"]
        spans = _spans_for(_trace_spans(srv), tid)
        assert spans["request"]["args"]["status"] == 404


class TestProfileEndpoint:
    def test_profile_disabled_is_404(self, replica_server):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(replica_server.url + "/debug/profile?ms=10")
        assert ei.value.code == 404

    def test_profile_window_runs_and_serializes(
        self, greedy_server, monkeypatch
    ):
        import jax

        calls = []
        monkeypatch.setattr(
            jax.profiler, "start_trace",
            lambda d, **kw: calls.append(("start", d)),
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: calls.append(("stop",))
        )
        srv = greedy_server
        status, _, body = _get(srv.url + "/debug/profile?ms=200")
        assert status == 202
        out = json.loads(body)
        assert out["profiling_ms"] == 200
        # a second window while one is running -> 409
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url + "/debug/profile?ms=50")
        assert ei.value.code == 409
        for _ in range(100):
            if ("stop",) in calls:
                break
            time.sleep(0.02)
        assert calls[0] == ("start", srv._http.profile_dir)
        assert ("stop",) in calls
        # the window itself landed in the timeline
        for _ in range(50):
            names = {e["name"] for e in _trace_spans(srv)}
            if "profile" in names:
                break
            time.sleep(0.02)
        assert "profile" in names

    def test_bad_window_is_400(self, greedy_server):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(greedy_server.url + "/debug/profile?ms=notanumber")
        assert ei.value.code == 400


# ------------------------------------------------- kill -> flight dump


class TestKillReplicaFlightDump:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_fuzzed_kill_mid_traffic_yields_wellformed_dump(
        self, tmp_path, seed
    ):
        """Acceptance: kill_replica mid-traffic writes a flight dump
        containing that replica's last ticks — fuzzed over kill timing,
        every dump schema-valid, no accepted request lost."""
        from cst_captioning_tpu.serving.engine import InferenceEngine
        from cst_captioning_tpu.serving.replicas import ReplicaSet

        cfg = _tiny_cfg("greedy")
        cfg.serving.replicas = 2
        cfg.serving.flight_dir = str(tmp_path)
        vocab = Vocabulary([f"w{i}" for i in range(40)])
        engine = InferenceEngine(cfg, random_init=True, vocab=vocab)
        rs = ReplicaSet.from_engine(engine, ServingMetrics()).start()
        rng = np.random.RandomState(seed)
        errors, served = [], []
        lock = threading.Lock()

        def client(cid):
            for k in range(4):
                try:
                    rs.submit(
                        _payload(1000 + seed * 100 + cid * 10 + k),
                        deadline_ms=120_000.0,
                    )
                    with lock:
                        served.append(cid)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")

        threads = [
            threading.Thread(target=client, args=(c,)) for c in range(3)
        ]
        for t in threads:
            t.start()
        time.sleep(float(rng.uniform(0.01, 0.25)))
        rs.kill_replica(0)
        for t in threads:
            t.join(timeout=120.0)
        rs.stop()
        assert not errors, errors
        assert len(served) == 12  # zero-drop: survivors absorbed it all
        dumps = list(tmp_path.glob("flight-replica0-*.json"))
        assert dumps, "kill_replica produced no flight dump"
        for p in dumps:
            body = validate_flight_dump(json.loads(p.read_text()))
            names = [e["event"] for e in body["events"]]
            assert "kill" in names
            assert "drain_requeue" in names
            assert body["tags"] == {"replica": 0}
        # the dead replica's last ticks are in at least one dump
        all_events = [
            e
            for p in dumps
            for e in json.loads(p.read_text())["events"]
        ]
        assert any(e["event"] == "tick" for e in all_events)


# ------------------------------------- Prometheus text-format audit


def _parse_prometheus(text):
    """Minimal text-format parser: returns ({name: help}, {name: type},
    [(name, labels, value)]); raises AssertionError on malformed lines
    or samples emitted before their family header."""
    helps, types, samples = {}, {}, []
    announced = set()
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name, _, help_text = line[len("# HELP "):].partition(" ")
            assert help_text.strip(), f"empty HELP for {name}"
            helps[name] = help_text
        elif line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split(" ")
            assert len(parts) == 2, f"malformed TYPE line: {line}"
            name, typ = parts
            assert typ in (
                "counter", "gauge", "histogram", "summary", "untyped"
            )
            assert name in helps, f"TYPE before HELP for {name}"
            types[name] = typ
            announced.add(name)
        elif line.startswith("#"):
            continue
        else:
            m = re.fullmatch(
                r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})?\s+(\S+)",
                line,
            )
            assert m, f"malformed sample line: {line!r}"
            name, labels, value = m.groups()
            float(value)  # must parse
            base = name
            for suffix in ("_bucket", "_count", "_sum"):
                if name.endswith(suffix):
                    base = name[: -len(suffix)]
                    break
            assert (
                name in announced or base in announced
            ), f"sample {name} has no preceding HELP/TYPE"
            samples.append((name, labels, value))
    return helps, types, samples


class TestPrometheusExposition:
    def test_exposition_parses_and_every_family_is_typed(
        self, replica_server
    ):
        from fnmatch import fnmatchcase

        status, headers, text = _get(replica_server.url + "/metrics")
        assert status == 200
        assert headers["Content-Type"] == (
            "text/plain; version=0.0.4; charset=utf-8"
        )
        helps, types, samples = _parse_prometheus(text)
        assert samples
        registry = dict(METRIC_FAMILIES)

        def family_of(name):
            base = name
            for suffix in ("_bucket", "_count", "_sum"):
                if name.endswith(suffix):
                    base = name[: -len(suffix)]
            if base in registry:
                return base, registry[base]
            for pat, typ in METRIC_FAMILIES:
                if fnmatchcase(base, pat):
                    return pat, typ
            raise AssertionError(f"sample {name} matches no family")

        for name, _labels, _v in samples:
            fam, typ = family_of(name)
            base = name
            for suffix in ("_bucket", "_count", "_sum"):
                if name.endswith(suffix):
                    base = name[: -len(suffix)]
            assert types[base] == typ, (
                f"{name}: exposed type {types[base]} != registered "
                f"{typ} (family {fam})"
            )

    def test_every_registered_family_has_help_text(self):
        for pattern, _typ in METRIC_FAMILIES:
            assert pattern in METRIC_HELP, (
                f"family {pattern} has no HELP text — add it to "
                "serving/metrics.py::METRIC_HELP"
            )
            assert METRIC_HELP[pattern].strip()

    def test_histogram_buckets_are_cumulative(self, replica_server):
        _, _, text = _get(replica_server.url + "/metrics")
        buckets = {}
        for line in text.splitlines():
            m = re.fullmatch(
                r"(caption_latency_total_ms)_bucket\{le=\"([^\"]+)\"\}"
                r"\s+(\d+)",
                line,
            )
            if m:
                buckets[m.group(2)] = int(m.group(3))
        assert buckets and "+Inf" in buckets
        vals = list(buckets.values())
        assert vals == sorted(vals)
        counts = re.findall(
            r"caption_latency_total_ms_count (\d+)", text
        )
        assert int(counts[0]) == buckets["+Inf"]


# --------------------------------------------- analysis vacuous-green guard


class TestObsCheckerSeesRealSites:
    def test_emission_sites_discovered_in_serving_and_training(self):
        from pathlib import Path

        from cst_captioning_tpu.analysis.astutil import scan_package
        from cst_captioning_tpu.analysis.observability import (
            emission_sites,
        )

        root = Path(
            __file__
        ).resolve().parent.parent / "cst_captioning_tpu"
        mods = [
            m for m in scan_package(root)
            if not m.rel.startswith("analysis/")
        ]
        sites = emission_sites(mods)
        by_file = {}
        for mi, node in sites:
            by_file.setdefault(mi.rel, 0)
            by_file[mi.rel] += 1
        for rel in (
            "serving/slots.py",
            "serving/batcher.py",
            "serving/replicas.py",
            "serving/server.py",
            "training/steps.py",
        ):
            assert by_file.get(rel, 0) >= 1, (
                f"CST-OBS checker sees no emission sites in {rel} — "
                "the rule went vacuously green"
            )
        assert by_file["serving/slots.py"] >= 3  # dispatch/wait/harvest


# ------------------------------------------- ISSUE 12 satellite fixes


class TestIssue12ExceptionAndKnobFixes:
    """Regression pins for the true positives the new analysis
    families surfaced (each fixed for real, per the PR-8 precedent):
    CST-CFG-002 on serving.trace_buffer_spans, CST-EXC-002 on the
    profiler window thread and the SIGTERM shutdown thread."""

    def test_trace_buffer_spans_knob_reaches_the_tracer(self):
        from cst_captioning_tpu.observability.trace import get_tracer
        from cst_captioning_tpu.serving.batcher import ContinuousBatcher

        tracer = get_tracer()
        orig = tracer.buffer_spans
        try:
            eng = _StubSlotEngine(S=1)
            eng.cfg.serving.trace_buffer_spans = 77
            b = ContinuousBatcher(eng, ServingMetrics())
            assert b.tracer is tracer
            assert tracer.buffer_spans == 77
        finally:
            tracer.set_buffer_spans(orig)

    def test_set_buffer_spans_rebounds_rings(self):
        from cst_captioning_tpu.observability.trace import Tracer

        t = Tracer(buffer_spans=8)
        for i in range(6):
            t.record("profile", 0.0, 1.0)   # registered span name
        t.set_buffer_spans(4)
        assert t.buffer_spans == 4
        # retired ring re-bounds immediately, keeping newest spans
        assert t._retired.maxlen == 4
        # invalid / no-op sizes leave the tracer alone
        t.set_buffer_spans(0)
        t.set_buffer_spans(-3)
        assert t.buffer_spans == 4

    def test_profile_window_failure_releases_flag_and_logs(
        self, greedy_server, monkeypatch, caplog
    ):
        """CST-EXC-002 fix: a start_trace failure must not kill the
        window thread silently with the 409 flag stuck True."""
        import logging

        import jax

        def boom(*a, **kw):
            raise RuntimeError("no profiler on this backend")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        monkeypatch.setattr(
            jax.profiler, "stop_trace",
            lambda: (_ for _ in ()).throw(RuntimeError("not tracing")),
        )
        srv = greedy_server
        with caplog.at_level(
            logging.ERROR, logger="cst_captioning_tpu.serving"
        ):
            status, _, body = _get(srv.url + "/debug/profile?ms=50")
            assert status == 202
            for _ in range(200):
                if not srv._http._profiling:
                    break
                time.sleep(0.01)
        assert not srv._http._profiling, (
            "window flag stuck True after a start_trace failure — "
            "every later /debug/profile would 409 forever"
        )
        assert any(
            "profiler window failed" in r.message for r in caplog.records
        )

    def test_sigterm_shutdown_wrapper_logs_not_raises(self, caplog):
        """CST-EXC-002 fix: the SIGTERM thread targets
        _signal_shutdown, which contains and logs shutdown failures."""
        import logging

        from cst_captioning_tpu.serving.server import CaptionServer

        srv = CaptionServer.__new__(CaptionServer)

        def broken_shutdown(drain=True):
            raise RuntimeError("teardown exploded")

        srv.shutdown = broken_shutdown
        with caplog.at_level(
            logging.ERROR, logger="cst_captioning_tpu.serving"
        ):
            srv._signal_shutdown()     # must not raise
        assert any(
            "SIGTERM shutdown failed" in r.message
            for r in caplog.records
        )
