"""Ring attention / sequence-parallel context attention: exactness vs the
dense computation on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.parallel import make_mesh
from cst_captioning_tpu.parallel.ring import (
    ring_attention,
    sharded_context_attention,
)


def dense_attention(q, k, v, kv_mask):
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqh,bkh->bqk", q, k) * scale
    s = jnp.where(kv_mask[:, None, :] > 0, s, -1e30)
    a = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkh->bqh", a, v)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"data": 1, "model": 8})


class TestRingAttention:
    @pytest.mark.parametrize("S", [64, 128])
    def test_matches_dense(self, mesh, S):
        rng = np.random.RandomState(0)
        B, H = 2, 16
        q = jnp.asarray(rng.randn(B, S, H), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H), jnp.float32)
        ref = dense_attention(q, k, v, jnp.ones((B, S)))
        got = ring_attention(q, k, v, mesh)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-6
        )

    def test_padding_mask(self, mesh):
        rng = np.random.RandomState(1)
        B, S, H = 2, 64, 8
        q = jnp.asarray(rng.randn(B, S, H), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H), jnp.float32)
        mask = jnp.asarray(rng.rand(B, S) > 0.3, jnp.float32)
        ref = dense_attention(q, k, v, mask)
        got = ring_attention(q, k, v, mesh, kv_mask=mask)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-6
        )
        # masked keys truly cannot influence the output
        v_pert = jnp.where(mask[..., None] > 0, v, 1e4)
        got2 = ring_attention(q, k, v_pert, mesh, kv_mask=mask)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(got2), rtol=2e-5, atol=2e-6
        )

    def test_jits_and_shards(self, mesh):
        rng = np.random.RandomState(2)
        B, S, H = 2, 64, 8
        q = jnp.asarray(rng.randn(B, S, H), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H), jnp.float32)
        f = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))
        out = f(q, k, v)
        ref = dense_attention(q, k, v, jnp.ones((B, S)))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
        )


class TestShardedContextAttention:
    def test_matches_dense_bahdanau(self, mesh):
        """Mirror of CaptionModel._context's dense math, frame-sharded."""
        rng = np.random.RandomState(3)
        B, F, E, A = 4, 32, 8, 12
        query = jnp.asarray(rng.randn(B, A), jnp.float32)
        vals = jnp.asarray(rng.randn(B, F, E), jnp.float32)
        proj = jnp.asarray(rng.randn(B, F, A), jnp.float32)
        att_v = jnp.asarray(rng.randn(A, 1), jnp.float32)
        mask = jnp.ones((B, F)).at[:, -5:].set(0.0)

        # dense reference (same ops as captioner._context)
        s = (jnp.tanh(proj + query[:, None, :]) @ att_v)[..., 0]
        s = jnp.where(mask > 0, s, -1e30)
        a = jax.nn.softmax(s, axis=-1)
        ref = jnp.einsum("bf,bfe->be", a, vals)

        got = sharded_context_attention(
            query, vals, proj, mask, att_v, mesh
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-6
        )

    def test_composes_with_batch_axis(self):
        """DP batch axis + frame axis together (the trainer's layout)."""
        mesh2 = make_mesh({"data": 2, "model": 4})
        rng = np.random.RandomState(4)
        B, F, E, A = 4, 16, 8, 12
        query = jnp.asarray(rng.randn(B, A), jnp.float32)
        vals = jnp.asarray(rng.randn(B, F, E), jnp.float32)
        proj = jnp.asarray(rng.randn(B, F, A), jnp.float32)
        att_v = jnp.asarray(rng.randn(A, 1), jnp.float32)
        mask = jnp.ones((B, F))
        s = (jnp.tanh(proj + query[:, None, :]) @ att_v)[..., 0]
        ref = jnp.einsum("bf,bfe->be", jax.nn.softmax(s, -1), vals)
        got = sharded_context_attention(
            query, vals, proj, mask, att_v, mesh2, batch_axis="data"
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-6
        )


class TestShardFramesModel:
    """model.shard_frames: the captioner's attention fusion runs
    frame-sharded over the mesh and must match the dense model exactly."""

    def _cfg(self):
        from cst_captioning_tpu.config import get_preset

        cfg = get_preset("synthetic_smoke")
        cfg.model.feature_fusion = "attention"
        cfg.data.max_frames = 8   # divisible by the model axis
        cfg.model.vocab_size = 32
        return cfg

    def _batch(self, cfg, rng):
        B, F = 4, cfg.data.max_frames
        D = cfg.data.feature_dims["resnet"]
        feats = {"resnet": jnp.asarray(rng.randn(B, F, D), jnp.float32)}
        masks = {"resnet": jnp.ones((B, F)).at[:, -2:].set(0.0)}
        ids = jnp.asarray(
            rng.randint(4, cfg.model.vocab_size, (B, 10)), jnp.int32
        )
        ids = ids.at[:, 0].set(1)
        return feats, masks, ids

    def test_forward_matches_dense(self):
        from cst_captioning_tpu.models import model_from_config

        cfg = self._cfg()
        mesh = make_mesh({"data": 2, "model": 4})
        rng = np.random.RandomState(5)
        feats, masks, ids = self._batch(cfg, rng)

        dense = model_from_config(cfg)
        cfg.model.shard_frames = True
        sharded = model_from_config(cfg, mesh=mesh)
        assert sharded.shard_frames and sharded.frame_batch_axis == "data"

        params = dense.init(jax.random.PRNGKey(0), feats, masks, ids)
        out_d = dense.apply(params, feats, masks, ids)
        out_s = sharded.apply(params, feats, masks, ids)
        np.testing.assert_allclose(
            np.asarray(out_s), np.asarray(out_d), rtol=2e-5, atol=2e-5
        )

    def test_shard_frames_takes_priority_over_pallas_kernel(self):
        """With both shard_frames and use_pallas_attention set, the
        sharded (exact, collective) path wins — and still matches dense."""
        from cst_captioning_tpu.models import model_from_config

        cfg = self._cfg()
        mesh = make_mesh({"data": 2, "model": 4})
        rng = np.random.RandomState(8)
        feats, masks, ids = self._batch(cfg, rng)
        dense = model_from_config(cfg)
        cfg.model.shard_frames = True
        cfg.model.use_pallas_attention = True
        both = model_from_config(cfg, mesh=mesh)
        assert both.shard_frames
        params = dense.init(jax.random.PRNGKey(0), feats, masks, ids)
        np.testing.assert_allclose(
            np.asarray(both.apply(params, feats, masks, ids)),
            np.asarray(dense.apply(params, feats, masks, ids)),
            rtol=2e-5,
            atol=2e-5,
        )

    def test_grads_match_dense(self):
        """Training differentiates through the shard_map body (pmax needs
        the stop_gradient-inside construction) — grads must equal dense."""
        from cst_captioning_tpu.models import model_from_config

        cfg = self._cfg()
        mesh = make_mesh({"data": 2, "model": 4})
        rng = np.random.RandomState(7)
        feats, masks, ids = self._batch(cfg, rng)
        dense = model_from_config(cfg)
        cfg.model.shard_frames = True
        sharded = model_from_config(cfg, mesh=mesh)
        params = dense.init(jax.random.PRNGKey(0), feats, masks, ids)

        def loss(mdl, p):
            return jnp.sum(mdl.apply(p, feats, masks, ids) ** 2)

        gd = jax.grad(lambda p: loss(dense, p))(params)
        gs = jax.grad(lambda p: loss(sharded, p))(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            ),
            gd,
            gs,
        )

    def test_sample_matches_dense(self):
        from cst_captioning_tpu.models import model_from_config

        cfg = self._cfg()
        mesh = make_mesh({"data": 1, "model": 8})
        rng = np.random.RandomState(6)
        feats, masks, ids = self._batch(cfg, rng)
        dense = model_from_config(cfg)
        cfg.model.shard_frames = True
        sharded = model_from_config(cfg, mesh=mesh)
        params = dense.init(jax.random.PRNGKey(0), feats, masks, ids)
        out_d = dense.apply(
            params, feats, masks, greedy=True, max_len=8, method="sample"
        )
        out_s = sharded.apply(
            params, feats, masks, greedy=True, max_len=8, method="sample"
        )
        np.testing.assert_array_equal(
            np.asarray(out_s.tokens), np.asarray(out_d.tokens)
        )
