"""ISSUE 14: the shard_map port of the fused decode kernels and the
cross-shard top-K candidate merge.

The shared harness (tests/test_decode_core.py) pins the end-to-end
backends (`fused_beam_tp2`, `fused_sampler_tp2`,
`slot_decoder_beam_tp2_fused`, `slot_decoder_greedy_tp2_fused`)
token-exact against the scan references; this file pins the MERGE
PRIMITIVES directly — including engineered EXACT ties spanning the
vocab-tile shard boundary, the case a wrong tie order would get away
with on random weights — plus the sampler-stream bit-exactness
contract and the capability gate plumbing."""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.constants import PAD_ID
from cst_captioning_tpu.decoding import core
from cst_captioning_tpu.parallel import make_mesh

G, K, V = 3, 4, 40
M = 2


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"data": 1, "model": M}, devices=jax.devices()[:M])


def _inline_beam_topk(logits, scores, finished):
    """The decode_step beam selection, verbatim (the reference the
    merge must reproduce bit-for-bit including tie order)."""
    logp = jax.nn.log_softmax(logits, axis=-1).reshape(G, K, V)
    pad_only = jnp.full((V,), core.NEG_INF).at[PAD_ID].set(0.0)
    logp = jnp.where(finished[..., None], pad_only[None, None, :], logp)
    total = scores[..., None] + logp
    return jax.lax.top_k(total.reshape(G, K * V), K)


def _st(scores, finished):
    return core.CoreState(
        state=None, seqs=jnp.zeros((G, K, 8), jnp.int32), scores=scores,
        lps=None, finished=finished, tokens=None, step=None, rng=None,
    )


class TestTpBeamTopkMerge:
    def _compare(self, mesh, logits, scores, finished):
        tp = core.make_tp_beam_topk(mesh)
        ref_sc, ref_fl = jax.jit(_inline_beam_topk)(
            logits, scores, finished
        )
        got_sc, got_fl = jax.jit(
            lambda l, s, f: tp(l, _st(s, f))
        )(logits, scores, finished)
        np.testing.assert_array_equal(
            np.asarray(got_fl), np.asarray(ref_fl),
            err_msg="cross-shard merge picked different flat keys "
            "than the inline top-K",
        )
        np.testing.assert_allclose(
            np.asarray(got_sc), np.asarray(ref_sc), rtol=1e-6, atol=1e-6
        )

    def test_random_logits_and_finished_rows(self, mesh):
        rng = np.random.RandomState(0)
        self._compare(
            mesh,
            jnp.asarray(rng.randn(G * K, V).astype(np.float32)),
            jnp.asarray(rng.randn(G, K).astype(np.float32)),
            jnp.asarray(rng.rand(G, K) < 0.3),
        )

    def test_exact_tie_across_the_shard_boundary(self, mesh):
        """Columns V/M - 1 and V/M hold BITWISE equal logits — one on
        each shard.  The merge must resolve the tie exactly like
        ``lax.top_k`` over the full vocab: lowest flat key (the last
        column of shard 0) wins."""
        rng = np.random.RandomState(1)
        lg = rng.randn(G * K, V).astype(np.float32)
        b = V // M
        lg[:, b] = lg[:, b - 1]
        # Make the tied pair the row maximum so it MUST enter the top-K.
        lg[:, b - 1] = lg[:, b] = np.abs(lg).max() + 1.0
        scores = jnp.zeros((G, K), jnp.float32)
        fin = jnp.zeros((G, K), bool)
        self._compare(mesh, jnp.asarray(lg), scores, fin)
        tp = core.make_tp_beam_topk(mesh)
        _, fl = jax.jit(lambda l: tp(l, _st(scores, fin)))(
            jnp.asarray(lg)
        )
        fl = np.asarray(fl)
        # The winning beam's tied twins are the two largest candidates
        # (bitwise-equal totals): key order puts the shard-0 column
        # first and its cross-boundary twin (key + 1) second.
        assert (fl[:, 0] % V == b - 1).all(), fl[:, 0]
        np.testing.assert_array_equal(fl[:, 1], fl[:, 0] + 1)

    def test_finished_rows_collapse_to_pad(self, mesh):
        rng = np.random.RandomState(2)
        self._compare(
            mesh,
            jnp.asarray(rng.randn(G * K, V).astype(np.float32)),
            jnp.asarray(rng.randn(G, K).astype(np.float32)),
            jnp.ones((G, K), bool),
        )


class TestTpRowPick:
    def test_matches_argmax_and_boundary_tie(self, mesh):
        rng = np.random.RandomState(3)
        lg = rng.randn(G, V).astype(np.float32)
        b = V // M
        lg[:, b] = lg[:, b - 1] = np.abs(lg).max() + 1.0
        pick = core.make_tp_row_pick(mesh)
        nxt, lp = jax.jit(pick)(jnp.asarray(lg))
        logp = jax.nn.log_softmax(jnp.asarray(lg), axis=-1)
        ref = jnp.argmax(logp, axis=-1)
        np.testing.assert_array_equal(np.asarray(nxt), np.asarray(ref))
        # lowest-index tie: the shard-0 column of the tied pair
        assert (np.asarray(nxt) == b - 1).all()
        ref_lp = jnp.take_along_axis(logp, ref[:, None], -1)[:, 0]
        np.testing.assert_allclose(
            np.asarray(lp), np.asarray(ref_lp), atol=1e-6
        )


def _sampler_world(rng, B=8, F=3, A=16, E=16, H=16, V=40):
    f32 = lambda *s: jnp.asarray(rng.randn(*s).astype(np.float32))  # noqa: E731
    return dict(
        gx=f32(B, 4 * H), w_x=f32(E, 4 * H), wh=f32(H, 4 * H),
        w_ctx=f32(E, 4 * H), att_wh=f32(H, A), att_v=f32(A, 1),
        proj=f32(B, F, A), mask=jnp.ones((B, F), jnp.float32),
        vals=f32(B, F, E), emb=f32(V, E), w_out=f32(H, V), b_out=f32(V),
    )


class TestShardedSamplerStream:
    """The multinomial hash-Gumbel stream is a function of (seed, row,
    step, GLOBAL vocab position) — sharding must not move a single
    draw.  Tokens are BIT-exact vs the single-device scan twin, greedy
    and multinomial, both fusion modes."""

    @pytest.mark.parametrize("greedy", [True, False])
    @pytest.mark.parametrize("fusion", ["attention", "meanpool"])
    def test_tokens_bit_exact_vs_scan_twin(self, mesh, greedy, fusion):
        from cst_captioning_tpu.ops import pallas_sampler as ps
        from cst_captioning_tpu.ops import shard_decode as sd

        w = _sampler_world(np.random.RandomState(7))
        seed = jnp.asarray([123, 456], jnp.int32)
        kw = dict(max_len=10, greedy=greedy, temperature=0.8)
        if fusion == "attention":
            args = (
                w["gx"], w["w_x"], w["wh"], w["w_ctx"], w["att_wh"],
                w["att_v"], w["proj"], w["mask"], w["vals"], w["emb"],
                w["w_out"], w["b_out"], seed,
            )
            ref = ps.attlstm_sample_scan(*args, **kw)
            got = sd.sharded_attlstm_sample(*args, mesh=mesh, **kw)
        else:
            args = (
                w["gx"], w["w_x"], w["wh"], w["emb"], w["w_out"],
                w["b_out"], seed,
            )
            ref = ps.lstm_sample_scan(*args, **kw)
            got = sd.sharded_lstm_sample(*args, mesh=mesh, **kw)
        np.testing.assert_array_equal(
            np.asarray(got[0]), np.asarray(ref[0])
        )
        np.testing.assert_allclose(
            np.asarray(got[1]), np.asarray(ref[1]), atol=1e-5
        )
        np.testing.assert_array_equal(
            np.asarray(got[2]), np.asarray(ref[2])
        )


class TestShardedBeamBoundaryTies:
    def test_duplicate_vocab_columns_across_shards(self, mesh):
        """w_out columns V/M - 1 and V/M are byte-identical, so their
        logits tie EXACTLY at every step, one candidate per shard —
        the sharded beam must emit the same token sequences as the
        single-device scan twin (ties to the lower global id)."""
        from cst_captioning_tpu.ops import pallas_beam as pb
        from cst_captioning_tpu.ops import shard_decode as sd

        w = _sampler_world(np.random.RandomState(11))
        b = 40 // M
        w_out = np.asarray(w["w_out"]).copy()
        b_out = np.asarray(w["b_out"]).copy()
        w_out[:, b] = w_out[:, b - 1]
        # Boosted shared bias: the twins stay competitive, so the tie
        # actually steers the search instead of hiding in the tail.
        b_out[b] = b_out[b - 1] = float(np.abs(b_out).max()) + 4.0
        kw = dict(beam_size=3, max_len=8)
        args = (
            w["gx"], w["w_x"], w["wh"], w["emb"],
            jnp.asarray(w_out), jnp.asarray(b_out),
        )
        ref_seqs, ref_sc = pb.lstm_beam_scan(*args, **kw)
        got_seqs, got_sc = sd.sharded_lstm_beam(*args, mesh=mesh, **kw)
        np.testing.assert_array_equal(
            np.asarray(got_seqs), np.asarray(ref_seqs)
        )
        np.testing.assert_allclose(
            np.asarray(got_sc), np.asarray(ref_sc), rtol=1e-5, atol=1e-5
        )
        # The engineered twin columns really were selected somewhere.
        assert (np.asarray(ref_seqs) == b - 1).any()


class TestGatePlumbing:
    def test_shard_decode_ok(self):
        from cst_captioning_tpu.ops.shard_decode import shard_decode_ok

        assert shard_decode_ok(40, 2, 5)
        assert not shard_decode_ok(40, 1, 5)     # not sharded
        assert not shard_decode_ok(41, 2, 5)     # uneven tile
        assert not shard_decode_ok(8, 4, 3)      # tile smaller than K

    def test_capability_table_covers_the_kernels(self):
        assert core.kernel_supports("use_pallas_beam", "model")
        assert core.kernel_supports("use_pallas_sampler", "model")
        assert not core.kernel_supports("use_pallas_beam", "data")
        assert not core.kernel_supports("use_pallas_attention", "model")
        assert not core.kernel_supports("nonsense_flag", "model")

    def test_model_from_config_enables_tp_fused(self, mesh):
        """Under a model>1 mesh the gate now ENGAGES the fused flags
        via the shard_map port (pure XLA — no TPU requirement), and
        the model carries decode_mesh."""
        from cst_captioning_tpu.config import get_preset
        from cst_captioning_tpu.models import model_from_config

        cfg = get_preset("synthetic_smoke")
        cfg.model.vocab_size = 40
        cfg.model.use_pallas_beam = True
        cfg.model.use_pallas_sampler = True
        m = model_from_config(cfg, mesh=mesh)
        assert m.use_pallas_beam and m.use_pallas_sampler
        assert m.decode_mesh is mesh
        assert m.decode_shards == M

    def test_uneven_vocab_declines_with_reason(self, mesh, caplog):
        from cst_captioning_tpu.config import get_preset
        from cst_captioning_tpu.models import model_from_config

        cfg = get_preset("synthetic_smoke")
        cfg.model.vocab_size = 41                 # 41 % 2 != 0
        cfg.model.use_pallas_beam = True
        with caplog.at_level(
            logging.WARNING, logger="cst_captioning_tpu.models"
        ):
            m = model_from_config(cfg, mesh=mesh)
        assert not m.use_pallas_beam
        assert m.decode_mesh is None
        assert any(
            "does not tile evenly" in r.getMessage()
            for r in caplog.records
        )

    def test_batch_sharded_mesh_still_declines(self, caplog):
        from cst_captioning_tpu.config import get_preset
        from cst_captioning_tpu.models import model_from_config

        cfg = get_preset("synthetic_smoke")
        cfg.model.vocab_size = 40
        cfg.model.use_pallas_beam = True
        dp = make_mesh(
            {"data": 2, "model": 1}, devices=jax.devices()[:2]
        )
        with caplog.at_level(
            logging.WARNING, logger="cst_captioning_tpu.models"
        ):
            m = model_from_config(cfg, mesh=dp)
        assert not m.use_pallas_beam
        assert any(
            "batch sharding" in r.getMessage()
            for r in caplog.records
        )
