"""Resume-from-checkpoint: an interrupted-then-resumed run must reproduce
the uninterrupted run exactly (params, history continuation, counters)."""

import os

import jax
import numpy as np
import pytest

from cst_captioning_tpu.config import get_preset
from cst_captioning_tpu.data import make_synthetic_dataset
from cst_captioning_tpu.training import Trainer


def cfg_for(tmp_path, name, max_epochs, resume=False):
    cfg = get_preset("synthetic_smoke")
    cfg.name = name
    cfg.data.batch_size = 8
    cfg.data.seq_per_img = 2
    cfg.train.checkpoint_dir = str(tmp_path / "ck")
    cfg.train.max_epochs = max_epochs
    cfg.train.max_patience = 0
    cfg.train.resume = resume
    cfg.train.learning_rate = 3e-3
    cfg.eval.metrics = ["CIDEr"]
    cfg.eval.max_decode_len = 11
    return cfg


@pytest.fixture(scope="module")
def ds():
    return make_synthetic_dataset(num_videos=16, max_frames=6, seed=3)[0]


class TestResume:
    def test_resumed_run_matches_uninterrupted(self, ds, tmp_path):
        # Uninterrupted: 4 epochs.
        cfg_a = cfg_for(tmp_path, "full", 4)
        ta = Trainer(cfg_a, train_ds=ds, val_ds=None)
        hist_a = ta.fit()

        # Interrupted: 2 epochs, then resume to 4 in the same workdir.
        cfg_b = cfg_for(tmp_path, "halves", 2)
        tb = Trainer(cfg_b, train_ds=ds, val_ds=None)
        tb.fit()
        cfg_c = cfg_for(tmp_path, "halves", 4, resume=True)
        tc = Trainer(cfg_c, train_ds=ds, val_ds=None)
        assert tc.start_epoch == 2
        assert int(tc.state.step) == int(tb.state.step)
        hist_c = tc.fit()

        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            ),
            ta.state.params,
            tc.state.params,
        )
        # history holds all 4 epochs, later losses identical
        assert set(hist_c) == {"0", "1", "2", "3"}
        np.testing.assert_allclose(
            hist_c["3"]["train_loss"], hist_a["3"]["train_loss"], rtol=1e-6
        )

    def test_midepoch_preemption_resume_matches_uninterrupted(
        self, ds, tmp_path, monkeypatch
    ):
        """A preemption that lands MID-epoch must still resume to the
        exact uninterrupted result: the checkpoint records steps_done and
        the replay skips exactly those batches (ADVICE r2 #3)."""
        from cst_captioning_tpu.training.preemption import PreemptionGuard

        def mk(name, max_epochs, resume=False):
            # batch 8 over 16 videos -> 2 steps/epoch (and divisible by
            # the conftest's 8-device data axis).
            return cfg_for(tmp_path, name, max_epochs, resume=resume)

        ta = Trainer(mk("mid_full", 3), train_ds=ds, val_ds=None)
        ta.fit()

        class FlagAfter:
            """Latches True after n polls — deterministically lands the
            'signal' between two specific step dispatches."""

            def __init__(self, n):
                self.n = n
                self.reads = 0

            @property
            def triggered(self):
                self.reads += 1
                return self.reads > self.n

        # Polls: 2 per epoch (one per batch) + 1 at epoch end.  n=4 ->
        # epoch 0 completes (reads 1-3), epoch 1 breaks before its step 1
        # (reads 4, 5) with exactly one update applied.
        fake = FlagAfter(4)
        monkeypatch.setattr(
            PreemptionGuard, "install", classmethod(lambda cls: fake)
        )
        tb = Trainer(mk("mid_halves", 3), train_ds=ds, val_ds=None)
        tb.fit()
        assert tb.preempted
        monkeypatch.undo()

        from cst_captioning_tpu.training.checkpoint import load_infos

        infos = load_infos(os.path.join(tb.workdir, "last"))
        assert int(infos["epoch"]) == 1
        assert int(infos["steps_done"]) == 1

        tc = Trainer(
            mk("mid_halves", 3, resume=True), train_ds=ds, val_ds=None
        )
        assert tc.start_epoch == 1 and tc._resume_skip_steps == 1
        tc.fit()
        assert int(tc.state.step) == int(ta.state.step)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            ),
            ta.state.params,
            tc.state.params,
        )

    def test_midepoch_preemption_resume_pipelined_cst(
        self, ds, tmp_path, monkeypatch
    ):
        """Round-3's bit-exact mid-epoch resume must survive the
        pipelined CST layout: at the preemption break the trainer
        flushes the one-step-delayed pending update, so ``steps_done``
        matches the updates actually in params and the replay reproduces
        the uninterrupted run exactly."""
        from cst_captioning_tpu.training import cst as cst_mod
        from cst_captioning_tpu.training.preemption import PreemptionGuard

        # The CPU backend supports io_callback, so the auto path would
        # pick the one-graph step; pretend it doesn't and force the
        # pipelined split layout.
        monkeypatch.setattr(cst_mod, "io_callback_supported", lambda: False)

        def mk(name, max_epochs, resume=False):
            cfg = cfg_for(tmp_path, name, max_epochs, resume=resume)
            cfg.train.train_mode = "cst"
            cfg.train.cst_baseline = "scb"
            cfg.train.cst_num_samples = 2
            cfg.train.cst_split_layout = "pipeline"
            cfg.data.max_seq_len = ds.captions(0).shape[1] - 1
            return cfg

        def build(name, max_epochs, resume=False):
            t = Trainer(mk(name, max_epochs, resume=resume),
                        train_ds=ds, val_ds=None)
            # The auto-selection consults io_callback support first;
            # assert the forced layout actually engaged.
            assert getattr(t._train_step, "layout", "") == "pipeline"
            return t

        ta = build("pmid_full", 2)
        ta.fit()

        class FlagAfter:
            def __init__(self, n):
                self.n = n
                self.reads = 0

            @property
            def triggered(self):
                self.reads += 1
                return self.reads > self.n

        # 2 steps/epoch: epoch 0 completes (polls 1-3), epoch 1 breaks
        # before its second step — ONE update pending at the break.
        fake = FlagAfter(4)
        monkeypatch.setattr(
            PreemptionGuard, "install", classmethod(lambda cls: fake)
        )
        tb = build("pmid_halves", 2)
        tb.fit()
        assert tb.preempted
        # undo() drops EVERY patch from this monkeypatch (the fake guard
        # AND the io_callback stub) — re-apply the stub for the resume.
        monkeypatch.undo()
        monkeypatch.setattr(cst_mod, "io_callback_supported", lambda: False)

        from cst_captioning_tpu.training.checkpoint import load_infos

        infos = load_infos(os.path.join(tb.workdir, "last"))
        assert int(infos["epoch"]) == 1
        assert int(infos["steps_done"]) == 1
        # The flush ran: the saved optimizer step count equals the
        # number of updates steps_done claims.
        assert int(tb.state.step) == 3  # 2 (epoch 0) + 1 (epoch 1 flush)

        tc = build("pmid_halves", 2, resume=True)
        assert tc.start_epoch == 1 and tc._resume_skip_steps == 1
        tc.fit()
        assert int(tc.state.step) == int(ta.state.step)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            ),
            ta.state.params,
            tc.state.params,
        )

    def test_resume_without_checkpoint_is_fresh(self, ds, tmp_path):
        cfg = cfg_for(tmp_path, "fresh", 1, resume=True)
        t = Trainer(cfg, train_ds=ds, val_ds=None)
        assert t.start_epoch == 0
        t.fit()

    def test_resume_restores_best_counters(self, ds, tmp_path):
        cfg = cfg_for(tmp_path, "with_val", 2)
        t = Trainer(cfg, train_ds=ds, val_ds=ds)
        t.fit()
        best_before = t.best_score
        cfg2 = cfg_for(tmp_path, "with_val", 3, resume=True)
        t2 = Trainer(cfg2, train_ds=ds, val_ds=ds)
        assert t2.best_score == pytest.approx(best_before)
        assert t2.best_epoch == t.best_epoch
        assert os.path.exists(os.path.join(t2.workdir, "best"))
