"""Fused whole-recurrence beam-search kernel (ops/pallas_beam.py).

Parity strategy, mirroring tests/test_pallas_sampler.py: the kernel and
its pure-XLA twin ``attlstm_beam_scan`` share the decomposed GEMM order,
the V-tile-chunked log-sum-exp accumulation and the ``_row_topk`` tie
helpers, so tokens AND scores must match EXACTLY.  Against the scan path
(``decoding/beam.py`` driving ``CaptionModel.decode_one``), float32
tokens must match exactly on the fixed-seed shapes here (the residual
daylight is <1-ulp float association at top-K tie boundaries —
docs/PARITY.md), with scores allclose.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.constants import BOS_ID, EOS_ID, PAD_ID, UNK_ID
from cst_captioning_tpu.decoding.beam import (
    beam_search,
    fused_beam_engaged,
    make_beam_search_fn,
)
from cst_captioning_tpu.models.captioner import CaptionModel
from cst_captioning_tpu.ops.pallas_beam import (
    attlstm_beam,
    attlstm_beam_scan,
    beam_shapes_ok,
    lstm_beam,
    lstm_beam_scan,
)


def make_args(B=4, H=16, A=16, E=16, F=5, V=50, seed=0, logit_scale=0.3):
    rng = np.random.RandomState(seed)
    cdt = jnp.float32
    arr = lambda *s, sc=0.3: jnp.asarray(rng.randn(*s) * sc, cdt)
    return dict(
        gx_static=jnp.asarray(rng.randn(B, 4 * H) * 0.1, jnp.float32),
        w_x=arr(E, 4 * H),
        wh=arr(H, 4 * H),
        w_ctx=arr(E, 4 * H),
        att_wh=arr(H, A),
        att_v=arr(A, 1),
        att_proj=arr(B, F, A),
        att_mask=jnp.asarray((rng.rand(B, F) > 0.2).astype(np.float32)),
        att_vals=arr(B, F, E),
        emb=arr(V, E),
        w_out=arr(H, V, sc=logit_scale),
        b_out=jnp.asarray(rng.randn(V) * 0.1, jnp.float32),
    )


def run_both(args, **kw):
    k = attlstm_beam(*args.values(), **kw)
    r = attlstm_beam_scan(*args.values(), **kw)
    return k, r


def assert_exact(k, r):
    np.testing.assert_array_equal(np.asarray(k[0]), np.asarray(r[0]))
    np.testing.assert_array_equal(np.asarray(k[1]), np.asarray(r[1]))


class TestKernelVsTwin:
    @pytest.mark.parametrize("beam_size", [1, 3, 5])
    def test_exact_parity(self, beam_size):
        args = make_args()
        k, r = run_both(args, beam_size=beam_size, max_len=8)
        assert_exact(k, r)
        assert k[0].shape == (4, beam_size, 8)
        assert k[1].shape == (4, beam_size)

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_randomized_shapes(self, seed):
        rng = np.random.RandomState(100 + seed)
        B = int(rng.choice([2, 3, 4]))
        F = int(rng.choice([3, 5, 7]))
        V = int(rng.choice([24, 50, 130]))
        K = int(rng.choice([2, 3, 4]))
        args = make_args(B=B, F=F, V=V, seed=seed)
        k, r = run_both(args, beam_size=K, max_len=6)
        assert_exact(k, r)

    def test_multi_tile_vocab_with_padding(self):
        """V=1100 forces multiple streamed V-tiles plus a padded tail:
        the online top-K must merge across tiles and padded columns must
        never be selected."""
        args = make_args(V=1100)
        k, r = run_both(args, beam_size=4, max_len=6)
        assert_exact(k, r)
        assert np.asarray(k[0]).max() < 1100

    def test_suppress_unk(self):
        args = make_args(V=24, seed=3)
        # Rig UNK to dominate; suppression must bar it from every beam.
        args["b_out"] = args["b_out"].at[UNK_ID].set(50.0)
        k_on, r_on = run_both(
            args, beam_size=3, max_len=5, suppress_unk=True
        )
        assert_exact(k_on, r_on)
        assert not np.any(np.asarray(k_on[0]) == UNK_ID)
        k_off, _ = run_both(
            args, beam_size=3, max_len=5, suppress_unk=False
        )
        assert np.all(np.asarray(k_off[0])[:, 0, 0] == UNK_ID)

    def test_static_ctx_variant(self):
        a = make_args(seed=31)
        sa = {
            k: a[k] for k in ("gx_static", "w_x", "wh", "emb", "w_out",
                              "b_out")
        }
        k = lstm_beam(*sa.values(), beam_size=3, max_len=8)
        r = lstm_beam_scan(*sa.values(), beam_size=3, max_len=8)
        assert_exact(k, r)


class TestTiesAndSemantics:
    def test_duplicate_vocab_columns_tie_to_lower_id(self):
        """Two vocab entries with IDENTICAL logits at every step: the
        scan path's lax.top_k resolves the exact tie to the lower flat
        index, and the kernel's merge must do the same."""
        args = make_args(V=30, seed=7)
        lo, hi = 10, 20
        args["w_out"] = args["w_out"].at[:, hi].set(args["w_out"][:, lo])
        args["b_out"] = args["b_out"].at[hi].set(args["b_out"][lo])
        # Rig the tied pair to win step 0 so the tie decides the beam.
        args["b_out"] = (
            args["b_out"].at[lo].add(30.0).at[hi].add(30.0)
        )
        k, r = run_both(args, beam_size=3, max_len=4)
        assert_exact(k, r)
        # The winning beam's first token is the LOWER id of the pair.
        assert np.all(np.asarray(k[0])[:, 0, 0] == lo)

    def test_eos_freeze_emits_pad_and_holds_score(self):
        """EOS rigged to win at step 0: the best beam finishes
        immediately, rides along frozen (PAD continuation at zero cost)
        and its raw score never changes — the scan path's freeze."""
        args = make_args(V=24, seed=5)
        args["b_out"] = args["b_out"].at[EOS_ID].set(50.0)
        k, r = run_both(args, beam_size=3, max_len=6)
        assert_exact(k, r)
        toks = np.asarray(k[0])
        # Some beam per video starts with EOS; everything after is PAD.
        eos_rows = toks[:, :, 0] == EOS_ID
        assert eos_rows.any(axis=1).all()
        assert np.all(toks[eos_rows][:, 1:] == PAD_ID)

    def test_never_emits_pad_or_bos_while_live(self):
        args = make_args(V=24, seed=9)
        args["b_out"] = (
            args["b_out"].at[PAD_ID].set(50.0).at[BOS_ID].set(49.0)
        )
        k, r = run_both(args, beam_size=3, max_len=6)
        assert_exact(k, r)
        toks = np.asarray(k[0])
        # PAD appears only AFTER an EOS (the freeze), never as a live
        # emission, and BOS never appears at all.
        assert not np.any(toks == BOS_ID)
        for row in toks.reshape(-1, toks.shape[-1]):
            pads = np.nonzero(row == PAD_ID)[0]
            if len(pads):
                before = row[: pads[0]]
                assert len(before) and before[-1] == EOS_ID

    def test_scores_are_summed_logprobs(self):
        """Beam-1 raw score == the greedy trajectory's summed log-probs
        (cross-checked against the sampler twin's per-token values)."""
        from cst_captioning_tpu.ops.pallas_sampler import (
            attlstm_sample_scan,
        )

        args = make_args(seed=11)
        k, r = run_both(args, beam_size=1, max_len=6)
        assert_exact(k, r)
        seqs, scores = k
        toks, lps, mask = attlstm_sample_scan(
            *args.values(), 0, max_len=6, greedy=True
        )
        np.testing.assert_array_equal(
            np.asarray(seqs)[:, 0], np.asarray(toks)
        )
        np.testing.assert_allclose(
            np.asarray(scores)[:, 0],
            np.asarray(lps).sum(-1),
            rtol=2e-5, atol=2e-5,
        )


class TestCaptionerIntegration:
    @staticmethod
    def build(use_beam, fusion="attention", B=4, V=40, F=3,
              use_category=False, **extra):
        kw = dict(
            vocab_size=V, rnn_size=16, embed_size=16, att_hidden_size=16,
            num_layers=1, fusion=fusion, modalities=("resnet",),
            feature_dims=(12,), compute_dtype="float32", drop_prob=0.0,
            use_category=use_category,
        )
        kw.update(extra)
        model = CaptionModel(use_pallas_beam=use_beam, **kw)
        rng = np.random.RandomState(2)
        feats = {"resnet": jnp.asarray(rng.randn(B, F, 12), jnp.float32)}
        masks = {"resnet": jnp.ones((B, F), jnp.float32)}
        ids = jnp.asarray(
            rng.randint(4, V, size=(B, 6)), jnp.int32
        ).at[:, 0].set(BOS_ID)
        cat = (
            jnp.asarray(rng.randint(0, 20, (B,)), jnp.int32)
            if use_category else None
        )
        params = CaptionModel(**kw).init(
            jax.random.PRNGKey(0), feats, masks, ids, category=cat
        )
        return model, params, feats, masks, cat

    # Token-exact fused-vs-scan parity (attention + meanpool), beam1 ==
    # greedy, and the registry drive all moved to the SHARED harness:
    # tests/test_decode_core.py ("fused_beam" backend vs "scan_beam").

    def test_category_model(self):
        """Category embedding wiring is the one input surface the shared
        harness ctx doesn't carry — keep the fused-vs-scan pin here."""
        fused, params, feats, masks, cat = self.build(
            True, use_category=True
        )
        scan, *_ = self.build(False, use_category=True)
        rf = beam_search(
            fused, params, feats, masks, category=cat, beam_size=3,
            max_len=7,
        )
        rs = beam_search(
            scan, params, feats, masks, category=cat, beam_size=3,
            max_len=7,
        )
        np.testing.assert_array_equal(
            np.asarray(rf.all_tokens), np.asarray(rs.all_tokens)
        )

    def test_jitted_dispatch(self):
        """make_beam_search_fn wraps the dispatch in jit — the fused
        branch must trace cleanly (pallas_call under jit)."""
        fused, params, feats, masks, _ = self.build(True)
        fn = make_beam_search_fn(fused, beam_size=3, max_len=6)
        r = fn(params, feats, masks)
        assert r.tokens.shape == (4, 6)
        assert r.all_tokens.shape == (4, 3, 6)
        s = np.asarray(r.all_scores)
        assert (np.diff(s, axis=1) <= 1e-6).all()


class TestGateAndFallback:
    def test_beam_shapes_ok_vocab_floor(self):
        # The union argument needs >= K live candidates: V < K + 4 fails.
        assert not beam_shapes_ok(8, 5, 8, 16, 16, 16, 3, 4)
        assert beam_shapes_ok(8, 5, 50, 16, 16, 16, 3, 4)
        assert not beam_shapes_ok(8, 0, 50, 16, 16, 16, 3, 4)

    def test_gate_falls_back_to_scan(self):
        """Vocab too small for the fused path: beam_search must decline
        (with a log line) and still produce correct output."""
        m, params, feats, masks, _ = TestCaptionerIntegration.build(
            True, V=8
        )
        scan, *_ = TestCaptionerIntegration.build(False, V=8)
        engaged, reason = fused_beam_engaged(m, feats, 5)
        assert not engaged and "shape gate" in reason
        rf = beam_search(m, params, feats, masks, beam_size=5, max_len=5)
        rs = beam_search(
            scan, params, feats, masks, beam_size=5, max_len=5
        )
        np.testing.assert_array_equal(
            np.asarray(rf.all_tokens), np.asarray(rs.all_tokens)
        )

    def test_two_layer_model_declines(self):
        m, params, feats, masks, _ = TestCaptionerIntegration.build(
            True, num_layers=2
        )
        engaged, reason = fused_beam_engaged(m, feats, 3)
        assert not engaged and "num_layers" in reason
        r = beam_search(m, params, feats, masks, beam_size=3, max_len=5)
        assert r.tokens.shape == (4, 5)


class TestDeclineWarnings:
    """VERDICT r5 #4: a requested-but-gated-off fused path must say so."""

    def test_beam_search_warns_on_shape_decline(self, caplog):
        m, params, feats, masks, _ = TestCaptionerIntegration.build(
            True, V=8
        )
        with caplog.at_level(
            logging.WARNING, logger="cst_captioning_tpu.models"
        ):
            beam_search(m, params, feats, masks, beam_size=5, max_len=4)
        assert any(
            "use_pallas_beam" in r.message and "gated off" in r.message
            for r in caplog.records
        )

    def test_model_from_config_warns_on_backend_gate(self, caplog):
        """On the CPU test backend, the MSR-VTT preset's requested
        sampler AND beam kernels are gated off — both must log why."""
        from cst_captioning_tpu.config import get_preset
        from cst_captioning_tpu.models import model_from_config

        cfg = get_preset("msrvtt_resnet_c3d_xe")
        cfg.model.vocab_size = 64
        with caplog.at_level(
            logging.WARNING, logger="cst_captioning_tpu.models"
        ):
            model = model_from_config(cfg)
        msgs = [r.message for r in caplog.records]
        assert any(
            "use_pallas_sampler" in m and "not tpu" in m for m in msgs
        )
        assert any(
            "use_pallas_beam" in m and "not tpu" in m for m in msgs
        )
        assert not model.use_pallas_sampler and not model.use_pallas_beam

    def test_model_from_config_warns_on_two_layers(self, caplog,
                                                   monkeypatch):
        from cst_captioning_tpu.config import get_preset
        from cst_captioning_tpu.models import captioner, model_from_config

        cfg = get_preset("msrvtt_resnet_c3d_xe")
        cfg.model.vocab_size = 64
        cfg.model.num_layers = 2
        monkeypatch.setattr(
            captioner.jax, "default_backend", lambda: "tpu"
        )
        with caplog.at_level(
            logging.WARNING, logger="cst_captioning_tpu.models"
        ):
            model = model_from_config(cfg)
        assert any(
            "num_layers=2" in r.message for r in caplog.records
        )
        assert not model.use_pallas_beam

    def test_sampler_shape_decline_warns(self, caplog):
        """Directly-constructed model (bypasses model_from_config): the
        in-model shape gate must log when it declines."""
        m, params, feats, masks, _ = TestCaptionerIntegration.build(
            False, B=3, use_pallas_sampler=True
        )
        with caplog.at_level(
            logging.WARNING, logger="cst_captioning_tpu.models"
        ):
            m.apply(params, feats, masks, max_len=4, method="sample")
        assert any(
            "use_pallas_sampler" in r.message and "shape gate"
            in r.message
            for r in caplog.records
        )
