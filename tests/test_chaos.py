"""Fault-injection chaos engine + degradation ladder (ISSUE 11):
`cst_captioning_tpu/serving/chaos.py` and the priority/shed/retry/
requeue machinery it exercises in batcher.py / replicas.py.

Covers the acceptance bars:

* ChaosEngine determinism: same seed + schedule => byte-identical fault
  schedule; off-by-default (`from_config` of every default preset is
  None) with byte-identical serving behavior (no-chaos parity);
* the virtual-time soak replay: same (trace, chaos seed) => identical
  per-request shed/requeue/expiry/routing decision logs across runs;
* a seeded mid-traffic soak (>= 1 replica kill + >= 1 tick stall) with
  ZERO lost requests, schema-valid flight dumps on disk, and
  interactive-priority SLO attainment >= best-effort at overload;
* priority-aware load shedding: best-effort evicted before interactive,
  sheds counted per class + flight `shed` events;
* queue-depth-derived, per-request-jittered Retry-After on 429 AND 503
  (HTTP-level pin — the ISSUE 11 satellite);
* the server-side requeue budget capping requeue storms;
* the fuzzed requeue-deadline audit across 3 seeds: requeued requests
  keep their ORIGINAL deadlines, expired ones are shed (never served
  late), every shed leaves a flight-recorder event — the untested
  corner of PR 4's death/requeue path;
* request hedging on stubs: first result wins, exactly one result per
  request, losers cancelled.

All stub-engine (no real jax decode) — the real-engine twins (hedged
token-exactness, chaos bursts during elastic regrow) live in
tests/test_replicas.py / tests/test_serving.py.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from cst_captioning_tpu.config import PRESETS, get_preset
from cst_captioning_tpu.observability.flight import validate_flight_dump
from cst_captioning_tpu.serving.batcher import (
    PRIORITY_RANK,
    BackpressureError,
)
from cst_captioning_tpu.serving.cache import TwoTierCache
from cst_captioning_tpu.serving.chaos import (
    FAULT_SITES,
    ChaosEngine,
    make_diurnal_trace,
    run_soak,
)
from cst_captioning_tpu.serving.engine import DecodedResult, PreparedRequest
from cst_captioning_tpu.serving.metrics import PRIORITIES, ServingMetrics
from cst_captioning_tpu.serving.replicas import ReplicaSet


# ------------------------------------------------------ stub scheduler
# Async-API SlotDecoder/engine doubles (the test_replicas pattern): a
# request's tick budget rides `prepared.category`.

class _StubDecoder:
    def __init__(self, S=2, block=1):
        self.S, self.K, self.L, self.block = S, 1, 10_000, block
        self.admit_cap = S
        self.free = list(range(S))
        self.occupied = {}
        self._remaining = {}
        self._admit_seq = {}
        self._seq = 0
        self.resize_count = 0

    @property
    def n_occupied(self):
        return len(self.occupied)

    def maybe_resize(self, pending=0):
        return self.S

    def live_state_bytes(self):
        return 64 * self.n_occupied

    def tick_begin(self, prepared=(), datas=()):
        for req, data in zip(prepared, datas):
            slot = self.free.pop()
            assert slot not in self.occupied, "slot double-assigned"
            self.occupied[slot] = data
            self._remaining[slot] = req.category
            self._admit_seq[slot] = self._seq + 1
        if not self.occupied:
            return None
        self._seq += 1
        for s in self.occupied:
            self._remaining[s] -= self.block
        done = tuple(s for s in self.occupied if self._remaining[s] <= 0)
        return (self._seq, done)

    def tick_wait(self, handle):
        time.sleep(0.001)         # a "device step block"
        seq, done = handle
        return [
            s for s in done
            if s in self.occupied and self._admit_seq[s] <= seq
        ]

    def harvest_from(self, handle, slots):
        seq, _ = handle
        out = []
        for s in slots:
            data = self.occupied.pop(s)
            steps = (seq - self._admit_seq.pop(s) + 1) * self.block
            self._remaining.pop(s, None)
            self.free.append(s)
            out.append((data, np.asarray([5, 2], np.int32), 0.0, steps))
        return out

    def evict(self, slot):
        data = self.occupied.pop(slot)
        self._remaining.pop(slot, None)
        self._admit_seq.pop(slot, None)
        self.free.append(slot)
        return data


class _StubEngine:
    def __init__(self, S=2, cfg=None):
        self.cfg = cfg if cfg is not None else get_preset("synthetic_smoke")
        self.cache = TwoTierCache(8, 8)
        self._decoder = _StubDecoder(S=S)
        self.device = None

    def prepare(self, payload):
        return PreparedRequest(
            feats=None, masks=None,
            category=int(payload.get("steps", 3)),  # tick budget
            feature_id=None, cache_key=payload.get("key", ""),
            enc_row=None,
        )

    def lookup_caption(self, key):
        return self.cache.captions.get(key) if key else None

    def slot_decoder(self):
        return self._decoder

    def result_from_tokens(self, req, tokens, timings_ms, store=True):
        return DecodedResult(
            caption="chaos-stub",
            tokens=[int(t) for t in tokens],
            timings_ms=timings_ms,
        )


def _payloads(n, steps=3):
    return [{"steps": steps, "key": f"chaos-{i}"} for i in range(n)]


# --------------------------------------------------------- ChaosEngine

class TestChaosEngine:
    def test_off_by_default_for_every_preset(self):
        """Chaos must be opt-in everywhere: the default serving config
        of EVERY preset builds no engine at all (the no-chaos path is
        byte-identical by construction — no engine, no branches)."""
        for name in PRESETS:
            assert ChaosEngine.from_config(
                get_preset(name).serving
            ) is None, name

    def test_same_seed_same_schedule_identical_fault_log(self):
        sched = [
            {"site": "tick_stall", "every": 3, "value": 0.05},
            {"site": "cache_miss", "p": 0.4},
            {"site": "replica_kill", "at": 5, "replica": 1},
        ]

        def drive(engine):
            for n in range(20):
                engine.fire("tick_stall")
                engine.fire("cache_miss")
                for rid in (0, 1):
                    engine.fire("replica_kill", replica=rid)
            return engine.decision_log()

        a = drive(ChaosEngine(seed=11, schedule=sched))
        b = drive(ChaosEngine(seed=11, schedule=sched))
        assert a == b and a, "seeded schedule must replay byte-identical"
        c = drive(ChaosEngine(seed=12, schedule=sched))
        # deterministic triggers agree; the probabilistic stream moves
        assert [e for e in c if e[0] != "cache_miss"] == [
            e for e in a if e[0] != "cache_miss"
        ]

    def test_replica_scoped_entry_only_fires_there(self):
        ce = ChaosEngine(schedule=[
            {"site": "replica_kill", "at": 0, "replica": 1},
        ])
        assert ce.fire("replica_kill", replica=0) is False
        assert ce.fire("replica_kill", replica=1) is True

    def test_unregistered_site_raises(self):
        ce = ChaosEngine()
        with pytest.raises(ValueError, match="FAULT_SITES"):
            ce.fire("made_up_site")
        with pytest.raises(ValueError, match="FAULT_SITES"):
            ChaosEngine(schedule=[{"site": "nope", "at": 0}])

    @pytest.mark.parametrize("bad", [
        {"site": "tick_stall"},                        # no trigger
        {"site": "tick_stall", "at": 1, "every": 2},   # two triggers
        {"site": "tick_stall", "at": -1},
        {"site": "tick_stall", "every": 0},
        {"site": "tick_stall", "p": 1.5},
        {"site": "tick_stall", "at": True},
        "not a dict",
    ])
    def test_malformed_schedule_entries_rejected(self, bad):
        with pytest.raises(ValueError):
            ChaosEngine(schedule=[bad])

    def test_config_keys_validated(self):
        class SV:
            chaos = {"seed": 1, "sched": []}

        with pytest.raises(ValueError, match="unknown serving.chaos"):
            ChaosEngine.from_config(SV())

    def test_fault_sites_catalogue_is_unique_and_nonempty(self):
        names = [s for s, _, _ in FAULT_SITES]
        assert len(names) == len(set(names)) >= 5


# -------------------------------------------------- priority shedding

class TestPriorityShedding:
    def _rs(self, queue_depth=2):
        return ReplicaSet(
            [_StubEngine(S=1)], ServingMetrics(),
            queue_depth=queue_depth,
        )

    def test_best_effort_shed_before_interactive(self):
        """Queue full of best-effort: an interactive arrival evicts the
        OLDEST best-effort request (429 to its submitter with a
        computed Retry-After), lands in its place, and the decision is
        counted + flight-recorded."""
        rs = self._rs(queue_depth=2)
        be = [
            rs.submit_async({"steps": 3, "key": f"b{i}"},
                            priority="best_effort")
            for i in range(2)
        ]
        it = rs.submit_async({"steps": 3, "key": "i0"},
                             priority="interactive")
        assert be[0].future.done()
        with pytest.raises(BackpressureError) as ei:
            be[0].future.result()
        assert ei.value.retry_after_s > 0
        assert not be[1].future.done() and not it.future.done()
        assert rs.metrics.shed("best_effort").value == 1
        assert rs.metrics.shed("interactive").value == 0
        events = [
            e["event"]
            for ring in rs.flight_snapshot().values()
            for e in ring["events"]
        ]
        assert "shed" in events

    def test_shed_prefers_the_lowest_class_present(self):
        rs = self._rs(queue_depth=2)
        b = rs.submit_async({"steps": 3, "key": "b"}, priority="batch")
        e = rs.submit_async({"steps": 3, "key": "e"},
                            priority="best_effort")
        rs.submit_async({"steps": 3, "key": "i"}, priority="interactive")
        assert e.future.done() and not b.future.done()

    def test_lowest_priority_arrival_rejects_itself(self):
        """Within/below the queued classes the ARRIVAL is the shed
        decision: nothing queued is dropped."""
        rs = self._rs(queue_depth=2)
        kept = [
            rs.submit_async({"steps": 3, "key": f"k{i}"},
                            priority="interactive")
            for i in range(2)
        ]
        with pytest.raises(BackpressureError):
            rs.submit_async({"steps": 3, "key": "x"},
                            priority="interactive")
        with pytest.raises(BackpressureError):
            rs.submit_async({"steps": 3, "key": "y"},
                            priority="best_effort")
        assert not any(p.future.done() for p in kept)
        assert rs.metrics.requests_rejected.value == 2

    def test_unknown_priority_is_a_value_error(self):
        rs = self._rs()
        with pytest.raises(ValueError, match="priority"):
            rs.submit_async({"steps": 1}, priority="urgent")

    def test_priority_rank_covers_the_metric_vocabulary(self):
        assert set(PRIORITY_RANK) == set(PRIORITIES)
        assert (
            PRIORITY_RANK["interactive"]
            > PRIORITY_RANK["batch"]
            > PRIORITY_RANK["best_effort"]
        )

    def test_shed_counters_render_with_priority_labels(self):
        m = ServingMetrics()
        m.shed("best_effort").inc(3)
        text = m.to_prometheus()
        assert 'caption_shed_total{priority="best_effort"} 3' in text
        assert 'caption_shed_total{priority="interactive"} 0' in text
        d = m.to_dict()
        assert d["degradation"]["shed"]["best_effort"] == 3


# ------------------------------------------------ retry-after (HTTP)

class TestRetryAfter:
    def test_value_scales_with_depth_and_jitters_per_request(self):
        rs = ReplicaSet([_StubEngine(S=1)], ServingMetrics())
        lo = rs._retry_after_value(0, None)
        hi = rs._retry_after_value(rs.queue_depth, None)
        assert hi > lo > 0
        a1 = rs._retry_after_value(4, "chaos-a")
        a2 = rs._retry_after_value(4, "chaos-a")
        b = rs._retry_after_value(4, "chaos-b")
        assert a1 == a2, "per-request jitter must be deterministic"
        assert a1 != b, "different requests must spread their retries"

    def test_http_429_and_503_carry_computed_retry_after(self):
        """THE satellite pin: queue-full 429s and draining 503s carry a
        queue-depth-derived, per-request-jittered Retry-After header —
        not the constant hint."""
        from cst_captioning_tpu.serving.server import CaptionServer

        eng = _StubEngine(S=1)
        metrics = ServingMetrics()
        rs = ReplicaSet([eng], metrics, queue_depth=1)
        srv = CaptionServer(
            eng, host="127.0.0.1", port=0, metrics=metrics, batcher=rs,
        ).start()
        bg, bg_err = [], []
        lock = threading.Lock()

        def submit_bg(payload):
            def go():
                try:
                    out = rs.submit(payload)
                    with lock:
                        bg.append(out)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        bg_err.append(e)
            t = threading.Thread(target=go)
            t.start()
            return t

        def post(key):
            body = json.dumps({"steps": 1, "key": key}).encode()
            req = urllib.request.Request(
                srv.url + "/v1/caption", data=body,
                headers={"Content-Type": "application/json"},
            )
            return urllib.request.urlopen(req, timeout=30.0)

        threads = []
        try:
            # Fill the single slot with a ~forever job, then the
            # 1-deep queue with another.
            threads.append(submit_bg({"steps": 500_000, "key": "hold"}))
            for _ in range(200):
                if eng._decoder.occupied:
                    break
                time.sleep(0.005)
            threads.append(submit_bg({"steps": 500_000, "key": "queued"}))
            for _ in range(200):
                if rs.depth >= 1:
                    break
                time.sleep(0.005)
            retry = {}
            for key in ("chaos-a", "chaos-b"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    post(key)
                assert ei.value.code == 429
                retry[key] = float(ei.value.headers["Retry-After"])
                assert retry[key] > 0
                body = json.loads(ei.value.read())
                # header renders at ms precision; the body is exact
                assert body["retry_after_s"] == pytest.approx(
                    retry[key], abs=5e-4
                )
            assert retry["chaos-a"] != retry["chaos-b"], (
                "429 Retry-After must jitter per request"
            )
            # Draining: 503 carries a computed hint too.
            srv.begin_drain()
            with pytest.raises(urllib.error.HTTPError) as ei:
                post("chaos-c")
            assert ei.value.code == 503
            assert float(ei.value.headers["Retry-After"]) > 0
        finally:
            srv.shutdown(drain=False)
            for t in threads:
                t.join(timeout=10.0)


# ------------------------------------------------------ requeue budget

class TestRequeueBudget:
    def test_budget_exhaustion_fails_instead_of_requeueing(self):
        engines = [_StubEngine(S=1), _StubEngine(S=1)]
        rs = ReplicaSet(
            engines, ServingMetrics(), requeue_budget=1,
        )
        p = rs.submit_async({"steps": 50, "key": "rq"})
        rep = rs.replicas[p.rid]
        # First drain: requeued onto the survivor within budget.
        rs._drain_replica(rep, "test kill 1")
        assert not p.future.done()
        assert p.requeues == 1
        assert rs.metrics.requeues_total.value == 1
        # Survivor dies too: the budget is spent — fail, don't bounce.
        rs.replicas[rep.rid].healthy = True  # a second survivor exists
        rep2 = rs.replicas[p.rid]
        rs._drain_replica(rep2, "test kill 2")
        assert p.future.done()
        with pytest.raises(RuntimeError, match="requeue budget"):
            p.future.result()
        assert rs.metrics.requeue_overflow.value == 1
        assert rs.metrics.shed(p.priority).value == 1


# ------------------------------------------------- soak: determinism

def _soak_world(n_replicas=2, S=1, flight_dir="", queue_depth=6):
    cfg = get_preset("synthetic_smoke")
    if flight_dir:
        cfg.serving.flight_dir = flight_dir
    engines = [_StubEngine(S=S, cfg=cfg) for _ in range(n_replicas)]
    rs = ReplicaSet(engines, ServingMetrics(), queue_depth=queue_depth)
    return rs


MID_TRAFFIC_SCHEDULE = [
    {"site": "replica_kill", "at": 6, "replica": 0},
    {"site": "tick_stall", "every": 4, "replica": 1, "value": 0.03},
    {"site": "queue_burst", "every": 5, "value": 3},
    {"site": "cache_miss", "p": 0.25},
    {"site": "deadline_skew", "at": 9, "value": 0.0},
]


def _mid_traffic_soak(seed, flight_dir=""):
    trace = make_diurnal_trace(
        seed, 40, 12, base_per_tick=1.0, burst_factor=5.0,
        period_ticks=24,
    )
    rs = _soak_world(flight_dir=flight_dir)
    chaos = ChaosEngine(seed=seed, schedule=MID_TRAFFIC_SCHEDULE)
    report = run_soak(
        rs, _payloads(12, steps=4), trace, chaos=chaos,
    )
    return rs, report


class TestSoakDeterminism:
    def test_same_seed_identical_decisions_and_fault_log(self):
        """THE determinism bar: same serving.chaos seed + recorded
        trace => the identical fault schedule AND identical per-request
        shed/requeue/serving decisions, byte for byte."""
        _, a = _mid_traffic_soak(31)
        _, b = _mid_traffic_soak(31)
        assert a.completed and b.completed
        assert a.chaos_log == b.chaos_log and a.chaos_log
        assert a.decisions == b.decisions and a.decisions
        _, c = _mid_traffic_soak(32)
        assert c.decisions != a.decisions  # the seed actually steers

    def test_no_chaos_parity(self):
        """Chaos off = byte-identical scheduler behavior: a soak with
        no engine and one with an engine that has an EMPTY schedule
        produce identical decisions, and the empty engine never
        fires."""
        trace = make_diurnal_trace(5, 24, 8, base_per_tick=0.8,
                                   burst_factor=2.0)
        rs1 = _soak_world()
        off = run_soak(rs1, _payloads(8, steps=4), trace)
        rs2 = _soak_world()
        empty = ChaosEngine(seed=99, schedule=[])
        on = run_soak(rs2, _payloads(8, steps=4), trace, chaos=empty)
        assert empty.decision_log() == []
        assert off.decisions == on.decisions
        assert rs2.metrics.chaos_faults.value == 0
        assert rs1.chaos is None  # default config builds no engine


class TestMidTrafficSoak:
    def test_kill_plus_stall_zero_lost_and_valid_flight_dumps(
        self, tmp_path
    ):
        """THE acceptance soak: a seeded mid-traffic run with >= 1
        replica kill and >= 1 tick stall completes with ZERO lost
        requests, leaves schema-valid flight dumps on disk, and
        interactive SLO-attainment >= best-effort at overload."""
        rs, report = _mid_traffic_soak(31, flight_dir=str(tmp_path))
        assert report.completed
        assert report.kills >= 1
        assert report.stall_ticks >= 1
        assert report.lost == 0
        # Every recorded request reached a terminal outcome.
        assert len(report.outcomes) == 40
        assert report.served > 0
        # The degradation ladder ordered the pain: interactive fared at
        # least as well as best-effort under overload.
        att = report.attainment(slo_ticks=30)
        assert att["interactive"] >= att["best_effort"]
        # Requeues happened (the kill had in-flight/queued work) and
        # the shed ladder fired.
        assert rs.metrics.requeues_total.value >= 1
        shed = sum(rs.metrics.shed(p).value for p in PRIORITIES)
        assert shed >= 1
        # Flight dumps: the killed replica dumped, and every dump on
        # disk validates against the flight schema.
        dumps = sorted(Path(tmp_path).glob("flight-*.json"))
        assert dumps, "replica death must leave a flight dump"
        for path in dumps:
            rec = validate_flight_dump(json.loads(path.read_text()))
            names = [e["event"] for e in rec["events"]]
            assert names, path

    def test_soak_drives_every_fault_site(self):
        """Vacuous-green guard for the soak itself: the mid-traffic
        schedule exercises every registered FAULT_SITES name."""
        _, report = _mid_traffic_soak(31)
        fired = {site for site, *_ in report.chaos_log}
        assert fired == {s for s, _, _ in FAULT_SITES}


# --------------------------------- requeue-deadline audit (3 seeds)

class TestRequeueDeadlineAudit:
    """ISSUE 11 satellite: the untested corner of PR 4's death/requeue
    path — fuzzed `kill_replica` (via the chaos site) across 3 seeds,
    asserting requeued requests keep their ORIGINAL deadlines, expired
    ones are shed (never served late), and every shed leaves a
    flight-recorder event."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_requeue_keeps_original_deadlines_and_sheds_expired(
        self, seed
    ):
        trace = make_diurnal_trace(
            100 + seed, 30, 10, base_per_tick=1.5, burst_factor=3.0,
        )
        rs = _soak_world(n_replicas=3, S=1, queue_depth=64)
        # Two kills mid-traffic; deadline_skew plants already-expired
        # requests in the queues so the drain path must SHED them.
        chaos = ChaosEngine(seed=seed, schedule=[
            {"site": "replica_kill", "at": 4, "replica": 0},
            {"site": "replica_kill", "at": 8, "replica": 1},
            {"site": "deadline_skew", "every": 6, "value": 0.0},
        ])
        seen = []
        deadlines = {}
        orig = rs.submit_async

        def tracking_submit(payload, **kw):
            out = orig(payload, **kw)
            if not isinstance(out, dict):
                seen.append(out)
                deadlines[id(out)] = out.deadline
            return out

        rs.submit_async = tracking_submit
        report = run_soak(
            rs, _payloads(10, steps=6), trace, chaos=chaos,
        )
        assert report.completed and report.lost == 0
        assert report.kills == 2
        requeued = [p for p in seen if p.requeues >= 1]
        assert requeued, "kills mid-traffic must requeue something"
        for p in seen:
            assert p.deadline == deadlines[id(p)], (
                "a requeue rewrote the request's original deadline"
            )
        # Skewed (already-expired) requests were shed, never served.
        expired = rs.metrics.requests_expired.value
        assert expired >= 1
        assert report.count("expired") == expired
        # Every shed left a flight event across the replica rings.
        shed_events = [
            e for ring in rs.flight_snapshot().values()
            for e in ring["events"] if e["event"] == "shed"
        ]
        assert len(shed_events) >= expired
        assert all(
            e["tags"]["reason"] in
            ("deadline", "priority_evict", "requeue_budget")
            for e in shed_events
        )


# ------------------------------------------------------ chaos sites

class TestChaosSubmitSites:
    def test_cache_miss_storm_forces_full_decode(self):
        """A tier-1 hit is suppressed by the `cache_miss` site: the
        request queues for a real decode instead of short-circuiting
        (tokens unaffected — the stub serves the same caption)."""
        cfg = get_preset("synthetic_smoke")
        cfg.serving.chaos = {
            "seed": 0,
            "schedule": [{"site": "cache_miss", "at": 1}],
        }
        eng = _StubEngine(S=1, cfg=cfg)
        eng.cache.captions.put(
            "chaos-hot", {"caption": "hot", "tokens": [5, 2]}
        )
        rs = ReplicaSet([eng], ServingMetrics())
        assert rs.chaos is not None
        hit = rs.submit_async({"steps": 1, "key": "chaos-hot"})
        assert isinstance(hit, dict) and hit["cached"] is True
        missed = rs.submit_async({"steps": 1, "key": "chaos-hot"})
        assert not isinstance(missed, dict), (
            "the cache_miss storm must force a real decode"
        )
        assert rs.metrics.chaos_faults.value == 1

    def test_deadline_skew_expires_at_admission(self):
        cfg = get_preset("synthetic_smoke")
        cfg.serving.chaos = {
            "seed": 0,
            "schedule": [
                {"site": "deadline_skew", "at": 0, "value": 0.0}
            ],
        }
        eng = _StubEngine(S=1, cfg=cfg)
        rs = ReplicaSet([eng], ServingMetrics())
        p = rs.submit_async({"steps": 1, "key": "skewed"})
        assert p.deadline <= p.t_enqueue


# ----------------------------------------- bench child (subprocess)

class TestBenchSLOChild:
    def test_slo_child_emits_schema_valid_deterministic_rows(self):
        """End-to-end over the REAL bench child (the rows the SLO gate
        reads): the subprocess soak emits schema-valid slo_* extras
        with zero lost requests and a deterministic replay.  Applies
        the PR-7 deterministic skip-with-reason hygiene: an external
        signal or a blown budget on a starved host is an environment
        property, not a code failure — skip with the reason instead of
        going intermittently red."""
        import os
        import subprocess
        import sys

        import bench

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_SLO_CHILD"] = "1"
        env["BENCH_SLO_REQS"] = "16"
        repo = Path(__file__).resolve().parent.parent
        proc = subprocess.Popen(
            [sys.executable, str(repo / "bench.py")],
            cwd=str(repo), env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            out, err = proc.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            pytest.skip(
                "slo soak child exceeded the 300s budget — host too "
                "contended for a subprocess soak"
            )
        if proc.returncode is not None and proc.returncode < 0:
            pytest.skip(
                f"slo soak child killed by external signal "
                f"{proc.returncode} (resource-constrained environment)"
            )
        assert proc.returncode == 0, err[-3000:]
        row = json.loads(out.strip().splitlines()[-1])
        # The extras ride the bench record contract.
        rec = {
            "metric": "m", "value": 1.0, "unit": "u",
            "vs_baseline": 1.0, "extra": row,
        }
        assert bench.validate_record(rec) is rec
        assert row["slo_reference_lost"] == 0.0
        assert row["slo_chaos_lost"] == 0.0
        assert row["slo_chaos_kills"] >= 1.0
        assert row["slo_chaos_stall_ticks"] >= 1.0
        assert row["slo_replay_mismatches"] == 0.0
        assert bench.slo_gate(row) is None


# --------------------------------------------------- hedging (stubs)

class TestHedgingStubs:
    def test_first_result_wins_and_loser_is_cancelled(self):
        """A slow primary triggers a hedge onto the second replica;
        exactly ONE result resolves the submitter, requests_served
        counts once, and the losing copy is discarded."""
        engines = [_StubEngine(S=1), _StubEngine(S=1)]
        rs = ReplicaSet(engines, ServingMetrics(), hedge_ms=5.0)
        results, errors = [], []
        with rs:
            def go():
                try:
                    results.append(rs.submit({"steps": 40, "key": "h"}))
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            t = threading.Thread(target=go)
            t.start()
            t.join(timeout=30.0)
        assert not errors and len(results) == 1
        assert rs.metrics.hedges_total.value == 1
        assert rs.metrics.requests_served.value == 1
        # Both decoders end clean — the loser was evicted/discarded,
        # not leaked.
        for eng in engines:
            assert not eng._decoder.occupied

    def test_hedging_off_by_default(self):
        rs = ReplicaSet([_StubEngine(S=1), _StubEngine(S=1)])
        assert rs.hedge_ms == 0.0
        assert rs._hedge_threshold_s() is None
