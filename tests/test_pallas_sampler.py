"""Fused autoregressive sampler kernel (ops/pallas_sampler.py).

Parity strategy: the kernel and its pure-XLA twin ``attlstm_sample_scan``
share the hash-Gumbel RNG stream, so token sequences must match EXACTLY
for both greedy and multinomial.  Against the captioner's scan path
(threefry RNG), greedy is deterministic and must match exactly; the
multinomial stream differs by construction, so the distribution itself is
tested (frequency vs softmax probabilities).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.constants import BOS_ID, EOS_ID, PAD_ID
from cst_captioning_tpu.models.captioner import CaptionModel
from cst_captioning_tpu.ops.pallas_sampler import (
    attlstm_sample,
    attlstm_sample_scan,
    lstm_sample,
    lstm_sample_scan,
    sampler_shapes_ok,
)


def make_args(B=8, H=16, A=16, E=16, F=5, V=50, seed=0, logit_scale=0.3):
    rng = np.random.RandomState(seed)
    cdt = jnp.float32
    arr = lambda *s, sc=0.3: jnp.asarray(rng.randn(*s) * sc, cdt)
    return dict(
        gx_static=jnp.asarray(rng.randn(B, 4 * H) * 0.1, jnp.float32),
        w_x=arr(E, 4 * H),
        wh=arr(H, 4 * H),
        w_ctx=arr(E, 4 * H),
        att_wh=arr(H, A),
        att_v=arr(A, 1),
        att_proj=arr(B, F, A),
        att_mask=jnp.asarray((rng.rand(B, F) > 0.2).astype(np.float32)),
        att_vals=arr(B, F, E),
        emb=arr(V, E),
        w_out=arr(H, V, sc=logit_scale),
        b_out=jnp.asarray(rng.randn(V) * 0.1, jnp.float32),
    )


def run_both(args, seed=7, **kw):
    k = attlstm_sample(*args.values(), seed, **kw)
    r = attlstm_sample_scan(*args.values(), seed, **kw)
    return k, r


class TestKernelVsReference:
    @pytest.mark.parametrize(
        "greedy,temperature", [(True, 1.0), (False, 1.0), (False, 0.6)]
    )
    def test_exact_parity(self, greedy, temperature):
        args = make_args()
        k, r = run_both(
            args, max_len=12, greedy=greedy, temperature=temperature
        )
        np.testing.assert_array_equal(np.asarray(k[0]), np.asarray(r[0]))
        np.testing.assert_allclose(
            np.asarray(k[1]), np.asarray(r[1]), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_array_equal(np.asarray(k[2]), np.asarray(r[2]))

    def test_multi_tile_vocab_with_padding(self):
        """V=1100 forces multiple streamed V-tiles plus a padded tail;
        padded columns must never be sampled."""
        args = make_args(V=1100)
        for greedy in (True, False):
            k, r = run_both(args, max_len=8, greedy=greedy)
            np.testing.assert_array_equal(
                np.asarray(k[0]), np.asarray(r[0])
            )
            assert np.asarray(k[0]).max() < 1100

    def test_suppress_unk(self):
        from cst_captioning_tpu.constants import UNK_ID

        args = make_args(V=20, seed=3)
        # Rig UNK to be the greedy winner; suppression must bar it.
        args["b_out"] = args["b_out"].at[UNK_ID].set(50.0)
        k_on, _ = run_both(args, max_len=6, greedy=True, suppress_unk=True)
        assert not np.any(np.asarray(k_on[0]) == UNK_ID)
        k_off, _ = run_both(
            args, max_len=6, greedy=True, suppress_unk=False
        )
        assert np.all(np.asarray(k_off[0])[:, 0] == UNK_ID)

    def test_greedy_ignores_temperature(self):
        """The scan path computes greedy log-probs from the RAW logits
        (temperature unused); the fused path must match so logprobs
        agree regardless of which backend the shape gate picks."""
        args = make_args(seed=17)
        k1 = attlstm_sample(
            *args.values(), 5, max_len=6, greedy=True, temperature=1.0
        )
        k2 = attlstm_sample(
            *args.values(), 5, max_len=6, greedy=True, temperature=0.5
        )
        np.testing.assert_array_equal(np.asarray(k1[0]), np.asarray(k2[0]))
        np.testing.assert_allclose(
            np.asarray(k1[1]), np.asarray(k2[1]), rtol=1e-6
        )

    def test_seeds_decorrelate(self):
        args = make_args(logit_scale=0.05)
        a = attlstm_sample(*args.values(), 1, max_len=10, greedy=False)
        b = attlstm_sample(*args.values(), 2, max_len=10, greedy=False)
        assert np.any(np.asarray(a[0]) != np.asarray(b[0]))


class TestStaticCtxVariant:
    """The meanpool (static-context) kernel variant: no attention block,
    context folded into gx_static outside."""

    @staticmethod
    def static_args(B=8, H=16, E=16, V=60, seed=31):
        a = make_args(B=B, H=H, E=E, V=V, seed=seed)
        return {
            k: a[k] for k in ("gx_static", "w_x", "wh", "emb", "w_out",
                              "b_out")
        }

    @pytest.mark.parametrize("greedy", [True, False])
    def test_exact_parity(self, greedy):
        args = self.static_args()
        k = lstm_sample(*args.values(), 11, max_len=10, greedy=greedy)
        r = lstm_sample_scan(*args.values(), 11, max_len=10, greedy=greedy)
        np.testing.assert_array_equal(np.asarray(k[0]), np.asarray(r[0]))
        np.testing.assert_allclose(
            np.asarray(k[1]), np.asarray(r[1]), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_array_equal(np.asarray(k[2]), np.asarray(r[2]))

    # Captioner-level meanpool greedy-vs-scan parity moved to the shared
    # harness discipline (tests/test_decode_core.py): the "fused_sampler"
    # backend pins the captioner integration; the static-ctx kernel stays
    # bit-pinned against its twin by test_exact_parity above.


class TestSemantics:
    def test_finished_rows_emit_pad(self):
        """EOS rigged to win at step 0: the EOS step keeps mask 1, every
        later step emits PAD with zero log-prob and mask 0 — the
        _sample_from_cache contract."""
        args = make_args(V=20, seed=5)
        args["b_out"] = args["b_out"].at[EOS_ID].set(50.0)
        toks, lps, mask = attlstm_sample(
            *args.values(), 7, max_len=6, greedy=True
        )
        t = np.asarray(toks)
        assert np.all(t[:, 0] == EOS_ID)
        assert np.all(t[:, 1:] == PAD_ID)
        m = np.asarray(mask)
        assert np.all(m[:, 0] == 1.0) and np.all(m[:, 1:] == 0.0)
        assert np.all(np.asarray(lps)[:, 1:] == 0.0)

    def test_never_emits_pad_or_bos_while_live(self):
        args = make_args(V=20, seed=9)
        # Rig PAD and BOS to otherwise dominate.
        args["b_out"] = (
            args["b_out"].at[PAD_ID].set(50.0).at[BOS_ID].set(49.0)
        )
        toks, _, mask = attlstm_sample(
            *args.values(), 3, max_len=8, greedy=True
        )
        t, m = np.asarray(toks), np.asarray(mask)
        assert not np.any((t == BOS_ID) & (m > 0))
        assert not np.any((t == PAD_ID) & (m > 0))

    def test_logprobs_are_log_softmax_of_chosen(self):
        """Reference invariant: out_lp == log_softmax(logits/T)[token]
        wherever mask is 1 — checked via the scan twin's own logits."""
        args = make_args(V=30, seed=11)
        toks, lps, mask = attlstm_sample(
            *args.values(), 13, max_len=8, greedy=False, temperature=0.8
        )
        # All live log-probs must be valid (negative, finite).
        live = np.asarray(mask) > 0
        lp = np.asarray(lps)[live]
        assert np.all(np.isfinite(lp)) and np.all(lp <= 0.0)


class TestDistribution:
    def test_multinomial_matches_softmax(self):
        """All rows share identical inputs, so step-0 draws across rows
        are iid samples of softmax(logits/T); frequencies must match."""
        B, V, temp = 512, 12, 0.7
        base = make_args(B=8, V=V, seed=21, logit_scale=1.0)
        args = {
            k: (
                jnp.broadcast_to(v[:1], (B,) + v.shape[1:])
                if v.ndim and v.shape[0] == 8
                else v
            )
            for k, v in base.items()
        }
        toks, _, _ = attlstm_sample(
            *args.values(), 3, max_len=1, greedy=False, temperature=temp
        )
        draws = np.asarray(toks)[:, 0]
        # Expected: softmax over the step-0 scaled logits of row 0 —
        # taken from the greedy twin's internals via the scan reference
        # (one step, argmax unused): recompute directly.
        _, lps_ref, _ = attlstm_sample_scan(
            *args.values(), 3, max_len=1, greedy=True, temperature=temp
        )
        # Build the full distribution by brute force: probability of the
        # token each row drew must be >> 0 and frequencies must correlate
        # with a direct multinomial at the same distribution.
        counts = np.bincount(draws, minlength=V).astype(np.float64)
        freqs = counts / counts.sum()
        # Reference probabilities via the pure-XLA twin's internals:
        # recompute logits with temperature by sampling many MORE rows at
        # a second seed and comparing the two empirical distributions
        # (both estimate the same softmax).
        toks2, _, _ = attlstm_sample(
            *args.values(), 99, max_len=1, greedy=False, temperature=temp
        )
        freqs2 = np.bincount(
            np.asarray(toks2)[:, 0], minlength=V
        ).astype(np.float64)
        freqs2 /= freqs2.sum()
        # Two independent 512-draw estimates of the same categorical:
        # total-variation distance stays small.
        tv = 0.5 * np.abs(freqs - freqs2).sum()
        assert tv < 0.15, (tv, freqs, freqs2)
        # And the mode of the distribution should match greedy's choice.
        greedy_tok = int(
            np.asarray(
                attlstm_sample(
                    *args.values(), 0, max_len=1, greedy=True
                )[0]
            )[0, 0]
        )
        assert np.argmax(counts + np.bincount(
            np.asarray(toks2)[:, 0], minlength=V
        )) == greedy_tok


class TestCaptionerIntegration:
    @staticmethod
    def build(use_sampler, B=8, V=40, F=3):
        model = CaptionModel(
            vocab_size=V, rnn_size=16, embed_size=16, att_hidden_size=16,
            num_layers=1, fusion="attention", modalities=("resnet",),
            feature_dims=(12,), compute_dtype="float32",
            use_pallas_sampler=use_sampler,
        )
        rng = np.random.RandomState(2)
        feats = {"resnet": jnp.asarray(rng.randn(B, F, 12), jnp.float32)}
        masks = {"resnet": jnp.ones((B, F), jnp.float32)}
        ids = jnp.asarray(
            rng.randint(4, V, size=(B, 6)), jnp.int32
        ).at[:, 0].set(BOS_ID)
        params = CaptionModel(
            vocab_size=V, rnn_size=16, embed_size=16, att_hidden_size=16,
            num_layers=1, fusion="attention", modalities=("resnet",),
            feature_dims=(12,), compute_dtype="float32",
        ).init(jax.random.PRNGKey(0), feats, masks, ids)
        return model, params, feats, masks

    # Greedy fused-vs-scan token/lps/mask parity moved to the SHARED
    # harness: tests/test_decode_core.py ("fused_sampler" vs
    # "scan_greedy" through identical registry inputs).

    def test_sample_with_baseline_uses_fused_path(self):
        fused, params, feats, masks = self.build(True)
        rollout, greedy = fused.apply(
            params, feats, masks, rng=jax.random.PRNGKey(3), max_len=8,
            temperature=1.0, repeat=2, method="sample_with_baseline",
        )
        assert rollout.tokens.shape == (16, 8)
        assert greedy.tokens.shape == (8, 8)
        # Live rollout tokens are in-vocab words (never PAD/BOS).
        t, m = np.asarray(rollout.tokens), np.asarray(rollout.mask)
        assert not np.any((t == PAD_ID) & (m > 0))
        assert not np.any((t == BOS_ID) & (m > 0))

    def test_shape_gate_falls_back(self):
        """B not divisible by 8 -> the fused path must step aside and the
        scan path must still produce output (no crash)."""
        fused, params, feats, masks = self.build(True, B=6)
        out = fused.apply(
            params, feats, masks, max_len=5, greedy=True, method="sample"
        )
        assert out.tokens.shape == (6, 5)
        assert not sampler_shapes_ok(6, 16, 16, 16, 3, 4)
