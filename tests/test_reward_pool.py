"""Parallel CIDEr-D reward pool parity: pooled and streamed scoring must
be BIT-IDENTICAL to serial scoring across worker counts and shard
remainders (docs/PARITY.md — the pool shards an order-preserving,
row-independent loop), including degenerate rows (empty hypothesis,
all-EOS) and weighted references."""

import numpy as np
import pytest

from cst_captioning_tpu.constants import EOS_ID
from cst_captioning_tpu.data import make_synthetic_dataset
from cst_captioning_tpu.training.rewards import (
    CiderDRewarder,
    RewardPool,
    make_reward_scorer,
)


@pytest.fixture(scope="module")
def corpus():
    return make_synthetic_dataset(
        num_videos=12, max_frames=6, max_words=10, seed=5
    )


@pytest.fixture(scope="module")
def serial(corpus):
    ds, _ = corpus
    return CiderDRewarder(ds, backend="python")


def make_rows(corpus, n_rows: int, L: int = 9):
    """n_rows candidate rows: mostly reference prefixes (non-zero
    scores), plus an empty-hypothesis row (all PAD) and an all-EOS row
    when there is space for them."""
    ds, vocab = corpus
    rng = np.random.RandomState(7)
    toks = np.zeros((n_rows, L), np.int32)
    vids = rng.randint(0, len(ds), size=(n_rows,)).astype(np.int32)
    for b in range(n_rows):
        ref = ds.references(int(vids[b]))[b % 2].split()
        ids = [vocab.word_to_idx[w] for w in ref][: L - 1]
        toks[b, : len(ids)] = ids
    if n_rows >= 2:
        toks[0, :] = 0       # empty hypothesis: PAD from position 0
        toks[1, :] = 0
        toks[1, 0] = EOS_ID  # all-EOS row: terminates immediately
    return vids, toks


@pytest.mark.parametrize("workers", [1, 2])
@pytest.mark.parametrize("n_rows", [1, 5, 8])
def test_pool_bitexact_vs_serial(corpus, serial, workers, n_rows):
    """Every (workers, rows) combination — including shard remainders
    (5 rows over 2 workers) and fewer rows than workers — must
    concatenate back to the exact serial scores."""
    vids, toks = make_rows(corpus, n_rows)
    want = serial.score_ids(vids, toks)
    with RewardPool(serial, workers) as pool:
        got = pool.score_ids(vids, toks)
    assert got.dtype == np.float32
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
@pytest.mark.parametrize("n_rows", [1, 5, 8, 13])
def test_pool_bitexact_four_workers(corpus, serial, n_rows):
    vids, toks = make_rows(corpus, n_rows)
    want = serial.score_ids(vids, toks)
    with RewardPool(serial, 4) as pool:
        np.testing.assert_array_equal(pool.score_ids(vids, toks), want)


def test_degenerate_rows_score_zero(corpus, serial):
    """Empty-hypothesis and all-EOS rows reduce to a zero-length
    candidate — score must be exactly 0 in both paths, not NaN."""
    vids, toks = make_rows(corpus, 4)
    want = serial.score_ids(vids, toks)
    assert want[0] == 0.0 and want[1] == 0.0
    with RewardPool(serial, 2) as pool:
        got = pool.score_ids(vids, toks)
    np.testing.assert_array_equal(got[:2], [0.0, 0.0])
    np.testing.assert_array_equal(got, want)


def test_stream_feed_order_preserved(corpus, serial):
    """Uneven streamed chunks concatenate in feed order == the serial
    scores of the concatenated rows."""
    vids, toks = make_rows(corpus, 11)
    want = serial.score_ids(vids, toks)
    with RewardPool(serial, 2) as pool:
        st = pool.stream()
        for lo, hi in ((0, 3), (3, 4), (4, 11)):
            st.feed(vids[lo:hi], toks[lo:hi])
        np.testing.assert_array_equal(st.finish(), want)
    # The serial rewarder's eager stream matches too (the overlap-off
    # twin the split step uses when no pool is configured).
    st = serial.stream()
    st.feed(vids[:6], toks[:6])
    st.feed(vids[6:], toks[6:])
    np.testing.assert_array_equal(st.finish(), want)


def test_submit_async_matches_sync(corpus, serial):
    vids, toks = make_rows(corpus, 7)
    want = serial.score_ids(vids, toks)
    with RewardPool(serial, 2) as pool:
        handles = [pool.submit(vids, toks) for _ in range(3)]
        for h in handles:  # persistent pool, repeated async use
            np.testing.assert_array_equal(h.wait(), want)
    np.testing.assert_array_equal(serial.submit(vids, toks).wait(), want)


def test_zero_rows(serial):
    with RewardPool(serial, 2) as pool:
        out = pool.score_ids(
            np.zeros((0,), np.int32), np.zeros((0, 9), np.int32)
        )
    assert out.shape == (0,) and out.dtype == np.float32


def test_weighted_refs_parity(corpus):
    """Per-reference consensus weights must survive the pool boundary."""
    ds, _ = corpus
    rng = np.random.RandomState(3)
    try:
        ds.set_caption_weights({
            ds.video_id(i): rng.uniform(
                0.2, 2.0, size=len(ds.references(i))
            ).astype(np.float32)
            for i in range(len(ds))
        })
        rw = CiderDRewarder(ds, backend="python", weighted_refs=True)
        vids, toks = make_rows(corpus, 8)
        want = rw.score_ids(vids, toks)
        with RewardPool(rw, 2) as pool:
            np.testing.assert_array_equal(pool.score_ids(vids, toks), want)
    finally:
        ds._weight_override = None  # module-scoped fixture


def test_gt_consensus_passthrough(corpus, serial):
    with RewardPool(serial, 2) as pool:
        np.testing.assert_array_equal(
            pool.gt_consensus(), serial.gt_consensus()
        )


def test_make_reward_scorer_gating(corpus, serial):
    """0/1 workers (and non-python backends) keep the serial scorer."""
    assert make_reward_scorer(serial, 0) is serial
    assert make_reward_scorer(serial, 1) is serial
    scorer = make_reward_scorer(serial, 2)
    try:
        assert isinstance(scorer, RewardPool)
        assert scorer.num_workers == 2
    finally:
        scorer.close()
