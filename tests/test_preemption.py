"""Preemption guard: SIGTERM during fit() -> `last` checkpoint + clean
exit; resume restarts the interrupted epoch (SURVEY.md §5 "Failure
detection")."""

import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from cst_captioning_tpu.training.preemption import PreemptionGuard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestGuard:
    def test_flag_latches_and_chains(self):
        PreemptionGuard._reset_for_tests()
        seen = []
        prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
        try:
            guard = PreemptionGuard.install()
            assert not guard.triggered
            os.kill(os.getpid(), signal.SIGTERM)
            assert guard.triggered
            assert seen == [signal.SIGTERM]  # chained to prior handler
            assert PreemptionGuard.install() is guard  # idempotent
        finally:
            PreemptionGuard._reset_for_tests()
            signal.signal(signal.SIGTERM, prev)


WORKER = r"""
import os, sys, threading, time
import jax

jax.config.update("jax_platforms", "cpu")
workdir = sys.argv[1]

from cst_captioning_tpu.config import get_preset
from cst_captioning_tpu.data import make_synthetic_dataset
from cst_captioning_tpu.training import Trainer

from cst_captioning_tpu.training.preemption import PreemptionGuard

cfg = get_preset("synthetic_smoke")
cfg.train.max_epochs = 500          # would run ~forever without the signal
cfg.train.checkpoint_dir = os.path.join(workdir, "ck")
cfg.train.save_checkpoint_every = 10**6   # only the preemption save writes
# Install BEFORE the (slow, jit-compiling) Trainer construction so the
# timer can never race an uninstalled handler; fit()'s install is
# idempotent and returns this same guard.
PreemptionGuard.install()
ds, _ = make_synthetic_dataset(num_videos=16, max_frames=6)
t = Trainer(cfg, train_ds=ds, val_ds=None, workdir=workdir)

# Self-deliver SIGTERM shortly after training starts (simulated eviction).
threading.Timer(3.0, lambda: os.kill(os.getpid(), __import__("signal").SIGTERM)).start()
t.fit()
print("FIT RETURNED CLEANLY")
"""


def test_sigterm_checkpoints_and_resumes(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    workdir = str(tmp_path / "w")
    try:
        res = subprocess.run(
            [sys.executable, "-c", WORKER, workdir],
            env=env, capture_output=True, text=True, timeout=300,
        )
    except subprocess.TimeoutExpired:
        # Deterministic environment gate (PR-6 seed-run flake): the
        # worker compiles a full Trainer before the 3s SIGTERM timer
        # matters; on a heavily contended host that can blow the 300s
        # budget.  Host property, not a preemption-guard failure.
        pytest.skip(
            "preemption worker exceeded the 300s budget — host too "
            "contended for the subprocess smoke"
        )
    if res.returncode < 0:
        # The worker installs its SIGTERM guard BEFORE the timer that
        # self-delivers the signal, so a handled run always exits 0 —
        # any negative return code means an EXTERNAL signal killed it
        # (OOM-killer SIGKILL, CI process-group teardown): the
        # environment reclaiming resources, not a code failure.
        pytest.skip(
            f"preemption worker killed by external signal "
            f"{-res.returncode} (resource-constrained environment)"
        )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "FIT RETURNED CLEANLY" in res.stdout
    assert "preemption checkpoint saved" in (res.stdout + res.stderr)

    # The checkpoint is resumable through the normal path.
    import jax  # noqa: F401  (conftest pinned CPU)
    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.data import make_synthetic_dataset
    from cst_captioning_tpu.training import Trainer
    from cst_captioning_tpu.training.checkpoint import load_infos

    infos = load_infos(os.path.join(workdir, "last"))
    assert "preempted_during" in infos
    assert "steps_done" in infos  # mid-epoch position recorded
    cfg = get_preset("synthetic_smoke")
    cfg.train.checkpoint_dir = os.path.join(str(tmp_path), "ck2")
    cfg.train.max_epochs = int(infos["epoch"]) + 2
    cfg.train.resume = True
    ds, _ = make_synthetic_dataset(num_videos=16, max_frames=6)
    t = Trainer(cfg, train_ds=ds, val_ds=None, workdir=workdir)
    # Resume replays the REMAINDER of the interrupted epoch.
    assert t.start_epoch == int(infos["epoch"])
    assert t._resume_skip_steps == int(infos["steps_done"])
    hist = t.fit()
    assert any(np.isfinite(e["train_loss"]) for e in hist.values())
