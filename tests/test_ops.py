"""Unit tests for ops: LSTM cell vs torch oracle, losses vs closed form.

SURVEY.md §4 unit-test strategy: "LSTM step vs torch (installed, usable as
an oracle for layer math); XE/WXE/PG loss values vs closed-form tiny cases".
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.ops import (
    LSTMWeights,
    init_lstm_weights,
    lstm_step,
    masked_cross_entropy,
    weighted_cross_entropy,
    reward_criterion,
)


class TestLSTMStep:
    def test_matches_torch_lstmcell(self):
        torch = pytest.importorskip("torch")
        rng = np.random.RandomState(0)
        B, D, H = 4, 6, 8
        cell = torch.nn.LSTMCell(D, H)
        # Port torch's weights into our layout: rows [x; h], gates i|f|g|o.
        w_ih = cell.weight_ih.detach().numpy()  # (4H, D)
        w_hh = cell.weight_hh.detach().numpy()  # (4H, H)
        b = (cell.bias_ih + cell.bias_hh).detach().numpy()
        w = np.concatenate([w_ih.T, w_hh.T], axis=0)  # (D+H, 4H)
        weights = LSTMWeights(w=jnp.asarray(w), b=jnp.asarray(b))

        x = rng.randn(B, D).astype(np.float32)
        h = rng.randn(B, H).astype(np.float32)
        c = rng.randn(B, H).astype(np.float32)
        with torch.no_grad():
            th, tc = cell(
                torch.from_numpy(x), (torch.from_numpy(h), torch.from_numpy(c))
            )
        jh, jc = lstm_step(weights, jnp.asarray(x), jnp.asarray(h), jnp.asarray(c))
        np.testing.assert_allclose(np.asarray(jh), th.numpy(), rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(jc), tc.numpy(), rtol=1e-5, atol=1e-5)

    def test_init_shapes_and_forget_bias(self):
        w = init_lstm_weights(jax.random.PRNGKey(0), 6, 8)
        assert w.w.shape == (14, 32) and w.b.shape == (32,)
        np.testing.assert_array_equal(np.asarray(w.b[8:16]), np.ones(8))
        np.testing.assert_array_equal(np.asarray(w.b[:8]), np.zeros(8))

    def test_bfloat16_compute_keeps_c_f32(self):
        w = init_lstm_weights(jax.random.PRNGKey(0), 4, 4)
        x = jnp.ones((2, 4))
        h = jnp.zeros((2, 4), jnp.bfloat16)
        c = jnp.zeros((2, 4), jnp.float32)
        h2, c2 = lstm_step(w, x, h, c, compute_dtype=jnp.bfloat16)
        assert h2.dtype == jnp.bfloat16
        assert c2.dtype == jnp.float32


class TestLosses:
    def test_xe_closed_form(self):
        # Two tokens, vocab 2. Uniform logits -> nll = log 2 per token.
        logits = jnp.zeros((1, 2, 2))
        targets = jnp.array([[0, 1]])
        mask = jnp.ones((1, 2))
        loss = masked_cross_entropy(logits, targets, mask)
        np.testing.assert_allclose(float(loss), np.log(2.0), rtol=1e-6)

    def test_xe_masking_excludes_padding(self):
        logits = jnp.array([[[10.0, 0.0], [0.0, 10.0]]])  # confident: tok0 then tok1
        targets = jnp.array([[0, 0]])  # second target wrong, but masked out
        mask = jnp.array([[1.0, 0.0]])
        loss = masked_cross_entropy(logits, targets, mask)
        assert float(loss) < 1e-3

    def test_xe_perfect_prediction_near_zero(self):
        logits = jnp.full((2, 3, 5), -20.0)
        targets = jnp.array([[1, 2, 3], [4, 0, 2]])
        logits = logits.at[
            jnp.arange(2)[:, None], jnp.arange(3)[None, :], targets
        ].set(20.0)
        loss = masked_cross_entropy(logits, targets, jnp.ones((2, 3)))
        assert float(loss) < 1e-3

    def test_wxe_weights_scale_per_caption(self):
        logits = jnp.zeros((2, 2, 2))
        targets = jnp.zeros((2, 2), jnp.int32)
        mask = jnp.ones((2, 2))
        base = masked_cross_entropy(logits, targets, mask)
        # Weight caption 0 by 2, caption 1 by 0 -> sum = 2*base_half*2 tokens
        w = jnp.array([2.0, 0.0])
        loss = weighted_cross_entropy(logits, targets, mask, w)
        np.testing.assert_allclose(float(loss), float(base), rtol=1e-6)
        # all-ones weights == unweighted
        loss1 = weighted_cross_entropy(logits, targets, mask, jnp.ones(2))
        np.testing.assert_allclose(float(loss1), float(base), rtol=1e-6)

    def test_reward_criterion_closed_form(self):
        lp = jnp.array([[-1.0, -2.0], [-3.0, -4.0]])
        mask = jnp.array([[1.0, 1.0], [1.0, 0.0]])
        adv = jnp.array([1.0, -1.0])
        # -( (−1−2)*1 + (−3)*(−1) ) / 3 = -(−3 + 3)/3 = 0
        loss = reward_criterion(lp, mask, adv)
        np.testing.assert_allclose(float(loss), 0.0, atol=1e-7)
        adv2 = jnp.array([1.0, 0.0])
        loss2 = reward_criterion(lp, mask, adv2)
        np.testing.assert_allclose(float(loss2), 1.0, rtol=1e-6)

    def test_reward_criterion_no_grad_through_advantage(self):
        lp = jnp.array([[-1.0]])
        mask = jnp.ones((1, 1))

        def f(adv):
            return reward_criterion(lp, mask, adv)

        g = jax.grad(f)(jnp.array([2.0]))
        np.testing.assert_allclose(np.asarray(g), np.zeros(1))

    def test_reward_criterion_grad_direction(self):
        # Positive advantage -> gradient pushes logprob up (dloss/dlp < 0).
        mask = jnp.ones((1, 1))

        def f(lp):
            return reward_criterion(lp, mask, jnp.array([1.0]))

        g = jax.grad(f)(jnp.array([[-1.0]]))
        assert float(g[0, 0]) < 0.0
