"""Elastic replica autoscaler (ISSUE 13, serving/autoscaler.py).

Covers the satellite acceptance bars on the PR-11 virtual-time
machinery (stub engines — no jax decode; the real-engine
artifact-vs-warm token pins live in tests/test_artifact.py and the
``slot_decoder_beam_aot`` harness backend):

* off-by-default: every preset's ``AutoscaleConfig.from_config`` is
  None; unknown/invalid keys are named errors;
* scale-UP under a recorded queue burst (through the real
  ``ReplicaSet.add_replica`` router admission), scale-DOWN only after a
  full idle window + cooldown, bounds respected throughout;
* ZERO requests lost across a scale-down drain: the victim's in-flight
  work requeues onto survivors (the PR-4 path) and still serves;
* determinism: the same recorded trace + config replays to a
  byte-identical decision log (the chaos-engine determinism contract
  applied to scaling);
* every applied decision lands as a registered ``autoscale`` flight
  event and on the ``caption_autoscale_*`` metric families.
"""

import pytest

from test_chaos import _StubEngine, _payloads

from cst_captioning_tpu.config import PRESETS
from cst_captioning_tpu.serving.autoscaler import (
    AutoscaleConfig,
    Autoscaler,
    Decision,
    Signals,
)
from cst_captioning_tpu.serving.chaos import make_diurnal_trace, run_soak
from cst_captioning_tpu.serving.metrics import ServingMetrics
from cst_captioning_tpu.serving.replicas import ReplicaSet


def _sig(queued=0, occupied=0, slots=1, healthy=1, shed=0, p99=0.0):
    return Signals(
        queued=queued, occupied=occupied, slots=slots,
        healthy=healthy, shed=shed, queue_wait_p99_ms=p99,
    )


class TestAutoscaleConfig:
    def test_off_by_default_in_every_preset(self):
        for name, mk in PRESETS.items():
            cfg = mk()
            assert AutoscaleConfig.from_config(cfg.serving) is None, (
                f"preset {name} silently enables autoscaling"
            )

    def test_unknown_key_is_a_named_error(self):
        class S:
            autoscale = {"max_replicas": 2, "scale_up_qeue_depth": 1}

        with pytest.raises(ValueError, match="scale_up_qeue_depth"):
            AutoscaleConfig.from_config(S())

    def test_bounds_validated(self):
        with pytest.raises(ValueError, match="max_replicas"):
            AutoscaleConfig(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError, match="min_replicas"):
            AutoscaleConfig(min_replicas=0)


class TestDecisionPolicy:
    """The pure signal-window policy (observe): deterministic in the
    signal stream, hysteretic, bounded."""

    def _scaler(self, **kw):
        cfg = AutoscaleConfig(**{
            "min_replicas": 1, "max_replicas": 3, "window_ticks": 3,
            "scale_up_queue_depth": 4.0, "cooldown_ticks": 4, **kw,
        })
        return Autoscaler(cfg, engine_factory=lambda: _StubEngine(S=1))

    def test_queue_pressure_scales_up(self):
        sc = self._scaler()
        d = sc.observe(_sig(queued=8, healthy=1))
        assert d == Decision("up", "queue_depth", 1, 2)

    def test_shed_inside_window_scales_up(self):
        sc = self._scaler()
        sc.observe(_sig(shed=0))
        d = sc.observe(_sig(shed=2))   # cumulative counter jumped
        assert d.action == "up" and d.reason == "shed"

    def test_at_max_holds_with_named_reason(self):
        sc = self._scaler()
        d = sc.observe(_sig(queued=50, healthy=3))
        assert d.action == "hold" and "at_max" in d.reason

    def test_scale_down_needs_a_full_quiet_window(self):
        sc = self._scaler(scale_down_occupancy=0.5)
        quiet = _sig(queued=0, occupied=0, slots=4, healthy=2)
        assert sc.observe(quiet).action == "hold"    # window filling
        assert sc.observe(quiet).action == "hold"
        d = sc.observe(quiet)                        # window full
        assert d == Decision("down", "idle_window", 2, 1)

    def test_busy_tick_resets_the_quiet_window(self):
        sc = self._scaler(scale_down_occupancy=0.5)
        quiet = _sig(queued=0, occupied=0, slots=4, healthy=2)
        sc.observe(quiet)
        sc.observe(_sig(queued=3, occupied=4, slots=4, healthy=2))
        d = sc.observe(quiet)
        assert d.action == "hold", "a busy tick must not allow shrink"

    def test_cooldown_holds_both_directions(self):
        sc = self._scaler()
        sc._cooldown = 2
        assert sc.observe(_sig(queued=50)).reason == "cooldown"
        assert sc.observe(_sig(queued=50)).reason == "cooldown"
        assert sc.observe(_sig(queued=50)).action == "up"

    def test_never_below_min(self):
        sc = self._scaler(min_replicas=2)
        quiet = _sig(queued=0, occupied=0, slots=2, healthy=2)
        for _ in range(6):
            assert sc.observe(quiet).action != "down"


def _fleet(n=1, queue_depth=64):
    engines = [_StubEngine(S=1) for _ in range(n)]
    return ReplicaSet(engines, ServingMetrics(), queue_depth=queue_depth)


def _soak_with_scaler(seed, *, n_reqs=30, cfg_kw=None):
    trace = make_diurnal_trace(
        seed, n_reqs, 10, base_per_tick=1.5, burst_factor=5.0,
        period_ticks=24,
    )
    rs = _fleet(1)
    cfg = AutoscaleConfig(**{
        "min_replicas": 1, "max_replicas": 3, "window_ticks": 2,
        "scale_up_queue_depth": 2.0, "cooldown_ticks": 3,
        "scale_down_occupancy": 0.9, **(cfg_kw or {}),
    })
    scaler = Autoscaler(cfg, engine_factory=lambda: _StubEngine(S=1))
    report = run_soak(
        rs, _payloads(10, steps=4), trace, autoscaler=scaler,
    )
    return rs, scaler, report


class TestVirtualTimeAutoscale:
    def test_scale_up_under_queue_burst_zero_lost(self):
        rs, scaler, report = _soak_with_scaler(11)
        assert report.completed and report.lost == 0
        ups = [e for e in scaler.decision_log() if e[1] == "up"]
        assert ups, "the burst trace must trigger a scale-up"
        assert len(rs.replicas) > 1
        assert rs.metrics.autoscale_ups.value == len(ups)
        # bounds held through the whole run
        assert all(e[4] <= 3 for e in scaler.decision_log())
        assert rs.healthy_replicas <= 3
        # every recorded request reached a terminal outcome
        assert len(report.outcomes) == 30

    def test_cooldown_spaces_applied_actions(self):
        _, scaler, _ = _soak_with_scaler(11)
        log = scaler.decision_log()
        ticks = [e[0] for e in log]
        assert all(
            b - a > 3 for a, b in zip(ticks, ticks[1:])
        ), f"actions closer than the cooldown: {log}"

    def test_replay_is_byte_identical(self):
        _, s1, r1 = _soak_with_scaler(23)
        _, s2, r2 = _soak_with_scaler(23)
        assert s1.decision_log() == s2.decision_log()
        assert s1.decision_log(), "vacuous replay — nothing was decided"
        assert r1.decisions == r2.decisions
        # both directions exercised: the burst scaled up, the quiet
        # tail scaled back down — and the replay reproduced both.
        actions = {e[1] for e in s1.decision_log()}
        assert actions == {"up", "down"}

    def test_scale_down_drain_loses_nothing(self):
        """A scale-down with IN-FLIGHT work on the victim requeues it
        onto survivors (the PR-4 path) and the request still serves —
        zero loss across the drain."""
        rs = _fleet(2)
        cfg = AutoscaleConfig(
            min_replicas=1, max_replicas=3, window_ticks=2,
            cooldown_ticks=0, scale_down_occupancy=1.0,
        )
        scaler = Autoscaler(cfg, engine_factory=lambda: _StubEngine(S=1))
        # Park a long decode on replica 1 (the deterministic victim:
        # highest healthy rid) with nothing queued anywhere.
        p = rs.submit_async({"steps": 20, "key": "drain-me"})
        with rs._cond:
            for rep in rs.replicas:
                if p in rep.q:
                    rep.q.remove(p)
            p.rid = 1
            rs.replicas[1].q.append(p)
        dec1 = rs.replicas[1].decoder
        with rs._cond:
            pend = rs.replicas[1].q.popleft()
        dec1.tick_begin([pend.prepared], [pend])   # now in flight
        assert dec1.n_occupied == 1
        # Quiet window (occupancy allowed) -> down on the 2nd step.
        d1 = scaler.step(rs, drain_inline=True)
        d2 = scaler.step(rs, drain_inline=True)
        assert (d1.action, d2.action) == ("hold", "down")
        assert not rs.replicas[1].healthy
        assert rs.metrics.requeues_total.value == 1
        # The survivor serves the requeued request to completion.
        for _ in range(40):
            if p.future.done():
                break
            rep0 = rs.replicas[0]
            with rs._cond:
                admits = [
                    rep0.q.popleft() for _ in range(
                        min(len(rep0.q), len(rep0.decoder.free))
                    )
                ]
            handle = rep0.decoder.tick_begin(
                [x.prepared for x in admits], admits
            )
            if handle is None:
                continue
            done = rep0.decoder.tick_wait(handle)
            if done:
                rs._resolve(
                    rep0, rs.metrics.replica(0),
                    rep0.decoder.harvest_from(handle, done),
                )
        assert p.future.done(), "scale-down drain lost the request"
        assert p.future.result()["caption"] == "chaos-stub"
        assert rs.metrics.autoscale_downs.value == 1

    def test_flight_events_and_metric_families(self):
        rs, scaler, _ = _soak_with_scaler(11)
        events = [
            e for e in rs.flight.snapshot()["events"]
            if e["event"] == "autoscale"
        ]
        assert events, "applied decisions must land on the flight ring"
        e = events[0]
        assert e["tags"]["action"] in ("up", "down")
        assert {"reason", "frm", "to", "replica"} <= set(e["tags"])
        text = rs.metrics.to_prometheus()
        assert "caption_autoscale_decisions_total" in text
        assert "caption_autoscale_scale_ups_total" in text
        assert "caption_autoscale_target_replicas" in text
        d = rs.metrics.to_dict()
        assert d["autoscale"]["scale_ups"] >= 1
        assert d["autoscale"]["decisions"] >= 1

    def test_live_control_thread_steps_and_stops_clean(self):
        """The threaded mode the CaptionServer wires: the loop samples
        on its interval, and stop() joins it — no decisions land after
        stop returns."""
        import time

        rs = _fleet(1)
        cfg = AutoscaleConfig(
            window_ticks=1, cooldown_ticks=0, interval_s=0.01,
            scale_up_queue_depth=1e9,   # never actually scales
        )
        scaler = Autoscaler(
            cfg, engine_factory=lambda: _StubEngine(S=1)
        )
        scaler.start(rs)
        deadline = time.monotonic() + 5.0
        while (
            rs.metrics.autoscale_decisions.value < 3
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert rs.metrics.autoscale_decisions.value >= 3
        scaler.stop()
        settled = rs.metrics.autoscale_decisions.value
        time.sleep(0.06)
        assert rs.metrics.autoscale_decisions.value == settled
        assert len(rs.replicas) == 1   # the threshold never tripped

    def test_added_replica_is_routable_and_labeled(self):
        rs = _fleet(1)
        rid = rs.add_replica(_StubEngine(S=2))
        assert rid == 1
        assert rs.healthy_replicas == 2
        assert rs.replicas[1].decoder.S == 2
        # router sees it immediately: least-loaded prefers the roomier
        # fresh replica
        p = rs.submit_async({"steps": 1, "key": "routed"})
        assert p.rid == 1
        d = rs.describe()
        assert len(d["artifact_versions"]) == 2
