"""Pallas LSTM kernel tests (interpret mode on CPU): recurrence parity vs
the scan reference and vs ops.lstm_step, gradient correctness through the
custom VJP, and full-model fused-path equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.ops.pallas_lstm import (
    lstm_recurrence,
    lstm_recurrence_pallas,
    lstm_recurrence_scan,
)
from cst_captioning_tpu.ops.rnn import init_lstm_weights, lstm_step


@pytest.fixture(scope="module")
def problem():
    rng = np.random.RandomState(0)
    B, T, D, H = 16, 6, 12, 8
    w = init_lstm_weights(jax.random.PRNGKey(0), D, H)
    x = jnp.asarray(rng.randn(B, T, D), jnp.float32)
    gx = jnp.einsum("btd,dg->btg", x, w.w[:D]) + w.b
    wh = w.w[D:]
    zeros = jnp.zeros((B, H), jnp.float32)
    return w, x, gx, wh, zeros, (B, T, D, H)


class TestRecurrence:
    def test_scan_matches_lstm_step(self, problem):
        w, x, gx, wh, zeros, (B, T, D, H) = problem
        h_seq = lstm_recurrence_scan(gx, wh)
        h = jnp.zeros((B, H))
        c = jnp.zeros((B, H))
        for t in range(T):
            h, c = lstm_step(w, x[:, t], h, c)
            np.testing.assert_allclose(
                np.asarray(h_seq[:, t]), np.asarray(h), rtol=1e-5, atol=1e-6
            )

    def test_pallas_matches_scan(self, problem):
        _, _, gx, wh, zeros, _ = problem
        ref, ref_c = lstm_recurrence_scan(gx, wh, with_cell=True)
        got, got_c = lstm_recurrence_pallas(gx, wh, with_cell=True,
                                            interpret=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(got_c), np.asarray(ref_c), rtol=1e-5, atol=1e-6
        )

    def test_pallas_odd_time_and_batch_tiles(self):
        rng = np.random.RandomState(1)
        B, T, H = 24, 7, 8  # awkward sizes exercise the tile fallbacks
        wh = jnp.asarray(rng.randn(H, 4 * H) * 0.1, jnp.float32)
        gx = jnp.asarray(rng.randn(B, T, 4 * H), jnp.float32)
        zeros = jnp.zeros((B, H), jnp.float32)
        ref = lstm_recurrence_scan(gx, wh)
        got = lstm_recurrence_pallas(gx, wh, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-6
        )

    def test_custom_vjp_grads_match_scan(self, problem):
        _, _, gx, wh, zeros, _ = problem

        def loss_fused(gx_, wh_):
            return jnp.sum(lstm_recurrence(gx_, wh_, True) ** 2)

        def loss_ref(gx_, wh_):
            return jnp.sum(lstm_recurrence_scan(gx_, wh_) ** 2)

        g1 = jax.grad(loss_fused, argnums=(0, 1))(gx, wh)
        g2 = jax.grad(loss_ref, argnums=(0, 1))(gx, wh)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            )

    def test_jit_wrapped(self, problem):
        _, _, gx, wh, zeros, _ = problem
        f = jax.jit(lambda gx_: lstm_recurrence(gx_, wh, True))
        out = f(gx)
        ref = lstm_recurrence_scan(gx, wh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6
        )


class TestFusedModelPath:
    def test_fused_forward_matches_scan_path(self):
        from cst_captioning_tpu.models import CaptionModel

        rng = np.random.RandomState(3)
        V, B, T, F, D, H = 23, 8, 7, 5, 12, 16
        feats = {"resnet": jnp.asarray(rng.randn(B, F, D), jnp.float32)}
        masks = {"resnet": jnp.ones((B, F))}
        ids = jnp.asarray(rng.randint(4, V, (B, T)), jnp.int32).at[:, 0].set(1)

        def build(use_pallas):
            return CaptionModel(
                vocab_size=V, rnn_size=H, num_layers=2, embed_size=H,
                modalities=("resnet",), feature_dims=(D,), drop_prob=0.0,
                compute_dtype="float32", use_pallas=use_pallas,
            )

        m0, m1 = build(False), build(True)
        params = m0.init(jax.random.PRNGKey(0), feats, masks, ids)
        ref = m0.apply(params, feats, masks, ids)
        got = m1.apply(params, feats, masks, ids)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-4, atol=1e-5
        )

    def test_fused_path_grads_match(self):
        from cst_captioning_tpu.models import CaptionModel
        from cst_captioning_tpu.ops import masked_cross_entropy

        rng = np.random.RandomState(4)
        V, B, T, F, D, H = 23, 8, 7, 5, 12, 16
        feats = {"resnet": jnp.asarray(rng.randn(B, F, D), jnp.float32)}
        masks = {"resnet": jnp.ones((B, F))}
        ids = jnp.asarray(rng.randint(4, V, (B, T)), jnp.int32).at[:, 0].set(1)
        tmask = jnp.ones((B, T - 1))

        def build(use_pallas):
            return CaptionModel(
                vocab_size=V, rnn_size=H, num_layers=1, embed_size=H,
                modalities=("resnet",), feature_dims=(D,), drop_prob=0.0,
                compute_dtype="float32", use_pallas=use_pallas,
            )

        m0, m1 = build(False), build(True)
        params = m0.init(jax.random.PRNGKey(0), feats, masks, ids)

        def loss(model):
            def f(p):
                logits = model.apply(p, feats, masks, ids[:, :-1])
                return masked_cross_entropy(logits, ids[:, 1:], tmask)

            return f

        g0 = jax.grad(loss(m0))(params)
        g1 = jax.grad(loss(m1))(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
            ),
            g0,
            g1,
        )
