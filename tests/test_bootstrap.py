"""parallel/distributed.ensure_initialized: env-driven bootstrap logic
(single-process no-op, explicit coordinator, env-var plumbing) without a
real multi-process rendezvous (that path is covered by
tests/test_distributed.py)."""

from unittest import mock

import pytest

from cst_captioning_tpu.parallel import distributed


@pytest.fixture(autouse=True)
def reset_state(monkeypatch):
    monkeypatch.setattr(distributed, "_INITIALIZED", False)
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID", "TPU_WORKER_HOSTNAMES",
                "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    yield


def test_single_process_is_noop():
    with mock.patch("jax.distributed.initialize") as init:
        distributed.ensure_initialized()
        init.assert_not_called()
    assert not distributed._INITIALIZED


def test_explicit_coordinator_initializes():
    with mock.patch("jax.distributed.initialize") as init:
        distributed.ensure_initialized(
            coordinator_address="host:1234", num_processes=2, process_id=1
        )
        init.assert_called_once_with(
            coordinator_address="host:1234", num_processes=2, process_id=1
        )
    assert distributed._INITIALIZED


def test_env_vars_plumb_through(monkeypatch):
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "envhost:9")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "0")  # rank 0 must survive `or`
    with mock.patch("jax.distributed.initialize") as init:
        distributed.ensure_initialized()
        init.assert_called_once_with(
            coordinator_address="envhost:9", num_processes=4, process_id=0
        )


def test_idempotent():
    with mock.patch("jax.distributed.initialize") as init:
        distributed.ensure_initialized(
            coordinator_address="host:1", num_processes=2, process_id=0
        )
        distributed.ensure_initialized(
            coordinator_address="host:1", num_processes=2, process_id=0
        )
        assert init.call_count == 1


def test_tpu_pod_env_triggers_autodetect(monkeypatch):
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0,w1")
    with mock.patch("jax.distributed.initialize") as init:
        distributed.ensure_initialized()
        init.assert_called_once_with(
            coordinator_address=None, num_processes=None, process_id=None
        )
