"""Golden tests for BLEU / ROUGE-L / CIDEr-D / METEOR-lite (SURVEY.md §4:
"CiderD golden scores vs hand-cooked tiny corpus")."""

import math

import numpy as np
import pytest

from cst_captioning_tpu.metrics.bleu import Bleu
from cst_captioning_tpu.metrics.cider import (
    Cider,
    CiderD,
    ciderd_score_cooked,
    compute_doc_freq,
    precook,
    save_df,
)
from cst_captioning_tpu.metrics.meteor import MeteorLite
from cst_captioning_tpu.metrics.rouge import Rouge, _lcs_len
from cst_captioning_tpu.metrics.evaluator import language_eval


GTS = {
    "v1": ["a man is playing a guitar", "a man plays a guitar",
           "someone is playing music"],
    "v2": ["a dog runs in the park", "the dog is running outside",
           "a dog runs around"],
    "v3": ["a woman is cooking food", "a woman cooks in a kitchen",
           "someone is cooking a meal"],
}
RES_PERFECT = {"v1": ["a man is playing a guitar"],
               "v2": ["a dog runs in the park"],
               "v3": ["a woman is cooking food"]}
RES_PARTIAL = {"v1": ["a man is playing music"],
               "v2": ["a cat sleeps on the sofa"],
               "v3": ["a woman is cooking food"]}


# ------------------------------------------------------------------- BLEU

def test_bleu_perfect():
    scores, seg = Bleu(4).compute_score(GTS, RES_PERFECT)
    assert all(abs(s - 1.0) < 1e-6 for s in scores)
    assert len(seg[3]) == 3


def test_bleu_hand_computed_unigram():
    gts = {"a": ["the cat sat on the mat"]}
    res = {"a": ["the cat the cat"]}
    scores, _ = Bleu(1).compute_score(gts, res)
    # clipped unigram matches: "the"x2, "cat"x1 -> 3/4; BP=exp(1-6/4)
    assert scores[0] == pytest.approx(0.75 * math.exp(1 - 6 / 4), rel=1e-6)


def test_bleu_order():
    s_good, _ = Bleu(4).compute_score(GTS, RES_PERFECT)
    s_bad, _ = Bleu(4).compute_score(GTS, RES_PARTIAL)
    assert s_good[3] > s_bad[3]


# ---------------------------------------------------------------- ROUGE-L

def test_lcs():
    assert _lcs_len("abcde", "ace") == 3
    assert _lcs_len([], "abc") == 0


def test_rouge_perfect():
    score, seg = Rouge().compute_score(GTS, RES_PERFECT)
    assert score == pytest.approx(1.0)
    assert seg.shape == (3,)


def test_rouge_hand_computed():
    gts = {"a": ["the cat sat on the mat"]}
    res = {"a": ["the cat on the mat"]}
    # LCS=5, P=5/5=1, R=5/6; F = (1+b^2)PR/(R+b^2 P), beta=1.2
    p, r, b = 1.0, 5 / 6, 1.2
    expect = (1 + b * b) * p * r / (r + b * b * p)
    score, _ = Rouge().compute_score(gts, res)
    assert score == pytest.approx(expect, rel=1e-9)


# ------------------------------------------------------------------ CIDEr

def test_cider_perfect_greater_than_partial():
    d = CiderD()
    s_good, _ = d.compute_score(GTS, RES_PERFECT)
    s_bad, _ = d.compute_score(GTS, RES_PARTIAL)
    assert s_good > s_bad > 0


def test_ciderd_identity_score_single_ngram_corpus():
    """Hand-checkable case: every video has one ref; candidate == ref.

    With 3 distinct single-sentence refs, cosine similarity per order is 1
    wherever the candidate has ngrams with nonzero idf, giving score 10 per
    matching order; orders with all-zero idf vectors contribute 0.
    """
    gts = {"a": ["x y z"], "b": ["p q r"], "c": ["m n o"]}
    res = {"a": ["x y z"], "b": ["p q r"], "c": ["m n o"]}
    score, seg = CiderD().compute_score(gts, res)
    # all ngrams unique to each video: df=1, idf=log(3); norms match exactly
    # orders 1..3 exist (len-3 sentence has no 4-gram) -> mean over 4 orders
    assert seg[0] == pytest.approx(10.0 * 3 / 4, rel=1e-6)


def test_ciderd_length_penalty():
    gts = {"a": ["a b c d e f g h"], "b": ["z z z z"]}
    res_same = {"a": ["a b c d e f g h"], "b": ["z z z z"]}
    res_short = {"a": ["a b c"], "b": ["z z z z"]}
    s_same, seg_same = CiderD().compute_score(gts, res_same)
    s_short, seg_short = CiderD().compute_score(gts, res_short)
    assert seg_same[0] > seg_short[0]


def test_cider_vs_ciderd_differ_on_repeats():
    # plain CIDEr doesn't clip counts; repeating a rare ngram inflates it.
    gts = {"a": ["a b a b"], "b": ["c d e f"]}
    res = {"a": ["a b a b a b a b"], "b": ["c d e f"]}
    c, _ = Cider().compute_score(gts, res)
    cd, _ = CiderD().compute_score(gts, res)
    assert c != pytest.approx(cd)


def test_precook_counts():
    c = precook("a b a".split())
    assert c[("a",)] == 2 and c[("b",)] == 1
    assert c[("a", "b")] == 1 and c[("b", "a")] == 1
    assert c[("a", "b", "a")] == 1


def test_doc_freq():
    crefs = [[precook("a b".split()), precook("a c".split())],
             [precook("a d".split())]]
    df = compute_doc_freq(crefs)
    assert df[("a",)] == 2  # appears in both videos' ref sets
    assert df[("b",)] == 1


def test_saved_df_roundtrip(tmp_path):
    path = str(tmp_path / "df.json")
    save_df(GTS, path)
    d1 = CiderD(df_mode=path)
    s1, _ = d1.compute_score(GTS, RES_PARTIAL)
    s2, _ = CiderD().compute_score(GTS, RES_PARTIAL)
    assert s1 == pytest.approx(s2, rel=1e-9)


def test_cooked_scoring_matches_string_path():
    """The RL hot-path entry (cooked counters) must agree with the string API."""
    crefs = [[precook(c.split()) for c in caps] for caps in
             (GTS[k] for k in sorted(GTS))]
    df = compute_doc_freq(crefs)
    log_n = math.log(len(crefs))
    keys = sorted(GTS)
    for i, k in enumerate(keys):
        cooked = ciderd_score_cooked(precook(RES_PARTIAL[k][0].split()),
                                     crefs[i], df, log_n)
        _, seg = CiderD().compute_score(GTS, RES_PARTIAL)
        assert cooked == pytest.approx(seg[i], rel=1e-9)


# ----------------------------------------------------------------- METEOR

def test_meteor_lite_orders_correctly():
    m = MeteorLite()
    s_good, _ = m.compute_score(GTS, RES_PERFECT)
    s_bad, _ = m.compute_score(GTS, RES_PARTIAL)
    assert s_good > s_bad > 0


def test_meteor_stem_match():
    m = MeteorLite()
    gts = {"a": ["a man is running fast"]}
    res_stem = {"a": ["a man is run fast"]}     # "run" stem-matches "running"
    res_miss = {"a": ["a man is xyz fast"]}
    s_stem, _ = m.compute_score(gts, res_stem)
    s_miss, _ = m.compute_score(gts, res_miss)
    assert s_stem > s_miss


class TestMeteorGolden:
    """Hand-computed golden values pinning the METEOR-lite math
    (alpha=0.85, gamma=0.6, frag_exp=3, stage weights 1.0/0.6/0.8)."""

    def test_identity(self):
        # 6 exact matches, 1 chunk: fmean=1, penalty=0.6*(1/6)^3.
        m = MeteorLite()
        s, _ = m.compute_score(
            {"a": ["the cat sat on the mat"]},
            {"a": ["the cat sat on the mat"]},
        )
        assert s == pytest.approx(1.0 - 0.6 * (1 / 6) ** 3, rel=1e-9)

    def test_precision_recall_fmean(self):
        # hyp "the cat" vs ref "the cat sat": P=1, R=2/3, m=2, ch=1.
        p, r = 1.0, 2 / 3
        fmean = p * r / (0.85 * p + 0.15 * r)
        expect = fmean * (1 - 0.6 * 0.5**3)
        s, _ = MeteorLite().compute_score(
            {"a": ["the cat sat"]}, {"a": ["the cat"]}
        )
        assert s == pytest.approx(expect, rel=1e-9)

    def test_stem_weight(self):
        # "cats"~"cat" stem match w=0.6: wm=1.6, P=R=0.8, m=2, ch=1.
        expect = 0.8 * (1 - 0.6 * 0.5**3)
        s, _ = MeteorLite().compute_score(
            {"a": ["the cat"]}, {"a": ["the cats"]}
        )
        assert s == pytest.approx(expect, rel=1e-9)

    def test_fragmentation_penalty(self):
        # "b a" vs "a b": 2 exact matches in 2 chunks: penalty=0.6*1^3.
        s, _ = MeteorLite().compute_score({"a": ["a b"]}, {"a": ["b a"]})
        assert s == pytest.approx(1.0 - 0.6, rel=1e-9)

    def test_synonym_stage(self, tmp_path):
        import json

        path = tmp_path / "syn.json"
        path.write_text(json.dumps({"feline": ["cat"]}))
        m = MeteorLite(synonym_file=str(path))
        # "a feline" vs "a cat": exact + synonym (w=0.8): wm=1.8,
        # P=R=0.9, m=2, ch=1.
        s, _ = m.compute_score({"a": ["a cat"]}, {"a": ["a feline"]})
        assert s == pytest.approx(0.9 * (1 - 0.6 * 0.5**3), rel=1e-9)
        # symmetric closure: the table entry works in either direction
        s2, _ = m.compute_score({"a": ["a feline"]}, {"a": ["a cat"]})
        assert s2 == pytest.approx(s, rel=1e-9)
        # with the synonym matcher disabled the token goes unmatched
        # (the VENDORED default table also knows cat~feline, so the
        # control must disable the stage, not just drop the custom file)
        s_no, _ = MeteorLite(synonym_file="none").compute_score(
            {"a": ["a cat"]}, {"a": ["a feline"]}
        )
        assert s_no < s
        # ... and the vendored default table matches it out of the box
        s_default, _ = MeteorLite().compute_score(
            {"a": ["a cat"]}, {"a": ["a feline"]}
        )
        assert s_default == pytest.approx(s, rel=1e-9)

    def test_banerjee_lavie_2005_worked_example(self):
        """External golden: the chunk-penalty worked example of the
        METEOR paper (Banerjee & Lavie 2005, §3.1) under THAT paper's
        constants (Fmean = 10PR/(R+9P) i.e. alpha=0.9; penalty =
        0.5*(chunks/matches)^3).  hyp 'the president spoke to the
        audience' vs ref 'the president then spoke to the audience':
        6 matches in 2 chunks ('the president' / 'spoke to the
        audience')."""
        m = MeteorLite(synonym_file="none", alpha=0.9, gamma=0.5,
                       frag_exp=3.0)
        p, r = 6 / 6, 6 / 7
        fmean = 10 * p * r / (r + 9 * p)            # = 60/69
        expect = fmean * (1 - 0.5 * (2 / 6) ** 3)
        s, _ = m.compute_score(
            {"a": ["the president then spoke to the audience"]},
            {"a": ["the president spoke to the audience"]},
        )
        assert s == pytest.approx(expect, rel=1e-9)

    def test_banerjee_lavie_2005_identity(self):
        """External golden: identical sentences align as ONE chunk, so
        the 2005 penalty is 0.5*(1/6)^3 — the paper's 'as the number of
        chunks goes to 1 the penalty vanishes' behavior."""
        m = MeteorLite(synonym_file="none", alpha=0.9, gamma=0.5,
                       frag_exp=3.0)
        s, _ = m.compute_score(
            {"a": ["the president spoke to the audience"]},
            {"a": ["the president spoke to the audience"]},
        )
        assert s == pytest.approx(1 - 0.5 * (1 / 6) ** 3, rel=1e-9)

    def test_corpus_aggregation(self):
        # Corpus score recomputes from summed statistics, not mean of
        # per-segment scores (jar EVAL semantics).
        m = MeteorLite()
        gts = {"a": ["the cat"], "b": ["a dog runs"]}
        res = {"a": ["the cat"], "b": ["a dog sleeps"]}
        # seg a: wm=2, m=2, ch=1, lh=lr=2; seg b: wm=2, m=2, ch=1,
        # lh=lr=3.  Aggregate: P=R=4/5, m=4, ch=2.
        expect = 0.8 * (1 - 0.6 * 0.5**3)
        s, seg = m.compute_score(gts, res)
        assert s == pytest.approx(expect, rel=1e-9)
        assert len(seg) == 2


class TestMeteor15Delta:
    """METEOR 1.3/1.5 function-word (delta) weighting, against values
    derived in closed form from the published formula (Denkowski & Lavie
    2011 §3-4: matches weighted delta for content / 1-delta for function
    words on each side; penalty gamma*(ch/m)^beta with the tuned English
    alpha=0.85, beta=0.2, gamma=0.6, delta=0.75)."""

    def _lite(self, **kw):
        from cst_captioning_tpu.metrics.meteor import MeteorLite

        kw.setdefault("synonym_file", "none")
        return MeteorLite.meteor15_en(**kw)

    def test_identical_sentence_closed_form(self):
        # hyp == ref = "the cat sat on the mat": 6 exact matches in one
        # chunk -> P = R = 1, fmean = 1, penalty = 0.6 * (1/6)^0.2.
        m = self._lite()
        score, _ = m.compute_score(
            {"0": ["the cat sat on the mat"]},
            {"0": ["the cat sat on the mat"]},
        )
        expected = 1.0 - 0.6 * (1.0 / 6.0) ** 0.2
        assert abs(score - expected) < 1e-9

    def test_function_word_miss_discounted(self):
        # "a" vs "the" is a FUNCTION-word miss: content words dog/runs
        # match.  delta config: P = R = (2*0.75) / (0.25 + 2*0.75) =
        # 0.857... vs the unweighted 2/3 — the miss costs ~3x less.
        from cst_captioning_tpu.metrics.meteor import MeteorLite

        delta = self._lite()
        classic = MeteorLite(synonym_file="none", frag_exp=0.2)
        gts = {"0": ["the dog runs"]}
        res = {"0": ["a dog runs"]}
        s_delta, _ = delta.compute_score(gts, res)
        s_classic, _ = classic.compute_score(gts, res)
        p_delta = (2 * 0.75) / (0.25 + 2 * 0.75)
        assert s_delta > s_classic
        # closed form: fmean = p (P == R), m=2 matches, ch=1 chunk.
        fmean = p_delta
        expected = fmean * (1 - 0.6 * (1 / 2) ** 0.2)
        assert abs(s_delta - expected) < 1e-9

    def test_content_word_miss_costs_more(self):
        # "dog" vs "cat" is a CONTENT miss: only the/runs match ->
        # P = R = (0.25 + 0.75) / 1.75 ~ 0.571 < unweighted 2/3.
        from cst_captioning_tpu.metrics.meteor import MeteorLite

        delta = self._lite()
        classic = MeteorLite(synonym_file="none", frag_exp=0.2)
        gts = {"0": ["the cat runs"]}
        res = {"0": ["the dog runs"]}
        s_delta, _ = delta.compute_score(gts, res)
        s_classic, _ = classic.compute_score(gts, res)
        assert s_delta < s_classic

    def test_delta_orders_function_vs_content_miss(self):
        # Same edit distance, different word class: the function-word
        # miss must strictly outscore the content-word miss under delta.
        m = self._lite()
        s_func, _ = m.compute_score(
            {"0": ["the dog runs"]}, {"0": ["a dog runs"]}
        )
        s_cont, _ = m.compute_score(
            {"0": ["the cat runs"]}, {"0": ["the dog runs"]}
        )
        assert s_func > s_cont

    def test_default_configuration_unchanged(self):
        # The default MeteorLite must stay the classic unweighted scorer
        # (delta off) so earlier rounds' stamped scores remain comparable.
        from cst_captioning_tpu.metrics.meteor import MeteorLite

        m = MeteorLite(synonym_file="none")
        assert m.delta is None
        score, _ = m.compute_score(
            {"0": ["the cat sat"]}, {"0": ["the cat sat"]}
        )
        expected = 1.0 - 0.6 * (1.0 / 3.0) ** 3.0  # gamma=0.6, beta=3
        assert abs(score - expected) < 1e-9


class TestMeteorAlignment:
    """The alignment is a beam search minimizing chunks among
    max-match alignments (the jar's objective) — these are the
    adversarial cases where greedy left-to-right matching picks a
    chunk-suboptimal alignment (VERDICT r2 #4)."""

    def test_duplicate_word_prefers_chunk_minimal_slot(self):
        from cst_captioning_tpu.metrics.meteor import _align

        # hyp 'a b' vs ref 'a x a b': greedy binds hyp 'a' to ref[0]
        # (2 chunks); the optimum binds it to ref[2] -> ONE chunk.
        wm_h, wm_r, m, ch = _align(["a", "b"], ["a", "x", "a", "b"])
        assert (m, ch) == (2, 1)
        assert wm_h == pytest.approx(2.0)

    def test_never_trades_a_match_for_a_chunk(self):
        from cst_captioning_tpu.metrics.meteor import _align

        # Dropping hyp 'a' would leave one perfect chunk, but matches
        # dominate chunks lexicographically.
        wm_h, _, m, ch = _align(["a", "b"], ["b", "q", "r", "s", "a"])
        assert (m, ch) == (2, 2)

    def test_crossing_alignment_counts_chunks(self):
        from cst_captioning_tpu.metrics.meteor import _align

        # 'a b c' vs 'b c x a': best is a->3 (chunk), b,c->0,1 (chunk).
        _, _, m, ch = _align(["a", "b", "c"], ["b", "c", "x", "a"])
        assert (m, ch) == (3, 2)

    def test_stem_and_exact_compete_for_one_slot(self):
        from cst_captioning_tpu.metrics.meteor import _align

        # ref has ONE 'run' slot; hyp 'run running': exact pair gets the
        # surface slot, the other hyp word stem-matches nothing else ->
        # weight must be 1.0 + 0 (not 0.6 + ...): total m=1.
        wm_h, _, m, ch = _align(["run"], ["running"])
        assert m == 1 and wm_h == pytest.approx(0.6)  # stem-only pair
        wm_h2, _, m2, _ = _align(["run", "running"], ["running", "run"])
        # both surface forms present: two EXACT matches (w=1 each),
        # beam must not settle for stem pairings
        assert m2 == 2 and wm_h2 == pytest.approx(2.0)

    def test_surface_equal_pair_never_scores_as_synonym(self):
        from cst_captioning_tpu.metrics.meteor import _align

        # ADVICE r2 #5: with a synonym table containing the word itself,
        # a surface-identical residual pair must weigh W_EXACT, not
        # W_SYN.
        syn = {"cat": frozenset({"cat", "feline"})}
        wm_h, _, m, _ = _align(["cat"], ["cat"], synonyms=syn)
        assert m == 1 and wm_h == pytest.approx(1.0)


class TestMeteorJavaProtocol:
    """MeteorJava's stdin/stdout protocol, tested end-to-end against a
    mock `java` executable that speaks the meteor-1.5 -stdio protocol —
    the wrapper (arg order, SCORE/EVAL framing, flushing, key ordering)
    is exercised without a JRE."""

    FAKE_JAVA = r"""#!/usr/bin/env python3
import sys
args = sys.argv
assert "-stdio" in args and "-jar" in args, args
for line in sys.stdin:
    line = line.rstrip("\n")
    if line.startswith("SCORE"):
        parts = line.split(" ||| ")
        refs, hyp = parts[1:-1], parts[-1]
        h = set(hyp.split())
        best = max(
            len(h & set(r.split())) / max(len(set(r.split())), 1)
            for r in refs
        )
        print(f"stat {best}")
        sys.stdout.flush()
    elif line.startswith("EVAL"):
        parts = line.split(" ||| ")[1:]
        segs = [float(p.split()[1]) for p in parts]
        for s in segs:
            print(s)
        print(sum(segs) / len(segs))
        sys.stdout.flush()
"""

    def test_wrapper_round_trip(self, tmp_path, monkeypatch):
        import os
        import stat as stat_mod

        from cst_captioning_tpu.metrics.meteor import Meteor

        fake = tmp_path / "java"
        fake.write_text(self.FAKE_JAVA)
        fake.chmod(fake.stat().st_mode | stat_mod.S_IEXEC)
        jar = tmp_path / "meteor-1.5.jar"
        jar.write_bytes(b"")
        monkeypatch.setenv(
            "PATH", f"{tmp_path}{os.pathsep}{os.environ['PATH']}"
        )
        monkeypatch.setenv("METEOR_JAR", str(jar))

        m = Meteor()
        try:
            assert m.backend_name == "java"
            gts = {
                "b": ["a dog runs fast", "a dog sprints"],
                "a": ["a cat sits"],
            }
            res = {"b": ["a dog runs fast"], "a": ["zzz qqq"]}
            final, segs = m.compute_score(gts, res)
            # keys sort as ("a", "b"): segment 0 is the garbage hyp,
            # segment 1 the exact match.
            assert segs[0] == pytest.approx(0.0)
            assert segs[1] == pytest.approx(1.0)
            assert final == pytest.approx(0.5)
            # second EVAL on the same process (the wrapper keeps one
            # subprocess alive across calls)
            final2, _ = m.compute_score(
                {"x": ["hello world"]}, {"x": ["hello world"]}
            )
            assert final2 == pytest.approx(1.0)
        finally:
            if m.backend_name == "java":
                m.backend.close()


class TestMeteorJarDiff:
    """tools/meteor_jar_diff.py: the one-command jar-vs-lite parity
    harness (VERDICT r4 #7).  Blocked path without a JRE; computed path
    against the TestMeteorJavaProtocol mock."""

    def test_blocked_without_jar(self, monkeypatch, capsys):
        import json as json_mod

        from cst_captioning_tpu.tools.meteor_jar_diff import main

        monkeypatch.delenv("METEOR_JAR", raising=False)
        rc = main([])
        assert rc == 2
        out = json_mod.loads(capsys.readouterr().out.strip())
        assert "blocked" in out

    def test_diff_against_mock_jar(self, tmp_path, monkeypatch, capsys):
        import json as json_mod
        import os
        import stat as stat_mod

        from cst_captioning_tpu.tools.meteor_jar_diff import main

        fake = tmp_path / "java"
        fake.write_text(TestMeteorJavaProtocol.FAKE_JAVA)
        fake.chmod(fake.stat().st_mode | stat_mod.S_IEXEC)
        jar = tmp_path / "meteor-1.5.jar"
        jar.write_bytes(b"")
        monkeypatch.setenv(
            "PATH", f"{tmp_path}{os.pathsep}{os.environ['PATH']}"
        )
        monkeypatch.setenv("METEOR_JAR", str(jar))
        rc = main([])
        assert rc == 0
        out = json_mod.loads(capsys.readouterr().out.strip().splitlines()[-1])
        for key in ("corpus_java", "corpus_lite", "corpus_abs_delta",
                    "seg_abs_delta_max", "worst_segments"):
            assert key in out
        assert out["segments"] > 5


# -------------------------------------------------------------- evaluator

def test_meteor_backend_stamped():
    out = language_eval(GTS, RES_PARTIAL, metrics=["METEOR"])
    assert out["METEOR_backend"] in ("java", "lite", "lite+syn")


def test_language_eval_suite():
    out = language_eval(GTS, RES_PARTIAL)
    for k in ("Bleu_1", "Bleu_4", "METEOR", "ROUGE_L", "CIDEr"):
        assert k in out
        assert 0.0 <= float(out[k]) <= 10.0 * (k == "CIDEr") + 1.0 or k == "CIDEr"
    assert out["Bleu_1"] >= out["Bleu_4"]
