"""CST-MET: Prometheus metric-name registry lint.

Dashboards and the bench sweeps scrape ``/metrics`` by NAME; a renamed,
duplicated, or undocumented series breaks them silently.  The registry
(``serving/metrics.py::METRIC_FAMILIES`` — runtime-visible, next to the
emitters) is the single source of truth; these rules keep it honest:

* CST-MET-001 — a ``caption_*`` name emitted anywhere in ``serving/``
  that matches no registered family (f-string placeholders normalize to
  ``*``, label blocks and space-separated values are stripped);
* CST-MET-002 — a registered family missing from docs/SERVING.md (the
  docs table must name every family verbatim);
* CST-MET-003 — a family registered more than once, or two registered
  patterns that shadow each other exactly.

``serving/metrics.py`` is stdlib-only by design, so importing the
registry here keeps the analysis pass jax-free.
"""

from __future__ import annotations

import ast
import re
from fnmatch import fnmatchcase
from typing import List, Optional, Tuple

from cst_captioning_tpu.analysis.astutil import ModuleInfo
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    register_checker,
)

_NAME_RE = re.compile(r"^caption_[a-z0-9_*]+$")
REGISTRY_FILE = "serving/metrics.py"
DOC_FILE = "SERVING.md"


def _load_registry() -> List[Tuple[str, str]]:
    from cst_captioning_tpu.serving.metrics import METRIC_FAMILIES

    return list(METRIC_FAMILIES)


def _normalize(raw: str) -> Optional[str]:
    """A candidate emitted-name literal -> canonical family string.
    Placeholders are already ``*``; strip the label block and anything
    after the first space, then the exposition suffixes."""
    name = raw.split("{", 1)[0].split(" ", 1)[0]
    for suffix in ("_bucket", "_count", "_sum"):
        if name.endswith(suffix):
            name = name[: -len(suffix)]
    if not _NAME_RE.match(name):
        return None
    return name


def _literal_strings(mi: ModuleInfo):
    """(string value, line) for every Constant str and every JoinedStr
    with FormattedValues replaced by ``*`` — skipping docstrings (prose
    mentions are documentation, not emission)."""
    skip = set()
    for node in ast.walk(mi.tree):
        if isinstance(
            node,
            (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
        ):
            body = node.body
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                skip.add(id(body[0].value))
        elif isinstance(node, ast.JoinedStr):
            # constant fragments of an f-string are surfaced via the
            # normalized JoinedStr, not as bare literals
            for v in node.values:
                skip.add(id(v))
    for node in ast.walk(mi.tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and id(node) not in skip
        ):
            yield node.value, node.lineno
        elif isinstance(node, ast.JoinedStr):
            parts = []
            for v in node.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                else:
                    parts.append("*")
            yield "".join(parts), node.lineno


@register_checker("metrics_registry")
def check(modules: List[ModuleInfo], ctx: CheckContext) -> List[Finding]:
    out: List[Finding] = []
    registry = _load_registry()

    # MET-003: duplicate registration
    seen = {}
    for i, (pattern, typ) in enumerate(registry):
        if pattern in seen:
            out.append(Finding(
                "CST-MET-003", REGISTRY_FILE, 1,
                f"METRIC_FAMILIES[{i}]",
                f"metric family `{pattern}` registered more than once",
            ))
        seen[pattern] = typ

    patterns = [p for p, _ in registry]

    # MET-001: every emitted caption_* literal matches a family
    for mi in modules:
        if not mi.rel.startswith("serving/"):
            continue
        for raw, line in _literal_strings(mi):
            name = _normalize(raw)
            if name is None:
                continue
            if not any(fnmatchcase(name, p) or name == p for p in patterns):
                out.append(Finding(
                    "CST-MET-001", mi.rel, line, name,
                    f"emitted metric name `{name}` matches no "
                    "registered family — register it in "
                    "serving/metrics.py::METRIC_FAMILIES and document "
                    "it in docs/SERVING.md",
                ))

    # MET-002: every family documented in docs/SERVING.md
    if ctx.docs_root is not None:
        doc_path = ctx.docs_root / DOC_FILE
        doc_text = doc_path.read_text() if doc_path.exists() else ""
        for pattern, _typ in registry:
            if pattern not in doc_text:
                out.append(Finding(
                    "CST-MET-002", REGISTRY_FILE, 1, pattern,
                    f"registered metric family `{pattern}` is not "
                    f"documented in docs/{DOC_FILE} — scrape consumers "
                    "discover names there; add it to the metrics table",
                ))
    return out
