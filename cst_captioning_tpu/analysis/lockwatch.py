"""Dynamic twin of the CST-THR-001 static rule: instrumented locks that
record the REAL acquisition order under traffic.

The static pass proves the lock graph acyclic for the paths it can see;
this harness proves it for the paths that actually ran.  Usage (the
tier-1 pattern, tests/test_lockwatch.py)::

    watch = LockWatch()
    with watch.patched():            # threading.Lock/RLock/Condition
        batcher = ContinuousBatcher(engine)   # builds instrumented locks
    batcher.start(); ...traffic...; batcher.stop()
    watch.assert_acyclic()           # raises listing any cycle

Locks created while patched stay instrumented after the context exits —
``patched()`` only bounds WHICH constructors are wrapped, not for how
long recording runs, so worker threads started later keep feeding the
graph.  Each lock is labelled with its construction site
(``file:line``); an edge A→B means some thread acquired B while holding
A, recorded with the acquiring site.  A cycle in that digraph is a
lock-order inversion: two threads interleaving those paths can deadlock
even if this run didn't.

The wrapper keeps a per-thread stack of held locks (reentrant RLock
holds collapse to one entry).  ``threading.Condition.wait`` releases
and reacquires through the lock object's own ``acquire``/``release``
(we pass a plain wrapped Lock, so the stdlib Condition uses exactly
those), which keeps the stack truthful across waits.
"""

from __future__ import annotations

import threading
import traceback
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, List, Optional, Set, Tuple

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition


def _creation_site(skip_substrings=("lockwatch.py", "threading.py")) -> str:
    for frame in reversed(traceback.extract_stack()):
        fname = frame.filename
        if any(s in fname for s in skip_substrings):
            continue
        short = "/".join(fname.split("/")[-2:])
        return f"{short}:{frame.lineno}"
    return "<unknown>"


class InstrumentedLock:
    """A ``threading.Lock``/``RLock`` stand-in that reports every
    acquisition to its :class:`LockWatch`."""

    def __init__(self, watch: "LockWatch", reentrant: bool = False):
        self._watch = watch
        self._reentrant = reentrant
        self._lock = _REAL_RLOCK() if reentrant else _REAL_LOCK()
        self.label = f"{_creation_site()}#{watch._next_id()}"

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._watch._before_acquire(self)
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._watch._acquired(self)
        return got

    def release(self) -> None:
        self._lock.release()
        self._watch._released(self)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        if self._reentrant:  # RLock has no .locked() before 3.12
            raise AttributeError("locked() on an RLock wrapper")
        return self._lock.locked()

    # threading.Condition probes these on non-RLock locks; delegating
    # keeps wait() releasing through OUR release (stack stays truthful)
    def _is_owned(self) -> bool:
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True


class LockWatch:
    """Records the acquisition-order digraph over instrumented locks."""

    def __init__(self) -> None:
        self._tls = threading.local()
        self._mu = _REAL_LOCK()
        self._seq = 0
        # (held_label, acquired_label) -> sample acquisition site
        self.edges: Dict[Tuple[str, str], str] = {}
        self.acquisitions: Dict[str, int] = defaultdict(int)

    def _next_id(self) -> int:
        with self._mu:
            self._seq += 1
            return self._seq

    # ------------------------------------------------------ lock callbacks
    def _stack(self) -> List[InstrumentedLock]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _before_acquire(self, lock: InstrumentedLock) -> None:
        held = self._stack()
        if any(h is lock for h in held):  # reentrant re-hold: no edge
            return
        site = _creation_site()
        with self._mu:
            for h in held:
                if h.label != lock.label:
                    self.edges.setdefault((h.label, lock.label), site)

    def _acquired(self, lock: InstrumentedLock) -> None:
        held = self._stack()
        if self._reentrant_hold(held, lock):
            return
        held.append(lock)
        with self._mu:
            self.acquisitions[lock.label] += 1

    def _released(self, lock: InstrumentedLock) -> None:
        held = self._stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    @staticmethod
    def _reentrant_hold(
        held: List[InstrumentedLock], lock: InstrumentedLock
    ) -> bool:
        return lock._reentrant and any(h is lock for h in held)

    # ----------------------------------------------------------- patching
    @contextmanager
    def patched(self):
        """Swap ``threading.Lock``/``RLock``/``Condition`` for
        instrumented builders for the duration of the block.  Objects
        constructed inside keep recording after exit."""
        watch = self

        def make_lock():
            return InstrumentedLock(watch)

        def make_rlock():
            return InstrumentedLock(watch, reentrant=True)

        def make_condition(lock: Optional[object] = None):
            return _REAL_CONDITION(lock if lock is not None else make_lock())

        threading.Lock = make_lock            # type: ignore[assignment]
        threading.RLock = make_rlock          # type: ignore[assignment]
        threading.Condition = make_condition  # type: ignore[assignment]
        try:
            yield self
        finally:
            threading.Lock = _REAL_LOCK
            threading.RLock = _REAL_RLOCK
            threading.Condition = _REAL_CONDITION

    # ------------------------------------------------------------- verdict
    def cycles(self) -> List[List[str]]:
        graph: Dict[str, Set[str]] = defaultdict(set)
        for a, b in self.edges:
            graph[a].add(b)
            graph[b]
        out: List[List[str]] = []
        color: Dict[str, int] = {}
        path: List[str] = []

        def dfs(n: str) -> None:
            color[n] = 1
            path.append(n)
            for m in sorted(graph[n]):
                if color.get(m, 0) == 0:
                    dfs(m)
                elif color.get(m) == 1:
                    cyc = path[path.index(m):] + [m]
                    if not any(set(cyc) == set(c) for c in out):
                        out.append(cyc)
            path.pop()
            color[n] = 2

        for n in sorted(graph):
            if color.get(n, 0) == 0:
                dfs(n)
        return out

    def assert_acyclic(self) -> None:
        cyc = self.cycles()
        if cyc:
            lines = []
            for c in cyc:
                pairs = list(zip(c, c[1:]))
                lines.append(
                    " -> ".join(c)
                    + "  ("
                    + "; ".join(
                        f"{a}->{b} acquired at {self.edges[(a, b)]}"
                        for a, b in pairs
                        if (a, b) in self.edges
                    )
                    + ")"
                )
            raise AssertionError(
                "lock-order inversion observed under traffic:\n"
                + "\n".join(lines)
            )
