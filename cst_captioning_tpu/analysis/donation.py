"""CST-DON: donation + compile-discipline lint over jit call sites.

Two contracts from the perf PRs:

* **Donation** (PR 5, docs/PARITY.md r9): update-step jit sites donate
  the incoming TrainState (``donate_argnums=(0,)``) so param/optimizer
  buffers are aliased in place — pinned against the lowered StableHLO
  by tests/test_training.py::TestBufferDonation.  A NEW update step
  that forgets donation doubles peak memory silently; CST-DON-001
  catches it at the AST.
* **Compile discipline** (PR 2/3/7): every jit call site must have a
  KNOWN retrace story (a fixed shape ladder, a pre-warmed bank ladder,
  a handful of static values) — the ``compile_count`` pinning in
  serving and the bench exit heuristics depend on it.  CST-DON-002
  requires every jit site in the package to be registered in
  ``jit_registry.py`` with an expected retrace budget; CST-DON-003
  flags stale registry entries so the registry cannot rot.
* **AOT discipline** (PR 13, the serving-artifact subsystem): a
  ``.lower(...).compile(...)`` chain compiles OUTSIDE the jit dispatch
  path, and ``deserialize_and_load`` installs an executable that was
  compiled in ANOTHER process — both bypass every runtime retrace
  guard, so each such site must be registered in
  ``jit_registry.py::AOT_SITE_REGISTRY`` with the story of what
  enumerates its variants and what refuses a stale/foreign executable
  (CST-DON-004); CST-DON-005 flags stale AOT entries (the DON-003 rot
  guard applied to the AOT registry).

Site keys are ``<file>::<qualname>`` (decorated defs) or
``<file>::<enclosing qualname>::<target>`` (jit-by-call) — stable under
reformatting, unlike line numbers.  AOT sites key on the enclosing
qualname alone (one entry covers a function's whole lower/compile
loop).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from cst_captioning_tpu.analysis.astutil import (
    ModuleInfo,
    call_name,
    dotted,
)
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    register_checker,
)
from cst_captioning_tpu.analysis import jit_registry

_JIT_CALLEES = {"jax.jit", "jit", "pjit", "jax.experimental.pjit.pjit"}
_PARTIAL = {"functools.partial", "partial"}


def _is_jit_call(node: ast.Call) -> bool:
    return call_name(node) in _JIT_CALLEES


def _is_jit_partial(node: ast.Call) -> bool:
    return (
        call_name(node) in _PARTIAL
        and bool(node.args)
        and dotted(node.args[0]) in _JIT_CALLEES
    )


def _has_donate(node: ast.Call) -> bool:
    return any(
        kw.arg in ("donate_argnums", "donate_argnames")
        for kw in node.keywords
    )


def collect_jit_sites(
    modules: List[ModuleInfo],
) -> List[Tuple[str, ModuleInfo, ast.Call, str]]:
    """Every jit application in the package as
    ``(site_key, module, kwargs-carrying Call, symbol)``."""
    sites: List[Tuple[str, ModuleInfo, ast.Call, str]] = []
    seen: Dict[str, int] = {}

    def add(key: str, mi: ModuleInfo, call: ast.Call, sym: str) -> None:
        # Deterministic dedupe of key collisions (two jit lambdas in
        # one scope): suffix #2, #3 ... in line order.
        n = seen.get(key, 0) + 1
        seen[key] = n
        if n > 1:
            key = f"{key}#{n}"
        sites.append((key, mi, call, sym))

    for mi in modules:
        decorated_calls: Set[int] = set()
        for qn, fn in sorted(
            mi.functions.items(), key=lambda kv: kv[1].line
        ):
            node = fn.node
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and (
                    _is_jit_call(dec) or _is_jit_partial(dec)
                ):
                    decorated_calls.add(id(dec))
                    add(f"{mi.rel}::{qn}", mi, dec, qn)
                elif dotted(dec) in _JIT_CALLEES:
                    # bare @jax.jit — synthesize an argless marker call
                    marker = ast.Call(func=dec, args=[], keywords=[])
                    ast.copy_location(marker, dec)
                    add(f"{mi.rel}::{qn}", mi, marker, qn)
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call) or id(node) in decorated_calls:
                continue
            if _is_jit_call(node) and node.args:
                target = node.args[0]
                tname = (
                    target.id if isinstance(target, ast.Name)
                    else "<lambda>" if isinstance(target, ast.Lambda)
                    else dotted(target) or "<expr>"
                )
                scope = mi.qualname_of(node)
                add(
                    f"{mi.rel}::{scope}::{tname}", mi, node,
                    f"{scope}::{tname}",
                )
    return sites


# AOT executable production/installation shapes (CST-DON-004): the
# `<lowered>.compile()` chain and the cross-process executable loader.
_AOT_LOADERS = {"deserialize_and_load"}


def _is_chained_lower_compile(node: ast.Call) -> bool:
    """``<expr>.lower(...).compile(...)`` — compilation outside the jit
    dispatch path (the AOT artifact builder's shape)."""
    f = node.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr == "compile"
        and isinstance(f.value, ast.Call)
        and isinstance(f.value.func, ast.Attribute)
        and f.value.func.attr == "lower"
    )


def _produces_lowerings(fn_node: ast.AST) -> bool:
    """Whether a function body contains a lowering producer: an ARGFUL
    ``.lower(...)`` call (jax lowering always takes avals — ``str.lower()``
    takes none) or a call into the ``aot_lower*`` enumeration API."""
    for n in ast.walk(fn_node):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if isinstance(f, ast.Attribute):
            if f.attr == "lower" and (n.args or n.keywords):
                return True
            if f.attr.startswith("aot_lower"):
                return True
        elif isinstance(f, ast.Name) and f.id.startswith("aot_lower"):
            return True
    return False


def _is_lowered_compile(node: ast.Call, mi: ModuleInfo) -> bool:
    """The chained shape, or ``<name>.compile(...)`` inside a function
    that produces lowerings (the builder keeps lowering and compiling in
    separate expressions — the def-use-free, deterministic
    approximation)."""
    if _is_chained_lower_compile(node):
        return True
    f = node.func
    if not (
        isinstance(f, ast.Attribute)
        and f.attr == "compile"
        and isinstance(f.value, ast.Name)
    ):
        return False
    qn = mi.qualname_of(node)
    fn = mi.functions.get(qn)
    return fn is not None and _produces_lowerings(fn.node)


def _is_executable_load(node: ast.Call) -> bool:
    """``deserialize_and_load(...)`` (any alias path) — installing an
    executable compiled in another process."""
    name = call_name(node) or ""
    return name.rsplit(".", 1)[-1] in _AOT_LOADERS


def collect_aot_sites(
    modules: List[ModuleInfo],
) -> List[Tuple[str, ModuleInfo, ast.Call, str]]:
    """Every AOT compile/install site as
    ``(site_key, module, call, kind)`` — keyed on the enclosing
    qualname (one registry entry covers a function's variant loop)."""
    sites: List[Tuple[str, ModuleInfo, ast.Call, str]] = []
    for mi in modules:
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_lowered_compile(node, mi):
                kind = "lowered-compile"
            elif _is_executable_load(node):
                kind = "executable-load"
            else:
                continue
            sites.append(
                (f"{mi.rel}::{mi.qualname_of(node)}", mi, node, kind)
            )
    return sites


@register_checker("donation")
def check(modules: List[ModuleInfo], ctx: CheckContext) -> List[Finding]:
    out: List[Finding] = []
    sites = collect_jit_sites(modules)
    seen_keys = set()
    for key, mi, call, sym in sites:
        seen_keys.add(key)
        entry = jit_registry.JIT_SITE_REGISTRY.get(key)
        if entry is None:
            out.append(Finding(
                "CST-DON-002", mi.rel, call.lineno, sym,
                f"jit site `{key}` is not registered — add it to "
                "analysis/jit_registry.py with an expected retrace "
                "budget (what bounds recompiles at this site?)",
            ))
            continue
        if entry.update_step and not _has_donate(call):
            out.append(Finding(
                "CST-DON-001", mi.rel, call.lineno, sym,
                f"update-step jit site `{key}` does not donate its "
                "TrainState (donate_argnums) — peak memory doubles "
                "and the TestBufferDonation aliasing pin will fail",
            ))
        if not entry.update_step and _has_donate(call) and not entry.donates:
            out.append(Finding(
                "CST-DON-001", mi.rel, call.lineno, sym,
                f"jit site `{key}` donates buffers but its registry "
                "entry does not declare `donates=True` — donation "
                "invalidates the caller's input arrays; declare it "
                "so reviewers see the aliasing contract",
            ))
    for key in sorted(jit_registry.JIT_SITE_REGISTRY):
        if key not in seen_keys:
            out.append(Finding(
                "CST-DON-003", "analysis/jit_registry.py", 1, key,
                f"stale jit-registry entry `{key}` matches no site — "
                "the code moved; update or remove the entry",
            ))
    # ---- AOT lowered/compiled + executable-install coverage (PR 13)
    seen_aot = set()
    for key, mi, call, kind in collect_aot_sites(modules):
        seen_aot.add(key)
        if key not in jit_registry.AOT_SITE_REGISTRY:
            out.append(Finding(
                "CST-DON-004", mi.rel, call.lineno,
                mi.qualname_of(call),
                f"AOT {kind} site `{key}` is not registered — add it "
                "to analysis/jit_registry.py::AOT_SITE_REGISTRY with "
                "the story of what enumerates its variants and what "
                "refuses a stale or foreign executable",
            ))
    for key in sorted(jit_registry.AOT_SITE_REGISTRY):
        if key not in seen_aot:
            out.append(Finding(
                "CST-DON-005", "analysis/jit_registry.py", 1, key,
                f"stale AOT-registry entry `{key}` matches no "
                "lower/compile or executable-load site — the code "
                "moved; update or remove the entry",
            ))
    return out
