"""Invariant engine: AST-based machine-checking of the repo's
correctness contracts (rule catalogue in docs/ANALYSIS.md).

Public surface::

    from cst_captioning_tpu.analysis import run_analysis
    report = run_analysis()          # whole package, all rules
    report.findings                  # unsuppressed [Finding]

    python -m cst_captioning_tpu.analysis [--json]   # CLI / preflight

The engine is pure stdlib-AST (no jax import) so it runs in well under
the 30 s tier-1 budget; the dynamic lock-order twin lives in
``analysis.lockwatch`` and runs under stub traffic in tier-1.
"""

from cst_captioning_tpu.analysis.engine import (  # noqa: F401
    CHECKERS,
    Finding,
    Report,
    run_analysis,
    validate_report,
)
from cst_captioning_tpu.analysis.sarif import (  # noqa: F401
    to_sarif,
    validate_sarif,
)
