"""CST-DTY: dtype-flow discipline over the traced surface (ISSUE 15).

The PARITY tiers (docs/PARITY.md) are dtype contracts in disguise:
"token-exact" survives only while every precision change on a decode
path is deliberate, registered, and justified.  The bf16/int8 serving
PR this paves will add cast sites on purpose — these rules make sure
it CANNOT add them silently (catalogue in docs/ANALYSIS.md):

* **CST-DTY-001** — every dtype-cast application (``.astype``,
  ``lax.convert_element_type``) reachable from a registered jit root
  must be covered by ``analysis/jit_registry.py::CAST_REGISTRY``
  (keyed ``<file>::<qualname>``, lambda segments folded) with a
  PARITY-tier justification; stale registry entries fire too — the
  SHARD_MAP_REGISTRY discipline applied to precision.
* **CST-DTY-002** — implicit weak-type promotion: a binop between a
  value the abstract interpreter PROVES is an integer array and a bare
  Python float literal inside traced code.  JAX floats the int array
  to the default float silently (``tokens * 0.5`` is f32, no cast in
  sight) — on a decode/loss path that is an unregistered precision
  change.  Proven-int-only by construction: traced params are TOP, so
  the rule cannot fire on uncertainty.
* **CST-DTY-003** — accumulation-dtype discipline: inside a
  ``CAST_REGISTRY`` entry declaring ``low_precision=True`` (the paths
  that compute in ``compute_dtype``/``cdt`` today and will carry bf16
  under the serving fast path), every matmul — ``dot_general``,
  ``jnp.matmul``/``dot``/``einsum``/``tensordot`` AND the bare ``@``
  operator — must pin ``preferred_element_type`` (the ``@`` operator
  cannot, so it must be spelled as a pinning call).  A bf16 matmul
  accumulating in bf16 is the classic silent-divergence source the
  bounded-divergence contract cannot absorb.
* **CST-DTY-004** — donation/dtype aliasing: a jit site with
  ``donate_argnums``/``donate_argnames`` whose donated parameter is
  dtype-cast inside the traced body.  XLA only aliases buffers whose
  dtype (hence byte size) matches; a cast donated input silently
  disables donation — memory doubles with zero warnings.
"""

from __future__ import annotations

import ast
import time
from typing import List, Set

from cst_captioning_tpu.analysis import jit_registry
from cst_captioning_tpu.analysis.astutil import (
    FuncInfo,
    ModuleInfo,
    call_name,
    walk_body,
)
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    register_checker,
)
from cst_captioning_tpu.analysis import typeflow as tfmod
from cst_captioning_tpu.analysis.typeflow import (
    cast_sites,
    is_int,
    site_key,
)

_MATMUL_CALLS = ("dot_general", "dot", "matmul", "einsum", "tensordot")


def _check_cast_registry(
    modules: List[ModuleInfo], tf
) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[str] = set()
    flagged: Set[str] = set()
    for key, mi, fn, call, kind in cast_sites(modules, tf):
        seen.add(key)
        if key in jit_registry.CAST_REGISTRY or key in flagged:
            continue
        flagged.add(key)
        out.append(Finding(
            "CST-DTY-001", mi.rel, call.lineno, fn.qualname,
            f"cast site `{key}` ({kind}) is reachable from a jit root "
            "but not registered — add it to analysis/jit_registry.py::"
            "CAST_REGISTRY with the PARITY tier it preserves and why "
            "(an unregistered precision change is how token-exact "
            "silently becomes close-enough)",
        ))
    scanned = {m.rel for m in modules}
    for key in sorted(jit_registry.CAST_REGISTRY):
        rel = key.split("::", 1)[0]
        if rel not in scanned:
            continue
        if key not in seen:
            out.append(Finding(
                "CST-DTY-001", "analysis/jit_registry.py", 1, key,
                f"stale CAST_REGISTRY entry `{key}` matches no "
                "traced cast site — the code moved; update or remove "
                "the entry",
            ))
        tier = jit_registry.CAST_REGISTRY[key].tier
        if tier not in jit_registry.PARITY_TIERS:
            # Tier-vocabulary legality (ISSUE 16): an entry naming a
            # tier docs/PARITY.md doesn't define claims a guarantee
            # nothing enforces — a typo'd "token-exact" would
            # otherwise pass review as a real contract.
            out.append(Finding(
                "CST-DTY-001", "analysis/jit_registry.py", 1, key,
                f"CAST_REGISTRY entry `{key}` names illegal parity "
                f"tier {tier!r} — legal tiers are "
                f"{sorted(jit_registry.PARITY_TIERS)} "
                "(jit_registry.PARITY_TIERS; docs/PARITY.md r17)",
            ))
    return out


def _check_weak_promotion(tf) -> List[Finding]:
    out: List[Finding] = []
    for fn in tf.traced_functions():
        mi = fn.module
        types = tf.types_of(fn)
        for node in walk_body(fn):
            if not isinstance(node, ast.BinOp) or isinstance(
                node.op, ast.MatMult
            ):
                continue
            for lit, other in (
                (node.right, node.left), (node.left, node.right),
            ):
                if not (
                    isinstance(lit, ast.Constant)
                    and isinstance(lit.value, float)
                ):
                    continue
                v = types.value_of(other)
                if v.array and is_int(v.dtype):
                    out.append(Finding(
                        "CST-DTY-002", mi.rel, node.lineno, fn.qualname,
                        f"integer array ({v.dtype}) combined with the "
                        f"bare float literal {lit.value!r} inside "
                        "traced code — JAX silently floats the array "
                        "to the default float (an unregistered "
                        "precision change on this path); cast "
                        "explicitly or keep the arithmetic integral",
                    ))
                    break
    return out


def _check_accumulation(
    modules: List[ModuleInfo], tf
) -> List[Finding]:
    """CST-DTY-003 over the qualnames whose CAST_REGISTRY entries
    declare ``low_precision=True``."""
    low = {
        key for key, e in jit_registry.CAST_REGISTRY.items()
        if e.low_precision
    }
    if not low:
        return []
    out: List[Finding] = []
    for fn in tf.traced_functions():
        mi = fn.module
        if site_key(mi, fn.qualname) not in low:
            continue
        for node in walk_body(fn):
            if isinstance(node, ast.BinOp) and isinstance(
                node.op, ast.MatMult
            ):
                out.append(Finding(
                    "CST-DTY-003", mi.rel, node.lineno, fn.qualname,
                    "bare `@` matmul on a registered low-precision "
                    "path — the operator cannot pin an accumulation "
                    "dtype; spell it jnp.matmul(..., "
                    "preferred_element_type=jnp.float32) (or "
                    "lax.dot_general) so bf16 operands accumulate in "
                    "f32",
                ))
            if isinstance(node, ast.Call) and (
                call_name(node) or ""
            ).rsplit(".", 1)[-1] in _MATMUL_CALLS:
                if not any(
                    kw.arg == "preferred_element_type"
                    for kw in node.keywords
                ):
                    out.append(Finding(
                        "CST-DTY-003", mi.rel, node.lineno, fn.qualname,
                        "matmul on a registered low-precision path "
                        "without preferred_element_type — low-precision "
                        "operands accumulate in their own width unless "
                        "pinned; declare the accumulation dtype "
                        "explicitly",
                    ))
    return out


def _donated_params(call: ast.Call, fn: FuncInfo) -> Set[str]:
    names: Set[str] = set()
    params = [p for p in fn.params if p not in ("self", "cls")]
    for kw in call.keywords:
        vals: List = []
        if isinstance(kw.value, (ast.Tuple, ast.List)):
            vals = [
                e.value for e in kw.value.elts
                if isinstance(e, ast.Constant)
            ]
        elif isinstance(kw.value, ast.Constant):
            vals = [kw.value.value]
        if kw.arg == "donate_argnums":
            for i in vals:
                if isinstance(i, int) and i < len(params):
                    names.add(params[i])
        elif kw.arg == "donate_argnames":
            names.update(v for v in vals if isinstance(v, str))
    return names


def _check_donated_casts(modules: List[ModuleInfo]) -> List[Finding]:
    from cst_captioning_tpu.analysis.donation import collect_jit_sites
    from cst_captioning_tpu.analysis.typeflow import is_cast_call

    out: List[Finding] = []
    for key, mi, call, sym in collect_jit_sites(modules):
        donated: Set[str] = set()
        fn: FuncInfo = None
        if call.args:                     # jit-by-call: jit(fn, ...)
            target = call.args[0]
            if isinstance(target, ast.Name):
                scope = mi.qualname_of(call)
                for qn in (
                    [f"{scope}.{target.id}"] if scope != "<module>"
                    else []
                ) + [target.id]:
                    fn = mi.functions.get(qn)
                    if fn is not None:
                        break
        else:                             # decorator site
            fn = mi.functions.get(sym)
        if fn is None:
            continue
        donated = _donated_params(call, fn)
        if not donated:
            continue
        for node in walk_body(fn, into_nested=True):
            if not isinstance(node, ast.Call):
                continue
            if is_cast_call(node) is None:
                continue
            f = node.func
            operand = f.value if isinstance(f, ast.Attribute) else (
                node.args[0] if node.args else None
            )
            root = operand
            while isinstance(root, (ast.Attribute, ast.Subscript)):
                root = root.value
            if isinstance(root, ast.Name) and root.id in donated:
                out.append(Finding(
                    "CST-DTY-004", mi.rel, node.lineno, fn.qualname,
                    f"donated parameter `{root.id}` of jit site "
                    f"`{key}` is dtype-cast inside the traced body — "
                    "XLA only aliases buffers whose dtype matches, so "
                    "the donation is silently disabled and peak memory "
                    "doubles; cast before the jit boundary or drop the "
                    "donation",
                ))
    return out


@register_checker("dtypeflow")
def check(modules: List[ModuleInfo], ctx: CheckContext) -> List[Finding]:
    t0 = time.perf_counter()
    tf = tfmod.build(modules, ctx)
    out: List[Finding] = []
    out.extend(_check_cast_registry(modules, tf))
    out.extend(_check_weak_promotion(tf))
    out.extend(_check_accumulation(modules, tf))
    out.extend(_check_donated_casts(modules))
    tfmod.note_duration(time.perf_counter() - t0)
    return out
