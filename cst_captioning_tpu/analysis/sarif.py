"""SARIF 2.1.0 export of the analysis report (minimal profile).

SARIF is the interchange format every code-scanning UI ingests
(GitHub code scanning, VS Code SARIF viewer, …).  The export carries
exactly what the findings carry — rule ID, file, region, level,
message — nothing invented:

* unsuppressed findings export at ``level: "error"`` (they fail the
  pass);
* suppressed findings export at ``level: "note"`` with a SARIF
  ``suppressions`` entry carrying the annotated justification, so a
  viewer shows the recorded argument instead of hiding the site;
* ``tool.driver.rules`` lists every rule ID that appears, each with
  the rule's first message as its short description.

``validate_sarif`` is the same hand-rolled schema discipline as
``validate_report`` / bench's ``validate_record``: the minimal-profile
shape is pinned by tests, not by an external jsonschema dependency.
"""

from __future__ import annotations

from typing import Any, Dict, List

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)
TOOL_NAME = "cst-invariant-engine"


def _result(f: Dict[str, Any], level: str, rule_index: int) -> dict:
    out = {
        "ruleId": f["rule"],
        "ruleIndex": rule_index,
        "level": level,
        "message": {"text": f"[{f['symbol']}] {f['message']}"},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f["file"]},
                "region": {"startLine": f["line"]},
            },
        }],
    }
    if "justification" in f:
        out["suppressions"] = [{
            "kind": "external",
            "justification": f["justification"],
        }]
    return out


def to_sarif(report_dict: Dict[str, Any]) -> Dict[str, Any]:
    """SARIF 2.1.0 document from a ``Report.to_dict()`` payload."""
    rule_ids: List[str] = []
    rule_text: Dict[str, str] = {}
    for f in list(report_dict["findings"]) + list(
        report_dict["suppressed"]
    ):
        if f["rule"] not in rule_ids:
            rule_ids.append(f["rule"])
            rule_text[f["rule"]] = f["message"]
    rule_index = {r: i for i, r in enumerate(rule_ids)}
    results = [
        _result(f, "error", rule_index[f["rule"]])
        for f in report_dict["findings"]
    ] + [
        _result(f, "note", rule_index[f["rule"]])
        for f in report_dict["suppressed"]
    ]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": TOOL_NAME,
                    "informationUri": "docs/ANALYSIS.md",
                    "rules": [
                        {
                            "id": r,
                            "shortDescription": {
                                "text": rule_text[r][:200]
                            },
                        }
                        for r in rule_ids
                    ],
                },
            },
            "results": results,
        }],
    }


def validate_sarif(doc: Any) -> Dict[str, Any]:
    """Schema-check a minimal-profile SARIF 2.1.0 document; returns it
    or raises ValueError naming the violation."""

    def fail(msg: str) -> None:
        raise ValueError(f"malformed SARIF document: {msg}")

    if not isinstance(doc, dict):
        fail("not a dict")
    if doc.get("version") != SARIF_VERSION:
        fail(f"version must be {SARIF_VERSION!r}")
    if not (
        isinstance(doc.get("$schema"), str) and "sarif" in doc["$schema"]
    ):
        fail("'$schema' must name a SARIF schema")
    runs = doc.get("runs")
    if not (isinstance(runs, list) and len(runs) == 1):
        fail("'runs' must be a one-element list")
    run = runs[0]
    if not isinstance(run, dict):
        fail("runs[0] is not an object")
    driver = run.get("tool", {}).get("driver") if isinstance(
        run.get("tool"), dict
    ) else None
    if not isinstance(driver, dict) or not (
        isinstance(driver.get("name"), str) and driver["name"]
    ):
        fail("tool.driver.name must be a non-empty string")
    rules = driver.get("rules")
    if not isinstance(rules, list):
        fail("tool.driver.rules must be a list")
    ids = []
    for i, r in enumerate(rules):
        if not (
            isinstance(r, dict)
            and isinstance(r.get("id"), str) and r["id"]
        ):
            fail(f"rules[{i}].id must be a non-empty string")
        ids.append(r["id"])
    if len(set(ids)) != len(ids):
        fail("duplicate rule ids in tool.driver.rules")
    results = run.get("results")
    if not isinstance(results, list):
        fail("'results' must be a list")
    for i, res in enumerate(results):
        if not isinstance(res, dict):
            fail(f"results[{i}] is not an object")
        if res.get("ruleId") not in ids:
            fail(
                f"results[{i}].ruleId {res.get('ruleId')!r} not in "
                "tool.driver.rules"
            )
        ri = res.get("ruleIndex")
        if not (
            isinstance(ri, int) and not isinstance(ri, bool)
            and 0 <= ri < len(ids) and ids[ri] == res["ruleId"]
        ):
            fail(f"results[{i}].ruleIndex disagrees with ruleId")
        if res.get("level") not in ("error", "warning", "note"):
            fail(f"results[{i}].level must be error/warning/note")
        msg = res.get("message")
        if not (
            isinstance(msg, dict)
            and isinstance(msg.get("text"), str) and msg["text"]
        ):
            fail(f"results[{i}].message.text must be non-empty")
        locs = res.get("locations")
        if not (isinstance(locs, list) and len(locs) >= 1):
            fail(f"results[{i}].locations must be non-empty")
        phys = locs[0].get("physicalLocation") if isinstance(
            locs[0], dict
        ) else None
        if not isinstance(phys, dict):
            fail(f"results[{i}] missing physicalLocation")
        art = phys.get("artifactLocation")
        if not (
            isinstance(art, dict)
            and isinstance(art.get("uri"), str) and art["uri"]
        ):
            fail(f"results[{i}] artifactLocation.uri must be non-empty")
        region = phys.get("region")
        line = region.get("startLine") if isinstance(
            region, dict
        ) else None
        if not (
            isinstance(line, int) and not isinstance(line, bool)
            and line >= 1
        ):
            fail(f"results[{i}] region.startLine must be a positive int")
    return doc
