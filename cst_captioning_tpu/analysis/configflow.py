"""CST-CFG: config-knob lifecycle rules over the def-use layer.

The 693-line config surface (grown every PR: ``serving.chaos``,
``hedge_ms``, ``requeue_budget``, ``model_shards`` …) is read through
unchecked attribute chains: a typo'd knob read silently evaluates the
dataclass default (``Config.from_dict`` validates WRITES from JSON,
nothing validates reads), a knob nothing reads is dead weight every
operator still has to reason about, and the docs knob catalogue can
rot silently.  These rules close the loop:

* CST-CFG-001 — a dotted config read (``cfg.serving.X``,
  ``self.cfg.train.X``, ``getattr(cfg.train, "X", default)``, or a
  read through a section alias ``sv = cfg.serving; sv.X``) resolving
  to no declared dataclass field of that section.  Reads through
  aliases ride :mod:`analysis.dataflow`'s per-function def-use chains.
* CST-CFG-002 — a declared field with ZERO reads anywhere in the
  package (dead knob): either wire it or delete it.  Fires only on a
  full-package scan (the config module present).
* CST-CFG-003 — a declared field missing from the docs/ANALYSIS.md
  knob catalogue (the ``METRIC_FAMILIES`` doc discipline applied to
  config: operators discover knob vocabulary there).
* CST-CFG-004 — a preset (any function in the config module)
  assigning an UNDECLARED field: the assignment silently creates a
  new attribute instead of configuring anything.

Section expressions are recognized structurally: ``<base>.<section>``
where ``<section>`` is a field of the ``Config`` dataclass and
``<base>``'s attribute chain contains a config-flavored name (``cfg``,
``config``, ``c``, ``*cfg``) — the naming convention every call site
follows.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from cst_captioning_tpu.analysis.astutil import (
    FuncInfo,
    ModuleInfo,
)
from cst_captioning_tpu.analysis.dataflow import DefUse
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    register_checker,
)

DOC_FILE = "ANALYSIS.md"

_CFG_BASES = {"cfg", "config", "c"}


def find_config_module(modules: List[ModuleInfo]) -> Optional[ModuleInfo]:
    """The module declaring the ``Config`` dataclass tree —
    ``config.py`` at the package root (or the corpus twin)."""
    for mi in modules:
        if (
            (mi.rel == "config.py" or mi.rel.endswith("/config.py"))
            and "Config" in mi.classes
        ):
            return mi
    return None


def declared_fields(
    config_mi: ModuleInfo,
) -> Dict[str, Dict[str, int]]:
    """``{section: {field: lineno}}`` from the dataclass declarations:
    ``Config``'s annotated fields name the sections, each section
    class's annotated fields are the knobs."""
    cfg_cls = config_mi.classes["Config"]
    sections: Dict[str, str] = {}
    for node in cfg_cls.body:
        if (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and isinstance(node.annotation, ast.Name)
            and node.annotation.id in config_mi.classes
        ):
            sections[node.target.id] = node.annotation.id
    out: Dict[str, Dict[str, int]] = {}
    for sect, clsname in sections.items():
        cls = config_mi.classes[clsname]
        out[sect] = {
            n.target.id: n.lineno
            for n in cls.body
            if isinstance(n, ast.AnnAssign)
            and isinstance(n.target, ast.Name)
        }
    return out


def _base_is_cfg(node: ast.AST) -> bool:
    """Whether an expression reads as a config object: its attribute
    chain (climbing through subscripts/calls) contains a
    config-flavored name."""
    tokens: List[str] = []
    cur = node
    while True:
        if isinstance(cur, ast.Attribute):
            tokens.append(cur.attr)
            cur = cur.value
        elif isinstance(cur, ast.Subscript):
            cur = cur.value
        elif isinstance(cur, ast.Call):
            cur = cur.func
        elif isinstance(cur, ast.Name):
            tokens.append(cur.id)
            break
        else:
            break
    return any(
        t in _CFG_BASES or t.endswith("cfg") or t.endswith("config")
        for t in tokens
    )


def _section_expr(
    node: ast.AST, sections: Set[str]
) -> Optional[str]:
    """``"serving"`` when ``node`` is a config-section expression
    (``cfg.serving`` / ``self.cfg.serving`` / ``engines[0].cfg.serving``)."""
    if (
        isinstance(node, ast.Attribute)
        and node.attr in sections
        and _base_is_cfg(node.value)
    ):
        return node.attr
    return None


# One observed knob access.
#   kind: "load" | "store" | "getattr"
Access = Tuple[str, str, str, int, str]   # (section, field, rel, line, kind)

FnKey = Tuple[str, str]                   # (rel, qualname)


def _fn_key(fn: FuncInfo) -> FnKey:
    return (fn.module.rel, fn.qualname)


class _Flow:
    """The interprocedural section-alias state: which function
    PARAMETERS are config sections (``make_optimizer(cfg.train, …)``
    → ``cfg_train`` is the train section inside), and which
    string-typed parameters carry constant field names
    (``_decode_kernel_gate("use_pallas_beam")`` →
    ``getattr(m, flag_name)`` reads that knob).  Computed to a
    fixpoint over the call graph so aliases chain
    (``make_optimizer`` → ``make_lr_schedule``)."""

    def __init__(self, modules, ctx, sections: Set[str]):
        self.modules = modules
        self.ctx = ctx
        self.sections = sections
        self._du: Dict[FnKey, DefUse] = {}
        self.param_section: Dict[Tuple[FnKey, str], str] = {}
        self.param_strings: Dict[Tuple[FnKey, str], Set[str]] = {}
        self._fixpoint()

    def du(self, fn: FuncInfo) -> DefUse:
        k = _fn_key(fn)
        if k not in self._du:
            self._du[k] = DefUse(fn)
        return self._du[k]

    # ----------------------------------------------- alias resolution
    def section_of(
        self, fn: FuncInfo, expr: ast.AST
    ) -> Optional[str]:
        """The config section ``expr`` evaluates to, chasing local
        bindings, parameters (interprocedural), and enclosing-scope
        closures."""
        sect = _section_expr(expr, self.sections)
        if sect is not None:
            return sect
        if not isinstance(expr, ast.Name):
            return None
        return self._name_section(fn, expr)

    def _name_section(
        self, fn: FuncInfo, use: ast.Name
    ) -> Optional[str]:
        du = self.du(fn)
        b = du.reaching_def(use)
        if b is not None:
            if b.kind == "param":
                return self.param_section.get((_fn_key(fn), use.id))
            if b.value is not None:
                sect = _section_expr(b.value, self.sections)
                if sect is not None:
                    return sect
                if isinstance(b.value, ast.Name):
                    return self._name_section(fn, b.value)
            return None
        if du.is_local(use.id):
            return None
        # closure read: an enclosing scope's binding or parameter
        from cst_captioning_tpu.analysis.dataflow import _enclosing_scopes

        for enc in _enclosing_scopes(fn):
            enc_du = self.du(enc)
            if use.id in enc.params:
                return self.param_section.get((_fn_key(enc), use.id))
            for b in enc_du.bindings_of(use.id):
                if b.value is not None:
                    sect = _section_expr(b.value, self.sections)
                    if sect is not None:
                        return sect
            if enc_du.is_local(use.id):
                return None
        return None

    def string_values(
        self, fn: FuncInfo, expr: ast.AST
    ) -> Optional[Set[str]]:
        """Constant string value(s) of ``expr``: a literal, a binding
        of one, or a parameter whose call sites all pass literals."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return {expr.value}
        if not isinstance(expr, ast.Name):
            return None
        du = self.du(fn)
        b = du.reaching_def(expr)
        if b is not None and b.kind == "param":
            return self.param_strings.get((_fn_key(fn), expr.id))
        if b is not None and b.value is not None:
            return self.string_values(fn, b.value)
        return None

    # ------------------------------------------------------- fixpoint
    def _map_args(
        self, callee: FuncInfo, call: ast.Call
    ) -> List[Tuple[str, ast.AST]]:
        params = callee.params
        if callee.cls is not None and params and params[0] in (
            "self", "cls"
        ):
            params = params[1:]
        pairs = list(zip(params, call.args))
        for kw in call.keywords:
            if kw.arg and kw.arg in callee.params:
                pairs.append((kw.arg, kw.value))
        return pairs

    def _fixpoint(self) -> None:
        from cst_captioning_tpu.analysis.astutil import walk_body

        for _ in range(8):   # package alias chains are ~2 deep
            changed = False
            for mi in self.modules:
                for qn, fn in mi.functions.items():
                    for call in (
                        n for n in walk_body(fn)
                        if isinstance(n, ast.Call)
                    ):
                        callees = self.ctx.index.resolve_call(
                            mi, fn, call
                        )
                        for callee in callees:
                            for pname, arg in self._map_args(
                                callee, call
                            ):
                                ck = (_fn_key(callee), pname)
                                sect = self.section_of(fn, arg)
                                if sect is not None and (
                                    self.param_section.get(ck) != sect
                                ):
                                    self.param_section[ck] = sect
                                    changed = True
                                strs = self.string_values(fn, arg)
                                if strs:
                                    have = self.param_strings.setdefault(
                                        ck, set()
                                    )
                                    if not strs <= have:
                                        have.update(strs)
                                        changed = True
            if not changed:
                break


def collect_accesses(
    modules: List[ModuleInfo], ctx, sections: Set[str]
) -> List[Access]:
    """Every recognized knob access in the scanned modules — direct
    dotted chains, ``getattr``/``hasattr`` string reads (constant or
    dataflow-resolved names), alias reads through the def-use chains
    (``sv = cfg.serving; sv.X``), closure reads, and reads through
    section-typed parameters (``make_optimizer(cfg.train)`` →
    ``cfg_train.beta1``).  The tests' vacuous-green guard pins that
    this discovers the real read surface."""
    flow = _Flow(modules, ctx, sections)
    out: List[Access] = []
    for mi in modules:
        # ---- direct dotted accesses (module level + functions) -----
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Attribute):
                sect = _section_expr(node.value, sections)
                if sect is None:
                    continue
                kind = "store" if isinstance(
                    node.ctx, (ast.Store, ast.Del)
                ) else "load"
                out.append((sect, node.attr, mi.rel, node.lineno, kind))
        # ---- alias / parameter / closure / getattr reads -----------
        for qn, fn in mi.functions.items():
            du = flow.du(fn)
            for use in du.uses:
                sect = flow._name_section(fn, use)
                if sect is None:
                    continue
                parent = mi.parent.get(use)
                if isinstance(parent, ast.Attribute) and (
                    parent.value is use
                ):
                    kind = "store" if isinstance(
                        parent.ctx, (ast.Store, ast.Del)
                    ) else "load"
                    out.append((
                        sect, parent.attr, mi.rel, parent.lineno, kind
                    ))
            # getattr/hasattr on anything section-typed
            from cst_captioning_tpu.analysis.astutil import walk_body

            for call in (
                n for n in walk_body(fn) if isinstance(n, ast.Call)
            ):
                if not (
                    isinstance(call.func, ast.Name)
                    and call.func.id in ("getattr", "hasattr")
                    and len(call.args) >= 2
                ):
                    continue
                sect = flow.section_of(fn, call.args[0])
                if sect is None:
                    continue
                names = flow.string_values(fn, call.args[1])
                for field in sorted(names or ()):
                    out.append((
                        sect, field, mi.rel, call.lineno, "getattr"
                    ))
    return out


@register_checker("configflow")
def check(modules: List[ModuleInfo], ctx: CheckContext) -> List[Finding]:
    config_mi = find_config_module(modules)
    if config_mi is None:
        return []
    fields = declared_fields(config_mi)
    sections = set(fields)
    accesses = collect_accesses(modules, ctx, sections)
    out: List[Finding] = []

    # ---- CFG-001 / CFG-004: every access names a declared field ------
    for sect, field, rel, line, kind in accesses:
        if field in fields[sect]:
            continue
        mi = ctx.index.by_rel.get(rel)
        symbol = "<module>"
        if mi is not None:
            for node in ast.walk(mi.tree):
                if getattr(node, "lineno", None) == line and isinstance(
                    node, (ast.Attribute, ast.Call)
                ):
                    symbol = mi.qualname_of(node)
                    break
        if rel == config_mi.rel and kind == "store":
            out.append(Finding(
                "CST-CFG-004", rel, line, symbol,
                f"preset assigns `{sect}.{field}`, which is not a "
                f"declared field of {sect!r} — the assignment "
                "silently creates a new attribute instead of "
                "configuring anything; fix the name or declare the "
                "field",
            ))
        else:
            out.append(Finding(
                "CST-CFG-001", rel, line, symbol,
                f"config read `{sect}.{field}` resolves to no "
                f"declared field of {sect!r} — a typo'd knob "
                "silently falls back to defaults; fix the name or "
                "declare the field in config.py",
            ))

    # Corpus scans stop here unless they carry the real config module;
    # the package-wide lifecycle rules need the full read surface.
    full_scan = config_mi.rel == "config.py" or len(modules) > 1
    if not full_scan:
        return out

    # ---- CFG-002: dead knobs ----------------------------------------
    read_fields = {
        (s, f)
        for s, f, rel, _, kind in accesses
        if kind in ("load", "getattr") and rel != config_mi.rel
    }
    for sect in sorted(fields):
        for field, line in sorted(fields[sect].items()):
            if (sect, field) not in read_fields:
                out.append(Finding(
                    "CST-CFG-002", config_mi.rel, line,
                    f"{sect}.{field}",
                    f"declared knob `{sect}.{field}` has zero reads "
                    "anywhere in the package — a dead knob misleads "
                    "every operator who sets it; wire it or delete "
                    "it",
                ))

    # ---- CFG-003: docs knob catalogue coverage ----------------------
    if ctx.docs_root is not None:
        doc_path = ctx.docs_root / DOC_FILE
        doc_text = doc_path.read_text() if doc_path.exists() else ""
        for sect in sorted(fields):
            for field, line in sorted(fields[sect].items()):
                if f"{sect}.{field}" not in doc_text:
                    out.append(Finding(
                        "CST-CFG-003", config_mi.rel, line,
                        f"{sect}.{field}",
                        f"knob `{sect}.{field}` is missing from the "
                        f"docs/{DOC_FILE} knob catalogue — operators "
                        "discover the config vocabulary there; add "
                        "the row",
                    ))
    return out
