"""CST-EXC: silent-exception audit of the threaded serving/training
surface.

A worker or scheduler thread that swallows ``Exception`` dies SILENTLY
— the queue backs up, deadlines expire, and the flight recorder PR 10
built to explain crashes records nothing, because nothing crashed.
The same failure mode hides in thread-target functions whose
exceptions escape the target: ``threading`` prints them to stderr (if
anything) and the thread is simply gone.  Two rules over the
:mod:`analysis.dataflow` call-graph closure:

* CST-EXC-001 — a ``try/except`` catching ``Exception``/
  ``BaseException``/bare that neither re-raises, logs, emits a flight
  event, nor ROUTES the caught exception onward (referencing the
  bound name — the ``_settle_exception(p, e)`` / poison-pill ``_put(e)``
  patterns), on code reachable from the concurrency roots: package
  ``threading.Thread`` targets, HTTP handler methods, the
  ``RewardPool`` and its worker module.
* CST-EXC-002 — a package function used as a ``Thread`` target whose
  body is not exception-contained: some top-level statement sits
  outside every ``try`` that has a broad, non-silent handler, so an
  exception there escapes the thread unlogged.  (Lambda targets must
  delegate to a contained function.)

Both rules are scoped to the reachable set on purpose: a broad
``except`` on a REQUEST path that maps failures to HTTP 500s, or a
best-effort ``__del__``, answers to different contracts.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from cst_captioning_tpu.analysis.astutil import (
    FuncInfo,
    ModuleInfo,
    call_name,
    dotted,
    walk_body,
)
from cst_captioning_tpu.analysis.dataflow import expand_call_closure
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    register_checker,
)

_THREAD_CTORS = {"threading.Thread", "Thread"}
_LOG_METHODS = {
    "debug", "info", "warning", "warn", "error", "exception",
    "critical", "log",
}
_FLIGHT_METHODS = {"event", "dump"}
# The reward-scoring pool: worker death here is exactly the silent
# failure the rules exist for (rows never come back, training hangs).
_POOL_FILES = ("training/rewards.py", "metrics/reward_worker.py")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [dotted(e) for e in t.elts]
    else:
        names = [dotted(t)]
    return any(
        n.split(".")[-1] in ("Exception", "BaseException") for n in names
    )


def _handler_is_silent(handler: ast.ExceptHandler) -> bool:
    """Whether a handler swallows: no raise, no logging-flavored call,
    no flight event, and the bound exception name (if any) is never
    referenced (referencing it routes the failure onward)."""
    bound = handler.name
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Name) and bound and node.id == bound:
            return False
        if isinstance(node, ast.Call):
            name = call_name(node)
            parts = name.split(".") if name else []
            if parts and parts[-1] in _LOG_METHODS and (
                len(parts) == 1
                or any(
                    "log" in p.lower() or "warn" in p.lower()
                    for p in parts[:-1]
                )
                or isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Call)
            ):
                return False
            if name in ("warnings.warn", "traceback.print_exc"):
                return False
            if isinstance(node.func, ast.Attribute) and (
                node.func.attr in _FLIGHT_METHODS
                and "flight" in dotted(node.func.value).lower()
            ):
                return False
    return True


def _resolve_target(
    mi: ModuleInfo, node: ast.AST, scope_qn: str
) -> Optional[FuncInfo]:
    """Resolve a ``Thread(target=X)`` expression to a package
    function: local/enclosing names, ``self.method`` (enclosing class
    from the qualname chain), and lambdas."""
    if isinstance(node, ast.Lambda):
        for fn in mi.functions.values():
            if fn.node is node:
                return fn
        return None
    name = dotted(node)
    if not name:
        return None
    head, _, rest = name.partition(".")
    if head == "self" and rest and "." not in rest:
        for seg in scope_qn.split("."):
            if seg in mi.classes:
                return mi.functions.get(f"{seg}.{rest}")
        return None
    if not rest:
        # plain name: innermost enclosing scope first
        parts = scope_qn.split(".") if scope_qn != "<module>" else []
        for i in range(len(parts), -1, -1):
            qn = ".".join(parts[:i] + [head]) if i else head
            fn = mi.functions.get(qn)
            if fn is not None:
                return fn
    return None


def thread_targets(
    modules: List[ModuleInfo],
) -> List[Tuple[ModuleInfo, ast.Call, Optional[FuncInfo]]]:
    """Every ``threading.Thread(...)`` construction with its resolved
    package target (None for stdlib/unresolvable targets).  The tests'
    vacuous-green guard pins that this finds the real serving worker
    threads."""
    out = []
    for mi in modules:
        for node in ast.walk(mi.tree):
            if not (
                isinstance(node, ast.Call)
                and call_name(node) in _THREAD_CTORS
            ):
                continue
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
            if target is None and len(node.args) >= 2:
                target = node.args[1]
            if target is None:
                continue
            fn = _resolve_target(mi, target, mi.qualname_of(node))
            out.append((mi, node, fn))
    return out


def collect_roots(
    modules: List[ModuleInfo],
) -> Dict[Tuple[str, str], str]:
    """Concurrency roots: thread targets, HTTP ``do_*`` handler
    methods, and the reward pool + its worker module."""
    roots: Dict[Tuple[str, str], str] = {}
    for mi, node, fn in thread_targets(modules):
        if fn is not None:
            roots.setdefault(
                (mi.rel, fn.qualname),
                f"Thread target at {mi.rel}:{node.lineno}",
            )
    for mi in modules:
        for qn, fn in mi.functions.items():
            if fn.cls is not None and fn.name.startswith("do_"):
                roots.setdefault(
                    (mi.rel, qn), "HTTP handler thread"
                )
            if mi.rel in _POOL_FILES and (
                fn.cls == "RewardPool" or mi.rel.endswith(
                    "reward_worker.py"
                )
            ):
                roots.setdefault((mi.rel, qn), "reward pool")
    return roots


def reachable_from_roots(
    modules: List[ModuleInfo], ctx: CheckContext,
) -> Dict[Tuple[str, str], str]:
    """The roots closed over nested defs + the package call graph —
    the CST-JIT traced-set machinery pointed at concurrency roots."""
    roots = collect_roots(modules)
    by_mod = {m.rel: m for m in modules}
    reach: Dict[Tuple[str, str], str] = dict(roots)
    seeds = [
        by_mod[rel].functions[qn]
        for (rel, qn) in roots
        if rel in by_mod and qn in by_mod[rel].functions
    ]

    def admit(fn: FuncInfo, reason: str) -> bool:
        k = (fn.module.rel, fn.qualname)
        if k in reach:
            return False
        reach[k] = reason
        return True

    expand_call_closure(modules, ctx, seeds, admit)
    return reach


def broad_handlers(
    modules: List[ModuleInfo],
) -> List[Tuple[ModuleInfo, FuncInfo, ast.ExceptHandler, bool]]:
    """Every broad ``except`` in every function:
    ``(module, function, handler, is_silent)``."""
    out = []
    for mi in modules:
        for qn, fn in mi.functions.items():
            for node in walk_body(fn):
                if isinstance(node, ast.ExceptHandler) and _is_broad(
                    node
                ):
                    out.append((mi, fn, node, _handler_is_silent(node)))
    return out


def _is_contained(fn: FuncInfo) -> bool:
    """Whether a thread target's body is exception-contained: every
    non-docstring top-level statement sits inside a ``try`` whose
    handlers include a broad, NON-silent one."""
    node = fn.node
    if isinstance(node, ast.Lambda):
        return False
    body = list(node.body)
    if body and isinstance(body[0], ast.Expr) and isinstance(
        body[0].value, ast.Constant
    ) and isinstance(body[0].value.value, str):
        body = body[1:]
    for stmt in body:
        if isinstance(stmt, ast.Try) and any(
            _is_broad(h) and not _handler_is_silent(h)
            for h in stmt.handlers
        ):
            continue
        return False
    return bool(body)


@register_checker("exceptions")
def check(modules: List[ModuleInfo], ctx: CheckContext) -> List[Finding]:
    out: List[Finding] = []
    reach = reachable_from_roots(modules, ctx)

    # ---- EXC-001: silent broad swallow on reachable code -------------
    for mi, fn, handler, silent in broad_handlers(modules):
        if not silent:
            continue
        k = (mi.rel, fn.qualname)
        if k not in reach:
            continue
        out.append(Finding(
            "CST-EXC-001", mi.rel, handler.lineno, fn.qualname,
            "broad `except` swallows the exception on code reachable "
            f"from a concurrency root ({reach[k]}) — a silently dead "
            "worker is exactly what the flight recorder exists to "
            "catch; re-raise, log, emit a flight event, or route the "
            "exception to the submitter",
        ))

    # ---- EXC-002: thread targets must be exception-contained ---------
    seen: Set[Tuple[str, str]] = set()
    for mi, node, fn in thread_targets(modules):
        if fn is None:
            continue
        k = (fn.module.rel, fn.qualname)
        if k in seen:
            continue
        seen.add(k)
        if isinstance(fn.node, ast.Lambda):
            # a lambda target delegating to a contained function is
            # fine; anything else cannot contain exceptions
            body = fn.node.body
            delegate = None
            if isinstance(body, ast.Call):
                delegate = _resolve_target(
                    fn.module, body.func, mi.qualname_of(node)
                )
            if delegate is not None and _is_contained(delegate):
                continue
            out.append(Finding(
                "CST-EXC-002", mi.rel, node.lineno,
                mi.qualname_of(node),
                "lambda thread target cannot contain exceptions — "
                "point the thread at a function whose body is wrapped "
                "in a logging broad `except`",
            ))
            continue
        if not _is_contained(fn):
            out.append(Finding(
                "CST-EXC-002", fn.module.rel, fn.line, fn.qualname,
                "thread-target function is not exception-contained — "
                "an exception here kills the thread with at best a "
                "stderr traceback nothing collects; wrap the body in "
                "`try/except Exception` that logs (and flight-dumps "
                "on worker death)",
            ))
    return out
