"""CLI for the invariant engine — pre-commit / bench preflight entry.

    python -m cst_captioning_tpu.analysis            # human output
    python -m cst_captioning_tpu.analysis --json     # machine-readable
    python -m cst_captioning_tpu.analysis --rules single_site,donation

Exit codes: 0 clean, 1 unsuppressed findings, 2 over the wall-clock
budget (``ANALYSIS_BUDGET_S``, default 30 — the same discipline as
``TIER1_BUDGET_S``: a slow pass silently eats the suite's headroom).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

from cst_captioning_tpu.analysis.engine import run_analysis, validate_report

DEFAULT_BUDGET_S = 30.0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cst_captioning_tpu.analysis",
        description="Run the invariant engine over the package.",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit the machine-readable report on stdout",
    )
    ap.add_argument(
        "--rules", default="",
        help="comma-separated rule families (default: all)",
    )
    ap.add_argument(
        "--root", default="",
        help="package root to scan (default: the installed package)",
    )
    args = ap.parse_args(argv)

    budget = float(os.environ.get("ANALYSIS_BUDGET_S", DEFAULT_BUDGET_S))
    report = run_analysis(
        Path(args.root) if args.root else None,
        rules=[r for r in args.rules.split(",") if r] or None,
    )
    if args.json:
        rec = validate_report(report.to_dict())
        print(json.dumps(rec, indent=2))
    else:
        print(report.render())
    if budget and report.duration_s > budget:
        print(
            f"ANALYSIS BUDGET EXCEEDED: {report.duration_s:.1f}s > "
            f"ANALYSIS_BUDGET_S={budget:.0f}s",
            file=sys.stderr,
        )
        return 2
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
