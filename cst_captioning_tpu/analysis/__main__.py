"""CLI for the invariant engine — pre-commit / bench preflight entry.

    python -m cst_captioning_tpu.analysis            # human output
    python -m cst_captioning_tpu.analysis --json     # machine-readable
    python -m cst_captioning_tpu.analysis --sarif    # SARIF 2.1.0
    python -m cst_captioning_tpu.analysis --rules single_site,donation
    python -m cst_captioning_tpu.analysis --cache          # warm reuse
    python -m cst_captioning_tpu.analysis --changed-only   # diff focus
    python -m cst_captioning_tpu.analysis \
        --baseline BASELINE.analysis.json --fail-on-new    # adoption

Exit codes: 0 clean, 1 unsuppressed findings, 2 over the wall-clock
budget (``ANALYSIS_BUDGET_S``, default 30 — the same discipline as
``TIER1_BUDGET_S``: a slow pass silently eats the suite's headroom).

The incremental cache (``--cache`` / ``--cache-dir PATH``, default
store ``.analysis_cache/``) reuses the full report when nothing that
can change it changed; ``--changed-only`` additionally restricts the
REPORTED findings (and the exit code) to files whose content hash
moved since the last cached run — the "what did my diff introduce"
view.  Both are plain content-hash machinery (analysis/cache.py), no
daemon, no state beyond one JSON file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

from cst_captioning_tpu.analysis.engine import run_analysis, validate_report

DEFAULT_BUDGET_S = 30.0
DEFAULT_CACHE_DIR = ".analysis_cache"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cst_captioning_tpu.analysis",
        description="Run the invariant engine over the package.",
    )
    out_fmt = ap.add_mutually_exclusive_group()
    out_fmt.add_argument(
        "--json", action="store_true",
        help="emit the machine-readable report on stdout",
    )
    out_fmt.add_argument(
        "--sarif", action="store_true",
        help="emit a SARIF 2.1.0 document on stdout",
    )
    ap.add_argument(
        "--rules", default="",
        help="comma-separated rule families (default: all)",
    )
    ap.add_argument(
        "--root", default="",
        help="package root to scan (default: the installed package)",
    )
    ap.add_argument(
        "--cache", action="store_true",
        help=f"enable the incremental cache ({DEFAULT_CACHE_DIR}/)",
    )
    ap.add_argument(
        "--cache-dir", default="",
        help="cache store directory (implies --cache)",
    )
    ap.add_argument(
        "--changed-only", action="store_true",
        help="report only findings in files changed since the last "
             "cached run (implies --cache)",
    )
    ap.add_argument(
        "--baseline", default="",
        help="committed baseline report JSON (a prior --json output); "
             "findings already in the baseline are reported as known",
    )
    ap.add_argument(
        "--fail-on-new", action="store_true",
        help="with --baseline: exit 1 only on findings NOT in the "
             "baseline — the incremental-adoption mode for new noisy "
             "rules",
    )
    args = ap.parse_args(argv)

    if args.fail_on_new and not args.baseline:
        ap.error("--fail-on-new requires --baseline")

    cache_dir = None
    if args.cache or args.cache_dir or args.changed_only:
        cache_dir = Path(args.cache_dir or DEFAULT_CACHE_DIR)

    budget = float(os.environ.get("ANALYSIS_BUDGET_S", DEFAULT_BUDGET_S))
    root = Path(args.root) if args.root else None

    changed = None
    if args.changed_only and cache_dir is not None:
        # Baseline BEFORE the run (the run overwrites the store).
        from cst_captioning_tpu.analysis import cache as _cache
        from cst_captioning_tpu.analysis.engine import (
            default_package_root,
        )

        files = _cache.file_digests(root or default_package_root())
        changed = _cache.changed_files(cache_dir, files)

    report = run_analysis(
        root,
        rules=[r for r in args.rules.split(",") if r] or None,
        cache_dir=cache_dir,
    )
    findings = report.findings
    if changed is not None:
        changed_set = set(changed)
        findings = [f for f in findings if f.file in changed_set]

    # Baseline diffing (ISSUE 15): a committed baseline report absorbs
    # KNOWN findings so a new noisy rule can be adopted incrementally —
    # the gate only trips on findings the baseline has never seen.
    # Identity is the (rule, file, symbol) triple, count-aware (two
    # same-triple findings against one baseline entry = one new), and
    # line-number-free so unrelated edits can't churn the diff.
    new_findings = None
    if args.baseline:
        new_findings = _diff_baseline(Path(args.baseline), findings)

    if args.json:
        rec = validate_report(report.to_dict())
        if new_findings is not None:
            rec["new_findings"] = [f.to_dict() for f in new_findings]
        print(json.dumps(rec, indent=2))
    elif args.sarif:
        from cst_captioning_tpu.analysis.sarif import (
            to_sarif,
            validate_sarif,
        )

        doc = validate_sarif(to_sarif(report.to_dict()))
        print(json.dumps(doc, indent=2))
    else:
        if changed is not None:
            lines = [f.render() for f in findings]
            lines.append(
                f"analysis (changed-only, {len(changed)} changed "
                f"file(s)): {len(findings)} finding(s), "
                f"{report.files_scanned} files, "
                f"{report.duration_s:.2f}s"
            )
            print("\n".join(lines))
        else:
            print(report.render())
    if new_findings is not None and not args.json:
        known = len(findings) - len(new_findings)
        lines = [f"NEW: {f.render()}" for f in new_findings]
        lines.append(
            f"baseline: {known} known finding(s) absorbed, "
            f"{len(new_findings)} new"
        )
        print("\n".join(lines))
    if budget and report.duration_s > budget:
        print(
            f"ANALYSIS BUDGET EXCEEDED: {report.duration_s:.1f}s > "
            f"ANALYSIS_BUDGET_S={budget:.0f}s",
            file=sys.stderr,
        )
        return 2
    if args.fail_on_new:
        return 0 if not new_findings else 1
    return 0 if not findings else 1


def _diff_baseline(path: Path, findings):
    """Findings not absorbed by the baseline report at ``path`` (a
    prior ``--json`` output, or a bare list of finding objects).
    Raises SystemExit(2) with a named reason on an unreadable or
    malformed baseline — a silently-empty baseline would absorb
    nothing and fail every adopter, or worse, absorb everything."""
    try:
        data = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        print(f"baseline {path} unreadable: {e}", file=sys.stderr)
        raise SystemExit(2)
    raw = data.get("findings") if isinstance(data, dict) else data
    if not isinstance(raw, list) or not all(
        isinstance(f, dict)
        and all(isinstance(f.get(k), str) for k in ("rule", "file", "symbol"))
        for f in raw
    ):
        print(
            f"baseline {path} malformed: expected a --json report or a "
            "list of {rule, file, symbol} objects",
            file=sys.stderr,
        )
        raise SystemExit(2)
    budgets: dict = {}
    for f in raw:
        key = (f["rule"], f["file"], f["symbol"])
        budgets[key] = budgets.get(key, 0) + 1
    new = []
    for f in findings:
        key = (f.rule, f.file, f.symbol)
        if budgets.get(key, 0) > 0:
            budgets[key] -= 1
        else:
            new.append(f)
    return new


if __name__ == "__main__":
    sys.exit(main())
