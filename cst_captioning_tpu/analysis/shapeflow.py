"""CST-SHP: static recompile-storm detection (ISSUE 15).

The jit_registry records WHAT bounds each site's recompiles as prose;
the shape discipline that makes the prose true — pow2 slot banks,
padded admit buckets, the serving batch ladder — lives in code the
registry never sees.  These rules close the gap (catalogue in
docs/ANALYSIS.md):

* **CST-SHP-001** — every jit site must have an
  ``analysis/jit_registry.py::SHAPE_LADDER_REGISTRY`` entry declaring
  the shape family its array params may see (``fixed`` /
  ``enumerated`` / ``probe``) and, for enumerated ladders, the bucket
  functions that quantize runtime counts onto the ladder.  Stale
  entries and bucket functions that resolve to no live def fire too.
  On top, the dataflow half: a device-array creation whose dimension
  PROVABLY derives from ``len(...)`` (the abstract interpreter's
  data-dependent taint) without passing a registered bucket function,
  in serving/decoding dispatch code, is a statically-detected
  recompile storm — one compile per distinct queue depth.
* **CST-SHP-002** — AOT enumeration drift: in a class that ships the
  artifact contract (defines BOTH ``aot_variant_keys`` and
  ``aot_lower``), (a) the f-string variant-key prefixes the two
  methods emit must agree, (b) every compiled-variant builder the
  class defines (methods named ``_*_fn``) must be lowered by
  ``aot_lower``, and (c) the ladder sources ``warmup`` walks
  (``bank_ladder``, ``warm_admit_counts``) must also drive
  ``aot_variant_keys`` — a reachable (bank, bucket, transition)
  combination missing from the AOT enumeration is a cold-start
  surprise the loader cannot refuse.
* **CST-SHP-003** — a Python ``for``/``while`` whose trip count reads
  ``.shape`` inside traced code: the loop unrolls at trace time, once
  per shape — a per-shape graph-size blowup the scan/fori primitives
  exist to avoid.
"""

from __future__ import annotations

import ast
import time
from typing import Dict, List, Optional, Set, Tuple

from cst_captioning_tpu.analysis import jit_registry
from cst_captioning_tpu.analysis.astutil import (
    ModuleInfo,
    call_name,
    walk_body,
)
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    register_checker,
)
from cst_captioning_tpu.analysis import typeflow as tfmod
from cst_captioning_tpu.analysis.typeflow import dim_is_data_dependent

# Device-array creators whose shape argument the dataflow half audits.
_CREATORS = ("zeros", "ones", "empty", "full")
# Dispatch surfaces where a data-dependent device shape means a
# recompile per distinct count (host-side metrics/eval assembly is out
# of scope — it never crosses a jit boundary at varying shapes).
# Matched as path COMPONENTS so the corpus can mirror the layout under
# a subdirectory, like the thread-safety corpus does.
_DISPATCH_DIRS = ("serving", "decoding")


def _in_dispatch_dirs(rel: str) -> bool:
    return any(seg in _DISPATCH_DIRS for seg in rel.split("/")[:-1])


def _ladder_entry_ok(entry) -> Optional[str]:
    if entry.kind not in ("fixed", "enumerated", "probe"):
        return f"unknown ladder kind {entry.kind!r}"
    if entry.kind == "enumerated" and not entry.bucket_fns:
        return (
            "an enumerated ladder must name the bucket function(s) "
            "that quantize runtime counts onto it"
        )
    return None


def _check_ladder_registry(
    modules: List[ModuleInfo],
) -> List[Finding]:
    from cst_captioning_tpu.analysis.donation import collect_jit_sites

    out: List[Finding] = []
    reg = jit_registry.SHAPE_LADDER_REGISTRY
    seen: Set[str] = set()
    for key, mi, call, sym in collect_jit_sites(modules):
        seen.add(key)
        entry = reg.get(key)
        if entry is None:
            out.append(Finding(
                "CST-SHP-001", mi.rel, call.lineno, sym,
                f"jit site `{key}` has no SHAPE_LADDER_REGISTRY entry "
                "— declare the shape family its array params may see "
                "(fixed / enumerated ladder / probe) and, for "
                "ladders, the bucket functions that enforce it; an "
                "unladdered site is a recompile storm waiting for a "
                "data-dependent shape",
            ))
            continue
        bad = _ladder_entry_ok(entry)
        if bad:
            out.append(Finding(
                "CST-SHP-001", mi.rel, call.lineno, sym,
                f"SHAPE_LADDER_REGISTRY entry `{key}`: {bad}",
            ))
    scanned = {m.rel for m in modules}
    # qualnames defined anywhere in the scan, for bucket-fn rot checks
    defined: Set[str] = set()
    for mi in modules:
        for qn in mi.functions:
            defined.add(f"{mi.rel}::{qn}")
    for key in sorted(reg):
        rel = key.split("::", 1)[0]
        if rel not in scanned:
            continue
        if key not in seen:
            out.append(Finding(
                "CST-SHP-001", "analysis/jit_registry.py", 1, key,
                f"stale SHAPE_LADDER_REGISTRY entry `{key}` matches "
                "no live jit site — the code moved; update or remove "
                "the entry",
            ))
        for fq in reg[key].bucket_fns:
            if fq.split("::", 1)[0] in scanned and fq not in defined:
                out.append(Finding(
                    "CST-SHP-001", "analysis/jit_registry.py", 1, key,
                    f"ladder entry `{key}` names bucket function "
                    f"`{fq}` which resolves to no live def — the "
                    "quantizer was renamed or removed; the ladder "
                    "prose no longer matches the code",
                ))
    return out


def _check_data_dependent_dims(
    modules: List[ModuleInfo], tf
) -> List[Finding]:
    out: List[Finding] = []
    for mi in modules:
        if not _in_dispatch_dirs(mi.rel):
            continue
        for qn, fn in mi.functions.items():
            if not isinstance(
                fn.node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            types = tf.types_of(fn)
            for node in walk_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node) or ""
                parts = name.split(".")
                # Device-array creators only: a len()-shaped np host
                # buffer is result assembly (it never compiles); a
                # len()-shaped jnp array IS a per-count compile the
                # moment it meets a jit boundary.
                if parts[-1] not in _CREATORS or len(parts) < 2 or (
                    parts[0] not in ("jnp", "jax")
                ):
                    continue
                if not node.args:
                    continue
                shape = types._shape_arg(node.args[0], 0)
                if not shape:
                    continue
                for d in shape:
                    if dim_is_data_dependent(d):
                        out.append(Finding(
                            "CST-SHP-001", mi.rel, node.lineno, qn,
                            f"array created with data-dependent dim "
                            f"`{d}` (derives from len(...) with no "
                            "registered ladder bucket in the chain) — "
                            "a distinct compile per distinct count if "
                            "this shape reaches a jit boundary; route "
                            "the count through the site's bucket "
                            "function first",
                        ))
                        break
    return out


# --------------------------------------------------- AOT drift (SHP-002)

def _fstring_key_prefixes(fn_node: ast.AST) -> Set[str]:
    """Prefixes (text up to the first ':') of f-string/str literals
    that look like variant keys (``tick:S8:A4``)."""
    out: Set[str] = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.JoinedStr):
            first = n.values[0] if n.values else None
            if isinstance(first, ast.Constant) and isinstance(
                first.value, str
            ) and ":" in first.value:
                out.add(first.value.split(":", 1)[0])
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            if ":" in n.value and n.value.split(":", 1)[0].isidentifier():
                out.add(n.value.split(":", 1)[0])
    return out


def _self_attr_reads(fn_node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Attribute) and isinstance(
            n.value, ast.Name
        ) and n.value.id == "self":
            out.add(n.attr)
    return out


# Ladder sources the enumeration must share with warmup: the bank
# ladder and the admit-bucket closure.
_LADDER_SOURCES = ("bank_ladder", "warm_admit_counts")


def aot_contract_classes(
    modules: List[ModuleInfo],
) -> List[Tuple[ModuleInfo, str, Dict[str, ast.AST]]]:
    """Classes shipping the AOT artifact contract (both
    ``aot_variant_keys`` and ``aot_lower``) — the drift-rule surface,
    exposed so the vacuous-green guard can pin discovery of the real
    ``SlotDecoder``."""
    out = []
    for mi in modules:
        for cls_name, cls in mi.classes.items():
            methods: Dict[str, ast.AST] = {}
            for stmt in cls.body:
                if isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    methods[stmt.name] = stmt
            if "aot_variant_keys" in methods and "aot_lower" in methods:
                out.append((mi, cls_name, methods))
    return out


def _check_aot_drift(modules: List[ModuleInfo]) -> List[Finding]:
    out: List[Finding] = []
    for mi, cls_name, methods in aot_contract_classes(modules):
        keys_fn = methods["aot_variant_keys"]
        lower_fn = methods["aot_lower"]
        kp = _fstring_key_prefixes(keys_fn)
        lp = _fstring_key_prefixes(lower_fn)
        if kp != lp:
            out.append(Finding(
                "CST-SHP-002", mi.rel, keys_fn.lineno,
                f"{cls_name}.aot_variant_keys",
                f"variant-key families drifted: aot_variant_keys "
                f"emits {sorted(kp)} but aot_lower builds "
                f"{sorted(lp)} — the loader's key-set refusal "
                "cannot catch a family the enumeration never names",
            ))
        lowered = _self_attr_reads(lower_fn)
        for name, m in sorted(methods.items()):
            if name.startswith("_") and name.endswith("_fn") and (
                name not in lowered
            ):
                out.append(Finding(
                    "CST-SHP-002", mi.rel, m.lineno,
                    f"{cls_name}.{name}",
                    f"compiled-variant builder `{name}` is never "
                    "lowered by aot_lower — its variants compile "
                    "at first traffic instead of boot (the "
                    "cold-start surprise the artifact exists to "
                    "remove); add it to the AOT enumeration",
                ))
        if "warmup" in methods:
            warm_reads = _self_attr_reads(methods["warmup"])
            key_reads = _self_attr_reads(keys_fn)
            for src in _LADDER_SOURCES:
                if src in warm_reads and src not in key_reads:
                    out.append(Finding(
                        "CST-SHP-002", mi.rel, keys_fn.lineno,
                        f"{cls_name}.aot_variant_keys",
                        f"warmup walks `{src}` but aot_variant_keys "
                        "never reads it — the enumeration cannot "
                        "cover combinations it does not iterate; "
                        "drive both from the same ladder source",
                    ))
    return out


# ------------------------------------------- trace-time unroll (SHP-003)

def _reads_shape(expr: ast.AST, types) -> Optional[str]:
    """A ``.shape`` read inside ``expr`` (directly or through the
    def-use chains), rendered for the finding message — or None."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and n.attr in ("shape", "ndim"):
            from cst_captioning_tpu.analysis.astutil import dotted

            return dotted(n) or f"<expr>.{n.attr}"
        if isinstance(n, ast.Name):
            b = types.du.reaching_def(n)
            if b is not None and b.value is not None and b.kind in (
                "assign", "walrus",
            ):
                for sub in ast.walk(b.value):
                    if isinstance(sub, ast.Attribute) and sub.attr in (
                        "shape", "ndim",
                    ):
                        from cst_captioning_tpu.analysis.astutil import (
                            dotted,
                        )

                        return dotted(sub) or f"<expr>.{sub.attr}"
    return None


def _check_shape_unroll(tf) -> List[Finding]:
    out: List[Finding] = []
    for fn in tf.traced_functions():
        mi = fn.module
        types = tf.types_of(fn)
        for node in walk_body(fn):
            if isinstance(node, ast.For):
                it = node.iter
                if isinstance(it, ast.Call) and (
                    call_name(it) or ""
                ).rsplit(".", 1)[-1] == "range":
                    for a in it.args:
                        hit = _reads_shape(a, types)
                        if hit:
                            out.append(Finding(
                                "CST-SHP-003", mi.rel, it.lineno,
                                fn.qualname,
                                f"Python `for … in range({hit})` "
                                "inside traced code unrolls the loop "
                                "at trace time, once per shape — a "
                                "per-shape graph-size blowup; use "
                                "lax.scan/fori_loop (or hoist the "
                                "loop out of the jit boundary)",
                            ))
                            break
            elif isinstance(node, ast.While):
                hit = _reads_shape(node.test, types)
                if hit:
                    out.append(Finding(
                        "CST-SHP-003", mi.rel, node.lineno, fn.qualname,
                        f"Python `while` on `{hit}` inside traced "
                        "code — trip count is fixed at trace time; "
                        "use lax.while_loop",
                    ))
    return out


@register_checker("shapeflow")
def check(modules: List[ModuleInfo], ctx: CheckContext) -> List[Finding]:
    t0 = time.perf_counter()
    tf = tfmod.build(modules, ctx)
    out: List[Finding] = []
    out.extend(_check_ladder_registry(modules))
    out.extend(_check_data_dependent_dims(modules, tf))
    out.extend(_check_aot_drift(modules))
    out.extend(_check_shape_unroll(tf))
    tfmod.note_duration(time.perf_counter() - t0)
    return out
