"""CST-RNG: PRNG key discipline over the def-use dataflow layer.

Every parity pin in docs/PARITY.md — slot-geometry invariance,
padded-vs-slot bit-identical params, fixed-seed reproducibility —
ultimately rests on disciplined JAX key handling: keys are split or
folded, never reused; every draw's key traces back to the seeded root;
rollout-path token draws are keyed by ROW IDENTITY
(``fold_in(fold_in(rng, row_id), t)``, PARITY r10) so slot position and
admission order cannot change a sampled token.  These rules
machine-check that contract with :mod:`analysis.dataflow`'s per-function
def-use chains:

* CST-RNG-001 — a key binding consumed by TWO draws without an
  intervening ``split``/``fold_in`` redefinition (the classic JAX
  key-reuse bug: silently correlated randomness), including the loop
  flavor — a draw inside a ``for``/``while`` whose key is bound
  outside the loop reuses the key every iteration.  Draws on the two
  arms of one ``if``/``else`` are mutually exclusive and do NOT fire.
* CST-RNG-002 — untracked entropy: a ``jax.random.PRNGKey``/``key``
  root seeded from a nondeterministic source (``time.*``,
  ``np.random.*``, ``os.urandom``, stdlib ``random.*``, ``secrets``,
  ``uuid``), or a draw whose key is a free name bound nowhere
  (parameter, enclosing scope, module level, import, or attribute all
  count as tracked).  Untracked entropy breaks every fixed-seed
  bit-identical pin at once.
* CST-RNG-003 — a rollout-flavored token draw
  (``jax.random.categorical``, vmapped or direct) outside
  :data:`ROW_KEYED_ALLOWED` — the CST-DEC single-site discipline
  applied to the sampling recurrence: the row-keyed contract lives in
  ``decoding/core.py`` (``row_sample_fn``), and the legacy batch
  stream in ``models/captioner.py``; a new call site would bypass the
  PARITY r10 row-keying argument entirely.

Derivation calls (``split``/``fold_in``) are transparent to the
provenance walk; ``PRNGKey``/``key`` with a deterministic seed
expression IS the registered root (the seed is config state, pinned at
``train.seed``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from cst_captioning_tpu.analysis.astutil import (
    FuncInfo,
    ModuleInfo,
    call_name,
    dotted,
)
from cst_captioning_tpu.analysis.dataflow import (
    Binding,
    DefUse,
    provenance_chain,
)
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    register_checker,
)

# Files allowed to call the token-sampling draw (CST-RNG-003) — the
# row-keyed noise source (decoding/core.py::row_sample_fn + the legacy
# batch stream of decode_step) and the scan-path scheduled-sampling /
# rollout draw inside the model.  Extending this list is a conscious
# decision, exactly like the CST-DEC allowlists.
ROW_KEYED_ALLOWED = frozenset({
    "decoding/core.py",
    "models/captioner.py",
})

# jax.random functions that CONSUME a key (first arg / key=).
DRAW_FNS = frozenset({
    "categorical", "uniform", "normal", "bernoulli", "bits", "gumbel",
    "truncated_normal", "choice", "randint", "permutation", "shuffle",
    "exponential", "laplace", "poisson", "gamma", "beta", "dirichlet",
    "multivariate_normal", "rademacher", "cauchy", "logistic",
    "loggamma", "orthogonal", "binomial", "ball",
})
# Functions that DERIVE fresh keys from a parent (transparent to the
# provenance walk; reuse of the parent across derivations is the
# intended fold_in idiom, not a bug).
DERIVE_FNS = frozenset({"split", "fold_in", "clone"})
# Root-key constructors: a deterministic seed here IS the registry.
ROOT_FNS = frozenset({"PRNGKey", "key"})

_NONDET_PREFIXES = (
    "time.", "np.random.", "numpy.random.", "random.", "secrets.",
    "uuid.", "os.urandom", "os.getrandom",
)


def _resolved(mi: ModuleInfo, node: ast.Call) -> str:
    """Dotted callee resolved through the import map (so ``from
    jax.random import categorical as cat`` still reads
    ``jax.random.categorical``)."""
    callee = dotted(node.func)
    if not callee:
        return ""
    head, _, rest = callee.partition(".")
    target = mi.imports.get(head)
    if target:
        return target + (("." + rest) if rest else "")
    return callee


def _random_fn(mi: ModuleInfo, node: ast.Call) -> str:
    """``"categorical"`` for any spelling of a ``jax.random.*`` call,
    ``""`` otherwise.  numpy's host RNG is CST-JIT-001's domain and is
    explicitly excluded."""
    name = _resolved(mi, node)
    if not name.startswith("jax.random."):
        # stdlib random / np.random are host RNG (CST-JIT-001's
        # domain), not key consumers.
        return ""
    fn = name.split(".")[-1]
    return fn if fn in DRAW_FNS | DERIVE_FNS | ROOT_FNS else ""


def _vmapped_draw(mi: ModuleInfo, node: ast.Call) -> str:
    """``jax.vmap(jax.random.categorical)(keys, x)`` — the row-keyed
    idiom: the OUTER call is the draw, its first arg the key batch."""
    if not isinstance(node.func, ast.Call):
        return ""
    inner = node.func
    if call_name(inner).split(".")[-1] != "vmap" or not inner.args:
        return ""
    target = inner.args[0]
    if isinstance(target, ast.Call):
        return ""
    name = dotted(target)
    if not name:
        return ""
    head, _, rest = name.partition(".")
    resolved = mi.imports.get(head)
    full = (resolved + ("." + rest if rest else "")) if resolved else name
    if full.startswith("jax.random.") and full.split(".")[-1] in DRAW_FNS:
        return full.split(".")[-1]
    return ""


def _key_arg(node: ast.Call) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg == "key":
            return kw.value
    return node.args[0] if node.args else None


def draw_sites(
    modules: List[ModuleInfo],
) -> List[Tuple[ModuleInfo, FuncInfo, ast.Call, str, Optional[ast.AST]]]:
    """Every key-consuming draw site in the package:
    ``(module, function, call, fn_name, key_expr)``.  The vacuous-green
    guard in tests pins that this discovers the REAL sampling sites
    (decode_step's categorical, the captioner's scheduled-sampling
    draws, the dropout bernoulli …)."""
    out = []
    for mi in modules:
        for qn, fn in mi.functions.items():
            for node in _body_calls(fn):
                name = _random_fn(mi, node)
                if name in DRAW_FNS:
                    out.append((mi, fn, node, name, _key_arg(node)))
                    continue
                vname = _vmapped_draw(mi, node)
                if vname:
                    out.append((
                        mi, fn, node, vname,
                        node.args[0] if node.args else None,
                    ))
    return out


def _body_calls(fn: FuncInfo):
    from cst_captioning_tpu.analysis.astutil import walk_body

    for node in walk_body(fn):
        if isinstance(node, ast.Call):
            yield node


def _ancestors(mi: ModuleInfo, node: ast.AST) -> List[ast.AST]:
    out = []
    cur = mi.parent.get(node)
    while cur is not None:
        out.append(cur)
        cur = mi.parent.get(cur)
    return out


def _in_subtree(root: ast.AST, node: ast.AST, mi: ModuleInfo) -> bool:
    cur = node
    while cur is not None:
        if cur is root:
            return True
        cur = mi.parent.get(cur)
    return False


def _disjoint_branches(
    mi: ModuleInfo, a: ast.AST, b: ast.AST
) -> bool:
    """Whether two nodes sit on mutually exclusive arms of one
    ``if``/``else`` (or ``try``/``except``) — both can never execute
    in the same pass, so a key consumed once per arm is a single
    consumption."""
    for anc in _ancestors(mi, a):
        if isinstance(anc, ast.If):
            a_in_body = any(_in_subtree(s, a, mi) for s in anc.body)
            a_in_else = any(_in_subtree(s, a, mi) for s in anc.orelse)
            b_in_body = any(_in_subtree(s, b, mi) for s in anc.body)
            b_in_else = any(_in_subtree(s, b, mi) for s in anc.orelse)
            if (a_in_body and b_in_else) or (a_in_else and b_in_body):
                return True
        if isinstance(anc, ast.Try):
            a_in_try = any(_in_subtree(s, a, mi) for s in anc.body)
            b_in_h = any(
                _in_subtree(h, b, mi) for h in anc.handlers
            )
            a_in_h = any(
                _in_subtree(h, a, mi) for h in anc.handlers
            )
            b_in_try = any(_in_subtree(s, b, mi) for s in anc.body)
            if (a_in_try and b_in_h) or (a_in_h and b_in_try):
                return True
    return False


def _enclosing_loops(
    mi: ModuleInfo, node: ast.AST, fn: FuncInfo
) -> List[ast.AST]:
    """``for``/``while`` ancestors of ``node`` within ``fn``'s body."""
    out = []
    cur = mi.parent.get(node)
    while cur is not None and cur is not fn.node:
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            out.append(cur)
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            break
        cur = mi.parent.get(cur)
    return out


def _nondet_entropy(mi: ModuleInfo, expr: ast.AST) -> Optional[str]:
    """Dotted name of a nondeterministic-source call inside ``expr``."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = _resolved(mi, node)
            if name.startswith(_NONDET_PREFIXES) or name in (
                "os.urandom", "os.getrandom",
            ):
                return name
    return None


def _module_level_names(mi: ModuleInfo) -> set:
    names = set(mi.imports)
    for node in mi.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            names.add(node.target.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            names.add(node.name)
    return names


def _key_through(mi: ModuleInfo):
    """Provenance ``through`` hook: derivation calls are transparent —
    keep chasing their parent-key operand."""
    def through(call: ast.Call) -> Optional[ast.AST]:
        name = _random_fn(mi, call)
        if name in DERIVE_FNS:
            return _key_arg(call)
        return None

    return through


def row_key_fold_depth(
    mi: ModuleInfo, fn: FuncInfo
) -> Optional[int]:
    """For a vmapped row-keyed draw inside ``fn``: the ``fold_in``
    nesting depth of the per-row key expression (2 for the PARITY r10
    ``fold_in(fold_in(rng, row_id), t)`` contract), or None when no
    such site exists.  The tests' vacuous-green guard pins this
    proves the REAL contract at ``decoding/core.py::row_sample_fn``."""
    du = DefUse(fn)
    for node in _body_calls(fn):
        if not _vmapped_draw(mi, node) or not node.args:
            continue
        key_expr = node.args[0]
        if not isinstance(key_expr, ast.Name):
            continue
        b = du.reaching_def(key_expr)
        if b is None or b.value is None:
            continue
        # keys = jax.vmap(lambda r, t: fold_in(fold_in(base, r), t))(…)
        for n in ast.walk(b.value):
            if isinstance(n, ast.Lambda):
                depth, cur = 0, n.body
                while isinstance(cur, ast.Call) and _random_fn(
                    mi, cur
                ) == "fold_in":
                    depth += 1
                    cur = _key_arg(cur)
                if depth:
                    return depth
    return None


@register_checker("rng")
def check(modules: List[ModuleInfo], ctx: CheckContext) -> List[Finding]:
    out: List[Finding] = []

    for mi in modules:
        mod_names = None  # lazy
        for qn, fn in mi.functions.items():
            sites = []
            for node in _body_calls(fn):
                name = _random_fn(mi, node)
                if name in ROOT_FNS:
                    src = _nondet_entropy(
                        mi, node.args[0] if node.args else node
                    )
                    if src is not None:
                        out.append(Finding(
                            "CST-RNG-002", mi.rel, node.lineno, qn,
                            f"PRNG root seeded from `{src}` — "
                            "nondeterministic entropy breaks every "
                            "fixed-seed bit-identical pin; seed from "
                            "config (train.seed) and derive with "
                            "fold_in/split",
                        ))
                    continue
                if name in DRAW_FNS:
                    sites.append((node, name, _key_arg(node)))
                    continue
                vname = _vmapped_draw(mi, node)
                if vname:
                    sites.append((
                        node, vname,
                        node.args[0] if node.args else None,
                    ))
            if not sites:
                continue
            # walk_body is stack-ordered; consumption counting needs
            # SOURCE order so the second draw is the one that fires
            sites.sort(key=lambda s: (s[0].lineno, s[0].col_offset))
            du = DefUse(fn)
            through = _key_through(mi)
            consumed: Dict[int, Tuple[ast.AST, Binding]] = {}
            for node, name, key in sites:
                # ---- RNG-003: token draws stay at the allowlisted
                # row-keyed definition sites -----------------------
                if name == "categorical" and mi.rel not in (
                    ROW_KEYED_ALLOWED
                ):
                    out.append(Finding(
                        "CST-RNG-003", mi.rel, node.lineno, qn,
                        "rollout-flavored token draw (categorical) "
                        "outside the row-keyed allowlist — sampled "
                        "tokens must come from decoding/core.py's "
                        "row-keyed machinery (fold_in(fold_in(rng, "
                        "row_id), t), PARITY r10) so slot geometry "
                        "and admission order cannot change any token",
                    ))
                if key is None or not isinstance(key, ast.Name):
                    continue
                # ---- RNG-002: key provenance through the def-use
                # chains (split/fold_in transparent) -----------------
                orig = provenance_chain(fn, du, key, through=through)
                if orig.kind == "free":
                    if mod_names is None:
                        mod_names = _module_level_names(mi)
                    if orig.name not in mod_names:
                        out.append(Finding(
                            "CST-RNG-002", mi.rel, node.lineno, qn,
                            f"draw `{name}` keyed by `{orig.name}`, "
                            "which is bound nowhere (not a parameter, "
                            "enclosing scope, module global or "
                            "import) — untracked entropy; thread the "
                            "key in from the seeded root",
                        ))
                b = du.reaching_def(key)
                if b is None:
                    continue
                # ---- RNG-001: one binding, one consumption ----------
                prev = consumed.get(id(b))
                if prev is not None and not _disjoint_branches(
                    mi, prev[0], node
                ):
                    out.append(Finding(
                        "CST-RNG-001", mi.rel, node.lineno, qn,
                        f"key `{key.id}` consumed again by `{name}` "
                        f"(first drawn at line {prev[0].lineno}) "
                        "without an intervening split/fold_in — "
                        "reused keys draw CORRELATED randomness "
                        "silently; split the key per draw",
                    ))
                else:
                    consumed[id(b)] = (node, b)
                # loop flavor: key bound outside the enclosing loop
                for loop in _enclosing_loops(mi, node, fn):
                    def_inside = (
                        b.stmt is not None
                        and b.kind != "param"
                        and _in_subtree(loop, b.stmt, mi)
                    )
                    if not def_inside:
                        out.append(Finding(
                            "CST-RNG-001", mi.rel, node.lineno, qn,
                            f"key `{key.id}` drawn inside a loop but "
                            "bound outside it — every iteration "
                            "reuses the same key (correlated draws); "
                            "derive a per-iteration key with "
                            "fold_in(key, i)",
                        ))
                        break
    return out
