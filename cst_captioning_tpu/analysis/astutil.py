"""Shared AST plumbing for the invariant engine: the module index
(parse the package once), dotted-name resolution, enclosing-scope
qualnames, and the intra-package call graph the checkers walk.

Pure stdlib-``ast`` on purpose: the analysis pass runs as a pre-commit /
bench preflight and inside tier-1, so it must not import jax (or the
package under analysis) — it READS source, it never executes it.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

PACKAGE = "cst_captioning_tpu"


def dotted(node: ast.AST) -> str:
    """``a.b.c`` for a Name/Attribute chain, ``""`` for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(node: ast.Call) -> str:
    """Dotted name of a call's callee (``""`` for computed callees)."""
    return dotted(node.func)


@dataclass
class FuncInfo:
    """One function/method definition (incl. nested defs and lambdas)."""

    module: "ModuleInfo"
    qualname: str                  # e.g. "ReplicaSet._worker_loop"
    node: ast.AST                  # FunctionDef | AsyncFunctionDef | Lambda
    cls: Optional[str] = None      # enclosing class name, if a method

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    @property
    def params(self) -> List[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    @property
    def line(self) -> int:
        return self.node.lineno


@dataclass
class ModuleInfo:
    """One parsed source file of the package under analysis."""

    path: Path
    rel: str                       # package-relative posix path
    tree: ast.Module
    source: str
    # name in this module -> fully dotted target it was imported from
    # ("cst_captioning_tpu.decoding.core.decode_step" for symbols,
    #  "cst_captioning_tpu.decoding.core" for module aliases).
    imports: Dict[str, str] = field(default_factory=dict)
    functions: Dict[str, FuncInfo] = field(default_factory=dict)
    classes: Dict[str, ast.ClassDef] = field(default_factory=dict)
    parent: Dict[ast.AST, ast.AST] = field(default_factory=dict)

    @property
    def modname(self) -> str:
        stem = self.rel[:-3].replace("/", ".")
        if stem.endswith(".__init__"):
            stem = stem[: -len(".__init__")]
        return f"{PACKAGE}.{stem}" if stem else PACKAGE

    def qualname_of(self, node: ast.AST) -> str:
        """Dotted path of enclosing defs/classes for any node
        ("<module>" at top level)."""
        parts: List[str] = []
        cur = self.parent.get(node)
        while cur is not None:
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                parts.append(cur.name)
            cur = self.parent.get(cur)
        return ".".join(reversed(parts)) or "<module>"


def _index_module(mi: ModuleInfo) -> None:
    for node in ast.walk(mi.tree):
        for child in ast.iter_child_nodes(node):
            mi.parent[child] = node

    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Import):
            for al in node.names:
                mi.imports[al.asname or al.name.split(".")[0]] = al.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            base = node.module
            if node.level:  # relative import -> resolve against package
                pkg_parts = mi.modname.split(".")[: -node.level]
                base = ".".join(pkg_parts + ([node.module] if node.module else []))
            for al in node.names:
                mi.imports[al.asname or al.name] = f"{base}.{al.name}"

    class _V(ast.NodeVisitor):
        def __init__(self) -> None:
            self.stack: List[str] = []
            self.lambda_seq = 0

        def _add(self, node, name: str, cls: Optional[str]) -> None:
            qn = ".".join(self.stack + [name])
            mi.functions[qn] = FuncInfo(mi, qn, node, cls=cls)

        def visit_ClassDef(self, node: ast.ClassDef) -> None:
            mi.classes[node.name] = node
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
            cls = self.stack[-1] if (
                self.stack and self.stack[-1] in mi.classes
            ) else None
            self._add(node, node.name, cls)
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

        def visit_Lambda(self, node: ast.Lambda) -> None:
            self.lambda_seq += 1
            self._add(node, f"<lambda#{self.lambda_seq}>", None)
            self.generic_visit(node)

    _V().visit(mi.tree)


def scan_package(root: Path) -> List[ModuleInfo]:
    """Parse every ``.py`` under ``root`` once, sorted by relative path.
    ``root`` is the package directory (the one holding ``__init__.py``)
    or any directory of loose files (the seeded-violation corpus)."""
    mods: List[ModuleInfo] = []
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(root).as_posix()
        src = path.read_text()
        try:
            tree = ast.parse(src, filename=str(path))
        except SyntaxError as e:  # corpus files must still be valid py
            raise SyntaxError(f"{rel}: {e}") from e
        mi = ModuleInfo(path=path, rel=rel, tree=tree, source=src)
        _index_module(mi)
        mods.append(mi)
    return mods


class PackageIndex:
    """Cross-module symbol table + call-graph resolution."""

    def __init__(self, modules: List[ModuleInfo]):
        self.modules = modules
        self.by_rel: Dict[str, ModuleInfo] = {m.rel: m for m in modules}
        self.by_modname: Dict[str, ModuleInfo] = {
            m.modname: m for m in modules
        }
        # (modname, top-level-or-method qualname) -> FuncInfo
        self.funcs: Dict[Tuple[str, str], FuncInfo] = {}
        for m in modules:
            for qn, fi in m.functions.items():
                self.funcs[(m.modname, qn)] = fi
        # method name -> [FuncInfo] across all classes (fallback lookup)
        self.methods_by_name: Dict[str, List[FuncInfo]] = {}
        for (_, qn), fi in self.funcs.items():
            if fi.cls is not None:
                self.methods_by_name.setdefault(fi.name, []).append(fi)

    def resolve_call(
        self, mi: ModuleInfo, caller: FuncInfo, node: ast.Call
    ) -> List[FuncInfo]:
        """Best-effort resolution of a call to package functions.

        Handles: local names, ``from pkg.x import f`` names, module
        aliases (``core.decode_step``), ``self.method`` within a class,
        and flax ``X.apply(..., method="name")`` indirection (resolved
        to every package method of that name — the model hook pattern).
        Unresolvable callees return [].
        """
        name = call_name(node)
        out: List[FuncInfo] = []
        if not name:
            return out

        # flax apply indirection: X.apply(params, ..., method="m")
        if name.endswith(".apply"):
            target = "__call__"
            for kw in node.keywords:
                if kw.arg == "method" and isinstance(kw.value, ast.Constant):
                    target = str(kw.value.value)
            return list(self.methods_by_name.get(target, []))

        head, _, rest = name.partition(".")
        if head == "self" and caller.cls is not None:
            if rest and "." not in rest:
                fi = mi.functions.get(f"{caller.cls}.{rest}")
                return [fi] if fi else []
            return out
        if not rest:
            # plain name: sibling def in the same scope chain, then
            # module level, then imports
            scope = caller.qualname.rsplit(".", 1)[0]
            for qn in (f"{scope}.{head}", head):
                fi = mi.functions.get(qn)
                if fi:
                    return [fi]
            imp = mi.imports.get(head)
            if imp and imp.startswith(PACKAGE):
                modname, _, sym = imp.rpartition(".")
                m2 = self.by_modname.get(modname)
                if m2 and sym in m2.functions:
                    return [m2.functions[sym]]
            return out
        # dotted: module alias (core.decode_step), imported class
        # (ChaosEngine.from_config), or local class attr
        imp = mi.imports.get(head)
        if imp and imp.startswith(PACKAGE):
            m2 = self.by_modname.get(imp)
            if m2 and rest in m2.functions:
                return [m2.functions[rest]]
            modname, _, clsname = imp.rpartition(".")
            m2 = self.by_modname.get(modname)
            if m2 and f"{clsname}.{rest}" in m2.functions:
                return [m2.functions[f"{clsname}.{rest}"]]
        if head in mi.classes and "." not in rest:
            fi = mi.functions.get(f"{head}.{rest}")
            if fi:
                return [fi]
        return out


def walk_body(fn: FuncInfo, *, into_nested: bool = False):
    """Walk a function's own body; by default stop at nested def/lambda
    boundaries (nested defs are their own FuncInfo — decorators and
    default expressions of a nested def still belong to the parent and
    are walked)."""
    stack = list(ast.iter_child_nodes(fn.node))
    while stack:
        node = stack.pop()
        if not into_nested and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            # still surface the nested def's decorators/defaults
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.extend(node.decorator_list)
                stack.extend(
                    d
                    for d in node.args.defaults + node.args.kw_defaults
                    if d is not None
                )
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def func_body_calls(fn: FuncInfo) -> Iterable[ast.Call]:
    """Every Call in a function's own body (nested defs excluded)."""
    for node in walk_body(fn):
        if isinstance(node, ast.Call):
            yield node


