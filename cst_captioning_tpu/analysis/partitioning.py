"""CST-SHD: partition-rule and sharding-constraint discipline.

The 2D (data x model) mesh work (ISSUE 9) hangs every placement
decision off ONE literal table — ``parallel/partition.py``'s
``PARTITION_RULES`` — and a handful of ``with_sharding_constraint``
activation pins.  Both rot silently: a new param family falls through
to an accidental default, a constraint site appears without a recorded
retrace/propagation story, a renamed tensor leaves a rule matching
nothing.  Three rules machine-check the contracts (catalogue in
docs/ANALYSIS.md):

* **CST-SHD-001** — every leaf in ``KNOWN_PARAM_LEAVES`` must match
  EXACTLY ONE rule regex: an unmatched leaf means a new tensor has no
  placement decision; a doubly-matched leaf means the table is
  ambiguous (first-match-wins would hide the conflict).
* **CST-SHD-002** — every ``with_sharding_constraint`` call site (and
  every call through the ``partition.constrain`` helper) must be
  registered in ``analysis/jit_registry.py::
  SHARDING_CONSTRAINT_REGISTRY`` with a prose justification of what the
  pin buys (which all-gather it prevents / which partitioner cliff it
  avoids); stale registry entries are findings too.  pjit/jit sites are
  already covered by CST-DON-002.
* **CST-SHD-003** — a rule whose regex matches NO known leaf is stale:
  the tensor it governed was renamed or removed.
* **CST-SHD-004** — every ``shard_map`` call site (raw jax API, the
  ``parallel/mesh.py`` compat wrapper, or its ``_shard_map_impl``
  indirection) must be registered in ``analysis/jit_registry.py::
  SHARD_MAP_REGISTRY`` with a prose justification of the collective
  layout it buys (which per-step gather the manual specs avoid, what
  bounds its recompiles); stale entries fire too.  A shard_map with no
  story is usually a partitioner workaround nobody can maintain.
* **CST-SHD-005** — the fused-decode kernel GATE must be table-driven:
  ``DECODE_KERNEL_CAPS`` (decoding/core.py) must be a literal dict
  covering every ``use_pallas_*`` field ``ModelConfig`` declares (and
  naming no undeclared flag — stale rows fire), and any module
  defining a ``_decode_kernel_gate`` function must route it through
  ``kernel_supports`` — an ad-hoc mesh condition in the gate is
  exactly the hardcoded refusal ISSUE 14 removed.

The checker is table-driven off the AST (``ast.literal_eval`` of the
module-level assignments), so it runs jax-free like every other
family, and it applies to ANY scanned module defining the names — the
corpus seeds violations in toy tables without touching the real ones.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from cst_captioning_tpu.analysis import jit_registry
from cst_captioning_tpu.analysis.astutil import ModuleInfo, call_name
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    register_checker,
)

RULES_NAME = "PARTITION_RULES"
LEAVES_NAME = "KNOWN_PARAM_LEAVES"

# Call names that ARE a sharding constraint: the raw jax API under any
# import spelling, plus the package's partition.constrain helper.
_RAW_CONSTRAINT = "with_sharding_constraint"
_HELPER_NAMES = ("constrain",)


def _module_assign(mi: ModuleInfo, name: str) -> Optional[ast.Assign]:
    for node in mi.tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    return node
    return None


def _rule_table(
    node: ast.Assign,
) -> Optional[List[Tuple[str, int]]]:
    """[(regex string, lineno)] from a literal PARTITION_RULES tuple —
    None when the assignment isn't the expected literal shape."""
    val = node.value
    if not isinstance(val, (ast.Tuple, ast.List)):
        return None
    out: List[Tuple[str, int]] = []
    for elt in val.elts:
        if not (
            isinstance(elt, (ast.Tuple, ast.List))
            and elt.elts
            and isinstance(elt.elts[0], ast.Constant)
            and isinstance(elt.elts[0].value, str)
        ):
            return None
        out.append((elt.elts[0].value, elt.elts[0].lineno))
    return out


def _leaf_list(node: ast.Assign) -> Optional[List[str]]:
    try:
        val = ast.literal_eval(node.value)
    except (ValueError, SyntaxError):
        return None
    if isinstance(val, (tuple, list)) and all(
        isinstance(x, str) for x in val
    ):
        return list(val)
    return None


def _check_rule_tables(mi: ModuleInfo) -> List[Finding]:
    rules_node = _module_assign(mi, RULES_NAME)
    leaves_node = _module_assign(mi, LEAVES_NAME)
    if rules_node is None or leaves_node is None:
        return []
    rules = _rule_table(rules_node)
    leaves = _leaf_list(leaves_node)
    out: List[Finding] = []
    if rules is None or leaves is None:
        out.append(Finding(
            "CST-SHD-001", mi.rel,
            (rules_node if rules is None else leaves_node).lineno,
            "<module>",
            f"{RULES_NAME}/{LEAVES_NAME} must be literal tuples the "
            "jax-free pass can read off the AST",
        ))
        return out
    compiled: List[Tuple[str, int, re.Pattern]] = []
    for pat, lineno in rules:
        try:
            compiled.append((pat, lineno, re.compile(pat)))
        except re.error as e:
            out.append(Finding(
                "CST-SHD-001", mi.rel, lineno, RULES_NAME,
                f"rule regex {pat!r} does not compile: {e}",
            ))
    for leaf in leaves:
        hits = [pat for pat, _, rx in compiled if rx.search(leaf)]
        if len(hits) == 1:
            continue
        what = (
            "matches NO partition rule — a new tensor has no placement "
            "decision; add a rule"
            if not hits
            else f"matches {len(hits)} rules {hits} — the table is "
            "ambiguous; rules must partition the leaves exactly once"
        )
        out.append(Finding(
            "CST-SHD-001", mi.rel, leaves_node.lineno, LEAVES_NAME,
            f"param leaf {leaf!r} {what}",
        ))
    for pat, lineno, rx in compiled:
        if not any(rx.search(leaf) for leaf in leaves):
            out.append(Finding(
                "CST-SHD-003", mi.rel, lineno, RULES_NAME,
                f"partition rule {pat!r} matches no known param leaf — "
                "the tensor it governed was renamed or removed; update "
                "or delete the rule",
            ))
    return out


def _is_constraint_call(node: ast.Call) -> bool:
    name = call_name(node)
    if not name:
        return False
    last = name.rsplit(".", 1)[-1]
    return last == _RAW_CONSTRAINT or last in _HELPER_NAMES


# Call names that ARE a shard_map entry: the raw/top-level jax API, the
# parallel/mesh.py version-compat wrapper, and the wrapper's resolved
# implementation alias.
_SHARD_MAP_NAMES = ("shard_map", "_shard_map_impl")


def _is_shard_map_call(node: ast.Call) -> bool:
    name = call_name(node)
    if not name:
        return False
    return name.rsplit(".", 1)[-1] in _SHARD_MAP_NAMES


def _check_shard_map_sites(
    mi: ModuleInfo, seen: Dict[str, Tuple[str, int, str]]
) -> List[Finding]:
    out: List[Finding] = []
    flagged = set()
    for node in ast.walk(mi.tree):
        if not (isinstance(node, ast.Call) and _is_shard_map_call(node)):
            continue
        sym = mi.qualname_of(node)
        key = f"{mi.rel}::{sym}"
        seen[key] = (mi.rel, node.lineno, sym)
        if key in jit_registry.SHARD_MAP_REGISTRY:
            continue
        if key in flagged:
            continue
        flagged.add(key)
        out.append(Finding(
            "CST-SHD-004", mi.rel, node.lineno, sym,
            f"shard_map site `{key}` is not registered — add it to "
            "analysis/jit_registry.py::SHARD_MAP_REGISTRY with the "
            "collective layout it buys (which per-step gather the "
            "manual specs avoid) and what bounds its recompiles",
        ))
    return out


# ----------------------------------------- kernel-gate capability table

CAPS_NAME = "DECODE_KERNEL_CAPS"
_CAPS_AXES = ("model", "data")
_GATE_FN = "_decode_kernel_gate"
_CAPS_LOOKUP = "kernel_supports"


def _caps_table(node: ast.Assign, mi: ModuleInfo) -> Optional[dict]:
    try:
        val = ast.literal_eval(node.value)
    except (ValueError, SyntaxError):
        return None
    if not isinstance(val, dict):
        return None
    for flag, caps in val.items():
        if not (
            isinstance(flag, str)
            and isinstance(caps, dict)
            and set(caps) == set(_CAPS_AXES)
            and all(isinstance(v, bool) for v in caps.values())
        ):
            return None
    return val


def _model_config_flags(mi: ModuleInfo) -> Optional[List[str]]:
    """``use_pallas_*`` field names of a ``class ModelConfig`` in this
    module, or None when the module declares no such class."""
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.ClassDef) and node.name == "ModelConfig":
            flags = []
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    if stmt.target.id.startswith("use_pallas_"):
                        flags.append(stmt.target.id)
            return flags
    return None


def _gate_functions(mi: ModuleInfo) -> List[ast.FunctionDef]:
    return [
        node for node in ast.walk(mi.tree)
        if isinstance(node, ast.FunctionDef) and node.name == _GATE_FN
    ]


def _check_kernel_caps(modules: List[ModuleInfo]) -> List[Finding]:
    """CST-SHD-005: cross-module capability-table discipline (see the
    module doc).  Only judged when a scanned module defines the table —
    a corpus scan seeds its own toy table + ModelConfig + gate."""
    tables: List[Tuple[ModuleInfo, ast.Assign, Optional[dict]]] = []
    flags: Optional[List[str]] = None
    gates: List[Tuple[ModuleInfo, ast.FunctionDef]] = []
    for mi in modules:
        node = _module_assign(mi, CAPS_NAME)
        if node is not None:
            tables.append((mi, node, _caps_table(node, mi)))
        f = _model_config_flags(mi)
        if f is not None:
            flags = (flags or []) + f
        for g in _gate_functions(mi):
            gates.append((mi, g))
    if not tables:
        return []
    out: List[Finding] = []
    caps: dict = {}
    for mi, node, parsed in tables:
        if parsed is None:
            out.append(Finding(
                "CST-SHD-005", mi.rel, node.lineno, "<module>",
                f"{CAPS_NAME} must be a literal dict of "
                "{'use_pallas_*': {'model': bool, 'data': bool}} the "
                "jax-free pass can read off the AST",
            ))
        else:
            caps.update(parsed)
            caps_mi, caps_node = mi, node
    if flags is not None and caps:
        for flag in flags:
            if flag not in caps:
                out.append(Finding(
                    "CST-SHD-005", caps_mi.rel, caps_node.lineno,
                    CAPS_NAME,
                    f"kernel flag {flag!r} (ModelConfig) has no "
                    f"{CAPS_NAME} row — every fused-kernel gate "
                    "decision must come from the table, not an ad-hoc "
                    "mesh condition",
                ))
        for flag in caps:
            if flag not in flags:
                out.append(Finding(
                    "CST-SHD-005", caps_mi.rel, caps_node.lineno,
                    CAPS_NAME,
                    f"stale {CAPS_NAME} row {flag!r} names no declared "
                    "ModelConfig flag — the kernel it gated was "
                    "renamed or removed",
                ))
    for mi, g in gates:
        calls = [
            n for n in ast.walk(g)
            if isinstance(n, ast.Call)
            and (call_name(n) or "").rsplit(".", 1)[-1] == _CAPS_LOOKUP
        ]
        if not calls:
            out.append(Finding(
                "CST-SHD-005", mi.rel, g.lineno, mi.qualname_of(g),
                f"{_GATE_FN} never consults {_CAPS_LOOKUP} — the gate "
                f"condition must be driven by {CAPS_NAME}, not a "
                "hardcoded mesh check (the ISSUE-14 contract)",
            ))
    return out


def _check_constraint_sites(
    mi: ModuleInfo, seen: Dict[str, Tuple[str, int, str]]
) -> List[Finding]:
    out: List[Finding] = []
    flagged = set()
    for node in ast.walk(mi.tree):
        if not (isinstance(node, ast.Call) and _is_constraint_call(node)):
            continue
        sym = mi.qualname_of(node)
        key = f"{mi.rel}::{sym}"
        seen[key] = (mi.rel, node.lineno, sym)
        if key in jit_registry.SHARDING_CONSTRAINT_REGISTRY:
            continue
        if key in flagged:
            continue
        flagged.add(key)
        out.append(Finding(
            "CST-SHD-002", mi.rel, node.lineno, sym,
            f"sharding-constraint site `{key}` is not registered — add "
            "it to analysis/jit_registry.py::"
            "SHARDING_CONSTRAINT_REGISTRY with what the pin buys "
            "(which all-gather/partitioner cliff it prevents)",
        ))
    return out


@register_checker("partitioning")
def check(modules: List[ModuleInfo], ctx: CheckContext) -> List[Finding]:
    out: List[Finding] = []
    seen: Dict[str, Tuple[str, int, str]] = {}
    seen_sm: Dict[str, Tuple[str, int, str]] = {}
    scanned_rels = set()
    for mi in modules:
        scanned_rels.add(mi.rel)
        out.extend(_check_rule_tables(mi))
        out.extend(_check_constraint_sites(mi, seen))
        out.extend(_check_shard_map_sites(mi, seen_sm))
    out.extend(_check_kernel_caps(modules))
    # Stale registry entries: only judged for files this scan actually
    # covered (a corpus scan must not flag the real package's entries).
    for key in sorted(jit_registry.SHARDING_CONSTRAINT_REGISTRY):
        rel = key.split("::", 1)[0]
        if rel in scanned_rels and key not in seen:
            out.append(Finding(
                "CST-SHD-002", "analysis/jit_registry.py", 1, key,
                f"stale sharding-constraint registry entry `{key}` "
                "matches no site — the code moved; update or remove it",
            ))
    for key in sorted(jit_registry.SHARD_MAP_REGISTRY):
        rel = key.split("::", 1)[0]
        if rel in scanned_rels and key not in seen_sm:
            out.append(Finding(
                "CST-SHD-004", "analysis/jit_registry.py", 1, key,
                f"stale shard_map registry entry `{key}` matches no "
                "site — the code moved; update or remove it",
            ))
    return out
