"""CST-SHD: partition-rule and sharding-constraint discipline.

The 2D (data x model) mesh work (ISSUE 9) hangs every placement
decision off ONE literal table — ``parallel/partition.py``'s
``PARTITION_RULES`` — and a handful of ``with_sharding_constraint``
activation pins.  Both rot silently: a new param family falls through
to an accidental default, a constraint site appears without a recorded
retrace/propagation story, a renamed tensor leaves a rule matching
nothing.  Three rules machine-check the contracts (catalogue in
docs/ANALYSIS.md):

* **CST-SHD-001** — every leaf in ``KNOWN_PARAM_LEAVES`` must match
  EXACTLY ONE rule regex: an unmatched leaf means a new tensor has no
  placement decision; a doubly-matched leaf means the table is
  ambiguous (first-match-wins would hide the conflict).
* **CST-SHD-002** — every ``with_sharding_constraint`` call site (and
  every call through the ``partition.constrain`` helper) must be
  registered in ``analysis/jit_registry.py::
  SHARDING_CONSTRAINT_REGISTRY`` with a prose justification of what the
  pin buys (which all-gather it prevents / which partitioner cliff it
  avoids); stale registry entries are findings too.  pjit/jit sites are
  already covered by CST-DON-002.
* **CST-SHD-003** — a rule whose regex matches NO known leaf is stale:
  the tensor it governed was renamed or removed.

The checker is table-driven off the AST (``ast.literal_eval`` of the
two module-level assignments), so it runs jax-free like every other
family, and it applies to ANY scanned module defining both names — the
corpus seeds violations in a toy table without touching the real one.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from cst_captioning_tpu.analysis import jit_registry
from cst_captioning_tpu.analysis.astutil import ModuleInfo, call_name
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    register_checker,
)

RULES_NAME = "PARTITION_RULES"
LEAVES_NAME = "KNOWN_PARAM_LEAVES"

# Call names that ARE a sharding constraint: the raw jax API under any
# import spelling, plus the package's partition.constrain helper.
_RAW_CONSTRAINT = "with_sharding_constraint"
_HELPER_NAMES = ("constrain",)


def _module_assign(mi: ModuleInfo, name: str) -> Optional[ast.Assign]:
    for node in mi.tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    return node
    return None


def _rule_table(
    node: ast.Assign,
) -> Optional[List[Tuple[str, int]]]:
    """[(regex string, lineno)] from a literal PARTITION_RULES tuple —
    None when the assignment isn't the expected literal shape."""
    val = node.value
    if not isinstance(val, (ast.Tuple, ast.List)):
        return None
    out: List[Tuple[str, int]] = []
    for elt in val.elts:
        if not (
            isinstance(elt, (ast.Tuple, ast.List))
            and elt.elts
            and isinstance(elt.elts[0], ast.Constant)
            and isinstance(elt.elts[0].value, str)
        ):
            return None
        out.append((elt.elts[0].value, elt.elts[0].lineno))
    return out


def _leaf_list(node: ast.Assign) -> Optional[List[str]]:
    try:
        val = ast.literal_eval(node.value)
    except (ValueError, SyntaxError):
        return None
    if isinstance(val, (tuple, list)) and all(
        isinstance(x, str) for x in val
    ):
        return list(val)
    return None


def _check_rule_tables(mi: ModuleInfo) -> List[Finding]:
    rules_node = _module_assign(mi, RULES_NAME)
    leaves_node = _module_assign(mi, LEAVES_NAME)
    if rules_node is None or leaves_node is None:
        return []
    rules = _rule_table(rules_node)
    leaves = _leaf_list(leaves_node)
    out: List[Finding] = []
    if rules is None or leaves is None:
        out.append(Finding(
            "CST-SHD-001", mi.rel,
            (rules_node if rules is None else leaves_node).lineno,
            "<module>",
            f"{RULES_NAME}/{LEAVES_NAME} must be literal tuples the "
            "jax-free pass can read off the AST",
        ))
        return out
    compiled: List[Tuple[str, int, re.Pattern]] = []
    for pat, lineno in rules:
        try:
            compiled.append((pat, lineno, re.compile(pat)))
        except re.error as e:
            out.append(Finding(
                "CST-SHD-001", mi.rel, lineno, RULES_NAME,
                f"rule regex {pat!r} does not compile: {e}",
            ))
    for leaf in leaves:
        hits = [pat for pat, _, rx in compiled if rx.search(leaf)]
        if len(hits) == 1:
            continue
        what = (
            "matches NO partition rule — a new tensor has no placement "
            "decision; add a rule"
            if not hits
            else f"matches {len(hits)} rules {hits} — the table is "
            "ambiguous; rules must partition the leaves exactly once"
        )
        out.append(Finding(
            "CST-SHD-001", mi.rel, leaves_node.lineno, LEAVES_NAME,
            f"param leaf {leaf!r} {what}",
        ))
    for pat, lineno, rx in compiled:
        if not any(rx.search(leaf) for leaf in leaves):
            out.append(Finding(
                "CST-SHD-003", mi.rel, lineno, RULES_NAME,
                f"partition rule {pat!r} matches no known param leaf — "
                "the tensor it governed was renamed or removed; update "
                "or delete the rule",
            ))
    return out


def _is_constraint_call(node: ast.Call) -> bool:
    name = call_name(node)
    if not name:
        return False
    last = name.rsplit(".", 1)[-1]
    return last == _RAW_CONSTRAINT or last in _HELPER_NAMES


def _check_constraint_sites(
    mi: ModuleInfo, seen: Dict[str, Tuple[str, int, str]]
) -> List[Finding]:
    out: List[Finding] = []
    flagged = set()
    for node in ast.walk(mi.tree):
        if not (isinstance(node, ast.Call) and _is_constraint_call(node)):
            continue
        sym = mi.qualname_of(node)
        key = f"{mi.rel}::{sym}"
        seen[key] = (mi.rel, node.lineno, sym)
        if key in jit_registry.SHARDING_CONSTRAINT_REGISTRY:
            continue
        if key in flagged:
            continue
        flagged.add(key)
        out.append(Finding(
            "CST-SHD-002", mi.rel, node.lineno, sym,
            f"sharding-constraint site `{key}` is not registered — add "
            "it to analysis/jit_registry.py::"
            "SHARDING_CONSTRAINT_REGISTRY with what the pin buys "
            "(which all-gather/partitioner cliff it prevents)",
        ))
    return out


@register_checker("partitioning")
def check(modules: List[ModuleInfo], ctx: CheckContext) -> List[Finding]:
    out: List[Finding] = []
    seen: Dict[str, Tuple[str, int, str]] = {}
    scanned_rels = set()
    for mi in modules:
        scanned_rels.add(mi.rel)
        out.extend(_check_rule_tables(mi))
        out.extend(_check_constraint_sites(mi, seen))
    # Stale registry entries: only judged for files this scan actually
    # covered (a corpus scan must not flag the real package's entries).
    for key in sorted(jit_registry.SHARDING_CONSTRAINT_REGISTRY):
        rel = key.split("::", 1)[0]
        if rel in scanned_rels and key not in seen:
            out.append(Finding(
                "CST-SHD-002", "analysis/jit_registry.py", 1, key,
                f"stale sharding-constraint registry entry `{key}` "
                "matches no site — the code moved; update or remove it",
            ))
    return out
